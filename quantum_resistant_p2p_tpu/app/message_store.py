"""In-memory message store with unread tracking.

Parity with the reference's MessageStore (app/messaging.py:2045-2147):
chat history is deliberately memory-only and dies with the process.
"""

from __future__ import annotations

import base64
import time
import uuid
from dataclasses import dataclass, field
from typing import Any


@dataclass
class Message:
    """One chat or file message (reference: app/messaging.py:30-85)."""

    content: bytes
    sender_id: str
    recipient_id: str
    timestamp: float = field(default_factory=time.time)
    message_id: str = field(default_factory=lambda: str(uuid.uuid4()))
    is_file: bool = False
    filename: str | None = None
    is_system: bool = False
    key_exchange_algo: str = ""
    symmetric_algo: str = ""
    signature_algo: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {
            "content": base64.b64encode(self.content).decode("ascii"),
            "sender_id": self.sender_id,
            "recipient_id": self.recipient_id,
            "timestamp": self.timestamp,
            "message_id": self.message_id,
            "is_file": self.is_file,
            "filename": self.filename,
            "is_system": self.is_system,
            "key_exchange_algo": self.key_exchange_algo,
            "symmetric_algo": self.symmetric_algo,
            "signature_algo": self.signature_algo,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Message":
        d = dict(d)
        d["content"] = base64.b64decode(d["content"])
        return cls(**d)


class MessageStore:
    """Per-conversation history + unread counts (memory only)."""

    def __init__(self) -> None:
        self._conversations: dict[str, list[Message]] = {}
        self._unread: dict[str, int] = {}

    def add_message(self, peer_id: str, message: Message, unread: bool = False) -> None:
        self._conversations.setdefault(peer_id, []).append(message)
        if unread:
            self._unread[peer_id] = self._unread.get(peer_id, 0) + 1

    def get_messages(self, peer_id: str) -> list[Message]:
        return list(self._conversations.get(peer_id, []))

    def get_unread_count(self, peer_id: str) -> int:
        return self._unread.get(peer_id, 0)

    def mark_read(self, peer_id: str) -> None:
        self._unread.pop(peer_id, None)

    def conversations(self) -> list[str]:
        return list(self._conversations)
