"""SecureMessaging — the post-quantum secure messaging protocol engine.

Capability parity with the reference's app/messaging.py (2146 LoC), redesigned
around the provider registry and (optionally) the TPU batching queue:

* 5-message authenticated handshake with ephemeral KEM keys (reference flow:
  app/messaging.py:546-1261 — init / response / confirm / test / rejected),
  signature-authenticated with a 300 s replay window and typed rejection
  reasons (app/messaging.py:724-905).
* Sign-then-encrypt AEAD messaging with associated-data cross-checks and
  duplicate suppression (app/messaging.py:1437-1668).
* Crypto-settings gossip + algorithm hot-swap: changing the KEM drops shared
  keys and re-initiates; changing the AEAD re-derives from the stored raw
  shared secret without a new handshake; changing the signature algorithm
  loads-or-generates a keypair lazily (app/messaging.py:1741-1851).
* Shared keys persisted to the vault with history (app/messaging.py:274-309);
  fresh handshake per session by design.

Algorithm objects come from the provider registry — replacing the reference's
display-name string matching (app/messaging.py:1893-2011) with canonical names.
"""

from __future__ import annotations

import asyncio
import enum
import hashlib
import hmac
import json
import logging
import os
import time
import uuid
from pathlib import Path
from typing import Any, Callable

from ..faults import plan as _faults
from ..net.p2p_node import P2PNode
from ..obs import flight as obs_flight
from ..obs import trace as obs_trace
from ..obs.cost import CostLedger
from ..obs.metrics import Registry
from ..provider import get_fused, get_kem, get_signature, get_symmetric
from ..provider.base import KeyExchangeAlgorithm, SignatureAlgorithm, SymmetricAlgorithm
from ..provider.batched import (LANE_BULK, LANE_HANDSHAKE, LANE_REKEY,
                                LaneShed)
from .message_store import Message
from .resumption import (ReplayCache, STEKRing, TicketError,
                         derive_resumed_key, derive_resumption_secret,
                         hkdf_sha256 as _hkdf_sha256,
                         mint_fields, ratchet_resumption_secret,
                         resume_binder, resume_confirm_tag,
                         resumption_default)

logger = logging.getLogger(__name__)

REPLAY_WINDOW = 300.0  # seconds, matching the reference's timestamp check
KEY_EXCHANGE_TIMEOUT = 20.0
DEDUP_CAPACITY = 1000
#: bounded retry for initiate_key_exchange: a single dropped datagram (or a
#: transiently corrupted handshake message) no longer needs a caller-driven
#: retry.  Retries cover timeouts and invalid_signature rejections only —
#: structural failures (algorithm mismatch, disconnect) fail fast.
KE_RETRY_ATTEMPTS = 2
KE_RETRY_BACKOFF_S = 0.25
#: session healing: a mid-session disconnect triggers reconnection (with
#: backoff) then an automatic re-handshake; outbound messages sent during
#: the outage are queued (bounded) and flushed after re-establishment
HEAL_ATTEMPTS = 3
HEAL_BACKOFF_S = 0.25
OUTBOX_CAPACITY = 32
#: consecutive AEAD decrypt failures from one peer before the session key is
#: declared desynchronised/tampered and dropped for an automatic re-key (a
#: corrupted ciphertext mid-session must trigger a rekey, never plaintext)
REKEY_AFTER_AEAD_FAILURES = 1
#: minimum spacing between automatic re-keys per peer: old-key messages
#: legitimately in flight across a rekey (and attacker-sent garbage) must
#: not force handshake churn — at most one forced handshake per window
REKEY_COOLDOWN_S = 5.0
#: how long a completed session keeps its peer on the rekey lane (and
#: exempt from the handshake budget) after the key is gone, and the cap
#: on remembered peers (oldest evicted) — bounds both the memory and the
#: budget-bypass surface of the rekey exemption
HAD_SESSION_TTL_S = 3600.0
HAD_SESSION_CAP = 4096
#: pow2 flush buckets precompiled by the background warmup: bucket 1 (the
#: sequential-handshake case) plus the first pow-2 buckets a small burst of
#: concurrent handshakes coalesces into — warming ONLY size 1 (the old
#: default) left the first live size-2/4 flush eating a cold jit inside
#: KEY_EXCHANGE_TIMEOUT
WARMUP_SIZES = (1, 2, 4)
#: latency SLO threshold for an initiated handshake attempt (obs/slo.py):
#: chosen ON a DEFAULT_LATENCY_BUCKETS boundary so the good/bad split of
#: the burn-rate math is exact, and generous enough that only a degraded
#: plane (cold compiles on the hot path, breaker storms, gateway
#: saturation) burns budget — warm fused handshakes measure ~0.1-0.2 s
HANDSHAKE_SLO_THRESHOLD_S = 2.0
#: session-resumption tickets (docs/protocol.md "Session resumption"):
#: how long a minted ticket may resume, and the bound on tickets a client
#: holds (oldest evicted, secrets wiped) — both sides of the memory story
RESUME_TICKET_TTL_S = 2 * 3600.0
TICKET_CAP = 1024


class KeyExchangeState(enum.Enum):
    NONE = "none"
    INITIATED = "initiated"
    RESPONDED = "responded"
    CONFIRMED = "confirmed"
    ESTABLISHED = "established"


class RejectReason(str, enum.Enum):
    INVALID_SIGNATURE = "invalid_signature"
    IDENTITY_MISMATCH = "identity_mismatch"
    TIMESTAMP_INVALID = "timestamp_invalid"
    ALGORITHM_MISMATCH = "algorithm_mismatch"
    KEYGEN_ERROR = "keypair_generation_error"
    ENCAPSULATION_ERROR = "encapsulation_error"
    GENERAL_ERROR = "general_error"
    #: gateway admission control (docs/gateway.md): the responder is over
    #: its concurrent-handshake budget — a typed, FAST rejection the
    #: initiator treats as transient (retry with backoff), never a timeout
    BUSY = "server_busy"


def _canonical(data: dict) -> bytes:
    return json.dumps(data, sort_keys=True, separators=(",", ":")).encode()


#: sentinel: a fused handler consumed the message (failed the exchange with
#: a typed reason) — distinct from None, which means "not applicable, run
#: the per-op path"
_HANDLED = object()


class KeyExchangeFailed(RuntimeError):
    """A handshake attempt failed with a typed ``reason`` (a RejectReason
    value or a local failure tag) — carried as an attribute so the retry
    classifier never parses message text."""

    def __init__(self, reason: str):
        super().__init__(f"key exchange failed: {reason}")
        self.reason = reason


def _wipe(buf) -> None:
    """Best-effort in-place zeroization of a mutable secret buffer.

    Secrets this engine must shorten the lifetime of (ephemeral KEM secret
    keys, per-peer raw shared secrets) are stored as ``bytearray`` so that
    dropping them can actually clear the bytes — ``bytes`` copies made
    transiently by providers are immutable and left to the GC (a documented
    CPython limitation, not a policy choice).
    """
    if isinstance(buf, bytearray):
        buf[:] = b"\x00" * len(buf)


# RFC 5869 HKDF-SHA256 on the stdlib: ONE copy lives in app/resumption.py
# (the ticket machinery needs it below the engine), re-exported from the
# import block above under the historical name — tests/test_faults.py pins
# the RFC 5869 A.1 vector through ``_hkdf_sha256``.


def derive_message_key(shared_secret: bytes, id_a: str, id_b: str, aead_name: str) -> bytes:
    """HKDF-SHA256 over the raw KEM secret, salted by the sorted peer ids.

    Sorted ids make both sides derive identically (reference:
    app/messaging.py:350-382); binding the AEAD name lets an AEAD hot-swap
    re-derive a distinct key from the same secret (reference: :1797-1810).
    """
    ids = "|".join(sorted([id_a, id_b]))
    return _hkdf_sha256(
        shared_secret,
        salt=ids.encode(),
        info=b"qrp2p-tpu/msgkey/" + aead_name.encode(),
    )


class SecureMessaging:
    """Protocol engine: owns algorithms, per-peer keys, and the handshake FSM."""

    def __init__(
        self,
        node: P2PNode,
        key_storage=None,
        secure_logger=None,
        kem: KeyExchangeAlgorithm | None = None,
        symmetric: SymmetricAlgorithm | None = None,
        signature: SignatureAlgorithm | None = None,
        backend: str = "cpu",
        use_batching: bool = False,
        max_batch: int = 4096,
        max_wait_ms: float = 2.0,
        batch_floor: int = 1,
        mesh_devices: int = 0,
        shard_devices: int = 0,
        sig_keypair: tuple[bytes, bytes] | None = None,
        breaker_cooloff_s: float = 30.0,
        auto_heal: bool = True,
        autotune: bool | None = None,
        max_inflight_handshakes: int = 0,
        bulk_lane_capacity: int = 0,
        telemetry_port: int | None = None,
        batch_aead: bool | None = None,
        resumption: bool | None = None,
        stek: STEKRing | None = None,
    ):
        self.node = node
        self.key_storage = key_storage
        self.secure_logger = secure_logger
        self.backend = backend
        # multi-chip: tpu-backend providers shard device batches across a
        # mesh of this many chips (Config.mesh_devices; 0 = single device)
        self.mesh_devices = mesh_devices
        # multi-chip, latency path: the batching queues place each flush
        # on one of this many shards (provider/scheduler.py; 0/1 = one
        # logical shard, bit-for-bit the classic single-device behavior)
        self.shard_devices = shard_devices
        self.kem = kem or get_kem("ML-KEM-768", backend, devices=mesh_devices)
        self.symmetric = symmetric or get_symmetric("AES-256-GCM")
        self.signature = signature or get_signature("ML-DSA-65", backend,
                                                    devices=mesh_devices)

        # Optional TPU batching queue (the north-star refactor): when enabled,
        # every handshake/sign/verify op from every concurrent peer coalesces
        # into padded device batches instead of dispatching one-by-one.
        self.use_batching = use_batching
        self._batch_cfg = (max_batch, max_wait_ms)
        # bucket_floor collapses the flush-size bucket space so a pre-warm
        # covers every size a live swarm can hit (keyword so the positional
        # _batch_cfg unpacking at hot-swap stays untouched)
        self._batch_floor = batch_floor
        self._bkem = self._bsig = self._bfused = self._baead = None
        # batched device AEAD (the data plane): None reads the registry /
        # QRP2P_BATCH_AEAD default; False pins the scalar path (the
        # bulk-storm baseline configuration)
        self._batch_aead = batch_aead
        self._warmup_thread = None
        self._queue_breaker = None
        # The engine's metrics registry (obs/metrics.py) — the single source
        # metrics() reads from: the pre-existing queue/breaker/opcache
        # counters join via collectors, new resilience counters and the
        # per-handshake trip histogram live here directly.
        self.registry = Registry(name=f"messaging:{node.node_id[:8]}")
        #: dispatch trips per completed initiated handshake (integer samples;
        #: meaningful at concurrency 1 — overlapping handshakes share the
        #: breaker counter).  docs/dispatch_budget.md defines the budget;
        #: integer bucket boundaries make the percentiles exact.
        self._handshake_trips = self.registry.histogram(
            "handshake_trips", "dispatch trips per initiated handshake",
            buckets=tuple(float(i) for i in range(33)),
        )
        self._ctr_rekeys = self.registry.counter(
            "rekeys", "automatic re-keys after AEAD failures")
        self._ctr_heals_ok = self.registry.counter(
            "heals_ok", "session heals that reconnected and re-keyed")
        self._ctr_heals_failed = self.registry.counter(
            "heals_failed", "session heals that gave up")
        self._ctr_outbox_queued = self.registry.counter(
            "outbox_queued", "messages parked while a session healed")
        self._ctr_outbox_dropped = self.registry.counter(
            "outbox_dropped", "parked messages dropped (capacity or give-up)")
        self._ctr_handshake_giveups = self.registry.counter(
            "handshake_giveups", "initiated handshakes that failed finally")
        # gateway admission counters (docs/gateway.md): every shed is loud
        self._ctr_handshake_sheds = self.registry.counter(
            "handshake_sheds", "inbound handshakes rejected over budget")
        self._ctr_bulk_sheds = self.registry.counter(
            "bulk_sheds", "bulk sends shed at the bulk-lane bound")
        self._ctr_hs_admitted = self.registry.counter(
            "handshakes_admitted", "inbound ke_inits admitted past the budget")
        #: wall latency of every initiated-handshake attempt (success or
        #: failure — a timed-out attempt is exactly what the latency SLO
        #: must count against the budget).  Default le-buckets include the
        #: 2 s SLO threshold boundary, so the good/bad split is exact.
        self._handshake_latency = self.registry.histogram(
            "handshake_latency_s", "initiated handshake attempt latency (s)")
        # session-resumption tickets (docs/protocol.md "Session
        # resumption"): None reads the QRP2P_RESUMPTION default (on);
        # engine-level behavior only fires for peers whose hello ALSO
        # offered resumption (net/p2p_node.py negotiation), so an opted-out
        # or older peer sees wire-byte-identical frames (pinned).
        self.resumption = (resumption_default() if resumption is None
                           else resumption)
        #: this engine's ticket-sealing keys: a locally random ring by
        #: default (standalone responder); a fleet gateway's is replaced by
        #: the router's distributed set (fleet/manager.py __gw_stek__)
        self.tickets = stek if stek is not None else STEKRing()
        self._replay = ReplayCache()
        #: client side: issuer peer -> {ticket, expires_at, secret, ...};
        #: bounded (TICKET_CAP), secrets wiped on every drop path
        self._tickets: dict[str, dict] = {}
        #: in-flight resume exchanges: message_id -> context
        self._resume_pending: dict[str, dict] = {}
        #: peers whose CURRENT connection has not yet established a
        #: session: the one window a ticket may be presented in.  Armed on
        #: every connect, disarmed on establishment — an in-session rekey
        #: (AEAD failure, forced rekey) always runs the full KEM handshake
        #: for fresh entropy; resumption is strictly a reconnect fast path.
        self._resume_armed: set[str] = set()
        #: graceful drain (docs/robustness.md "Rolling restarts"): once
        #: set, /readyz answers 503 draining, new handshakes shed BUSY,
        #: resumes are rejected typed, and peers have been nudged to
        #: resume on their ring successor
        self.draining = False
        self.drain_reason: str | None = None
        self._ctr_tickets_minted = self.registry.counter(
            "tickets_minted", "resumption tickets sealed and sent")
        self._ctr_resumes_ok = self.registry.counter(
            "resumes_ok", "inbound ticket resumes accepted (responder)")
        self._ctr_resume_rejects = self.registry.counter(
            "resume_rejects", "inbound ticket resumes rejected, typed")
        self._ctr_resumes_used = self.registry.counter(
            "resumes_used", "handshakes completed via ticket resume (initiator)")
        self._ctr_resume_fallbacks = self.registry.counter(
            "resume_fallbacks", "resume attempts that fell back to a full handshake")
        self._ctr_rehome_nudges = self.registry.counter(
            "rehome_nudges", "drain nudges received from draining peers")
        self.registry.register_collector("queues", self._collect_queues)
        self.registry.register_collector("opcaches", self._collect_opcaches)
        #: engine birth (uptime for /healthz and snapshot-mode hs/s rates)
        self._t0 = time.monotonic()
        #: the device-cost ledger (obs/cost.py): padding waste, compile
        #: attribution, device seconds per op family, opcache windows, and
        #: the autotuner decision journal — registered on this registry so
        #: one Prometheus scrape exports the serving economics
        self.cost = CostLedger(registry=self.registry)
        # both halves of the handshake work feed the per-1k denominator:
        # a pure fleet gateway only RESPONDS (admitted ke_inits), so an
        # initiator-only count would leave the headline gauge permanently
        # None on exactly the processes the ledger exists to price
        self.cost.set_handshakes_fn(
            lambda: self._handshake_latency.count + self._ctr_hs_admitted.value)
        #: responder-side concurrent-handshake budget (0 = unlimited):
        #: over it, ke_init draws a typed BUSY rejection instead of joining
        #: a pile-up that times every initiator out
        self._hs_budget = max_inflight_handshakes
        self._responding = 0
        #: per-queue bulk-lane pending bound (0 = unbounded), applied to
        #: every facade queue so a bulk flood sheds bulk, not handshakes
        self._lane_capacity = (
            {LANE_BULK: bulk_lane_capacity} if bulk_lane_capacity else None
        )
        #: peer -> monotonic time of the last COMPLETED session: a recent
        #: entry makes the peer's next handshake a re-key (top-priority
        #: lane, exempt from the handshake budget).  Bounded and
        #: time-limited — an unbounded ever-seen set would grow one entry
        #: per peer forever AND hand every historical peer a permanent
        #: budget bypass, defeating admission control in exactly the
        #: mass-reconnect flood it exists for.
        self._had_session: dict[str, float] = {}
        self._autotuner = None
        self._scheduler = None
        if use_batching:
            from ..provider.batched import BatchedKEM, BatchedSignature
            from ..provider.scheduler import DeviceProgramScheduler

            # the device-program scheduler: the placement axis every queue
            # flush routes through.  One shard (the default) IS the old
            # one-breaker world — shard 0's breaker doubles as the legacy
            # _queue_breaker handle, so either path discovering slowness
            # shields its sibling queues exactly as before; with
            # shard_devices > 1 each shard gets its own breaker + heal
            # cycle and a sick chip quarantines one shard, not the fleet.
            self._scheduler = DeviceProgramScheduler(
                shards=shard_devices, cooloff_s=breaker_cooloff_s,
                registry=self.registry,
            )
            self._queue_breaker = self._scheduler.shards[0].breaker
            self._scheduler.attach_cost(self.cost)
            # the adaptive batch/flush autotuner (provider/autotune.py):
            # replaces the static flush policy on the hot path when armed;
            # autotune=None reads the QRP2P_AUTOTUNE env default, and OFF
            # leaves every queue reading its static constants bit-for-bit
            from ..provider.autotune import (Autotuner,
                                             autotune_enabled_default)

            enabled = (autotune_enabled_default() if autotune is None
                       else autotune)
            if enabled:
                self._autotuner = Autotuner(registry=self.registry,
                                            scheduler=self._scheduler,
                                            cost=self.cost)
            self._bkem = BatchedKEM(self.kem, max_batch, max_wait_ms,
                                    fallback=self._cpu_fallback_kem(),
                                    scheduler=self._scheduler,
                                    bucket_floor=batch_floor,
                                    lane_capacity=self._lane_capacity)
            self._bsig = BatchedSignature(self.signature, max_batch, max_wait_ms,
                                          fallback=self._cpu_fallback_sig(),
                                          scheduler=self._scheduler,
                                          bucket_floor=batch_floor,
                                          lane_capacity=self._lane_capacity)
            self._bfused = self._make_fused()
            # the DATA plane: bulk AEAD seal/open batches through the same
            # scheduler/lanes/breaker machinery (provider/batched.py
            # BatchedAEAD); None when the AEAD has no device capability
            self._baead = self._make_batched_aead()
            self._attach_tuners()
            self._attach_cost()
            self._spawn_warmup()

        # the SLO engine (obs/slo.py): burn-rate evaluation over the
        # counters above — metrics()["slo"], the CLI /slo command, and the
        # slo_burn flight trigger all read through it
        self.slo = self._build_slo_engine()

        # per-peer protocol state.  raw_secrets values are bytearrays so
        # every drop path (rekey, reconnect, hot-swap) can zeroize in place
        # (_wipe) instead of leaving the KEM secret to the GC.
        self.shared_keys: dict[str, bytes] = {}
        self.raw_secrets: dict[str, bytearray] = {}  # for AEAD-change re-derive
        self.ke_state: dict[str, KeyExchangeState] = {}
        self.peer_settings: dict[str, dict] = {}
        #: msg_id -> (peer, ephemeral KEM sk) — sk is a bytearray so every
        #: drop path can zeroize it in place (_wipe)
        self._ephemeral: dict[str, tuple[str, bytearray]] = {}
        self._pending: dict[str, asyncio.Future] = {}
        #: msg_id -> confirm transcript signed by the fused initiator step,
        #: parked so _handle_ke_response sends EXACTLY the signed bytes
        self._fused_confirm: dict[str, dict] = {}
        self._processed_ids: dict[str, float] = {}
        self._listeners: list[Callable[[str, Message], None]] = []
        #: session resilience (docs/robustness.md): peers currently being
        #: healed, per-peer queued outbound messages, and consecutive AEAD
        #: failure counters driving the automatic re-key
        self.auto_heal = auto_heal
        self._healing: set[str] = set()
        self._outbox: dict[str, list[Message]] = {}
        self._aead_failures: dict[str, int] = {}
        self._last_rekey: dict[str, float] = {}
        #: strong refs to fire-and-forget tasks — the event loop only keeps
        #: weak ones, so an unreferenced task can be GC'd mid-flight
        self._bg_tasks: set[asyncio.Task] = set()

        # sig_keypair injection skips the one-time scalar keygen dispatch —
        # swarm simulations construct thousands of stacks and pre-generate
        # their keypairs in one device batch (tools/swarm_bench.py)
        self._sig_keypair = (
            sig_keypair if sig_keypair is not None
            else self._load_or_generate_sig_keypair()
        )

        for msg_type, handler in (
            ("ke_init", self._handle_ke_init),
            ("ke_response", self._handle_ke_response),
            ("ke_confirm", self._handle_ke_confirm),
            ("ke_test", self._handle_ke_test),
            ("ke_reject", self._handle_ke_reject),
            ("ke_resume", self._handle_ke_resume),
            ("ke_resume_ok", self._handle_ke_resume_ok),
            ("ke_resume_reject", self._handle_ke_resume_reject),
            ("ke_rehome", self._handle_ke_rehome),
            ("secure_message", self._handle_secure_message),
            ("settings_update", self._handle_settings_update),
            ("settings_request", self._handle_settings_request),
        ):
            node.register_message_handler(msg_type, handler)
        node.register_connection_handler(self._on_connection_event)

        # live telemetry endpoints (obs/http.py), started LAST so a scrape
        # can never race a partially constructed engine.  OFF by default —
        # no listener, no thread, not even the module import.  An explicit
        # telemetry_port wins; otherwise QRP2P_HTTP_PORT decides (unset/
        # empty = disabled, 0 = ephemeral, N = fixed port).
        self.telemetry = None
        if telemetry_port is None and os.environ.get("QRP2P_HTTP_PORT"):
            from ..obs.http import env_port

            telemetry_port = env_port()
        if telemetry_port is not None:
            from ..obs.http import TelemetryServer

            try:
                self.telemetry = TelemetryServer.for_engine(
                    self, port=telemetry_port)
            except OSError as e:
                # same policy as a malformed env value: an optional
                # observability listener (port in use, privileged port)
                # must degrade loudly, never kill the serving engine
                logger.warning(
                    "telemetry endpoints disabled: cannot bind port %s "
                    "(%s)", telemetry_port, e)

    # ------------------------------------------------------------------ util

    @property
    def node_id(self) -> str:
        return self.node.node_id

    def register_message_listener(self, cb: Callable[[str, Message], None]) -> None:
        if cb not in self._listeners:
            self._listeners.append(cb)

    def _spawn(self, coro, what: str) -> asyncio.Task:
        """Supervised fire-and-forget: keep a strong reference until done and
        log unexpected exceptions (otherwise they only surface at GC)."""
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)

        def _done(t: asyncio.Task) -> None:
            self._bg_tasks.discard(t)
            if not t.cancelled() and t.exception() is not None:
                logger.error("background %s failed", what, exc_info=t.exception())

        task.add_done_callback(_done)
        return task

    def _notify(self, peer_id: str, message: Message) -> None:
        for cb in list(self._listeners):
            try:
                cb(peer_id, message)
            except Exception:
                logger.exception("message listener failed")

    def _log(self, event_type: str, **fields: Any) -> None:
        if self.secure_logger is not None:
            try:
                self.secure_logger.log_event(event_type, **fields)
            except Exception:
                logger.exception("audit log failed")

    def _load_or_generate_sig_keypair(self) -> tuple[bytes, bytes]:
        """Per-algorithm persistent signature keypair (reference: :254-272)."""
        name = f"signature_keypair_{self.signature.name}"
        if self.key_storage is not None and getattr(self.key_storage, "is_unlocked", False):
            stored = self.key_storage.retrieve(name)
            if stored:
                import base64

                return (
                    base64.b64decode(stored["public"]),
                    base64.b64decode(stored["secret"]),
                )
            pk, sk = self.signature.generate_keypair()
            import base64

            self.key_storage.store(
                name,
                {
                    "public": base64.b64encode(pk).decode(),
                    "secret": base64.b64encode(sk).decode(),
                },
            )
            return pk, sk
        return self.signature.generate_keypair()

    # -- async crypto helpers: route through the batch queue when enabled ----

    def _attach_tuners(self) -> None:
        """(Re-)attach the autotuner to every live facade queue — called at
        construction and after every hot-swap facade rebuild (the rebuilt
        queues are fresh objects; attach is idempotent per queue)."""
        if self._autotuner is not None:
            self._autotuner.attach_facades(self._bkem, self._bsig,
                                           self._bfused, self._baead)

    def _attach_cost(self) -> None:
        """(Re-)attach the cost ledger to every live facade queue and the
        providers' opcaches — called at construction and after every
        hot-swap facade/provider rebuild (fresh queue and cache objects
        each time; attach is a plain attribute set, so re-running is
        idempotent)."""
        from ..provider.batched import facade_queues

        for facade in (self._bkem, self._bsig, self._bfused, self._baead):
            if facade is None:
                continue
            facade.cost = self.cost
            for q in facade_queues(facade):
                q.cost = self.cost
        for algo, kind in ((self.kem, "kem"), (self.signature, "sig")):
            cache = getattr(algo, "opcache", None)
            if cache is not None and hasattr(cache, "attach_cost"):
                cache.attach_cost(self.cost, kind)

    def _is_rekey(self, peer_id: str) -> bool:
        """True while ``peer_id`` has a RECENT completed session (within
        HAD_SESSION_TTL_S): its next handshake is a re-key — top-priority
        lane, exempt from the handshake budget.  The table is pruned here
        (TTL + size cap), so stale peers age back to stranger status and
        the exemption never becomes a permanent budget bypass."""
        now = time.monotonic()
        t = self._had_session.get(peer_id)
        if t is not None and now - t > HAD_SESSION_TTL_S:
            del self._had_session[peer_id]
            t = None
        if len(self._had_session) > HAD_SESSION_CAP:
            for pid, ts in sorted(self._had_session.items(),
                                  key=lambda kv: kv[1])[: HAD_SESSION_CAP // 2]:
                del self._had_session[pid]
        return t is not None

    def _hs_lane(self, peer_id: str) -> int:
        """Handshake priority lane for ``peer_id``: a peer with a recent
        completed session is RE-KEYING (top priority — an established
        session must never lose its key behind a flood of strangers); a
        fresh (or long-gone) peer rides the new-handshake lane."""
        return LANE_REKEY if self._is_rekey(peer_id) else LANE_HANDSHAKE

    async def _kem_keygen(self, lane: int = LANE_HANDSHAKE) -> tuple[bytes, bytes]:
        if self._bkem is not None:
            return await self._bkem.generate_keypair(lane)
        return self.kem.generate_keypair()

    async def _kem_encaps(self, pk: bytes,
                          lane: int = LANE_HANDSHAKE) -> tuple[bytes, bytes]:
        if self._bkem is not None:
            return await self._bkem.encapsulate(pk, lane)
        return self.kem.encapsulate(pk)

    async def _kem_decaps(self, sk: bytes, ct: bytes,
                          lane: int = LANE_HANDSHAKE) -> bytes:
        if self._bkem is not None:
            return await self._bkem.decapsulate(sk, ct, lane)
        return self.kem.decapsulate(sk, ct)

    async def _sign(self, message: bytes, lane: int = LANE_HANDSHAKE) -> bytes:
        if self._bsig is not None:
            return await self._bsig.sign(self._sig_keypair[1], message, lane)
        return self.signature.sign(self._sig_keypair[1], message)

    async def _verify(self, sig_algo: str, pk: bytes, message: bytes, sig: bytes,
                      lane: int = LANE_HANDSHAKE) -> bool | None:
        """False on verification failure, None for an unknown/unsupported
        signature algorithm (the caller maps None to ALGORITHM_MISMATCH, the
        reference's typed rejection, rather than INVALID_SIGNATURE).  Never
        raises: malformed attacker input means False."""
        if sig_algo != self.signature.name:
            try:
                verifier = get_signature(sig_algo, self.backend)
            except (KeyError, ValueError, TypeError):
                # TypeError: attacker-supplied non-string sig_algo (unhashable)
                return None
            try:
                return verifier.verify(pk, message, sig)
            except Exception:  # qrlint: disable=broad-except  — verify contract: malformed attacker input maps to False, never an exception
                return False
        try:
            if self._bsig is not None:
                return await self._bsig.verify(pk, message, sig, lane)
            return self.signature.verify(pk, message, sig)
        except LaneShed:
            if lane != LANE_BULK:
                # a capped handshake/rekey lane (not reachable through
                # this engine's own knobs, which bound only bulk) must
                # surface as a typed shed, never as a signature verdict —
                # _check_common maps it to RejectReason.BUSY
                raise
            # inbound bulk shed at its lane bound: loud and counted — the
            # caller still sees False (the message is dropped), so its
            # "verification failed" log line follows this shed line
            self._ctr_bulk_sheds.inc()
            logger.warning("inbound bulk-lane verify shed (%d total)",
                           self._ctr_bulk_sheds.value)
            return False
        except Exception:  # qrlint: disable=broad-except  — verify contract: malformed attacker input maps to False, never an exception
            return False

    def _dedup(self, message_id: str) -> bool:
        """True if already seen; prunes the table at capacity (ref: :1506-1517)."""
        if message_id in self._processed_ids:
            return True
        self._processed_ids[message_id] = time.time()
        if len(self._processed_ids) > DEDUP_CAPACITY:
            for mid, _ in sorted(self._processed_ids.items(), key=lambda kv: kv[1])[
                : DEDUP_CAPACITY // 2
            ]:
                del self._processed_ids[mid]
        return False

    def _on_connection_event(self, event: str, peer_id: str) -> None:
        if event == "connect":
            # Fresh handshake per session: drop any stale key (ref: :447-452).
            self.shared_keys.pop(peer_id, None)
            _wipe(self.raw_secrets.pop(peer_id, None))
            self.ke_state[peer_id] = KeyExchangeState.NONE
            # a fresh connection is the one window a held resumption
            # ticket may be presented in (disarmed on establishment)
            self._resume_armed.add(peer_id)
            self._spawn(self.request_peer_settings(peer_id), "settings gossip")
        elif event == "disconnect":
            self.ke_state[peer_id] = KeyExchangeState.NONE
            self._resume_armed.discard(peer_id)
            # fail any in-flight ticket resume with this peer, typed, and
            # wipe its parked secret — same promptness contract as the
            # ephemeral-KEM cleanup below
            for mid, ctx in list(self._resume_pending.items()):
                if ctx["peer"] == peer_id:
                    _wipe(self._resume_pending.pop(mid)["secret"])
                    self._fail_pending(mid, "peer_disconnected")
            # Fail any IN-FLIGHT handshake with the dropped peer now, with
            # a typed reason: no ke_response can ever resolve its future,
            # and burning the full protocol timeout on it would stall the
            # initiator's retry loop — which is exactly the loop a fleet
            # handoff (fleet/manager.py) relies on to re-route promptly to
            # the ring successor of a dead gateway.
            for mid, entry in list(self._ephemeral.items()):
                if entry[0] == peer_id:
                    self._fail_pending(mid, "peer_disconnected")
            if (
                self.auto_heal
                and peer_id not in self._healing
                and self.node.should_heal(peer_id)
            ):
                # Mid-session drop of a peer WE dialed: reconnect with
                # backoff, re-handshake, then flush queued outbound —
                # instead of the old permanent dead peer.
                self._healing.add(peer_id)
                self._spawn(self._heal_session(peer_id), "session heal")

    async def _heal_session(self, peer_id: str) -> None:
        """Reconnect -> automatic re-handshake -> flush the outbox.

        Bounded: HEAL_ATTEMPTS redials with exponential backoff (each redial
        itself uses P2PNode.connect_to_peer's transient-failure retry); on
        exhaustion the outbox is dropped with a loud warning — messages are
        never silently black-holed, and never sent unencrypted.
        """
        try:
            delay = HEAL_BACKOFF_S
            for _attempt in range(HEAL_ATTEMPTS):
                if not self.node.should_heal(peer_id):
                    # the disconnect became intentional (stop(), explicit
                    # API) mid-heal: the outbox must not strand silently
                    dropped = len(self._outbox.pop(peer_id, []))
                    if dropped:
                        self._ctr_outbox_dropped.inc(dropped)
                        logger.warning(
                            "session heal for %s abandoned (no longer "
                            "healable); %d queued message(s) dropped",
                            peer_id[:8], dropped,
                        )
                    self._ctr_heals_failed.inc()
                    obs_flight.record("heal_abandoned", peer=peer_id[:8],
                                      dropped=dropped)
                    return
                await asyncio.sleep(delay)
                delay *= 2
                if await self.node.reconnect(peer_id):
                    break
            else:
                dropped = len(self._outbox.pop(peer_id, []))
                self._ctr_outbox_dropped.inc(dropped)
                self._ctr_heals_failed.inc()
                logger.warning(
                    "session heal: %s unreachable after %d redials; giving up"
                    " (%d queued message(s) dropped)",
                    peer_id[:8], HEAL_ATTEMPTS, dropped,
                )
                self._log("session_heal", peer=peer_id, success=False)
                obs_flight.trigger("heal_giveup", peer=peer_id[:8],
                                   reason="unreachable", dropped=dropped)
                return
            # reconnect fired the "connect" event, which reset the session
            # state; establish a fresh key before flushing anything
            ok = await self.initiate_key_exchange(peer_id)
            if not ok:
                # a concurrent initiator (an app send, the AEAD rekey) may
                # own the handshake ("already_in_flight"): give it a bounded
                # moment before declaring the heal failed
                for _ in range(40):
                    if self.verify_key_exchange_state(peer_id):
                        ok = True
                        break
                    if not self.node.is_connected(peer_id):
                        break
                    await asyncio.sleep(0.05)
            if ok:
                self._ctr_heals_ok.inc()
                logger.warning(
                    "session heal: %s reconnected and re-keyed; flushing %d "
                    "queued message(s)",
                    peer_id[:8], len(self._outbox.get(peer_id, [])),
                )
                self._log("session_heal", peer=peer_id, success=True)
                obs_flight.record("heal_ok", peer=peer_id[:8],
                                  flushed=len(self._outbox.get(peer_id, [])))
                await self._flush_outbox(peer_id)
            else:
                # reconnected but could not re-key: the outbox must not
                # strand silently — drop it loudly, exactly like the
                # unreachable case above
                dropped = len(self._outbox.pop(peer_id, []))
                self._ctr_outbox_dropped.inc(dropped)
                self._ctr_heals_failed.inc()
                logger.warning(
                    "session heal: %s reconnected but re-handshake failed; "
                    "giving up (%d queued message(s) dropped)",
                    peer_id[:8], dropped,
                )
                self._log("session_heal", peer=peer_id, success=False)
                obs_flight.trigger("heal_giveup", peer=peer_id[:8],
                                   reason="rehandshake_failed", dropped=dropped)
        finally:
            self._healing.discard(peer_id)
            # a message queued in the window between the flush completing
            # and _healing clearing would otherwise sit until the next
            # outage: flush the tail now that the session is live
            if (
                self._outbox.get(peer_id)
                and self.verify_key_exchange_state(peer_id)
            ):
                self._spawn(self._flush_outbox(peer_id), "outbox tail flush")

    def _queue_outbound(self, peer_id: str, content: bytes, is_file: bool,
                        filename: str | None) -> Message | None:
        """Park an outbound message while its session heals (bounded)."""
        box = self._outbox.setdefault(peer_id, [])
        if len(box) >= OUTBOX_CAPACITY:
            self._ctr_outbox_dropped.inc()
            logger.warning("outbox for %s full; dropping message", peer_id[:8])
            return None
        self._ctr_outbox_queued.inc()
        message = Message(
            content=content,
            sender_id=self.node_id,
            recipient_id=peer_id,
            is_file=is_file,
            filename=filename,
            key_exchange_algo=self.kem.name,
            symmetric_algo=self.symmetric.name,
            signature_algo=self.signature.name,
        )
        box.append(message)
        return message

    async def _flush_outbox(self, peer_id: str) -> None:
        queued = self._outbox.pop(peer_id, [])
        for i, message in enumerate(queued):
            try:
                sent = await self._encrypt_and_send(peer_id, message)
            except Exception:
                logger.exception("outbox flush to %s failed", peer_id[:8])
                sent = False
            if not sent:
                # re-queue the unsent remainder: a send failure mid-flush
                # (connection flapped again) re-enters the heal cycle with
                # these messages still parked, not silently dropped
                remainder = queued[i:]
                self._outbox[peer_id] = remainder + self._outbox.pop(peer_id, [])
                logger.warning(
                    "outbox flush to %s failed; %d message(s) re-queued",
                    peer_id[:8], len(remainder),
                )
                # the eviction's disconnect event fired while peer_id was
                # still in _healing, so no new heal was spawned for it —
                # re-enter the cycle ourselves once the current heal exits
                # (bounded in practice: every cycle needs a successful
                # reconnect + re-handshake to reach this line again, pays
                # the full redial backoff, and logs loudly)
                if self.auto_heal and self.node.should_heal(peer_id):
                    self._spawn(self._reheal(peer_id), "session re-heal")
                else:
                    # no further heal possible (intentional disconnect,
                    # node stopping): never strand silently
                    dropped = len(self._outbox.pop(peer_id, []))
                    self._ctr_outbox_dropped.inc(dropped)
                    logger.warning(
                        "outbox for %s not healable; %d queued message(s) "
                        "dropped", peer_id[:8], dropped,
                    )
                return

    async def _reheal(self, peer_id: str) -> None:
        """Re-enter the heal cycle after a mid-flush connection flap (the
        flap's disconnect event was suppressed by the in-progress heal)."""
        while peer_id in self._healing:
            await asyncio.sleep(0.05)
        if (
            self.auto_heal
            and self._outbox.get(peer_id)
            and not self.node.is_connected(peer_id)
            and self.node.should_heal(peer_id)
        ):
            self._healing.add(peer_id)
            await self._heal_session(peer_id)

    # ----------------------------------------------------------- key exchange

    def verify_key_exchange_state(self, peer_id: str) -> bool:
        """Key present AND state established/confirmed AND peer connected."""
        return (
            peer_id in self.shared_keys
            and self.ke_state.get(peer_id)
            in (KeyExchangeState.CONFIRMED, KeyExchangeState.ESTABLISHED)
            and self.node.is_connected(peer_id)
        )

    async def initiate_key_exchange(self, peer_id: str,
                                    retries: int = KE_RETRY_ATTEMPTS) -> bool:
        """Initiator side of the 5-message handshake (reference: :546-693),
        with bounded retry-with-backoff on TRANSIENT failures (a timed-out
        exchange — e.g. one dropped datagram — or an invalid-signature
        rejection from one corrupted-in-flight message).  Structural
        failures (algorithm mismatch, keygen error, peer gone) fail fast.

        When a resumption ticket for this peer is held and the connection
        is fresh (docs/protocol.md "Session resumption"), the abbreviated
        1-RTT ticket resume runs FIRST — no KEM, no signatures, no device
        dispatch.  Any resume failure (hostile/expired/replayed ticket, a
        peer that never saw the STEK) falls back LOUDLY to the full
        handshake below — never a stall, never plaintext.
        """
        if self._resume_allowed(peer_id):
            status = await self._resume_once(peer_id)
            if status == "ok":
                return True
            self._ctr_resume_fallbacks.inc()
            logger.warning(
                "ticket resume with %s failed (%s); falling back to a "
                "full handshake", peer_id[:8], status,
            )
            obs_flight.record("ticket_fallback", peer=peer_id[:8],
                              reason=status)
        delay = KE_RETRY_BACKOFF_S
        for attempt in range(retries + 1):
            status = await self._initiate_once(peer_id)
            if status == "ok":
                return True
            # BUSY is the gateway's typed load-shed: the responder is over
            # its admission budget NOW but will drain — retry with backoff
            # exactly like a transient network fault
            transient = status in ("timeout", RejectReason.INVALID_SIGNATURE.value,
                                   RejectReason.BUSY.value)
            if not transient or attempt == retries or not self.node.is_connected(peer_id):
                if status != "already_in_flight":
                    # final failure: a flight-recorder trigger (auto-dumps a
                    # diagnostic bundle when armed) — a benign concurrent
                    # initiation is not a give-up
                    self._ctr_handshake_giveups.inc()
                    obs_flight.trigger(
                        "handshake_giveup", peer=peer_id[:8], status=status,
                        attempt=attempt + 1,
                    )
                return False
            logger.warning(
                "key exchange with %s failed (%s); retry %d/%d in %.2fs",
                peer_id[:8], status, attempt + 1, retries, delay,
            )
            await asyncio.sleep(delay)
            delay *= 2
        return False

    async def _initiate_once(self, peer_id: str) -> str:
        """One handshake attempt -> "ok" | "timeout" | a typed failure."""
        # node_scope: one process may host many engines (swarm benches) —
        # the span (and everything it parents) lands on THIS node's lane
        # in a merged multi-node flame graph (tools/trace_merge.py)
        with obs_trace.node_scope(self.node_id), \
                obs_trace.span("handshake.initiate", peer=peer_id[:8],
                               kem=self.kem.name,
                               sig=self.signature.name) as sp, \
                self._handshake_latency.time():
            status = await self._initiate_attempt(peer_id)
            sp.set_attr("status", status)
            return status

    async def _initiate_attempt(self, peer_id: str) -> str:
        if self.ke_state.get(peer_id) == KeyExchangeState.INITIATED:
            logger.info("handshake with %s already in flight", peer_id[:8])
            return "already_in_flight"
        # Compatibility pre-check against gossiped peer settings (ref: :564-586).
        peer_cfg = self.peer_settings.get(peer_id)
        if peer_cfg and peer_cfg.get("kem") != self.kem.name:
            logger.warning(
                "algorithm mismatch with %s: %s vs %s",
                peer_id[:8], self.kem.name, peer_cfg.get("kem"),
            )
            return RejectReason.ALGORITHM_MISMATCH.value

        message_id = str(uuid.uuid4())
        trips0 = self._trips_now()
        # priority lane for every queued op of THIS handshake: top priority
        # when re-keying an established peer, middle for a fresh one
        lane = self._hs_lane(peer_id)
        ke_data = {
            "message_id": message_id,
            "kem": self.kem.name,
            "aead": self.symmetric.name,
            "public_key": "",
            "sender": self.node_id,
            "recipient": peer_id,
            "timestamp": time.time(),
        }
        pk = sk = sig = None
        if self._bfused is not None:
            # Composite path: keygen + sign(init transcript) in ONE device
            # trip.  The transcript is shipped as a template — the canonical
            # JSON with a same-length placeholder where the device hex-
            # encodes the fresh public key — so the signed bytes are
            # identical to the per-op path's (wire-compatible).
            ke_data["public_key"] = "0" * (2 * self.kem.public_key_len)
            template = _canonical(ke_data)
            if len(template) <= self._bfused.fused.init_template_len:
                try:
                    pk, sk, sig = await self._bfused.keygen_sign(
                        self._sig_keypair[1], template, lane
                    )
                except Exception:
                    logger.exception("fused keygen_sign failed; per-op fallback")
                    pk = None
        if pk is None:
            try:
                pk, sk = await self._kem_keygen(lane)
            except Exception:
                logger.exception("ephemeral keygen failed")
                return RejectReason.KEYGEN_ERROR.value  # qrlife: disable=life-wipe-gap — sk is None on this path: the fused branch failed or was skipped (pk None guard) and this keygen raised before binding one
            ke_data["public_key"] = pk.hex()
            sig = await self._sign(_canonical(ke_data), lane)
        else:
            ke_data["public_key"] = pk.hex()
        self._ephemeral[message_id] = (peer_id, bytearray(sk))
        self.ke_state[peer_id] = KeyExchangeState.INITIATED

        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[message_id] = fut

        sent = await self.node.send_message(
            peer_id,
            "ke_init",
            ke_data=ke_data,
            sig=sig,
            sig_algo=self.signature.name,
            sig_pk=self._sig_keypair[0],
        )
        if not sent:
            self._cleanup_exchange(message_id, peer_id)
            return "send_failed"
        try:
            await asyncio.wait_for(fut, KEY_EXCHANGE_TIMEOUT)
            self._handshake_trips.record(self._trips_now() - trips0)
            return "ok"
        except asyncio.TimeoutError:
            # Timeout-but-key-exists recovery (reference: :670-681).
            if peer_id in self.shared_keys:
                return "ok"
            self._cleanup_exchange(message_id, peer_id)
            self._log("key_exchange", peer=peer_id, success=False, reason="timeout")
            return "timeout"
        except RuntimeError as e:
            # Typed rejection from the peer (ke_reject) or a local crypto
            # error; KeyExchangeFailed carries the reason as an attribute
            # so the retry loop classifies on the typed value, never on
            # message text.
            logger.warning("key exchange with %s failed: %s", peer_id[:8], e)
            self._cleanup_exchange(message_id, peer_id)
            return getattr(e, "reason", "error")

    def _cpu_fallback_kem(self):
        """cpu-backend twin of the active KEM, arming the batch queue's
        degrade-don't-fail path (device slow/hung -> ops run on cpu instead
        of failing their protocol timeouts).  None when the active provider
        IS the cpu one — no point falling back to itself."""
        if getattr(self.kem, "backend", "") != "tpu":
            return None
        try:
            return get_kem(self.kem.name, "cpu")
        except Exception:
            logger.exception("no cpu fallback for %s", self.kem.name)
            return None

    def _cpu_fallback_sig(self):
        """cpu-backend twin of the active signature (see _cpu_fallback_kem)."""
        if getattr(self.signature, "backend", "") != "tpu":
            return None
        try:
            return get_signature(self.signature.name, "cpu")
        except Exception:
            logger.exception("no cpu fallback for %s", self.signature.name)
            return None

    def _make_fused(self):
        """Composite-queue facade (provider.batched.BatchedFused) when the
        active (KEM, signature) pair advertises the fused-handshake
        capability — None (cpu backend, unregistered pair, batching off)
        keeps every step on the per-op queues.  The transcript offsets are
        protocol facts of THIS engine's canonical-JSON layout, computed here
        and baked into the facade (jit keys on them)."""
        if not self.use_batching:
            return None
        fused = get_fused(self.kem, self.signature)
        if fused is None:
            return None
        from ..provider.batched import BatchedFused
        from ..provider.fused_providers import init_pk_offset, resp_ct_offset

        max_batch, max_wait_ms = self._batch_cfg
        return BatchedFused(
            fused,
            pk_off=init_pk_offset(self.kem.name, self.symmetric.name),
            ct_off=resp_ct_offset(),
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            fallback_kem=self._cpu_fallback_kem(),
            fallback_sig=self._cpu_fallback_sig(),
            scheduler=self._scheduler,
            bucket_floor=self._batch_floor,
            lane_capacity=self._lane_capacity,
        )

    def _make_batched_aead(self):
        """Batched-AEAD facade (provider.batched.BatchedAEAD) when the
        active AEAD advertises the device capability — None (no capability,
        ``QRP2P_BATCH_AEAD=0``, or ``batch_aead=False``) keeps every seal/
        open on the scalar path.  Shares the scheduler/lanes/breakers with
        the handshake facades, so a bulk AEAD flood sheds at the bulk lane
        and a sick device degrades the whole plane to cpu together."""
        if not self.use_batching or self._batch_aead is False:
            return None
        from ..provider.registry import get_batched_aead

        device = get_batched_aead(self.symmetric)
        if device is None:
            return None
        from ..provider.batched import BatchedAEAD

        max_batch, max_wait_ms = self._batch_cfg
        return BatchedAEAD(
            device, self.symmetric, max_batch, max_wait_ms,
            scheduler=self._scheduler, bucket_floor=self._batch_floor,
            lane_capacity=self._lane_capacity,
        )

    async def _aead_encrypt(self, key: bytes, plaintext: bytes, ad: bytes,
                            lane: int = LANE_BULK) -> bytes:
        """Seal through the batched facade when armed, else scalar — the
        wire bytes are format-identical either way (KAT-pinned)."""
        if self._baead is not None:
            return await self._baead.encrypt(key, plaintext, ad, lane)
        return self.symmetric.encrypt(key, plaintext, ad)

    async def _aead_decrypt(self, key: bytes, data, ad: bytes,
                            lane: int = LANE_BULK) -> bytes:
        """Open through the batched facade when armed (``data`` may be a
        zero-copy memoryview off the binary wire), else scalar."""
        if self._baead is not None:
            return await self._baead.decrypt(key, data, ad, lane)
        return self.symmetric.decrypt(key, bytes(data), ad)

    def _trips_now(self) -> int:
        """Serial dispatch steps (device + fallback) so far on the breaker
        (or placement axis) the live queues actually share — swarm clients
        share another stack's queues, so the facade's scheduler/breaker is
        the truthful one.  Under a scheduler trips sum across every
        shard's breaker (docs/dispatch_budget.md per-shard ledger)."""
        if self._bkem is None:
            return 0
        sched = getattr(self._bkem, "scheduler", None)
        if sched is not None:
            return sched.total_trips()
        b = self._bkem.breaker
        return b.device_trips + b.fallback_trips

    def _collect_queues(self) -> dict[str, Any]:
        """Registry collector: the queue/breaker counters this engine's
        facades already keep, absorbed at snapshot time (obs/metrics.py —
        no second set of hot-path increments)."""
        out: dict[str, Any] = {}
        if self._bkem is None:
            return out
        out["kem_queue"] = self._bkem.stats()
        out["sig_queue"] = self._bsig.stats()
        if self._bfused is not None:
            out["fused_queue"] = self._bfused.stats()
        if self._baead is not None:
            # the data plane's seal/open queues (additive key, same
            # compatibility contract as fused_queue)
            out["aead_queue"] = self._baead.stats()
        b = self._bkem.breaker
        sched = getattr(self._bkem, "scheduler", None)
        if sched is not None:
            # legacy keys stay truthful across the placement axis: trips
            # and open/close counters SUM over every shard's breaker, and
            # breaker_state reports the WORST shard — a dashboard/alert
            # keyed on the documented legacy keys must fire when ANY
            # shard degrades, not only shard 0.  (Mesh-of-1: one shard,
            # so every value is identical to the old single breaker's.)
            out["device_trips"] = sum(
                s.breaker.device_trips for s in sched.shards)
            out["fallback_trips"] = sum(
                s.breaker.fallback_trips for s in sched.shards)
            out["breaker_trips"] = sum(s.breaker.trips for s in sched.shards)
            severity = {"closed": 0, "half_open": 1, "open": 2,
                        "quarantined": 3}
            out["breaker_state"] = max(
                (s.breaker.state for s in sched.shards),
                key=lambda st: severity.get(st, 0))
            out["breaker_opens"] = sum(s.breaker.opens for s in sched.shards)
            out["breaker_closes"] = sum(s.breaker.closes for s in sched.shards)
            # the placement axis, per shard (additive key: the legacy
            # layout above is a compatibility contract, tests/test_obs.py)
            out["shards"] = sched.stats()
        else:
            out["device_trips"] = b.device_trips
            out["fallback_trips"] = b.fallback_trips
            out["breaker_trips"] = b.trips
            out["breaker_state"] = b.state
            out["breaker_opens"] = b.opens
            out["breaker_closes"] = b.closes
        # the degradation gauge across every queue of this engine
        # (VERDICT r3: a silently cpu-served "TPU" fleet must be visible)
        total = fb = 0
        for fam_key in ("kem_queue", "sig_queue", "fused_queue",
                        "aead_queue"):
            for q in out.get(fam_key, {}).values():
                total += q["ops"]
                fb += q["fallback_ops"]
        out["device_served_fraction"] = (
            round((total - fb) / total, 4) if total else None
        )
        return out

    def _collect_opcaches(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for algo, key in ((self.kem, "kem_opcache"), (self.signature, "sig_opcache")):
            cache = getattr(algo, "opcache", None)
            if cache is not None:
                out[key] = cache.stats()
        return out

    def _build_slo_engine(self):
        """Declarative SLOs over the counters this engine already keeps
        (obs/slo.py; docs/observability.md "SLO specs"):

        * ``handshake_p99`` — initiated attempts complete within
          HANDSHAKE_SLO_THRESHOLD_S (timeouts count against the budget);
        * ``gateway_shed_rate`` — inbound work admitted vs shed across
          every admission boundary (connection / handshake / bulk lane);
        * per-shard ``device_served_shard<i>`` — dispatch steps the shard
          served from the device vs its cpu fallback (objective matches
          the 0.9 bench gate, thresholds sized to its burn ceiling);
        * ``breaker_availability`` — wall-time fraction the facade
          breaker's device path was closed.

        Probes read live objects that survive algorithm hot-swaps (the
        scheduler's shard breakers, registry instruments, the node), so
        the engine never needs re-wiring."""
        from ..obs import slo as obs_slo

        eng = obs_slo.SLOEngine(registry=self.registry)
        eng.add(obs_slo.SLOSpec(
            "handshake_p99", objective=0.99,
            probe=obs_slo.latency_probe(self._handshake_latency,
                                        HANDSHAKE_SLO_THRESHOLD_S),
            description=("initiated handshake attempts complete within "
                         f"{HANDSHAKE_SLO_THRESHOLD_S:g}s"),
        ))
        # session-admission SLI only, and SYMMETRIC per boundary: each
        # side of a counted decision must have its twin — connection
        # admissions (node.admitted) balance connection sheds
        # (node.sheds), handshake admissions balance handshake sheds.
        # Counting connection sheds against handshake admissions alone
        # turned a reconnect wave of admitted-but-not-yet-handshaking
        # peers into a ~100x false burn.  Bulk-lane sheds are
        # per-MESSAGE and deliberately excluded — a bulk flood shedding
        # 1% of 10k sends must not read as a 40x session-admission burn.
        eng.add(obs_slo.SLOSpec(
            "gateway_shed_rate", objective=0.99,
            probe=obs_slo.counter_pair_probe(
                lambda: (self._ctr_hs_admitted.value + self.node.admitted),
                lambda: (self._ctr_handshake_sheds.value + self.node.sheds)),
            description="admission decisions accepted vs shed (connection "
                        "+ handshake boundaries)",
            fast_burn=10.0, slow_burn=1.0,
        ))
        # ticket resumes (docs/protocol.md "Session resumption"): good =
        # resumes completed on either side, bad = typed rejects + client
        # fallbacks.  A reconnect wave that stops resuming (rotated-away
        # STEK, clock skew expiring tickets, a replay storm) burns here
        # long before it shows as handshake-latency or admission pain —
        # under the 1/(1-0.9) = 10x ceiling so it can actually fire.
        eng.add(obs_slo.SLOSpec(
            "resume_success", objective=0.9,
            probe=obs_slo.counter_pair_probe(
                lambda: (self._ctr_resumes_ok.value
                         + self._ctr_resumes_used.value),
                lambda: (self._ctr_resume_rejects.value
                         + self._ctr_resume_fallbacks.value)),
            description="ticket resumes completed vs rejected/fallen back "
                        "(both roles)",
            fast_burn=5.0, slow_burn=2.0,
        ))
        if self._scheduler is not None:
            for sh in self._scheduler.shards:
                eng.add(obs_slo.SLOSpec(
                    f"device_served_shard{sh.index}", objective=0.9,
                    probe=obs_slo.counter_pair_probe(
                        lambda b=sh.breaker: b.device_trips,
                        lambda b=sh.breaker: b.fallback_trips),
                    description=("dispatch steps this shard served from "
                                 "the device path (vs cpu fallback)"),
                    # a full outage burns at 1/(1-0.9) = 10x: thresholds
                    # must sit under that ceiling to ever fire
                    fast_burn=5.0, slow_burn=2.0,
                ))
            eng.add(obs_slo.SLOSpec(
                "breaker_availability", objective=0.95,
                probe=obs_slo.breaker_availability_probe(self._queue_breaker),
                description=("wall-time fraction the facade breaker's "
                             "device path was closed"),
                fast_burn=5.0, slow_burn=1.0,
            ))
        # evaluation rides the registry's collector hook so a gateway
        # monitored ONLY through Prometheus scrapes still advances the
        # burn windows, refreshes the slo_* gauges, and can fire the
        # slo_burn flight trigger mid-incident — metrics()/ /slo are not
        # the only readers that keep the engine honest.  The summary the
        # collector returns is the scrape-able roll-up; the full report
        # stays on metrics()["slo"].
        def _collect_slo() -> dict[str, Any]:
            specs = eng.evaluate()
            return {
                "alerts_total": sum(s["alerts"] for s in specs),
                "alerting_count": sum(1 for s in specs if s["alerting"]),
            }

        self.registry.register_collector("slo_health", _collect_slo)
        return eng

    def slo_status(self) -> dict[str, Any]:
        """Evaluate the SLO engine now and return its burn/budget report
        (also served as ``metrics()["slo"]`` and the CLI ``/slo``)."""
        return self.slo.status()

    # ------------------------------------------------------- live telemetry

    @property
    def telemetry_port(self) -> int | None:
        """The bound telemetry port (None when telemetry is disabled)."""
        return self.telemetry.port if self.telemetry is not None else None

    def stop_telemetry(self) -> None:
        """Close the telemetry listener (engine drain; idempotent)."""
        srv, self.telemetry = self.telemetry, None
        if srv is not None:
            srv.stop()

    def health_doc(self) -> dict[str, Any]:
        """The ``/healthz`` document: liveness + uptime (a process that
        answers at all is alive; readiness is :meth:`ready_status`)."""
        return {
            "ok": True,
            "node": self.node_id,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            # both halves of the handshake work: initiated attempts AND
            # inbound ke_inits admitted (a pure gateway only responds, so
            # a dashboard's hs/s must not read 0 off the initiator count)
            "handshake_attempts": self._handshake_latency.count,
            "handshakes_admitted": self._ctr_hs_admitted.value,
        }

    def ready_status(self) -> dict[str, Any]:
        """The ``/readyz`` document: ready = the background warm-up sweep
        finished (every warm bucket compiled — a cold gateway serves its
        first handshakes from the cpu fallback at cpu latency) AND no
        breaker is away from ``closed`` (an open/quarantined plane is
        serving degraded).  A load balancer keys on the 200/503 status;
        the body says WHY."""
        warm = self._warmup_thread is None or not self._warmup_thread.is_alive()
        breakers: dict[str, str] = {}
        if self._scheduler is not None:
            breakers = {f"shard{s.index}": s.breaker.state
                        for s in self._scheduler.shards}
        elif self._bkem is not None:
            breakers = {"breaker": self._bkem.breaker.state}
        degraded = sorted(k for k, st in breakers.items() if st != "closed")
        return {
            # a draining gateway answers 503 with the reason: the load
            # balancer routes around it and qrtop renders the DRAIN state
            # while the rolling restart is in flight
            "ready": warm and not degraded and not self.draining,
            "warm": warm,
            "breakers": breakers,
            "degraded": degraded,
            "draining": self.draining,
            "drain_reason": self.drain_reason,
        }

    def slo_report(self) -> dict[str, Any]:
        """The per-NODE SLO report document: one gateway process's burn
        evaluation plus the cumulative counters a fleet merge needs.
        fleet/gateway.py writes this as ``<node>_slo_report.json`` on
        shutdown; ``tools/slo_merge.py`` (or
        :func:`obs.slo.merge_reports`) folds N of them into one fleet
        report with worst-node attribution."""
        q = self._collect_queues()
        return {
            "node": self.node_id,
            "slo": self.slo.status(),
            "device_served_fraction": q.get("device_served_fraction"),
            "device_trips": q.get("device_trips", 0),
            "fallback_trips": q.get("fallback_trips", 0),
            "counters": {
                "handshakes_admitted": self._ctr_hs_admitted.value,
                "handshake_sheds": self._ctr_handshake_sheds.value,
                "connections_admitted": self.node.admitted,
                "connection_sheds": self.node.sheds,
                "handshake_giveups": self._ctr_handshake_giveups.value,
                "tickets_minted": self._ctr_tickets_minted.value,
                "resumes_ok": self._ctr_resumes_ok.value,
                "resume_rejects": self._ctr_resume_rejects.value,
            },
        }

    def metrics(self) -> dict[str, Any]:
        """Operational counters: per-queue stats, aggregate dispatch trips,
        operand-cache hit rates, and trips-per-initiated-handshake — read
        from the obs registry (obs/metrics.py), which is also what the
        Prometheus exporter and flight-recorder bundles serve.  The legacy
        key layout is a compatibility contract (tests/test_obs.py parity
        test): keys are never removed or renamed, only added."""
        out: dict[str, Any] = {
            "backend": self.backend,
            "batching": self.use_batching,
        }
        # the registry's collectors ARE the source; calling them directly
        # skips exporting every instrument just to read two dicts back
        out.update(self._collect_queues())
        out.update(self._collect_opcaches())
        t = self._handshake_trips
        out["handshake_trips"] = {
            "count": t.count,
            "last": int(t.last) if t.last is not None else None,
            "p50": t.percentile(50),
            "p99": t.percentile(99),
        }
        out["resilience"] = {
            "rekeys": self._ctr_rekeys.value,
            "heals_ok": self._ctr_heals_ok.value,
            "heals_failed": self._ctr_heals_failed.value,
            "outbox_queued": self._ctr_outbox_queued.value,
            "outbox_dropped": self._ctr_outbox_dropped.value,
            "handshake_giveups": self._ctr_handshake_giveups.value,
        }
        # the gateway section (docs/gateway.md; CLI /metrics): admission-
        # control state and the autotuner's live decisions — additive key,
        # same compatibility contract as "resilience"
        out["gateway"] = {
            "max_peers": self.node.max_peers,
            "connections_admitted": self.node.admitted,
            "connection_sheds": self.node.sheds,
            "busy_rejects": self.node.busy_rejects,
            "handshake_budget": self._hs_budget,
            "handshakes_in_flight": self._responding,
            "handshake_sheds": self._ctr_handshake_sheds.value,
            "bulk_sheds": self._ctr_bulk_sheds.value,
            "autotune": (self._autotuner.snapshot()
                         if self._autotuner is not None
                         else {"enabled": False}),
        }
        # the resumption/drain section (docs/protocol.md "Session
        # resumption") — additive key, same compatibility contract
        out["resumption"] = {
            "enabled": self.resumption,
            "tickets_minted": self._ctr_tickets_minted.value,
            "tickets_held": len(self._tickets),
            "resumes_ok": self._ctr_resumes_ok.value,
            "resume_rejects": self._ctr_resume_rejects.value,
            "resumes_used": self._ctr_resumes_used.value,
            "resume_fallbacks": self._ctr_resume_fallbacks.value,
            "replay_cache": len(self._replay),
            "draining": self.draining,
        }
        # the SLO section (docs/observability.md): burn rates and budget
        # remaining per objective — additive key, same compatibility
        # contract as "resilience"/"gateway".  This evaluates the engine,
        # as does the registry's "slo_health" collector on every
        # snapshot/Prometheus scrape — whichever surface a gateway is
        # watched through, the burn windows advance.
        out["slo"] = self.slo.status()
        # the device-cost ledger (obs/cost.py; docs/observability.md
        # "Reading the cost ledger") — additive key, same contract
        out["cost"] = self.cost.snapshot()
        return out

    def _spawn_warmup(self, kem: bool = True, sig: bool = True) -> None:
        """Precompile batched providers' size-1 buckets in the background so
        a live handshake's cold jit never races KEY_EXCHANGE_TIMEOUT
        (SURVEY.md §7.4 item 6; the round-1 flake).  Called at construction
        AND after an algorithm hot-swap (only for the swapped provider — the
        other is already warm).  cpu-backend algorithms have no jit cache to
        warm, so they are skipped (their warmup would run real slow crypto)."""
        import threading

        bkem = self._bkem if kem and getattr(self.kem, "backend", "") == "tpu" else None
        bsig = (
            self._bsig if sig and getattr(self.signature, "backend", "") == "tpu" else None
        )
        # the fused facade is rebuilt on every swap (it bakes in the pair AND
        # the transcript offsets), so whenever it exists it needs a warm;
        # likewise the batched-AEAD facade (rebuilt on every AEAD swap)
        bfused = self._bfused
        baead = self._baead
        if bkem is None and bsig is None and bfused is None and baead is None:
            return

        def _warm():
            try:
                # Device-health gate first (provider/health.py): validate the
                # accelerated path for THIS environment before trusting it
                # with live traffic — a failed family quarantines the shared
                # breaker onto the cpu fallback, and HQC re-routes its FFT.
                from ..provider import health

                health.gate_facades(bkem, bsig, bfused, baead)
                first = bkem or bsig or bfused or baead
                if first is not None and first.breaker.state == "quarantined":
                    # the facades share one breaker: a quarantine pins the
                    # cpu fallback for the process, so compiling the device
                    # buckets would burn minutes for a path that can never
                    # serve traffic
                    logger.warning(
                        "device path quarantined by the health gate; "
                        "skipping device warmup"
                    )
                    return
                if bkem is not None:
                    bkem.warmup(WARMUP_SIZES)
                if bsig is not None:
                    bsig.warmup(WARMUP_SIZES)
                if bfused is not None:
                    bfused.warmup(WARMUP_SIZES)
                if baead is not None:
                    baead.warmup(WARMUP_SIZES)
            except Exception:
                logger.exception("batched-provider warmup failed")

        self._warmup_thread = threading.Thread(
            target=_warm, name="qrp2p-warmup", daemon=True
        )
        self._warmup_thread.start()

    async def wait_ready(self, timeout: float | None = None) -> None:
        """Await background batched-provider warmup (no-op when batching off)."""
        if self._warmup_thread is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self._warmup_thread.join, timeout
            )

    def _drop_ephemeral(self, message_id: str) -> None:
        """Drop an exchange's ephemeral KEM sk, zeroizing it in place — the
        single chokepoint for every drop path, so a future path cannot
        forget the wipe.  In-flight decapsulations are safe: the handlers
        pass an immutable COPY of the sk to the crypto layer, never the
        wiped buffer itself."""
        entry = self._ephemeral.pop(message_id, None)
        if entry is not None:
            _wipe(entry[1])

    def _cleanup_exchange(self, message_id: str, peer_id: str) -> None:
        self._drop_ephemeral(message_id)
        self._pending.pop(message_id, None)
        if self.ke_state.get(peer_id) == KeyExchangeState.INITIATED:
            self.ke_state[peer_id] = KeyExchangeState.NONE

    async def _reject(self, peer_id: str, message_id: str, reason: RejectReason) -> None:
        await self.node.send_message(
            peer_id, "ke_reject", message_id=message_id, reason=reason.value
        )

    async def _check_common(self, peer_id: str, data: dict, sig: bytes, sig_pk: bytes,
                            sig_algo: str,
                            lane: int = LANE_HANDSHAKE) -> RejectReason | None:
        """Signature + identity + replay-window checks shared by init/response."""
        try:
            ok = await self._verify(sig_algo, sig_pk, _canonical(data), sig,
                                    lane)
        except LaneShed:
            # handshake-lane shed (a hand-capped lane): a typed, transient
            # BUSY — disjoint from any signature verdict
            return RejectReason.BUSY
        if ok is None:
            return RejectReason.ALGORITHM_MISMATCH
        if not ok:
            return RejectReason.INVALID_SIGNATURE
        return self._check_host(peer_id, data)

    def _check_host(self, peer_id: str, data: dict) -> RejectReason | None:
        """The host-side half of _check_common (identity + replay window).
        The fused handshake paths run these BEFORE dispatch and let the
        signature check ride the composite device program — so a message
        failing several checks at once may draw a different (equally valid)
        typed rejection than the per-op path would."""
        if data.get("sender") != peer_id or data.get("recipient") != self.node_id:
            return RejectReason.IDENTITY_MISMATCH
        if abs(time.time() - float(data.get("timestamp", 0))) > REPLAY_WINDOW:
            return RejectReason.TIMESTAMP_INVALID
        return None

    async def _handle_ke_init(self, peer_id: str, msg: dict) -> None:
        """Responder: verify, encapsulate, derive, reply (reference: :695-905).

        Admission control first: over the concurrent-handshake budget, the
        init draws a typed BUSY rejection — a fast, retryable shed instead
        of joining a pile-up that times every initiator out.  Re-keys of
        established peers are EXEMPT from the budget (they ride the top
        priority lane; shedding them would cost a live session)."""
        data = msg.get("ke_data") or {}
        message_id = data.get("message_id", "?")
        if self.draining:
            # draining: EVERYTHING new is shed (rekeys included — the
            # peers are being nudged to the ring successor); the typed
            # BUSY keeps the initiator's retry machinery in charge
            self._shed_handshake(peer_id)
            await self._reject(peer_id, message_id, RejectReason.BUSY)
            return
        if (
            self._hs_budget
            and self._responding >= self._hs_budget
            and not self._is_rekey(peer_id)
        ):
            self._shed_handshake(peer_id)
            await self._reject(peer_id, message_id, RejectReason.BUSY)
            return
        self._responding += 1
        self._ctr_hs_admitted.inc()  # the shed-rate SLO's "good" side
        try:
            with obs_trace.span("handshake.respond", peer=peer_id[:8],
                                kem=self.kem.name):
                await self._handle_ke_init_inner(peer_id, msg, data, message_id)
        finally:
            self._responding -= 1

    def _shed_handshake(self, peer_id: str) -> None:
        self._ctr_handshake_sheds.inc()
        n = self._ctr_handshake_sheds.value
        if n == 1 or n % 64 == 0:
            logger.warning(
                "handshake budget reached (%d in flight, max %d): shedding "
                "ke_init from %s (%d shed so far)",
                self._responding, self._hs_budget, peer_id[:8], n,
            )
            obs_flight.record(
                "load_shed", where="handshake", peer=peer_id[:8],
                in_flight=self._responding, budget=self._hs_budget, sheds=n,
            )

    async def _handle_ke_init_inner(self, peer_id: str, msg: dict, data: dict,
                                    message_id: str) -> None:
        lane = self._hs_lane(peer_id)
        if await self._fused_handle_ke_init(peer_id, msg, data, message_id,
                                            lane):
            return
        err = await self._check_common(peer_id, data, msg.get("sig", b""),
                                 msg.get("sig_pk", b""), msg.get("sig_algo", ""),
                                 lane)
        if err is not None:
            await self._reject(peer_id, message_id, err)
            return
        if data.get("kem") != self.kem.name or data.get("aead") != self.symmetric.name:
            await self._reject(peer_id, message_id, RejectReason.ALGORITHM_MISMATCH)
            return
        try:
            ct, secret = await self._kem_encaps(bytes.fromhex(data["public_key"]),
                                                lane)
        except Exception:
            logger.exception("encapsulation failed")
            await self._reject(peer_id, message_id, RejectReason.ENCAPSULATION_ERROR)
            return
        resp = {
            "message_id": message_id,
            "ciphertext": ct.hex(),
            "sender": self.node_id,
            "recipient": peer_id,
            "timestamp": time.time(),
        }
        sig = await self._sign(_canonical(resp), lane)
        await self._respond_established(peer_id, secret, resp, sig)

    async def _respond_established(self, peer_id: str, secret: bytes,
                                   resp: dict, sig: bytes) -> None:
        """Responder success tail, shared by the per-op and fused ke_init
        paths (contractually wire-identical): adopt the shared secret and
        send the signed ke_response."""
        self._adopt_secret(peer_id, secret)
        self.shared_keys[peer_id] = derive_message_key(
            secret, self.node_id, peer_id, self.symmetric.name
        )
        self.ke_state[peer_id] = KeyExchangeState.RESPONDED
        # the resumption ticket rides INSIDE the ke_response frame (extra
        # unsigned sibling fields, negotiated-only — un-negotiated peers'
        # frames are byte-identical): the initiator holds the ticket in
        # the same instant it considers the session live, so a gateway
        # death/drain at ANY later point finds it already delivered.  (A
        # separate ticket frame left one loop-scheduling window where an
        # interrupted session reconnected ticketless — measured in the
        # roll storm.)  The initiator is already signature-authenticated
        # by its ke_init, and a tampered ticket field can only produce a
        # typed resume reject + full-handshake fallback later.  A DRAINING
        # responder still mints: a session established at drain onset is
        # exactly the one about to be nudged to the ring successor.
        extra: dict[str, Any] = {}
        if self._resumption_negotiated(peer_id):
            blob, expires_at = self._mint_ticket(peer_id)
            extra = {"ticket": blob, "ticket_expires": expires_at}
        await self.node.send_message(
            peer_id,
            "ke_response",
            ke_data=resp,
            sig=sig,
            sig_algo=self.signature.name,
            sig_pk=self._sig_keypair[0],
            **extra,
        )

    async def _fused_handle_ke_init(self, peer_id: str, msg: dict, data: dict,
                                    message_id: str,
                                    lane: int = LANE_HANDSHAKE) -> bool:
        """Composite responder step: verify(init) + encaps + sign(response)
        in ONE device trip.  True = handled (replied or rejected); False =
        not applicable (no capability, algorithm/shape mismatch, composite
        failure) — the caller falls through to the per-op path, which owns
        every typed rejection for malformed input."""
        f = self._bfused
        if f is None or msg.get("sig_algo", "") != self.signature.name:
            return False
        if data.get("kem") != self.kem.name or data.get("aead") != self.symmetric.name:
            return False  # per-op path sends ALGORITHM_MISMATCH
        err = self._check_host(peer_id, data)
        if err is not None:
            await self._reject(peer_id, message_id, err)
            return True
        try:
            peer_pk = bytes.fromhex(data.get("public_key", ""))
        except (TypeError, ValueError):  # non-str JSON value raises TypeError
            return False
        sig_pk, sig_in = msg.get("sig_pk", b""), msg.get("sig", b"")
        if (
            len(peer_pk) != self.kem.public_key_len
            or len(sig_pk) != self.signature.public_key_len
            or len(sig_in) != self.signature.signature_len
        ):
            return False
        resp = {
            "message_id": message_id,
            "ciphertext": "0" * (2 * self.kem.ciphertext_len),
            "sender": self.node_id,
            "recipient": peer_id,
            "timestamp": time.time(),
        }
        template = _canonical(resp)
        if len(template) > f.fused.resp_template_len:
            return False
        try:
            ok, ct, secret, sig = await f.encaps_verify_sign(
                peer_pk, sig_pk, _canonical(data), sig_in,
                self._sig_keypair[1], template, lane,
            )
        except Exception:
            logger.exception("fused encaps_verify_sign failed; per-op fallback")
            return False
        if not ok:
            _wipe(secret)  # encapsulated for a peer whose signature failed
            await self._reject(peer_id, message_id, RejectReason.INVALID_SIGNATURE)
            return True
        resp["ciphertext"] = ct.hex()
        await self._respond_established(peer_id, secret, resp, sig)
        return True

    async def _handle_ke_response(self, peer_id: str, msg: dict) -> None:
        """Initiator: verify, decapsulate, confirm + AEAD test (ref: :907-1146)."""
        data = msg.get("ke_data") or {}
        message_id = data.get("message_id", "?")
        entry = self._ephemeral.get(message_id)
        if entry is None or entry[0] != peer_id:
            logger.warning("ke_response for unknown exchange %s", message_id)
            return
        with obs_trace.span("handshake.confirm", peer=peer_id[:8]):
            await self._handle_ke_response_inner(peer_id, msg, data,
                                                 message_id, entry)

    async def _handle_ke_response_inner(self, peer_id: str, msg: dict,
                                        data: dict, message_id: str,
                                        entry) -> None:
        lane = self._hs_lane(peer_id)
        fused = await self._fused_handle_ke_response(
            peer_id, msg, data, message_id, entry, lane
        )
        if fused is _HANDLED:
            return
        if fused is not None:
            secret, sig = fused
        else:
            err = await self._check_common(peer_id, data, msg.get("sig", b""),
                                     msg.get("sig_pk", b""), msg.get("sig_algo", ""),
                                     lane)
            if err is not None:
                self._fail_pending(message_id, err.value)
                return
            try:
                # decapsulate a COPY: if the handshake timeout fires during
                # this await, _cleanup_exchange wipes the stored bytearray —
                # which must not zero the operand mid-decapsulation
                secret = await self._kem_decaps(bytes(entry[1]),
                                                bytes.fromhex(data["ciphertext"]),
                                                lane)
            except Exception:
                logger.exception("decapsulation failed")
                self._fail_pending(message_id, "decapsulation_error")
                return
            finally:
                # Delete AND zeroize the ephemeral secret key immediately
                # (reference: :1041) — decapsulation is done with it either way.
                self._drop_ephemeral(message_id)
            sig = None

        self._adopt_secret(peer_id, secret)
        key = derive_message_key(secret, self.node_id, peer_id, self.symmetric.name)
        self.shared_keys[peer_id] = key
        self.ke_state[peer_id] = KeyExchangeState.CONFIRMED
        self._save_peer_key(peer_id, secret)
        # the responder's resumption ticket rides this same frame: store
        # it in the same instant the session becomes live (no window in
        # which an interrupted session is established-but-ticketless)
        self._accept_ticket(peer_id, msg, secret)

        confirm = {
            "message_id": message_id,
            "sender": self.node_id,
            "recipient": peer_id,
            "timestamp": time.time(),
        }
        if sig is None:
            sig = await self._sign(_canonical(confirm), lane)
        else:
            # the fused step signed the confirm transcript it was handed
            confirm = self._fused_confirm.pop(message_id)
        await self.node.send_message(
            peer_id, "ke_confirm", ke_data=confirm, sig=sig,
            sig_algo=self.signature.name, sig_pk=self._sig_keypair[0],
        )
        test_ct = self.symmetric.encrypt(key, b"key-exchange-test", message_id.encode())
        await self.node.send_message(peer_id, "ke_test", ct=test_ct, message_id=message_id)

        self._log(
            "key_exchange", peer=peer_id, success=True,
            algorithm=self.kem.name, role="initiator",
        )
        fut = self._pending.pop(message_id, None)
        if fut is not None and not fut.done():
            fut.set_result(True)

    async def _fused_handle_ke_response(self, peer_id: str, msg: dict,
                                        data: dict, message_id: str, entry,
                                        lane: int = LANE_HANDSHAKE):
        """Composite initiator step: verify(response) + decaps +
        sign(confirm transcript) in ONE device trip.  Returns
        (shared_secret, confirm_sig) on success; ``_HANDLED`` when the
        exchange was failed here (the composite verify failing maps to
        INVALID_SIGNATURE, matching the per-op rejection for a bad response
        signature); None when not applicable (caller runs the per-op path).
        The signed confirm transcript is parked in ``_fused_confirm`` so
        the caller sends EXACTLY the signed bytes.
        """
        f = self._bfused
        if f is None or msg.get("sig_algo", "") != self.signature.name:
            return None
        err = self._check_host(peer_id, data)
        if err is not None:
            self._fail_pending(message_id, err.value)
            self._drop_ephemeral(message_id)
            return _HANDLED
        try:
            ct = bytes.fromhex(data.get("ciphertext", ""))
        except (TypeError, ValueError):  # non-str JSON value raises TypeError
            return None
        sig_pk, sig_in = msg.get("sig_pk", b""), msg.get("sig", b"")
        if (
            len(ct) != self.kem.ciphertext_len
            or len(sig_pk) != self.signature.public_key_len
            or len(sig_in) != self.signature.signature_len
        ):
            return None
        confirm = {
            "message_id": message_id,
            "sender": self.node_id,
            "recipient": peer_id,
            "timestamp": time.time(),
        }
        try:
            # COPY of the ephemeral sk: a timeout-path wipe racing this
            # await must not zero the composite dispatch's operand
            ok, secret, sig = await f.decaps_verify_sign(
                bytes(entry[1]), ct, sig_pk, _canonical(data), sig_in,
                self._sig_keypair[1], _canonical(confirm), lane,
            )
        except Exception:
            logger.exception("fused decaps_verify_sign failed; per-op fallback")
            return None
        if not ok:
            _wipe(secret)  # decapsulated under a signature that failed
            self._fail_pending(message_id, RejectReason.INVALID_SIGNATURE.value)
            self._drop_ephemeral(message_id)
            return _HANDLED
        self._drop_ephemeral(message_id)  # composite decaps used a copy
        self._fused_confirm[message_id] = confirm
        return secret, sig

    def _fail_pending(self, message_id: str, reason: str) -> None:
        fut = self._pending.pop(message_id, None)
        if fut is not None and not fut.done():
            fut.set_exception(KeyExchangeFailed(reason))

    async def _handle_ke_confirm(self, peer_id: str, msg: dict) -> None:
        data = msg.get("ke_data") or {}
        err = await self._check_common(peer_id, data, msg.get("sig", b""),
                                 msg.get("sig_pk", b""), msg.get("sig_algo", ""))
        if err is not None:
            logger.warning("bad ke_confirm from %s: %s", peer_id[:8], err.value)
            return
        if self.ke_state.get(peer_id) == KeyExchangeState.RESPONDED:
            self.ke_state[peer_id] = KeyExchangeState.ESTABLISHED
            secret = self.raw_secrets.get(peer_id)
            if secret is not None:
                self._save_peer_key(peer_id, secret)
            self._log(
                "key_exchange", peer=peer_id, success=True,
                algorithm=self.kem.name, role="responder",
            )

    async def _handle_ke_test(self, peer_id: str, msg: dict) -> None:
        key = self.shared_keys.get(peer_id)
        if key is None:
            return
        try:
            # bytes(): over the binary wire the ct is a zero-copy
            # memoryview, which stdlib scalar AEADs cannot concatenate
            pt = self.symmetric.decrypt(
                key, bytes(msg.get("ct", b"")),
                str(msg.get("message_id", "")).encode()
            )
        except ValueError:
            logger.warning("ke_test decrypt failed from %s", peer_id[:8])
            return
        if pt == b"key-exchange-test":
            sysmsg = Message(
                content=b"Secure connection established",
                sender_id=peer_id,
                recipient_id=self.node_id,
                is_system=True,
                key_exchange_algo=self.kem.name,
                symmetric_algo=self.symmetric.name,
                signature_algo=self.signature.name,
            )
            self._notify(peer_id, sysmsg)

    async def _handle_ke_reject(self, peer_id: str, msg: dict) -> None:
        """Typed rejection handling (reference: :1282-1337)."""
        message_id = str(msg.get("message_id", ""))
        reason = str(msg.get("reason", "unknown"))
        logger.warning("key exchange rejected by %s: %s", peer_id[:8], reason)
        self._drop_ephemeral(message_id)
        self.ke_state[peer_id] = KeyExchangeState.NONE
        self._log("key_exchange", peer=peer_id, success=False, reason=reason)
        self._fail_pending(message_id, reason)

    def _adopt_secret(self, peer_id: str, secret: bytes) -> None:
        """Install a session's raw KEM shared secret, zeroizing any
        predecessor in place (rekey/re-handshake must not extend the old
        secret's lifetime)."""
        _wipe(self.raw_secrets.get(peer_id))
        self.raw_secrets[peer_id] = bytearray(secret)
        # this peer now has a completed session: its NEXT handshake (for
        # HAD_SESSION_TTL_S) is a re-key on the top-priority lane
        self._had_session[peer_id] = time.monotonic()
        # the connection's resume window closes with establishment: any
        # later handshake on this connection is an in-session rekey and
        # runs the full KEM exchange for fresh entropy
        self._resume_armed.discard(peer_id)

    def _save_peer_key(self, peer_id: str, secret: bytes) -> None:
        if self.key_storage is not None and getattr(self.key_storage, "is_unlocked", False):
            try:
                self.key_storage.save_peer_shared_key(peer_id, secret, self.kem.name)
            except Exception:
                logger.exception("failed to persist shared key")

    # ------------------------------------------------- session resumption
    #
    # docs/protocol.md "Session resumption": after a confirmed full
    # handshake the RESPONDER mints a STEK-sealed, self-contained ticket;
    # a reconnect presents it for a 1-RTT abbreviated exchange (HKDF over
    # the resumption secret + fresh nonces — no KEM, no signatures, no
    # device dispatch).  Hostile/expired/replayed tickets fall back loudly
    # to the full handshake, never to a stall; accepted resumes are
    # admission-EXEMPT, which is what keeps admission control survivable
    # during a reconnect storm (the gateway sheds full handshakes but
    # admits cheap resumes).

    def _resumption_negotiated(self, peer_id: str) -> bool:
        """True when BOTH sides offered resumption in their hellos (the
        same negotiation shape as the binary wire): an opted-out or older
        peer never sees a ticket/resume frame — its wire stays
        byte-identical to the pre-resumption protocol (pinned)."""
        return self.resumption and self.node.peer_resumption(peer_id)

    def _resume_allowed(self, peer_id: str) -> bool:
        """A resume may be attempted only on a FRESH connection (armed by
        the connect event, disarmed at establishment) with a live,
        unexpired ticket from this peer."""
        if not (self._resumption_negotiated(peer_id)
                and peer_id in self._resume_armed):
            return False
        return self.ticket_for(peer_id) is not None

    def ticket_for(self, peer_id: str) -> dict | None:
        """The held (unexpired) resumption ticket entry for ``peer_id``,
        or None.  Expired entries are dropped (secret wiped) here."""
        entry = self._tickets.get(peer_id)
        if entry is None:
            return None
        if entry["expires_at"] <= time.time():
            self._drop_ticket(peer_id)
            return None
        return entry

    def take_ticket(self, peer_id: str) -> dict | None:
        """Remove and return the held ticket entry for ``peer_id`` (the
        fleet-handoff transfer API: a ticket minted by a dead gateway is
        presented to its ring successor, which shares the STEK)."""
        return self._tickets.pop(peer_id, None)

    def adopt_ticket(self, peer_id: str, entry: dict | None) -> None:
        """Re-key a transferred ticket entry to a new peer (the successor
        half of :meth:`take_ticket`)."""
        if entry is not None:
            self._drop_ticket(peer_id)
            self._tickets[peer_id] = entry

    def _drop_ticket(self, peer_id: str) -> None:
        entry = self._tickets.pop(peer_id, None)
        if entry is not None:
            _wipe(entry["secret"])

    def _store_ticket(self, peer_id: str, blob: bytes, expires_at: float,
                      secret: bytes) -> None:
        """Install a received ticket (bounded; oldest-expiry eviction with
        secrets wiped — the client-side memory half of the ticket story)."""
        self._drop_ticket(peer_id)
        self._tickets[peer_id] = {
            "ticket": blob,
            "expires_at": expires_at,
            "secret": bytearray(secret),
        }
        if len(self._tickets) > TICKET_CAP:
            for pid, _e in sorted(self._tickets.items(),
                                  key=lambda kv: kv[1]["expires_at"])[
                    : TICKET_CAP // 2]:
                self._drop_ticket(pid)

    def _mint_ticket(self, peer_id: str) -> tuple[bytes, float]:
        """Responder: seal a fresh ticket for ``peer_id``'s live session
        (single-use nonce, current STEK, suite-bound) — attached to the
        ke_response frame by :meth:`_respond_established`."""
        secret = self.raw_secrets[peer_id]
        rsec = derive_resumption_secret(bytes(secret), self.node_id, peer_id)
        expires_at = time.time() + RESUME_TICKET_TTL_S
        blob = self.tickets.seal_ticket(mint_fields(
            peer_id, self.node_id, rsec, self.kem.name, self.symmetric.name,
            self.signature.name, expires_at))
        self._ctr_tickets_minted.inc()
        obs_flight.record("ticket_minted", peer=peer_id[:8],
                          epoch=self.tickets.current_epoch,
                          expires_at=round(expires_at, 3))
        _wipe(rsec)  # sealed into the ticket; the local copy is done
        return blob, expires_at

    def _accept_ticket(self, peer_id: str, msg: dict, secret: bytes) -> None:
        """Initiator: store the ticket riding a ke_response (with the
        locally re-derived resumption secret) for the next reconnect."""
        if not self._resumption_negotiated(peer_id):
            return
        blob = bytes(msg.get("ticket") or b"")
        if not blob or len(blob) > 4096:
            return
        rsec = derive_resumption_secret(bytes(secret), peer_id, self.node_id)
        self._store_ticket(peer_id, blob,
                           float(msg.get("ticket_expires") or 0.0), rsec)
        obs_flight.record("ticket_received", peer=peer_id[:8])

    async def _resume_once(self, peer_id: str) -> str:
        """One abbreviated 1-RTT resume attempt -> "ok" | a typed failure.
        The held ticket is consumed either way (single-use): success
        returns a fresh one, failure falls back to a full handshake whose
        confirm mints a fresh one."""
        with obs_trace.node_scope(self.node_id), \
                obs_trace.span("handshake.resume", peer=peer_id[:8]) as sp, \
                self._handshake_latency.time():
            status = await self._resume_attempt(peer_id)
            sp.set_attr("status", status)
            return status

    async def _resume_attempt(self, peer_id: str) -> str:
        entry = self._tickets.pop(peer_id, None)
        if entry is None:
            return "no_ticket"
        message_id = str(uuid.uuid4())
        client_nonce = os.urandom(16).hex()
        data = {
            "message_id": message_id,
            "sender": self.node_id,
            "recipient": peer_id,
            "timestamp": time.time(),
            "client_nonce": client_nonce,
            "aead": self.symmetric.name,
        }
        binder = resume_binder(bytes(entry["secret"]), _canonical(data),
                               entry["ticket"])
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[message_id] = fut
        self._resume_pending[message_id] = {
            "peer": peer_id,
            "secret": entry["secret"],
            "client_nonce": client_nonce,
        }
        self.ke_state[peer_id] = KeyExchangeState.INITIATED
        sent = await self.node.send_message(
            peer_id, "ke_resume", resume_data=data, ticket=entry["ticket"],
            binder=binder,
        )
        if not sent:
            self._cleanup_resume(message_id, peer_id)
            return "send_failed"
        try:
            await asyncio.wait_for(fut, KEY_EXCHANGE_TIMEOUT)
            return "ok"
        except asyncio.TimeoutError:
            self._cleanup_resume(message_id, peer_id)
            return "timeout"
        except RuntimeError as e:
            self._cleanup_resume(message_id, peer_id)
            return getattr(e, "reason", "error")

    def _cleanup_resume(self, message_id: str, peer_id: str) -> None:
        ctx = self._resume_pending.pop(message_id, None)
        if ctx is not None:
            _wipe(ctx["secret"])
        self._pending.pop(message_id, None)
        if self.ke_state.get(peer_id) == KeyExchangeState.INITIATED:
            self.ke_state[peer_id] = KeyExchangeState.NONE

    async def _handle_ke_resume(self, peer_id: str, msg: dict) -> None:
        """Responder: validate a presented ticket and run the abbreviated
        exchange.  EVERY failure is a typed ``ke_resume_reject`` the
        initiator maps to a full-handshake fallback — no plaintext, no
        stall; accepted resumes bypass the handshake admission budget
        (they are what admission control exists to protect)."""
        data = msg.get("resume_data") or {}
        message_id = str(data.get("message_id", "?"))
        with obs_trace.span("handshake.resume_respond", peer=peer_id[:8]):
            reason = await self._resume_respond(peer_id, msg, data,
                                                message_id)
        if reason is not None:
            self._ctr_resume_rejects.inc()
            logger.warning(
                "ticket resume from %s rejected (%s); peer falls back to a "
                "full handshake (%d rejected so far)",
                peer_id[:8], reason, self._ctr_resume_rejects.value,
            )
            obs_flight.record("ticket_reject", peer=peer_id[:8],
                              reason=reason)
            await self.node.send_message(peer_id, "ke_resume_reject",
                                         message_id=message_id,
                                         reason=reason)

    async def _resume_respond(self, peer_id: str, msg: dict, data: dict,
                              message_id: str) -> str | None:
        """-> None on success (reply sent), else the typed reject reason."""
        if not self._resumption_negotiated(peer_id):
            return "resumption_disabled"
        if self.draining:
            return "draining"
        err = self._check_host(peer_id, data)
        if err is not None:
            return err.value
        client_nonce = str(data.get("client_nonce", ""))
        if not client_nonce or len(client_nonce) > 64:
            return "malformed_ticket"
        blob = bytes(msg.get("ticket") or b"")
        # chaos seam (faults/plan.py "ticket" scope): a plan may corrupt
        # the presented blob or force the expiry/replay verdicts — each
        # exercises one typed reject + fallback path end-to-end
        forced = _faults.ticket_validation(self.node_id, peer_id)
        if "corrupt" in forced and blob:
            doctored = bytearray(blob)
            doctored[len(doctored) // 2] ^= 0xFF
            blob = bytes(doctored)
        try:
            fields, rsec = self.tickets.open_ticket(blob)
        except TicketError as e:
            return e.reason
        # every exit below — typed reject or success — drops the opened
        # resumption secret (the success path adopts a bytearray COPY)
        next_secret = b""
        try:
            expires_at = float(fields.get("expires_at") or 0.0)
            nonce = str(fields.get("nonce") or "")
            if not nonce:
                return "malformed_ticket"
            if "expire" in forced or expires_at <= time.time():
                return "expired_ticket"
            if fields.get("holder") != peer_id:
                return "holder_mismatch"
            if (fields.get("kem"), fields.get("aead"), fields.get("sig")) != (
                    self.kem.name, self.symmetric.name, self.signature.name):
                return "suite_mismatch"
            want = resume_binder(rsec, _canonical(data), blob)
            if not hmac.compare_digest(want, str(msg.get("binder", ""))):
                return "bad_binder"
            if "replay" in forced or self._replay.seen(nonce, expires_at,
                                                       time.time()):
                return "replayed_ticket"
            # accepted: derive, install, re-mint (single-use), confirm — the
            # whole exchange is host-side HKDF/HMAC, ~0 device-seconds (the
            # cost ledger's resume probe pins that claim in the storm bench)
            server_nonce = os.urandom(16).hex()
            key = derive_resumed_key(rsec, client_nonce, server_nonce,
                                     self.symmetric.name)
            next_secret = ratchet_resumption_secret(rsec, client_nonce,
                                                    server_nonce)
            fresh_expires = time.time() + RESUME_TICKET_TTL_S
            fresh = self.tickets.seal_ticket(mint_fields(
                peer_id, self.node_id, next_secret, self.kem.name,
                self.symmetric.name, self.signature.name, fresh_expires))
            self._adopt_secret(peer_id, rsec)
            self.shared_keys[peer_id] = key
            self.ke_state[peer_id] = KeyExchangeState.ESTABLISHED
            self._ctr_resumes_ok.inc()
            self._ctr_tickets_minted.inc()
            obs_flight.record("ticket_resumed", peer=peer_id[:8],
                              role="responder")
            self._log("key_exchange", peer=peer_id, success=True,
                      algorithm="ticket_resume", role="responder")
            await self.node.send_message(
                peer_id, "ke_resume_ok", message_id=message_id,
                server_nonce=server_nonce,
                confirm=resume_confirm_tag(key, message_id, client_nonce,
                                           server_nonce),
                ticket=fresh, expires_at=fresh_expires,
            )
            return None
        finally:
            _wipe(rsec)
            _wipe(next_secret)

    async def _handle_ke_resume_ok(self, peer_id: str, msg: dict) -> None:
        """Initiator: verify the responder's proof-of-secret, install the
        resumed key, store the fresh ticket (ratcheted secret)."""
        message_id = str(msg.get("message_id", ""))
        ctx = self._resume_pending.get(message_id)
        if ctx is None or ctx["peer"] != peer_id:
            logger.warning("ke_resume_ok for unknown resume %s", message_id)
            return
        self._resume_pending.pop(message_id, None)
        server_nonce = str(msg.get("server_nonce", ""))
        rsec = bytes(ctx["secret"])
        key = derive_resumed_key(rsec, ctx["client_nonce"], server_nonce,
                                 self.symmetric.name)
        want = resume_confirm_tag(key, message_id, ctx["client_nonce"],
                                  server_nonce)
        if not (server_nonce and len(server_nonce) <= 64
                and hmac.compare_digest(want, str(msg.get("confirm", "")))):
            _wipe(ctx["secret"])
            self._fail_pending(message_id, "bad_confirm")
            return
        self._adopt_secret(peer_id, rsec)
        _wipe(ctx["secret"])
        self.shared_keys[peer_id] = key
        self.ke_state[peer_id] = KeyExchangeState.ESTABLISHED
        fresh = bytes(msg.get("ticket") or b"")
        if fresh:
            # ratchet only when there is a ticket to bind it to — no
            # fresh ticket means no stored secret to account for
            next_secret = ratchet_resumption_secret(rsec, ctx["client_nonce"],
                                                    server_nonce)
            self._store_ticket(peer_id, fresh,
                               float(msg.get("expires_at") or 0.0),
                               next_secret)
        self._ctr_resumes_used.inc()
        obs_flight.record("ticket_resumed", peer=peer_id[:8],
                          role="initiator")
        self._log("key_exchange", peer=peer_id, success=True,
                  algorithm="ticket_resume", role="initiator")
        fut = self._pending.pop(message_id, None)
        if fut is not None and not fut.done():
            fut.set_result(True)

    async def _handle_ke_resume_reject(self, peer_id: str, msg: dict) -> None:
        """Initiator: a typed resume rejection — release the parked
        context and fail the pending future (the caller falls back to the
        full handshake, loudly)."""
        message_id = str(msg.get("message_id", ""))
        reason = str(msg.get("reason", "unknown"))[:64]
        ctx = self._resume_pending.pop(message_id, None)
        if ctx is not None:
            _wipe(ctx["secret"])
        if self.ke_state.get(peer_id) == KeyExchangeState.INITIATED:
            self.ke_state[peer_id] = KeyExchangeState.NONE
        self._fail_pending(message_id, reason)

    # ---------------------------------------------------------- graceful drain

    async def drain(self, reason: str = "drain") -> dict[str, Any]:
        """Graceful drain (docs/robustness.md "Rolling restarts"): stop
        admitting (new handshakes shed BUSY, resumes draw a typed
        ``draining`` reject, /readyz answers 503), flush every healable
        outbox, then nudge every connected peer (``ke_rehome``) to resume
        on its ring successor — their held tickets make that reconnect a
        cheap 1-RTT resume instead of a full-handshake storm.  Idempotent."""
        if self.draining:
            return {"reason": self.drain_reason, "already_draining": True}
        self.draining = True
        self.drain_reason = reason
        peers = self.node.get_peers()
        obs_flight.trigger("drain_started", node=self.node_id[:8],
                           reason=reason, peers=len(peers))
        flushed = 0
        for peer_id in list(self._outbox):
            queued = len(self._outbox.get(peer_id, ()))
            if queued and self.verify_key_exchange_state(peer_id):
                await self._flush_outbox(peer_id)
                flushed += queued
        nudged = 0
        for peer_id in self.node.get_peers():
            if await self.node.send_message(peer_id, "ke_rehome",
                                            reason=reason):
                nudged += 1
        logger.warning(
            "draining (%s): admission stopped; %d queued message(s) "
            "flushed, %d peer(s) nudged to resume elsewhere",
            reason, flushed, nudged,
        )
        obs_flight.record("drain_done", node=self.node_id[:8], nudged=nudged,
                          flushed=flushed)
        return {"reason": reason, "nudged": nudged, "flushed": flushed}

    async def _handle_ke_rehome(self, peer_id: str, msg: dict) -> None:
        """A peer announced it is draining: the disconnect that follows is
        PLANNED — surfaced to listeners so apps can re-route proactively
        (the fleet storm clients re-route on the drop either way; their
        ticket makes the new gateway a 1-RTT resume)."""
        reason = str(msg.get("reason", ""))[:64]
        self._ctr_rehome_nudges.inc()
        obs_flight.record("rehome_nudge", peer=peer_id[:8], reason=reason)
        logger.info("peer %s is draining (%s); expect a planned disconnect",
                    peer_id[:8], reason)
        self._notify(peer_id, Message(
            content=b"Peer draining: reconnect will resume via ticket",
            sender_id=peer_id, recipient_id=self.node_id, is_system=True,
            key_exchange_algo=self.kem.name,
            symmetric_algo=self.symmetric.name,
            signature_algo=self.signature.name,
        ))

    # --------------------------------------------------------- secure message

    async def send_message(
        self,
        peer_id: str,
        content: bytes,
        is_file: bool = False,
        filename: str | None = None,
    ) -> Message | None:
        """Sign-then-encrypt send (reference: :1560-1668).

        While a dropped session is healing (reconnect + re-handshake in
        flight), the message is queued in the bounded outbox and delivered —
        encrypted under the POST-heal key — once the session re-establishes;
        the returned Message is the queued one.  With no heal in progress
        and no session, returns None as before (fail closed).
        """
        if not self.node.is_connected(peer_id) and peer_id in self._healing:
            return self._queue_outbound(peer_id, content, is_file, filename)
        if not self.verify_key_exchange_state(peer_id):
            ok = await self.initiate_key_exchange(peer_id)
            if not ok and peer_id in self._healing:
                return self._queue_outbound(peer_id, content, is_file, filename)
            if not ok and peer_id not in self.shared_keys:
                logger.warning("no shared key with %s; message not sent", peer_id[:8])
                return None
        message = Message(
            content=content,
            sender_id=self.node_id,
            recipient_id=peer_id,
            is_file=is_file,
            filename=filename,
            key_exchange_algo=self.kem.name,
            symmetric_algo=self.symmetric.name,
            signature_algo=self.signature.name,
        )
        if not await self._encrypt_and_send(peer_id, message):
            return None
        return message

    async def _encrypt_and_send(self, peer_id: str, message: Message) -> bool:
        """Sign-then-encrypt tail of send_message, shared with the outbox
        flush (which re-encrypts queued messages under the healed key)."""
        package = {
            "message": message.to_dict(),
            "sig_algo": self.signature.name,
        }
        try:
            # bulk lane: under a flood with a bulk bound armed, this send
            # is SHED here (loud, counted) — rekey/handshake ops sharing
            # the queue are untouched
            sig = await self._sign(_canonical(package["message"]), LANE_BULK)
        except LaneShed:
            self._ctr_bulk_sheds.inc()
            logger.warning(
                "bulk send to %s shed at the bulk-lane bound (%d total)",
                peer_id[:8], self._ctr_bulk_sheds.value,
            )
            return False
        package["sig"] = sig.hex()
        package["sig_pk"] = self._sig_keypair[0].hex()
        ad = _canonical(
            {
                "type": "secure_message",
                "message_id": message.message_id,
                "sender": self.node_id,
                "recipient": peer_id,
                "is_file": message.is_file,
            }
        )
        key = self.shared_keys.get(peer_id)
        if key is None:
            logger.warning("no shared key with %s; message not sent", peer_id[:8])
            return False
        try:
            # batched seal on the bulk lane (the DATA plane): coalesces
            # with every live session's seals into one device dispatch;
            # sheds exactly like the sign above under a bulk-lane bound
            ct = await self._aead_encrypt(key, _canonical(package), ad)
        except LaneShed:
            self._ctr_bulk_sheds.inc()
            logger.warning(
                "bulk seal to %s shed at the bulk-lane bound (%d total)",
                peer_id[:8], self._ctr_bulk_sheds.value,
            )
            return False
        sent = await self.node.send_message(peer_id, "secure_message", ct=ct, ad=ad)
        if not sent:
            return False
        self._log(
            "message_sent", peer=peer_id, size=len(message.content),
            algorithm=self.symmetric.name, is_file=message.is_file,
        )
        return True

    async def send_file(self, peer_id: str, path: str | Path) -> Message | None:
        p = Path(path)
        # Read on a worker thread: a large file would otherwise stall every
        # peer this loop is serving.
        content = await asyncio.get_running_loop().run_in_executor(None, p.read_bytes)
        return await self.send_message(peer_id, content, is_file=True, filename=p.name)

    async def _handle_secure_message(self, peer_id: str, msg: dict) -> None:
        """Decrypt -> verify -> cross-check -> dedup -> fan out (ref: :1437-1558)."""
        key = self.shared_keys.get(peer_id)
        if key is None:
            logger.warning("secure message from %s without shared key", peer_id[:8])
            return
        ad: bytes = bytes(msg.get("ad", b""))
        try:
            # batched open on the bulk lane; over the binary wire ``ct`` is
            # a memoryview into the socket buffer — zero-copy into the
            # device batch (net/p2p_node.py binary framing)
            pt = await self._aead_decrypt(key, msg.get("ct", b""), ad)
        except LaneShed:
            # inbound bulk shed at its lane bound: loud and counted; the
            # message is dropped WITHOUT touching the AEAD-failure/rekey
            # machinery (a shed is load, not tampering)
            self._ctr_bulk_sheds.inc()
            logger.warning("inbound bulk-lane open shed (%d total)",
                           self._ctr_bulk_sheds.value)
            return
        except ValueError:
            # Corrupted/tampered ciphertext, or a desynchronised key.  Never
            # plaintext; after REKEY_AFTER_AEAD_FAILURES consecutive
            # failures, drop the session key and re-key automatically
            # instead of silently rejecting this peer's traffic forever.
            failures = self._aead_failures.get(peer_id, 0) + 1
            self._aead_failures[peer_id] = failures
            logger.warning("AEAD decrypt failed from %s (%d consecutive)",
                           peer_id[:8], failures)
            now = time.monotonic()
            if now - self._last_rekey.get(peer_id, -REKEY_COOLDOWN_S) < REKEY_COOLDOWN_S:
                # a rekey just happened: this is (very likely) an old-key
                # message still in flight — undecryptable either way, and
                # re-dropping the fresh key would churn forever under
                # steady traffic (and hand any peer a one-message DoS
                # lever forcing endless handshakes)
                return
            if failures >= REKEY_AFTER_AEAD_FAILURES:
                self._aead_failures[peer_id] = 0
                self._last_rekey[peer_id] = now
                logger.warning(
                    "dropping session key for %s after %d AEAD failure(s); "
                    "re-keying", peer_id[:8], failures,
                )
                self.shared_keys.pop(peer_id, None)
                _wipe(self.raw_secrets.pop(peer_id, None))
                self.ke_state[peer_id] = KeyExchangeState.NONE
                self._log("rekey", peer=peer_id, reason="aead_failures")
                self._ctr_rekeys.inc()
                obs_flight.record("rekey", peer=peer_id[:8],
                                  reason="aead_failures", failures=failures)
                self._spawn(self.initiate_key_exchange(peer_id), "rekey")
            return
        self._aead_failures.pop(peer_id, None)
        try:
            package = json.loads(pt)
            message = Message.from_dict(package["message"])
            ad_data = json.loads(ad)
        except (ValueError, KeyError, TypeError):
            logger.warning("malformed secure message from %s", peer_id[:8])
            return
        # Verify signature over the message body (bulk lane: inbound bulk
        # verification must not starve handshake ops either).
        if not await self._verify(
            package.get("sig_algo", ""),
            bytes.fromhex(package.get("sig_pk", "")),
            _canonical(package["message"]),
            bytes.fromhex(package.get("sig", "")),
            LANE_BULK,
        ):
            logger.warning("signature verification failed from %s", peer_id[:8])
            return
        # Associated-data cross-checks (reference: :1489-1503).
        if (
            ad_data.get("message_id") != message.message_id
            or ad_data.get("sender") != message.sender_id
            or message.sender_id != peer_id
            or ad_data.get("recipient") != self.node_id
        ):
            logger.warning("associated-data mismatch from %s", peer_id[:8])
            return
        if self._dedup(message.message_id):
            return
        self._log(
            "message_received", peer=peer_id, size=len(message.content),
            algorithm=self.symmetric.name, is_file=message.is_file,
        )
        self._notify(peer_id, message)

    # ------------------------------------------------------- settings gossip

    def get_settings(self) -> dict:
        return {
            "kem": self.kem.name,
            "aead": self.symmetric.name,
            "signature": self.signature.name,
        }

    async def notify_peers_of_settings_change(self) -> None:
        for peer_id in self.node.get_peers():
            await self.node.send_message(
                peer_id, "settings_update", settings=self.get_settings()
            )

    async def request_peer_settings(self, peer_id: str) -> None:
        await self.node.send_message(peer_id, "settings_request")
        await self.node.send_message(
            peer_id, "settings_update", settings=self.get_settings()
        )

    async def _handle_settings_update(self, peer_id: str, msg: dict) -> None:
        settings = msg.get("settings") or {}
        self.peer_settings[peer_id] = settings

    async def _handle_settings_request(self, peer_id: str, msg: dict) -> None:
        await self.node.send_message(
            peer_id, "settings_update", settings=self.get_settings()
        )

    def settings_match(self, peer_id: str) -> bool | None:
        peer = self.peer_settings.get(peer_id)
        if peer is None:
            return None
        mine = self.get_settings()
        return all(peer.get(k) == v for k, v in mine.items())

    # ------------------------------------------------------ algorithm hot-swap

    async def set_key_exchange_algorithm(self, name: str) -> None:
        """Drop all shared keys and re-handshake (reference: :1741-1781)."""
        old_cache = getattr(self.kem, "opcache", None)
        if old_cache is not None:
            # the outgoing provider's operand cache pins key-derived device
            # state; the swap ends those keys' sessions, so end their cache
            # lifetime too (qrflow secret-lifetime audit)
            old_cache.zeroize()
        self.kem = get_kem(name, self.backend, devices=self.mesh_devices)
        if self.use_batching:
            from ..provider.batched import BatchedKEM

            self._bkem = BatchedKEM(self.kem, *self._batch_cfg,
                                    fallback=self._cpu_fallback_kem(),
                                    scheduler=self._scheduler,
                                    bucket_floor=self._batch_floor,
                                    lane_capacity=self._lane_capacity)
            self._bfused = self._make_fused()
            self._attach_tuners()
            self._attach_cost()
            self._spawn_warmup(kem=True, sig=False)
        peers = list(self.shared_keys)
        self.shared_keys.clear()
        for stale in self.raw_secrets.values():
            _wipe(stale)
        self.raw_secrets.clear()
        for peer_id in peers:
            self.ke_state[peer_id] = KeyExchangeState.NONE
        self._log("crypto_settings_changed", component="kem", algorithm=name)
        # Neither our re-handshakes nor peer-initiated ones (triggered by the
        # gossip below) may race the fresh provider's cold jit: wait first.
        await self.wait_ready()
        await self.notify_peers_of_settings_change()
        for peer_id in peers:
            if self.node.is_connected(peer_id):
                self._spawn(self.initiate_key_exchange(peer_id), "re-handshake")

    async def set_symmetric_algorithm(self, name: str) -> None:
        """Re-derive per-peer keys from stored raw secrets (reference: :1783-1810)."""
        self.symmetric = get_symmetric(name)
        if self.use_batching:
            # the data plane follows the AEAD: rebuild the batched facade
            # for the new algorithm (None when it has no device capability)
            self._baead = self._make_batched_aead()
            if self._bfused is not None:
                # the AEAD name sits BEFORE public_key in the canonical init
                # JSON, so the fused facade's baked-in pk offset just moved
                self._bfused = self._make_fused()
            self._attach_tuners()
            self._attach_cost()
            self._spawn_warmup(kem=False, sig=False)
        for peer_id, secret in self.raw_secrets.items():
            self.shared_keys[peer_id] = derive_message_key(
                secret, self.node_id, peer_id, name
            )
        self._log("crypto_settings_changed", component="aead", algorithm=name)
        await self.notify_peers_of_settings_change()

    async def set_signature_algorithm(self, name: str) -> None:
        """Lazily load-or-generate the new keypair (reference: :1827-1851)."""
        old_cache = getattr(self.signature, "opcache", None)
        if old_cache is not None:
            old_cache.zeroize()  # sk-derived device precomputes die with the swap
        self.signature = get_signature(name, self.backend,
                                       devices=self.mesh_devices)
        if self.use_batching:
            from ..provider.batched import BatchedSignature

            self._bsig = BatchedSignature(self.signature, *self._batch_cfg,
                                           fallback=self._cpu_fallback_sig(),
                                           scheduler=self._scheduler,
                                           bucket_floor=self._batch_floor,
                                           lane_capacity=self._lane_capacity)
            self._bfused = self._make_fused()
            self._attach_tuners()
            self._attach_cost()
            self._spawn_warmup(kem=False, sig=True)
        self._sig_keypair = self._load_or_generate_sig_keypair()
        self._log("crypto_settings_changed", component="signature", algorithm=name)
        # peers adopting the new signature re-handshake through our _bsig;
        # don't gossip until it is warm
        await self.wait_ready()
        await self.notify_peers_of_settings_change()

    async def adopt_peer_settings(self, peer_id: str) -> bool:
        """Switch local algorithms to the peer's gossiped set (ref: :1893-2011)."""
        peer = self.peer_settings.get(peer_id)
        if not peer:
            return False
        try:
            if peer.get("aead") and peer["aead"] != self.symmetric.name:
                await self.set_symmetric_algorithm(peer["aead"])
            if peer.get("signature") and peer["signature"] != self.signature.name:
                await self.set_signature_algorithm(peer["signature"])
            if peer.get("kem") and peer["kem"] != self.kem.name:
                await self.set_key_exchange_algorithm(peer["kem"])
        except KeyError as e:
            logger.warning("cannot adopt peer settings: %s", e)
            return False
        return True
