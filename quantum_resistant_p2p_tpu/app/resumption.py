"""Session-resumption tickets — PSK-style abbreviated handshakes
(docs/protocol.md "Session resumption").

At fleet scale the expensive traffic is exactly the reconnect wave after a
gateway death or a rolling restart: every re-established session used to
pay the full KEM + 3-signature handshake at the worst possible moment.
This module implements the "Faster Post-Quantum TLS 1.3" deployment
reality (PAPERS.md #1): after a confirmed full handshake the responder
mints an **encrypted, self-contained resumption ticket** — sealed under a
session-ticket-encryption key (STEK) only gateways hold — and a reconnect
presents it for a **1-RTT abbreviated exchange**: two HKDF calls and two
HMACs, no KEM, no signatures, no device dispatch.

Ticket blob layout (opaque to the holder)::

    b"QT1" | epoch 8B (ascii hex) | nonce 16B | ct | tag 32B

``ct`` seals the canonical-JSON ticket fields (holder identity, the
HKDF-derived resumption secret, negotiated suite, expiry, a single-use
nonce) with a stdlib encrypt-then-MAC construction (SHA-256 keystream +
HMAC-SHA256) keyed by the STEK — the same wheel-less discipline as the
protocol engine's HKDF, so tickets work on minimal images.  The ``epoch``
names WHICH key sealed the blob: a :class:`STEKRing` accepts the current
and the previous key (the dual-key rotation window), so a ticket minted
just before a rotation still resumes.

Trust model: the sealed blob is public by construction — it reveals
nothing without the STEK, and a STOLEN blob is useless without the
resumption secret (the presenter must also supply a binder HMAC keyed by
it, the TLS-PSK binder analog).  Hostile input of any shape is a typed
:class:`TicketError` whose ``reason`` the responder echoes in its reject
frame; every reject path ends in a full-handshake fallback, never a
stall and never plaintext.  Replay is bounded per responder by a
:class:`ReplayCache` over the ticket's single-use nonce; across gateways
it is bounded by the ticket expiry (caches are per-process — see
docs/protocol.md for the exact bound).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import uuid
import json

__all__ = [
    "TicketError", "STEKRing", "ReplayCache", "hkdf_sha256",
    "derive_resumption_secret", "derive_resumed_key",
    "ratchet_resumption_secret", "resume_binder", "resume_confirm_tag",
    "resumption_default",
]

#: ticket wire magic + version (bump on layout change)
TICKET_MAGIC = b"QT1"
#: epoch field: 8 ascii-hex bytes naming the sealing STEK
EPOCH_LEN = 8
NONCE_LEN = 16
TAG_LEN = 32
#: hard bound on accepted ticket blobs — a hostile length claim must cost
#: one comparison, never memory
MAX_TICKET_LEN = 4096
MIN_TICKET_LEN = len(TICKET_MAGIC) + EPOCH_LEN + NONCE_LEN + TAG_LEN

#: typed reject reasons (docs/protocol.md table); the responder echoes
#: these in ``ke_resume_reject`` so the initiator's fallback is explainable
REASONS = (
    "malformed_ticket", "unknown_stek", "bad_ticket_auth", "expired_ticket",
    "replayed_ticket", "holder_mismatch", "suite_mismatch", "bad_binder",
    "resumption_disabled", "draining",
)


def resumption_default() -> bool:
    """``QRP2P_RESUMPTION`` policy: tickets are on unless ``0`` (the same
    shape as the binary-wire knob; ``0`` is pinned wire byte-identical to
    the pre-resumption protocol by tests/test_resumption.py)."""
    return os.environ.get("QRP2P_RESUMPTION", "1") != "0"


class TicketError(ValueError):
    """Typed ticket-validation failure.  ``reason`` is one of
    :data:`REASONS` — carried as an attribute so the responder's reject
    frame and the tests classify on the typed value, never message text."""

    def __init__(self, reason: str):
        super().__init__(f"ticket rejected: {reason}")
        self.reason = reason


def hkdf_sha256(ikm: bytes, salt: bytes, info: bytes, length: int = 32) -> bytes:
    """RFC 5869 HKDF-SHA256 (extract + expand) on the stdlib — THE copy
    the protocol engine re-exports as ``_hkdf_sha256`` (tests/test_faults.py
    pins the RFC A.1 vector through that name)."""
    prk = hmac.new(salt or bytes(32), ikm, hashlib.sha256).digest()
    okm = b""
    t = b""
    i = 1
    while len(okm) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]


def derive_resumption_secret(raw_secret: bytes, id_a: str, id_b: str) -> bytes:
    """The resumption master secret: HKDF over the session's raw KEM
    secret, salted by the sorted peer ids (both sides derive identically,
    mirroring :func:`app.messaging.derive_message_key`).  Knowing it —
    not holding the sealed blob — is what authorizes a resume."""
    ids = "|".join(sorted([id_a, id_b]))
    return hkdf_sha256(raw_secret, salt=ids.encode(),
                       info=b"qrp2p-tpu/resumption/v1")


def derive_resumed_key(resumption_secret: bytes, client_nonce: str,
                       server_nonce: str, aead_name: str) -> bytes:
    """The resumed session's message key: fresh per resume (both nonces
    are single-exchange), bound to the AEAD name exactly like the full
    handshake's key derivation."""
    return hkdf_sha256(
        resumption_secret,
        salt=(client_nonce + "|" + server_nonce).encode(),
        info=b"qrp2p-tpu/resume-key/" + aead_name.encode(),
    )


def ratchet_resumption_secret(resumption_secret: bytes, client_nonce: str,
                              server_nonce: str) -> bytes:
    """The NEXT resumption secret, derived by both sides on every
    successful resume: the fresh ticket a resume returns never carries the
    secret that authorized it (one-way ratchet — an old secret cannot
    redeem a new ticket)."""
    return hkdf_sha256(
        resumption_secret,
        salt=(client_nonce + "|" + server_nonce).encode(),
        info=b"qrp2p-tpu/resumption/next",
    )


def resume_binder(resumption_secret: bytes, resume_data: bytes,
                  ticket_blob: bytes) -> str:
    """The presenter's proof-of-secret (TLS-PSK binder analog): an HMAC
    over the resume transcript AND the exact blob presented, keyed by the
    resumption secret — a stolen sealed blob without the secret fails
    here, typed, before any state changes."""
    return hmac.new(resumption_secret,
                    b"qrp2p-tpu/resume-binder|" + resume_data + bytes(ticket_blob),
                    hashlib.sha256).hexdigest()


def resume_confirm_tag(resumed_key: bytes, message_id: str, client_nonce: str,
                       server_nonce: str) -> str:
    """The responder's proof-of-secret: an HMAC under the RESUMED key over
    the exchange ids — the initiator installs nothing until it verifies."""
    return hmac.new(
        resumed_key,
        b"qrp2p-tpu/resume-confirm|" + "|".join(
            (message_id, client_nonce, server_nonce)).encode(),
        hashlib.sha256).hexdigest()


def _keystream(stek: bytes, nonce: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(stek + nonce
                              + ctr.to_bytes(8, "big")).digest()
        ctr += 1
    return out[:n]


class STEKRing:
    """Current + previous session-ticket-encryption keys (the dual-key
    rotation accept window).

    Mints with the CURRENT key; opens with any key in the window, so a
    rotation never strands the tickets minted just before it.  A fleet
    router owns one authoritative ring and pushes it to every gateway
    over the control link (fleet/manager.py ``__gw_stek__``), which is
    what lets a ticket minted by gw1 resume on gw2 after a handoff — and
    resume on the RESPAWNED gw1 after a rolling restart.
    """

    #: keys kept: current + previous (the accept window)
    WINDOW = 2

    def __init__(self, keys: "list[tuple[str, bytes]] | None" = None):
        #: epoch -> key, newest first
        self._keys: dict[str, bytes] = {}
        if keys:
            self.install(keys)
        else:
            self.rotate()

    # -- key management -------------------------------------------------------

    @property
    def current_epoch(self) -> str:
        return next(iter(self._keys))

    @property
    def epochs(self) -> list[str]:
        return list(self._keys)

    def rotate(self, stek: bytes | None = None,
               epoch: str | None = None) -> str:
        """Install a fresh current key (random unless given), demoting the
        old current to the accept-only slot and dropping anything older.
        Returns the new epoch."""
        stek_key = stek if stek is not None else os.urandom(32)
        if len(stek_key) != 32:
            raise ValueError("STEK must be 32 bytes")
        new_epoch = epoch if epoch is not None else os.urandom(4).hex()
        keep = list(self._keys.items())[: self.WINDOW - 1]
        self._keys = dict([(new_epoch, stek_key)] + keep)
        return new_epoch

    def install(self, keys: "list[tuple[str, bytes]]", *,
                guard: bool = False) -> bool:
        """Replace the ring with a distributed key set (newest first) —
        the gateway side of the fleet's STEK push.

        ``guard=True`` refuses a set that would REGRESS the accept
        window: with a replicated control plane, a rotation push and a
        renewal-time re-replication ride separate short-lived
        connections, so a pre-rotation frame can land after the rotation
        it predates.  Epochs are random (unordered), but a regression is
        still detectable structurally — the incoming CURRENT key is one
        we already demoted to the accept-only slot.  Installing it would
        re-mint under a key the rest of the fleet is about to drop.
        Returns True when the set was installed, False when the guard
        skipped it (callers flight-record the skip).
        """
        cleaned: list[tuple[str, bytes]] = []
        for epoch, stek_key in keys[: self.WINDOW]:
            epoch = str(epoch)
            stek_key = bytes(stek_key)
            if len(epoch) != EPOCH_LEN or len(stek_key) != 32:
                raise ValueError("malformed STEK entry")
            cleaned.append((epoch, stek_key))
        if not cleaned:
            raise ValueError("empty STEK set")
        if guard and self._keys:
            incoming_current = cleaned[0][0]
            if (incoming_current != self.current_epoch
                    and incoming_current in self._keys):
                return False
        self._keys = dict(cleaned)
        return True

    def export(self) -> list[list[str]]:
        """The distributable form (newest first): ``[[epoch, key_hex]]``
        — for the fleet control link only; never for any peer-facing or
        observability surface."""
        return [[epoch, stek_key.hex()]
                for epoch, stek_key in self._keys.items()]

    # -- seal / open ----------------------------------------------------------

    def seal_ticket(self, fields: dict) -> bytes:
        """Seal the ticket fields under the CURRENT key.  The blob is
        public by construction (qrflow models it like a signature): it
        reveals nothing without the STEK and authorizes nothing without
        the resumption secret inside it."""
        body = json.dumps(fields, sort_keys=True,
                          separators=(",", ":")).encode()
        epoch = self.current_epoch
        stek_key = self._keys[epoch]
        nonce = os.urandom(NONCE_LEN)
        ct = bytes(a ^ b for a, b in
                   zip(body, _keystream(stek_key, nonce, len(body))))
        header = TICKET_MAGIC + epoch.encode() + nonce
        tag = hmac.new(stek_key, header + ct, hashlib.sha256).digest()
        return header + ct + tag

    def open_ticket(self, blob) -> "tuple[dict, bytes]":
        """Open a presented blob -> ``(public_fields, resumption_secret)``.

        Every failure is a typed :class:`TicketError`: wrong
        magic/truncated/oversized -> ``malformed_ticket``, an epoch outside
        the accept window (or a gateway that never saw the STEK) ->
        ``unknown_stek``, a failed MAC (corruption, tampering) ->
        ``bad_ticket_auth``.  The secret is returned SEPARATELY from the
        metadata so callers never branch on secret-tainted values."""
        blob = bytes(blob)
        if (len(blob) < MIN_TICKET_LEN or len(blob) > MAX_TICKET_LEN
                or blob[:len(TICKET_MAGIC)] != TICKET_MAGIC):
            raise TicketError("malformed_ticket")
        off = len(TICKET_MAGIC)
        try:
            epoch = blob[off:off + EPOCH_LEN].decode("ascii")
        except UnicodeDecodeError:
            raise TicketError("malformed_ticket") from None
        stek_key = self._keys.get(epoch)
        if stek_key is None:
            raise TicketError("unknown_stek")
        off += EPOCH_LEN
        nonce = blob[off:off + NONCE_LEN]
        ct = blob[off + NONCE_LEN:-TAG_LEN]
        tag = blob[-TAG_LEN:]
        want = hmac.new(stek_key, blob[:-TAG_LEN], hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise TicketError("bad_ticket_auth")
        body = bytes(a ^ b for a, b in
                     zip(ct, _keystream(stek_key, nonce, len(ct))))
        try:
            fields = json.loads(body)
        except ValueError:
            raise TicketError("malformed_ticket") from None
        if not isinstance(fields, dict):
            raise TicketError("malformed_ticket")
        try:
            secret = bytes.fromhex(str(fields.pop("secret", "")))
        except ValueError:
            raise TicketError("malformed_ticket") from None
        if len(secret) != 32:
            raise TicketError("malformed_ticket")
        return fields, secret


def mint_fields(holder: str, issuer: str, secret: bytes, kem: str, aead: str,
                sig: str, expires_at: float) -> dict:
    """The canonical ticket-field layout (one constructor so the mint and
    re-mint paths cannot drift): peer identity, the resumption secret,
    the negotiated suite, expiry, and a fresh single-use nonce."""
    return {
        "v": 1,
        "holder": holder,
        "issuer": issuer,
        "secret": secret.hex(),
        "kem": kem,
        "aead": aead,
        "sig": sig,
        "expires_at": round(float(expires_at), 3),
        "nonce": uuid.uuid4().hex,
    }


class ReplayCache:
    """Bounded single-use ledger over ticket nonces.

    ``seen(nonce, expires_at, now)`` returns True for a REPLAY (and
    records first uses).  Entries expire with their ticket; at capacity
    the earliest-expiring half is evicted — bounded memory under a nonce
    flood, at the documented cost that a very old first-use may be
    forgotten before its ticket expires (the expiry bound still holds)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._seen: dict[str, float] = {}
        #: replays observed (the counter the hostile-ticket tests bump)
        self.replays = 0

    def seen(self, nonce: str, expires_at: float, now: float) -> bool:
        expiry = self._seen.get(nonce)
        if expiry is not None and expiry >= now:
            self.replays += 1
            return True
        self._seen[nonce] = expires_at
        if len(self._seen) > self.capacity:
            for n, _exp in sorted(self._seen.items(),
                                  key=lambda kv: kv[1])[: self.capacity // 2]:
                if n != nonce:
                    del self._seen[n]
        return False

    def __len__(self) -> int:
        return len(self._seen)
