"""Application layer: the secure-messaging protocol engine and message store.

Capability parity with the reference's app/ package (SURVEY.md §2 row 12-13):
authenticated ephemeral-KEM handshakes, sign-then-encrypt AEAD messaging,
crypto-settings gossip, algorithm hot-swap, dedup, key persistence.
"""

from .message_store import Message, MessageStore
from .messaging import KeyExchangeState, SecureMessaging

__all__ = ["Message", "MessageStore", "KeyExchangeState", "SecureMessaging"]
