"""Batched ML-DSA (FIPS 204) in JAX — lattice signatures on the TPU VPU.

TPU-native design
-----------------
* q = 8380417 < 2**23, so residues fit int32 but products do not; TPUs have no
  64-bit lanes.  ``_mm`` performs modular multiplication via a Horner split of
  one operand into 8-bit limbs: every intermediate stays below 2**31, all in
  int32 — no 64-bit emulation, fully vectorised.
* The signing rejection loop (reference behavior: liboqs ML-DSA via
  crypto/signatures.py:157; spec loop in pyref.mldsa_ref.sign_internal) is a
  ``lax.while_loop`` over whole *batches* with per-lane done masks and
  per-lane kappa counters: lanes that already produced a valid signature keep
  their result via ``jnp.where`` while stragglers retry, reproducing each
  lane's serial kappa sequence exactly (bit-exact vs the oracle).
* SampleInBall's data-dependent Fisher–Yates is a fixed 1024-step ``lax.scan``
  over the SHAKE buffer bytes, maintaining (c, i, sign-bit index) state — same
  fixed-buffer convention as the pyref oracle.
* ExpandA / ExpandS rejection sampling uses the same fixed-squeeze +
  gather-free bitonic compaction as kem.mlkem.sample_ntt (XLA argsort /
  take_along_axis serialise per-lane on TPU; see core/sortnet.py).
* Variable-length messages are hashed to ``mu = SHAKE256(tr||M', 64)``
  host-side (cheap, public data); the device kernels take fixed-shape mu
  batches.  Key-dependent NTTs (A_hat, s1_hat, s2_hat, t0_hat) are hoisted out
  of the per-message batch and computed once per key.

Bit-exactness oracle: ``pyref.mldsa_ref`` (tests/test_mldsa.py).
Replaces (reference): MLDSASignature's per-call liboqs objects
(crypto/signatures.py:58-188, vendor/oqs.py:506-583).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import keccak
from ..utils import next_pow2 as _next_pow2_i
from ..core.sortnet import bitonic_sort, bitonic_sort_pairs
from ..pyref.mldsa_ref import (
    D,
    MLDSA44,
    MLDSA65,
    MLDSA87,
    MLDSAParams,
    PARAMS,
    ZETAS,
)

Q = 8380417
N = 256
_N_INV = pow(256, -1, Q)
_ZETAS = np.asarray(ZETAS, dtype=np.int32)

MAX_SIGN_ITERS = 128  # P[a lane needs >128 attempts] < 1e-12 (avg ~4-6 attempts)

# Test/debug guard: fail loudly if the truncated 1024-candidate sampler
# buffers would diverge from the oracle's full-buffer convention (advisor
# round-2 finding; P < 1e-94 per poly, but silent divergence is worse than
# a crash).  Enabled by tests; off in production (adds a host callback).
# NOTE: read at TRACE time — jitted entry points (get()) bake the setting
# into their cached trace, so set it before the first call of a fresh
# process/jit wrapper (same caveat as QRP2P_PALLAS).
STRICT_SAMPLERS = False


def _check_sampler_fill(ok, name: str) -> None:
    if not np.all(np.asarray(ok)):
        raise AssertionError(
            f"{name}: fewer than {N} accepted candidates in the truncated "
            "sort buffer — output diverges from the pyref oracle convention"
        )

# --------------------------------------------------------------------------
# int32 modular arithmetic without 64-bit lanes
# --------------------------------------------------------------------------


def _mm(a: jax.Array, b: jax.Array) -> jax.Array:
    """(a * b) mod q for a, b int32 in [0, q); all intermediates < 2**31.

    Horner over 8-bit limbs of b: a*b2 < 2**30, r<<8 < 2**31, a*b_i < 2**31.
    """
    b2 = b >> 16
    b1 = (b >> 8) & 0xFF
    b0 = b & 0xFF
    r = (a * b2) % Q
    r = (((r << 8) % Q) + (a * b1) % Q) % Q
    r = (((r << 8) % Q) + (a * b0) % Q) % Q
    return r


def _center(x: jax.Array, m: int = Q) -> jax.Array:
    """mod± representative in (-m/2, m/2]."""
    x = x % m
    return jnp.where(x > m // 2, x - m, x)


# --------------------------------------------------------------------------
# NTT over Z_q[X]/(X^256+1) (FIPS 204 §7.5) — complete 256-point transform
# --------------------------------------------------------------------------


def _ntt_pallas(f: jax.Array, inverse: bool) -> jax.Array:
    """Route one (inv)NTT through the VMEM-resident kernel: flatten every
    leading axis into the lane dimension (polys transform independently),
    transpose to the words layout, and back."""
    from . import mldsa_pallas  # deferred: pallas import

    sh = f.shape
    x = f.reshape(-1, N).T  # (256, L)
    out = mldsa_pallas.ntt_words(x, inverse=inverse)
    return out.T.reshape(sh)


def ntt(f: jax.Array) -> jax.Array:
    """(..., 256) int32 in [0,q) -> NTT domain.

    On TPU the transform runs as one VMEM-resident Pallas program (1 HBM
    read + 1 write instead of 16 stage round-trips; sig/mldsa_pallas.py) —
    the sign rejection loop runs ~29 poly transforms per attempt."""
    if keccak._use_pallas():
        return _ntt_pallas(f, inverse=False)
    zetas = jnp.asarray(_ZETAS)
    k = 1
    length = 128
    while length >= 1:
        groups = N // (2 * length)
        z = zetas[k : k + groups]
        fr = f.reshape(f.shape[:-1] + (groups, 2, length))
        f0, f1 = fr[..., 0, :], fr[..., 1, :]
        t = _mm(jnp.broadcast_to(z[:, None], f1.shape), f1)
        f = jnp.stack([(f0 + t) % Q, (f0 - t) % Q], axis=-2).reshape(f.shape)
        k += groups
        length //= 2
    return f


def ntt_inv(f: jax.Array) -> jax.Array:
    if keccak._use_pallas():
        return _ntt_pallas(f, inverse=True)
    zetas = jnp.asarray(_ZETAS)
    k = 255
    length = 1
    while length <= 128:
        groups = N // (2 * length)
        z = zetas[k - groups + 1 : k + 1][::-1]
        fr = f.reshape(f.shape[:-1] + (groups, 2, length))
        f0, f1 = fr[..., 0, :], fr[..., 1, :]
        s = (f0 + f1) % Q
        t = _mm(jnp.broadcast_to(z[:, None], f1.shape), (f1 - f0) % Q)
        f = jnp.stack([s, t], axis=-2).reshape(f.shape)
        k -= groups
        length *= 2
    return _mm(f, jnp.asarray(np.int32(_N_INV)))


def pw_mul(a: jax.Array, b: jax.Array) -> jax.Array:
    a, b = jnp.broadcast_arrays(a, b)
    return _mm(a, b)


# --------------------------------------------------------------------------
# Bit packing (FIPS 204 §7.1), batched
# --------------------------------------------------------------------------


def simple_bit_pack(vals: jax.Array, bits: int) -> jax.Array:
    """(..., 256) int32 in [0, 2^bits) -> (..., 32*bits) uint8, LSB-first.

    Byte-assembly formulation: the LSB-first bitstream is periodic with
    period lcm(bits, 8) — ``pc`` coefficients fill ``pb`` bytes — so each
    output byte position is a STATIC shift/or of at most a few
    coefficients.  The naive bit-matrix route (explode to (..., 256, bits)
    then regroup by 8) materialises a bits-x blowup in HBM: for the z
    packing inside the sign rejection loop (bits=20, batch 8192 x l=5)
    that alone measured tens of ms per attempt (r4 prefix probe)."""
    import math

    period = math.lcm(bits, 8)
    pb, pc = period // 8, period // bits
    g = vals.reshape(vals.shape[:-1] + (N // pc, pc))
    outs = []
    for j in range(pb):
        lo = 8 * j
        acc = None
        for c in range(pc):
            s = c * bits
            if s + bits <= lo or s >= lo + 8:
                continue
            sh = lo - s
            contrib = (g[..., c] >> sh) if sh >= 0 else (g[..., c] << (-sh))
            acc = contrib if acc is None else (acc | contrib)
        outs.append(acc & 0xFF)
    b = jnp.stack(outs, axis=-1)  # (..., 256/pc, pb)
    return b.reshape(vals.shape[:-1] + (32 * bits,)).astype(jnp.uint8)


def simple_bit_unpack(b: jax.Array, bits: int) -> jax.Array:
    """(..., 32*bits) uint8 -> (..., 256) int32 (byte-assembly, see pack)."""
    import math

    period = math.lcm(bits, 8)
    pb, pc = period // 8, period // bits
    g = b.reshape(b.shape[:-1] + (N // pc, pb)).astype(jnp.int32)
    outs = []
    for c in range(pc):
        s = c * bits
        acc = None
        for j in range(pb):
            lo = 8 * j
            if lo + 8 <= s or lo >= s + bits:
                continue
            sh = lo - s
            contrib = (g[..., j] << sh) if sh >= 0 else (g[..., j] >> (-sh))
            acc = contrib if acc is None else (acc | contrib)
        outs.append(acc & ((1 << bits) - 1))
    x = jnp.stack(outs, axis=-1)  # (..., 256/pc, pc)
    return x.reshape(b.shape[:-1] + (N,))


def bit_pack(vals: jax.Array, up: int, bits: int) -> jax.Array:
    return simple_bit_pack((up - _center(vals)), bits)


def bit_unpack(b: jax.Array, up: int, bits: int) -> jax.Array:
    return (up - simple_bit_unpack(b, bits)) % Q


# --------------------------------------------------------------------------
# Rounding (FIPS 204 §7.4), batched
# --------------------------------------------------------------------------


def power2round(r: jax.Array) -> tuple[jax.Array, jax.Array]:
    r = r % Q
    r0 = _center(r, 1 << D)
    return (r - r0) >> D, r0


def decompose(p: MLDSAParams, r: jax.Array) -> tuple[jax.Array, jax.Array]:
    alpha = 2 * p.gamma2
    r = r % Q
    r0 = _center(r, alpha)
    wrap = (r - r0) == (Q - 1)
    r1 = jnp.where(wrap, 0, (r - r0) // alpha)
    r0 = jnp.where(wrap, r0 - 1, r0)
    return r1, r0


def use_hint(p: MLDSAParams, h: jax.Array, r: jax.Array) -> jax.Array:
    m = (Q - 1) // (2 * p.gamma2)
    r1, r0 = decompose(p, r)
    up = jnp.where(r0 > 0, (r1 + 1) % m, (r1 - 1) % m)
    return jnp.where(h != 0, up, r1)


# --------------------------------------------------------------------------
# Samplers (FIPS 204 §7.3), batched fixed-shape
# --------------------------------------------------------------------------

_REJ_NTT_BYTES = 168 * 7  # 392 candidates for 256 slots (matches oracle buffer)
_REJ_BOUNDED_BYTES = 136 * 4  # 1088 nibbles for 256 slots
_REJ_BOUNDED_SORT = 1024  # nibbles fed to the compaction (see rej_bounded_poly)


def rej_ntt_poly(seeds: jax.Array) -> jax.Array:
    """(..., 34) uint8 -> (..., 256) int32 NTT-domain uniform polys.

    Compaction is the gather-free bitonic network (core/sortnet.py) — XLA's
    stable argsort + take_along_axis serialise per-lane on TPU (the same
    hazard kem/mlkem.py:sample_ntt documents).  23-bit candidates don't fit
    an int32 key next to the index, so the pairs variant carries them.

    On TPU the whole pipeline (SHAKE squeeze -> extraction -> compaction)
    is one fused Pallas kernel with every intermediate in VMEM
    (sig/mldsa_pallas.py) — the jnp pairs-network alone moves ~11 GB of
    HBM per 1024-batch of ExpandA otherwise.
    """
    if keccak._use_pallas():
        from . import mldsa_pallas  # deferred: pallas import

        ph, plo, batch = keccak.seed_block_words(seeds, 168, 0x1F)
        return mldsa_pallas.rej_ntt_words(ph, plo).T.reshape(batch + (N,))

    buf = keccak.shake128(seeds, _REJ_NTT_BYTES).astype(jnp.int32)
    t = buf.reshape(buf.shape[:-1] + (-1, 3))
    cand = t[..., 0] | (t[..., 1] << 8) | ((t[..., 2] & 0x7F) << 16)
    nc = cand.shape[-1]
    idx = jnp.arange(nc, dtype=jnp.int32)
    key = jnp.where(cand < Q, 0, 1 << 10) | idx  # accepted first, spec order
    np2 = 1 << (nc - 1).bit_length()
    pad = [(0, 0)] * (key.ndim - 1) + [(0, np2 - nc)]
    key = jnp.pad(key, pad, constant_values=1 << 11)
    cand = jnp.pad(cand, pad)
    _, cand = bitonic_sort_pairs(key, cand)
    return cand[..., :N]


def rej_bounded_poly(eta: int, seeds: jax.Array) -> jax.Array:
    """(..., 66) uint8 -> (..., 256) int32 coefficients in {q-eta..q+eta mod q}.

    The raw nibble rides in the low bits of the (unique) sort key, so one
    int32 bitonic network replaces the serialised argsort; the eta-map is
    applied after compaction.  Only the first 1024 of the 1088 squeezed
    nibbles feed the network (1024 is the power of two the sort wants):
    output differs from the full-buffer formulation only if fewer than 256
    of the first 1024 candidates are accepted — P < 1e-164 for eta=2
    (accept 15/16), < 1e-94 for eta=4 (accept 9/16).

    On TPU the whole pipeline is one fused Pallas kernel
    (sig/mldsa_pallas.py), same recipe as rej_ntt_poly.
    """
    if keccak._use_pallas():
        from . import mldsa_pallas  # deferred: pallas import

        ph, plo, batch = keccak.seed_block_words(seeds, 136, 0x1F)
        z = mldsa_pallas.rej_bounded_words(ph, plo, eta=eta).T.reshape(batch + (N,))
    else:
        buf = keccak.shake256(seeds, _REJ_BOUNDED_BYTES).astype(jnp.int32)
        z = jnp.stack([buf & 0xF, buf >> 4], axis=-1).reshape(buf.shape[:-1] + (-1,))
        z = z[..., :_REJ_BOUNDED_SORT]
        ok = z < (15 if eta == 2 else 9)
        idx = jnp.arange(_REJ_BOUNDED_SORT, dtype=jnp.int32)
        key = jnp.where(ok, 0, 1 << 16) | (idx << 4) | z
        skey = bitonic_sort(key)
        if STRICT_SAMPLERS:
            # slot N-1 must still be an accepted candidate (reject bit clear)
            jax.debug.callback(
                _check_sampler_fill, skey[..., N - 1] < (1 << 16), "rej_bounded_poly"
            )
        z = skey[..., :N] & 0xF
    if eta == 2:
        return (2 - z % 5) % Q
    return (4 - z) % Q


def expand_a(p: MLDSAParams, rho: jax.Array) -> jax.Array:
    """rho (..., 32) -> A_hat (..., k, l, 256); A[r,s] = RejNTTPoly(rho||s||r)."""
    sr = np.array([[s, r] for r in range(p.k) for s in range(p.l)], dtype=np.uint8)
    rho_rep = jnp.broadcast_to(rho[..., None, :], rho.shape[:-1] + (p.k * p.l, 32))
    sr_rep = jnp.broadcast_to(jnp.asarray(sr), rho.shape[:-1] + (p.k * p.l, 2))
    a = rej_ntt_poly(jnp.concatenate([rho_rep, sr_rep], axis=-1))
    return a.reshape(rho.shape[:-1] + (p.k, p.l, N))


def expand_s(p: MLDSAParams, rhop: jax.Array) -> tuple[jax.Array, jax.Array]:
    """rhop (..., 64) -> s1 (..., l, 256), s2 (..., k, 256)."""
    total = p.l + p.k
    n16 = np.zeros((total, 2), dtype=np.uint8)
    n16[:, 0] = np.arange(total) & 0xFF
    rep = jnp.broadcast_to(rhop[..., None, :], rhop.shape[:-1] + (total, 64))
    seeds = jnp.concatenate(
        [rep, jnp.broadcast_to(jnp.asarray(n16), rhop.shape[:-1] + (total, 2))], axis=-1
    )
    s = rej_bounded_poly(p.eta, seeds)
    return s[..., : p.l, :], s[..., p.l :, :]


def expand_mask(p: MLDSAParams, rhopp: jax.Array, kappa: jax.Array) -> jax.Array:
    """rhopp (..., 64), kappa (...,) int32 -> y (..., l, 256).

    kappa is traced data (per-lane counters differ), so the 2-byte LE suffix is
    built from arithmetic on the traced value.
    """
    kr = kappa[..., None] + jnp.arange(p.l)  # (..., l)
    suffix = jnp.stack([kr & 0xFF, (kr >> 8) & 0xFF], axis=-1).astype(jnp.uint8)
    rep = jnp.broadcast_to(rhopp[..., None, :], rhopp.shape[:-1] + (p.l, 64))
    buf = keccak.shake256(jnp.concatenate([rep, suffix], axis=-1), 32 * p.z_bits)
    return bit_unpack(buf, p.gamma1, p.z_bits)


_BALL_BYTES = 8 + 1024  # fixed SHAKE squeeze, same convention as the oracle


def sample_in_ball(p: MLDSAParams, ctilde: jax.Array) -> jax.Array:
    """(..., lambda/4) uint8 -> (..., 256) int32 with tau ±1 coefficients.

    Gather-free reformulation of the spec's Fisher-Yates (fixed 1024-byte
    buffer, same convention as the oracle).  The naive per-byte scan needs a
    dynamic gather + two dynamic scatters per step x 1024 steps, which
    serialise per-lane on TPU (measured 24 us/op — 73% of a whole verify).
    Three phases instead:

    1. a scalar scan over the 1024 bytes carrying only the insertion index
       ``i`` per lane — which bytes are *accepted* depends on nothing else;
    2. a bitonic compaction of the accepted bytes to the front (spec order);
    3. ``tau`` static swap steps: at the s-th accepted swap the insertion
       position is ALWAYS ``N - tau + s`` (a static index) and the sign bit
       index is ``s``, so only the ``j`` side needs a one-hot mask.  The
       sign write lands after the ``c[i] = c[j]`` copy, preserving the
       ``j == i`` overwrite order of the sequential formulation.
    """
    buf = keccak.shake256(ctilde, _BALL_BYTES)
    signs = buf[..., :8]
    # 64 sign bits as two uint32 words
    s_lo = jnp.sum(
        signs[..., :4].astype(jnp.uint32) << (8 * jnp.arange(4, dtype=jnp.uint32)), axis=-1
    )
    s_hi = jnp.sum(
        signs[..., 4:8].astype(jnp.uint32) << (8 * jnp.arange(4, dtype=jnp.uint32)), axis=-1
    )
    rejb = buf[..., 8:].astype(jnp.int32)
    batch = ctilde.shape[:-1]
    tau = p.tau
    nb = rejb.shape[-1]

    def step(i, j):
        take = (i < N) & (j <= i)
        return i + take, take

    i0 = jnp.full(batch, N - tau, dtype=jnp.int32)
    _, takes = lax.scan(step, i0, jnp.moveaxis(rejb, -1, 0))
    takes = jnp.moveaxis(takes, 0, -1)  # (..., 1024) bool
    ntakes = jnp.sum(takes, axis=-1)

    # accepted bytes to the front, spec order (nb is a power of two)
    idx = jnp.arange(nb, dtype=jnp.int32)
    key = jnp.where(takes, 0, 1 << 18) | (idx << 8) | rejb
    j_acc = bitonic_sort(key)[..., :tau] & 0xFF

    c = jnp.zeros(batch + (N,), dtype=jnp.int32)
    pos = jnp.arange(N, dtype=jnp.int32)
    for s in range(tau):
        valid = s < ntakes
        mask = (pos == j_acc[..., s, None]) & valid[..., None]
        cj = jnp.sum(c * mask, axis=-1)
        bit = ((s_lo >> s) if s < 32 else (s_hi >> (s - 32))) & 1
        sign_val = jnp.where(bit == 0, 1, Q - 1).astype(jnp.int32)
        tgt = N - tau + s
        c = c.at[..., tgt].set(jnp.where(valid, cj, c[..., tgt]))
        c = jnp.where(mask, sign_val[..., None], c)
    return c


# --------------------------------------------------------------------------
# Hint packing (FIPS 204 §7.1 HintBitPack / HintBitUnpack), batched
# --------------------------------------------------------------------------


def hint_bit_pack(p: MLDSAParams, h: jax.Array) -> jax.Array:
    """h (..., k, 256) in {0,1} -> (..., omega + k) uint8.

    Gather/scatter/sort-free: the destination byte of each set hint bit is
    its prefix rank (cumsum) plus the preceding rows' total, and the output
    is a one-hot contraction out[w] = sum_n pos_n * [dest_n == w] over the
    k*256 candidate bits — (omega+k) x 1536 compares per lane, pure VPU.
    The previous stable-argsort + put_along_axis formulation serialised
    per-lane on TPU and dominated the sign attempt (r4 prefix probe: the
    pack stage was ~68%% of the whole attempt at batch 8192)."""
    batch = h.shape[:-2]
    h = h.astype(jnp.int32)
    counts = jnp.sum(h, axis=-1)  # (..., k)
    ends = jnp.cumsum(counts, axis=-1)
    starts = ends - counts
    # rank of each set bit within its row (0-based among ones, index order)
    rank = jnp.cumsum(h, axis=-1) - h
    dest = jnp.where(h == 1, starts[..., None] + rank, -1)  # (..., k, 256)
    npos = jnp.arange(N, dtype=jnp.int32)
    flat_dest = dest.reshape(batch + (1, -1))  # (..., 1, k*256)
    flat_pos = jnp.broadcast_to(
        jnp.tile(npos, h.shape[-2]), flat_dest.shape[:-2] + (flat_dest.shape[-1],)
    )[..., None, :]
    w = jnp.arange(p.omega, dtype=jnp.int32)[..., :, None]  # (omega, 1)
    packed = jnp.sum(
        jnp.where(flat_dest == w, flat_pos, 0), axis=-1
    )  # (..., omega)
    out = jnp.concatenate([packed, ends], axis=-1)
    return out.astype(jnp.uint8)


def hint_bit_unpack(p: MLDSAParams, b: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(..., omega + k) uint8 -> (h (..., k, 256), ok (...,) bool)."""
    pos = b[..., : p.omega].astype(jnp.int32)  # (..., omega)
    ends = b[..., p.omega :].astype(jnp.int32)  # (..., k)
    starts = jnp.concatenate([jnp.zeros_like(ends[..., :1]), ends[..., :-1]], axis=-1)
    ok = jnp.all(ends >= starts, axis=-1) & jnp.all(ends <= p.omega, axis=-1)
    widx = jnp.arange(p.omega)
    in_row = (widx >= starts[..., None]) & (widx < ends[..., None])  # (..., k, omega)
    # strictly increasing within each row
    prev_same_row = in_row & (widx > starts[..., None])
    inc_ok = jnp.where(
        prev_same_row,
        pos[..., None, :] > jnp.roll(pos, 1, axis=-1)[..., None, :],
        True,
    )
    ok = ok & jnp.all(inc_ok, axis=(-1, -2))
    total = ends[..., -1]
    ok = ok & jnp.all(jnp.where(widx >= total[..., None], pos == 0, True), axis=-1)
    # scatter ones: h[r, pos[w]] = 1 for w in [starts[r], ends[r])
    h = jnp.zeros(b.shape[:-1] + (p.k, N + 1), dtype=jnp.int32)
    dest = jnp.where(in_row, pos[..., None, :], N)  # sentinel column dropped
    h = jnp.put_along_axis(h, dest, jnp.where(in_row, 1, 0), axis=-1, inplace=False)
    return h[..., :N], ok


# --------------------------------------------------------------------------
# KeyGen (FIPS 204 Algorithm 6), batched
# --------------------------------------------------------------------------


def _matvec(a_hat: jax.Array, v_hat: jax.Array) -> jax.Array:
    """(..., k, l, 256) ∘ (..., l, 256) -> (..., k, 256) pointwise-NTT matvec."""
    return jnp.sum(pw_mul(a_hat, v_hat[..., None, :, :]), axis=-2) % Q


def keygen(p: MLDSAParams, xi: jax.Array) -> tuple[jax.Array, jax.Array]:
    """xi (..., 32) uint8 -> (pk (..., pk_len), sk (..., sk_len)) uint8."""
    xi = jnp.asarray(xi, jnp.uint8)
    batch = xi.shape[:-1]
    kl = jnp.broadcast_to(jnp.asarray([p.k, p.l], jnp.uint8), batch + (2,))
    seed = keccak.shake256(jnp.concatenate([xi, kl], axis=-1), 128)
    rho, rhop, cap_k = seed[..., :32], seed[..., 32:96], seed[..., 96:]
    a_hat = expand_a(p, rho)
    s1, s2 = expand_s(p, rhop)
    s1_hat = ntt(s1)
    t = (ntt_inv(_matvec(a_hat, s1_hat)) + s2) % Q
    t1, t0 = power2round(t)
    pk = jnp.concatenate(
        [rho, simple_bit_pack(t1, 23 - D).reshape(batch + (-1,))], axis=-1
    )
    tr = keccak.shake256(pk, 64)
    sk = jnp.concatenate(
        [
            rho,
            cap_k,
            tr,
            bit_pack(s1, p.eta, p.s_bits).reshape(batch + (-1,)),
            bit_pack(s2, p.eta, p.s_bits).reshape(batch + (-1,)),
            bit_pack(t0, 1 << (D - 1), D).reshape(batch + (-1,)),
        ],
        axis=-1,
    )
    return pk, sk


# --------------------------------------------------------------------------
# Sign (FIPS 204 Algorithm 7), batched with masked retry loop
# --------------------------------------------------------------------------


def _unpack_sk(p: MLDSAParams, sk: jax.Array):
    batch = sk.shape[:-1]
    rho, cap_k, tr = sk[..., :32], sk[..., 32:64], sk[..., 64:128]
    off = 128
    sb = 32 * p.s_bits
    s1 = bit_unpack(sk[..., off : off + p.l * sb].reshape(batch + (p.l, sb)), p.eta, p.s_bits)
    off += p.l * sb
    s2 = bit_unpack(sk[..., off : off + p.k * sb].reshape(batch + (p.k, sb)), p.eta, p.s_bits)
    off += p.k * sb
    tb = 32 * D
    t0 = bit_unpack(
        sk[..., off : off + p.k * tb].reshape(batch + (p.k, tb)), 1 << (D - 1), D
    )
    return rho, cap_k, tr, s1, s2, t0


def _inf_norm(x: jax.Array, axes) -> jax.Array:
    return jnp.max(jnp.abs(_center(x)), axis=axes)


def precompute_sk(p: MLDSAParams, sk: jax.Array) -> dict[str, jax.Array]:
    """Per-key device state the sign loop reuses across every dispatch.

    ExpandA and the key-dependent NTTs (s1, s2, t0) depend only on the
    secret key — hoisting them out of ``sign_mu`` lets the operand cache
    (provider/opcache.py) compute them ONCE per key and keep them
    device-resident, so repeat sign dispatches against the same key skip
    both the sk re-upload and the ExpandA work.  The returned pytree may be
    unbatched (one key) and broadcasts against any mu/rnd batch.
    """
    rho, cap_k, tr, s1, s2, t0 = _unpack_sk(p, jnp.asarray(sk, jnp.uint8))
    del tr
    return {
        "cap_k": cap_k,
        "a_hat": expand_a(p, rho),
        "s1_hat": ntt(s1),
        "s2_hat": ntt(s2),
        "t0_hat": ntt(t0),
    }


def sign_mu_rounds(p: MLDSAParams, sk: jax.Array, mu: jax.Array, rnd: jax.Array,
                   kappa0: jax.Array, n_iters: int, unroll: int = 1):
    """At most ``n_iters`` rejection-loop iterations from per-lane ``kappa0``.

    Returns (sigma, done, kappa): each lane's kappa sequence depends only on
    its own rhopp and counter, so a caller may stop, compact the unfinished
    lanes into a smaller batch, and resume from the returned kappa — the
    produced signatures are bit-identical to the run-to-completion loop
    (the compact-and-refill driver below, ``sign_mu_compact``).

    ``unroll`` runs that many attempts per ``while_loop`` body (masked
    selection keeps each lane's FIRST accept, so results are bit-identical;
    ``n_iters`` must be a multiple of ``unroll`` so the attempt budget —
    and thus the returned (done, kappa) resumption state — is exactly the
    unroll=1 contract).  Committed NEGATIVE result (bench_report.md):
    an in-loop attempt measures ~155 ms at batch 8192 while its standalone
    stages sum to ~55 ms, but unroll=5 changed nothing (784.7 vs 794.6 ms
    for 5 attempts) — the gap is NOT the iteration boundary; standalone
    stage timings are flattered by cross-dispatch overlap in the timing
    harness, and the serial in-context chain is the true cost.  Default 1.
    """
    return _sign_mu_core(p, precompute_sk(p, sk), mu, rnd, kappa0, n_iters,
                         unroll)


def _sign_mu_core(p: MLDSAParams, pre: dict[str, jax.Array], mu: jax.Array,
                  rnd: jax.Array, kappa0: jax.Array, n_iters: int,
                  unroll: int = 1):
    """Rejection loop over precomputed key state (see ``precompute_sk``)."""
    if unroll < 1 or n_iters % unroll:
        raise ValueError(f"n_iters ({n_iters}) must be a positive multiple "
                         f"of unroll ({unroll})")
    mu = jnp.asarray(mu, jnp.uint8)
    rnd = jnp.asarray(rnd, jnp.uint8)
    batch = mu.shape[:-1]
    a_hat = pre["a_hat"]
    s1_hat, s2_hat, t0_hat = pre["s1_hat"], pre["s2_hat"], pre["t0_hat"]
    cap_k = jnp.broadcast_to(pre["cap_k"], batch + (32,))
    rhopp = keccak.shake256(jnp.concatenate([cap_k, rnd, mu], axis=-1), 64)

    zb = 32 * p.z_bits
    sig_len = p.sig_len
    done0 = jnp.zeros(batch, dtype=bool)
    kappa_init = jnp.broadcast_to(jnp.asarray(kappa0, jnp.int32), batch)
    sig0 = jnp.zeros(batch + (sig_len,), dtype=jnp.uint8)

    def attempt(kappa):
        """One rejection-loop iteration for every lane; returns (ok, sigma)."""
        y = expand_mask(p, rhopp, kappa)
        w = ntt_inv(_matvec(a_hat, ntt(y)))
        w1, _ = decompose(p, w)
        w1_enc = simple_bit_pack(w1, p.w1_bits).reshape(batch + (-1,))
        ctilde = keccak.shake256(
            jnp.concatenate([mu, w1_enc], axis=-1), p.ctilde_len
        )
        c_hat = ntt(sample_in_ball(p, ctilde))
        cs1 = ntt_inv(pw_mul(c_hat[..., None, :], s1_hat))
        z = (y + cs1) % Q
        ok = _inf_norm(z, (-1, -2)) < p.gamma1 - p.beta
        cs2 = ntt_inv(pw_mul(c_hat[..., None, :], s2_hat))
        r_minus = (w - cs2) % Q
        _, r0 = decompose(p, r_minus)
        ok &= jnp.max(jnp.abs(r0), axis=(-1, -2)) < p.gamma2 - p.beta
        ct0 = ntt_inv(pw_mul(c_hat[..., None, :], t0_hat))
        ok &= _inf_norm(ct0, (-1, -2)) < p.gamma2
        h_arg = (_center(r_minus) + _center(ct0)) % Q
        hi_with = decompose(p, h_arg)[0]
        hi_base = decompose(p, r_minus)[0]
        h = (hi_with != hi_base).astype(jnp.int32)
        ok &= jnp.sum(h, axis=(-1, -2)) <= p.omega
        sigma = jnp.concatenate(
            [
                ctilde,
                bit_pack(z, p.gamma1, p.z_bits).reshape(batch + (-1,)),
                hint_bit_pack(p, h),
            ],
            axis=-1,
        )
        return ok, sigma

    def cond(state):
        done, _, _, it = state
        return (~jnp.all(done)) & (it < n_iters)

    def body(state):
        done, kappa, sig, it = state
        for _ in range(unroll):
            ok, sigma = attempt(kappa)
            newly = (~done) & ok
            sig = jnp.where(newly[..., None], sigma, sig)
            kappa = jnp.where(done | ok, kappa, kappa + p.l)
            done = done | ok
        return done, kappa, sig, it + unroll

    done, kappa, sig, _ = lax.while_loop(
        cond, body, (done0, kappa_init, sig0, jnp.int32(0))
    )
    return sig, done, kappa


def sign_mu(p: MLDSAParams, sk: jax.Array, mu: jax.Array, rnd: jax.Array):
    """Core of Algorithm 7 given mu = SHAKE256(tr||M', 64).

    sk (..., sk_len), mu (..., 64), rnd (..., 32) ->
    (sigma (..., sig_len), done (...,) bool).

    ``done`` is False for any lane whose rejection loop exhausted
    MAX_SIGN_ITERS attempts (P < 1e-12 per lane); such a lane's sigma is
    all-zero and must not be emitted — callers check host-side and raise.
    """
    sig, done, _ = sign_mu_rounds(p, sk, mu, rnd, jnp.int32(0), MAX_SIGN_ITERS)
    return sig, done


def sign_mu_pre(p: MLDSAParams, pre: dict[str, jax.Array], mu: jax.Array,
                rnd: jax.Array):
    """``sign_mu`` over a ``precompute_sk`` pytree — bit-identical output
    (the precompute is a pure hoist of the key-dependent prefix)."""
    sig, done, _ = _sign_mu_core(p, pre, mu, rnd, jnp.int32(0), MAX_SIGN_ITERS)
    return sig, done


#: compact-and-refill schedule: iterations for the first dispatches; after
#: the schedule is exhausted the surviving (small) bucket runs to
#: completion in ONE dispatch.  Three total dispatches — on a remote/slow
#: link each round-trip costs real time, so the tail must not become a
#: string of tiny rounds (measured: a 3-iter/round greedy schedule was 2x
#: SLOWER than the plain loop from ~11 rounds of dispatch overhead).
COMPACT_SCHEDULE = (6, 6)


@functools.cache
def _rounds_jit(name: str, n_iters: int):
    p = PARAMS[name]
    return jax.jit(functools.partial(sign_mu_rounds, p, n_iters=n_iters))


def sign_mu_compact(name: str, sk, mu, rnd, *,
                    schedule: tuple[int, ...] = COMPACT_SCHEDULE,
                    min_bucket: int = 64):
    """Compact-and-refill signing driver (host-orchestrated, device-resident).

    The all-lanes loop in ``sign_mu`` iterates until the SLOWEST lane
    accepts — E[max of B geometrics] ≈ 30 attempts at B = 8192 where the
    mean is ~4, so ~7x the necessary work.  This driver runs ``schedule[0]``
    iterations on the full batch, gathers the unfinished lanes into the
    next power-of-two bucket ON DEVICE (the host only downloads the done
    mask and uploads a small index list — operand rows never cross the
    host link), repeats for ``schedule[1:]`` from each lane's saved kappa,
    then runs the last survivors to completion in one final dispatch.
    Results are bit-identical to ``sign_mu`` (same per-lane kappa
    sequences); attempted work drops ~3x at batch 8192.

    Returns (sigma, done) as numpy arrays.
    """
    p = PARAMS[name]
    sk_d = jnp.asarray(sk, jnp.uint8)
    mu_d = jnp.asarray(mu, jnp.uint8)
    rnd_d = jnp.asarray(rnd, jnp.uint8)
    b = mu_d.shape[0]
    sig_out = jnp.zeros((b, p.sig_len), jnp.uint8)
    done_out = np.zeros(b, dtype=bool)
    idx = np.arange(b)
    kappa_d = jnp.zeros(b, jnp.int32)
    iters_used = 0
    round_no = 0
    while idx.size and iters_used < MAX_SIGN_ITERS:
        bucket = max(min(_next_pow2_i(idx.size), b), min(min_bucket, b))
        pad_idx = np.concatenate([idx, np.full(bucket - idx.size, idx[-1])]) \
            if idx.size < bucket else idx
        idx_d = jnp.asarray(pad_idx)
        if round_no < len(schedule):
            n_it = min(schedule[round_no], MAX_SIGN_ITERS - iters_used)
        else:
            # Completion round: a CONSTANT iteration bound so every bucket
            # size shares one compiled variant regardless of the schedule
            # (the while_loop exits as soon as all lanes accept; lanes may
            # thus exceed MAX_SIGN_ITERS total by the schedule's length —
            # strictly more attempts than the plain loop, never fewer).
            n_it = MAX_SIGN_ITERS
        round_no += 1
        sig_r, done_r, kappa_r = _rounds_jit(name, n_it)(
            jnp.take(sk_d, idx_d, axis=0),
            jnp.take(mu_d, idx_d, axis=0),
            jnp.take(rnd_d, idx_d, axis=0),
            jnp.take(kappa_d, idx_d, axis=0),
        )
        iters_used += n_it
        live = idx.size
        # scatter finished rows back (device-side); dedupe pad rows first
        sig_out = sig_out.at[idx_d[:live]].set(sig_r[:live])
        kappa_d = kappa_d.at[idx_d[:live]].set(kappa_r[:live])
        done_host = np.asarray(done_r)[:live]  # tiny d2h transfer
        done_out[idx[done_host]] = True
        idx = idx[~done_host]  # qrlint: disable=flow-secret-branch — ML-DSA rejection-sampling bookkeeping: which rows finished per round is public by FIPS 204 design (iteration counts leak, coefficients don't)
    return np.asarray(sig_out), done_out





# --------------------------------------------------------------------------
# Verify (FIPS 204 Algorithm 8), batched
# --------------------------------------------------------------------------


def precompute_pk(p: MLDSAParams, pk: jax.Array) -> dict[str, jax.Array]:
    """Per-key device state the verify path reuses across dispatches:
    ExpandA(rho) and NTT(t1 << D) depend only on the public key (same
    rationale as ``precompute_sk``; consumed by the operand cache).  May be
    unbatched and broadcasts against any mu/sigma batch."""
    pk = jnp.asarray(pk, jnp.uint8)
    rho = pk[..., :32]
    t1 = simple_bit_unpack(
        pk[..., 32:].reshape(pk.shape[:-1] + (p.k, 32 * (23 - D))), 23 - D
    )
    t1_shift = (t1.astype(jnp.int32) << D) % Q
    return {"a_hat": expand_a(p, rho), "t1_hat": ntt(t1_shift)}


def verify_mu(p: MLDSAParams, pk: jax.Array, mu: jax.Array, sigma: jax.Array) -> jax.Array:
    """Core of Algorithm 8 given mu. pk (..., pk_len), mu (..., 64),
    sigma (..., sig_len) -> bool (...,)."""
    return verify_mu_pre(p, precompute_pk(p, pk), mu, sigma)


def verify_mu_pre(p: MLDSAParams, pre: dict[str, jax.Array], mu: jax.Array,
                  sigma: jax.Array) -> jax.Array:
    """``verify_mu`` over a ``precompute_pk`` pytree (pure hoist)."""
    mu = jnp.asarray(mu, jnp.uint8)
    sigma = jnp.asarray(sigma, jnp.uint8)
    batch = mu.shape[:-1]
    ctilde = sigma[..., : p.ctilde_len]
    zb = 32 * p.z_bits
    off = p.ctilde_len
    z = bit_unpack(
        sigma[..., off : off + p.l * zb].reshape(batch + (p.l, zb)), p.gamma1, p.z_bits
    )
    h, ok = hint_bit_unpack(p, sigma[..., off + p.l * zb :])
    ok &= _inf_norm(z, (-1, -2)) < p.gamma1 - p.beta
    c_hat = ntt(sample_in_ball(p, ctilde))
    az = _matvec(pre["a_hat"], ntt(z))
    ct1 = pw_mul(c_hat[..., None, :], pre["t1_hat"])
    w_approx = ntt_inv((az - ct1) % Q)
    w1 = use_hint(p, h, w_approx)
    w1_enc = simple_bit_pack(w1, p.w1_bits).reshape(batch + (-1,))
    ctilde2 = keccak.shake256(jnp.concatenate([mu, w1_enc], axis=-1), p.ctilde_len)
    ok &= jnp.all(ctilde == ctilde2, axis=-1)
    return ok


# --------------------------------------------------------------------------
# Jitted per-parameter-set entry points
# --------------------------------------------------------------------------


@functools.cache
def get(name: str):
    """Jitted (keygen, sign_mu, verify_mu) triple for a parameter-set name."""
    p = PARAMS[name]
    return (
        jax.jit(functools.partial(keygen, p)),
        jax.jit(functools.partial(sign_mu, p)),
        jax.jit(functools.partial(verify_mu, p)),
    )


def sign_mu_cold(p: MLDSAParams, sk: jax.Array, mu: jax.Array, rnd: jax.Array):
    """Cache-filling sign: ONE dispatch returning the per-key device state
    (ExpandA + key NTTs) alongside the signatures, so a cache miss costs no
    extra round trip over the uncached path (see kem.mlkem.encaps_cold)."""
    pre = precompute_sk(p, sk)
    sig, done = sign_mu_pre(p, pre, mu, rnd)
    return pre, sig, done


def verify_mu_cold(p: MLDSAParams, pk: jax.Array, mu: jax.Array, sigma: jax.Array):
    """Cache-filling verify (see ``sign_mu_cold``)."""
    pre = precompute_pk(p, pk)
    return pre, verify_mu_pre(p, pre, mu, sigma)


@functools.cache
def get_pre(name: str):
    """Jitted (sign_mu_cold, sign_mu_pre, verify_mu_cold, verify_mu_pre)
    for the device operand cache (provider/opcache.py): the cold variants
    fill the cache in one dispatch; the pre variants run over a cached
    pytree, skipping the key upload and ExpandA."""
    p = PARAMS[name]
    return (
        jax.jit(functools.partial(sign_mu_cold, p)),
        jax.jit(functools.partial(sign_mu_pre, p)),
        jax.jit(functools.partial(verify_mu_cold, p)),
        jax.jit(functools.partial(verify_mu_pre, p)),
    )
