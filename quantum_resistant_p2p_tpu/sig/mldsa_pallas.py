"""Fused Pallas TPU kernel for ML-DSA's RejNTTPoly (FIPS 204 Algorithm 30).

Same recipe as kem/mlkem_pallas.py, which took ML-KEM encaps off the HBM
roofline: ExpandA draws k*l uniform NTT-domain polynomials per op (30 for
ML-DSA-65), and the jnp path's pairs-bitonic compaction moves ~11 GB of
HBM per 1024-batch — measured 22.6k polys-batch/s, ~45% of the whole
verify budget.  This kernel runs SHAKE-128 absorb, all 7 squeeze
permutations, 3-byte candidate extraction, and the 512-wide key/value
bitonic compaction in VMEM; HBM sees only the 21 input lane-words and the
256 output coefficients per seed.

The 23-bit candidates do not fit an int32 sort key next to the index, so
the network carries (key = reject<<10 | idx, val = candidate) register
pairs — :func:`core.sortnet.bitonic_sort_pairs_regs`, bit-identical in
output order to sig/mldsa.py:rej_ntt_poly's array formulation (asserted by
tests/test_mldsa_pallas.py; the kernel body is tested eagerly on CPU, the
full pallas_call natively on the chip).

Replaces (reference): the rejection loop inside liboqs ML-DSA
(vendor/oqs.py:506-583 via crypto/signatures.py:58-188).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.keccak_pallas import _f1600, absorb_block, block_bytes, sampler_call
from ..core.sortnet import bitonic_sort_pairs_regs, bitonic_sort_regs

Q = 8380417
RATE_WORDS = 21  # SHAKE-128 rate: 168 bytes = 21 lanes
N_SQUEEZE = 7  # 7 * 168 = 1176 bytes -> 392 candidates for 256 slots
N_CAND = 392
N_SORT = 512
N_OUT = 256


def _rej_ntt_tiles(in_hi: list, in_lo: list) -> list:
    """The full RejNTTPoly pipeline over 21 input lane-word tiles.

    Pure function of same-shaped uint32 arrays -> 256 int32 arrays; the
    Pallas kernel calls it on VMEM tiles, tests call it eagerly on CPU.
    """
    sh, sl = absorb_block(in_hi, in_lo, RATE_WORDS)

    # Squeeze 1176 bytes; each byte triple is one 23-bit candidate
    # b0 | b1<<8 | (b2 & 0x7F)<<16.
    cand = []
    for blk in range(N_SQUEEZE):
        byts = block_bytes(sh, sl, RATE_WORDS)
        for t in range(len(byts) // 3):
            b0, b1, b2 = byts[3 * t], byts[3 * t + 1], byts[3 * t + 2]
            c = (b0 | (b1 << 8) | ((b2 & 0x7F) << 16)).astype(jnp.int32)  # 23-bit bound machine-proved by qrkernel's interval analysis
            cand.append(c)
        if blk + 1 < N_SQUEEZE:
            sh, sl = _f1600(sh, sl)
    assert len(cand) == N_CAND

    # key = reject<<10 | index: accepted candidates first, spec order —
    # identical packing to sig/mldsa.py:rej_ntt_poly.
    keys = [jnp.where(c < Q, 0, 1 << 10) | i for i, c in enumerate(cand)]
    val_sent = jnp.zeros_like(cand[0])
    # unique sentinel keys, all above every real key (pairs-sort contract)
    keys += [jnp.full_like(keys[0], (1 << 11) | s) for s in range(N_SORT - N_CAND)]
    cand += [val_sent] * (N_SORT - N_CAND)
    _, cand = bitonic_sort_pairs_regs(keys, cand)
    return cand[:N_OUT]


def _rej_ntt_kernel(in_hi_ref, in_lo_ref, out_ref):
    out = _rej_ntt_tiles(
        [in_hi_ref[w] for w in range(RATE_WORDS)],
        [in_lo_ref[w] for w in range(RATE_WORDS)],
    )
    for i in range(N_OUT):
        out_ref[i] = out[i]


# --------------------------------------------------------------------------
# RejBoundedPoly (FIPS 204 Algorithm 31): SHAKE-256 nibble rejection
# --------------------------------------------------------------------------

RB_RATE_WORDS = 17  # SHAKE-256 rate: 136 bytes = 17 lanes
RB_N_SQUEEZE = 4  # 544 bytes squeezed; the first 512 feed the compaction
RB_N_SORT = 1024  # nibble candidates (= mldsa._REJ_BOUNDED_SORT), a power of 2


def _rej_bounded_tiles(in_hi: list, in_lo: list, eta: int) -> list:
    """RejBoundedPoly pipeline over 17 input lane-word tiles -> 256 nibble tiles.

    Returns the RAW accepted nibbles (0..14 / 0..8); the caller applies the
    eta-map — keeping the kernel's output identical to the jnp path's
    pre-map compaction.
    """
    sh, sl = absorb_block(in_hi, in_lo, RB_RATE_WORDS)

    bound = 15 if eta == 2 else 9
    byts = []
    for blk in range(RB_N_SQUEEZE):
        byts += block_bytes(sh, sl, RB_RATE_WORDS)
        if blk + 1 < RB_N_SQUEEZE and 2 * len(byts) < RB_N_SORT:
            sh, sl = _f1600(sh, sl)
    byts = byts[: RB_N_SORT // 2]  # first 512 bytes -> 1024 nibble candidates
    keys = []
    for byte in byts:
        for z in (byte & 0xF, byte >> 4):
            i = len(keys)
            keys.append(
                jnp.where(z < bound, 0, 1 << 16) | (i << 4) | z.astype(jnp.int32)
            )
    assert len(keys) == RB_N_SORT
    keys = bitonic_sort_regs(keys)
    return [k & 0xF for k in keys[:N_OUT]]


def _rej_bounded_kernel(in_hi_ref, in_lo_ref, out_ref, *, eta: int):
    out = _rej_bounded_tiles(
        [in_hi_ref[w] for w in range(RB_RATE_WORDS)],
        [in_lo_ref[w] for w in range(RB_RATE_WORDS)],
        eta,
    )
    for i in range(N_OUT):
        out_ref[i] = out[i]


@functools.partial(jax.jit, static_argnames=("eta", "interpret"))
def rej_bounded_words(in_hi: jax.Array, in_lo: jax.Array, *, eta: int,
                      interpret: bool = False):
    """Batched RejBoundedPoly over word-transposed padded seed blocks.

    Args:
      in_hi/in_lo: (17, B) uint32 — the padded 136-byte XOF seed block
        (rhop || n || 0x1F pad || 0x80) as hi/lo lane words, batch minor.
      eta: 2 or 4 (static; sets the nibble acceptance bound).

    Returns:
      (256, B) int32 raw accepted nibbles (pre eta-map) in [0, bound).
    """
    return sampler_call(functools.partial(_rej_bounded_kernel, eta=eta),
                        RB_RATE_WORDS, N_OUT, in_hi, in_lo, interpret=interpret)


# --------------------------------------------------------------------------
# NTT / invNTT over Z_q[X]/(X^256+1) (FIPS 204 §7.5) — VMEM-resident
# --------------------------------------------------------------------------
#
# The jnp formulation (sig/mldsa.py ntt/ntt_inv) materialises the full
# batched coefficient array between each of the 8 butterfly stages — 16 HBM
# round-trips per transform, and a sign attempt runs ~29 poly transforms
# (ntt(y) x l, invntt(w) x k, ntt(c), invntt(cs1/cs2/ct0) x l+2k).  Here a
# poly's 256 coefficients live as 256 (8, 128) int32 register tiles across
# 1024 lanes; all 1024 butterflies run in VMEM and HBM sees one read + one
# write.  Same register-resident recipe as the sampler kernels above.

from ..pyref.mldsa_ref import ZETAS as _ZETAS_PY

_N = 256
_N_INV = pow(_N, -1, Q)


def _mm_zeta(a, z: int):
    """(a * z) % Q for an int32 tile a in [0, q) and STATIC z in [0, q).

    Horner over 8-bit limbs of z keeps every intermediate under 2**31
    (identical arithmetic to sig/mldsa.py:_mm with b static).  The limb
    bounds are machine-checked: qrkernel's interval analysis proves every
    product/shift below from the two declared contracts."""
    # qrkernel: assume a in [0, Q) — FIPS 204 §7.5: NTT butterfly operands are mod-q residues (every caller reduces % Q first)
    # qrkernel: assume z in [0, Q) — zeta table entries are powers of the 512th root of unity mod q
    b2, b1, b0 = z >> 16, (z >> 8) & 0xFF, z & 0xFF
    r = (a * b2) % Q
    r = (((r << 8) % Q) + (a * b1) % Q) % Q
    r = (((r << 8) % Q) + (a * b0) % Q) % Q
    return r


def ntt_tiles(f: list) -> list:
    """256 int32 tiles in [0, q) -> NTT domain (bit-exact vs mldsa.ntt)."""
    f = list(f)
    k = 1
    length = 128
    while length >= 1:
        groups = _N // (2 * length)
        for g in range(groups):
            z = int(_ZETAS_PY[k + g])
            base = g * 2 * length
            for j in range(length):
                i0, i1 = base + j, base + length + j
                t = _mm_zeta(f[i1], z)
                f[i0], f[i1] = (f[i0] + t) % Q, (f[i0] - t) % Q
        k += groups
        length //= 2
    return f


def ntt_inv_tiles(f: list) -> list:
    """Inverse transform; bit-exact vs mldsa.ntt_inv."""
    f = list(f)
    k = 255
    length = 1
    while length <= 128:
        groups = _N // (2 * length)
        zs = [int(_ZETAS_PY[k - groups + 1 + i]) for i in range(groups)][::-1]
        for g in range(groups):
            base = g * 2 * length
            for j in range(length):
                i0, i1 = base + j, base + length + j
                s = (f[i0] + f[i1]) % Q
                t = _mm_zeta((f[i1] - f[i0]) % Q, zs[g])
                f[i0], f[i1] = s, t
        k -= groups
        length *= 2
    return [_mm_zeta(x, _N_INV) for x in f]


def _ntt_kernel(in_ref, out_ref, *, inverse: bool):
    f = [in_ref[i] for i in range(_N)]
    out = ntt_inv_tiles(f) if inverse else ntt_tiles(f)
    for i in range(_N):
        out_ref[i] = out[i]


@functools.partial(jax.jit, static_argnames=("inverse", "interpret"))
def ntt_words(x: jax.Array, *, inverse: bool = False, interpret: bool = False):
    """Batched (inv)NTT over words layout.

    Args:
      x: (256, L) int32 coefficients in [0, q), lanes batch-minor (L is
        padded to the 1024-lane tile internally).

    Returns:
      (256, L) int32 transformed coefficients.
    """
    from jax.experimental import pallas as pl

    from ..core.keccak_pallas import _TL, _TS, BT

    n, l = x.shape
    assert n == _N
    lp = -(-l // BT) * BT
    if lp != l:
        x = jnp.pad(x, ((0, 0), (0, lp - l)))
    x = x.reshape(_N, lp // _TL, _TL)
    out = pl.pallas_call(
        functools.partial(_ntt_kernel, inverse=inverse),
        grid=(lp // BT,),
        in_specs=[pl.BlockSpec((_N, _TS, _TL), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((_N, _TS, _TL), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((_N, lp // _TL, _TL), jnp.int32),
        interpret=interpret,
    )(x)
    return out.reshape(_N, lp)[:, :l]


@functools.partial(jax.jit, static_argnames=("interpret",))
def rej_ntt_words(in_hi: jax.Array, in_lo: jax.Array, *, interpret: bool = False):
    """Batched RejNTTPoly over word-transposed padded seed blocks.

    Args:
      in_hi/in_lo: (21, B) uint32 — the padded 168-byte XOF seed block
        (rho || s || r || 0x1F pad || 0x80) as hi/lo lane words, batch minor.

    Returns:
      (256, B) int32 NTT-domain coefficients in [0, q).
    """
    return sampler_call(_rej_ntt_kernel, RATE_WORDS, N_OUT, in_hi, in_lo,
                        interpret=interpret)
