"""Batched SPHINCS+ / SLH-DSA-SHA2 (FIPS 205, 'simple') in JAX.

TPU-native design
-----------------
SPHINCS+ is hash trees all the way down — embarrassingly parallel across WOTS+
chains, tree leaves, FORS trees, and independent signatures.  This
implementation vectorises every one of those axes:

* All F / PRF calls share the constant first SHA-256 block
  ``pk_seed || zero-pad`` (FIPS 205 §11.2.1): its midstate is computed once
  per batch and every hash resumes from it (halves compression count).
  H / T_l for the 192/256-bit sets resume a SHA-512 midstate
  (``core.sha512``, 64-bit words as uint32 pairs).
* WOTS+ chains run as W-1 = 15 lock-step rounds over a ``(batch, leaves,
  wots_len, n)`` array with per-chain masks (``t < d`` when signing, ``t >= d``
  when verifying) — no data-dependent control flow.
* An XMSS tree hashes all 2^h' leaves at once, then h' halving rounds; FORS
  hashes all k * 2^a leaves at once.  Auth paths are `take_along_axis`
  gathers with traced indices.
* The hypertree's 64-bit tree index is kept as an LSB-first bit array (TPUs
  have no 64-bit lanes); per-layer leaf indices and the 8-byte big-endian
  ADRS tree field are static bit-slices of it.
* Variable-length message hashing (H_msg, PRF_msg) happens host-side in the
  provider (public data, negligible cost); the device kernels take the fixed
  m-byte digest.  Signing is fully deterministic given (sk, digest) — no
  rejection loops anywhere.

Bit-exactness oracle: ``pyref.slhdsa_ref`` (tests/test_sphincs.py).
Replaces (reference): SPHINCSSignature's per-call liboqs objects
(crypto/signatures.py:191-315, vendor/oqs.py:506-583).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core import sha256 as jsha256
from ..core import sha512 as jsha512
from ..pyref.slhdsa_ref import (
    FORS_PRF,
    FORS_ROOTS,
    FORS_TREE,
    PARAMS,
    SLHDSAParams,
    TREE,
    W,
    WOTS_HASH,
    WOTS_PK,
    WOTS_PRF,
)

# --------------------------------------------------------------------------
# ADRS construction (compressed 22-byte SHA2 form, FIPS 205 §11.2)
# --------------------------------------------------------------------------


def _be4(x, lead: tuple[int, ...]) -> jax.Array:
    """int or int32 array -> (..., 4) uint8 big-endian, broadcast to lead."""
    x = jnp.asarray(x, jnp.int32)
    x = jnp.broadcast_to(x, lead)
    return jnp.stack(
        [(x >> 24) & 0xFF, (x >> 16) & 0xFF, (x >> 8) & 0xFF, x & 0xFF], axis=-1
    ).astype(jnp.uint8)


def _adrs(lead: tuple[int, ...], layer, tree8, typ: int, w1, w2, w3) -> jax.Array:
    """Build (..., 22) uint8 compressed ADRS broadcast over lead dims.

    ``layer`` may be a static int OR a traced int32 scalar (the layered
    sign path compiles one XMSS-layer program and feeds the layer index
    as an operand).
    """
    lb = jnp.broadcast_to(jnp.asarray(layer, jnp.uint8), lead + (1,))
    if tree8 is None:
        tb = jnp.zeros(lead + (8,), jnp.uint8)
    else:
        tb = jnp.broadcast_to(_fit(tree8, len(lead)), lead + (8,))
    ty = jnp.broadcast_to(jnp.uint8(typ), lead + (1,))
    return jnp.concatenate([lb, tb, ty, _be4(w1, lead), _be4(w2, lead), _be4(w3, lead)], axis=-1)


def _fit(a: jax.Array, lead_ndim: int) -> jax.Array:
    """Insert singleton dims so a (B..., k) array broadcasts over lead dims."""
    extra = lead_ndim - (a.ndim - 1)
    if extra < 0:
        raise ValueError("array has more batch dims than target")
    return a.reshape(a.shape[:-1] + (1,) * extra + (a.shape[-1],)) if extra else a


# --------------------------------------------------------------------------
# Hash engines with precomputed pk_seed midstates
# --------------------------------------------------------------------------


class _Ctx:
    """Per-call context: params + pk_seed midstates (batch shape B)."""

    def __init__(self, p: SLHDSAParams, pk_seed: jax.Array):
        self.p = p
        self.batch = pk_seed.shape[:-1]
        pad256 = jnp.zeros(self.batch + (64 - p.n,), jnp.uint8)
        self.mid_f = jsha256.midstate(jnp.concatenate([pk_seed, pad256], axis=-1))
        if p.big_hash:
            pad512 = jnp.zeros(self.batch + (128 - p.n,), jnp.uint8)
            self.mid_t = jsha512.midstate(jnp.concatenate([pk_seed, pad512], axis=-1))

    def f(self, adrs: jax.Array, m: jax.Array) -> jax.Array:
        """F / PRF (always SHA-256): adrs (..., 22), m (..., n) -> (..., n)."""
        data = jnp.concatenate([adrs, m], axis=-1)
        lead = data.shape[:-1]
        mid = jnp.broadcast_to(_fit(self.mid_f, len(lead)), lead + (8,))
        return jsha256.sha256_from_midstate(mid, data, 1)[..., : self.p.n]

    def t(self, adrs: jax.Array, m: jax.Array) -> jax.Array:
        """H / T_l: SHA-256 (n=16) or SHA-512 (n=24/32)."""
        data = jnp.concatenate([adrs, m], axis=-1)
        lead = data.shape[:-1]
        if not self.p.big_hash:
            mid = jnp.broadcast_to(_fit(self.mid_f, len(lead)), lead + (8,))
            return jsha256.sha256_from_midstate(mid, data, 1)[..., : self.p.n]
        mid = (
            jnp.broadcast_to(_fit(self.mid_t[0], len(lead)), lead + (8,)),
            jnp.broadcast_to(_fit(self.mid_t[1], len(lead)), lead + (8,)),
        )
        return jsha512.sha512_from_midstate(mid, data, 1)[..., : self.p.n]


# --------------------------------------------------------------------------
# WOTS+ (FIPS 205 §5), all chains in lock-step
# --------------------------------------------------------------------------


def _wots_digits(p: SLHDSAParams, m: jax.Array) -> jax.Array:
    """(..., n) uint8 -> (..., wots_len) int32 base-16 digits + checksum."""
    m = m.astype(jnp.int32)
    nib = jnp.stack([m >> 4, m & 0xF], axis=-1).reshape(m.shape[:-1] + (p.len1,))
    csum = jnp.sum(W - 1 - nib, axis=-1) << 4
    cs = jnp.stack([(csum >> 12) & 0xF, (csum >> 8) & 0xF, (csum >> 4) & 0xF], axis=-1)
    return jnp.concatenate([nib, cs], axis=-1)


def _chain(ctx: _Ctx, x: jax.Array, d: jax.Array, from_start: bool,
           layer: int, tree8, kp) -> jax.Array:
    """Lock-step chains: x (..., wots_len, n), d (..., wots_len) digits.

    from_start=True  -> apply F at steps t < d   (sign: 0 -> d)
    from_start=False -> apply F at steps t >= d  (verify: d -> W-1)
    """
    p = ctx.p
    lead = x.shape[:-1]
    chains = jnp.arange(p.wots_len)
    for t in range(W - 1):
        adrs = _adrs(lead, layer, tree8, WOTS_HASH, kp, chains, t)
        fx = ctx.f(adrs, x)
        active = (t < d) if from_start else (t >= d)
        x = jnp.where(active[..., None], fx, x)
    return x


def _wots_sk(ctx: _Ctx, sk_seed: jax.Array, layer: int, tree8, kp, lead) -> jax.Array:
    """Secret chain heads: (..., wots_len, n)."""
    p = ctx.p
    chains = jnp.arange(p.wots_len)
    adrs = _adrs(lead, layer, tree8, WOTS_PRF, kp, chains, 0)
    seed = jnp.broadcast_to(_fit(sk_seed, len(lead)), lead + (p.n,))
    return ctx.f(adrs, seed)


def _wots_pkgen(ctx: _Ctx, sk_seed: jax.Array, layer: int, tree8, kp, lead) -> jax.Array:
    """kp (..., leaves) -> compressed WOTS pk (..., leaves, n)."""
    p = ctx.p
    chain_lead = lead + (p.wots_len,)
    sk = _wots_sk(ctx, sk_seed, layer, tree8, kp[..., None], chain_lead)
    full = jnp.full(chain_lead, W - 1, jnp.int32)
    tips = _chain(ctx, sk, full, True, layer, tree8, kp[..., None])
    tmp = tips.reshape(lead + (p.wots_len * p.n,))
    pk_adrs = _adrs(lead, layer, tree8, WOTS_PK, kp, 0, 0)
    return ctx.t(pk_adrs, tmp)


# --------------------------------------------------------------------------
# XMSS (FIPS 205 §6)
# --------------------------------------------------------------------------


def _xmss_levels(ctx: _Ctx, sk_seed: jax.Array, layer: int, tree8) -> list[jax.Array]:
    """All tree levels: levels[z] has shape (B, 2^(hp-z), n)."""
    p = ctx.p
    nl = 1 << p.hp
    lead = ctx.batch + (nl,)
    leaves = _wots_pkgen(ctx, sk_seed, layer, tree8, jnp.arange(nl), lead)
    levels = [leaves]
    node = leaves
    for z in range(1, p.hp + 1):
        pairs = node.reshape(ctx.batch + (node.shape[-2] // 2, 2 * p.n))
        idx = jnp.arange(pairs.shape[-2])
        adrs = _adrs(ctx.batch + (pairs.shape[-2],), layer, tree8, TREE, 0, z, idx)
        node = ctx.t(adrs, pairs)
        levels.append(node)
    return levels


def _xmss_sign(ctx: _Ctx, m: jax.Array, sk_seed: jax.Array, idx: jax.Array,
               layer: int, tree8) -> tuple[jax.Array, jax.Array]:
    """-> (sig_xmss (B, (wots_len+hp)*n), root (B, n)); idx (B,) int32."""
    p = ctx.p
    levels = _xmss_levels(ctx, sk_seed, layer, tree8)
    digits = _wots_digits(p, m)
    chain_lead = ctx.batch + (p.wots_len,)
    sk = _wots_sk(ctx, sk_seed, layer, tree8, idx[..., None], chain_lead)
    sig_w = _chain(ctx, sk, digits, True, layer, tree8, idx[..., None])
    auth = []
    for j in range(p.hp):
        sib = ((idx >> j) ^ 1)[..., None, None]
        auth.append(jnp.take_along_axis(levels[j], sib, axis=-2)[..., 0, :])
    sig = jnp.concatenate(
        [sig_w.reshape(ctx.batch + (p.wots_len * p.n,))] + auth, axis=-1
    )
    return sig, levels[p.hp][..., 0, :]


def _xmss_pk_from_sig(ctx: _Ctx, idx: jax.Array, sig_xmss: jax.Array, m: jax.Array,
                      layer: int, tree8) -> jax.Array:
    p = ctx.p
    wlen = p.wots_len * p.n
    sig_w = sig_xmss[..., :wlen].reshape(ctx.batch + (p.wots_len, p.n))
    digits = _wots_digits(p, m)
    tips = _chain(ctx, sig_w, digits, False, layer, tree8, idx[..., None])
    pk_adrs = _adrs(ctx.batch, layer, tree8, WOTS_PK, idx, 0, 0)
    node = ctx.t(pk_adrs, tips.reshape(ctx.batch + (wlen,)))
    for k in range(p.hp):
        sib = sig_xmss[..., wlen + k * p.n : wlen + (k + 1) * p.n]
        bit = (idx >> k) & 1
        node_idx = idx >> (k + 1)
        adrs = _adrs(ctx.batch, layer, tree8, TREE, 0, k + 1, node_idx)
        pair = jnp.where(
            bit[..., None],
            jnp.concatenate([sib, node], axis=-1),
            jnp.concatenate([node, sib], axis=-1),
        )
        node = ctx.t(adrs, pair)
    return node


# --------------------------------------------------------------------------
# Hypertree index plumbing: 64-bit tree index as an LSB-first bit array
# --------------------------------------------------------------------------


def _digest_split(p: SLHDSAParams, digest: jax.Array):
    """digest (B, m) -> (md (B, ka), tree_bits (B, h-hp) lsb-first, leaf (B,))."""
    ka = (p.k * p.a + 7) // 8
    t = (p.h - p.hp + 7) // 8
    u = (p.hp + 7) // 8
    md = digest[..., :ka]
    tb = digest[..., ka : ka + t].astype(jnp.int32)
    bits = ((tb[..., :, None] >> np.arange(7, -1, -1)) & 1).reshape(tb.shape[:-1] + (8 * t,))
    tree_bits = bits[..., ::-1][..., : p.h - p.hp]
    lb = digest[..., ka + t : ka + t + u].astype(jnp.int32)
    lbits = ((lb[..., :, None] >> np.arange(7, -1, -1)) & 1).reshape(lb.shape[:-1] + (8 * u,))
    lbits = lbits[..., ::-1][..., : p.hp]
    leaf = jnp.sum(lbits << np.arange(p.hp), axis=-1)
    return md, tree_bits, leaf


def _tree8_at(p: SLHDSAParams, tree_bits: jax.Array, j: int) -> jax.Array:
    """8-byte BE ADRS tree field for hypertree layer j (idx_tree >> j*hp)."""
    nbits = p.h - p.hp
    shift = j * p.hp
    bytes_out = []
    for bb in range(7, -1, -1):  # bb = little-endian byte index; emit MSB first
        acc = jnp.zeros(tree_bits.shape[:-1], jnp.int32)
        for t in range(8):
            e = shift + 8 * bb + t
            if e < nbits:
                acc = acc | (tree_bits[..., e] << t)
        bytes_out.append(acc)
    return jnp.stack(bytes_out, axis=-1).astype(jnp.uint8)


def _leaf_at(p: SLHDSAParams, tree_bits: jax.Array, j: int) -> jax.Array:
    """Layer-j (>=1) leaf index: bits [(j-1)*hp, j*hp) of idx_tree."""
    lo = (j - 1) * p.hp
    acc = jnp.zeros(tree_bits.shape[:-1], jnp.int32)
    for t in range(p.hp):
        if lo + t < p.h - p.hp:
            acc = acc | (tree_bits[..., lo + t] << t)
    return acc


# --------------------------------------------------------------------------
# FORS (FIPS 205 §8)
# --------------------------------------------------------------------------


def _fors_indices(p: SLHDSAParams, md: jax.Array) -> jax.Array:
    """(B, ka) -> (B, k) int32 base-2^a digits, MSB-first per digit."""
    bits = ((md[..., :, None].astype(jnp.int32) >> np.arange(7, -1, -1)) & 1).reshape(
        md.shape[:-1] + (-1,)
    )[..., : p.k * p.a]
    grp = bits.reshape(md.shape[:-1] + (p.k, p.a))
    return jnp.sum(grp << np.arange(p.a - 1, -1, -1), axis=-1)


def _fors_levels(ctx: _Ctx, sk_seed: jax.Array, tree8, idx_leaf) -> list[jax.Array]:
    """levels[z]: (B, k, 2^(a-z), n) — all k FORS trees in parallel."""
    p = ctx.p
    npos = 1 << p.a
    ti = jnp.arange(p.k)[:, None]
    pos = jnp.arange(npos)[None, :]
    gidx = (ti << p.a) + pos  # (k, 2^a) global node indices
    lead = ctx.batch + (p.k, npos)
    prf_adrs = _adrs(lead, 0, tree8, FORS_PRF, idx_leaf[..., None, None], 0, gidx)
    seed = jnp.broadcast_to(_fit(sk_seed, len(lead)), lead + (p.n,))
    sk = ctx.f(prf_adrs, seed)
    leaf_adrs = _adrs(lead, 0, tree8, FORS_TREE, idx_leaf[..., None, None], 0, gidx)
    node = ctx.f(leaf_adrs, sk)
    levels = [node]
    for z in range(1, p.a + 1):
        width = node.shape[-2] // 2
        pairs = node.reshape(ctx.batch + (p.k, width, 2 * p.n))
        g = (ti << (p.a - z)) + jnp.arange(width)[None, :]
        adrs = _adrs(ctx.batch + (p.k, width), 0, tree8, FORS_TREE,
                     idx_leaf[..., None, None], z, g)
        node = ctx.t(adrs, pairs)
        levels.append(node)
    return levels, sk


def _fors_sign(ctx: _Ctx, md: jax.Array, sk_seed: jax.Array, tree8, idx_leaf):
    """-> (sig_fors (B, k*(1+a)*n), indices (B, k))."""
    p = ctx.p
    indices = _fors_indices(p, md)
    levels, sk = _fors_levels(ctx, sk_seed, tree8, idx_leaf)
    parts = []
    sk_sel = jnp.take_along_axis(sk, indices[..., :, None, None], axis=-2)[..., 0, :]
    for i in range(p.k):
        parts.append(sk_sel[..., i, :])
        for j in range(p.a):
            sib = ((indices[..., i] >> j) ^ 1)[..., None, None]
            node = jnp.take_along_axis(levels[j][..., i, :, :], sib, axis=-2)[..., 0, :]
            parts.append(node)
    sig = jnp.concatenate(parts, axis=-1)
    return sig, indices, levels


def _fors_pk_from_sig(ctx: _Ctx, sig_fors: jax.Array, md: jax.Array, tree8, idx_leaf):
    p = ctx.p
    indices = _fors_indices(p, md)
    per = (1 + p.a) * p.n
    roots = []
    for i in range(p.k):
        chunk = sig_fors[..., i * per : (i + 1) * per]
        sk = chunk[..., : p.n]
        idx = indices[..., i]
        gidx = (i << p.a) + idx
        leaf_adrs = _adrs(ctx.batch, 0, tree8, FORS_TREE, idx_leaf, 0, gidx)
        node = ctx.f(leaf_adrs, sk)
        for j in range(p.a):
            sib = chunk[..., (1 + j) * p.n : (2 + j) * p.n]
            bit = (gidx >> j) & 1
            adrs = _adrs(ctx.batch, 0, tree8, FORS_TREE, idx_leaf, j + 1, gidx >> (j + 1))
            pair = jnp.where(
                bit[..., None],
                jnp.concatenate([sib, node], axis=-1),
                jnp.concatenate([node, sib], axis=-1),
            )
            node = ctx.t(adrs, pair)
        roots.append(node)
    pk_adrs = _adrs(ctx.batch, 0, tree8, FORS_ROOTS, idx_leaf, 0, 0)
    return ctx.t(pk_adrs, jnp.concatenate(roots, axis=-1))


# --------------------------------------------------------------------------
# SLH-DSA top level (device cores take the fixed-size H_msg digest)
# --------------------------------------------------------------------------


def keygen(p: SLHDSAParams, sk_seed: jax.Array, sk_prf: jax.Array, pk_seed: jax.Array):
    """Three (..., n) seeds -> (pk (..., 2n), sk (..., 4n))."""
    sk_seed = jnp.asarray(sk_seed, jnp.uint8)
    sk_prf = jnp.asarray(sk_prf, jnp.uint8)
    pk_seed = jnp.asarray(pk_seed, jnp.uint8)
    ctx = _Ctx(p, pk_seed)
    tree8 = jnp.zeros(ctx.batch + (8,), jnp.uint8)
    levels = _xmss_levels(ctx, sk_seed, p.d - 1, tree8)
    pk_root = levels[p.hp][..., 0, :]
    pk = jnp.concatenate([pk_seed, pk_root], axis=-1)
    return pk, jnp.concatenate([sk_seed, sk_prf, pk], axis=-1)


def sign_digest(p: SLHDSAParams, sk: jax.Array, r: jax.Array, digest: jax.Array):
    """sk (B, 4n), r (B, n) randomizer, digest (B, m) = H_msg -> sig (B, sig_len)."""
    sk = jnp.asarray(sk, jnp.uint8)
    r = jnp.asarray(r, jnp.uint8)
    digest = jnp.asarray(digest, jnp.uint8)
    sk_seed, pk_seed = sk[..., : p.n], sk[..., 2 * p.n : 3 * p.n]
    ctx = _Ctx(p, pk_seed)
    md, tree_bits, idx_leaf = _digest_split(p, digest)
    tree8 = _tree8_at(p, tree_bits, 0)
    sig_fors, _, _ = _fors_sign(ctx, md, sk_seed, tree8, idx_leaf)
    pk_fors = _fors_pk_from_sig(ctx, sig_fors, md, tree8, idx_leaf)
    parts = [r, sig_fors]
    msg = pk_fors
    leaf = idx_leaf
    for j in range(p.d):
        t8 = _tree8_at(p, tree_bits, j)
        sig_x, root = _xmss_sign(ctx, msg, sk_seed, leaf, j, t8)
        parts.append(sig_x)
        msg = root
        if j + 1 < p.d:
            leaf = _leaf_at(p, tree_bits, j + 1)
    return jnp.concatenate(parts, axis=-1)


@functools.cache
def _layered_fns(p: SLHDSAParams):
    """Jitted (fors_part, xmss_layer) pair for the layered sign path."""

    @jax.jit
    def fors_part(sk_seed, pk_seed, digest):
        ctx = _Ctx(p, pk_seed)
        md, tree_bits, idx_leaf = _digest_split(p, digest)
        t8_0 = _tree8_at(p, tree_bits, 0)
        sig_fors, _, _ = _fors_sign(ctx, md, sk_seed, t8_0, idx_leaf)
        pk_fors = _fors_pk_from_sig(ctx, sig_fors, md, t8_0, idx_leaf)
        t8s = jnp.stack([t8_0] + [_tree8_at(p, tree_bits, j) for j in range(1, p.d)])
        leaves = jnp.stack(
            [idx_leaf] + [_leaf_at(p, tree_bits, j) for j in range(1, p.d)]
        )
        return sig_fors, pk_fors, t8s, leaves

    @jax.jit
    def xmss_layer(sk_seed, pk_seed, msg, leaf, layer, t8):
        ctx = _Ctx(p, pk_seed)
        return _xmss_sign(ctx, msg, sk_seed, leaf, layer, t8)

    return fors_part, xmss_layer


def sign_digest_layered(p: SLHDSAParams, sk: jax.Array, r: jax.Array,
                        digest: jax.Array):
    """``sign_digest`` as 1 FORS dispatch + d per-layer XMSS dispatches.

    Bit-identical output.  The XMSS-layer program takes the hypertree layer
    index, ADRS tree field, and leaf index as traced operands, so it is
    traced and compiled ONCE and reused for all d layers — the XLA graph is
    ~d× smaller than the monolithic sign.  Measured effect (bench_report.md
    config 4): 256s sign, whose monolithic graph never compiled at ANY
    batch in this environment, runs at batch 32; 128s compiles at 512 vs
    the monolithic 128.  Remote-compile-helper 500s at larger batches are
    often transient (retry once before trusting a ceiling).
    """
    sk = jnp.asarray(sk, jnp.uint8)
    r = jnp.asarray(r, jnp.uint8)
    digest = jnp.asarray(digest, jnp.uint8)
    fors_part, xmss_layer = _layered_fns(p)
    sk_seed, pk_seed = sk[..., : p.n], sk[..., 2 * p.n : 3 * p.n]
    sig_fors, msg, t8s, leaves = fors_part(sk_seed, pk_seed, digest)
    parts = [r, sig_fors]
    for j in range(p.d):
        sig_x, msg = xmss_layer(sk_seed, pk_seed, msg, leaves[j],
                                jnp.int32(j), t8s[j])
        parts.append(sig_x)
    return jnp.concatenate(parts, axis=-1)


def verify_digest(p: SLHDSAParams, pk: jax.Array, digest: jax.Array, sig: jax.Array):
    """pk (B, 2n), digest (B, m), sig (B, sig_len) -> bool (B,)."""
    pk = jnp.asarray(pk, jnp.uint8)
    digest = jnp.asarray(digest, jnp.uint8)
    sig = jnp.asarray(sig, jnp.uint8)
    pk_seed, pk_root = pk[..., : p.n], pk[..., p.n :]
    ctx = _Ctx(p, pk_seed)
    md, tree_bits, idx_leaf = _digest_split(p, digest)
    fors_len = p.k * (1 + p.a) * p.n
    sig_fors = sig[..., p.n : p.n + fors_len]
    sig_ht = sig[..., p.n + fors_len :]
    tree8 = _tree8_at(p, tree_bits, 0)
    node = _fors_pk_from_sig(ctx, sig_fors, md, tree8, idx_leaf)
    per = (p.wots_len + p.hp) * p.n
    leaf = idx_leaf
    for j in range(p.d):
        t8 = _tree8_at(p, tree_bits, j)
        chunk = sig_ht[..., j * per : (j + 1) * per]
        node = _xmss_pk_from_sig(ctx, leaf, chunk, node, j, t8)
        if j + 1 < p.d:
            leaf = _leaf_at(p, tree_bits, j + 1)
    return jnp.all(node == pk_root, axis=-1)


def _use_layered_sign(p: SLHDSAParams) -> bool:
    """Layered sign for the s-sets by default (256s's monolithic graph never
    compiled at any batch in this environment; 128s capped at 128);
    QRP2P_SPHINCS_LAYERED=1/0 forces either path (trace-time flag: fresh
    process per setting, same caveat as QRP2P_PALLAS)."""
    flag = os.environ.get("QRP2P_SPHINCS_LAYERED", "auto")
    if flag in ("0", "1"):
        return flag == "1"
    return p.hp >= 8


@functools.cache
def get(name: str):
    """(keygen, sign_digest, verify_digest) callables for a parameter set.

    keygen/verify are jitted; sign is jitted for the f-sets but is the
    layered multi-dispatch driver (``sign_digest_layered``, not a jit
    object) for the s-sets — see ``_use_layered_sign``.
    """
    p = PARAMS[name]
    sign = (
        functools.partial(sign_digest_layered, p)
        if _use_layered_sign(p)
        else jax.jit(functools.partial(sign_digest, p))
    )
    return (
        jax.jit(functools.partial(keygen, p)),
        sign,
        jax.jit(functools.partial(verify_digest, p)),
    )
