"""Batched TPU signature implementations (ML-DSA, SPHINCS+)."""
