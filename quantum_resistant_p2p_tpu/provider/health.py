"""Device-health gate: validate the accelerated path per environment.

Hardware-accelerator PQC evaluations (PQC-HA, arXiv:2308.06621) stress that
the correctness of an accelerated implementation must be RE-VALIDATED in
every environment before it is trusted — a new device kind, XLA release, or
JAX version can silently change numerics (the HQC f32-FFT cyclic product is
the documented in-repo example, kem/hqc.py).  This module runs fast
on-device self-checks at provider startup:

* **HQC** — the FFT-vs-Toeplitz cyclic-product exactness probe
  (``kem.hqc._fft_selfcheck``, the same check ``tools/check_pallas_device``
  runs manually); an unvalidated environment routes HQC to the exact
  Toeplitz-MXU path and logs why.
* **ML-KEM** — a pinned known-answer vector: deterministic
  ``keygen(d, z)`` / ``encaps(ek, m)`` digests computed from the pure-Python
  FIPS 203 reference (pyref/mlkem_ref.py), checked against the device path.
* **every other family** — a deterministic roundtrip on the device provider
  plus CROSS-IMPLEMENTATION agreement with its cpu twin (device-encapsulated
  secrets must decapsulate identically on the independent cpu backend;
  device signatures must verify on the cpu backend and a tampered signature
  must not).

Verdicts are keyed by an environment fingerprint (device kind, platform,
jax/jaxlib versions) and cached on disk (the native-build cache dir), so the
cost is once per environment, not per process.  Only POSITIVE verdicts are
trusted from the cache — this platform's device faults are documented
transient, so a failed probe re-runs at next startup (self-healing) instead
of pinning the slow path forever.

On failure the gate acts, loudly: HQC is re-routed to the Toeplitz path, and
a batched facade whose device provider fails is QUARANTINED — its shared
breaker pins the cpu fallback for the process lifetime, because a device
that computes wrong answers cannot be probed back to health by a latency
canary.  ``QRP2P_HEALTH_GATE=0`` skips the gate entirely (trust the device).
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac
import json
import logging
import os
import pathlib
from typing import Any

from ..native import wipe

logger = logging.getLogger(__name__)

#: bump to invalidate cached verdicts when the probe suite changes
_PROBE_VERSION = 1

#: pinned ML-KEM-768 KAT (seeds -> digests), computed from pyref/mlkem_ref
#: (ML_KEM.KeyGen_internal / Encaps_internal with d=00..1f, z=20..3f,
#: m=40..5f); the device path must reproduce these byte-for-byte
_MLKEM768_KAT = {
    "d": bytes(range(32)),
    "z": bytes(range(32, 64)),
    "m": bytes(range(64, 96)),
    "ek_sha256": "0b7934c83125c788995e2ba6bd761e33046b3e40571be53e023309a29f398cc9",
    "ct_sha256": "dbf4e9aa48b078ad46ec1c9c47bda8c2d2fec9d0e7a21bd48d2238a2abedb856",
    "ss_hex": "9cddd089ffe70e3996e76f7c8d06746df34d07e8657bc0fcf2bb0e1c3084aea1",
}

#: pinned FrodoKEM-640-SHAKE KAT, computed from pyref/frodo_ref (keygen
#: seeds s=00..0f, seedSE=10..1f, z=20..2f; encaps mu=30..3f); the Pallas
#: matmul + inline-SHAKE device path must reproduce these byte-for-byte
_FRODO640SHAKE_KAT = {
    "s": bytes(range(16)),
    "seed_se": bytes(range(16, 32)),
    "z": bytes(range(32, 48)),
    "mu": bytes(range(48, 64)),
    "pk_sha256": "e1933f44de4f6410af9155c4baa3b7454c6e93ec7701971daee3c7d2be3e03f3",
    "ct_sha256": "eefd2976cb8656e208526b33babf14eccd8f9a123db06e6032a30c449c1fc211",
    "ss_hex": "c2cb61ee5b4f5f6679259f09fc6b253b",
}


@dataclasses.dataclass
class HealthVerdict:
    family: str
    ok: bool
    detail: str
    cached: bool = False
    #: False = never write this verdict to the disk cache (e.g. the HQC gate
    #: manages its own marker with its own re-probe policy)
    cacheable: bool = True

    def as_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def env_fingerprint() -> str:
    """(device kind, platform, jax version, jaxlib version) — the axes along
    which accelerated numerics can silently change."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    return (
        f"jax={jax.__version__}|jaxlib={jaxlib.__version__}"
        f"|platform={dev.platform}|dev={kind}|probe={_PROBE_VERSION}"
    )


def _cache_dir() -> pathlib.Path:
    override = os.environ.get("QRP2P_HEALTH_CACHE")
    if override:
        return pathlib.Path(override)
    from ..native import _CACHE_DIR

    return pathlib.Path(_CACHE_DIR)


def _marker(family: str, fingerprint: str) -> pathlib.Path:
    digest = hashlib.sha256(f"{family}|{fingerprint}".encode()).hexdigest()[:16]
    return _cache_dir() / f"health_{digest}.json"


def _read_cached(family: str, fingerprint: str) -> HealthVerdict | None:
    """Positive cached verdict for (family, environment), else None."""
    try:
        rec = json.loads(_marker(family, fingerprint).read_text())
        if (isinstance(rec, dict) and rec.get("key") == fingerprint
                and rec.get("family") == family and rec.get("ok")):
            return HealthVerdict(family, True, rec.get("detail", "cached"),
                                 cached=True)
    except (OSError, ValueError, KeyError):
        pass
    return None


def _write_cached(family: str, fingerprint: str, verdict: HealthVerdict) -> None:
    if not verdict.ok or not verdict.cacheable:
        return  # negative verdicts re-probe every startup (self-healing)
    try:
        d = _cache_dir()
        d.mkdir(parents=True, exist_ok=True)
        _marker(family, fingerprint).write_text(json.dumps(
            {"family": family, "key": fingerprint, "ok": True,
             "detail": verdict.detail}
        ))
    except OSError:
        pass


# -- family probes ------------------------------------------------------------


def _check_hqc(algo) -> HealthVerdict:
    """FFT-vs-Toeplitz cyclic-product exactness on-device (the check
    ``tools/check_pallas_device.py`` runs manually).  An unvalidated
    environment is HEALED, not quarantined: ``kem.hqc`` re-routes every HQC
    op to the exact Toeplitz-MXU product for this process and logs why —
    so the verdict is ok either way, with the routing in the detail.

    Never cached here: kem.hqc keeps its own per-environment marker with
    the matching policy (positives cached, failures re-probed per process).
    """
    from ..kem import hqc

    hqc._maybe_gate_fft()  # runs (or recalls) the probe; forces Toeplitz on failure
    if hqc._FORCED_IMPL is not None:
        detail = (f"fft self-check failed; HQC re-routed to the exact "
                  f"{hqc._FORCED_IMPL} cyclic product for this process")
        logger.warning("device health %s: %s", algo.name, detail)
    else:
        detail = f"cyclic product impl {hqc._cyclic_impl()!r} validated on-device"
    return HealthVerdict(algo.name, True, detail, cacheable=False)


def _check_mlkem_kat(algo) -> HealthVerdict:
    """Pinned FIPS 203 vector through the device (jax) path, batch-1."""
    import numpy as np

    from ..kem import mlkem

    kat = _MLKEM768_KAT
    kg, enc, dec = mlkem.get("ML-KEM-768")
    d = np.frombuffer(kat["d"], np.uint8)[None]
    z = np.frombuffer(kat["z"], np.uint8)[None]
    m = np.frombuffer(kat["m"], np.uint8)[None]
    ek, dk = kg(d, z)
    ek_b = bytes(np.asarray(ek[0], np.uint8))
    if hashlib.sha256(ek_b).hexdigest() != kat["ek_sha256"]:
        return HealthVerdict(algo.name, False, "keygen KAT mismatch (ek)")
    ss, ct = enc(ek, m)
    ct_b = bytes(np.asarray(ct[0], np.uint8))
    ss_b = bytes(np.asarray(ss[0], np.uint8))
    if hashlib.sha256(ct_b).hexdigest() != kat["ct_sha256"]:
        return HealthVerdict(algo.name, False, "encaps KAT mismatch (ct)")
    if ss_b.hex() != kat["ss_hex"]:
        return HealthVerdict(algo.name, False, "encaps KAT mismatch (ss)")
    ss2 = dec(dk, ct)
    if bytes(np.asarray(ss2[0], np.uint8)) != ss_b:
        return HealthVerdict(algo.name, False, "decaps KAT mismatch")
    return HealthVerdict(algo.name, True, "FIPS 203 KAT ok (keygen/encaps/decaps)")


def _check_frodo_kat(algo) -> HealthVerdict:
    """Pinned FrodoKEM-640-SHAKE vector through the device (jax) path, batch-1.

    The SHAKE parameter sets share the Pallas matmul + inline-SHAKE kernels
    (kem/frodo_pallas.py), so one pinned set certifies the whole family's
    tile math on this environment; the AES sets still go through the
    generic roundtrip probe.
    """
    import numpy as np

    from ..kem import frodo

    kat = _FRODO640SHAKE_KAT
    kg, enc, dec = frodo.get("FrodoKEM-640-SHAKE")
    s = np.frombuffer(kat["s"], np.uint8)[None]
    se = np.frombuffer(kat["seed_se"], np.uint8)[None]
    z = np.frombuffer(kat["z"], np.uint8)[None]
    mu = np.frombuffer(kat["mu"], np.uint8)[None]
    pk, sk = kg(s, se, z)
    pk_b = bytes(np.asarray(pk[0], np.uint8))
    if hashlib.sha256(pk_b).hexdigest() != kat["pk_sha256"]:
        return HealthVerdict(algo.name, False, "keygen KAT mismatch (pk)")
    ct, ss = enc(pk, mu)
    ct_b = bytes(np.asarray(ct[0], np.uint8))
    ss_b = bytes(np.asarray(ss[0], np.uint8))
    if hashlib.sha256(ct_b).hexdigest() != kat["ct_sha256"]:
        return HealthVerdict(algo.name, False, "encaps KAT mismatch (ct)")
    if ss_b.hex() != kat["ss_hex"]:
        return HealthVerdict(algo.name, False, "encaps KAT mismatch (ss)")
    ss2 = dec(sk, ct)
    if bytes(np.asarray(ss2[0], np.uint8)) != ss_b:
        return HealthVerdict(algo.name, False, "decaps KAT mismatch")
    return HealthVerdict(algo.name, True,
                         "FrodoKEM KAT ok (keygen/encaps/decaps, pyref-pinned)")


def _check_kem_roundtrip(algo, cpu_twin) -> HealthVerdict:
    """Device roundtrip + cross-implementation agreement with the cpu twin."""
    pk, sk = algo.generate_keypair()
    ss = b""
    try:
        ct, ss = algo.encapsulate(pk)
        if not hmac.compare_digest(algo.decapsulate(sk, ct), ss):
            return HealthVerdict(algo.name, False,
                                 "device decaps != device encaps")
        if cpu_twin is not None and not hmac.compare_digest(
                cpu_twin.decapsulate(sk, ct), ss):
            return HealthVerdict(
                algo.name, False,
                "cpu reference decaps disagrees with device encaps",
            )
        agree = " + cpu agreement" if cpu_twin is not None else ""
        return HealthVerdict(algo.name, True, f"device roundtrip ok{agree}")
    finally:
        wipe(sk, ss)  # probe-only key material


def _check_sig_roundtrip(algo, cpu_twin) -> HealthVerdict:
    """Device sign/verify + cross-implementation verify + tamper rejection."""
    msg = b"qrp2p device-health probe"
    pk, sk = algo.generate_keypair()
    try:
        sig = algo.sign(sk, msg)
        if not algo.verify(pk, msg, sig):
            return HealthVerdict(algo.name, False,
                                 "device verify rejects device sign")
        if cpu_twin is not None and not cpu_twin.verify(pk, msg, sig):
            return HealthVerdict(
                algo.name, False,
                "cpu reference verify rejects device signature",
            )
        bad = bytes([sig[0] ^ 0xFF]) + sig[1:]
        if algo.verify(pk, msg, bad):
            return HealthVerdict(algo.name, False,
                                 "device verify accepts tampered sig")
        agree = " + cpu agreement" if cpu_twin is not None else ""
        return HealthVerdict(algo.name, True, f"device sign/verify ok{agree}")
    finally:
        wipe(sk)  # probe-only key material


def _check_fused(facade) -> HealthVerdict:
    """Validate the composite fused-handshake path (provider/batched.py
    ``BatchedFused``): the fused programs are a SEPARATE device code path
    from the per-op families (device-side hex render into transcript
    templates + fused sign), so both can pass while these kernels are
    broken.  Probe: one batch-1 ``keygen_sign`` at the facade's LIVE
    offsets; the rendered-template signature must verify on the cpu twin
    and the generated KEM keypair must roundtrip through the cpu twin —
    covering the shared render/sign machinery the other two composite ops
    reuse."""
    import numpy as np

    fused = facade.fused
    name = f"fused:{fused.name}"
    cpu_kem, cpu_sig = facade.fallback_kem, facade.fallback_sig
    if cpu_kem is None or cpu_sig is None:
        return HealthVerdict(name, True, "no cpu twins armed; skipped")
    sig_pk, sig_sk = cpu_sig.generate_keypair()
    ss = b""
    try:
        tmpl_len = min(fused.init_template_len,
                       facade.pk_off + 2 * fused.kem.public_key_len + 2)
        tmpl = b"{" + b"0" * (tmpl_len - 2) + b"}"
        pks, ksks, sigs = fused.keygen_sign_batch(
            np.frombuffer(sig_sk, np.uint8)[None], [tmpl], facade.pk_off
        )
        pk, ksk = (bytes(np.asarray(pks[0], np.uint8)),
                   bytes(np.asarray(ksks[0], np.uint8)))
        rendered = (tmpl[: facade.pk_off] + pk.hex().encode()
                    + tmpl[facade.pk_off + 2 * len(pk):])
        if not cpu_sig.verify(sig_pk, rendered, sigs[0]):
            return HealthVerdict(
                name, False,
                "cpu reference rejects the fused keygen_sign signature "
                "(device-side render/sign numerics)",
            )
        ct, ss = cpu_kem.encapsulate(pk)
        if not hmac.compare_digest(cpu_kem.decapsulate(ksk, ct), ss):
            return HealthVerdict(
                name, False, "fused keygen keypair fails the cpu KEM roundtrip",
            )
        return HealthVerdict(name, True,
                             "fused keygen_sign render/sign/keypair ok vs cpu")
    finally:
        wipe(sig_sk, ss)  # probe-only key material


#: pinned RFC 8439 §2.8.2 AEAD vector: the device seal must reproduce the
#: spec ciphertext+tag byte-for-byte before the batched data plane is
#: trusted with live traffic
_CHACHA_KAT = {
    "key": bytes(range(0x80, 0xA0)),
    "nonce": bytes([0x07, 0, 0, 0]) + bytes(range(0x40, 0x48)),
    "aad": bytes.fromhex("50515253c0c1c2c3c4c5c6c7"),
    "pt": (b"Ladies and Gentlemen of the class of '99: If I could offer "
           b"you only one tip for the future, sunscreen would be it."),
    "ct_tag_sha256":
        "4e54427e462f3beb69677d39865c5da8d57f603a85f7bf71368dce8ec9b9933c",
}


def _check_aead(facade) -> HealthVerdict:
    """Validate a batched AEAD facade's device path: the pinned RFC 8439
    §2.8.2 vector through the device seal, tamper rejection on open, and
    cross-implementation agreement with the scalar twin (device-sealed
    frames must open on the independent scalar path and vice versa)."""
    import numpy as np

    name = f"aead:{facade.name}"
    kat = _CHACHA_KAT
    dev, scalar = facade.device, facade.scalar
    keys = np.frombuffer(kat["key"], np.uint8)[None]
    nonces = np.frombuffer(kat["nonce"], np.uint8)[None]
    sealed = dev.seal_batch(keys, nonces, [kat["pt"]], [kat["aad"]])[0]
    if hashlib.sha256(sealed).hexdigest() != kat["ct_tag_sha256"]:
        return HealthVerdict(name, False, "RFC 8439 §2.8.2 KAT mismatch")
    got = dev.open_batch(keys, nonces, [sealed], [kat["aad"]])[0]
    if not isinstance(got, bytes) or got != kat["pt"]:
        return HealthVerdict(name, False, "device open rejects device seal")
    bad = bytes([sealed[0] ^ 0xFF]) + sealed[1:]
    if not isinstance(dev.open_batch(keys, nonces, [bad],
                                     [kat["aad"]])[0], ValueError):
        return HealthVerdict(name, False,
                             "device open accepts tampered ciphertext")
    if scalar is not None:
        if scalar.open_(kat["key"], kat["nonce"], sealed,
                        kat["aad"]) != kat["pt"]:
            return HealthVerdict(
                name, False, "scalar twin rejects device seal")
    agree = " + scalar agreement" if scalar is not None else ""
    return HealthVerdict(name, True, f"RFC 8439 KAT + tamper-reject ok{agree}")


def _probe(algo, cpu_twin) -> HealthVerdict:
    name = getattr(algo, "name", type(algo).__name__)
    if name.startswith("HQC"):
        return _check_hqc(algo)
    from .base import KeyExchangeAlgorithm, SignatureAlgorithm

    if name == "ML-KEM-768":
        # the pinned vector covers keygen/encaps/decaps end to end; the
        # generic roundtrip would add nothing
        return _check_mlkem_kat(algo)
    if name.startswith("FrodoKEM") and name.endswith("SHAKE"):
        # certifies the shared Pallas matmul + inline-SHAKE kernel family
        return _check_frodo_kat(algo)
    if isinstance(algo, KeyExchangeAlgorithm):
        return _check_kem_roundtrip(algo, cpu_twin)
    if isinstance(algo, SignatureAlgorithm):
        return _check_sig_roundtrip(algo, cpu_twin)
    return HealthVerdict(name, True, "no probe registered; skipped")


# -- public API ---------------------------------------------------------------


def gate_enabled() -> bool:
    return os.environ.get("QRP2P_HEALTH_GATE", "1") != "0"


def ensure_validated(algo, cpu_twin=None) -> HealthVerdict:
    """Run (or recall) the health probe for one provider's family.

    Positive verdicts are cached on disk keyed by the environment
    fingerprint; negatives are returned but never cached.  Probe crashes
    count as failures — an accelerator that cannot run the probe cannot be
    trusted with live traffic either.
    """
    family = getattr(algo, "name", type(algo).__name__)
    if getattr(algo, "backend", "cpu") != "tpu":
        return HealthVerdict(family, True, "cpu backend; no device to gate")
    fingerprint = env_fingerprint()
    cached = _read_cached(family, fingerprint)
    if cached is not None:
        return cached
    try:
        verdict = _probe(algo, cpu_twin)
    except Exception as e:
        logger.exception("device-health probe for %s crashed", family)
        verdict = HealthVerdict(family, False, f"probe crashed: {e!r}")
    _write_cached(family, fingerprint, verdict)
    return verdict


def gate_facades(*facades) -> list[HealthVerdict]:
    """Validate each batched facade's device provider at startup; quarantine
    the shared breaker on failure (only when a cpu fallback is armed — with
    no fallback there is nothing safer to route to, so only log).

    Accepts ``provider.batched.BatchedKEM`` / ``BatchedSignature`` /
    ``BatchedFused`` facades (None entries are skipped) and returns the
    verdicts.
    """
    out: list[HealthVerdict] = []
    if not gate_enabled():
        return out
    for facade in facades:
        if facade is None:
            continue
        if hasattr(facade, "fused"):
            verdict = _ensure_fused_validated(facade)
        elif hasattr(facade, "device"):  # BatchedAEAD (data plane)
            verdict = _ensure_aead_validated(facade)
        else:
            verdict = ensure_validated(facade.algo,
                                       getattr(facade, "fallback", None))
        out.append(verdict)
        from ..obs import flight as _flight

        if verdict.ok:
            _flight.record("health_ok", family=verdict.family,
                           detail=verdict.detail, cached=verdict.cached)
            logger.info("device health %s: ok (%s)%s", verdict.family,
                        verdict.detail, " [cached]" if verdict.cached else "")
            continue
        logger.error(
            "device health %s: FAILED (%s) in environment %s",
            verdict.family, verdict.detail, env_fingerprint(),
        )
        # the quarantine below emits the breaker_quarantined trigger; this
        # event records the verdict itself (also for the no-fallback case)
        _flight.record("health_failed", family=verdict.family,
                       detail=verdict.detail, env=env_fingerprint())
        have_fb = (getattr(facade, "fallback", None) is not None
                   or getattr(facade, "fallback_kem", None) is not None)
        if have_fb:
            why = (f"{verdict.family} failed the device-health gate: "
                   f"{verdict.detail}")
            sched = getattr(facade, "scheduler", None)
            if sched is not None:
                # the verdict is about the device PROGRAMS, which every
                # shard runs: quarantine the whole placement axis, not
                # just the shard-0 compat breaker
                sched.quarantine_all(why)
            else:
                facade.breaker.quarantine(why)
    return out


def _ensure_aead_validated(facade) -> HealthVerdict:
    """Cached wrapper around :func:`_check_aead` (same verdict policy as
    ensure_validated: positives cached per environment, failures
    re-probed)."""
    family = f"aead:{facade.name}"
    fingerprint = env_fingerprint()
    cached = _read_cached(family, fingerprint)
    if cached is not None:
        return cached
    try:
        verdict = _check_aead(facade)
    except Exception as e:
        logger.exception("device-health probe for %s crashed", family)
        verdict = HealthVerdict(family, False, f"probe crashed: {e!r}")
    verdict.family = family
    _write_cached(family, fingerprint, verdict)
    return verdict


def _ensure_fused_validated(facade) -> HealthVerdict:
    """Cached wrapper around :func:`_check_fused` (same verdict policy as
    ensure_validated; the cache key carries the live transcript offsets —
    jit keys on them, so a different protocol layout re-probes)."""
    family = f"fused:{facade.fused.name}@{facade.pk_off}"
    fingerprint = env_fingerprint()
    cached = _read_cached(family, fingerprint)
    if cached is not None:
        return cached
    try:
        verdict = _check_fused(facade)
    except Exception as e:
        logger.exception("device-health probe for %s crashed", family)
        verdict = HealthVerdict(family, False, f"probe crashed: {e!r}")
    verdict.family = family
    _write_cached(family, fingerprint, verdict)
    return verdict
