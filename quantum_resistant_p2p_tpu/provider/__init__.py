"""The algorithm-plugin boundary + batching queue.

This package replicates the pluggable-algorithm API surface of the
reference's crypto/ package (KeyExchangeAlgorithm crypto/key_exchange.py:19-54,
SignatureAlgorithm crypto/signatures.py:18-55, SymmetricAlgorithm
crypto/symmetric.py:19-63) and adds what the reference could not have:

* a **backend** axis (``cpu`` pure-Python reference vs ``tpu`` batched JAX),
* an explicit **algorithm registry** replacing the reference's string
  matching (app/messaging.py:1893-2011),
* an async **batching queue** (``BatchedProvider``) that coalesces many
  concurrent handshake ops into single TPU dispatches.
"""

from .base import (
    BatchedAEADOps,
    CryptoAlgorithm,
    FusedHandshakeOps,
    KeyExchangeAlgorithm,
    SignatureAlgorithm,
    SymmetricAlgorithm,
)
from .registry import (
    get_batched_aead,
    get_fused,
    get_kem,
    get_signature,
    get_symmetric,
    list_batched_aeads,
    list_fused,
    list_kems,
    list_signatures,
    list_symmetrics,
)

__all__ = [
    "BatchedAEADOps",
    "CryptoAlgorithm",
    "FusedHandshakeOps",
    "KeyExchangeAlgorithm",
    "SignatureAlgorithm",
    "SymmetricAlgorithm",
    "get_batched_aead",
    "get_fused",
    "get_kem",
    "get_signature",
    "get_symmetric",
    "list_batched_aeads",
    "list_fused",
    "list_kems",
    "list_signatures",
    "list_symmetrics",
]
