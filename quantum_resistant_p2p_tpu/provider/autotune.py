"""Adaptive batch-size / flush-window autotuner — the serving-loop brain.

The batching queues (provider/batched.py) have two knobs that decide the
throughput/latency trade under load: WHEN a flush fires (``max_wait_ms``,
the timer window) and HOW BIG a flush tries to be (the pow2 bucket the
batch pads to).  Until this module both were static — ``max_wait_ms`` a
constructor constant and the bucket space pinned by the hard-coded
``WARMUP_SIZES=(1, 2, 4)`` prior in app/messaging.py.  That is the wrong
shape for sustained traffic: the OpenACC LWE-KEM measurements (PAPERS.md
#4) show throughput is a strong function of batch size, so the right
bucket depends on the OFFERED LOAD and must be tuned from live
measurements, not constants.

The tuner consumes the metrics the queues already keep (obs/metrics.py —
``QueueStats``: op/flush counters, the per-flush dispatch-latency
percentile histogram, fallback/breaker activity) and derives, per queue:

* ``bucket``   — the demand-following right-size: the pow2 that just
  covers the observed average flush (jumping up in one step, shrinking
  one pow2 per step).  While the host keeps up, a wave reaching 2x the
  bucket flushes immediately instead of waiting out the window's tail.
* ``window_s`` — the timer backstop, a two-regime rule: ~2x the
  ON-WORKER device-program p50 while the host keeps up (cheap warm
  dispatches flush near-immediately), opened to the cap when the gap
  between loop-observed and on-worker latency says the host itself is
  saturated (bigger batches are then the only lever).

Degraded traffic (breaker open / half-open, fallback flushes observed
since the last step) snaps both knobs down: canary probes must measure
the device promptly, and big padded batches are wasted work on the cpu
fallback — so under breaker-probe traffic the tuner runs SMALL buckets
and SHORT windows until the plane heals.

Correctness contract: the tuner changes only WHEN a flush fires and how
many items it carries.  Padding/bucketing semantics are untouched
(``_run_valid`` pads to ``max(floor, next_pow2(n))`` exactly as before),
so every dispatch stays bit-exact vs. the static configuration; a bucket
the static prior never compiled is absorbed by the existing cold-bucket
machinery (served from the cpu fallback while the background warmup
compiles it — never hostage to a compile).  With ``QRP2P_AUTOTUNE=0`` (or
``autotune=False`` on the engine) no tuner is attached and the hot path
reads the static constants — bit-for-bit today's behavior, pinned by
tests/test_gateway.py.

Thread-safety: decisions are made on the event loop (stepping piggybacks
on flush completion), but the state is READ cross-thread — registry gauge
callbacks run on whatever thread snapshots/scrapes (CLI, Prometheus
exporter, the flight recorder's dump thread).  Every mutation and read of
tuner state is therefore lock-guarded (qrflow's cross-thread-state pack
maps gauge ``set_fn`` callbacks as executor-domain edges).
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Any, Callable

from ..obs import flight as obs_flight
from .base import next_pow2 as _next_pow2

logger = logging.getLogger(__name__)

AUTOTUNE_ENV = "QRP2P_AUTOTUNE"


def autotune_enabled_default() -> bool:
    """The env default: ``QRP2P_AUTOTUNE=0`` disables, anything else (or
    unset) enables.  Engines may override per instance (``autotune=``)."""
    return os.environ.get(AUTOTUNE_ENV, "1") != "0"


@dataclass(frozen=True)
class TunerConfig:
    """Bounds and cadence for the decision loop.  All decisions derive
    from queue counters + the injected clock, so a synthetic trace with a
    synthetic clock reproduces the exact decision sequence (tests)."""

    #: flush-window clamp (seconds)
    min_window_s: float = 0.0005
    max_window_s: float = 0.020
    #: largest flush-at bucket the tuner may choose
    max_bucket: int = 4096
    #: dispatch p99 budget: a bucket whose flushes exceed this steps down
    latency_budget_s: float = 0.050
    #: decision cadence: at most one step per interval, and only with
    #: at least this many flushes of fresh evidence
    step_interval_s: float = 0.25
    min_flushes_per_step: int = 4


def decide(cur_bucket: int, floor: int, avg_batch: float,
           p50_device_s: float | None, p50_dispatch_s: float | None,
           degraded: bool, cfg: TunerConfig) -> tuple[int, float, bool]:
    """Pure decision function: -> (bucket, window_s, saturated).

    Separated from the stateful stepper so the policy is unit-testable as
    a function of its inputs (tests/test_gateway.py drives it with a
    synthetic offered-load trace and asserts convergence).

    * **window** — the two-regime rule the storm measurements forced:

      - *keeping up* (loop-observed dispatch latency ~= on-worker program
        time, ``p50_dispatch_s ~= p50_device_s``): track the AMORTIZATION
        BOUND, ~2x the typical device-program time, floored at
        ``min_window_s``.  Cheap warm dispatches flush near-immediately —
        LOWER added latency than any static constant — while expensive
        device programs earn wide windows and real coalescing.
      - *saturated* (loop-observed latency well above on-worker time: the
        dispatch path is QUEUEING; the host, not the device, is the
        bottleneck): open the window to the cap.  Per-flush overhead is
        what is drowning the host, and bigger batches are the only lever
        that reduces it — small "responsive" windows here shatter the
        work into more overhead (the measured 1000-session regression
        that shaped this rule).

    * **bucket** is the DEMAND-FOLLOWING right-size: the pow2 that just
      covers the observed average flush.  It JUMPS up to demand in one
      step (a climb-one-pow2-per-step transient sits below live demand
      and shatters coalesced batches into undersized flushes) and shrinks
      at most one pow2 per step (hysteresis).  While KEEPING UP, the hot
      path flushes early at 2x the bucket — clear evidence of a fuller-
      than-usual wave, dispatched without waiting out the window's tail.
      While SATURATED the early trigger disengages entirely: the measured
      1000-session timeline showed it shearing backlog-grown waves in
      half (avg batch pinned at trigger/2), and under saturation bigger
      batches are the only lever — flushes then fire on the (late,
      elastic) timer alone.  The trigger is never a cap either way; a
      burst still flushes whole.
    * **degraded** (breaker open / half-open, fallback flushes observed)
      snaps both to the floor: canary probes must sample the device
      promptly and fallback batches amortise nothing.
    """
    floor = max(1, _next_pow2(floor))
    if degraded:
        return floor, cfg.min_window_s, False
    dev = p50_device_s if p50_device_s is not None else 0.0
    disp = p50_dispatch_s if p50_dispatch_s is not None else dev
    queueing = max(0.0, disp - dev)
    saturated = queueing > 2.0 * max(dev, cfg.min_window_s)
    if saturated:
        window = min(cfg.max_window_s, cfg.latency_budget_s)
    else:
        window = min(max(2.0 * dev, cfg.min_window_s), cfg.max_window_s,
                     cfg.latency_budget_s)
    target = _next_pow2(max(1, int(avg_batch + 0.5)))
    if target < cur_bucket:
        # shrink hysteresis: one pow2 per step
        target = max(target, cur_bucket // 2)
    bucket = min(max(target, floor), cfg.max_bucket)
    return bucket, window, saturated


class QueueTuner:
    """Per-queue adaptive state: the hot-path reads (flush-at bucket,
    flush window) plus the stepper that refreshes them from the queue's
    own counters.

    The queue holds a strong reference to its tuner; the tuner holds the
    queue weakly (facades are rebuilt on algorithm hot-swap and their dead
    queues must not linger).  All state crossing the lock is scalar, so
    the hot-path reads are two lock acquisitions per flush decision.
    """

    def __init__(self, queue, cfg: TunerConfig,
                 clock: Callable[[], float] = time.monotonic,
                 scheduler=None, cost=None):
        #: guards every read/write of decision state: written on the event
        #: loop (step), read from gauge/exporter/dump threads (qrflow
        #: cross-thread-state — set_fn callbacks are executor-domain)
        self._lock = threading.Lock()
        self._queue = weakref.ref(queue)
        self.label = queue.label
        self.cfg = cfg
        self._clock = clock
        self._scheduler = scheduler
        #: decision journal sink (obs/cost.py CostLedger): EVERY step is
        #: journaled with its inputs — the flight ``tuner_step`` event
        #: covers changes only; None (the default) journals nothing
        self._cost = cost
        self._floor = max(1, _next_pow2(queue.bucket_floor))
        #: cold-start prior: None = the STATIC configuration (flush at
        #: max_batch, the constructor window) until the first informed
        #: step — a fresh engine behaves exactly like the static stack
        #: for its first quarter second
        self.bucket: int | None = None
        self.window_s: float | None = None
        self.steps = 0
        self.changes = 0
        self.degraded = False
        self.saturated = False
        # last-step snapshot of the queue counters
        self._last_t = clock()
        self._last_ops = queue.stats.ops
        self._last_flushes = queue.stats.flushes
        self._last_fallback = queue.stats.fallback_flushes

    # -- hot path (event loop) ------------------------------------------------

    def flush_at(self) -> int | None:
        """Pending-op count that triggers an immediate flush (None: read
        the static configuration — before the first informed step, and
        whenever the host is SATURATED, where early triggering shears
        backlog-grown waves; see ``decide``).  Otherwise 2x the right-size
        bucket: a wave clearly fuller than typical dispatches without
        waiting out the window's tail, while typical batches are never
        undercut (shattering guard)."""
        with self._lock:
            if self.bucket is None or self.saturated:
                return None
            return 2 * self.bucket

    def chosen_bucket(self) -> int | None:
        """The right-size bucket itself (gauges; flush_at is 2x this)."""
        with self._lock:
            return self.bucket

    def alive(self) -> bool:
        """False once the tuned queue is gone (algorithm hot-swap rebuilt
        the facade): the gauge children registered for this tuner must
        stop reporting a live-looking value for a dead plane."""
        return self._queue() is not None

    def wait_s(self) -> float | None:
        """Timer window for a partially filled bucket (None = static)."""
        with self._lock:
            return self.window_s

    def maybe_step(self) -> bool:
        """Step if the cadence allows (called from flush completion — no
        background task, so tests drive it deterministically)."""
        q = self._queue()
        if q is None:
            return False
        now = self._clock()
        with self._lock:
            due = (now - self._last_t >= self.cfg.step_interval_s
                   and q.stats.flushes - self._last_flushes
                   >= self.cfg.min_flushes_per_step)
        if not due:
            return False
        self.step()
        return True

    # -- decisions ------------------------------------------------------------

    def _plane_degraded(self, q) -> bool:
        """Breaker-probe traffic on the path this queue dispatches to: any
        placement shard (or the single breaker) not closed."""
        if self._scheduler is not None:
            return any(s.breaker.state != "closed"
                       for s in self._scheduler.shards)
        return q.breaker.state != "closed"

    def step(self) -> None:
        """One decision from the counter deltas since the last step."""
        q = self._queue()
        if q is None:
            return
        now = self._clock()
        st = q.stats
        ops, flushes, fallback = st.ops, st.flushes, st.fallback_flushes
        # two latencies, one signal: device_hist is ON-WORKER program time,
        # dispatch_hist is loop-observed (program + executor queueing) —
        # their gap is the saturation detector (see ``decide``)
        p50_device = st.device_hist.percentile(50)
        p50_dispatch = st.dispatch_hist.percentile(50)
        degraded = fallback > self._last_fallback or self._plane_degraded(q)
        with self._lock:
            dt = max(now - self._last_t, 1e-9)
            rate = (ops - self._last_ops) / dt
            avg_batch = ((ops - self._last_ops)
                         / max(1, flushes - self._last_flushes))
            old_bucket, old_window = self.bucket, self.window_s
            self.bucket, self.window_s, self.saturated = decide(
                old_bucket if old_bucket is not None else self._floor,
                q.bucket_floor, avg_batch, p50_device, p50_dispatch,
                degraded, self.cfg
            )
            self.degraded = degraded
            self.steps += 1
            self._last_t = now
            self._last_ops, self._last_flushes = ops, flushes
            self._last_fallback = fallback
            changed = (self.bucket != old_bucket
                       or old_window is None
                       or abs(self.window_s - old_window) > 1e-9)
            if changed:
                self.changes += 1
            bucket, window_s = self.bucket, self.window_s
            saturated = self.saturated
        if self._cost is not None:
            # the full trajectory: every decide() step with its inputs,
            # stamped with the tuner's own (injectable) clock — a seeded
            # storm's tuning history replays deterministically from it
            self._cost.tuner_decision(
                self.label, now,
                {
                    "avg_batch": round(avg_batch, 4),
                    "rate_ops_s": round(rate, 2),
                    "p50_device_ms": (round(p50_device * 1e3, 3)
                                      if p50_device is not None else None),
                    "p50_dispatch_ms": (round(p50_dispatch * 1e3, 3)
                                        if p50_dispatch is not None else None),
                },
                bucket, window_s, saturated, degraded,
            )
        if changed:
            # decision CHANGES are flight events (every step would be
            # noise); the dump narrates why the serving loop re-shaped
            obs_flight.record(
                "tuner_step", queue=self.label, bucket=bucket,
                window_ms=round(window_s * 1e3, 3), rate_ops_s=round(rate, 1),
                avg_batch=round(avg_batch, 2),
                p50_device_ms=(round(p50_device * 1e3, 3)
                               if p50_device else None),
                p50_dispatch_ms=(round(p50_dispatch * 1e3, 3)
                                 if p50_dispatch else None),
                degraded=degraded,
            )

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "bucket": self.bucket,  # None = static cold-start prior
                "window_ms": (round(self.window_s * 1e3, 3)
                              if self.window_s is not None else None),
                "steps": self.steps,
                "changes": self.changes,
                "degraded": self.degraded,
                "saturated": self.saturated,
            }


class Autotuner:
    """The engine-level tuner set: one :class:`QueueTuner` per attached
    OpQueue, plus the obs surface (``autotune_chosen_bucket`` /
    ``autotune_flush_window_ms`` gauge children labeled by queue).

    Facades are rebuilt on algorithm hot-swap, so the engine re-attaches
    after every rebuild; attach is idempotent per queue object.
    """

    def __init__(self, registry=None, cfg: TunerConfig | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 scheduler=None, cost=None):
        self.cfg = cfg if cfg is not None else TunerConfig()
        self._clock = clock
        self._scheduler = scheduler
        self._cost = cost
        self._lock = threading.Lock()
        #: queue -> tuner (weak keys: hot-swapped facades' queues die)
        self._tuners: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self._g_bucket = self._g_window = None
        if registry is not None:
            self._g_bucket = registry.gauge(
                "autotune_chosen_bucket", "tuner-chosen flush-at bucket")
            self._g_window = registry.gauge(
                "autotune_flush_window_ms", "tuner-chosen flush window (ms)")

    def attach_queue(self, queue) -> QueueTuner:
        with self._lock:
            tuner = self._tuners.get(queue)
            if tuner is not None:
                return tuner
            tuner = QueueTuner(queue, self.cfg, self._clock,
                               scheduler=self._scheduler, cost=self._cost)
            self._tuners[queue] = tuner
        queue.tuner = tuner
        if self._g_bucket is not None:
            # lazy children: the scrape thread reads through the tuner
            # lock; 0 = "static cold-start prior, no decision yet"; None
            # (-> JSON null / Prometheus NaN) once the queue died in a
            # hot-swap — a dead plane must not keep exporting a
            # live-looking last value
            self._g_bucket.labels(queue=tuner.label).set_fn(
                lambda t=tuner: (t.chosen_bucket() or 0) if t.alive()
                else None)
            self._g_window.labels(queue=tuner.label).set_fn(
                lambda t=tuner: (t.wait_s() or 0.0) * 1e3 if t.alive()
                else None)
        return tuner

    def attach_facades(self, *facades) -> None:
        """Attach every OpQueue of the given batched facades (None entries
        are skipped — the fused facade is optional)."""
        from .batched import facade_queues

        for facade in facades:
            if facade is None:
                continue
            for q in facade_queues(facade):
                self.attach_queue(q)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            tuners = list(self._tuners.values())
        return {
            "enabled": True,
            "queues": {t.label: t.snapshot() for t in tuners},
        }
