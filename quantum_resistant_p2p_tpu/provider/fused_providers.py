"""Fused handshake capability providers (provider/base.py FusedHandshakeOps).

``FusedMLKEMMLDSA`` wraps an existing (ML-KEM, ML-DSA) tpu-backend provider
pair and exposes the three composite handshake programs from
``fused.mlkem_mldsa`` at the numpy/bytes level the batching queue speaks.
Host-side work mirrors the per-op providers: variable-length transcripts
that are fully host-known are hashed to the fixed 64-byte mu with hashlib
(public data, cheap); transcripts embedding a device output are shipped as
templates and hashed on device.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..native import wipe
from .base import FusedHandshakeOps, expect_cols, sliced_dispatch
from .sig_providers import _m_prime, _mu

#: static headroom past the hex payload for the JSON scaffolding (keys,
#: uuid, peer ids, timestamp repr) — one compiled template shape covers
#: every realistic transcript; longer ones fall back to the per-op path
TEMPLATE_HEADROOM = 1024


def init_pk_offset(kem_name: str, aead_name: str) -> int:
    """Byte offset of the public-key hex inside the canonical init
    transcript.  Canonical JSON sorts keys, and every key before
    "public_key" has a fixed-length value (aead/kem names, the 36-char
    uuid4 message_id), so the offset depends only on the algorithm names —
    computed by probing an actual canonical dump rather than hand-counting.
    """
    probe = {
        "aead": aead_name, "kem": kem_name, "message_id": "x" * 36,
        "public_key": "", "recipient": "", "sender": "", "timestamp": 0,
    }
    s = json.dumps(probe, sort_keys=True, separators=(",", ":"))
    return s.index('"public_key":"') + len('"public_key":"')


def resp_ct_offset() -> int:
    """Byte offset of the ciphertext hex inside the canonical response
    transcript ("ciphertext" sorts first, so the offset is constant)."""
    probe = {
        "ciphertext": "", "message_id": "x" * 36,
        "recipient": "", "sender": "", "timestamp": 0,
    }
    s = json.dumps(probe, sort_keys=True, separators=(",", ":"))
    return s.index('"ciphertext":"') + len('"ciphertext":"')


def _stack_templates(templates: list[bytes], lmax: int) -> tuple[np.ndarray, np.ndarray]:
    """list of transcript bytes -> ((n, lmax) uint8 zero-padded, (n,) int32
    true lengths).  Callers pre-check len <= lmax (see *_template_len)."""
    t = np.zeros((len(templates), lmax), np.uint8)
    lens = np.empty(len(templates), np.int32)
    for i, b in enumerate(templates):
        t[i, : len(b)] = np.frombuffer(b, np.uint8)
        lens[i] = len(b)
    return t, lens


def _rand(n: int, width: int, given) -> np.ndarray:
    if given is not None:
        return np.stack([np.frombuffer(bytes(r), np.uint8) for r in given])
    return np.frombuffer(os.urandom(width * n), np.uint8).reshape(n, width)


class FusedMLKEMMLDSA(FusedHandshakeOps):
    """Composite ML-KEM + ML-DSA handshake programs on the tpu backend."""

    def __init__(self, kem, sig):
        if getattr(kem, "backend", "") != "tpu" or getattr(sig, "backend", "") != "tpu":
            raise ValueError(
                f"fused ops need a tpu-backend pair, got {kem.backend}/{sig.backend}"
            )
        self.kem = kem
        self.sig = sig
        self.name = f"{kem.name}+{sig.name}"
        self.backend = "tpu"
        self.init_template_len = 2 * kem.public_key_len + TEMPLATE_HEADROOM
        self.resp_template_len = 2 * kem.ciphertext_len + TEMPLATE_HEADROOM
        from ..kem import mlkem as _jax_mlkem  # deferred: pulls in jax

        self._max_dispatch = _jax_mlkem.MAX_DEVICE_BATCH

    # -- jitted program access (cached per (names, offset)) -----------------

    def _kg_sign(self, pk_off: int):
        from ..fused import mlkem_mldsa as _fused

        return _fused.get_keygen_sign(self.kem.name, self.sig.name, pk_off)

    def _enc_vfy_sign(self, ct_off: int):
        from ..fused import mlkem_mldsa as _fused

        return _fused.get_encaps_verify_sign(self.kem.name, self.sig.name, ct_off)

    def _dec_vfy_sign(self):
        from ..fused import mlkem_mldsa as _fused

        return _fused.get_decaps_verify_sign(self.kem.name, self.sig.name)

    # -- host-side mu hashing (public transcripts) --------------------------

    def _mus_from_peer_pks(self, peer_sig_pks: np.ndarray,
                           msgs_in: list[bytes]) -> np.ndarray:
        import hashlib

        trs = [hashlib.shake_256(bytes(pk)).digest(64) for pk in peer_sig_pks]
        return np.stack(
            [np.frombuffer(_mu(tr, m), np.uint8) for tr, m in zip(trs, msgs_in)]
        )

    def _mus_from_own_sks(self, sig_sks: np.ndarray,
                          msgs_out: list[bytes]) -> np.ndarray:
        trs = [bytes(sk[64:128]) for sk in sig_sks]
        return np.stack(
            [np.frombuffer(_mu(tr, m), np.uint8) for tr, m in zip(trs, msgs_out)]
        )

    @staticmethod
    def _check_done(done: np.ndarray, what: str) -> None:
        if not np.asarray(done).all():
            # mirrors MLDSASignature.sign_batch: an all-zero sigma must
            # never leave the provider as if it were a signature
            raise RuntimeError(
                f"fused {what}: {int((~np.asarray(done)).sum())} lane(s) "
                "exhausted the rejection-sampling budget"
            )

    # -- FusedHandshakeOps surface ------------------------------------------

    def keygen_sign_batch(self, sig_sks: np.ndarray, templates: list[bytes],
                          pk_off: int, rnd=None):
        expect_cols(sig_sks, self.sig.secret_key_len, "secret keys", self.name)
        n = len(templates)
        d = np.frombuffer(os.urandom(32 * n), np.uint8).reshape(n, 32)
        z = np.frombuffer(os.urandom(32 * n), np.uint8).reshape(n, 32)
        rnds = _rand(n, 32, rnd)
        tmpl, lens = _stack_templates(templates, self.init_template_len)
        ek, dk, sigs, done = sliced_dispatch(
            self._kg_sign(pk_off), self._max_dispatch,
            d, z, np.asarray(sig_sks), rnds, tmpl, lens,
        )
        self._check_done(done, "keygen_sign")
        return np.asarray(ek), np.asarray(dk), [bytes(s) for s in sigs]

    def encaps_verify_sign_batch(self, public_keys: np.ndarray,
                                 peer_sig_pks: np.ndarray,
                                 msgs_in: list[bytes], sigs_in: list[bytes],
                                 sig_sks: np.ndarray, templates: list[bytes],
                                 ct_off: int, m=None, rnd=None):
        expect_cols(public_keys, self.kem.public_key_len, "public keys", self.name)
        expect_cols(sig_sks, self.sig.secret_key_len, "secret keys", self.name)
        n = len(templates)
        mus_in = self._mus_from_peer_pks(peer_sig_pks, msgs_in)
        sig_arr = np.stack([np.frombuffer(bytes(s), np.uint8) for s in sigs_in])
        ms = _rand(n, 32, m)
        rnds = _rand(n, 32, rnd)
        tmpl, lens = _stack_templates(templates, self.resp_template_len)
        ok, ct, key, sigs, done = sliced_dispatch(
            self._enc_vfy_sign(ct_off), self._max_dispatch,
            np.asarray(public_keys), ms, np.asarray(peer_sig_pks), mus_in,
            sig_arr, np.asarray(sig_sks), rnds, tmpl, lens,
        )
        self._check_done(done, "encaps_verify_sign")
        return np.asarray(ok), np.asarray(ct), np.asarray(key), [bytes(s) for s in sigs]

    def decaps_verify_sign_batch(self, secret_keys: np.ndarray,
                                 ciphertexts: np.ndarray,
                                 peer_sig_pks: np.ndarray,
                                 msgs_in: list[bytes], sigs_in: list[bytes],
                                 sig_sks: np.ndarray, msgs_out: list[bytes],
                                 rnd=None):
        expect_cols(secret_keys, self.kem.secret_key_len, "secret keys", self.name)
        expect_cols(ciphertexts, self.kem.ciphertext_len, "ciphertexts", self.name)
        n = len(msgs_out)
        mus_in = self._mus_from_peer_pks(peer_sig_pks, msgs_in)
        mus_out = self._mus_from_own_sks(sig_sks, msgs_out)
        sig_arr = np.stack([np.frombuffer(bytes(s), np.uint8) for s in sigs_in])
        rnds = _rand(n, 32, rnd)
        ok, ss, sigs, done = sliced_dispatch(
            self._dec_vfy_sign(), self._max_dispatch,
            np.asarray(secret_keys), np.asarray(ciphertexts),
            np.asarray(peer_sig_pks), mus_in, sig_arr, np.asarray(sig_sks),
            mus_out, rnds,
        )
        self._check_done(done, "decaps_verify_sign")
        return np.asarray(ok), np.asarray(ss), [bytes(s) for s in sigs]

    def warmup(self, sizes: tuple[int, ...] = (1,), pk_off: int | None = None,
               ct_off: int | None = None) -> None:
        """Compile the composite programs for the given pow2 bucket sizes
        (blocking; run off-loop).  Offsets default to the canonical
        transcript layout; pass the live ones when they differ (jit keys
        on them, so a mismatched warmup buys nothing)."""
        from ..utils import next_pow2

        if pk_off is None:
            pk_off = init_pk_offset(self.kem.name, "AES-256-GCM")
        if ct_off is None:
            ct_off = resp_ct_offset()
        spk, ssk = self.sig.generate_keypair()
        for n in sizes:
            n2 = next_pow2(n)
            sks = np.stack([np.frombuffer(ssk, np.uint8)] * n2)
            pks = np.stack([np.frombuffer(spk, np.uint8)] * n2)
            init_t = [b"w" * (pk_off + 2 * self.kem.public_key_len + 64)] * n2
            eks, dks, sigs = self.keygen_sign_batch(sks, init_t, pk_off)
            resp_t = [b"w" * (ct_off + 2 * self.kem.ciphertext_len + 64)] * n2
            _, cts, _, _ = self.encaps_verify_sign_batch(
                eks, pks, [t for t in init_t], sigs, sks, resp_t, ct_off
            )
            self.decaps_verify_sign_batch(
                dks, cts, pks, [t for t in resp_t], sigs, sks,
                [b"w" * 128] * n2,
            )
        wipe(ssk)  # warmup-only key material
