"""Signature algorithm providers over the cpu (pyref) and tpu (JAX) backends.

Mirrors the role of the reference's MLDSASignature / SPHINCSSignature classes
(crypto/signatures.py:58-315), parameterized by NIST level 2/3/5, with
verify returning False on any failure (crypto/signatures.py:186-188).
"""

from __future__ import annotations

import os

import numpy as np

from ..pyref import mldsa_ref
from .base import SignatureAlgorithm

_LEVEL_TO_MLDSA = {2: mldsa_ref.MLDSA44, 3: mldsa_ref.MLDSA65, 5: mldsa_ref.MLDSA87}


class MLDSASignature(SignatureAlgorithm):
    """ML-DSA (FIPS 204) at NIST level 2, 3 or 5."""

    def __init__(self, security_level: int = 3, backend: str = "cpu"):
        if security_level not in _LEVEL_TO_MLDSA:
            raise ValueError(f"ML-DSA level must be 2/3/5, got {security_level}")
        self.params = _LEVEL_TO_MLDSA[security_level]
        self.security_level = security_level
        self.backend = backend
        self.name = self.params.name
        self.display_name = f"{self.params.name} ({backend})"
        self.description = (
            f"Module-Lattice signature, FIPS 204, NIST level {security_level}, "
            f"{'batched JAX/TPU' if backend == 'tpu' else 'pure-Python CPU'} backend"
        )
        self.public_key_len = self.params.pk_len
        self.secret_key_len = self.params.sk_len
        self.signature_len = self.params.sig_len
        if backend == "tpu":
            from ..sig import mldsa as _jax_mldsa  # deferred: pulls in jax

            self._tpu = _jax_mldsa.get(self.params.name)

    def generate_keypair(self) -> tuple[bytes, bytes]:
        xi = os.urandom(32)
        if self.backend == "tpu":
            pk, sk = self._tpu.keygen(np.frombuffer(xi, np.uint8)[None])
            return bytes(np.asarray(pk)[0]), bytes(np.asarray(sk)[0])
        return mldsa_ref.keygen(self.params, xi)

    def sign(self, secret_key: bytes, message: bytes) -> bytes:
        rnd = os.urandom(32)  # hedged variant
        if self.backend == "tpu":
            sig = self._tpu.sign(
                np.frombuffer(secret_key, np.uint8)[None],
                np.frombuffer(message, np.uint8)[None],
                np.frombuffer(rnd, np.uint8)[None],
            )
            return bytes(np.asarray(sig)[0])
        return mldsa_ref.sign(self.params, secret_key, message, rnd=rnd)

    def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        try:
            if self.backend == "tpu":
                ok = self._tpu.verify(
                    np.frombuffer(public_key, np.uint8)[None],
                    np.frombuffer(message, np.uint8)[None],
                    np.frombuffer(signature, np.uint8)[None],
                )
                return bool(np.asarray(ok)[0])
            return mldsa_ref.verify(self.params, public_key, message, signature)
        except Exception:
            return False
