"""Signature algorithm providers over the cpu (pyref) and tpu (JAX) backends.

Mirrors the role of the reference's MLDSASignature / SPHINCSSignature classes
(crypto/signatures.py:58-315), parameterized by NIST level 2/3/5, with
verify returning False on any failure (crypto/signatures.py:186-188).

Host/device split for the tpu backend: variable-length messages are hashed to
the fixed 64-byte ``mu = SHAKE256(tr || M', 64)`` on the host (public data,
cheap); the lattice math runs as fixed-shape batched JAX programs.
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

from ..pyref import mldsa_ref
from .base import (SignatureAlgorithm, cpu_impl_desc, expect_cols, expect_len,
                   make_provider_mesh, mesh_dispatch, sliced_dispatch,
                   try_native)

_LEVEL_TO_MLDSA = {2: mldsa_ref.MLDSA44, 3: mldsa_ref.MLDSA65, 5: mldsa_ref.MLDSA87}

from ..pyref import slhdsa_ref  # noqa: E402

# (level, fast) -> params; 'f' = fast-sign/large-sig, 's' = small-sig/slow-sign
_LEVEL_TO_SLH = {
    (1, True): slhdsa_ref.SLH128F,
    (1, False): slhdsa_ref.SLH128S,
    (3, True): slhdsa_ref.SLH192F,
    (3, False): slhdsa_ref.SLH192S,
    (5, True): slhdsa_ref.SLH256F,
    (5, False): slhdsa_ref.SLH256S,
}


class _MeshDispatchMixin:
    """Routes jitted batch fns through the provider mesh when configured."""

    _mesh = None

    def _dispatch(self, fn, *arrays):
        if self._mesh is not None:
            return mesh_dispatch(fn, self._mesh, *arrays)
        out = fn(*arrays)
        if isinstance(out, tuple):
            return tuple(np.asarray(o) for o in out)
        return np.asarray(out)


def _m_prime(message: bytes, ctx: bytes = b"") -> bytes:
    """FIPS 204/205 pure-mode framing: M' = 0x00 || len(ctx) || ctx || M."""
    return bytes([0, len(ctx)]) + ctx + message


def _mu(tr: bytes, message: bytes, ctx: bytes = b"") -> bytes:
    """mu = SHAKE256(tr || M', 64)."""
    return hashlib.shake_256(tr + _m_prime(message, ctx)).digest(64)


class MLDSASignature(_MeshDispatchMixin, SignatureAlgorithm):
    """ML-DSA (FIPS 204) at NIST level 2, 3 or 5."""

    def __init__(self, security_level: int = 3, backend: str = "cpu",
                 devices: int = 0, compact_sign: bool = False,
                 opcache_size: int = 8):
        if security_level not in _LEVEL_TO_MLDSA:
            raise ValueError(f"ML-DSA level must be 2/3/5, got {security_level}")
        self.params = _LEVEL_TO_MLDSA[security_level]
        self.security_level = security_level
        self.backend = backend
        #: opt-in compact-and-refill signing (sig/mldsa.sign_mu_compact):
        #: ~7% faster at batch 8192 (measured, bench_report config 4) but its
        #: refill dispatches have data-dependent shapes, which interacts
        #: badly with the batch queue's warm-bucket bookkeeping — so the
        #: queue path keeps the single-program loop by default
        self.compact_sign = compact_sign
        self.name = self.params.name
        self.display_name = f"{self.params.name} ({backend})"
        self.public_key_len = self.params.pk_len
        self.secret_key_len = self.params.sk_len
        self.signature_len = self.params.sig_len
        #: device-resident per-key operand cache (tpu only): a node signs
        #: every transcript with ONE long-lived key and verifies a peer with
        #: one public key, so the key-dependent ExpandA + NTTs are per-KEY
        #: work recomputed by every dispatch without this.  0 disables.
        self.opcache = None
        if backend == "tpu":
            from ..sig import mldsa as _jax_mldsa  # deferred: pulls in jax

            self._kg, self._sign_mu, self._verify_mu = _jax_mldsa.get(self.params.name)
            (self._sign_cold, self._sign_pre,
             self._verify_cold, self._verify_pre) = _jax_mldsa.get_pre(self.params.name)
            if opcache_size > 0:
                from .opcache import DeviceOperandCache

                self.opcache = DeviceOperandCache(opcache_size)
        self._mesh = make_provider_mesh(devices, backend)
        self._native = None
        if backend == "cpu":
            # Native C++ fast path (the role liboqs plays for the reference:
            # crypto/signatures.py:58-188); pyref stays the fallback + oracle.
            self._native = try_native("NativeMLDSA", self.params.name)
        self.description = (
            f"Module-Lattice signature, FIPS 204, NIST level {security_level}, "
            f"{'batched JAX/TPU' if backend == 'tpu' else cpu_impl_desc(self._native)} backend"
        )

    def generate_keypair(self) -> tuple[bytes, bytes]:
        xi = os.urandom(32)
        if self.backend == "tpu":
            pk, sk = self._kg(np.frombuffer(xi, np.uint8)[None])
            return bytes(np.asarray(pk)[0]), bytes(np.asarray(sk)[0])
        if self._native is not None:
            return self._native.keygen(xi)
        return mldsa_ref.keygen(self.params, xi)

    def generate_keypair_batch(self, n: int) -> tuple[np.ndarray, np.ndarray]:
        if self.backend != "tpu":
            return super().generate_keypair_batch(n)
        xi = np.frombuffer(os.urandom(32 * n), np.uint8).reshape(n, 32)
        # _dispatch routes through the provider mesh when configured, like
        # every other ML-DSA device path (sign/verify); ML-DSA has no
        # sliced-dispatch cap (batch 8192 keygen is a routine dispatch)
        pk, sk = self._dispatch(self._kg, xi)
        return np.asarray(pk), np.asarray(sk)

    def sign(self, secret_key: bytes, message: bytes) -> bytes:
        expect_len(secret_key, self.secret_key_len, "secret key", self.name)
        rnd = os.urandom(32)  # hedged variant
        if self.backend == "tpu":
            sk = np.frombuffer(secret_key, np.uint8)[None]
            return bytes(self.sign_batch(sk, [message], rnd=[rnd])[0])
        if self._native is not None:
            return self._native.sign_internal(secret_key, _m_prime(message), rnd)
        return mldsa_ref.sign(self.params, secret_key, message, rnd=rnd)

    def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        try:
            if len(signature) != self.params.sig_len or len(public_key) != self.params.pk_len:
                return False
            if self.backend == "tpu":
                pk = np.frombuffer(public_key, np.uint8)[None]
                sig = np.frombuffer(signature, np.uint8)[None]
                return bool(self.verify_batch(pk, [message], [sig])[0])
            if self._native is not None:
                return self._native.verify_internal(
                    public_key, _m_prime(message), signature
                )
            return mldsa_ref.verify(self.params, public_key, message, signature)
        except Exception:  # qrlint: disable=broad-except  — verify contract (base.py): malformed attacker input maps to False, never an exception
            return False

    # -- batch API (tpu-native; cpu falls back to base-class loop) ----------

    def sign_batch(self, secret_keys: np.ndarray, messages: list[bytes], rnd=None):
        expect_cols(secret_keys, self.secret_key_len, "secret keys", self.name)
        if self.backend != "tpu":
            return super().sign_batch(secret_keys, messages)
        n = len(messages)
        if rnd is None:
            rnd = [os.urandom(32) for _ in range(n)]
        trs = [bytes(sk[64:128]) for sk in secret_keys]
        mus = np.stack(
            [np.frombuffer(_mu(tr, m), np.uint8) for tr, m in zip(trs, messages)]
        )
        rnds = np.stack([np.frombuffer(r, np.uint8) for r in rnd])
        sks = np.asarray(secret_keys)
        if self.compact_sign and self._mesh is None:
            # Opt-in compact-and-refill driver: unfinished lanes gather into
            # shrinking pow2 buckets between dispatches instead of every
            # lane riding until the slowest accepts (bit-identical output,
            # ~3x less attempted work, measured +7% wall-clock at 8192).
            from ..sig import mldsa as _jax_mldsa

            sigs, done = _jax_mldsa.sign_mu_compact(
                self.params.name, sks, mus, rnds
            )
        elif (self.opcache is not None and self._mesh is None
              and (n == 1 or (sks[0] == sks).all())):  # qrlint: disable=flow-secret-compare — single-key-batch detection compares the node's OWN sk rows for identity; timing reveals batch homogeneity (operational fact), not key content
            # Single-key batch — the steady state (one node, one long-lived
            # sig key): a hit skips the sk upload + ExpandA + key NTTs; a
            # miss runs the cache-filling combined program.  One dispatch
            # either way, bit-identical output (pure hoist).
            skb = sks[0].tobytes()
            pre = self.opcache.lookup("sk", skb)
            if pre is None:
                pre, sigs, done = self._sign_cold(sks[0], mus, rnds)
                self.opcache.put("sk", skb, pre)
            else:
                sigs, done = self._sign_pre(pre, mus, rnds)
            sigs, done = np.asarray(sigs), np.asarray(done)
        else:
            sigs, done = self._dispatch(self._sign_mu, sks, mus, rnds)
        if not done.all():
            # P < 1e-12 per lane; an all-zero sigma must never leave the
            # provider as if it were a signature (ADVICE r1).
            raise RuntimeError(
                f"{self.name}: {int((~done).sum())} lane(s) exhausted the "
                f"rejection-sampling budget"
            )
        return [bytes(s) for s in sigs]

    def verify_batch(self, public_keys: np.ndarray, messages: list[bytes], signatures):
        expect_cols(public_keys, self.public_key_len, "public keys", self.name)
        if self.backend != "tpu":
            return super().verify_batch(public_keys, messages, signatures)
        trs = [hashlib.shake_256(bytes(pk)).digest(64) for pk in public_keys]
        mus = np.stack(
            [np.frombuffer(_mu(tr, m), np.uint8) for tr, m in zip(trs, messages)]
        )
        sigs = np.stack([np.frombuffer(bytes(s), np.uint8) for s in signatures])
        pks = np.asarray(public_keys)
        if (self.opcache is not None and self._mesh is None
                and (pks.shape[0] == 1 or (pks[0] == pks).all())):
            # Single-key batch (a peer's long-lived sig key): cached
            # ExpandA + NTT(t1<<D); see sign_batch.
            pkb = pks[0].tobytes()
            pre = self.opcache.lookup("pk", pkb)
            if pre is None:
                pre, oks = self._verify_cold(pks[0], mus, sigs)
                self.opcache.put("pk", pkb, pre)
            else:
                oks = self._verify_pre(pre, mus, sigs)
            return np.asarray(oks)
        return self._dispatch(self._verify_mu, pks, mus, sigs)


# Per-set sign dispatch caps: the s-set values are the measured hard compile
# ceilings in this environment (bench_results/r3_sphincs_layered4.json — the
# next pow2 rung kills the remote compile helper twice in a row); the f-set
# values are the largest measured-good batches (bench_report.md config 4).
# sliced_dispatch keeps any queue-sized batch inside them, costing only
# extra dispatches — throughput is compute-saturated well below every cap.
_SLH_MAX_SIGN_BATCH = {
    "SPHINCS+-SHA2-128f-simple": 1024,
    "SPHINCS+-SHA2-192f-simple": 512,
    "SPHINCS+-SHA2-256f-simple": 256,
    "SPHINCS+-SHA2-128s-simple": 512,
    "SPHINCS+-SHA2-192s-simple": 64,
    "SPHINCS+-SHA2-256s-simple": 32,
}


class SPHINCSSignature(_MeshDispatchMixin, SignatureAlgorithm):
    """SPHINCS+-SHA2 'f' simple (FIPS 205 SLH-DSA) at NIST level 1, 3 or 5.

    Host/device split for the tpu backend: PRF_msg and the variable-length
    H_msg digest run host-side (hashlib/hmac, public data); the FORS +
    hypertree hashing — the actual work — runs as batched JAX programs.
    """

    def __init__(self, security_level: int = 1, backend: str = "cpu",
                 fast: bool = True, devices: int = 0):
        key = (security_level, fast)
        if key not in _LEVEL_TO_SLH:
            raise ValueError(f"SPHINCS+ level must be 1/3/5, got {security_level}")
        self.params = _LEVEL_TO_SLH[key]
        self.security_level = security_level
        self.backend = backend
        self.fast = fast
        self.name = self.params.name
        self.display_name = f"{self.params.name} ({backend})"
        self.public_key_len = self.params.pk_len
        self.secret_key_len = self.params.sk_len
        self.signature_len = self.params.sig_len
        if backend == "tpu":
            from ..sig import sphincs as _jax_slh  # deferred: pulls in jax

            self._kg, self._sign_digest, self._verify_digest = _jax_slh.get(self.params.name)
        self._mesh = make_provider_mesh(devices, backend)
        self._native = None
        if backend == "cpu":
            # Native C++ fast path (the role liboqs plays for the reference:
            # crypto/signatures.py:191-315); pyref stays the fallback + oracle.
            self._native = try_native("NativeSLHDSA", self.params.name)
        self.description = (
            f"Stateless hash-based signature, FIPS 205, NIST level {security_level}, "
            f"{'fast-sign' if fast else 'small-signature'} variant, "
            f"{'batched JAX/TPU' if backend == 'tpu' else cpu_impl_desc(self._native)} backend"
        )

    def generate_keypair(self) -> tuple[bytes, bytes]:
        p = self.params
        seeds = os.urandom(3 * p.n)
        sk_seed, sk_prf, pk_seed = seeds[: p.n], seeds[p.n : 2 * p.n], seeds[2 * p.n :]
        if self.backend == "tpu":
            pk, sk = self._kg(
                np.frombuffer(sk_seed, np.uint8)[None],
                np.frombuffer(sk_prf, np.uint8)[None],
                np.frombuffer(pk_seed, np.uint8)[None],
            )
            return bytes(np.asarray(pk)[0]), bytes(np.asarray(sk)[0])
        if self._native is not None:
            return self._native.keygen(sk_seed, sk_prf, pk_seed)
        return slhdsa_ref.keygen(p, sk_seed, sk_prf, pk_seed)

    def sign(self, secret_key: bytes, message: bytes) -> bytes:
        expect_len(secret_key, self.secret_key_len, "secret key", self.name)
        if self.backend == "tpu":
            sk = np.frombuffer(secret_key, np.uint8)[None]
            return bytes(self.sign_batch(sk, [message])[0])
        if self._native is not None:
            return self._native.sign_internal(message, secret_key)
        return slhdsa_ref.sign(self.params, secret_key, message)

    def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        try:
            if len(signature) != self.params.sig_len or len(public_key) != self.params.pk_len:
                return False
            if self.backend == "tpu":
                pk = np.frombuffer(public_key, np.uint8)[None]
                sig = np.frombuffer(signature, np.uint8)[None]
                return bool(self.verify_batch(pk, [message], [sig])[0])
            if self._native is not None:
                return self._native.verify_internal(message, signature, public_key)
            return slhdsa_ref.verify(self.params, public_key, message, signature)
        except Exception:  # qrlint: disable=broad-except  — verify contract (base.py): malformed attacker input maps to False, never an exception
            return False

    # -- batch API ----------------------------------------------------------

    def sign_batch(self, secret_keys: np.ndarray, messages: list[bytes]):
        expect_cols(secret_keys, self.secret_key_len, "secret keys", self.name)
        if self.backend != "tpu":
            return super().sign_batch(secret_keys, messages)
        p = self.params
        rs, digests = [], []
        for sk, m in zip(secret_keys, messages):
            skb = bytes(sk)
            sk_prf = skb[p.n : 2 * p.n]
            pk_seed, pk_root = skb[2 * p.n : 3 * p.n], skb[3 * p.n :]
            r = slhdsa_ref.prf_msg(p, sk_prf, pk_seed, m)  # deterministic variant
            rs.append(np.frombuffer(r, np.uint8))
            digests.append(
                np.frombuffer(slhdsa_ref.h_msg(p, r, pk_seed, pk_root, m), np.uint8)
            )
        cap = _SLH_MAX_SIGN_BATCH[self.params.name]
        if self._mesh is not None:
            # the ceiling is a COMPILE limit on the whole traced program, so
            # it caps the GLOBAL batch; sliced_dispatch's step is per-device
            cap = max(1, cap // self._mesh.size)
        sigs = sliced_dispatch(
            self._sign_digest, cap,
            np.asarray(secret_keys), np.stack(rs), np.stack(digests),
            mesh=self._mesh,
        )
        return [bytes(s) for s in sigs]

    def verify_batch(self, public_keys: np.ndarray, messages: list[bytes], signatures):
        expect_cols(public_keys, self.public_key_len, "public keys", self.name)
        if self.backend != "tpu":
            return super().verify_batch(public_keys, messages, signatures)
        p = self.params
        sigs = np.stack([np.frombuffer(bytes(s), np.uint8) for s in signatures])
        digests = []
        # iterate the NORMALIZED (L,) rows: a caller-supplied element may be
        # (1, L)-shaped (the scalar verify path), where sig[: p.n] would row-
        # slice and hand h_msg the whole signature as the randomizer
        for pk, m, sig in zip(public_keys, messages, sigs):
            pkb = bytes(pk)
            r = bytes(sig[: p.n])
            digests.append(
                np.frombuffer(
                    slhdsa_ref.h_msg(p, r, pkb[: p.n], pkb[p.n :], m), np.uint8
                )
            )
        return self._dispatch(
            self._verify_digest, np.asarray(public_keys), np.stack(digests), sigs
        )
