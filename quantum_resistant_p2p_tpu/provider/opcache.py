"""Device-resident operand cache — stop re-uploading hot keys every dispatch.

On this environment's remote-TPU tunnel every operand byte crosses a
~MB/s link, and even on an attached chip the per-key preprocessing
(ExpandA matrix expansion, the key-dependent NTTs) is recomputed by every
dispatch that carries the same key.  Both costs are per-KEY, not per-op:
a node signs every transcript with one long-lived key, verifies a given
peer with one public key, and a swarm encapsulates repeatedly against hot
peers.  The cache pins the precomputed per-key device state (pytrees of
jax arrays produced by ``kem.mlkem.precompute_ek`` /
``sig.mldsa.precompute_sk`` / ``sig.mldsa.precompute_pk``) keyed by a
content hash of the raw key bytes, with LRU eviction so unbounded peer
churn cannot pin unbounded device memory.

Security note: cached entries derived from SECRET keys (the sign-path
precompute) hold key-equivalent material on device for the cache's
lifetime — the same trust boundary as the provider object itself, which
already holds the raw secret key in host memory.  Keys are identified by
SHA-256 of their bytes; raw key material never appears in stats or logs.

Thread-safety: lookups/inserts take a lock (queues dispatch from executor
threads); the miss-path compute runs OUTSIDE the lock because it may jit,
so two threads racing the same cold key may both compute — the second
insert wins, which is harmless (identical value) and cheaper than holding
a lock across a compile.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import threading
from collections import OrderedDict
from typing import Any

#: the placement-axis coordinate of the CURRENT dispatch (set by
#: Shard.placement on the dispatching thread).  Cached pytrees live on one
#: chip; feeding shard i's device arrays to a program placed on shard j
#: would force a cross-chip transfer (or fail on committed operands), so
#: cache keys are namespaced by this scope — the opcache state partitions
#: across the device mesh.  Default 0 = the single-device world.
_SHARD: contextvars.ContextVar[int] = contextvars.ContextVar(
    "qrp2p_opcache_shard", default=0
)


@contextlib.contextmanager
def shard_scope(index: int):
    """Namespace opcache lookups/inserts to placement shard ``index`` for
    the duration of the block (entered on the dispatch worker thread by
    ``provider.scheduler.Shard.placement``)."""
    token = _SHARD.set(index)
    try:
        yield
    finally:
        _SHARD.reset(token)


def current_shard() -> int:
    """The active placement scope (tests; diagnostics)."""
    return _SHARD.get()


class DeviceOperandCache:
    """Content-hash-keyed LRU of per-key device operand pytrees,
    partitioned by placement shard (see :func:`shard_scope`)."""

    def __init__(self, capacity: int = 8):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple[str, int, bytes], Any] = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        #: cost-ledger feed (obs/cost.py): sliding-window hit rates next
        #: to the cumulative counters above — attached by the engine,
        #: None (the default) records nothing extra
        self._cost = None
        self._cost_kind = ""

    def attach_cost(self, ledger, kind: str) -> None:
        """Feed hit/miss events into a :class:`obs.cost.CostLedger` under
        cache label ``kind`` ("kem" / "sig")."""
        self._cost = ledger
        self._cost_kind = kind

    @staticmethod
    def _key(kind: str, key_bytes: bytes) -> tuple[str, int, bytes]:
        # the shard coordinate keeps per-chip device state per chip; LRU
        # pressure is shared (one capacity across shards, matching the
        # single HBM budget the cache models per process)
        return (kind, _SHARD.get(), hashlib.sha256(key_bytes).digest())

    def lookup(self, kind: str, key_bytes: bytes) -> Any | None:
        """Cached state or None.  Deliberately a lookup/put split, not a
        compute-on-miss callback: the providers' miss path is a COMBINED
        program (op + precompute in one dispatch, e.g. kem.mlkem.
        encaps_cold) whose other outputs the caller needs — a callback
        could not return those."""
        k = self._key(kind, bytes(key_bytes))
        with self._lock:
            if k in self._entries:
                self._entries.move_to_end(k)
                self.hits += 1
                hit, out = True, self._entries[k]
            else:
                self.misses += 1
                hit, out = False, None
        if self._cost is not None:
            # outside the lock: the ledger takes its own (obs/cost.py)
            self._cost.opcache_event(self._cost_kind, hit)
        return out

    def put(self, kind: str, key_bytes: bytes, val: Any) -> None:
        k = self._key(kind, bytes(key_bytes))
        with self._lock:
            self._entries[k] = val
            self._entries.move_to_end(k)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1

    def clear(self) -> int:
        """Drop every entry; returns how many were released (read and
        cleared under one lock hold, so the count is exact)."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            return n

    def zeroize(self) -> None:
        """End the cached keys' device-state lifetime (same convention as
        SecureLogger.zeroize / KeyStorage.lock).  Sign-path entries are
        KEY-EQUIVALENT material: an algorithm hot-swap or shutdown must not
        leave them pinned on device — dropping the references releases the
        buffers to the runtime (host code cannot overwrite device memory, so
        release is the strongest zeroization available here).  Called by
        SecureMessaging's hot-swap paths."""
        n = self.clear()
        # key-lifetime events belong in the flight ring: a dump after a
        # hot-swap shows WHEN the outgoing provider's device state was
        # released (counts only — never key identities)
        from ..obs import flight as _flight

        _flight.record("opcache_zeroized", entries=n)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }
