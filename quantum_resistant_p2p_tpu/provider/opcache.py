"""Device-resident operand cache — stop re-uploading hot keys every dispatch.

On this environment's remote-TPU tunnel every operand byte crosses a
~MB/s link, and even on an attached chip the per-key preprocessing
(ExpandA matrix expansion, the key-dependent NTTs) is recomputed by every
dispatch that carries the same key.  Both costs are per-KEY, not per-op:
a node signs every transcript with one long-lived key, verifies a given
peer with one public key, and a swarm encapsulates repeatedly against hot
peers.  The cache pins the precomputed per-key device state (pytrees of
jax arrays produced by ``kem.mlkem.precompute_ek`` /
``sig.mldsa.precompute_sk`` / ``sig.mldsa.precompute_pk``) keyed by a
content hash of the raw key bytes, with LRU eviction so unbounded peer
churn cannot pin unbounded device memory.

Security note: cached entries derived from SECRET keys (the sign-path
precompute) hold key-equivalent material on device for the cache's
lifetime — the same trust boundary as the provider object itself, which
already holds the raw secret key in host memory.  Keys are identified by
SHA-256 of their bytes; raw key material never appears in stats or logs.

Thread-safety: lookups/inserts take a lock (queues dispatch from executor
threads); the miss-path compute runs OUTSIDE the lock because it may jit,
so two threads racing the same cold key may both compute — the second
insert wins, which is harmless (identical value) and cheaper than holding
a lock across a compile.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any


class DeviceOperandCache:
    """Content-hash-keyed LRU of per-key device operand pytrees."""

    def __init__(self, capacity: int = 8):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple[str, bytes], Any] = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def _key(kind: str, key_bytes: bytes) -> tuple[str, bytes]:
        return (kind, hashlib.sha256(key_bytes).digest())

    def lookup(self, kind: str, key_bytes: bytes) -> Any | None:
        """Cached state or None.  Deliberately a lookup/put split, not a
        compute-on-miss callback: the providers' miss path is a COMBINED
        program (op + precompute in one dispatch, e.g. kem.mlkem.
        encaps_cold) whose other outputs the caller needs — a callback
        could not return those."""
        k = self._key(kind, bytes(key_bytes))
        with self._lock:
            if k in self._entries:
                self._entries.move_to_end(k)
                self.hits += 1
                return self._entries[k]
            self.misses += 1
            return None

    def put(self, kind: str, key_bytes: bytes, val: Any) -> None:
        k = self._key(kind, bytes(key_bytes))
        with self._lock:
            self._entries[k] = val
            self._entries.move_to_end(k)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1

    def clear(self) -> int:
        """Drop every entry; returns how many were released (read and
        cleared under one lock hold, so the count is exact)."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            return n

    def zeroize(self) -> None:
        """End the cached keys' device-state lifetime (same convention as
        SecureLogger.zeroize / KeyStorage.lock).  Sign-path entries are
        KEY-EQUIVALENT material: an algorithm hot-swap or shutdown must not
        leave them pinned on device — dropping the references releases the
        buffers to the runtime (host code cannot overwrite device memory, so
        release is the strongest zeroization available here).  Called by
        SecureMessaging's hot-swap paths."""
        n = self.clear()
        # key-lifetime events belong in the flight ring: a dump after a
        # hot-swap shows WHEN the outgoing provider's device state was
        # released (counts only — never key identities)
        from ..obs import flight as _flight

        _flight.record("opcache_zeroized", entries=n)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }
