"""KEM algorithm providers over the cpu (pyref) and tpu (JAX) backends.

Mirrors the role of the reference's MLKEMKeyExchange / HQCKeyExchange /
FrodoKEMKeyExchange classes (crypto/key_exchange.py:57-449), each
parameterized by NIST security level 1/3/5 — but instead of constructing a
fresh liboqs FFI object per operation (crypto/key_exchange.py:155,178), ops
dispatch either to the pure-Python FIPS 203 reference (cpu) or to jitted
batched JAX programs (tpu).

Randomness policy: seeds are always drawn host-side from ``os.urandom`` and
fed to the deterministic keygen/encaps cores — the TPU never needs a CSPRNG,
and KATs can inject seeds through the same seam.
"""

from __future__ import annotations

import os

import numpy as np

from ..pyref import frodo_ref, hqc_ref, mlkem_ref
from .base import (KeyExchangeAlgorithm, cpu_impl_desc, expect_cols, expect_len,
                   make_provider_mesh, sliced_dispatch, try_native)

_LEVEL_TO_MLKEM = {1: mlkem_ref.MLKEM512, 3: mlkem_ref.MLKEM768, 5: mlkem_ref.MLKEM1024}

_LEVEL_TO_FRODO = {
    (1, True): frodo_ref.FRODO640AES,
    (1, False): frodo_ref.FRODO640SHAKE,
    (3, True): frodo_ref.FRODO976AES,
    (3, False): frodo_ref.FRODO976SHAKE,
    (5, True): frodo_ref.FRODO1344AES,
    (5, False): frodo_ref.FRODO1344SHAKE,
}


class MLKEMKeyExchange(KeyExchangeAlgorithm):
    """ML-KEM (FIPS 203) at NIST level 1, 3 or 5."""

    def __init__(self, security_level: int = 3, backend: str = "cpu",
                 devices: int = 0, opcache_size: int = 8):
        if security_level not in _LEVEL_TO_MLKEM:
            raise ValueError(f"ML-KEM level must be 1/3/5, got {security_level}")
        self.params = _LEVEL_TO_MLKEM[security_level]
        self.security_level = security_level
        self.backend = backend
        self.name = self.params.name
        self.display_name = f"{self.params.name} ({backend})"
        self.public_key_len = self.params.ek_len
        self.secret_key_len = self.params.dk_len
        self.ciphertext_len = self.params.ct_len
        #: device-resident per-key operand cache (tpu only): repeat encaps
        #: against the same peer key skip the ek re-upload (the tunnel is
        #: ~MB/s) and the ExpandA matrix expansion.  0 disables.
        self.opcache = None
        if backend == "tpu":
            from ..kem import mlkem as _jax_mlkem  # deferred: pulls in jax

            self._kg, self._enc, self._dec = _jax_mlkem.get(self.params.name)
            self._enc_cold, self._enc_pre = _jax_mlkem.get_pre(self.params.name)
            self._max_dispatch = _jax_mlkem.MAX_DEVICE_BATCH
            if opcache_size > 0:
                from .opcache import DeviceOperandCache

                self.opcache = DeviceOperandCache(opcache_size)
        self._mesh = make_provider_mesh(devices, backend)
        self._native = None
        if backend == "cpu":
            # Native C++ fast path (the role liboqs plays for the reference);
            # pyref remains the fallback and the oracle.
            self._native = try_native("NativeMLKEM", self.params.name)
        self.description = (
            f"Module-Lattice KEM, FIPS 203, NIST level {security_level}, "
            f"{'batched JAX/TPU' if backend == 'tpu' else cpu_impl_desc(self._native)} backend"
        )

    # -- scalar API (batch-of-1 on the tpu backend) -------------------------

    def generate_keypair(self) -> tuple[bytes, bytes]:
        pk, sk = self.generate_keypair_batch(1)
        return bytes(pk[0]), bytes(sk[0])

    def encapsulate(self, public_key: bytes) -> tuple[bytes, bytes]:
        expect_len(public_key, self.public_key_len, "public key", self.name)
        pk = np.frombuffer(public_key, dtype=np.uint8)[None]
        ct, ss = self.encapsulate_batch(pk)
        return bytes(ct[0]), bytes(ss[0])

    def decapsulate(self, secret_key: bytes, ciphertext: bytes) -> bytes:
        expect_len(secret_key, self.secret_key_len, "secret key", self.name)
        expect_len(ciphertext, self.ciphertext_len, "ciphertext", self.name)
        sk = np.frombuffer(secret_key, dtype=np.uint8)[None]
        ct = np.frombuffer(ciphertext, dtype=np.uint8)[None]
        return bytes(self.decapsulate_batch(sk, ct)[0])

    # -- batch API ----------------------------------------------------------

    def generate_keypair_batch(self, n: int) -> tuple[np.ndarray, np.ndarray]:
        d = np.frombuffer(os.urandom(32 * n), dtype=np.uint8).reshape(n, 32)
        z = np.frombuffer(os.urandom(32 * n), dtype=np.uint8).reshape(n, 32)
        if self.backend == "tpu":
            return sliced_dispatch(self._kg, self._max_dispatch, d, z,
                                   mesh=self._mesh)
        impl = self._native if self._native is not None else None
        pairs = [
            (impl.keygen(d[i].tobytes(), z[i].tobytes()) if impl
             else mlkem_ref.keygen(self.params, d[i].tobytes(), z[i].tobytes()))
            for i in range(n)
        ]
        return (
            np.stack([np.frombuffer(ek, np.uint8) for ek, _ in pairs]),
            np.stack([np.frombuffer(dk, np.uint8) for _, dk in pairs]),
        )

    def encapsulate_batch(self, public_keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        expect_cols(public_keys, self.public_key_len, "public keys", self.name)
        n = public_keys.shape[0]
        m = np.frombuffer(os.urandom(32 * n), dtype=np.uint8).reshape(n, 32)
        if self.backend == "tpu":
            pks = np.asarray(public_keys)
            if (
                self.opcache is not None
                and self._mesh is None
                and n <= self._max_dispatch
                and (n == 1 or (pks[0] == pks).all())
            ):
                # Single-key batch (every handshake encaps; swarm hot peers):
                # on a hit the key stays device-resident and ExpandA is
                # skipped; a miss runs the cache-filling combined program —
                # one dispatch either way, bit-identical output (the
                # precompute is a pure hoist, tests/test_fused.py).
                pkb = pks[0].tobytes()
                pre = self.opcache.lookup("ek", pkb)
                if pre is None:
                    pre, key, ct = self._enc_cold(pks[0], m)
                    self.opcache.put("ek", pkb, pre)
                else:
                    key, ct = self._enc_pre(pre, m)
                return np.asarray(ct), np.asarray(key)
            key, ct = sliced_dispatch(self._enc, self._max_dispatch,
                                      pks, m, mesh=self._mesh)
            return ct, key
        impl = self._native
        outs = [
            (impl.encaps(public_keys[i].tobytes(), m[i].tobytes()) if impl
             else mlkem_ref.encaps(self.params, public_keys[i].tobytes(), m[i].tobytes()))
            for i in range(n)
        ]
        return (
            np.stack([np.frombuffer(c, np.uint8) for _, c in outs]),
            np.stack([np.frombuffer(k, np.uint8) for k, _ in outs]),
        )

    def decapsulate_batch(self, secret_keys: np.ndarray, ciphertexts: np.ndarray) -> np.ndarray:
        expect_cols(secret_keys, self.secret_key_len, "secret keys", self.name)
        expect_cols(ciphertexts, self.ciphertext_len, "ciphertexts", self.name)
        if self.backend == "tpu":
            return sliced_dispatch(self._dec, self._max_dispatch,
                                   np.asarray(secret_keys), np.asarray(ciphertexts),
                                   mesh=self._mesh)
        impl = self._native
        return np.stack(
            [
                np.frombuffer(
                    (impl.decaps(secret_keys[i].tobytes(), ciphertexts[i].tobytes())
                     if impl
                     else mlkem_ref.decaps(
                         self.params, secret_keys[i].tobytes(), ciphertexts[i].tobytes()
                     )),
                    np.uint8,
                )
                for i in range(secret_keys.shape[0])
            ]
        )


class FrodoKEMKeyExchange(KeyExchangeAlgorithm):
    """FrodoKEM at NIST level 1, 3 or 5, AES or SHAKE matrix-gen variant.

    Mirrors the reference's FrodoKEMKeyExchange (crypto/key_exchange.py:312-449),
    including its use_aes flag; BASELINE.json config 3 targets the AES variant.
    """

    def __init__(self, security_level: int = 1, backend: str = "cpu",
                 use_aes: bool = True, devices: int = 0, opcache_size: int = 8):
        key = (security_level, use_aes)
        if key not in _LEVEL_TO_FRODO:
            raise ValueError(f"FrodoKEM level must be 1/3/5, got {security_level}")
        self.params = _LEVEL_TO_FRODO[key]
        self.security_level = security_level
        self.backend = backend
        self.use_aes = use_aes
        self.name = self.params.name
        self.display_name = f"{self.params.name} ({backend})"
        self.public_key_len = self.params.pk_len
        self.secret_key_len = self.params.sk_len
        self.ciphertext_len = self.params.ct_len
        self.shared_secret_len = self.params.len_sec
        #: device-resident per-key operand cache (tpu only): repeat encaps
        #: against the same peer key skip re-expanding the n x n matrix A
        #: from seedA — by far the dominant cost of a Frodo encaps.  0
        #: disables.
        self.opcache = None
        if backend == "tpu":
            from ..kem import frodo as _jax_frodo  # deferred: pulls in jax

            self._kg, self._enc, self._dec = _jax_frodo.get(self.params.name)
            self._enc_cold, self._enc_pre = _jax_frodo.get_pre(self.params.name)
            self._max_dispatch = _jax_frodo.MAX_DEVICE_BATCH
            if opcache_size > 0:
                from .opcache import DeviceOperandCache

                self.opcache = DeviceOperandCache(opcache_size)
        self._mesh = make_provider_mesh(devices, backend)
        self._native = None
        if backend == "cpu":
            # Native C++ fast path (the role liboqs plays for the reference);
            # pyref stays the fallback + oracle.
            self._native = try_native("NativeFrodoKEM", self.params.name)
        self.description = (
            f"Dense-LWE KEM (FrodoKEM round 3), NIST level {security_level}, "
            f"{'AES' if use_aes else 'SHAKE'} matrix generation, "
            f"{'batched JAX/TPU (MXU matmul)' if backend == 'tpu' else cpu_impl_desc(self._native)}"
            " backend"
        )

    def generate_keypair(self) -> tuple[bytes, bytes]:
        pk, sk = self.generate_keypair_batch(1)
        return bytes(pk[0]), bytes(sk[0])

    def encapsulate(self, public_key: bytes) -> tuple[bytes, bytes]:
        expect_len(public_key, self.public_key_len, "public key", self.name)
        ct, ss = self.encapsulate_batch(np.frombuffer(public_key, np.uint8)[None])
        return bytes(ct[0]), bytes(ss[0])

    def decapsulate(self, secret_key: bytes, ciphertext: bytes) -> bytes:
        expect_len(secret_key, self.secret_key_len, "secret key", self.name)
        expect_len(ciphertext, self.ciphertext_len, "ciphertext", self.name)
        sk = np.frombuffer(secret_key, np.uint8)[None]
        ct = np.frombuffer(ciphertext, np.uint8)[None]
        return bytes(self.decapsulate_batch(sk, ct)[0])

    def generate_keypair_batch(self, n: int):
        p = self.params
        sec = p.len_sec
        seeds = np.frombuffer(os.urandom(3 * sec * n), np.uint8).reshape(3, n, sec)
        if self.backend == "tpu":
            return sliced_dispatch(self._kg, self._max_dispatch, seeds[0], seeds[1], seeds[2],
                                   mesh=self._mesh)
        impl = self._native
        pairs = [
            (impl.keygen(seeds[0, i].tobytes(), seeds[1, i].tobytes(),
                         seeds[2, i].tobytes()) if impl
             else frodo_ref.keygen(p, seeds[0, i].tobytes(), seeds[1, i].tobytes(),
                                   seeds[2, i].tobytes()))
            for i in range(n)
        ]
        return (
            np.stack([np.frombuffer(pk, np.uint8) for pk, _ in pairs]),
            np.stack([np.frombuffer(sk, np.uint8) for _, sk in pairs]),
        )

    def encapsulate_batch(self, public_keys: np.ndarray):
        expect_cols(public_keys, self.public_key_len, "public keys", self.name)
        p = self.params
        n = public_keys.shape[0]
        mu = np.frombuffer(os.urandom(p.len_sec * n), np.uint8).reshape(n, p.len_sec)
        if self.backend == "tpu":
            pks = np.asarray(public_keys)
            if (
                self.opcache is not None
                and self._mesh is None
                and n <= self._max_dispatch
                and (n == 1 or (pks[0] == pks).all())
            ):
                # Single-key batch (every handshake encaps): on a hit the
                # expanded A matrix and unpacked B stay device-resident; a
                # miss runs the cache-filling combined program — one
                # dispatch either way, bit-identical output (the precompute
                # is a pure hoist, tests/test_frodo_pallas.py).
                pkb = pks[0].tobytes()
                pre = self.opcache.lookup("pk", pkb)
                if pre is None:
                    pre, ct, ss = self._enc_cold(pks[0], mu)
                    self.opcache.put("pk", pkb, pre)
                else:
                    ct, ss = self._enc_pre(pre, mu)
                return np.asarray(ct), np.asarray(ss)
            return sliced_dispatch(self._enc, self._max_dispatch,
                                   pks, mu, mesh=self._mesh)
        impl = self._native
        outs = [
            (impl.encaps(public_keys[i].tobytes(), mu[i].tobytes()) if impl
             else frodo_ref.encaps(p, public_keys[i].tobytes(), mu[i].tobytes()))
            for i in range(n)
        ]
        return (
            np.stack([np.frombuffer(c, np.uint8) for c, _ in outs]),
            np.stack([np.frombuffer(s, np.uint8) for _, s in outs]),
        )

    def decapsulate_batch(self, secret_keys: np.ndarray, ciphertexts: np.ndarray):
        expect_cols(secret_keys, self.secret_key_len, "secret keys", self.name)
        expect_cols(ciphertexts, self.ciphertext_len, "ciphertexts", self.name)
        p = self.params
        if self.backend == "tpu":
            return sliced_dispatch(self._dec, self._max_dispatch,
                                   np.asarray(secret_keys), np.asarray(ciphertexts),
                                   mesh=self._mesh)
        impl = self._native
        return np.stack(
            [
                np.frombuffer(
                    (impl.decaps(secret_keys[i].tobytes(), ciphertexts[i].tobytes())
                     if impl
                     else frodo_ref.decaps(
                         p, secret_keys[i].tobytes(), ciphertexts[i].tobytes()
                     )),
                    np.uint8,
                )
                for i in range(secret_keys.shape[0])
            ]
        )


class HQCKeyExchange(KeyExchangeAlgorithm):
    """HQC at NIST level 1, 3 or 5.

    Mirrors the reference's HQCKeyExchange (crypto/key_exchange.py:189-309).
    See pyref.hqc_ref's compatibility note: the PRNG seam is this framework's
    own (no liboqs binary exists in this environment to KAT against); cpu and
    tpu backends are bit-exact against each other.
    """

    def __init__(self, security_level: int = 1, backend: str = "cpu",
                 devices: int = 0):
        levels = {1: hqc_ref.HQC128, 3: hqc_ref.HQC192, 5: hqc_ref.HQC256}
        if security_level not in levels:
            raise ValueError(f"HQC level must be 1/3/5, got {security_level}")
        self.params = levels[security_level]
        self.security_level = security_level
        self.backend = backend
        self.name = self.params.name
        self.display_name = f"{self.params.name} ({backend})"
        self.public_key_len = self.params.pk_len
        self.secret_key_len = self.params.sk_len
        self.ciphertext_len = self.params.ct_len
        self.shared_secret_len = self.params.ss_len
        if backend == "tpu":
            from ..kem import hqc as _jax_hqc  # deferred: pulls in jax

            self._kg, self._enc, self._dec = _jax_hqc.get(self.params.name)
            self._max_dispatch = _jax_hqc.MAX_DEVICE_BATCH
        self._mesh = make_provider_mesh(devices, backend)
        self._native = None
        if backend == "cpu":
            # Native C++ fast path (the role liboqs plays for the reference);
            # pyref stays the fallback + oracle.
            self._native = try_native("NativeHQC", self.params.name)
        self.description = (
            f"Quasi-cyclic code-based KEM (HQC round 4 shape), NIST level "
            f"{security_level}, "
            f"{'batched JAX/TPU' if backend == 'tpu' else cpu_impl_desc(self._native)} backend"
        )

    def generate_keypair(self) -> tuple[bytes, bytes]:
        pk, sk = self.generate_keypair_batch(1)
        return bytes(pk[0]), bytes(sk[0])

    def encapsulate(self, public_key: bytes) -> tuple[bytes, bytes]:
        expect_len(public_key, self.public_key_len, "public key", self.name)
        ct, ss = self.encapsulate_batch(np.frombuffer(public_key, np.uint8)[None])
        return bytes(ct[0]), bytes(ss[0])

    def decapsulate(self, secret_key: bytes, ciphertext: bytes) -> bytes:
        expect_len(secret_key, self.secret_key_len, "secret key", self.name)
        expect_len(ciphertext, self.ciphertext_len, "ciphertext", self.name)
        sk = np.frombuffer(secret_key, np.uint8)[None]
        ct = np.frombuffer(ciphertext, np.uint8)[None]
        return bytes(self.decapsulate_batch(sk, ct)[0])

    def generate_keypair_batch(self, n: int):
        p = self.params
        sk_seed = np.frombuffer(os.urandom(40 * n), np.uint8).reshape(n, 40)
        sigma = np.frombuffer(os.urandom(p.k * n), np.uint8).reshape(n, p.k)
        pk_seed = np.frombuffer(os.urandom(40 * n), np.uint8).reshape(n, 40)
        if self.backend == "tpu":
            return sliced_dispatch(self._kg, self._max_dispatch, sk_seed, sigma, pk_seed,
                                   mesh=self._mesh)
        impl = self._native
        pairs = [
            (impl.keygen(sk_seed[i].tobytes(), sigma[i].tobytes(), pk_seed[i].tobytes())
             if impl
             else hqc_ref.keygen(p, sk_seed[i].tobytes(), sigma[i].tobytes(),
                                 pk_seed[i].tobytes()))
            for i in range(n)
        ]
        return (
            np.stack([np.frombuffer(pk, np.uint8) for pk, _ in pairs]),
            np.stack([np.frombuffer(sk, np.uint8) for _, sk in pairs]),
        )

    def encapsulate_batch(self, public_keys: np.ndarray):
        expect_cols(public_keys, self.public_key_len, "public keys", self.name)
        p = self.params
        n = public_keys.shape[0]
        m = np.frombuffer(os.urandom(p.k * n), np.uint8).reshape(n, p.k)
        salt = np.frombuffer(os.urandom(16 * n), np.uint8).reshape(n, 16)
        if self.backend == "tpu":
            return sliced_dispatch(self._enc, self._max_dispatch,
                                   np.asarray(public_keys), m, salt, mesh=self._mesh)
        impl = self._native
        outs = [
            (impl.encaps(public_keys[i].tobytes(), m[i].tobytes(), salt[i].tobytes())
             if impl
             else hqc_ref.encaps(p, public_keys[i].tobytes(), m[i].tobytes(),
                                 salt[i].tobytes()))
            for i in range(n)
        ]
        return (
            np.stack([np.frombuffer(c, np.uint8) for c, _ in outs]),
            np.stack([np.frombuffer(s, np.uint8) for _, s in outs]),
        )

    def decapsulate_batch(self, secret_keys: np.ndarray, ciphertexts: np.ndarray):
        expect_cols(secret_keys, self.secret_key_len, "secret keys", self.name)
        expect_cols(ciphertexts, self.ciphertext_len, "ciphertexts", self.name)
        p = self.params
        if self.backend == "tpu":
            return sliced_dispatch(self._dec, self._max_dispatch,
                                   np.asarray(secret_keys), np.asarray(ciphertexts),
                                   mesh=self._mesh)
        impl = self._native
        return np.stack(
            [
                np.frombuffer(
                    (impl.decaps(secret_keys[i].tobytes(), ciphertexts[i].tobytes())
                     if impl
                     else hqc_ref.decaps(
                         p, secret_keys[i].tobytes(), ciphertexts[i].tobytes()
                     )),
                    np.uint8,
                )
                for i in range(secret_keys.shape[0])
            ]
        )
