"""KEM algorithm providers over the cpu (pyref) and tpu (JAX) backends.

Mirrors the role of the reference's MLKEMKeyExchange / HQCKeyExchange /
FrodoKEMKeyExchange classes (crypto/key_exchange.py:57-449), each
parameterized by NIST security level 1/3/5 — but instead of constructing a
fresh liboqs FFI object per operation (crypto/key_exchange.py:155,178), ops
dispatch either to the pure-Python FIPS 203 reference (cpu) or to jitted
batched JAX programs (tpu).

Randomness policy: seeds are always drawn host-side from ``os.urandom`` and
fed to the deterministic keygen/encaps cores — the TPU never needs a CSPRNG,
and KATs can inject seeds through the same seam.
"""

from __future__ import annotations

import os

import numpy as np

from ..pyref import mlkem_ref
from .base import KeyExchangeAlgorithm

_LEVEL_TO_MLKEM = {1: mlkem_ref.MLKEM512, 3: mlkem_ref.MLKEM768, 5: mlkem_ref.MLKEM1024}


class MLKEMKeyExchange(KeyExchangeAlgorithm):
    """ML-KEM (FIPS 203) at NIST level 1, 3 or 5."""

    def __init__(self, security_level: int = 3, backend: str = "cpu"):
        if security_level not in _LEVEL_TO_MLKEM:
            raise ValueError(f"ML-KEM level must be 1/3/5, got {security_level}")
        self.params = _LEVEL_TO_MLKEM[security_level]
        self.security_level = security_level
        self.backend = backend
        self.name = self.params.name
        self.display_name = f"{self.params.name} ({backend})"
        self.description = (
            f"Module-Lattice KEM, FIPS 203, NIST level {security_level}, "
            f"{'batched JAX/TPU' if backend == 'tpu' else 'pure-Python CPU'} backend"
        )
        self.public_key_len = self.params.ek_len
        self.secret_key_len = self.params.dk_len
        self.ciphertext_len = self.params.ct_len
        if backend == "tpu":
            from ..kem import mlkem as _jax_mlkem  # deferred: pulls in jax

            self._kg, self._enc, self._dec = _jax_mlkem.get(self.params.name)

    # -- scalar API (batch-of-1 on the tpu backend) -------------------------

    def generate_keypair(self) -> tuple[bytes, bytes]:
        pk, sk = self.generate_keypair_batch(1)
        return bytes(pk[0]), bytes(sk[0])

    def encapsulate(self, public_key: bytes) -> tuple[bytes, bytes]:
        pk = np.frombuffer(public_key, dtype=np.uint8)[None]
        ct, ss = self.encapsulate_batch(pk)
        return bytes(ct[0]), bytes(ss[0])

    def decapsulate(self, secret_key: bytes, ciphertext: bytes) -> bytes:
        sk = np.frombuffer(secret_key, dtype=np.uint8)[None]
        ct = np.frombuffer(ciphertext, dtype=np.uint8)[None]
        return bytes(self.decapsulate_batch(sk, ct)[0])

    # -- batch API ----------------------------------------------------------

    def generate_keypair_batch(self, n: int) -> tuple[np.ndarray, np.ndarray]:
        d = np.frombuffer(os.urandom(32 * n), dtype=np.uint8).reshape(n, 32)
        z = np.frombuffer(os.urandom(32 * n), dtype=np.uint8).reshape(n, 32)
        if self.backend == "tpu":
            ek, dk = self._kg(d, z)
            return np.asarray(ek), np.asarray(dk)
        pairs = [
            mlkem_ref.keygen(self.params, d[i].tobytes(), z[i].tobytes()) for i in range(n)
        ]
        return (
            np.stack([np.frombuffer(ek, np.uint8) for ek, _ in pairs]),
            np.stack([np.frombuffer(dk, np.uint8) for _, dk in pairs]),
        )

    def encapsulate_batch(self, public_keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        n = public_keys.shape[0]
        m = np.frombuffer(os.urandom(32 * n), dtype=np.uint8).reshape(n, 32)
        if self.backend == "tpu":
            key, ct = self._enc(public_keys, m)
            return np.asarray(ct), np.asarray(key)
        outs = [
            mlkem_ref.encaps(self.params, public_keys[i].tobytes(), m[i].tobytes())
            for i in range(n)
        ]
        return (
            np.stack([np.frombuffer(c, np.uint8) for _, c in outs]),
            np.stack([np.frombuffer(k, np.uint8) for k, _ in outs]),
        )

    def decapsulate_batch(self, secret_keys: np.ndarray, ciphertexts: np.ndarray) -> np.ndarray:
        if self.backend == "tpu":
            return np.asarray(self._dec(secret_keys, ciphertexts))
        return np.stack(
            [
                np.frombuffer(
                    mlkem_ref.decaps(
                        self.params, secret_keys[i].tobytes(), ciphertexts[i].tobytes()
                    ),
                    np.uint8,
                )
                for i in range(secret_keys.shape[0])
            ]
        )
