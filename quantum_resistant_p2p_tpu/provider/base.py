"""Abstract algorithm interfaces — the plugin boundary.

Same surface the reference exposes so application code (and users migrating
from it) find the familiar operations:
  * KEM:       generate_keypair / encapsulate / decapsulate
               (reference: crypto/key_exchange.py:19-54)
  * Signature: sign / verify            (reference: crypto/signatures.py:18-55)
  * AEAD:      encrypt / decrypt        (reference: crypto/symmetric.py:19-63)

Additions over the reference: every algorithm reports its ``backend`` ("cpu"
or "tpu") and offers ``*_batch`` operations with ``(batch, ...)`` numpy arrays
— the TPU backends implement these natively and the scalar ops are the
batch-of-1 special case, which is the inversion that makes 50k ops/s possible.
"""

from __future__ import annotations

import abc
import logging
from typing import Any

import numpy as np


def try_native(class_name: str, algo_name: str):
    """Instantiate a native-core wrapper (NativeMLKEM/NativeMLDSA/...), or
    None with a logged warning when the C++ fast path is unavailable —
    callers fall back to the pure-Python pyref implementations."""
    try:
        from .. import native as _native

        return getattr(_native, class_name)(algo_name)
    except Exception as e:
        logging.getLogger(__name__).warning(
            "%s: native fast path unavailable, using pure-Python fallback "
            "(orders of magnitude slower): %s",
            algo_name,
            e,
        )
        return None


def cpu_impl_desc(native_obj) -> str:
    """Truthful description of which cpu implementation actually runs."""
    return "native C++ CPU" if native_obj is not None else "pure-Python CPU"


from ..utils import next_pow2  # noqa: E402  (canonical shared helper)


def pad_rows(rows: np.ndarray, target: int) -> np.ndarray:
    """Pad the batch dim to ``target`` by repeating the last row.

    Device batches are padded to power-of-two buckets so XLA compiles at most
    log2(max_batch) program variants per op instead of one per batch size —
    without this, a cold queue spends tens of seconds per novel size.
    """
    n = rows.shape[0]
    if n == target:
        return rows
    pad = np.broadcast_to(rows[-1:], (target - n,) + rows.shape[1:])
    return np.concatenate([np.asarray(rows), pad], axis=0)


def mesh_dispatch(fn, mesh, *arrays):
    """Run a jitted batch fn with the batch axis sharded across ``mesh``.

    TPU-native scale-out for embarrassingly parallel crypto batches
    (SURVEY.md §2.3): operands are placed with a batch-axis NamedSharding and
    the computation follows the data — GSPMD partitions the already-jitted
    program across the mesh with zero cross-chip collectives on the hot path
    (each chip runs its shard of keygen/encaps/decaps/sign/verify locally).

    The batch is padded (last row repeated) to ``n_devices * pow2`` so every
    device receives an equal, compile-cached shard; results gather on the
    host and are trimmed.  Non-divisible batches therefore cost at most the
    pad rows, never a recompile.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    n = arrays[0].shape[0]
    if n == 0:  # pad_rows cannot repeat a row of an empty batch
        out = fn(*arrays)
        if isinstance(out, tuple):
            return tuple(np.asarray(o) for o in out)
        return np.asarray(out)
    ndev = mesh.size
    tgt = ndev * next_pow2(-(-n // ndev))
    sh = NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))
    parts = [jax.device_put(pad_rows(np.asarray(a), tgt), sh) for a in arrays]
    out = fn(*parts)
    if isinstance(out, tuple):
        return tuple(np.asarray(o)[:n] for o in out)
    return np.asarray(out)[:n]


def sliced_dispatch(fn, step: int, *arrays, mesh=None):
    """Run a jitted batch fn in ``step``-row slices and concatenate.

    Two reasons to slice device batches: FrodoKEM dispatches >= 1024 crash
    this environment's TPU worker (kem/frodo.py), and ML-KEM throughput peaks
    well below the queue's max batch (working set vs HBM/caches — see
    bench_report.md's scaling curve).  A non-divisible tail is padded to a
    full slice (last row repeated) so every dispatch hits an already-compiled
    shape, then trimmed.

    Slices are DOUBLE-BUFFERED: slice N+1 is dispatched before slice N's
    host readback, so the next slice's upload + compute overlaps the
    previous readback instead of serialising behind it (jax dispatch is
    async; ``np.asarray`` is the sync point).  Holding exactly one
    in-flight slice bounds device memory to two slices' outputs, where an
    eager dispatch-all would pin every slice of an arbitrarily large queue
    flush.

    With a ``mesh``, each slice is sharded across the mesh's devices via
    ``mesh_dispatch`` and ``step`` is the PER-DEVICE cap, so one dispatch
    covers ``step * mesh.size`` rows.  (mesh_dispatch gathers to numpy
    internally, so mesh slices do not pipeline.)
    """
    n = arrays[0].shape[0]
    if mesh is not None:
        cap = step * mesh.size
        if n <= cap:
            return mesh_dispatch(fn, mesh, *arrays)
        one = lambda *xs: mesh_dispatch(fn, mesh, *xs)  # noqa: E731
    else:
        cap = step
        if n <= cap:
            out = fn(*arrays)
            return (
                tuple(np.asarray(o) for o in out)
                if isinstance(out, tuple)
                else np.asarray(out)
            )
        one = fn

    def slice_of(a, i):
        return pad_rows(a[i : i + cap], cap)

    def read_back(p):
        return (
            tuple(np.asarray(o) for o in p) if isinstance(p, tuple) else np.asarray(p)
        )

    parts = []
    in_flight = None
    for i in range(0, n, cap):
        nxt = one(*(slice_of(a, i) for a in arrays))  # dispatch slice i ...
        if in_flight is not None:
            parts.append(read_back(in_flight))  # ... before reading slice i-1
        in_flight = nxt
    parts.append(read_back(in_flight))
    if isinstance(parts[0], tuple):
        return tuple(
            np.concatenate([p[j] for p in parts])[:n] for j in range(len(parts[0]))
        )
    return np.concatenate(parts)[:n]


def make_provider_mesh(devices: int, backend: str):
    """Build the provider-internal device mesh, or None when disabled.

    ``devices`` comes from Config.mesh_devices / the registry ``devices=``
    knob: 0 = single-device (default), N = 1-D mesh over the first N visible
    devices (make_mesh raises when fewer exist), -1 = all visible devices.
    Only the tpu backend shards; the cpu path never imports jax.
    """
    if not devices or backend != "tpu":
        return None
    from ..parallel.mesh import make_mesh

    return make_mesh(None if devices < 0 else devices)


class CryptoAlgorithm(abc.ABC):
    """Common metadata for all algorithms (reference: crypto/algorithm_base.py).

    Every concrete subclass's scalar ops (generate_keypair / encapsulate /
    decapsulate / sign / verify / encrypt / decrypt) are instrumented with
    the deterministic fault-injection hook (faults/) at class-creation time
    — one module-global ``None`` check per call when no plan is installed,
    so chaos tests never monkeypatch a provider.
    """

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        from ..faults import instrument_scalar_ops

        instrument_scalar_ops(cls)

    #: canonical registry name, e.g. "ML-KEM-768"
    name: str = ""
    #: human-readable name for UIs / settings gossip
    display_name: str = ""
    description: str = ""
    #: NIST security level (1/3/5)
    security_level: int = 0
    #: "cpu" (pure-Python reference) or "tpu" (batched JAX)
    backend: str = "cpu"

    @property
    def is_using_mock(self) -> bool:
        # Parity with crypto/algorithm_base.py:30-33 — mock crypto is never used.
        return False

    @property
    def actual_variant(self) -> str:
        return self.name

    def get_security_info(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "display_name": self.display_name,
            "description": self.description,
            "security_level": self.security_level,
            "backend": self.backend,
            "mock": self.is_using_mock,
        }


class KeyExchangeAlgorithm(CryptoAlgorithm):
    """KEM interface; byte-level scalar API + array-level batch API."""

    public_key_len: int = 0
    secret_key_len: int = 0
    ciphertext_len: int = 0
    shared_secret_len: int = 32

    @abc.abstractmethod
    def generate_keypair(self) -> tuple[bytes, bytes]:
        """-> (public_key, secret_key)"""

    @abc.abstractmethod
    def encapsulate(self, public_key: bytes) -> tuple[bytes, bytes]:
        """-> (ciphertext, shared_secret)"""

    @abc.abstractmethod
    def decapsulate(self, secret_key: bytes, ciphertext: bytes) -> bytes:
        """-> shared_secret"""

    # -- batch API (TPU-native path; default = loop over the scalar API) ----

    def generate_keypair_batch(self, n: int) -> tuple[np.ndarray, np.ndarray]:
        pks, sks = zip(*(self.generate_keypair() for _ in range(n)))
        return _stack_bytes(pks), _stack_bytes(sks)

    def encapsulate_batch(self, public_keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        cts, sss = zip(*(self.encapsulate(bytes(pk)) for pk in public_keys))
        return _stack_bytes(cts), _stack_bytes(sss)

    def decapsulate_batch(self, secret_keys: np.ndarray, ciphertexts: np.ndarray) -> np.ndarray:
        return _stack_bytes(
            [self.decapsulate(bytes(sk), bytes(ct)) for sk, ct in zip(secret_keys, ciphertexts)]
        )


class SignatureAlgorithm(CryptoAlgorithm):
    """Signature interface; verify returns False on any failure, never raises."""

    public_key_len: int = 0
    secret_key_len: int = 0
    signature_len: int = 0  # maximum length where variable

    @abc.abstractmethod
    def generate_keypair(self) -> tuple[bytes, bytes]:
        """-> (public_key, secret_key)"""

    def generate_keypair_batch(self, n: int) -> tuple[np.ndarray, np.ndarray]:
        """-> (public_keys (n, pk_len), secret_keys (n, sk_len)) uint8.

        Default loops the scalar path; batched backends override (the KEM
        interface's counterpart is abstract, but signature keypairs are
        long-lived so most callers never need the batch form)."""
        pairs = [self.generate_keypair() for _ in range(n)]
        return (
            np.stack([np.frombuffer(pk, np.uint8) for pk, _ in pairs]),
            np.stack([np.frombuffer(sk, np.uint8) for _, sk in pairs]),
        )

    @abc.abstractmethod
    def sign(self, secret_key: bytes, message: bytes) -> bytes:
        """-> signature"""

    @abc.abstractmethod
    def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        """-> True iff the signature is valid (exceptions map to False)"""

    def sign_batch(self, secret_keys: np.ndarray, messages: list[bytes]) -> list[bytes]:
        return [self.sign(bytes(sk), m) for sk, m in zip(secret_keys, messages)]

    def verify_batch(
        self, public_keys: np.ndarray, messages: list[bytes], signatures: list[bytes]
    ) -> np.ndarray:
        return np.array(
            [self.verify(bytes(pk), m, s) for pk, m, s in zip(public_keys, messages, signatures)]
        )


class FusedHandshakeOps(abc.ABC):
    """Optional capability: composite device programs for a (KEM, signature)
    provider pair, fusing what one handshake step executes back-to-back
    (kem op + transcript hash + signature op) into a single dispatch.

    Discovered through ``provider.registry.get_fused(kem, sig)`` — ``None``
    (capability absent: unregistered pair, or either provider not on the
    tpu backend) means callers stay on the per-op path; the wire protocol
    is identical either way.  ``templates`` are canonical transcript bytes
    with a zeroed gap at the given static offset where the device
    hex-encodes its own output (fresh public key / ciphertext) before
    hashing; ``msgs_in``/``msgs_out`` are fully host-known transcripts.

    Signature ops follow the provider conventions: sign raises when a lane
    exhausts its rejection budget, verify maps any failure to False.
    """

    kem: KeyExchangeAlgorithm
    sig: SignatureAlgorithm
    name: str = ""
    backend: str = "tpu"
    #: per-kind template capacity (static compiled buffer widths); callers
    #: fall back to the per-op path for transcripts that exceed them
    init_template_len: int = 0
    resp_template_len: int = 0

    @abc.abstractmethod
    def keygen_sign_batch(self, sig_sks: np.ndarray, templates: list[bytes],
                          pk_off: int, rnd=None):
        """-> (public_keys (n, pk_len), secret_keys (n, sk_len),
        sigs list[bytes]) — KEM keygen + sign(template with hex(pk) at
        ``pk_off``)."""

    @abc.abstractmethod
    def encaps_verify_sign_batch(self, public_keys: np.ndarray,
                                 peer_sig_pks: np.ndarray,
                                 msgs_in: list[bytes], sigs_in: list[bytes],
                                 sig_sks: np.ndarray, templates: list[bytes],
                                 ct_off: int, m=None, rnd=None):
        """-> (oks (n,) bool, cts, shared_secrets, sigs list[bytes]) —
        verify(msgs_in) + KEM encaps + sign(template with hex(ct) at
        ``ct_off``)."""

    @abc.abstractmethod
    def decaps_verify_sign_batch(self, secret_keys: np.ndarray,
                                 ciphertexts: np.ndarray,
                                 peer_sig_pks: np.ndarray,
                                 msgs_in: list[bytes], sigs_in: list[bytes],
                                 sig_sks: np.ndarray, msgs_out: list[bytes],
                                 rnd=None):
        """-> (oks (n,) bool, shared_secrets, sigs list[bytes]) —
        verify(msgs_in) + KEM decaps + sign(msgs_out)."""

    def warmup(self, sizes: tuple[int, ...] = (1,), pk_off: int | None = None,
               ct_off: int | None = None) -> None:
        """Pre-compile the composite programs (blocking; run off-loop).
        Offsets must match the live transcripts' — jit keys on them."""


class SymmetricAlgorithm(CryptoAlgorithm):
    """AEAD interface (scalar; the per-message CPU path).

    The batched device path is a SEPARATE optional capability
    (:class:`BatchedAEADOps`, discovered via
    ``provider.registry.get_batched_aead``) — the scalar ops here stay the
    universal fallback and the wire-format authority: 12-byte nonce
    prepended to ``ciphertext || tag``.
    """

    key_size: int = 32
    nonce_size: int = 12

    @abc.abstractmethod
    def encrypt(self, key: bytes, plaintext: bytes, associated_data: bytes | None = None) -> bytes:
        """-> nonce || ciphertext || tag"""

    @abc.abstractmethod
    def decrypt(self, key: bytes, data: bytes, associated_data: bytes | None = None) -> bytes:
        """-> plaintext; raises ValueError on authentication failure"""

    def seal(self, key: bytes, nonce: bytes, plaintext: bytes,
             associated_data: bytes | None = None) -> bytes:
        """Deterministic-nonce seal: -> ``ciphertext || tag`` (no nonce
        prefix).  The primitive both the batched facade's cpu fallback and
        the device cross-check tests need; ``encrypt`` is ``urandom nonce +
        seal``.  Default raises — concrete AEADs override."""
        raise NotImplementedError(f"{self.name} has no deterministic seal")

    def open_(self, key: bytes, nonce: bytes, data: bytes,
              associated_data: bytes | None = None) -> bytes:
        """Deterministic-nonce open of ``ciphertext || tag``; ValueError on
        authentication failure.  Default raises — concrete AEADs override."""
        raise NotImplementedError(f"{self.name} has no deterministic open")


class BatchedAEADOps(abc.ABC):
    """Optional capability: batched device seal/open for one AEAD.

    Discovered through ``provider.registry.get_batched_aead(symmetric)`` —
    ``None`` (capability absent: unregistered AEAD, jax unavailable, or
    ``QRP2P_BATCH_AEAD=0``) keeps every caller on the scalar
    :class:`SymmetricAlgorithm` path; the wire format is identical either
    way (the facade prepends the same random 12-byte nonce the scalar
    ``encrypt`` does).

    Array conventions: keys/nonces are ``(n, key_size)`` / ``(n,
    nonce_size)`` uint8 rows; messages and AADs are ragged lists of
    bytes-like objects (``memoryview`` welcome — the binary wire path hands
    socket-buffer views straight through).  Implementations pad to pow2
    length buckets with masked tails, so one flush costs one device
    program per (batch, length, aad) bucket triple.  Per-item
    authentication failures are reported as ``ValueError`` INSTANCES in
    the result list (the provider/batched.py per-item failure convention),
    never raised — one tampered ciphertext must not poison its batch
    mates.
    """

    name: str = ""
    backend: str = "tpu"
    key_size: int = 32
    nonce_size: int = 12
    tag_size: int = 16
    #: longest message / AAD the device bucket space serves; callers route
    #: longer items to the scalar path (bounded compile count + memory)
    max_len: int = 1 << 20
    max_aad_len: int = 1 << 16

    @abc.abstractmethod
    def seal_batch(self, keys: np.ndarray, nonces: np.ndarray,
                   plaintexts: list, aads: list) -> list[bytes]:
        """-> per-item ``ciphertext || tag``."""

    @abc.abstractmethod
    def open_batch(self, keys: np.ndarray, nonces: np.ndarray,
                   data: list, aads: list) -> list:
        """``data`` items are ``ciphertext || tag``; -> per-item plaintext
        bytes, or a ``ValueError`` instance where authentication failed."""


def _stack_bytes(items) -> np.ndarray:
    return np.stack([np.frombuffer(b, dtype=np.uint8) for b in items])


def expect_len(buf: bytes, expected: int, what: str, algo: str) -> None:
    """Reject wrong-length attacker-controlled material BEFORE it reaches a
    backend.  The native C++ core reads exactly ``expected`` bytes from the
    buffer it is handed, so an unchecked short input is a heap out-of-bounds
    read; the JAX backends would raise an opaque reshape error instead of a
    protocol-level one.  Raises ValueError (which the messaging layer maps to
    a typed rejection)."""
    if len(buf) != expected:
        raise ValueError(f"{algo}: {what} must be {expected} bytes, got {len(buf)}")


def expect_cols(arr: np.ndarray, expected: int, what: str, algo: str) -> None:
    """Batch-array analog of expect_len: trailing dim must match exactly."""
    if arr.ndim != 2 or arr.shape[1] != expected:
        raise ValueError(
            f"{algo}: batched {what} must have shape (n, {expected}), got {arr.shape}"
        )
