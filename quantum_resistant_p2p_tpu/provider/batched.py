"""Async batching queue — the host<->TPU boundary (the north-star refactor).

The reference performs one blocking liboqs FFI call per handshake op
(crypto/key_exchange.py:155,178).  Here, concurrent handshakes enqueue their
crypto ops as futures; a flusher collects them into one padded batch and
dispatches a single jitted TPU program, then resolves every future.  Flush
policy: immediately at ``max_batch``, otherwise ``max_wait_ms`` after the
first enqueue — bounding added p50 latency while amortising dispatch overhead
(SURVEY.md §7.4 item 6).

The dispatch itself runs in a worker thread (``run_in_executor``) so the
asyncio loop — which is also serving TCP peers (net.p2p_node) — never blocks
on device compute.

Wrapper classes expose the same op names as the plugin boundary
(KeyExchangeAlgorithm / SignatureAlgorithm, provider.base) but as coroutines;
``SecureMessaging`` awaits them on its handshake path (app/messaging.py here;
reference flow app/messaging.py:546-1134).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from ..utils.profiling import LatencyHistogram
from .base import (KeyExchangeAlgorithm, SignatureAlgorithm,
                   next_pow2 as _next_pow2, pad_rows as _pad_rows)


@dataclass
class QueueStats:
    """Per-op-queue counters (surfaced in metrics; SURVEY.md §5 tracing gap)."""

    ops: int = 0
    flushes: int = 0
    max_batch_seen: int = 0
    total_wait_s: float = 0.0
    total_dispatch_s: float = 0.0
    #: per-flush batch sizes, most recent last (bounded)
    batch_sizes: list[int] = field(default_factory=list)
    #: per-flush dispatch latency percentiles (utils.profiling)
    dispatch_hist: LatencyHistogram = field(default_factory=LatencyHistogram)
    BATCH_SIZE_HISTORY = 1024

    def as_dict(self) -> dict[str, Any]:
        h = self.dispatch_hist
        return {
            "ops": self.ops,
            "flushes": self.flushes,
            "max_batch_seen": self.max_batch_seen,
            "avg_batch": (self.ops / self.flushes) if self.flushes else 0.0,
            "avg_dispatch_ms": (
                1e3 * self.total_dispatch_s / self.flushes if self.flushes else 0.0
            ),
            "p50_dispatch_ms": round(1e3 * (h.percentile(50) or 0.0), 3),
            "p99_dispatch_ms": round(1e3 * (h.percentile(99) or 0.0), 3),
        }


class OpQueue:
    """Accumulates (item -> future) pairs; flushes through a batch function.

    ``batch_fn(items) -> list[results]`` is called with at most ``max_batch``
    items, inside the default executor.
    """

    def __init__(
        self,
        batch_fn: Callable[[list[Any]], list[Any]],
        max_batch: int = 4096,
        max_wait_ms: float = 2.0,
    ):
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.stats = QueueStats()
        self._items: list[Any] = []
        self._futures: list[asyncio.Future] = []
        self._timer: asyncio.TimerHandle | None = None
        self._first_enqueue_t = 0.0

    async def submit(self, item: Any) -> Any:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._items.append(item)
        self._futures.append(fut)
        self.stats.ops += 1
        if len(self._items) == 1:
            self._first_enqueue_t = time.perf_counter()
            self._timer = loop.call_later(self.max_wait_s, self._flush_soon)
        if len(self._items) >= self.max_batch:
            self._flush_soon()
        return await fut

    def _flush_soon(self) -> None:
        """Detach pending items synchronously (so late submits can't bloat a
        batch past max_batch) and dispatch them as a task."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        loop = asyncio.get_running_loop()
        while self._items:
            items = self._items[: self.max_batch]
            futs = self._futures[: self.max_batch]
            del self._items[: self.max_batch]
            del self._futures[: self.max_batch]
            loop.create_task(self._dispatch(items, futs, self._first_enqueue_t))

    async def _dispatch(self, items: list[Any], futs: list[asyncio.Future],
                        first_t: float) -> None:
        self.stats.flushes += 1
        self.stats.max_batch_seen = max(self.stats.max_batch_seen, len(items))
        self.stats.batch_sizes.append(len(items))
        del self.stats.batch_sizes[: -QueueStats.BATCH_SIZE_HISTORY]
        self.stats.total_wait_s += time.perf_counter() - first_t
        t0 = time.perf_counter()
        loop = asyncio.get_running_loop()
        try:
            results = await loop.run_in_executor(None, self.batch_fn, items)
            dt = time.perf_counter() - t0
            self.stats.total_dispatch_s += dt
            self.stats.dispatch_hist.record(dt)
            for f, r in zip(futs, results):
                if f.cancelled():
                    continue
                # batch fns report per-item failures as Exception instances so
                # one bad item doesn't poison its batch mates
                if isinstance(r, Exception):
                    f.set_exception(r)
                else:
                    f.set_result(r)
        except Exception as exc:  # propagate to every waiter
            for f in futs:
                if not f.cancelled():
                    f.set_exception(exc)


def _run_valid(items, is_valid, dispatch, invalid_result):
    """Shared filter-pad-dispatch-scatter skeleton for the batch fns.

    ``is_valid(item) -> bool`` selects items safe to stack; ``dispatch(valid
    items, pow2 target) -> per-item results`` runs the padded device batch;
    invalid slots get ``invalid_result()`` so one attacker-supplied ragged
    input never poisons its batch mates.
    """
    valid_idx = [i for i, it in enumerate(items) if is_valid(it)]
    results = [invalid_result() for _ in items]
    if valid_idx:
        tgt = _next_pow2(len(valid_idx))
        out = dispatch([items[i] for i in valid_idx], tgt)
        for j, i in enumerate(valid_idx):
            results[i] = out[j]
    return results


class BatchedKEM:
    """Async facade over a KeyExchangeAlgorithm's batch ops."""

    def __init__(self, algo: KeyExchangeAlgorithm, max_batch: int = 4096,
                 max_wait_ms: float = 2.0):
        self.algo = algo
        self.name = algo.name
        self._kg = OpQueue(self._kg_batch, max_batch, max_wait_ms)
        self._enc = OpQueue(self._enc_batch, max_batch, max_wait_ms)
        self._dec = OpQueue(self._dec_batch, max_batch, max_wait_ms)

    def _kg_batch(self, items: list[None]) -> list[tuple[bytes, bytes]]:
        n = len(items)
        pks, sks = self.algo.generate_keypair_batch(_next_pow2(n))
        return [(bytes(pk), bytes(sk)) for pk, sk in zip(pks[:n], sks[:n])]

    def _enc_batch(self, items: list[bytes]):
        def dispatch(valid, tgt):
            pks = _pad_rows(np.stack([np.frombuffer(pk, np.uint8) for pk in valid]), tgt)
            cts, sss = self.algo.encapsulate_batch(pks)
            return [(bytes(ct), bytes(ss)) for ct, ss in zip(cts, sss)]

        return _run_valid(
            items,
            lambda pk: len(pk) == self.algo.public_key_len,
            dispatch,
            lambda: ValueError("bad public-key length"),
        )

    def _dec_batch(self, items: list[tuple[bytes, bytes]]):
        def dispatch(valid, tgt):
            sks = _pad_rows(np.stack([np.frombuffer(sk, np.uint8) for sk, _ in valid]), tgt)
            cts = _pad_rows(np.stack([np.frombuffer(ct, np.uint8) for _, ct in valid]), tgt)
            return [bytes(ss) for ss in self.algo.decapsulate_batch(sks, cts)]

        return _run_valid(
            items,
            lambda it: (
                len(it[0]) == self.algo.secret_key_len
                and len(it[1]) == self.algo.ciphertext_len
            ),
            dispatch,
            lambda: ValueError("bad secret-key/ciphertext length"),
        )

    def warmup(self, sizes: tuple[int, ...] = (1,)) -> None:
        """Compile the pow2 buckets a live queue will hit (blocking; run in a
        background thread).  Cold jit of the first handshake's size-1 bucket
        otherwise races the protocol timeout (SURVEY.md §7.4 item 6)."""
        for n in sizes:
            pks, sks = self.algo.generate_keypair_batch(n)
            cts, _ = self.algo.encapsulate_batch(pks)
            self.algo.decapsulate_batch(sks, cts)

    async def generate_keypair(self) -> tuple[bytes, bytes]:
        return await self._kg.submit(None)

    async def encapsulate(self, public_key: bytes) -> tuple[bytes, bytes]:
        return await self._enc.submit(public_key)

    async def decapsulate(self, secret_key: bytes, ciphertext: bytes) -> bytes:
        return await self._dec.submit((secret_key, ciphertext))

    def stats(self) -> dict[str, Any]:
        return {
            "keygen": self._kg.stats.as_dict(),
            "encaps": self._enc.stats.as_dict(),
            "decaps": self._dec.stats.as_dict(),
        }


class BatchedSignature:
    """Async facade over a SignatureAlgorithm's batch ops."""

    def __init__(self, algo: SignatureAlgorithm, max_batch: int = 4096,
                 max_wait_ms: float = 2.0):
        self.algo = algo
        self.name = algo.name
        self._sign = OpQueue(self._sign_batch, max_batch, max_wait_ms)
        self._verify = OpQueue(self._verify_batch, max_batch, max_wait_ms)

    def _sign_batch(self, items: list[tuple[bytes, bytes]]):
        def dispatch(valid, tgt):
            sks = _pad_rows(np.stack([np.frombuffer(sk, np.uint8) for sk, _ in valid]), tgt)
            msgs = [m for _, m in valid] + [valid[-1][1]] * (tgt - len(valid))
            return self.algo.sign_batch(sks, msgs)

        return _run_valid(
            items,
            lambda it: len(it[0]) == self.algo.secret_key_len,
            dispatch,
            lambda: ValueError("bad secret-key length"),
        )

    def _verify_batch(self, items: list[tuple[bytes, bytes, bytes]]) -> list[bool]:
        # Per the verify contract, malformed input means False — never raise.
        def dispatch(valid, tgt):
            pks = _pad_rows(np.stack([np.frombuffer(pk, np.uint8) for pk, _, _ in valid]), tgt)
            pad = tgt - len(valid)
            msgs = [m for _, m, _ in valid] + [valid[-1][1]] * pad
            sigs = [s for _, _, s in valid] + [valid[-1][2]] * pad
            try:
                oks = self.algo.verify_batch(pks, msgs, sigs)
            except Exception:
                oks = [False] * tgt
            return [bool(ok) for ok in oks]

        return _run_valid(
            items,
            lambda it: (
                len(it[0]) == self.algo.public_key_len
                and len(it[2]) == self.algo.signature_len
            ),
            dispatch,
            lambda: False,
        )

    def warmup(self, sizes: tuple[int, ...] = (1,)) -> None:
        """Compile keygen/sign/verify for the pow2 buckets (blocking)."""
        pk, sk = self.algo.generate_keypair()
        for n in sizes:
            sks = np.stack([np.frombuffer(sk, np.uint8)] * n)
            pks = np.stack([np.frombuffer(pk, np.uint8)] * n)
            sigs = self.algo.sign_batch(sks, [b"warmup"] * n)
            self.algo.verify_batch(pks, [b"warmup"] * n, sigs)

    async def sign(self, secret_key: bytes, message: bytes) -> bytes:
        return await self._sign.submit((secret_key, message))

    async def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        return await self._verify.submit((public_key, message, signature))

    def stats(self) -> dict[str, Any]:
        return {
            "sign": self._sign.stats.as_dict(),
            "verify": self._verify.stats.as_dict(),
        }
