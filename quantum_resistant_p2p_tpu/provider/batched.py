"""Async batching queue — the host<->TPU boundary (the north-star refactor).

The reference performs one blocking liboqs FFI call per handshake op
(crypto/key_exchange.py:155,178).  Here, concurrent handshakes enqueue their
crypto ops as futures; a flusher collects them into one padded batch and
dispatches a single jitted TPU program, then resolves every future.  Flush
policy: immediately at ``max_batch``, otherwise ``max_wait_ms`` after the
first enqueue — bounding added p50 latency while amortising dispatch overhead
(SURVEY.md §7.4 item 6).

The dispatch itself runs in a worker thread (``run_in_executor``) so the
asyncio loop — which is also serving TCP peers (net.p2p_node) — never blocks
on device compute.

Wrapper classes expose the same op names as the plugin boundary
(KeyExchangeAlgorithm / SignatureAlgorithm, provider.base) but as coroutines;
``SecureMessaging`` awaits them on its handshake path (app/messaging.py here;
reference flow app/messaging.py:546-1134).
"""

from __future__ import annotations

import asyncio
import functools
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from ..faults import plan as _faults
from ..native import wipe
from ..obs import flight as obs_flight
from ..obs import trace as obs_trace
from ..obs.metrics import LatencyHistogram
from .base import (KeyExchangeAlgorithm, SignatureAlgorithm,
                   next_pow2 as _next_pow2, pad_rows as _pad_rows)

#: priority lanes, highest priority first (lowest value wins the flush
#: order): re-keys of live sessions must never starve behind a bulk
#: flood, and fresh handshakes sit between the two (docs/gateway.md).
#: Lane tags ride each queued op; the flush drain takes ops in
#: (lane, arrival) order, so with single-lane traffic (every pre-gateway
#: caller) the drain is bit-for-bit the old insertion-order slice.
LANE_REKEY, LANE_HANDSHAKE, LANE_BULK = 0, 1, 2
#: (Ticket-resume classification, docs/protocol.md "Session resumption":
#: the abbreviated exchange dispatches NO device ops, and any op a
#: RESUMED session later queues — a post-resume rekey, its bulk seals —
#: already classifies onto LANE_REKEY through the engine's
#: had-a-completed-session rule, which a successful resume marks exactly
#: like a full handshake.  No separate lane tag exists on purpose.)
LANE_NAMES = {LANE_REKEY: "rekey", LANE_HANDSHAKE: "handshake",
              LANE_BULK: "bulk"}


class LaneShed(RuntimeError):
    """A lane hit its pending-depth bound and this op was shed (loudly) —
    admission control at the queue: bounded memory, and a bulk flood
    degrades BULK, not the rekey/handshake lanes sharing the queue."""

    def __init__(self, label: str, lane: int, depth: int):
        super().__init__(
            f"queue {label}: {LANE_NAMES.get(lane, lane)} lane shed at "
            f"depth {depth}"
        )
        self.lane = lane


@dataclass
class QueueStats:
    """Per-op-queue counters (surfaced in metrics; SURVEY.md §5 tracing gap)."""

    ops: int = 0
    flushes: int = 0
    max_batch_seen: int = 0
    total_wait_s: float = 0.0
    total_dispatch_s: float = 0.0
    #: ops/flushes served by the cpu fallback while the device path was
    #: slow or timed out (degrade-don't-fail; VERDICT r2 weak #1)
    fallback_ops: int = 0
    fallback_flushes: int = 0
    breaker_trips: int = 0
    #: serial device-dispatch round trips this queue has made (one batch_fn
    #: call through the device or warmup executor = one trip; the handshake
    #: SLO is dispatch-trip-bound on a tunnel, so trips are counted, not
    #: inferred — see docs/dispatch_budget.md)
    device_trips: int = 0
    #: per-flush batch sizes, most recent last (bounded)
    batch_sizes: list[int] = field(default_factory=list)
    #: per-flush dispatch latency percentiles (obs.metrics) — measured
    #: from the event loop, so queue-wait/executor contention included
    dispatch_hist: LatencyHistogram = field(default_factory=LatencyHistogram)
    #: ON-WORKER batch-fn latency (the device program itself, no executor
    #: queueing): what the autotuner's amortization window keys on — the
    #: loop-side number would feed back (contention -> wider window ->
    #: more contention)
    device_hist: LatencyHistogram = field(default_factory=LatencyHistogram)
    #: ops submitted / shed per priority lane (lane tag -> count)
    lane_ops: dict = field(default_factory=dict)
    lane_sheds: dict = field(default_factory=dict)
    BATCH_SIZE_HISTORY = 1024

    def as_dict(self) -> dict[str, Any]:
        h = self.dispatch_hist
        return {
            "ops": self.ops,
            "flushes": self.flushes,
            "max_batch_seen": self.max_batch_seen,
            "avg_batch": (self.ops / self.flushes) if self.flushes else 0.0,
            "avg_dispatch_ms": (
                1e3 * self.total_dispatch_s / self.flushes if self.flushes else 0.0
            ),
            "p50_dispatch_ms": round(1e3 * (h.percentile(50) or 0.0), 3),
            "p99_dispatch_ms": round(1e3 * (h.percentile(99) or 0.0), 3),
            "p50_device_ms": round(
                1e3 * (self.device_hist.percentile(50) or 0.0), 3),
            "p99_device_ms": round(
                1e3 * (self.device_hist.percentile(99) or 0.0), 3),
            "fallback_ops": self.fallback_ops,
            "fallback_flushes": self.fallback_flushes,
            "breaker_trips": self.breaker_trips,
            "device_trips": self.device_trips,
            # the degradation gauge (VERDICT r3: the config-5 "TPU" swarm was
            # silently ~100% cpu-served): 1.0 = every op rode the device path
            "device_served_fraction": (
                round((self.ops - self.fallback_ops) / self.ops, 4)
                if self.ops else None
            ),
            # additive keys (the legacy layout above is a compatibility
            # contract): per-lane submit/shed counts, by lane name
            "lanes": {LANE_NAMES.get(k, str(k)): v
                      for k, v in sorted(self.lane_ops.items())},
            "lane_sheds": {LANE_NAMES.get(k, str(k)): v
                           for k, v in sorted(self.lane_sheds.items())},
        }


class CoalescingHub:
    """Shared flush-coalescing machinery: the queues registered on one hub
    (a :class:`Breaker`, or the placement scheduler) flush in the same
    scheduling window, so independent KEM/SIG batches go in flight
    together instead of serialising one timer window apart.  Only queues
    that already hold items are touched: nothing flushes emptier/earlier
    than it would have on its own timer."""

    def _init_coalescer(self) -> None:
        #: weak: a hot-swapped facade's dead queues must not linger
        import weakref

        self._queues: weakref.WeakSet = weakref.WeakSet()
        self._coalescing = False

    def register_queue(self, queue: "OpQueue") -> None:
        self._queues.add(queue)

    def coalesce(self, origin: "OpQueue") -> None:
        """Flush every sibling queue with pending items in the SAME
        scheduling window as ``origin``'s flush."""
        if self._coalescing:
            return
        self._coalescing = True
        try:
            for q in list(self._queues):
                if q is not origin and q._items:
                    q._flush_local()
        finally:
            self._coalescing = False


class Breaker(CoalescingHub):
    """Shared circuit breaker for one device's dispatch path — a full
    closed -> open -> half-open state machine (the r3 self-healing fix:
    the old open/closed breaker let one transient device fault pin a fleet
    on the cpu fallback forever).

    States:

    * ``closed``      — every armed flush dispatches to the device.
    * ``open``        — every armed flush runs on the fallback until the
                        cool-off clock expires.  Consecutive failures make
                        the cool-off grow exponentially (capped).
    * ``half_open``   — the cool-off expired: exactly ONE real queued flush
                        is let through as a canary probe; siblings keep
                        falling back while it is in flight.  Probe success
                        closes the breaker (traffic returns to the device,
                        cool-off resets); failure re-opens it with a doubled
                        cool-off.
    * ``quarantined`` — the device-health gate (provider/health.py) found
                        the device path INCORRECT (not merely slow); the
                        breaker pins the fallback for the process lifetime —
                        wrong answers cannot be probed back to health.

    State transitions log ONE loud WARNING each, so a degraded fleet is
    visible in logs, not just in metrics.

    All op queues of a provider (and, via SecureMessaging, the KEM and
    signature facades together) share one breaker: the device/tunnel is the
    common resource, so one op type discovering slowness shields the rest.

    The breaker also owns TWO executors: a 2-thread DEVICE pool for live
    dispatches (normal priority — steady-state dispatches must not be
    starved by the cpu fallback's own load, or the canary probe measures
    starvation instead of the device) and a 1-thread WARMUP pool
    at nice 19 for cold-bucket jit compiles, whose host-side CPU burn would
    otherwise starve the event loop and the fallback.  Hung, abandoned
    dispatches occupy at most the 2 device threads; they can never starve
    the default executor the fallback runs on.
    """

    def __init__(self, cooloff_s: float = 30.0, cooloff_max_s: float = 480.0,
                 clock: Callable[[], float] = time.monotonic):
        import threading

        #: injectable monotonic clock: the fleet manager (fleet/manager.py)
        #: reuses this exact state machine for its per-GATEWAY breakers and
        #: drives handoff/heal tests on deterministic timelines; production
        #: callers never pass it
        self._clock = clock

        #: guards every state-machine mutation: the breaker is shared between
        #: the event loop (dispatch outcomes) and the warmup thread (the
        #: device-health gate quarantines from there) — qrflow's
        #: cross-thread-state pack proved the unlocked writes racy
        self._lock = threading.RLock()
        self.base_cooloff_s = cooloff_s
        self.cooloff_s = cooloff_s  # current (grows exponentially while open)
        self.cooloff_max_s = cooloff_max_s
        #: placement identity ("shard<i>" when owned by a scheduler shard):
        #: rides in logs and flight events so a degraded SHARD is
        #: distinguishable from a degraded fleet
        self.label = ""
        self.state = "closed"
        self.trips = 0
        #: open/close transition counters (metrics; every transition also
        #: logs one WARNING)
        self.opens = 0
        self.closes = 0
        #: serial device-dispatch round trips aggregated across every queue
        #: sharing this breaker (KEM + signature + composite): the number
        #: SecureMessaging diffs around a handshake to measure
        #: trips-per-handshake (docs/dispatch_budget.md)
        self.device_trips = 0
        #: fallback flushes aggregated the same way (a fallback flush is a
        #: serial step too — just a cpu one)
        self.fallback_trips = 0
        self._open_until = 0.0
        self._probe_in_flight = False
        #: cumulative seconds spent NOT closed (open/half-open/quarantined)
        #: plus the start of the current degraded stretch — the "breaker
        #: open time" SLO feed (obs/slo.py): budget burn is the fraction of
        #: wall time the device path was unavailable
        self._degraded_s = 0.0
        self._degraded_since: float | None = None
        self._executor = None
        self._warmup_executor = None
        # queues sharing this breaker coalesce their flushes (CoalescingHub)
        self._init_coalescer()

    def is_open(self) -> bool:
        """True while no regular device dispatch may proceed."""
        with self._lock:
            if self.state == "quarantined":
                return True
            return self.state == "open" and self._clock() < self._open_until

    def probe_ready(self) -> bool:
        """True when the next :meth:`acquire_dispatch` would route a canary
        probe (open past the cool-off, or half-open with no probe in
        flight).  The placement policy (provider/scheduler.py) routes one
        flush back to such a shard so it can heal — without this, a
        multi-shard plane would starve open shards of the probe traffic
        the half-open state machine needs."""
        with self._lock:
            if self._probe_in_flight or self.state == "quarantined":
                return False
            if self.state == "half_open":
                return True
            return self.state == "open" and self._clock() >= self._open_until

    def _set_state(self, new: str, why: str = "") -> None:
        """Transition + loud log + structured flight-recorder event (the
        one-time WARNINGs were log-only and invisible to tooling before
        obs/; breaker-open and quarantine are auto-dump triggers).
        Callers hold ``self._lock`` (RLock)."""
        with self._lock:
            if new == self.state:
                return
            log = logging.getLogger(__name__)
            old = self.state
            self.state = new
            # degraded-time ledger (the breaker-availability SLO feed)
            now = self._clock()
            if old == "closed" and new != "closed":
                self._degraded_since = now
            elif new == "closed" and self._degraded_since is not None:
                self._degraded_s += now - self._degraded_since
                self._degraded_since = None
            if new == "open":
                self.opens += 1
                log.warning(
                    "circuit breaker OPEN (%s): device dispatch path degraded; "
                    "serving from cpu fallback for %.1fs, then probing",
                    why or "tripped", self.cooloff_s,
                )
            elif new == "closed":
                self.closes += 1
                self.cooloff_s = self.base_cooloff_s
                log.warning(
                    "circuit breaker CLOSED: device canary probe succeeded; "
                    "traffic restored to the device path"
                )
            elif new == "quarantined":
                log.error(
                    "circuit breaker QUARANTINED (%s): device path disabled for "
                    "this process; all ops served from the cpu fallback", why,
                )
            # emit AFTER the bookkeeping so the event carries the real
            # counters (open/quarantined are auto-dump triggers; the bundle
            # build runs on the flight recorder's own thread, never here)
            emit = (obs_flight.trigger if new in ("open", "quarantined")
                    else obs_flight.record)
            emit(
                "breaker_open" if new == "open"
                else "breaker_quarantined" if new == "quarantined"
                else "breaker_transition",
                state=new, prev=old, why=why, cooloff_s=round(self.cooloff_s, 3),
                opens=self.opens, closes=self.closes, shard=self.label or None,
            )

    def trip(self) -> None:
        """Record a device failure observed outside the claim protocol
        (direct callers, tests): opens the breaker without escalating the
        canary backoff."""
        self._trip(escalate=False)

    def _trip(self, escalate: bool) -> None:
        """From closed: open at the base cool-off.  ``escalate`` (a FAILED
        CANARY PROBE — the only fresh evidence the device is still broken)
        doubles the cool-off, capped.  Non-probe failures never escalate
        and never touch the probe token: a straggler dispatch from the
        previous incident finishing late while open/half-open only
        refreshes the clock (or re-opens), so one incident's concurrent
        dispatches cannot compound the backoff or race the live canary.
        A quarantined breaker stays quarantined."""
        with self._lock:
            self.trips += 1
            if self.state == "quarantined":
                return
            if escalate:
                self.cooloff_s = min(self.cooloff_s * 2.0, self.cooloff_max_s)
            elif self.state == "closed":
                self.cooloff_s = self.base_cooloff_s
            self._open_until = self._clock() + self.cooloff_s
            if self.state == "open":
                logging.getLogger(__name__).debug(
                    "circuit breaker already open: cool-off clock refreshed "
                    "(concurrent dispatch of the same incident)"
                )
            else:
                self._set_state(
                    "open", "canary probe failed" if escalate else "tripped"
                )

    def degraded_seconds(self) -> float:
        """Cumulative wall seconds this breaker spent NOT closed (open,
        half-open, or quarantined), the live stretch included — the
        numerator of the availability SLO (obs/slo.py): ``bad time /
        total time`` is the burn of the "device path available" objective."""
        with self._lock:
            total = self._degraded_s
            if self._degraded_since is not None:
                total += self._clock() - self._degraded_since
            return total

    def quarantine(self, why: str) -> None:
        """Pin the fallback for the process lifetime (device-health gate:
        the device path computes WRONG answers, which no latency probe can
        detect).  Runs on the WARMUP THREAD — the lock is what makes it safe
        against concurrent loop-side trips."""
        with self._lock:
            self.trips += 1
            self._set_state("quarantined", why)

    def acquire_dispatch(self) -> str:
        """Claim the next armed flush's route: ``"device"`` (closed),
        ``"probe"`` (half-open canary — exactly one in flight), or
        ``"fallback"``.  Pair with :meth:`record_success` /
        :meth:`record_failure` / :meth:`release`."""
        with self._lock:
            if self.state == "closed":
                return "device"
            if self.state == "quarantined":
                return "fallback"
            if self.state == "open":
                if self._clock() < self._open_until:
                    return "fallback"
                self._set_state("half_open")
            if self._probe_in_flight:
                return "fallback"
            self._probe_in_flight = True
            return "probe"

    def record_success(self, claim: str) -> None:
        with self._lock:
            if claim == "probe":
                self._probe_in_flight = False
                self._set_state("closed")

    def record_failure(self, claim: str) -> None:
        with self._lock:
            if claim == "probe":
                self._probe_in_flight = False
                self._trip(escalate=True)
            else:
                self._trip(escalate=False)

    def release(self, claim: str) -> None:
        """Return an un-dispatched claim (e.g. the flush went to the warm-up
        path instead) without recording an outcome."""
        with self._lock:
            if claim == "probe":
                self._probe_in_flight = False

    @property
    def device_executor(self):
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="qrp2p-device"
            )
        return self._executor

    @property
    def warmup_executor(self):
        if self._warmup_executor is None:
            import os
            from concurrent.futures import ThreadPoolExecutor

            def _background_priority():
                # Linux nice() is per-thread: demote the compile worker so
                # cold-bucket jit never preempts the loop or the fallback.
                try:
                    os.nice(19)
                except OSError:  # pragma: no cover
                    pass

            self._warmup_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="qrp2p-warmup",
                initializer=_background_priority,
            )
        return self._warmup_executor


class OpQueue:
    """Accumulates (item -> future) pairs; flushes through a batch function.

    ``batch_fn(items) -> list[results]`` is called with at most ``max_batch``
    items, inside the default executor.

    Degradation policy (a production queue must not fail handshakes because
    its accelerator link is slow — the reference's serial liboqs path never
    does): when ``fallback_fn`` is given, a circuit breaker watches device
    dispatch latency.  A dispatch slower than ``degrade_after_ms`` (or one
    that exceeds the hard ``dispatch_timeout_ms``, in which case the stuck
    device call is abandoned to finish in the background) trips the breaker
    for its cool-off; while open, flushes run on the fallback — slower per
    op, but it completes.  After the cool-off the next flush probes the
    device path again.
    """

    def __init__(
        self,
        batch_fn: Callable[[list[Any]], list[Any]],
        max_batch: int = 4096,
        max_wait_ms: float = 2.0,
        fallback_fn: Callable[[list[Any]], list[Any]] | None = None,
        degrade_after_ms: float = 2000.0,
        dispatch_timeout_ms: float = 15000.0,
        degrade_ref_batch: int = 256,
        breaker: Breaker | None = None,
        bucket_floor: int = 1,
        label: str = "",
        scheduler=None,
        lane_capacity: dict[int, int] | None = None,
        warm_check: Callable[[list[Any], int], bool] | None = None,
    ):
        #: queue name at the fault-injection boundary (faults/) and in logs
        self.label = label
        #: placement axis (provider.scheduler.DeviceProgramScheduler):
        #: every flush is placed on one of its shards, each with its OWN
        #: breaker + executors.  None = the classic single-breaker path.
        self.scheduler = scheduler
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.fallback_fn = fallback_fn
        self.degrade_after_s = degrade_after_ms / 1e3
        self.dispatch_timeout_s = dispatch_timeout_ms / 1e3
        #: flushes pad UP to at least this pow2 bucket.  Collapses the
        #: bucket space from log2(max_batch) sizes to a handful, so a
        #: pre-warm covers every size a live swarm can hit; small flushes
        #: cost the same as a floor-sized one (device dispatches at these
        #: sizes are launch-dominated, see bench_report.md scaling curves).
        #: Rounded up to a power of two and capped at max_batch so the
        #: effective bucket always matches what warmup() compiles.
        self.bucket_floor = min(_next_pow2(max(1, bucket_floor)), max_batch)
        #: thresholds are for a <= degrade_ref_batch flush and scale
        #: linearly above it — a 4096-row dispatch is ALLOWED to take 16x
        #: longer than a 256-row one before it counts as "slow"; without
        #: this, peak load (big healthy batches) trips the breaker forever
        self.degrade_ref_batch = degrade_ref_batch
        #: clears a stuck _warming flag so warm-ups are retried (see
        #: _run_batch); generous — first compiles take minutes on a tunnel
        self.warmup_watchdog_s = 600.0
        if scheduler is not None:
            # shard 0's breaker doubles as the compat handle (legacy stats
            # readers); claims are taken per-PLACED-shard in _run_batch
            self.breaker = (breaker if breaker is not None
                            else scheduler.shards[0].breaker)
            self._coalescer = scheduler
        else:
            self.breaker = breaker if breaker is not None else Breaker()
            self._coalescer = self.breaker
        self._coalescer.register_queue(self)
        #: pow2 sizes whose device program has completed at least once; a
        #: cold bucket's ops are served by the fallback while the compile
        #: runs in the background (never hostage to a compile).  Guarded by
        #: ``_warm_lock``: facade warmups mark buckets from the WARMUP
        #: THREAD while loop-side dispatches read and mutate the same sets
        #: (qrflow cross-thread-state).
        import threading

        self._warm_lock = threading.Lock()
        self._warm_buckets: set[int] = set()
        self._warming: set[int] = set()
        #: optional SECOND warm axis: ``warm_check(items, bucket) -> bool``
        #: refines the pow2-batch-bucket tracking for ops whose compiled
        #: program also keys on per-item shape (the AEAD queues' message/
        #: aad length buckets).  A flush whose batch bucket is warm but
        #: whose shapes are novel is served from the fallback while the
        #: background warm compiles EXACTLY the live shapes (_warm_call
        #: runs the real batch fn on the real items) — a novel length
        #: bucket must degrade gracefully, never compile inside a live
        #: device dispatch and trip the breaker as "slow".
        self.warm_check = warm_check
        self.stats = QueueStats()
        #: per-lane pending-depth bounds (lane tag -> max pending); an op
        #: submitted to a full lane is SHED (LaneShed, loud) instead of
        #: growing the queue without bound — None/absent = unbounded
        self.lane_capacity = lane_capacity
        #: adaptive flush policy (provider/autotune.py QueueTuner): when
        #: attached, overrides the flush-at threshold and timer window on
        #: the hot path; None (the default, and QRP2P_AUTOTUNE=0) reads
        #: the static constructor values — bit-for-bit the old behavior
        self.tuner = None
        #: device-cost ledger (obs/cost.py CostLedger): when attached,
        #: flushes record their occupancy (real vs padded slots), cold
        #: buckets their compile seconds, dispatches their device time.
        #: Observation only — never steers when/what a flush dispatches
        self.cost = None
        self._items: list[Any] = []
        self._futures: list[asyncio.Future] = []
        #: lane tag per pending item (parallel to _items), plus O(1)
        #: pending counts per lane — the capacity check runs on EVERY
        #: capped-lane submit, and a list scan there would make a
        #: saturated queue quadratic across a burst
        self._lane_tags: list[int] = []
        self._lane_pending: dict[int, int] = {}
        self._timer: asyncio.TimerHandle | None = None
        self._first_enqueue_t = 0.0
        #: strong refs to in-flight dispatch tasks: the loop holds only weak
        #: references, so an unreferenced flush could be GC'd mid-dispatch
        self._dispatch_tasks: set[asyncio.Task] = set()

    def mark_warm(self, bucket: int) -> None:
        """Record that ``bucket``'s device program is compiled.  Thread-safe:
        the facades' ``warmup()`` runs on the background warmup thread while
        the event loop reads/mutates the same sets mid-dispatch."""
        with self._warm_lock:
            self._warming.discard(bucket)
            self._warm_buckets.add(bucket)

    def _wait_s(self) -> float:
        """Flush-timer window: the tuner's adaptive window when attached
        and past its cold start, else the static constructor value
        (bit-for-bit the old path)."""
        if self.tuner is None:
            return self.max_wait_s
        w = self.tuner.wait_s()
        return self.max_wait_s if w is None else w

    def _flush_at(self) -> int:
        """Pending-op count that triggers an immediate flush: the tuner's
        chosen bucket when attached and decided, else ``max_batch`` (the
        old path: flush on the timer or a full batch).  A bucket of 1 is
        NOT an early trigger — flushing every submit solo would shatter
        the coalescing the (short) window still provides; at bucket 1 the
        window is the whole policy."""
        if self.tuner is None:
            return self.max_batch
        b = self.tuner.flush_at()
        if b is None or b <= 1:
            return self.max_batch
        return min(self.max_batch, b)

    def _shed(self, lane: int) -> None:
        n = self.stats.lane_sheds.get(lane, 0) + 1
        self.stats.lane_sheds[lane] = n
        # loud but bounded: a bulk flood must not turn the log/flight ring
        # into a wall of identical shed lines
        if n == 1 or n % 128 == 0:
            logging.getLogger(__name__).warning(
                "queue %s: %s lane at capacity (%d pending); op shed "
                "(%d total)", self.label or "?", LANE_NAMES.get(lane, lane),
                self.lane_capacity.get(lane), n,
            )
            obs_flight.record(
                "load_shed", where="lane", queue=self.label,
                lane=LANE_NAMES.get(lane, str(lane)), sheds=n,
            )
        raise LaneShed(self.label, lane, self.lane_capacity.get(lane, 0))

    async def submit(self, item: Any, lane: int = LANE_HANDSHAKE) -> Any:
        loop = asyncio.get_running_loop()
        cap = (self.lane_capacity or {}).get(lane)
        if cap is not None and self._lane_pending.get(lane, 0) >= cap:
            self._shed(lane)
        fut: asyncio.Future = loop.create_future()
        self._items.append(item)
        self._futures.append(fut)
        self._lane_tags.append(lane)
        self._lane_pending[lane] = self._lane_pending.get(lane, 0) + 1
        self.stats.ops += 1
        self.stats.lane_ops[lane] = self.stats.lane_ops.get(lane, 0) + 1
        if len(self._items) == 1:
            self._first_enqueue_t = time.perf_counter()
            self._timer = loop.call_later(self._wait_s(), self._flush_soon)
        if len(self._items) >= self._flush_at():
            self._flush_soon()
        return await fut

    def _flush_soon(self) -> None:
        """Flush this queue, then coalesce sibling queues sharing the
        breaker/scheduler into the same scheduling window so independent
        KEM/SIG batches go in flight together (under a scheduler, each
        coalesced flush is then PLACED independently — siblings can land
        on different shards and run in parallel)."""
        self._flush_local()
        self._coalescer.coalesce(self)

    def _take_batch(self) -> tuple[list[Any], list[asyncio.Future], int]:
        """Detach up to ``max_batch`` pending ops in (lane, arrival) order.

        With single-lane traffic (every caller that never passes ``lane``)
        the priority sort degenerates to the old insertion-order slice —
        the drain is bit-for-bit the pre-lane behavior.  Under mixed-lane
        load, a flush that cannot carry everything takes rekeys first,
        then handshakes, then bulk: a bulk flood defers bulk, never the
        rekey lane (the starvation bound, tests/test_gateway.py).
        Returns (items, futures, flush_lane) — flush_lane is the highest-
        priority lane aboard, stamped on the ``queue.flush`` span."""
        n = len(self._items)
        k = min(self.max_batch, n)
        if len(set(self._lane_tags)) <= 1:
            items = self._items[:k]
            futs = self._futures[:k]
            lane = self._lane_tags[0] if self._lane_tags else LANE_HANDSHAKE
            del self._items[:k], self._futures[:k], self._lane_tags[:k]
            if self._lane_tags:
                self._lane_pending[lane] = len(self._lane_tags)
            else:
                self._lane_pending.clear()
            return items, futs, lane
        order = sorted(range(n), key=lambda i: (self._lane_tags[i], i))
        take = order[:k]
        taken = set(take)
        items = [self._items[i] for i in take]
        futs = [self._futures[i] for i in take]
        lane = min(self._lane_tags[i] for i in take)
        for i in take:
            self._lane_pending[self._lane_tags[i]] -= 1
        self._items = [x for i, x in enumerate(self._items) if i not in taken]
        self._futures = [x for i, x in enumerate(self._futures)
                         if i not in taken]
        self._lane_tags = [x for i, x in enumerate(self._lane_tags)
                           if i not in taken]
        return items, futs, lane

    def _flush_local(self) -> None:
        """Detach pending items synchronously (so late submits can't bloat a
        batch past max_batch) and dispatch them as a task."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        loop = asyncio.get_running_loop()
        while self._items:
            items, futs, lane = self._take_batch()
            task = loop.create_task(
                self._dispatch(items, futs, self._first_enqueue_t, lane))
            self._dispatch_tasks.add(task)
            task.add_done_callback(self._reap_dispatch)

    def _reap_dispatch(self, task: asyncio.Task) -> None:
        self._dispatch_tasks.discard(task)
        if not task.cancelled() and task.exception() is not None:
            # _dispatch forwards batch errors to the waiter futures; anything
            # surfacing HERE escaped that path and must not vanish.
            logging.getLogger(__name__).error(
                "batch dispatch task failed", exc_info=task.exception()
            )

    def _trip_breaker(self, reason: str, dt: float, claim: str = "device",
                      breaker: Breaker | None = None) -> None:
        breaker = breaker if breaker is not None else self.breaker
        self.stats.breaker_trips += 1
        breaker.record_failure(claim)
        logging.getLogger(__name__).warning(
            "batch queue %s%s: device dispatch %s (%.1fs); serving from cpu "
            "fallback for %.0fs", self.label or "?",
            f" [{breaker.label}]" if breaker.label else "", reason, dt,
            breaker.cooloff_s,
        )

    async def _run_fallback(self, items: list[Any],
                            breaker: Breaker | None = None) -> list[Any]:
        breaker = breaker if breaker is not None else self.breaker
        self.stats.fallback_flushes += 1
        self.stats.fallback_ops += len(items)
        breaker.fallback_trips += 1
        loop = asyncio.get_running_loop()
        parent = obs_trace.current()
        return await loop.run_in_executor(
            None, self._traced_call, self.fallback_fn, "fallback.dispatch",
            "fallback", parent, items,
        )

    def _traced_call(self, fn, span_name: str, route: str, parent,
                     items: list[Any], shard=None) -> list[Any]:
        """Run one dispatch callable inside a span, ON the worker thread —
        so the span measures the actual device/fallback time and carries
        the worker's thread lane in the flame graph.  ``parent`` is the
        loop-side context captured before the executor hop (contextvars do
        not cross ``run_in_executor``).  With a ``shard``, the call runs
        under that shard's placement context (Shard.run_placed) and the
        span carries the shard index — the flame graph shows which chip
        served each dispatch."""
        attrs = {"op": self.label, "n": len(items), "route": route}
        if shard is not None:
            attrs["shard"] = shard.index
        with obs_trace.span(span_name, parent=parent, **attrs):
            t0 = time.perf_counter()
            try:
                if shard is not None:
                    return shard.run_placed(fn, items)
                return fn(items)
            finally:
                if route not in ("fallback", "warmup"):
                    # on-worker DEVICE-program time (no executor queueing):
                    # the autotuner's amortization signal.  Fallback and
                    # warmup-compile durations must not pollute it — a
                    # recovery phase would otherwise tune its windows to
                    # cpu/compile time instead of device time
                    dt = time.perf_counter() - t0
                    self.stats.device_hist.record(dt)
                    if self.cost is not None:
                        # the cost ledger's device-seconds feed shares the
                        # same purity rule: device-program time only
                        self.cost.device_time(self.label, dt)

    def _count_trip(self, breaker: Breaker | None = None) -> None:
        """One serial device round trip (device or warmup executor): the
        per-handshake SLO currency (docs/dispatch_budget.md).  Recorded on
        the PLACED shard's breaker so per-shard ledgers stay truthful."""
        self.stats.device_trips += 1
        (breaker if breaker is not None else self.breaker).device_trips += 1

    def _device_call(self, items: list[Any], shard_index: int | None = None,
                     lane: int | None = None) -> list[Any]:
        """The device dispatch boundary: the explicit fault-injection hook
        (faults/) wraps the real batch fn — a raise here IS a device fault
        and is handled (breaker + fallback) exactly like one.  The shard
        index and the flush's priority lane ride into the fault-match info
        so chaos plans can kill ONE shard's device (match={"shard": i}) or
        target one lane's flushes (match={"lane": "bulk"})."""
        _faults.device_dispatch(
            self.label, len(items), shard=shard_index,
            lane=LANE_NAMES.get(lane) if lane is not None else None,
        )
        return _faults.poison_results(self.label, self.batch_fn(items))

    def _warm_call(self, items: list[Any]) -> list[Any]:
        """The warm-up boundary (fault scope "warmup": a killed warm-up
        thread surfaces as this call raising).  Under a scheduler the warm
        runs on every CLOSED shard (``scheduler.warmable_shards``) — a
        sick shard's hung device must not block warm-marking for the
        healthy plane; it cold-compiles inside its first placed flush
        after healing, absorbed by the slow-trip machinery."""
        _faults.warmup(self.label)
        if self.scheduler is not None:
            warm = self.scheduler.warmable_shards()
            if warm:
                out = None
                for sh in warm:
                    out = sh.run_placed(self.batch_fn, items)
                return out
        return self.batch_fn(items)

    def _claim(self):
        """The placement step: -> (shard | None, claim, breaker).  With a
        scheduler, the flush is placed on a shard (load-aware, probe-first,
        quarantine-aware) and the claim is taken on THAT shard's breaker;
        without one, the classic single-breaker claim."""
        if self.scheduler is not None:
            shard = self.scheduler.place()
            return shard, shard.breaker.acquire_dispatch(), shard.breaker
        return None, self.breaker.acquire_dispatch(), self.breaker

    async def _run_batch(self, items: list[Any], flush_span=None,
                         lane: int | None = None) -> list[Any]:
        """Device path with watchdog + breaker; falls back to cpu when the
        device is slow, hung, or raising.  Each flush is placed whole on
        one shard (when a scheduler is armed) — a flush never splits
        across shards, so results stay bit-exact vs. the single path."""
        loop = asyncio.get_running_loop()
        if self.fallback_fn is None:
            shard = self.scheduler.place() if self.scheduler is not None else None
            if flush_span is not None and shard is not None:
                flush_span.set_attr("shard", shard.index)
            try:
                self._count_trip(shard.breaker if shard is not None else None)
                self._cost_occupancy(items, lane, shard)
                return await loop.run_in_executor(
                    shard.breaker.device_executor if shard is not None else None,
                    self._traced_call, self._direct_fn(shard, lane),
                    "device.dispatch", "direct", obs_trace.current(), items,
                    shard,
                )
            finally:
                if shard is not None:
                    self.scheduler.done(shard)
        shard, claim, breaker = self._claim()
        if flush_span is not None and shard is not None:
            flush_span.set_attr("shard", shard.index)
        try:
            return await self._run_claimed(loop, items, shard, claim, breaker,
                                           lane)
        finally:
            if shard is not None:
                self.scheduler.done(shard)

    def _cost_occupancy(self, items: list[Any], lane: int | None,
                        shard) -> None:
        """Ledger hook for one DEVICE-path flush: real items vs the padded
        pow2 bucket the batch fn will dispatch (cpu-fallback flushes pad
        nothing and never reach here)."""
        if self.cost is None:
            return
        bucket = max(self.bucket_floor, _next_pow2(len(items)))
        self.cost.flush_occupancy(
            self.label,
            LANE_NAMES.get(lane, str(lane)) if lane is not None else "?",
            len(items), bucket,
            shard=shard.index if shard is not None else None,
        )

    def _direct_fn(self, shard, lane: int | None = None):
        """Bind the shard index and flush lane into the fault-hooked device
        call (the callable crosses run_in_executor positionally)."""
        if shard is None and lane is None:
            return self._device_call
        return functools.partial(
            self._device_call,
            shard_index=shard.index if shard is not None else None, lane=lane,
        )

    async def _run_claimed(self, loop, items: list[Any], shard, claim: str,
                           breaker: Breaker,
                           lane: int | None = None) -> list[Any]:
        if claim == "fallback":
            return await self._run_fallback(items, breaker)
        bucket = max(self.bucket_floor, _next_pow2(len(items)))
        scale = max(1.0, bucket / self.degrade_ref_batch)
        with self._warm_lock:
            is_warm = bucket in self._warm_buckets
            if is_warm and self.warm_check is not None:
                is_warm = self.warm_check(items, bucket)
            start_warm = not is_warm and bucket not in self._warming
            if start_warm:
                self._warming.add(bucket)
        if not is_warm:
            # A bucket's first device dispatch is a jit compile — tens of
            # seconds cold, easily past the protocol timeout.  Never hold
            # live ops hostage to a compile: serve them from the cpu NOW and
            # warm the bucket in the background (the nice-19 1-thread warmup
            # pool serialises compiles; the device takes over once warm).
            breaker.release(claim)  # nothing dispatches on this claim
            if start_warm:
                self._count_trip(breaker)
                warm_t0 = time.perf_counter()
                warm = loop.run_in_executor(
                    breaker.warmup_executor, self._traced_call,
                    self._warm_call, "device.dispatch", "warmup",
                    obs_trace.current(), items,
                )

                def _mark(f, b=bucket, t0=warm_t0):
                    if f.cancelled():
                        with self._warm_lock:
                            self._warming.discard(b)
                        return
                    if f.exception() is None:
                        self.mark_warm(b)
                        if self.cost is not None:
                            # in-flush cold compile: a live flush hit this
                            # bucket cold and these are the wall seconds
                            # until the device path could take over (the
                            # 1-thread warmup pool's queueing included —
                            # that wait IS part of the observed cost)
                            self.cost.compile_event(
                                self.label, b, time.perf_counter() - t0,
                                where="in_flush",
                            )
                    else:
                        with self._warm_lock:
                            self._warming.discard(b)
                        logging.getLogger(__name__).warning(
                            "bucket %d warm-up failed: %s", b, f.exception()
                        )

                warm.add_done_callback(_mark)

                # Watchdog: a hung warm-up must not pin the bucket in
                # _warming forever (that would silently disable the device
                # path with no retry).  After the timeout, clear the flag so
                # a later flush retries; the stuck thread, if any, still
                # occupies only the 1-thread warmup pool.
                def _unstick(b=bucket, w=warm):
                    with self._warm_lock:
                        stuck = not w.done() and b in self._warming
                        if stuck:
                            self._warming.discard(b)
                    if stuck:
                        logging.getLogger(__name__).warning(
                            "bucket %d warm-up still running after %.0fs; "
                            "will retry on a later flush", b,
                            self.warmup_watchdog_s,
                        )

                loop.call_later(self.warmup_watchdog_s, _unstick)
            return await self._run_fallback(items, breaker)
        t0 = time.perf_counter()
        self._count_trip(breaker)
        self._cost_occupancy(items, lane, shard)
        # Dedicated 2-thread device pool PER BREAKER (per shard, under a
        # scheduler — placed flushes on different shards genuinely run in
        # parallel): an abandoned hung dispatch can never starve the
        # default executor that the cpu fallback runs on.
        device = loop.run_in_executor(
            breaker.device_executor, self._traced_call,
            self._direct_fn(shard, lane), "device.dispatch", claim,
            obs_trace.current(), items, shard,
        )
        try:
            results = await asyncio.wait_for(
                asyncio.shield(device), self.dispatch_timeout_s * scale
            )
        except asyncio.TimeoutError:
            # The device call cannot be cancelled (it is a thread); abandon it
            # to finish in the background and serve these ops from the cpu.
            self._trip_breaker("timed out", time.perf_counter() - t0, claim,
                               breaker)
            device.add_done_callback(lambda f: f.exception())  # reap quietly
            return await self._run_fallback(items, breaker)
        except Exception as exc:  # qrlint: disable=broad-except  — the failure is recorded to the breaker and logged by _trip_breaker, then served from the fallback
            # The device dispatch RAISED (worker crash, compile blow-up,
            # injected fault): record it to the breaker and degrade — a
            # raising device must heal through the half-open probe exactly
            # like a slow one, not fail its waiters.
            self._trip_breaker(f"raised {type(exc).__name__}",
                               time.perf_counter() - t0, claim, breaker)
            return await self._run_fallback(items, breaker)
        dt = time.perf_counter() - t0
        if dt > self.degrade_after_s * scale:
            self._trip_breaker("slow", dt, claim, breaker)
        else:
            breaker.record_success(claim)
        return results

    async def _dispatch(self, items: list[Any], futs: list[asyncio.Future],
                        first_t: float, lane: int = LANE_HANDSHAKE) -> None:
        self.stats.flushes += 1
        self.stats.max_batch_seen = max(self.stats.max_batch_seen, len(items))
        self.stats.batch_sizes.append(len(items))
        del self.stats.batch_sizes[: -QueueStats.BATCH_SIZE_HISTORY]
        self.stats.total_wait_s += time.perf_counter() - first_t
        t0 = time.perf_counter()
        try:
            # The flush task inherits the context captured when its timer/
            # task was scheduled — i.e. the FIRST enqueuer's span — so a
            # handshake's flushes chain under its handshake span.
            with obs_trace.span("queue.flush", op=self.label, n=len(items),
                                lane=LANE_NAMES.get(lane, str(lane)),
                                waited_ms=round(
                                    1e3 * (t0 - first_t), 3)) as sp:
                # _run_batch stamps the placed shard onto this span, so the
                # flame graph's flush lane names the chip that served it
                results = await self._run_batch(items, sp, lane)
            dt = time.perf_counter() - t0
            self.stats.total_dispatch_s += dt
            self.stats.dispatch_hist.record(dt)
            if self.tuner is not None:
                # the autotuner steps on flush completion (no background
                # task): cheap cadence check, decisions off the hot path
                self.tuner.maybe_step()
            for f, r in zip(futs, results):
                if f.cancelled():
                    continue
                # batch fns report per-item failures as Exception instances so
                # one bad item doesn't poison its batch mates
                if isinstance(r, Exception):
                    f.set_exception(r)
                else:
                    f.set_result(r)
        except Exception as exc:  # propagate to every waiter
            for f in futs:
                if not f.cancelled():
                    f.set_exception(exc)


def _run_valid(items, is_valid, dispatch, invalid_result, floor=1):
    """Shared filter-pad-dispatch-scatter skeleton for the batch fns.

    ``is_valid(item) -> bool`` selects items safe to stack; ``dispatch(valid
    items, pow2 target) -> per-item results`` runs the padded device batch;
    invalid slots get ``invalid_result()`` so one attacker-supplied ragged
    input never poisons its batch mates.
    """
    valid_idx = [i for i, it in enumerate(items) if is_valid(it)]
    results = [invalid_result() for _ in items]
    if valid_idx:
        # pad to the pow2 of the FLUSH size (raised to the facade's bucket
        # floor), not the valid count: OpQueue keys its warm-bucket tracking
        # on that same size, so the compiled program shape must match it
        # even when attacker-supplied invalid items were filtered out
        tgt = max(floor, _next_pow2(len(items)))
        out = dispatch([items[i] for i in valid_idx], tgt)
        for j, i in enumerate(valid_idx):
            results[i] = out[j]
    return results


def _make_queues(algo, fallback, breaker, max_batch, max_wait_ms,
                 batch_meths, degrade_opts, bucket_floor=1, scheduler=None,
                 lane_capacity=None):
    """Build one OpQueue per batch method, wiring the shared breaker (or the
    placement scheduler) and the fallback partials (used by both facades
    below).  The device path pads to ``bucket_floor``; the cpu fallback
    keeps floor 1 (padding would only add serial native work)."""
    out = []
    for meth in batch_meths:
        fb = functools.partial(meth, fallback, 1) if fallback is not None else None
        op = meth.__name__.strip("_").removesuffix("_batch").removesuffix("_")
        out.append(
            OpQueue(functools.partial(meth, algo, bucket_floor), max_batch,
                    max_wait_ms, fallback_fn=fb, breaker=breaker,
                    bucket_floor=bucket_floor, scheduler=scheduler,
                    lane_capacity=lane_capacity,
                    label=f"{algo.name}.{op}", **degrade_opts)
        )
    return out


def _facade_breaker(breaker, cooloff_s, scheduler=None):
    if scheduler is not None:
        if breaker is not None or cooloff_s is not None:
            raise ValueError("pass either scheduler or breaker/cooloff_s — "
                             "a scheduler owns one breaker per shard")
        return scheduler.shards[0].breaker  # the compat/metrics handle
    if breaker is not None:
        if cooloff_s is not None:
            raise ValueError("pass either breaker or cooloff_s, not both "
                             "(an explicit breaker carries its own cool-off)")
        return breaker
    return Breaker(cooloff_s if cooloff_s is not None else 30.0)


def _shard_placements(scheduler):
    """``(shard_index, placement context)`` pairs a facade warmup must
    compile under: one per CLOSED shard (jit caches are per device — a
    program warmed only on shard 0 would cold-compile inside shard 3's
    first live dispatch; a sick shard is skipped so its hung device
    cannot stall the sweep), or one ``(None, null context)`` for the
    classic single-device path (also the no-healthy-shard fallback:
    compiling the default-device program keeps the warmup contract's
    shape, and every claim routes to the cpu fallback until a shard
    heals anyway).  The index rides into the cost ledger's compile
    attribution (obs/cost.py)."""
    import contextlib

    if scheduler is None:
        yield None, contextlib.nullcontext()
        return
    warm = scheduler.warmable_shards()
    if not warm:
        yield None, contextlib.nullcontext()
        return
    for sh in warm:
        yield sh.index, sh.placement()


def facade_queues(facade):
    """The live OpQueues of one batched facade — BatchedKEM owns
    ``_kg``/``_enc``/``_dec``, BatchedSignature ``_sign``/``_verify``,
    BatchedFused the first three.  THE single source the engine-side
    attach loops iterate (the autotuner's ``attach_facades`` and the cost
    ledger's ``_attach_cost``): a queue added to a facade joins every
    observer by appearing here, instead of in N copied attribute lists."""
    for attr in ("_kg", "_enc", "_dec", "_sign", "_verify", "_seal", "_open"):
        q = getattr(facade, attr, None)
        if q is not None:
            yield q


def _timed_warm(facade, n: int, shard_idx: int | None) -> None:
    """Run one facade ``_warm_one`` under the clock and attribute its
    compile wall seconds to the cost ledger (obs/cost.py): one
    ``where="warmup"`` event per (shard, bucket) the background sweep
    compiled — the other half of the warmup-vs-in-flush attribution."""
    t0 = time.perf_counter()
    facade._warm_one(n)
    if facade.cost is not None:
        facade.cost.compile_event(
            facade.name, max(facade.bucket_floor, _next_pow2(n)),
            time.perf_counter() - t0, where="warmup", shard=shard_idx)


class BatchedAEAD:
    """Async facade over a ``BatchedAEADOps`` capability: the DATA plane.

    Bulk AEAD seal/open ops from every live session coalesce on the SAME
    OpQueue → scheduler → autotuner → breaker machinery as the KEM/
    signature facades — by default on :data:`LANE_BULK`, so a bulk flood
    defers bulk, never the rekey/handshake lanes sharing the queue window.

    Wire-format parity with the scalar path is structural: ``encrypt``
    prepends the same random 12-byte nonce ``SymmetricAlgorithm.encrypt``
    does, and the device seal/open is KAT-pinned bit-exact against the
    scalar twin at every length bucket (tests/test_chacha_pallas.py) — a
    peer cannot tell which path sealed a frame.

    ``scalar`` (the same-name scalar provider — OpenSSL wheel, or the
    pyref twin on wheel-less images) arms the degrade-don't-fail fallback:
    a slow/hung/raising device trips the shared breaker and messages are
    sealed on the cpu instead of failing.  Items longer than the device's
    bucket caps never enqueue at all — they run on the scalar path in an
    executor (one oversized file send must not compile a giant one-off
    device program or stall the loop).

    Zero-copy: plaintext/ciphertext operands may be ``memoryview``s (the
    binary wire path hands socket-buffer views straight through);
    ``np.frombuffer`` packs them into the device batch without an
    intermediate copy.
    """

    def __init__(self, device, scalar, max_batch: int = 4096,
                 max_wait_ms: float = 2.0,
                 breaker: Breaker | None = None,
                 cooloff_s: float | None = None,
                 bucket_floor: int = 1,
                 scheduler=None,
                 lane_capacity: dict[int, int] | None = None,
                 warm_shapes: tuple = ((256, 256), (1024, 256)),
                 **degrade_opts):
        self.device = device
        self.scalar = scalar
        #: the cpu-fallback handle the health gate checks (health.py)
        self.fallback = scalar
        self.name = device.name
        self.key_size = device.key_size
        self.nonce_size = device.nonce_size
        self.tag_size = device.tag_size
        self.bucket_floor = min(_next_pow2(max(1, bucket_floor)), max_batch)
        self.scheduler = scheduler
        #: cost ledger (obs/cost.py): warmup compile attribution
        self.cost = None
        #: (msg_len, aad_len) bucket pairs the background warmup compiles;
        #: storm/bench callers override to match their live payload shape
        self.warm_shapes = tuple(warm_shapes)
        self.breaker = _facade_breaker(breaker, cooloff_s, scheduler)
        self._seal, self._open = (
            OpQueue(batch_fn, max_batch, max_wait_ms, fallback_fn=fb,
                    breaker=None if scheduler is not None else self.breaker,
                    bucket_floor=self.bucket_floor, scheduler=scheduler,
                    lane_capacity=lane_capacity, warm_check=warm,
                    label=f"{device.name}.{op}", **degrade_opts)
            for batch_fn, fb, op, warm in (
                (self._seal_batch, self._seal_fallback, "seal",
                 self._seal_covered),
                (self._open_batch, self._open_fallback, "open",
                 self._open_covered),
            )
        )

    # -- validity (attacker-malformed operands fail alone, never the batch) --

    def _seal_valid(self, it) -> bool:
        key, nonce, pt, aad = it
        return (len(key) == self.key_size
                and len(nonce) == self.nonce_size
                and len(pt) <= self.device.max_len
                and len(aad) <= self.device.max_aad_len)

    def _open_valid(self, it) -> bool:
        key, nonce, data, aad = it
        return (len(key) == self.key_size
                and len(nonce) == self.nonce_size
                and self.tag_size <= len(data)
                and len(data) - self.tag_size <= self.device.max_len
                and len(aad) <= self.device.max_aad_len)

    # -- shape-aware warm checks (the OpQueue's second warm axis) ------------

    def _seal_covered(self, items, bucket: int) -> bool:
        valid = [it for it in items if self._seal_valid(it)]
        if not valid:
            return True
        return self.device.covers(True, bucket,
                                  max(len(it[2]) for it in valid),
                                  max(len(it[3]) for it in valid))

    def _open_covered(self, items, bucket: int) -> bool:
        valid = [it for it in items if self._open_valid(it)]
        if not valid:
            return True
        return self.device.covers(False, bucket,
                                  max(len(it[2]) - self.tag_size
                                      for it in valid),
                                  max(len(it[3]) for it in valid))

    # -- batch fns -----------------------------------------------------------

    @staticmethod
    def _rows(valid, idx, tgt):
        return _pad_rows(
            np.stack([np.frombuffer(it[idx], np.uint8) for it in valid]), tgt)

    def _seal_batch(self, items):
        def dispatch(valid, tgt):
            pad = tgt - len(valid)
            out = self.device.seal_batch(
                self._rows(valid, 0, tgt), self._rows(valid, 1, tgt),
                [it[2] for it in valid] + [valid[-1][2]] * pad,
                [it[3] for it in valid] + [valid[-1][3]] * pad,
            )
            return out

        return _run_valid(items, self._seal_valid, dispatch,
                          lambda: ValueError("bad AEAD seal operand"),
                          self.bucket_floor)

    def _open_batch(self, items):
        def dispatch(valid, tgt):
            pad = tgt - len(valid)
            return self.device.open_batch(
                self._rows(valid, 0, tgt), self._rows(valid, 1, tgt),
                [it[2] for it in valid] + [valid[-1][2]] * pad,
                [it[3] for it in valid] + [valid[-1][3]] * pad,
            )

        # the open contract maps EVERY malformed input to the same typed
        # failure the scalar decrypt raises — never a distinguishable crash
        return _run_valid(items, self._open_valid, dispatch,
                          lambda: ValueError("authentication failed"),
                          self.bucket_floor)

    # -- cpu scalar fallbacks (wire-identical) -------------------------------

    def _seal_fallback(self, items):
        def dispatch(valid, _tgt):
            return [self.scalar.seal(k, n, bytes(p), bytes(a) or None)
                    for k, n, p, a in valid]

        return _run_valid(items, self._seal_valid, dispatch,
                          lambda: ValueError("bad AEAD seal operand"), 1)

    def _open_fallback(self, items):
        def dispatch(valid, _tgt):
            out = []
            for k, n, d, a in valid:
                try:
                    out.append(self.scalar.open_(k, n, bytes(d),
                                                 bytes(a) or None))
                except ValueError as e:
                    out.append(ValueError(str(e)))
            return out

        return _run_valid(items, self._open_valid, dispatch,
                          lambda: ValueError("authentication failed"), 1)

    # -- async surface (scalar-compatible byte layouts) ----------------------

    async def encrypt(self, key: bytes, plaintext, associated_data=None,
                      lane: int = LANE_BULK) -> bytes:
        """-> ``nonce || ciphertext || tag`` — byte-compatible with the
        scalar ``SymmetricAlgorithm.encrypt``."""
        ad = bytes(associated_data) if associated_data else b""
        if (len(plaintext) > self.device.max_len
                or len(ad) > self.device.max_aad_len):
            # oversized for the device bucket space: scalar path, off-loop
            # (a wheel-less pure-Python seal of a big file must not stall
            # every peer this loop serves)
            if self.cost is not None:
                # keep the ledger's device-served story truthful: this item
                # never enqueues, so the occupancy rows never see it
                self.cost.bypass_items(f"{self.name}.seal", "oversize")
            return await asyncio.get_running_loop().run_in_executor(
                None, functools.partial(
                    self.scalar.encrypt, bytes(key), bytes(plaintext),
                    ad or None))
        nonce = os.urandom(self.nonce_size)
        ct_tag = await self._seal.submit((bytes(key), nonce, plaintext, ad),
                                         lane)
        return nonce + ct_tag

    async def decrypt(self, key: bytes, data, associated_data=None,
                      lane: int = LANE_BULK) -> bytes:
        """Open ``nonce || ciphertext || tag``; ValueError on failure —
        the scalar decrypt contract.  ``data`` may be a memoryview (the
        binary wire's zero-copy socket-buffer slice)."""
        ad = bytes(associated_data) if associated_data else b""
        if len(data) < self.nonce_size + self.tag_size:
            raise ValueError("ciphertext too short")
        if (len(data) - self.nonce_size - self.tag_size > self.device.max_len
                or len(ad) > self.device.max_aad_len):
            if self.cost is not None:
                self.cost.bypass_items(f"{self.name}.open", "oversize")
            return await asyncio.get_running_loop().run_in_executor(
                None, functools.partial(
                    self.scalar.decrypt, bytes(key), bytes(data), ad or None))
        view = memoryview(data)
        return await self._open.submit(
            (bytes(key), bytes(view[: self.nonce_size]),
             view[self.nonce_size:], ad), lane)

    # -- warmup --------------------------------------------------------------

    def warmup(self, sizes: tuple[int, ...] = (1,)) -> None:
        """Compile seal/open for the pow2 batch buckets at every
        ``warm_shapes`` (msg, aad) bucket pair, then mark the buckets warm
        (blocking; run on the warmup thread).  Under a scheduler every
        size compiles on every shard first (see BatchedKEM.warmup)."""
        for shard_idx, placement in _shard_placements(self.scheduler):
            with placement:
                for n in sizes:
                    _timed_warm(self, n, shard_idx)
        for n in sizes:
            n2 = max(self.bucket_floor, _next_pow2(n))
            for q in (self._seal, self._open):
                q.mark_warm(n2)  # runs on the warmup thread: locked handoff

    def _warm_one(self, n: int) -> None:
        n2 = max(self.bucket_floor, _next_pow2(n))
        keys = np.zeros((n2, self.key_size), np.uint8)
        nonces = np.zeros((n2, self.nonce_size), np.uint8)
        for msg_len, aad_len in self.warm_shapes:
            pts = [bytes(msg_len)] * n2
            aads = [bytes(aad_len)] * n2
            sealed = self.device.seal_batch(keys, nonces, pts, aads)
            self.device.open_batch(keys, nonces, sealed, aads)

    def stats(self) -> dict[str, Any]:
        return {
            "seal": self._seal.stats.as_dict(),
            "open": self._open.stats.as_dict(),
        }


class BatchedKEM:
    """Async facade over a KeyExchangeAlgorithm's batch ops.

    ``fallback`` (a same-name cpu-backend provider) arms the OpQueues'
    degrade-don't-fail path: slow/hung device dispatches trip a breaker and
    ops run on the cpu instead of failing their protocol timeouts.
    """

    def __init__(self, algo: KeyExchangeAlgorithm, max_batch: int = 4096,
                 max_wait_ms: float = 2.0,
                 fallback: KeyExchangeAlgorithm | None = None,
                 breaker: Breaker | None = None,
                 cooloff_s: float | None = None,
                 bucket_floor: int = 1,
                 scheduler=None,
                 lane_capacity: dict[int, int] | None = None,
                 **degrade_opts):
        self.algo = algo
        self.fallback = fallback
        self.name = algo.name
        self.bucket_floor = min(_next_pow2(max(1, bucket_floor)), max_batch)
        #: placement axis shared with the sibling facades (None = classic)
        self.scheduler = scheduler
        #: cost ledger (obs/cost.py): warmup compile attribution
        self.cost = None
        # one breaker across keygen/encaps/decaps: the device is shared, so
        # any op discovering slowness shields the others immediately (per
        # SHARD under a scheduler — each shard carries its own)
        self.breaker = _facade_breaker(breaker, cooloff_s, scheduler)
        self._kg, self._enc, self._dec = _make_queues(
            algo, fallback, None if scheduler is not None else self.breaker,
            max_batch, max_wait_ms,
            (self._kg_batch, self._enc_batch, self._dec_batch), degrade_opts,
            self.bucket_floor, scheduler, lane_capacity,
        )

    @staticmethod
    def _kg_batch(algo, floor, items: list[None]) -> list[tuple[bytes, bytes]]:
        n = len(items)
        pks, sks = algo.generate_keypair_batch(max(floor, _next_pow2(n)))
        return [(bytes(pk), bytes(sk)) for pk, sk in zip(pks[:n], sks[:n])]

    @staticmethod
    def _enc_batch(algo, floor, items: list[bytes]):
        def dispatch(valid, tgt):
            pks = _pad_rows(np.stack([np.frombuffer(pk, np.uint8) for pk in valid]), tgt)
            cts, sss = algo.encapsulate_batch(pks)
            return [(bytes(ct), bytes(ss)) for ct, ss in zip(cts, sss)]

        return _run_valid(
            items,
            lambda pk: len(pk) == algo.public_key_len,
            dispatch,
            lambda: ValueError("bad public-key length"),
            floor,
        )

    @staticmethod
    def _dec_batch(algo, floor, items: list[tuple[bytes, bytes]]):
        def dispatch(valid, tgt):
            sks = _pad_rows(np.stack([np.frombuffer(sk, np.uint8) for sk, _ in valid]), tgt)
            cts = _pad_rows(np.stack([np.frombuffer(ct, np.uint8) for _, ct in valid]), tgt)
            return [bytes(ss) for ss in algo.decapsulate_batch(sks, cts)]

        return _run_valid(
            items,
            lambda it: (
                len(it[0]) == algo.secret_key_len
                and len(it[1]) == algo.ciphertext_len
            ),
            dispatch,
            lambda: ValueError("bad secret-key/ciphertext length"),
            floor,
        )

    def warmup(self, sizes: tuple[int, ...] = (1,)) -> None:
        """Compile the pow2 buckets a live queue will hit (blocking; run in a
        background thread).  Cold jit of the first handshake's size-1 bucket
        otherwise races the protocol timeout (SURVEY.md §7.4 item 6).

        Single-key encaps batches (every handshake; swarm hot peers) take
        the operand-cache fast path — different jit programs on miss
        (``_enc_cold``) and hit (``_enc_pre``) — so each size additionally
        runs a same-key pair of encaps calls to compile both.

        Under a scheduler every size compiles on EVERY shard (jit caches
        are per device; the opcache partitions per shard) before the
        bucket is marked warm — a warm bucket means warm wherever the
        placement policy can put a flush."""
        for shard_idx, placement in _shard_placements(self.scheduler):
            with placement:
                for n in sizes:
                    _timed_warm(self, n, shard_idx)
        for n in sizes:
            n2 = max(self.bucket_floor, _next_pow2(n))
            for q in (self._kg, self._enc, self._dec):
                q.mark_warm(n2)  # runs on the warmup thread: locked handoff

    def _warm_one(self, n: int) -> None:
        # compile the shape the live bucket will use
        n2 = max(self.bucket_floor, _next_pow2(n))
        pks, sks = self.algo.generate_keypair_batch(n2)
        # distinct keys: at n2 > 1 this compiles the mixed-key sliced
        # program; at n2 == 1 a single row takes the same opcache path
        # live batch-1 encaps always takes, so nothing is missed
        cts, _ = self.algo.encapsulate_batch(pks)
        self.algo.decapsulate_batch(sks, cts)
        if getattr(self.algo, "opcache", None) is not None:
            same = np.repeat(np.asarray(pks)[:1], n2, axis=0)
            self.algo.encapsulate_batch(same)  # cache miss: _enc_cold
            self.algo.encapsulate_batch(same)  # cache hit:  _enc_pre
        wipe(sks)  # warmup-only key material

    async def generate_keypair(self, lane: int = LANE_HANDSHAKE) -> tuple[bytes, bytes]:
        return await self._kg.submit(None, lane)

    async def encapsulate(self, public_key: bytes,
                          lane: int = LANE_HANDSHAKE) -> tuple[bytes, bytes]:
        return await self._enc.submit(public_key, lane)

    async def decapsulate(self, secret_key: bytes, ciphertext: bytes,
                          lane: int = LANE_HANDSHAKE) -> bytes:
        return await self._dec.submit((secret_key, ciphertext), lane)

    def stats(self) -> dict[str, Any]:
        return {
            "keygen": self._kg.stats.as_dict(),
            "encaps": self._enc.stats.as_dict(),
            "decaps": self._dec.stats.as_dict(),
        }


class BatchedSignature:
    """Async facade over a SignatureAlgorithm's batch ops.

    ``fallback`` mirrors BatchedKEM: a cpu-backend provider serving ops
    while the device path is slow or hung.
    """

    def __init__(self, algo: SignatureAlgorithm, max_batch: int = 4096,
                 max_wait_ms: float = 2.0,
                 fallback: SignatureAlgorithm | None = None,
                 breaker: Breaker | None = None,
                 cooloff_s: float | None = None,
                 bucket_floor: int = 1,
                 scheduler=None,
                 lane_capacity: dict[int, int] | None = None,
                 **degrade_opts):
        self.algo = algo
        self.fallback = fallback
        self.name = algo.name
        self.bucket_floor = min(_next_pow2(max(1, bucket_floor)), max_batch)
        self.scheduler = scheduler
        #: cost ledger (obs/cost.py): warmup compile attribution
        self.cost = None
        self.breaker = _facade_breaker(breaker, cooloff_s, scheduler)
        self._sign, self._verify = _make_queues(
            algo, fallback, None if scheduler is not None else self.breaker,
            max_batch, max_wait_ms,
            (self._sign_batch, self._verify_batch), degrade_opts,
            self.bucket_floor, scheduler, lane_capacity,
        )

    @staticmethod
    def _sign_batch(algo, floor, items: list[tuple[bytes, bytes]]):
        def dispatch(valid, tgt):
            sks = _pad_rows(np.stack([np.frombuffer(sk, np.uint8) for sk, _ in valid]), tgt)
            msgs = [m for _, m in valid] + [valid[-1][1]] * (tgt - len(valid))
            return algo.sign_batch(sks, msgs)

        return _run_valid(
            items,
            lambda it: len(it[0]) == algo.secret_key_len,
            dispatch,
            lambda: ValueError("bad secret-key length"),
            floor,
        )

    @staticmethod
    def _verify_batch(algo, floor, items: list[tuple[bytes, bytes, bytes]]) -> list[bool]:
        # Per the verify contract, malformed input means False — never raise.
        def dispatch(valid, tgt):
            pks = _pad_rows(np.stack([np.frombuffer(pk, np.uint8) for pk, _, _ in valid]), tgt)
            pad = tgt - len(valid)
            msgs = [m for _, m, _ in valid] + [valid[-1][1]] * pad
            sigs = [s for _, _, s in valid] + [valid[-1][2]] * pad
            try:
                oks = algo.verify_batch(pks, msgs, sigs)
            except Exception:  # qrlint: disable=broad-except  — verify contract: malformed input means False for the whole batch, never an exception
                oks = [False] * tgt
            return [bool(ok) for ok in oks]

        return _run_valid(
            items,
            lambda it: (
                len(it[0]) == algo.public_key_len
                and len(it[2]) == algo.signature_len
            ),
            dispatch,
            lambda: False,
            floor,
        )

    def warmup(self, sizes: tuple[int, ...] = (1,)) -> None:
        """Compile keygen/sign/verify for the pow2 buckets (blocking).

        Single-key batches (a node's own long-lived sign key; a repeat
        peer's verify key) take the operand-cache fast path, which runs
        DIFFERENT jit programs on miss (cache-filling ``*_cold``) and hit
        (``*_pre``) — so each size runs twice with a key fresh to the
        cache: the first call compiles the cold program, the second the
        hit program.  Otherwise a "warm" bucket's first cache hit cold-jits
        inside a live device dispatch and trips the breaker.

        Under a scheduler every size compiles on EVERY shard before the
        bucket is marked warm (see BatchedKEM.warmup)."""
        for shard_idx, placement in _shard_placements(self.scheduler):
            with placement:
                for n in sizes:
                    _timed_warm(self, n, shard_idx)
        for n in sizes:
            n2 = max(self.bucket_floor, _next_pow2(n))
            for q in (self._sign, self._verify):
                q.mark_warm(n2)  # runs on the warmup thread: locked handoff

    def _warm_one(self, n: int) -> None:
        have_cache = getattr(self.algo, "opcache", None) is not None
        # fresh key per size: the opcache persists across sizes, and a
        # cached key would skip the cold-program compile for this shape
        pk, sk = self.algo.generate_keypair()
        # compile the shape the live bucket will use
        n2 = max(self.bucket_floor, _next_pow2(n))
        sks = np.stack([np.frombuffer(sk, np.uint8)] * n2)
        pks = np.stack([np.frombuffer(pk, np.uint8)] * n2)
        reps = 2 if have_cache else 1
        for _ in range(reps):
            sigs = self.algo.sign_batch(sks, [b"warmup"] * n2)
        for _ in range(reps):
            self.algo.verify_batch(pks, [b"warmup"] * n2, sigs)
        if have_cache and n2 > 1:
            # distinct keys: compile the MIXED-key programs that the
            # same-key stacks above divert away from (live flushes
            # coalescing >= 2 clients' ops carry distinct keys)
            pks_d, sks_d = self.algo.generate_keypair_batch(n2)
            sigs_d = self.algo.sign_batch(sks_d, [b"warmup"] * n2)
            self.algo.verify_batch(pks_d, [b"warmup"] * n2, sigs_d)
            wipe(sks_d)
        wipe(sk)  # warmup-only key material

    async def sign(self, secret_key: bytes, message: bytes,
                   lane: int = LANE_HANDSHAKE) -> bytes:
        return await self._sign.submit((secret_key, message), lane)

    async def verify(self, public_key: bytes, message: bytes, signature: bytes,
                     lane: int = LANE_HANDSHAKE) -> bool:
        return await self._verify.submit((public_key, message, signature), lane)

    def stats(self) -> dict[str, Any]:
        return {
            "sign": self._sign.stats.as_dict(),
            "verify": self._verify.stats.as_dict(),
        }


class BatchedFused:
    """Async facade over a ``FusedHandshakeOps`` capability: three composite
    queues (keygen+sign / verify+encaps+sign / verify+decaps+sign) that
    collapse a handshake step's 2-3 serial device trips into one dispatch.

    Shares the per-op facades' breaker, so composite and per-op batches
    coalesce into one scheduling window (Breaker.coalesce) and a slow
    tunnel discovered by either shields both.

    ``pk_off``/``ct_off`` are the static byte offsets of the hex-encoded
    device output inside the init/response transcript templates — protocol
    facts the caller (SecureMessaging) computes from its canonical-JSON
    layout; jit keys on them, so one facade serves one protocol layout.

    Fallback (armed when BOTH cpu twins are given): the same step composed
    from per-op cpu calls — verify, kem op, host-side hex render into the
    template, sign — producing wire-identical bytes, so a tripped breaker
    degrades to cpu per-op work instead of failing handshakes.  A missing
    capability never reaches this class: registry.get_fused returns None
    and SecureMessaging stays on the per-op queues entirely.

    Attacker-controlled fields (peer signature key, incoming signature) are
    length-checked per item and fail as ``ok=False`` — matching the verify
    contract — while malformed LOCAL operands (own secret key, template)
    raise, matching the per-op queues.
    """

    def __init__(self, fused, pk_off: int, ct_off: int, max_batch: int = 4096,
                 max_wait_ms: float = 2.0, fallback_kem=None, fallback_sig=None,
                 breaker: Breaker | None = None, cooloff_s: float | None = None,
                 bucket_floor: int = 1, scheduler=None,
                 lane_capacity: dict[int, int] | None = None, **degrade_opts):
        self.fused = fused
        self.name = fused.name
        self.pk_off = pk_off
        self.ct_off = ct_off
        self.bucket_floor = min(_next_pow2(max(1, bucket_floor)), max_batch)
        self.scheduler = scheduler
        #: cost ledger (obs/cost.py): warmup compile attribution
        self.cost = None
        self.breaker = _facade_breaker(breaker, cooloff_s, scheduler)
        self.fallback_kem = fallback_kem
        self.fallback_sig = fallback_sig
        have_fb = fallback_kem is not None and fallback_sig is not None
        self._kg, self._enc, self._dec = (
            OpQueue(batch_fn, max_batch, max_wait_ms,
                    fallback_fn=(fb if have_fb else None),
                    breaker=None if scheduler is not None else self.breaker,
                    bucket_floor=self.bucket_floor, scheduler=scheduler,
                    lane_capacity=lane_capacity,
                    label=f"{fused.name}.{op}", **degrade_opts)
            for batch_fn, fb, op in (
                (self._kg_batch, self._kg_fallback, "keygen_sign"),
                (self._enc_batch, self._enc_fallback, "encaps_verify_sign"),
                (self._dec_batch, self._dec_fallback, "decaps_verify_sign"),
            )
        )

    # -- validity (shared by device + fallback paths) -----------------------

    def _kg_valid(self, it) -> bool:
        sk, tmpl = it
        return (
            len(sk) == self.fused.sig.secret_key_len
            and self.pk_off + 2 * self.fused.kem.public_key_len <= len(tmpl)
            <= self.fused.init_template_len
        )

    def _enc_valid(self, it) -> bool:
        peer_pk, peer_sig_pk, _msg_in, sig_in, sk, tmpl = it
        return (
            len(peer_pk) == self.fused.kem.public_key_len
            and len(peer_sig_pk) == self.fused.sig.public_key_len
            and len(sig_in) == self.fused.sig.signature_len
            and len(sk) == self.fused.sig.secret_key_len
            and self.ct_off + 2 * self.fused.kem.ciphertext_len <= len(tmpl)
            <= self.fused.resp_template_len
        )

    def _dec_valid(self, it) -> bool:
        kem_sk, ct, peer_sig_pk, _msg_in, sig_in, sk, _msg_out = it
        return (
            len(kem_sk) == self.fused.kem.secret_key_len
            and len(ct) == self.fused.kem.ciphertext_len
            and len(peer_sig_pk) == self.fused.sig.public_key_len
            and len(sig_in) == self.fused.sig.signature_len
            and len(sk) == self.fused.sig.secret_key_len
        )

    @staticmethod
    def _render(tmpl: bytes, payload: bytes, off: int) -> bytes:
        """Host-side twin of the device hex-insert (fused.mlkem_mldsa)."""
        return tmpl[:off] + payload.hex().encode() + tmpl[off + 2 * len(payload):]

    # -- device batch fns ---------------------------------------------------

    def _kg_batch(self, items):
        def dispatch(valid, tgt):
            sks = _pad_rows(
                np.stack([np.frombuffer(sk, np.uint8) for sk, _ in valid]), tgt
            )
            tmpls = [t for _, t in valid] + [valid[-1][1]] * (tgt - len(valid))
            pks, ksks, sigs = self.fused.keygen_sign_batch(sks, tmpls, self.pk_off)
            return list(zip((bytes(p) for p in pks), (bytes(k) for k in ksks), sigs))

        return _run_valid(
            items, self._kg_valid, dispatch,
            lambda: ValueError("bad secret-key/template length"),
            self.bucket_floor,
        )

    def _enc_batch(self, items):
        def dispatch(valid, tgt):
            pad = tgt - len(valid)
            pks = _pad_rows(
                np.stack([np.frombuffer(it[0], np.uint8) for it in valid]), tgt
            )
            spks = _pad_rows(
                np.stack([np.frombuffer(it[1], np.uint8) for it in valid]), tgt
            )
            msgs = [it[2] for it in valid] + [valid[-1][2]] * pad
            sigs_in = [it[3] for it in valid] + [valid[-1][3]] * pad
            sks = _pad_rows(
                np.stack([np.frombuffer(it[4], np.uint8) for it in valid]), tgt
            )
            tmpls = [it[5] for it in valid] + [valid[-1][5]] * pad
            oks, cts, sss, sigs = self.fused.encaps_verify_sign_batch(
                pks, spks, msgs, sigs_in, sks, tmpls, self.ct_off
            )
            return [
                (bool(ok), bytes(ct), bytes(ss), sig)
                for ok, ct, ss, sig in zip(oks, cts, sss, sigs)
            ]

        return _run_valid(
            items, self._enc_valid, dispatch,
            lambda: (False, b"", b"", b""),  # verify contract: malformed -> False
            self.bucket_floor,
        )

    def _dec_batch(self, items):
        def dispatch(valid, tgt):
            pad = tgt - len(valid)
            ksks = _pad_rows(
                np.stack([np.frombuffer(it[0], np.uint8) for it in valid]), tgt
            )
            cts = _pad_rows(
                np.stack([np.frombuffer(it[1], np.uint8) for it in valid]), tgt
            )
            spks = _pad_rows(
                np.stack([np.frombuffer(it[2], np.uint8) for it in valid]), tgt
            )
            msgs = [it[3] for it in valid] + [valid[-1][3]] * pad
            sigs_in = [it[4] for it in valid] + [valid[-1][4]] * pad
            sks = _pad_rows(
                np.stack([np.frombuffer(it[5], np.uint8) for it in valid]), tgt
            )
            msgs_out = [it[6] for it in valid] + [valid[-1][6]] * pad
            oks, sss, sigs = self.fused.decaps_verify_sign_batch(
                ksks, cts, spks, msgs, sigs_in, sks, msgs_out
            )
            return [
                (bool(ok), bytes(ss), sig) for ok, ss, sig in zip(oks, sss, sigs)
            ]

        return _run_valid(
            items, self._dec_valid, dispatch,
            lambda: (False, b"", b""),
            self.bucket_floor,
        )

    # -- cpu per-op fallbacks (wire-identical composition) ------------------

    def _kg_fallback(self, items):
        def dispatch(valid, _tgt):
            out = []
            for sk, tmpl in valid:
                pk, ksk = self.fallback_kem.generate_keypair()
                sig = self.fallback_sig.sign(sk, self._render(tmpl, pk, self.pk_off))
                out.append((pk, ksk, sig))
            return out

        return _run_valid(
            items, self._kg_valid, dispatch,
            lambda: ValueError("bad secret-key/template length"), 1,
        )

    def _enc_fallback(self, items):
        def dispatch(valid, _tgt):
            out = []
            for peer_pk, peer_sig_pk, msg_in, sig_in, sk, tmpl in valid:
                if not self.fallback_sig.verify(peer_sig_pk, msg_in, sig_in):
                    out.append((False, b"", b"", b""))
                    continue
                ct, ss = self.fallback_kem.encapsulate(peer_pk)
                sig = self.fallback_sig.sign(sk, self._render(tmpl, ct, self.ct_off))
                out.append((True, ct, ss, sig))
            return out

        return _run_valid(
            items, self._enc_valid, dispatch, lambda: (False, b"", b"", b""), 1,
        )

    def _dec_fallback(self, items):
        def dispatch(valid, _tgt):
            out = []
            for kem_sk, ct, peer_sig_pk, msg_in, sig_in, sk, msg_out in valid:
                if not self.fallback_sig.verify(peer_sig_pk, msg_in, sig_in):
                    out.append((False, b"", b""))
                    continue
                ss = self.fallback_kem.decapsulate(kem_sk, ct)
                out.append((True, ss, self.fallback_sig.sign(sk, msg_out)))
            return out

        return _run_valid(
            items, self._dec_valid, dispatch, lambda: (False, b"", b""), 1,
        )

    # -- async surface ------------------------------------------------------

    async def keygen_sign(self, sig_sk: bytes, template: bytes,
                          lane: int = LANE_HANDSHAKE):
        """-> (kem_pk, kem_sk, sig) for the init step, one device trip."""
        return await self._kg.submit((sig_sk, template), lane)

    async def encaps_verify_sign(self, peer_pk: bytes, peer_sig_pk: bytes,
                                 msg_in: bytes, sig_in: bytes, sig_sk: bytes,
                                 template: bytes, lane: int = LANE_HANDSHAKE):
        """-> (ok, ct, shared_secret, sig) for the response step."""
        return await self._enc.submit(
            (peer_pk, peer_sig_pk, msg_in, sig_in, sig_sk, template), lane
        )

    async def decaps_verify_sign(self, kem_sk: bytes, ct: bytes,
                                 peer_sig_pk: bytes, msg_in: bytes,
                                 sig_in: bytes, sig_sk: bytes, msg_out: bytes,
                                 lane: int = LANE_HANDSHAKE):
        """-> (ok, shared_secret, sig) for the confirm step."""
        return await self._dec.submit(
            (kem_sk, ct, peer_sig_pk, msg_in, sig_in, sig_sk, msg_out), lane
        )

    def warmup(self, sizes: tuple[int, ...] = (1,)) -> None:
        """Compile the composite programs at the LIVE offsets (jit keys on
        them) for the given pow2 buckets and mark those buckets warm.
        Sizes are raised to the facade's bucket floor FIRST — the fused
        capability compiles exactly the shapes it is handed, and live
        flushes pad to the floor, so compiling un-raised sizes would mark
        buckets warm that were never compiled.  Under a scheduler the
        composite programs compile on every shard before marking."""
        buckets = sorted({max(self.bucket_floor, _next_pow2(n)) for n in sizes})
        for shard_idx, placement in _shard_placements(self.scheduler):
            with placement:
                for b in buckets:
                    # per-bucket calls so each compile's wall seconds can
                    # be attributed individually (the sweep compiles the
                    # same shapes either way)
                    t0 = time.perf_counter()
                    self.fused.warmup((b,), pk_off=self.pk_off,
                                      ct_off=self.ct_off)
                    if self.cost is not None:
                        self.cost.compile_event(
                            self.name, b, time.perf_counter() - t0,
                            where="warmup", shard=shard_idx)
        for q in (self._kg, self._enc, self._dec):
            for b in buckets:
                q.mark_warm(b)  # runs on the warmup thread: locked handoff

    def stats(self) -> dict[str, Any]:
        return {
            "keygen_sign": self._kg.stats.as_dict(),
            "encaps_verify_sign": self._enc.stats.as_dict(),
            "decaps_verify_sign": self._dec.stats.as_dict(),
        }
