"""Algorithm registry — the factory the reference lacked.

The reference resolves peer algorithm names by string-matching display names
back into classes (app/messaging.py:1893-2011).  Here every algorithm has a
canonical name in an explicit registry; lookups accept canonical names and the
backend is an orthogonal axis ("cpu" | "tpu" | "auto").

Registered families (full parity with the reference's Crypto Settings matrix
of 9 KEMs x 2 AEADs x 6 signatures, ui/settings_dialog.py:108-172 — plus the
AES/SHAKE FrodoKEM split exposed as distinct names and the SLH-DSA
small-signature 's' variants, BASELINE.json config 4):

  KEM:  ML-KEM-512/768/1024                     (cpu + tpu)
        FrodoKEM-640/976/1344-{AES,SHAKE}       (cpu + tpu)
        HQC-128/192/256                         (cpu + tpu)
  SIG:  ML-DSA-44/65/87                         (cpu + tpu)
        SPHINCS+-SHA2-{128,192,256}{s,f}-simple (cpu + tpu)
  AEAD: AES-256-GCM, ChaCha20-Poly1305 (host)
"""

from __future__ import annotations

from typing import Callable

from .base import (BatchedAEADOps, FusedHandshakeOps, KeyExchangeAlgorithm,
                   SignatureAlgorithm, SymmetricAlgorithm)
from .symmetric import AES256GCM, ChaCha20Poly1305

# name -> (factory(backend, devices) -> algorithm, supported_backends)
_KEMS: dict[str, tuple[Callable[[str, int], KeyExchangeAlgorithm], tuple[str, ...]]] = {}
_SIGS: dict[str, tuple[Callable[[str, int], SignatureAlgorithm], tuple[str, ...]]] = {}
_AEADS: dict[str, Callable[[], SymmetricAlgorithm]] = {
    "AES-256-GCM": AES256GCM,
    "ChaCha20-Poly1305": ChaCha20Poly1305,
}
# (kem name, sig name) -> factory(kem, sig) -> FusedHandshakeOps
_FUSED: dict[tuple[str, str], Callable] = {}
# AEAD name -> factory() -> BatchedAEADOps (the batched device capability)
_BATCHED_AEADS: dict[str, Callable[[], BatchedAEADOps]] = {}


def register_kem(name: str, factory, backends: tuple[str, ...]) -> None:
    _KEMS[name] = (factory, backends)


def register_signature(name: str, factory, backends: tuple[str, ...]) -> None:
    _SIGS[name] = (factory, backends)


def register_fused(kem_name: str, sig_name: str, factory) -> None:
    """Register a composite-op capability for a (KEM, signature) pair.
    ``factory(kem, sig)`` wraps EXISTING provider instances (the composite
    programs reuse their jitted cores) and must return a
    ``provider.base.FusedHandshakeOps``."""
    _FUSED[(kem_name, sig_name)] = factory


def _resolve_backend(requested: str, supported: tuple[str, ...]) -> str:
    if requested == "auto":
        return "tpu" if "tpu" in supported else "cpu"
    if requested not in supported:
        raise ValueError(f"backend {requested!r} not supported (have {supported})")
    return requested


def get_kem(name: str, backend: str = "auto", devices: int = 0) -> KeyExchangeAlgorithm:
    """``devices`` > 0 shards tpu-backend batches across a device mesh
    (Config.mesh_devices); ignored by the cpu backend."""
    if name not in _KEMS:
        raise KeyError(f"unknown KEM {name!r}; known: {sorted(_KEMS)}")
    factory, backends = _KEMS[name]
    return factory(_resolve_backend(backend, backends), devices)


def get_signature(name: str, backend: str = "auto", devices: int = 0) -> SignatureAlgorithm:
    """``devices`` > 0 shards tpu-backend batches across a device mesh
    (Config.mesh_devices); ignored by the cpu backend."""
    if name not in _SIGS:
        raise KeyError(f"unknown signature {name!r}; known: {sorted(_SIGS)}")
    factory, backends = _SIGS[name]
    return factory(_resolve_backend(backend, backends), devices)


def get_fused(kem: KeyExchangeAlgorithm,
              sig: SignatureAlgorithm) -> FusedHandshakeOps | None:
    """Composite-op capability for an existing provider pair, or ``None``
    when absent (unregistered pair, or either side not tpu-backed) — the
    caller then stays on the per-op path.  Never raises on lookup."""
    if getattr(kem, "backend", "") != "tpu" or getattr(sig, "backend", "") != "tpu":
        return None
    factory = _FUSED.get((getattr(kem, "name", None), getattr(sig, "name", None)))
    if factory is None:
        return None
    return factory(kem, sig)


def get_symmetric(name: str) -> SymmetricAlgorithm:
    if name not in _AEADS:
        raise KeyError(f"unknown AEAD {name!r}; known: {sorted(_AEADS)}")
    return _AEADS[name]()


def register_batched_aead(name: str, factory: Callable[[], BatchedAEADOps]) -> None:
    """Register the batched device capability for one AEAD name.  The
    factory runs lazily inside :func:`get_batched_aead` so registering
    never imports jax (cpu-only and wheel-less callers pay nothing)."""
    _BATCHED_AEADS[name] = factory


def get_batched_aead(symmetric) -> BatchedAEADOps | None:
    """Batched device AEAD capability for a symmetric algorithm (instance
    or name), or ``None`` when absent — unregistered AEAD, jax
    unavailable, or ``QRP2P_BATCH_AEAD=0`` (the kill switch that pins
    every caller to the scalar path).  Never raises on lookup."""
    import logging
    import os

    if os.environ.get("QRP2P_BATCH_AEAD", "1") == "0":
        return None
    name = getattr(symmetric, "name", symmetric)
    factory = _BATCHED_AEADS.get(name)
    if factory is None:
        return None
    try:
        return factory()
    except Exception:  # qrlint: disable=broad-except  — capability probe: any import/device failure means "no batched AEAD here", the scalar path serves
        logging.getLogger(__name__).warning(
            "batched AEAD capability for %s unavailable; scalar path serves",
            name, exc_info=True)
        return None


def list_batched_aeads() -> list[str]:
    return sorted(_BATCHED_AEADS)


def list_kems() -> list[str]:
    return sorted(_KEMS)


def list_signatures() -> list[str]:
    return sorted(_SIGS)


def list_symmetrics() -> list[str]:
    return sorted(_AEADS)


def list_fused() -> list[tuple[str, str]]:
    return sorted(_FUSED)


# -- default registrations ---------------------------------------------------

def _register_defaults() -> None:
    from .fused_providers import FusedMLKEMMLDSA
    from .kem_providers import FrodoKEMKeyExchange, HQCKeyExchange, MLKEMKeyExchange
    from .sig_providers import MLDSASignature, SPHINCSSignature

    for level, name in ((1, "ML-KEM-512"), (3, "ML-KEM-768"), (5, "ML-KEM-1024")):
        register_kem(
            name,
            lambda backend, devices=0, _level=level: MLKEMKeyExchange(
                _level, backend, devices=devices
            ),
            ("cpu", "tpu"),
        )
    for level, size in ((1, 640), (3, 976), (5, 1344)):
        for aes in (True, False):
            register_kem(
                f"FrodoKEM-{size}-{'AES' if aes else 'SHAKE'}",
                lambda backend, devices=0, _level=level, _aes=aes: FrodoKEMKeyExchange(
                    _level, backend, use_aes=_aes, devices=devices
                ),
                ("cpu", "tpu"),
            )
    for level, size in ((1, 128), (3, 192), (5, 256)):
        register_kem(
            f"HQC-{size}",
            lambda backend, devices=0, _level=level: HQCKeyExchange(
                _level, backend, devices=devices
            ),
            ("cpu", "tpu"),
        )
    for level, name in ((2, "ML-DSA-44"), (3, "ML-DSA-65"), (5, "ML-DSA-87")):
        register_signature(
            name,
            lambda backend, devices=0, _level=level: MLDSASignature(
                _level, backend, devices=devices
            ),
            ("cpu", "tpu"),
        )
    for level, size in ((1, 128), (3, 192), (5, 256)):
        for fast in (True, False):
            register_signature(
                f"SPHINCS+-SHA2-{size}{'f' if fast else 's'}-simple",
                lambda backend, devices=0, _level=level, _fast=fast: SPHINCSSignature(
                    _level, backend, fast=_fast, devices=devices
                ),
                ("cpu", "tpu"),
            )
    # Batched device AEAD capability (the data plane): ChaCha20-Poly1305
    # maps onto the Pallas/jnp ARX core; AES-GCM stays scalar (no device
    # kernel).  Deferred import: the factory touches jax only when a
    # batching caller actually asks for the capability.
    def _chacha_device():
        from .aead_device import ChaChaPolyDevice

        return ChaChaPolyDevice()

    register_batched_aead("ChaCha20-Poly1305", _chacha_device)

    # Composite handshake capability: every ML-KEM x ML-DSA pair shares the
    # same fused program shapes (fused/mlkem_mldsa.py), parameterized by the
    # pair's parameter sets.
    for kem_name in ("ML-KEM-512", "ML-KEM-768", "ML-KEM-1024"):
        for sig_name in ("ML-DSA-44", "ML-DSA-65", "ML-DSA-87"):
            register_fused(
                kem_name, sig_name,
                lambda kem, sig: FusedMLKEMMLDSA(kem, sig),
            )


_register_defaults()
