"""AEAD algorithms: AES-256-GCM and ChaCha20-Poly1305.

Host-side (OpenSSL via the ``cryptography`` package), as in the reference
(crypto/symmetric.py:66-258): transport encryption is latency-bound per
message, so it stays on CPU; the TPU earns its keep on the batched PQC math.

Wire format parity: 12-byte random nonce prepended to the ciphertext
(crypto/symmetric.py:110-146); authentication failure raises ValueError
(crypto/symmetric.py:159-161).
"""

from __future__ import annotations

import os

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers import aead as _aead
except ImportError:  # pragma: no cover - exercised only on minimal images
    # Gate, don't crash: the provider package (registry, batch queues, KEM/
    # signature providers) is fully usable without host AEAD — only actual
    # encrypt/decrypt needs OpenSSL.  Minimal accelerator images without
    # the wheel can still run the PQC layers and their tests.
    class InvalidTag(Exception):  # placeholder: never raised without OpenSSL
        pass

    _aead = None

from .base import SymmetricAlgorithm


class _AEADBase(SymmetricAlgorithm):
    _impl = ""  # cryptography AEAD class name (resolved lazily by _cipher)

    key_size = 32
    nonce_size = 12

    def generate_key(self) -> bytes:
        return os.urandom(self.key_size)

    @property
    def _cipher(self):
        if _aead is None:
            raise RuntimeError(
                f"{self.name} needs the 'cryptography' package for host AEAD"
            )
        return getattr(_aead, self._impl)

    def encrypt(self, key: bytes, plaintext: bytes, associated_data: bytes | None = None) -> bytes:
        if len(key) != self.key_size:
            raise ValueError(f"{self.name} requires a {self.key_size}-byte key")
        nonce = os.urandom(self.nonce_size)
        return nonce + self._cipher(key).encrypt(nonce, plaintext, associated_data)

    def decrypt(self, key: bytes, data: bytes, associated_data: bytes | None = None) -> bytes:
        if len(key) != self.key_size:
            raise ValueError(f"{self.name} requires a {self.key_size}-byte key")
        if len(data) < self.nonce_size + 16:
            raise ValueError("ciphertext too short")
        nonce, ct = data[: self.nonce_size], data[self.nonce_size :]
        try:
            return self._cipher(key).decrypt(nonce, ct, associated_data)
        except InvalidTag as e:
            raise ValueError("authentication failed") from e


class AES256GCM(_AEADBase):
    _impl = "AESGCM"
    name = "AES-256-GCM"
    display_name = "AES-256-GCM"
    description = "AES in Galois/Counter Mode with 256-bit keys (NIST SP 800-38D)"
    security_level = 5
    backend = "cpu"


class ChaCha20Poly1305(_AEADBase):
    _impl = "ChaCha20Poly1305"
    name = "ChaCha20-Poly1305"
    display_name = "ChaCha20-Poly1305"
    description = "RFC 8439 ChaCha20-Poly1305 AEAD"
    security_level = 5
    backend = "cpu"
