"""AEAD algorithms: AES-256-GCM and ChaCha20-Poly1305.

Scalar host-side path (OpenSSL via the ``cryptography`` package), as in the
reference (crypto/symmetric.py:66-258).  Two additions over the reference:

* deterministic-nonce ``seal``/``open_`` primitives (``encrypt`` is
  ``urandom nonce + seal``) — the batched device AEAD's cpu fallback and
  its cross-check tests need the nonce as an explicit operand;
* a wheel-less pure-Python fallback for ChaCha20-Poly1305
  (pyref/chacha_ref.py): minimal accelerator images without OpenSSL can
  still run the full bulk path — slowly, which is exactly what the batched
  device path (core/chacha_pallas.py, ``BatchedAEADOps``) exists to fix.
  AES-256-GCM has no pure-Python twin and still requires the wheel.

Wire format parity: 12-byte random nonce prepended to the ciphertext
(crypto/symmetric.py:110-146); authentication failure raises ValueError
(crypto/symmetric.py:159-161).
"""

from __future__ import annotations

import os

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers import aead as _aead
except ImportError:  # pragma: no cover - exercised only on minimal images
    # Gate, don't crash: the provider package (registry, batch queues, KEM/
    # signature providers) is fully usable without host AEAD — only actual
    # encrypt/decrypt needs OpenSSL.  Minimal accelerator images without
    # the wheel can still run the PQC layers and their tests (and, via the
    # pyref fallback below, the ChaCha20-Poly1305 bulk path).
    class InvalidTag(Exception):  # placeholder: never raised without OpenSSL
        pass

    _aead = None

from .base import SymmetricAlgorithm


class _AEADBase(SymmetricAlgorithm):
    _impl = ""  # cryptography AEAD class name (resolved lazily by _cipher)

    key_size = 32
    nonce_size = 12
    tag_size = 16

    def generate_key(self) -> bytes:
        return os.urandom(self.key_size)

    @property
    def _cipher(self):
        if _aead is None:
            raise RuntimeError(
                f"{self.name} needs the 'cryptography' package for host AEAD"
            )
        return getattr(_aead, self._impl)

    def _check_key(self, key: bytes) -> None:
        if len(key) != self.key_size:
            raise ValueError(f"{self.name} requires a {self.key_size}-byte key")

    def seal(self, key: bytes, nonce: bytes, plaintext: bytes,
             associated_data: bytes | None = None) -> bytes:
        self._check_key(key)
        if len(nonce) != self.nonce_size:
            raise ValueError(f"{self.name} requires a {self.nonce_size}-byte nonce")
        return self._cipher(key).encrypt(bytes(nonce), bytes(plaintext),
                                         associated_data)

    def open_(self, key: bytes, nonce: bytes, data: bytes,
              associated_data: bytes | None = None) -> bytes:
        self._check_key(key)
        if len(data) < self.tag_size:
            raise ValueError("ciphertext too short")
        try:
            return self._cipher(key).decrypt(bytes(nonce), bytes(data),
                                             associated_data)
        except InvalidTag as e:
            raise ValueError("authentication failed") from e

    def encrypt(self, key: bytes, plaintext: bytes, associated_data: bytes | None = None) -> bytes:
        nonce = os.urandom(self.nonce_size)
        return nonce + self.seal(key, nonce, plaintext, associated_data)

    def decrypt(self, key: bytes, data: bytes, associated_data: bytes | None = None) -> bytes:
        self._check_key(key)
        if len(data) < self.nonce_size + self.tag_size:
            raise ValueError("ciphertext too short")
        data = memoryview(data)  # zero-copy split (binary wire hands views)
        return self.open_(key, bytes(data[: self.nonce_size]),
                          data[self.nonce_size:], associated_data)


class AES256GCM(_AEADBase):
    _impl = "AESGCM"
    name = "AES-256-GCM"
    display_name = "AES-256-GCM"
    description = "AES in Galois/Counter Mode with 256-bit keys (NIST SP 800-38D)"
    security_level = 5
    backend = "cpu"


class ChaCha20Poly1305(_AEADBase):
    _impl = "ChaCha20Poly1305"
    name = "ChaCha20-Poly1305"
    display_name = "ChaCha20-Poly1305"
    description = "RFC 8439 ChaCha20-Poly1305 AEAD"
    security_level = 5
    backend = "cpu"

    def seal(self, key: bytes, nonce: bytes, plaintext: bytes,
             associated_data: bytes | None = None) -> bytes:
        if _aead is not None:
            return super().seal(key, nonce, plaintext, associated_data)
        # wheel-less scalar twin (pyref/chacha_ref.py): bit-identical to
        # OpenSSL, pure stdlib — the KAT oracle doubles as the fallback
        from ..pyref import chacha_ref

        self._check_key(key)
        return chacha_ref.seal(bytes(key), bytes(nonce), bytes(plaintext),
                               bytes(associated_data or b""))

    def open_(self, key: bytes, nonce: bytes, data: bytes,
              associated_data: bytes | None = None) -> bytes:
        if _aead is not None:
            return super().open_(key, nonce, data, associated_data)
        from ..pyref import chacha_ref

        self._check_key(key)
        return chacha_ref.open_(bytes(key), bytes(nonce), bytes(data),
                                bytes(associated_data or b""))
