"""Device-program scheduler with an explicit placement axis — the pod-scale
sharded crypto plane's control tier (ROADMAP item 1).

Before this module the batching stack had three coordinates but no axis
tying them together: ``OpQueue`` decided WHEN a batch dispatches, the
``opcache`` decided WHAT device state a program reuses, and the breaker
decided WHETHER the device path is trusted — all implicitly pinned to one
chip (every production dispatch landed on device 0 even with 8 reachable,
``MULTICHIP_r03.json``).  The scheduler adds the missing coordinate:
WHERE.  Every device program now runs against a :class:`Shard` — one slot
of a 1-D placement axis over the visible accelerators — chosen per flush
by a load-aware, health-aware policy.

Sharding model
--------------
Handshake crypto is embarrassingly parallel, so the two production paths
split cleanly (docs/sharding.md):

* **Large-batch raw-ops path** — a single big batch is partitioned ACROSS
  the mesh via ``jax.sharding``/GSPMD (``provider.base.mesh_dispatch``,
  the ``devices=`` knob on providers).  One program, N chips, zero
  hot-path collectives.
* **Latency-sensitive handshake path** — many small queue flushes are
  each placed WHOLE on one shard (``jax.default_device`` inside the
  dispatch worker), so concurrent flushes from independent handshakes run
  on different chips in parallel.  Program replicas compile per shard
  (the warmup loops the shards); the opcache partitions per shard
  (``opcache.shard_scope``) so device-resident operand state never
  crosses chips.

Isolation: each shard owns its own :class:`provider.batched.Breaker`
(with its own device/warmup executors), so a sick device quarantines ONE
shard while its siblings keep serving — the placement policy routes
around open/quarantined shards and routes a canary probe back when a
cool-off expires, running the PR-3 heal cycle per shard.

Degradation: ``shards=1`` (the default everywhere) is a single logical
shard with no device pinned — bit-for-bit the pre-scheduler behavior,
pinned by metrics-parity tests.  When jax (or enough devices) is absent,
requested shards degrade to LOGICAL shards: per-shard breakers, queues
and placement still partition the work (and are fully testable), only the
physical device pinning is skipped.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Any, Callable

from ..obs import flight as obs_flight
from .batched import Breaker, CoalescingHub

logger = logging.getLogger(__name__)


def select_slot(slots):
    """The placement policy, shared by BOTH placement levels (ROADMAP
    item 1's two-level lift): the local shard axis
    (:class:`DeviceProgramScheduler` picking a chip for a flush) and the
    fleet's process axis (:class:`fleet.manager.GatewayFleet` picking the
    gateway that receives the next unit of work — a canary probe or a
    rebalance placement).  A *slot* is anything with ``breaker`` /
    ``inflight`` / ``index`` — :class:`Shard` and
    :class:`fleet.manager.GatewayMember` both qualify, which is what
    makes placement, quarantine and rebalance ONE policy at both scopes:

    1. a probe-eligible slot (breaker open past its cool-off, or
       half-open with no canary in flight) wins first — healing requires
       routing exactly one unit of work back to it;
    2. otherwise the least-loaded CLOSED slot (tie → lowest index);
    3. otherwise (nothing healthy) the least-loaded non-quarantined slot
       — its breaker claim then degrades the work explicitly, exactly
       like the single-device stack's fallback.

    Deterministic given the load pattern; returns None only for an empty
    slot list.
    """
    slots = list(slots)
    if not slots:
        return None
    probe = [s for s in slots if s.breaker.probe_ready()]
    if probe:
        return min(probe, key=lambda s: (s.inflight, s.index))
    closed = [s for s in slots if s.breaker.state == "closed"]
    pool = closed or [s for s in slots if s.breaker.state != "quarantined"]
    return min(pool or slots, key=lambda s: (s.inflight, s.index))


def _resolve_devices(n: int) -> list[Any]:
    """First ``n`` visible accelerator devices (n == -1: all), or logical
    placeholders (``None``) when jax or the devices are unavailable —
    placement, per-shard breakers and quarantine still work, only the
    physical device pinning is skipped."""
    try:
        from ..parallel.mesh import shard_devices

        devs = shard_devices(None if n < 0 else n)
    except Exception as e:  # qrlint: disable=broad-except  — missing jax / too few devices must degrade to logical shards, not fail construction on minimal images
        count = 1 if n < 0 else n
        logger.warning(
            "shard placement: %d physical device(s) unavailable (%s); "
            "using logical shards", count, e,
        )
        return [None] * count
    return list(devs)


class Shard:
    """One slot of the placement axis: a device (or a logical slot), its
    breaker, and its load gauge.

    ``run_placed(fn, items)`` is the placement boundary: it runs one
    device-program callable ON the current (worker) thread under this
    shard's placement context — ``jax.default_device`` pins uncommitted
    operands and the computation to the shard's chip, and
    ``opcache.shard_scope`` namespaces device-resident operand state so a
    pytree cached on chip ``i`` is never fed to a program on chip ``j``.
    Placement changes only WHERE a program runs, never what it computes:
    sharded results are bit-exact vs the single-device path
    (tests/test_scheduler.py).
    """

    def __init__(self, index: int, device: Any = None,
                 breaker: Breaker | None = None):
        self.index = index
        self.device = device
        self.label = f"shard{index}"
        self.breaker = breaker if breaker is not None else Breaker()
        #: rides in the breaker's flight-recorder events so a dump tells
        #: WHICH shard opened/quarantined, not just that one did
        self.breaker.label = self.label
        #: guards the load gauge: place()/done() run on the event loop,
        #: run_placed on the dispatch workers (qrflow cross-thread-state)
        self._lock = threading.Lock()
        self.inflight = 0
        self.dispatches = 0
        # labeled obs instruments (attached by the scheduler when it is
        # given a registry; None otherwise — recording stays optional)
        self._ctr_dispatches = None
        self._hist_latency = None
        #: cost-ledger feed (obs/cost.py): per-shard placed-program
        #: seconds, attached via DeviceProgramScheduler.attach_cost
        self._cost = None

    @contextlib.contextmanager
    def placement(self):
        """Enter this shard's placement context (on the dispatching
        thread).  Logical shards (``device is None``) scope only the
        opcache — the single-device behavior stays untouched."""
        from .opcache import shard_scope

        with shard_scope(self.index):
            if self.device is None:
                yield
            else:
                import jax

                with jax.default_device(self.device):
                    yield

    def run_placed(self, fn: Callable[[list[Any]], list[Any]],
                   items: list[Any]) -> list[Any]:
        """Run one device-program callable under this shard's placement.
        Failures propagate to the caller, which records them to THIS
        shard's breaker (per-shard quarantine, not fleet-wide)."""
        t0 = time.perf_counter()
        with self.placement():
            out = fn(items)
        dt = time.perf_counter() - t0
        with self._lock:
            self.dispatches += 1
        if self._ctr_dispatches is not None:
            self._ctr_dispatches.inc()
        if self._hist_latency is not None:
            self._hist_latency.record(dt)
        if self._cost is not None:
            # per-shard device-seconds (obs/cost.py): the chip-level half
            # of the cost ledger's device-time accounting
            self._cost.shard_device_time(self.index, dt)
        return out

    def snapshot(self) -> dict[str, Any]:
        b = self.breaker
        with self._lock:
            inflight, dispatches = self.inflight, self.dispatches
        return {
            "shard": self.index,
            "device": str(self.device) if self.device is not None else None,
            "inflight": inflight,
            "dispatches": dispatches,
            "breaker_state": b.state,
            "breaker_opens": b.opens,
            "breaker_closes": b.closes,
            "device_trips": b.device_trips,
            "fallback_trips": b.fallback_trips,
        }


class DeviceProgramScheduler(CoalescingHub):
    """Places device-program flushes onto shards; owns the shard set.

    Placement policy (deterministic given the load pattern — pinned by
    tests):

    1. a probe-eligible shard (breaker open past its cool-off, or
       half-open with no canary in flight) wins first — healing a shard
       requires routing exactly one real flush back to it;
    2. otherwise the least-loaded CLOSED shard (tie → lowest index);
    3. otherwise (no healthy shard) the least-loaded non-quarantined
       shard — its breaker claim then serves the flush from the cpu
       fallback, degrading exactly like the single-device stack.

    The scheduler is also the coalescing hub for the queues it serves
    (:class:`provider.batched.CoalescingHub`, the machinery a
    ``Breaker`` provides for single-breaker stacks): sibling queues
    flush in one scheduling window, and each coalesced flush is then
    PLACED independently — coalesced KEM and SIG batches can run on
    different chips in parallel.
    """

    def __init__(self, shards: int = 1, cooloff_s: float = 30.0,
                 cooloff_max_s: float = 480.0, registry=None,
                 devices: list[Any] | None = None):
        if shards == 0:
            shards = 1
        if devices is None:
            # one logical shard needs no device lookup (and must not pull
            # in jax on minimal images); a real axis resolves devices
            devices = [None] if shards == 1 else _resolve_devices(shards)
        self.shards = [
            Shard(i, dev, Breaker(cooloff_s, cooloff_max_s))
            for i, dev in enumerate(devices)
        ]
        self._lock = threading.Lock()
        self._last_healthy: frozenset[int] = frozenset(
            s.index for s in self.shards
        )
        self._init_coalescer()
        if registry is not None:
            self.attach_registry(registry)

    # -- observability --------------------------------------------------------

    def attach_registry(self, registry) -> None:
        """Create the per-shard labeled children (obs/metrics.py): a
        ``shard=<i>`` child per instrument, so one Prometheus scrape (or
        JSON snapshot) breaks dispatch counts/latency down by chip."""
        ctr = registry.counter(
            "shard_dispatches", "device programs run, by placement shard")
        hist = registry.histogram(
            "shard_dispatch_latency", "placed device-program latency (s)")
        gauge = registry.gauge(
            "shard_inflight", "flushes currently placed, by shard")
        for s in self.shards:
            s._ctr_dispatches = ctr.labels(shard=s.index)
            s._hist_latency = hist.labels(shard=s.index)
            child = gauge.labels(shard=s.index)
            child.set_fn(lambda s=s: s.inflight)

    def attach_cost(self, ledger) -> None:
        """Feed per-shard placed-program seconds into a
        :class:`obs.cost.CostLedger` (the engine attaches its ledger)."""
        for s in self.shards:
            s._cost = ledger

    @property
    def n_shards(self) -> int:
        return len(self.shards)

    # -- placement ------------------------------------------------------------

    def place(self) -> Shard:
        """Claim the next flush's shard (pair with :meth:`done`) — the
        shared two-level policy (:func:`select_slot`) applied at the
        local-shard scope."""
        with self._lock:
            chosen = select_slot(self.shards)
            with chosen._lock:
                chosen.inflight += 1
            healthy = frozenset(
                s.index for s in self.shards if s.breaker.state == "closed"
            )
            if healthy != self._last_healthy:
                # the routing table just changed: a flight dump must show
                # WHEN traffic moved off (or back onto) a shard
                obs_flight.record(
                    "shard_rebalance",
                    healthy=sorted(healthy),
                    avoided=sorted(set(range(len(self.shards))) - healthy),
                    placed_on=chosen.index,
                )
                self._last_healthy = healthy
            return chosen

    def done(self, shard: Shard) -> None:
        with shard._lock:
            shard.inflight -= 1

    # -- fleet operations -----------------------------------------------------

    def quarantine_all(self, why: str) -> None:
        """Health-gate verdicts are about the device PROGRAMS (wrong
        answers), not one chip — every shard runs the same programs, so a
        correctness failure pins the whole axis onto the cpu fallback."""
        for s in self.shards:
            s.breaker.quarantine(why)

    def total_trips(self) -> int:
        """Serial dispatch steps (device + fallback) across every shard —
        the per-handshake SLO currency (docs/dispatch_budget.md) summed
        over the placement axis."""
        return sum(s.breaker.device_trips + s.breaker.fallback_trips
                   for s in self.shards)

    def warmable_shards(self) -> list[Shard]:
        """The shards a warm sweep should compile on: CLOSED breakers
        only.  A sick shard's device may hang the compile — and the warm
        runs on the single nice-19 warmup thread, so one hung shard would
        block warm-marking for the whole plane (the exact fleet-wide
        coupling per-shard breakers exist to prevent).  A shard skipped
        here cold-compiles inside its first placed flush after healing;
        the slow-trip machinery absorbs that (degrade, re-probe) — a
        bounded per-shard cost, never a fleet-wide stall."""
        return [s for s in self.shards if s.breaker.state == "closed"]

    def stats(self) -> dict[str, Any]:
        snaps = [s.snapshot() for s in self.shards]
        served = sum(s["dispatches"] for s in snaps)
        return {
            "n_shards": len(self.shards),
            "placement": "least-inflight, probe-first, quarantine-aware",
            "dispatches": served,
            "shards": snaps,
        }
