"""Device ChaCha20-Poly1305 — the ``BatchedAEAD`` capability implementation.

Thin array-marshalling shim between the batched facade
(provider/batched.py ``BatchedAEAD``) and the jitted seal/open core
(core/chacha_pallas.py): ragged bytes in, padded pow2 buckets through one
device program, exact-length bytes out.  Bucket policy:

* message length -> ``64 * next_pow2(ceil(len / 64))`` (whole ChaCha
  blocks; 64, 128, 256, ... up to :attr:`max_len`);
* AAD length -> ``16 * next_pow2(ceil(len / 16))`` (whole Poly1305
  blocks);
* one flush dispatches ONE program at the flush's max buckets — mixed
  sizes ride together with masked tails, bit-exact per item (the KAT
  suite pins every bucket edge).

jit compiles one program per (batch, length, aad) bucket triple; the
coarse pow2 grid keeps that space small enough for the facade warmup to
cover (docs/dispatch_budget.md "aead" row has the trip ledger).
"""

from __future__ import annotations

import hmac
import threading

import numpy as np

from ..utils import next_pow2
from .base import BatchedAEADOps


class ChaChaPolyDevice(BatchedAEADOps):
    """RFC 8439 ChaCha20-Poly1305 over the batched device core."""

    name = "ChaCha20-Poly1305"
    backend = "tpu"
    key_size = 32
    nonce_size = 12
    tag_size = 16
    #: device bucket caps: a 64 KiB message compiles the largest program
    #: this capability owns; longer payloads (file sends) stay scalar
    max_len = 64 * 1024
    max_aad_len = 4 * 1024

    def __init__(self, use_pallas: bool | None = None,
                 interpret: bool = False):
        from ..core import chacha_pallas

        self._core = chacha_pallas
        #: Pallas kernel on real TPU, jnp twin elsewhere (bit-identical;
        #: core.keccak's shared QRP2P_PALLAS policy)
        self.use_pallas = (chacha_pallas.use_pallas_default()
                           if use_pallas is None else use_pallas)
        self.interpret = interpret
        #: (seal, batch, msg_bucket, aad_bucket) program shapes this
        #: instance has dispatched at least once — the facade's OpQueue
        #: ``warm_check`` axis (a warm batch bucket with a novel LENGTH
        #: bucket would otherwise jit-compile inside a live dispatch).
        #: Lock-guarded: written from device/warmup worker threads, read
        #: from the event loop's warm check (qrflow cross-thread-state).
        self._shape_lock = threading.Lock()
        self.compiled_shapes: set[tuple[bool, int, int, int]] = set()

    # -- marshalling --------------------------------------------------------
    #
    # Bucket floors collapse the small end of the shape space: every
    # message <= 256 B and every AAD <= 256 B lands on ONE (msg, aad)
    # bucket pair, so the default facade warm shapes cover the whole
    # small-message regime instead of fragmenting across 64/128/16/32/...
    # variants (a novel shape costs a fallback window while it warms —
    # padding a few hundred bytes of ChaCha/Poly lanes costs ~nothing).

    MSG_BUCKET_FLOOR = 256
    AAD_BUCKET_FLOOR = 256

    @classmethod
    def _msg_bucket(cls, n: int) -> int:
        return max(cls.MSG_BUCKET_FLOOR, 64 * next_pow2(max(1, -(-n // 64))))

    @classmethod
    def _aad_bucket(cls, n: int) -> int:
        return max(cls.AAD_BUCKET_FLOOR, 16 * next_pow2(max(1, -(-n // 16))))

    def _pack(self, items: list, bucket: int) -> tuple[np.ndarray, np.ndarray]:
        out = np.zeros((len(items), bucket), np.uint8)
        lens = np.zeros(len(items), np.int32)
        for i, it in enumerate(items):
            row = np.frombuffer(it, np.uint8)
            out[i, : row.shape[0]] = row
            lens[i] = row.shape[0]
        return out, lens

    def _run(self, keys, nonces, data_items, aads, seal: bool):
        l_bucket = self._msg_bucket(max((len(d) for d in data_items),
                                        default=1))
        a_bucket = self._aad_bucket(max((len(a) for a in aads), default=1))
        data, lens = self._pack(data_items, l_bucket)
        aad_arr, aad_lens = self._pack(aads, a_bucket)
        out, tags = self._core.aead_core(
            np.ascontiguousarray(keys, dtype=np.uint8),
            np.ascontiguousarray(nonces, dtype=np.uint8),
            data, lens, aad_arr, aad_lens, seal=seal,
            use_pallas=self.use_pallas, interpret=self.interpret,
        )
        with self._shape_lock:
            self.compiled_shapes.add((seal, len(data_items), l_bucket,
                                      a_bucket))
        return np.asarray(out), np.asarray(tags), lens

    def covers(self, seal: bool, batch: int, msg_len: int,
               aad_len: int) -> bool:
        """True when the program for these buckets is already compiled —
        the facade's warm_check predicate (provider/batched.py)."""
        with self._shape_lock:
            return (seal, batch, self._msg_bucket(msg_len),
                    self._aad_bucket(aad_len)) in self.compiled_shapes

    # -- capability surface -------------------------------------------------

    def seal_batch(self, keys: np.ndarray, nonces: np.ndarray,
                   plaintexts: list, aads: list) -> list[bytes]:
        out, tags, lens = self._run(keys, nonces, plaintexts, aads, seal=True)
        return [bytes(out[i, : lens[i]]) + bytes(tags[i])
                for i in range(len(plaintexts))]

    def open_batch(self, keys: np.ndarray, nonces: np.ndarray,
                   data: list, aads: list) -> list:
        views = [memoryview(d) for d in data]
        cts = [v[: -self.tag_size] for v in views]
        out, tags, lens = self._run(keys, nonces, cts, aads, seal=False)
        results: list = []
        for i, v in enumerate(views):
            # constant-time per-item compare; a mismatch is a per-item
            # ValueError result, matching the scalar decrypt contract
            if hmac.compare_digest(bytes(tags[i]),
                                   bytes(v[-self.tag_size:])):
                results.append(bytes(out[i, : lens[i]]))
            else:
                results.append(ValueError("authentication failed"))
        return results
