"""Host networking: asyncio TCP P2P transport, UDP discovery, node identity.

Capability parity with the reference's networking/ package (SURVEY.md §2 rows
9-11).  The TPU is never on this path — it acts as a crypto coprocessor behind
the provider layer's batching queue; these modules move opaque bytes/JSON.
"""

from .identity import load_or_generate_node_id
from .p2p_node import P2PNode
from .discovery import NodeDiscovery

__all__ = ["P2PNode", "NodeDiscovery", "load_or_generate_node_id"]
