"""Persistent node identity.

Parity with the reference's networking/node_identity.py:15-113: a stable
UUID4 node ID, stored encrypted in the KeyStorage vault when one is supplied,
with a plaintext-file fallback that is migrated into the vault (and deleted)
on the next unlock.
"""

from __future__ import annotations

import logging
import uuid
from pathlib import Path

logger = logging.getLogger(__name__)

_ENTRY = "system_node_id"


def load_or_generate_node_id(key_storage=None, data_dir: Path | None = None) -> str:
    """Return the persistent node id, creating one on first run.

    Preference order: vault entry -> plaintext file (migrated to the vault
    and removed) -> freshly generated UUID4.
    """
    from ..storage.key_storage import get_app_data_dir

    data_dir = data_dir or get_app_data_dir()
    plain_path = data_dir / "node_id.txt"

    if key_storage is not None and getattr(key_storage, "is_unlocked", False):
        node_id = key_storage.retrieve(_ENTRY)
        if node_id:
            return node_id
        if plain_path.exists():
            node_id = plain_path.read_text().strip()
            key_storage.store(_ENTRY, node_id)
            plain_path.unlink()
            logger.info("migrated plaintext node id into the vault")
            return node_id
        node_id = str(uuid.uuid4())
        key_storage.store(_ENTRY, node_id)
        return node_id

    if plain_path.exists():
        return plain_path.read_text().strip()
    node_id = str(uuid.uuid4())
    plain_path.parent.mkdir(parents=True, exist_ok=True)
    plain_path.write_text(node_id)
    plain_path.chmod(0o600)
    return node_id
