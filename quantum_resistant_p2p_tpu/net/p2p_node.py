"""Asyncio TCP P2P node with framed, chunked message transport.

Capability parity with the reference's networking/p2p_node.py (552 LoC: TCP
server/client, peer registry, hello handshake, chunked binary framing,
per-type handler dispatch, disconnect fan-out) with a fresh wire design:

Frame:   magic b"QP" | version u8 | flags u8 | length u32be | payload
         flags bit0 = CHUNK (payload carries a chunk header)
         flags bit1 = BIN   (payload is the negotiated binary encoding)
Chunk:   stream_id 16B | index u32be | count u32be | data
Payload: UTF-8 JSON object with a mandatory "type" key (the compat
         default), or — on connections that negotiated ``bin1`` in the
         hello exchange — the compact binary encoding below.

Binary payload (docs/protocol.md "Wire-format negotiation"):

    token b"B1" | type_len u8 | type | n_fields u8 | fields...
    field := key_len u8 | key | kind u8 | value_len u32be | value
    kind 0 = raw bytes (decoded as a zero-copy memoryview into the frame
             buffer — ciphertexts go from socket buffer to the batched
             AEAD open with no copy and no base64/hex round-trip)
    kind 1 = UTF-8 canonical JSON (everything else, incl. ``_trace``)

Negotiation: a node with ``QRP2P_BINARY_WIRE`` unset/``1`` offers
``"wire": ["bin1"]`` in its hello; both sides offering upgrades every
subsequent frame on that connection.  ``QRP2P_BINARY_WIRE=0`` and
un-negotiated peers stay byte-identical to the historical JSON frames
(pinned by tests/test_binary_wire.py).  Hostile binary input — oversized
lengths, truncated headers, a wrong token, trailing garbage — fails as a
typed :class:`WireError`: loud log + flight event + ``wire_errors``
counter, the offending connection dropped, the serving loop and every
other peer untouched.

Messages above ``chunk_size`` (default 64 KiB) are split into chunk frames and
reassembled on the far side; anything smaller travels in a single frame.
The hello handshake exchanges node ids + listen ports with a timeout, after
which the peer enters the registry and connection handlers fire.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import os
import random
import struct
import uuid
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable

from ..faults import plan as _faults
from ..obs import flight as obs_flight
from ..obs import trace as obs_trace

logger = logging.getLogger(__name__)

#: hello-response window; generous because a peer's loop can stall for a
#: few seconds behind a background jit compile (provider/batched.py)
HELLO_TIMEOUT = 15.0

_MAGIC = b"QP"
_VERSION = 1
_FLAG_CHUNK = 0x01
_FLAG_BIN = 0x02
_HEADER = struct.Struct(">2sBBI")
_CHUNK_HEADER = struct.Struct(">16sII")

#: binary-payload negotiation token: the first two payload bytes of every
#: bin1 frame.  A frame flagged BIN without it is hostile/corrupt input
#: and fails typed (WireError), never as a stray json/struct exception.
_BIN_TOKEN = b"B1"
_BIN_WIRE_NAME = "bin1"
_BIN_KIND_RAW = 0
_BIN_KIND_JSON = 1

#: session-resumption negotiation token (docs/protocol.md "Session
#: resumption"): offered in the hello exactly like the wire format —
#: tickets/resume frames flow only when BOTH sides offered, so an
#: opted-out (``QRP2P_RESUMPTION=0``) or older peer sees byte-identical
#: pre-resumption frames (pinned by tests/test_resumption.py)
_RESUME_NAME = "tik1"

#: bounded reconnect jitter (seconds): N clients of one dead gateway must
#: not redial its ring successor in the same tick — each reconnect sleeps
#: a seeded uniform [0, this) before dialing (docs/robustness.md
#: "Reconnect thundering herd")
RECONNECT_JITTER_S = 0.25

MessageHandler = Callable[[str, dict], Awaitable[None]]
ConnectionHandler = Callable[[str, str], None]  # (event, peer_id)

MAX_FRAME = 16 * 1024 * 1024

#: largest raw value the binary decoder accepts per field — the sender
#: routes messages with a bigger bytes value (huge file transfers) over
#: the JSON wire instead, which chunks and reassembles without a
#: per-field cap; the receive-side bound stays tight against hostile
#: length claims
_BIN_MAX_FIELD = MAX_FRAME


class WireError(ValueError):
    """Typed wire-protocol violation (bad magic/version, oversized length,
    truncated or malformed binary payload, un-negotiated binary frame).
    The read loop maps it to one loud, counted connection drop — hostile
    input on one socket can never kill the node's serving loop."""


def binary_wire_default() -> bool:
    """``QRP2P_BINARY_WIRE`` policy: offer the binary wire unless ``0``."""
    return os.environ.get("QRP2P_BINARY_WIRE", "1") != "0"


def resumption_offer_default() -> bool:
    """``QRP2P_RESUMPTION`` policy: offer ticket resumption unless ``0``
    (the transport-side twin of ``app.resumption.resumption_default`` —
    kept local so net/ never imports the app layer)."""
    return os.environ.get("QRP2P_RESUMPTION", "1") != "0"


def _encode_bin(message: dict) -> list:
    """Encode a message dict as binary-payload segments (zero-copy: raw
    bytes/memoryview values ride as their own segments, uncopied)."""
    msg_type = str(message.get("type", ""))
    fields = [(k, v) for k, v in message.items() if k != "type"]
    tb = msg_type.encode()
    if len(tb) > 255 or len(fields) > 255:
        raise ValueError("binary frame: type/field count out of range")
    head = bytearray(_BIN_TOKEN)
    head.append(len(tb))
    head += tb
    head.append(len(fields))
    segs: list = [bytes(head)]
    for k, v in fields:
        kb = k.encode()
        if len(kb) > 255:
            raise ValueError(f"binary frame: key {k!r} too long")
        if isinstance(v, (bytes, bytearray, memoryview)):
            kind, vb = _BIN_KIND_RAW, v
        else:
            kind, vb = _BIN_KIND_JSON, json.dumps(
                v, separators=(",", ":")).encode()
        segs.append(bytes([len(kb)]) + kb + bytes([kind])
                    + len(vb).to_bytes(4, "big"))
        segs.append(vb)
    return segs


def _decode_bin(buf) -> dict:
    """Decode a binary payload into a message dict.

    ``memoryview``-parsed: raw-kind values are returned as views into the
    received frame buffer — the ciphertext of a ``secure_message`` flows
    from the socket buffer into the batched AEAD open without a copy.
    Every length is bounds-checked BEFORE use; any violation is a typed
    :class:`WireError` naming what was malformed.
    """
    view = memoryview(buf)
    pos = 0

    def take(n: int, what: str) -> memoryview:
        nonlocal pos
        if n < 0 or pos + n > len(view):
            raise WireError(f"truncated binary frame ({what})")
        out = view[pos:pos + n]
        pos += n
        return out

    if bytes(take(2, "wire token")) != _BIN_TOKEN:
        raise WireError("bad binary wire token")
    try:
        msg_type = bytes(take(take(1, "type length")[0], "type")).decode()
        message: dict = {"type": msg_type}
        for _ in range(take(1, "field count")[0]):
            fname = bytes(take(take(1, "name length")[0], "field name")).decode()
            kind = take(1, "field kind")[0]
            vlen = int.from_bytes(take(4, "value length"), "big")
            if vlen > _BIN_MAX_FIELD:
                raise WireError(f"oversized binary field {fname!r} ({vlen} bytes)")
            val = take(vlen, f"field {fname!r}")
            if kind == _BIN_KIND_RAW:
                message[fname] = val  # zero-copy view into the frame buffer
            elif kind == _BIN_KIND_JSON:
                message[fname] = json.loads(bytes(val))
            else:
                raise WireError(f"unknown binary field kind {kind}")
    except WireError:
        raise
    except (UnicodeDecodeError, ValueError) as e:
        raise WireError(f"malformed binary frame: {e}") from e
    if pos != len(view):
        raise WireError(f"trailing bytes in binary frame ({len(view) - pos})")
    return message


@dataclass
class _Peer:
    peer_id: str
    reader: asyncio.StreamReader
    writer: asyncio.StreamWriter
    host: str
    port: int  # the peer's listening port (from hello), not the socket port
    write_lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    reassembly: dict[bytes, dict] = field(default_factory=dict)
    #: negotiated wire format: "json" (compat default) or "bin1" (both
    #: sides offered it in the hello exchange)
    wire: str = "json"
    #: session resumption negotiated (both sides offered "tik1")
    resume: bool = False


class P2PNode:
    """TCP transport node: opaque JSON messages between identified peers."""

    def __init__(
        self,
        node_id: str | None = None,
        host: str = "0.0.0.0",
        port: int = 8000,
        key_storage=None,
        chunk_size: int = 64 * 1024,
        max_peers: int = 0,
        accept_backlog: int = 256,
        binary_wire: bool | None = None,
        resumption: bool | None = None,
        jitter_rng: "random.Random | None" = None,
    ):
        if node_id is None:
            from .identity import load_or_generate_node_id

            node_id = load_or_generate_node_id(key_storage)
        self.node_id = node_id
        self.host = host
        self.port = port
        self.chunk_size = chunk_size
        #: connection budget (admission control, docs/gateway.md): inbound
        #: peers beyond this many live connections are SHED at the hello —
        #: a typed ``__busy__`` reply then close, counted loudly — instead
        #: of admitted into a node already past its serving capacity.
        #: 0 = unlimited (the default; every pre-gateway caller).
        self.max_peers = max_peers
        #: kernel accept backlog for the listening socket: bounds the
        #: not-yet-accepted connection queue during an arrival storm (the
        #: kernel-side half of the backpressure story)
        self.accept_backlog = accept_backlog
        #: inbound connections shed over the budget (the gateway gauge)
        self.sheds = 0
        #: inbound connections ADMITTED at the same decision point — the
        #: good side matching ``sheds``: an SLI that counts connection
        #: sheds as bad must count connection admissions as good, or a
        #: reconnect wave of peers that never handshake reads as a
        #: near-total admission outage (docs/observability.md)
        self.admitted = 0
        #: peers admitted but not yet registered (the hello reply awaits
        #: between the budget check and registration): counted against
        #: the budget so a storm of concurrent hellos cannot all pass the
        #: check before any of them registers
        self._admitting: set[str] = set()
        #: dials WE made that a remote shed with ``__busy__``
        self.busy_rejects = 0
        #: offer the length-prefixed binary wire format in hellos; actual
        #: use is per-connection, negotiated (both sides must offer).
        #: None reads QRP2P_BINARY_WIRE (default: offer).
        self.binary_wire = (binary_wire_default() if binary_wire is None
                            else binary_wire)
        #: offer session-resumption tickets in hellos (the session layer
        #: only mints/presents for peers where BOTH sides offered).
        #: None reads QRP2P_RESUMPTION (default: offer).
        self.resumption = (resumption_offer_default() if resumption is None
                           else resumption)
        #: seeded reconnect-jitter RNG: derived from a digest of the FULL
        #: node id (a raw prefix would hand every 'peerNNNNN'-style id
        #: sharing 8 leading bytes the SAME stream — re-synchronizing
        #: exactly the reconnect wave the jitter exists to spread);
        #: injectable so tests pin the exact jitter sequence
        if jitter_rng is None:
            import hashlib

            jitter_rng = random.Random(int.from_bytes(
                hashlib.sha256(self.node_id.encode()).digest()[:8], "big"))
        self._jitter_rng = jitter_rng
        #: typed wire-protocol violations (WireError) observed on read
        #: loops — each one dropped exactly one connection, loudly
        self.wire_errors = 0
        self._server: asyncio.Server | None = None
        self._peers: dict[str, _Peer] = {}
        self._read_tasks: dict[str, asyncio.Task] = {}
        self._msg_handlers: dict[str, list[MessageHandler]] = {}
        self._conn_handlers: list[ConnectionHandler] = []
        self._running = False
        #: peers THIS node dialed (only the dialing side redials on a drop —
        #: the listening side cannot know the peer's current address)
        self._dialed: set[str] = set()
        #: last known (host, listen_port) per peer; survives disconnects so
        #: session healing (app/messaging.py) can redial
        self._addr: dict[str, tuple[str, int]] = {}
        #: peers whose disconnect was requested locally (stop(), an explicit
        #: disconnect): these must NOT be healed back
        self._intentional: set[str] = set()

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_inbound, self.host, self.port,
            backlog=self.accept_backlog,
        )
        self._running = True
        actual = self._server.sockets[0].getsockname()[1] if self._server.sockets else self.port
        self.port = actual
        logger.info("node %s listening on %s:%s", self.node_id[:8], self.host, self.port)

    async def stop(self) -> None:
        self._running = False
        for peer_id in list(self._peers):
            await self.disconnect_from_peer(peer_id)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- registry / handlers -------------------------------------------------

    def get_peers(self) -> list[str]:
        return list(self._peers)

    def is_connected(self, peer_id: str) -> bool:
        return peer_id in self._peers

    def get_peer_address(self, peer_id: str) -> tuple[str, int] | None:
        p = self._peers.get(peer_id)
        return (p.host, p.port) if p else None

    def peer_wire_format(self, peer_id: str) -> str | None:
        """The negotiated wire format for a live peer ("json" | "bin1"),
        None when unknown."""
        p = self._peers.get(peer_id)
        return p.wire if p else None

    def peer_resumption(self, peer_id: str) -> bool:
        """True when session resumption was negotiated with this live peer
        (both hellos offered it) — the session layer's gate for minting
        and presenting tickets."""
        p = self._peers.get(peer_id)
        return bool(p and p.resume)

    def _hello(self) -> dict:
        """Hello payload: node identity + (when enabled) the wire-format
        and resumption offers.  With the offers disabled the payload — and
        therefore the hello frame bytes — is identical to the historical
        one (pinned)."""
        hello = {"type": "__hello__", "node_id": self.node_id,
                 "listen_port": self.port}
        if self.binary_wire:
            hello["wire"] = [_BIN_WIRE_NAME]
        if self.resumption:
            hello["resume"] = [_RESUME_NAME]
        return hello

    def _negotiated_wire(self, hello: dict) -> str:
        """Per-connection wire format from the peer's hello: ``bin1`` iff
        BOTH sides offered it, else the JSON compat default."""
        offered = hello.get("wire")
        if (self.binary_wire and isinstance(offered, (list, tuple))
                and _BIN_WIRE_NAME in offered):
            return _BIN_WIRE_NAME
        return "json"

    def _negotiated_resume(self, hello: dict) -> bool:
        """Session resumption iff BOTH sides offered it (hostile hello
        shapes — wrong types, unknown tokens — read as not-offered)."""
        offered = hello.get("resume")
        return bool(self.resumption and isinstance(offered, (list, tuple))
                    and _RESUME_NAME in offered)

    def register_message_handler(self, msg_type: str, handler: MessageHandler) -> None:
        handlers = self._msg_handlers.setdefault(msg_type, [])
        if handler not in handlers:
            handlers.append(handler)

    def unregister_message_handler(self, msg_type: str, handler: MessageHandler) -> None:
        self._msg_handlers.get(msg_type, []).remove(handler)

    def register_connection_handler(self, handler: ConnectionHandler) -> None:
        if handler not in self._conn_handlers:
            self._conn_handlers.append(handler)

    def _fire_connection_event(self, event: str, peer_id: str) -> None:
        for h in list(self._conn_handlers):
            try:
                h(event, peer_id)
            except Exception:
                logger.exception("connection handler failed")

    # -- connecting ----------------------------------------------------------

    async def connect_to_peer(self, host: str, port: int, timeout: float = 10.0,
                              retries: int = 2) -> str | None:
        """Dial a peer, run the hello handshake, return its node id.

        A busy peer (e.g. its loop briefly stalled by a background jit
        compile, provider/batched.py) may miss the hello window; only
        TRANSIENT failures (timeouts, dropped connections) are retried with
        backoff — a wrong-protocol endpoint ("bad hello") fails once, fast.
        """
        for attempt in range(retries + 1):
            peer_id, retryable = await self._connect_once(host, port, timeout)
            if peer_id is not None:
                self._dialed.add(peer_id)
            if peer_id is not None or not retryable or attempt == retries:
                return peer_id
            await asyncio.sleep(0.5 * (attempt + 1))
        return None

    def should_heal(self, peer_id: str) -> bool:
        """True when a dropped session to ``peer_id`` is OURS to redial:
        this node is running, dialed the peer originally, knows an address,
        and the disconnect was not locally requested."""
        return (
            self._running
            and peer_id in self._dialed
            and peer_id in self._addr
            and peer_id not in self._intentional
        )

    def _reconnect_jitter(self) -> float:
        """The next seeded reconnect-jitter delay (uniform
        [0, RECONNECT_JITTER_S)): one draw per redial, pinned
        deterministic under an injected ``jitter_rng``."""
        return self._jitter_rng.uniform(0.0, RECONNECT_JITTER_S)

    async def reconnect(self, peer_id: str, timeout: float = 10.0,
                        retries: int = 2) -> bool:
        """Redial a dropped peer at its last known address (existing
        connect backoff applies).  False when unknown, unreachable, or a
        DIFFERENT node now answers there.

        Each redial first sleeps a seeded, bounded jitter: after a
        gateway death every one of its N clients enters this path at the
        same moment, and without the jitter they all hammer the ring
        successor in the same tick (the thundering herd the fleet
        handoff machinery would otherwise create for itself)."""
        addr = self._addr.get(peer_id)
        if addr is None:
            return False
        await asyncio.sleep(self._reconnect_jitter())
        prior_dialed = set(self._dialed)
        got = await self.connect_to_peer(addr[0], addr[1], timeout, retries)
        if got is not None and got != peer_id:
            if got in prior_dialed:
                # The address was reused by a node we HAD chosen to talk to
                # (its hello just re-registered it, clobbering any previous
                # socket): keep this verified session rather than killing a
                # peer the heal machinery exists to protect.
                logger.warning(
                    "reconnect to %s reached known peer %s instead; keeping "
                    "that session", peer_id[:8], got[:8],
                )
                return False
            # A true stranger answered.  Drop the probe connection WITHOUT
            # marking it intentional (a genuine later session stays
            # healable) — and remove it from _dialed first, so its
            # disconnect event cannot spawn a heal that redials a node this
            # peer never chose.
            logger.warning(
                "reconnect to %s found a different node (%s); dropping it",
                peer_id[:8], got[:8],
            )
            self._dialed.discard(got)
            await self.disconnect_from_peer(got, intentional=False)
            return False
        return got == peer_id

    async def _connect_once(self, host: str, port: int,
                            timeout: float) -> tuple[str | None, bool]:
        """-> (peer_id | None, retryable)."""
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), timeout
            )
        except (OSError, asyncio.TimeoutError) as e:
            logger.warning("connect to %s:%s failed: %s", host, port, e)
            return None, True
        try:
            await self._send_frame(writer, asyncio.Lock(), self._hello())
            hello = await asyncio.wait_for(self._read_plain_frame(reader), HELLO_TIMEOUT)
            if hello.get("type") == "__busy__":
                # the remote gateway shed this dial (connection budget):
                # a TYPED fast failure — retryable once load drains, and
                # counted so a storm driver can report client-side sheds
                self.busy_rejects += 1
                logger.warning("peer %s:%s is at capacity (shed our dial)",
                               host, port)
                writer.close()
                return None, True
            if hello.get("type") != "__hello__":
                raise ValueError("bad hello")
        except Exception as e:
            logger.warning("hello with %s:%s failed: %s", host, port, e)
            writer.close()
            # a peer that SPOKE but spoke wrong is not transient
            return None, not isinstance(e, ValueError)
        peer_id = hello["node_id"]
        self._register_peer(peer_id, reader, writer, host,
                            int(hello.get("listen_port", port)),
                            wire=self._negotiated_wire(hello),
                            resume=self._negotiated_resume(hello))
        return peer_id, False

    async def _on_inbound(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        addr = writer.get_extra_info("peername") or ("?", 0)
        try:
            hello = await asyncio.wait_for(self._read_plain_frame(reader), HELLO_TIMEOUT)
            if hello.get("type") != "__hello__":
                raise ValueError("bad hello")
            peer_id = str(hello.get("node_id", ""))
            if not peer_id:
                raise ValueError("bad hello")
            known = peer_id in self._peers or peer_id in self._admitting
            if (
                self.max_peers
                and not known
                and len(self._peers) + len(self._admitting) >= self.max_peers
            ):
                # Admission control: over the connection budget, shed LOUDLY
                # with a typed reply (the dialer sees a fast, retryable
                # "busy", never a timeout).  A reconnect of an already-
                # registered peer replaces its socket and is never shed.
                # In-flight admissions (_admitting) count against the
                # budget: the hello reply below AWAITS, so without the
                # reservation a storm of concurrent hellos would all pass
                # this check before any of them registers.
                await self._shed_inbound(writer, addr)
                return
            self._admitting.add(peer_id)
            try:
                await self._send_frame(writer, asyncio.Lock(), self._hello())
            finally:
                self._admitting.discard(peer_id)
        except Exception as e:
            logger.warning("inbound hello from %s failed: %s", addr, e)
            writer.close()
            return
        self._register_peer(
            peer_id, reader, writer, addr[0],
            int(hello.get("listen_port", addr[1])),
            wire=self._negotiated_wire(hello),
            resume=self._negotiated_resume(hello),
        )
        self.admitted += 1

    async def _shed_inbound(self, writer: asyncio.StreamWriter, addr) -> None:
        """Refuse one over-budget inbound connection: typed ``__busy__``
        reply, loud (rate-limited) log line, flight-recorder event."""
        self.sheds += 1
        if self.sheds == 1 or self.sheds % 64 == 0:
            logger.warning(
                "connection budget reached (%d peers, max %d): shedding "
                "inbound connection from %s (%d shed so far)",
                len(self._peers), self.max_peers, addr, self.sheds,
            )
            obs_flight.record(
                "load_shed", where="connection", node=self.node_id[:8],
                peers=len(self._peers), max_peers=self.max_peers,
                sheds=self.sheds,
            )
        try:
            await self._send_frame(writer, asyncio.Lock(), {"type": "__busy__"})
        except (ConnectionError, OSError):
            pass  # the dialer is gone; the shed stands either way
        writer.close()

    def _register_peer(self, peer_id, reader, writer, host, port,
                       wire: str = "json", resume: bool = False) -> None:
        old = self._peers.pop(peer_id, None)
        if old is not None:
            old.writer.close()
            task = self._read_tasks.pop(peer_id, None)
            if task:
                task.cancel()
        peer = _Peer(peer_id, reader, writer, host, port, wire=wire,
                     resume=resume)
        self._peers[peer_id] = peer
        self._addr[peer_id] = (host, port)
        self._intentional.discard(peer_id)
        self._read_tasks[peer_id] = asyncio.create_task(self._read_loop(peer))
        logger.info("peer %s connected (%s:%s, wire=%s)", peer_id[:8], host,
                    port, wire)
        self._fire_connection_event("connect", peer_id)

    async def disconnect_from_peer(self, peer_id: str,
                                   intentional: bool = True) -> None:
        """Drop a peer.  ``intentional=True`` (the default: a local request)
        additionally marks the peer as not-to-be-healed; transport-failure
        evictions pass False so session healing may redial."""
        if intentional:
            self._intentional.add(peer_id)
        peer = self._peers.pop(peer_id, None)
        task = self._read_tasks.pop(peer_id, None)
        if task:
            task.cancel()
        if peer is not None:
            peer.writer.close()
            self._fire_connection_event("disconnect", peer_id)

    # -- send ----------------------------------------------------------------

    async def send_message(self, peer_id: str, msg_type: str, **payload: Any) -> bool:
        """Send a JSON message; bytes values are transparently base64-tagged."""
        peer = self._peers.get(peer_id)
        if peer is None:
            logger.warning("send to unknown peer %s", peer_id[:8])
            return False
        # the send rides the caller's span chain (a handshake's net sends
        # interleave with its device dispatches in the flame graph); the
        # node scope attributes it to THIS node even when one process
        # hosts many (the swarm benches)
        with obs_trace.node_scope(self.node_id), \
                obs_trace.span("net.send", peer=peer_id[:8], msg_type=msg_type):
            # fault-injection boundary (faults/): a plan may drop, delay, or
            # corrupt this message BEFORE encoding — a no-op without a plan
            action, payload2 = _faults.net_send(self.node_id, peer_id, msg_type,
                                                payload)
            if action == "drop":
                return True  # swallowed by the (simulated) network
            if action == "delay":
                await asyncio.sleep(payload2)
            else:
                payload = payload2
            binary = peer.wire == _BIN_WIRE_NAME and not any(
                isinstance(v, (bytes, bytearray, memoryview))
                and len(v) > _BIN_MAX_FIELD
                for v in payload.values()
            )
            # ^ messages carrying a bytes value past the decoder's
            # per-field cap (huge file sends) fall back to the JSON wire
            # for THIS message — a bin1 peer accepts JSON frames at any
            # time, so the oversized transfer chunks through exactly as
            # before negotiation instead of being dropped as hostile
            if binary:
                # negotiated binary path: bytes values ride raw (no b64/hex
                # round-trip, no copy), everything else as per-field JSON
                message = {"type": msg_type, **payload}
            else:
                message = {"type": msg_type,
                           **{k: _encode_value(v) for k, v in payload.items()}}
            # cross-peer trace propagation: a bounded, ids-only ``_trace``
            # field (the net.send span's own context, so the receiver's
            # chain parents onto this exact send).  Correlation ids only —
            # never payload data (qrflow: flow-secret-in-trace sink).
            wire_ctx = obs_trace.wire_context()
            if wire_ctx is not None:
                message["_trace"] = wire_ctx
            try:
                if binary:
                    await self._send_frame_bin(peer.writer, peer.write_lock,
                                               message)
                else:
                    await self._send_frame(peer.writer, peer.write_lock, message)
                return True
            except (ConnectionError, OSError) as e:
                logger.warning("send to %s failed: %s; evicting", peer_id[:8], e)
                await self.disconnect_from_peer(peer_id, intentional=False)
                return False

    async def _send_frame(self, writer, lock: asyncio.Lock, message: dict) -> None:
        body = json.dumps(message, separators=(",", ":")).encode()
        async with lock:
            if len(body) <= self.chunk_size:
                writer.write(_HEADER.pack(_MAGIC, _VERSION, 0, len(body)) + body)
            else:
                stream_id = uuid.uuid4().bytes
                chunks = [
                    body[i : i + self.chunk_size]
                    for i in range(0, len(body), self.chunk_size)
                ]
                for idx, chunk in enumerate(chunks):
                    payload = _CHUNK_HEADER.pack(stream_id, idx, len(chunks)) + chunk
                    writer.write(
                        _HEADER.pack(_MAGIC, _VERSION, _FLAG_CHUNK, len(payload)) + payload
                    )
            await writer.drain()

    async def _send_frame_bin(self, writer, lock: asyncio.Lock,
                              message: dict) -> None:
        """Binary-wire twin of _send_frame: length-prefixed compact frames
        with raw-bytes pass-through.  Small frames write the header and
        each encoded segment straight to the transport buffer — the
        ciphertext bytes the AEAD produced are never concatenated, encoded,
        or copied on the way out (the qrflow raw-bytes network sink)."""
        segs = _encode_bin(message)
        total = sum(len(s) for s in segs)
        async with lock:
            if total <= self.chunk_size:
                writer.write(_HEADER.pack(_MAGIC, _VERSION, _FLAG_BIN, total))
                for seg in segs:
                    writer.write(seg)
            else:
                body = b"".join(segs)  # chunked path: slicing needs one buffer
                stream_id = uuid.uuid4().bytes
                chunks = [
                    body[i: i + self.chunk_size]
                    for i in range(0, len(body), self.chunk_size)
                ]
                for idx, chunk in enumerate(chunks):
                    payload = _CHUNK_HEADER.pack(stream_id, idx, len(chunks)) + chunk
                    writer.write(
                        _HEADER.pack(_MAGIC, _VERSION,
                                     _FLAG_CHUNK | _FLAG_BIN, len(payload))
                        + payload
                    )
            await writer.drain()

    # -- receive -------------------------------------------------------------

    async def _read_plain_frame(self, reader: asyncio.StreamReader) -> dict:
        flags, payload = await self._read_raw(reader)
        if flags & _FLAG_CHUNK:
            raise WireError("unexpected chunked hello")
        if flags & _FLAG_BIN:
            # the hello IS the negotiation; it always travels as JSON
            raise WireError("unexpected binary hello")
        return json.loads(payload)

    @staticmethod
    async def _read_raw(reader: asyncio.StreamReader) -> tuple[int, bytes]:
        header = await reader.readexactly(_HEADER.size)
        magic, version, flags, length = _HEADER.unpack(header)
        if magic != _MAGIC or version != _VERSION:
            raise WireError(f"bad frame header {header!r}")
        if length > MAX_FRAME:
            raise WireError(f"oversized frame ({length} bytes)")
        return flags, await reader.readexactly(length)

    def _decode_body(self, peer: _Peer, body, binary: bool) -> dict:
        """One logical frame body -> message dict; malformed input of
        either format is a typed WireError (the read loop's loud drop)."""
        if binary:
            if peer.wire != _BIN_WIRE_NAME:
                raise WireError("binary frame from un-negotiated peer")
            return _decode_bin(body)
        try:
            message = json.loads(body)
        except ValueError as e:
            raise WireError(f"malformed JSON frame: {e}") from e
        if not isinstance(message, dict):
            raise WireError("JSON frame is not an object")
        return message

    async def _read_loop(self, peer: _Peer) -> None:
        try:
            while True:
                flags, payload = await self._read_raw(peer.reader)
                chunks = 0
                binary = bool(flags & _FLAG_BIN)
                if flags & _FLAG_CHUNK:
                    reassembled = self._reassemble(peer, payload, binary)
                    if reassembled is None:
                        continue
                    message, chunks = reassembled
                else:
                    message = self._decode_body(peer, payload, binary)
                await self._dispatch(peer.peer_id, message, chunks)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        except WireError as e:
            # hostile or corrupt wire input: TYPED and loud — one warning,
            # one flight event, one counted connection drop.  The serving
            # loop and every other peer keep running (the finally below
            # evicts exactly this peer); the dialing side's session-heal
            # machinery may redial.
            self.wire_errors += 1
            logger.warning("wire error from %s: %s; dropping connection "
                           "(%d total)", peer.peer_id[:8], e, self.wire_errors)
            obs_flight.record("wire_error", node=self.node_id[:8],
                              peer=peer.peer_id[:8], error=str(e),
                              wire=peer.wire, total=self.wire_errors)
        except Exception:
            logger.exception("read loop error for %s", peer.peer_id[:8])
        finally:
            if self._peers.get(peer.peer_id) is peer:
                self._peers.pop(peer.peer_id, None)
                self._read_tasks.pop(peer.peer_id, None)
                peer.writer.close()
                self._fire_connection_event("disconnect", peer.peer_id)

    def _reassemble(self, peer: _Peer, payload: bytes,
                    binary: bool = False) -> tuple[dict, int] | None:
        """-> (message, chunk_count) once complete, None while partial.
        The chunk count rides into the dispatch's single ``net.recv`` span
        (``chunks=`` attr): the LOGICAL message gets one span linked to its
        handlers, not per-chunk spans with no edge to the dispatch."""
        if len(payload) < _CHUNK_HEADER.size:
            raise WireError("truncated chunk header")
        stream_id, index, count = _CHUNK_HEADER.unpack_from(payload)
        if count == 0 or index >= count:
            raise WireError(f"chunk index {index} out of range (count {count})")
        data = payload[_CHUNK_HEADER.size :]
        entry = peer.reassembly.setdefault(stream_id, {"count": count, "chunks": {}})
        if count != entry["count"]:
            raise WireError("chunk count changed mid-stream")
        entry["chunks"][index] = data
        if len(entry["chunks"]) < entry["count"]:
            return None
        del peer.reassembly[stream_id]
        body = b"".join(entry["chunks"][i] for i in range(count))
        return self._decode_body(peer, body, binary), count

    async def _dispatch(self, peer_id: str, message: dict,
                        chunks: int = 0) -> None:
        msg_type = message.get("type", "")
        # cross-peer propagation: adopt the sender's bounded _trace context
        # (validated — a malformed/hostile one is ignored and the receive
        # roots a fresh trace exactly as before).  Popped FIRST so handlers
        # never see the field: the wire protocol's payload surface is
        # unchanged for them, hostile or not.
        parent = obs_trace.adopt_wire_context(message.pop("_trace", None))
        decoded = {k: _decode_value(v) for k, v in message.items()}
        handlers = self._msg_handlers.get(msg_type, [])
        if not handlers:
            logger.debug("no handler for message type %r", msg_type)
        attrs = {"chunks": chunks} if chunks else {}
        # one receive span per LOGICAL message: handler work (and any
        # crypto dispatches it enqueues) correlates under it — and, with an
        # adopted parent, under the SENDER's trace (the initiator's
        # handshake and the responder's device dispatches become one tree)
        with obs_trace.node_scope(self.node_id), \
                obs_trace.span("net.recv", parent=parent, peer=peer_id[:8],
                               msg_type=msg_type, **attrs):
            for h in list(handlers):
                try:
                    await h(peer_id, decoded)
                except Exception:
                    logger.exception("handler for %r failed", msg_type)


def _encode_value(v: Any) -> Any:
    if isinstance(v, (bytes, bytearray)):
        return {"__b64__": base64.b64encode(bytes(v)).decode("ascii")}
    return v


def _decode_value(v: Any) -> Any:
    if isinstance(v, dict) and set(v) == {"__b64__"}:
        return base64.b64decode(v["__b64__"])
    return v
