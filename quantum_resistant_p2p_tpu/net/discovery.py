"""UDP broadcast peer discovery.

Capability parity with the reference's networking/discovery.py (257 LoC):
periodic ``node_announcement`` JSON datagrams broadcast on a well-known UDP
port, direct unicast announcements for manual connects, staleness expiry,
local-IP detection via the UDP-connect trick, and manual peer registration.
"""

from __future__ import annotations

import asyncio
import json
import logging
import socket
import time
from typing import Callable

logger = logging.getLogger(__name__)

ANNOUNCE_INTERVAL = 60.0
STALE_AFTER = 300.0
DISCOVERY_PORT = 8001


def get_local_ip() -> str:
    """Best-effort local IP: open a UDP socket toward a public address."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


class NodeDiscovery:
    """Announce this node over UDP broadcast and track announcements from others."""

    def __init__(
        self,
        node_id: str,
        tcp_port: int,
        discovery_port: int = DISCOVERY_PORT,
        announce_interval: float = ANNOUNCE_INTERVAL,
    ):
        self.node_id = node_id
        self.tcp_port = tcp_port
        self.discovery_port = discovery_port
        self.announce_interval = announce_interval
        # peer_id -> {"host", "port", "last_seen"}
        self.known_nodes: dict[str, dict] = {}
        self._transport: asyncio.DatagramTransport | None = None
        self._tasks: list[asyncio.Task] = []
        self._on_discover: list[Callable[[str, str, int], None]] = []

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _DiscoveryProtocol(self),
            local_addr=("0.0.0.0", self.discovery_port),
            allow_broadcast=True,
        )
        # self._tasks is the strong reference keeping both loops alive (the
        # event loop itself only holds weak refs); the done-callback surfaces
        # a loop that dies unexpectedly — otherwise discovery would go silent
        # with the exception parked on the task until GC.
        self._tasks = [
            self._supervise(self._announce_loop(), "announce loop"),
            self._supervise(self._expiry_loop(), "expiry loop"),
        ]
        logger.info("discovery listening on UDP %d", self.discovery_port)

    def _supervise(self, coro, what: str) -> asyncio.Task:
        task = asyncio.create_task(coro)

        def _done(t: asyncio.Task) -> None:
            if not t.cancelled() and t.exception() is not None:
                logger.error("discovery %s died", what, exc_info=t.exception())

        task.add_done_callback(_done)
        return task

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    # -- announcements ------------------------------------------------------

    def _announcement(self) -> bytes:
        return json.dumps(
            {
                "type": "node_announcement",
                "node_id": self.node_id,
                "ip": get_local_ip(),
                "port": self.tcp_port,
            }
        ).encode()

    async def _announce_loop(self) -> None:
        while True:
            try:
                if self._transport is not None:
                    self._transport.sendto(
                        self._announcement(), ("255.255.255.255", self.discovery_port)
                    )
            except OSError as e:
                logger.debug("broadcast failed: %s", e)
            await asyncio.sleep(self.announce_interval)

    async def _expiry_loop(self) -> None:
        while True:
            now = time.time()
            for node_id in [
                n
                for n, info in self.known_nodes.items()
                if now - info["last_seen"] > STALE_AFTER
            ]:
                logger.info("expiring stale peer %s", node_id[:8])
                del self.known_nodes[node_id]
            await asyncio.sleep(60.0)

    def announce_to(self, host: str, port: int | None = None) -> None:
        """Unicast announcement (manual connect flow)."""
        if self._transport is not None:
            self._transport.sendto(
                self._announcement(), (host, port or self.discovery_port)
            )

    def add_known_node(self, node_id: str, host: str, port: int) -> None:
        self.known_nodes[node_id] = {"host": host, "port": port, "last_seen": time.time()}
        self._fire(node_id, host, port)

    def on_discover(self, cb: Callable[[str, str, int], None]) -> None:
        self._on_discover.append(cb)

    def _fire(self, node_id: str, host: str, port: int) -> None:
        for cb in list(self._on_discover):
            try:
                cb(node_id, host, port)
            except Exception:
                logger.exception("discovery callback failed")

    def get_discovered_nodes(self) -> dict[str, dict]:
        return dict(self.known_nodes)

    # -- datagram ingress ----------------------------------------------------

    def _on_datagram(self, data: bytes, addr: tuple[str, int]) -> None:
        try:
            msg = json.loads(data)
        except (ValueError, UnicodeDecodeError):
            return
        if msg.get("type") != "node_announcement":
            return
        node_id = msg.get("node_id")
        if not node_id or node_id == self.node_id:
            return
        host = msg.get("ip") or addr[0]
        port = int(msg.get("port", 0))
        known = node_id in self.known_nodes
        self.add_known_node(node_id, host, port) if not known else self.known_nodes[
            node_id
        ].update({"host": host, "port": port, "last_seen": time.time()})


class _DiscoveryProtocol(asyncio.DatagramProtocol):
    def __init__(self, owner: NodeDiscovery):
        self.owner = owner

    def datagram_received(self, data: bytes, addr: tuple[str, int]) -> None:
        self.owner._on_datagram(data, addr)
