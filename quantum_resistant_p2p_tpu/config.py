"""Configuration system — algorithm defaults, provider selection, batching.

The reference has no config file at all (SURVEY.md §5: pyyaml/python-dotenv
declared but never imported; everything is constructor defaults + UI state).
This framework adds the real config layer the survey calls for: a JSON file
(``~/.quantum_resistant_p2p_tpu/config.json`` by default) overridden by
``QRP2P_*`` environment variables, feeding the CLI and SecureMessaging
constructors.

Precedence: explicit kwargs > environment > config file > defaults.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from pathlib import Path

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class Config:
    # algorithm defaults (reference defaults: app/messaging.py:126-128)
    kem: str = "ML-KEM-768"
    aead: str = "AES-256-GCM"
    signature: str = "ML-DSA-65"
    # provider
    backend: str = "auto"  # cpu | tpu | auto
    use_batching: bool = False
    max_batch: int = 4096
    max_wait_ms: float = 2.0
    # networking (reference defaults: networking/p2p_node.py:20-21)
    port: int = 8000
    discovery_port: int = 8001
    chunk_size: int = 64 * 1024
    # multi-chip: tpu-backend batches shard across this many devices
    # (0 = single device, -1 = all visible)
    mesh_devices: int = 0

    @classmethod
    def default_path(cls) -> Path:
        from .storage.key_storage import get_app_data_dir

        return get_app_data_dir() / "config.json"

    @classmethod
    def load(cls, path: str | os.PathLike | None = None, **overrides) -> "Config":
        cfg = cls()
        p = Path(path) if path else cls.default_path()
        if p.exists():
            try:
                data = json.loads(p.read_text())
                for k, v in data.items():
                    if hasattr(cfg, k):
                        setattr(cfg, k, v)
                    else:
                        logger.warning("unknown config key %r in %s", k, p)
            except ValueError as e:
                logger.warning("malformed config %s: %s (using defaults)", p, e)
        for f in dataclasses.fields(cls):
            env = os.environ.get(f"QRP2P_{f.name.upper()}")
            if env is not None:
                try:
                    if f.type == "bool":
                        setattr(cfg, f.name, env.lower() in ("1", "true", "yes", "on"))
                    else:
                        setattr(cfg, f.name, type(getattr(cfg, f.name))(env))
                except ValueError:
                    logger.warning("bad env value QRP2P_%s=%r", f.name.upper(), env)
        for k, v in overrides.items():
            if v is not None:
                setattr(cfg, k, v)
        return cfg

    def save(self, path: str | os.PathLike | None = None) -> Path:
        p = Path(path) if path else self.default_path()
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(dataclasses.asdict(self), indent=2))
        return p
