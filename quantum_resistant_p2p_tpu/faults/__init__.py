"""Deterministic fault injection for chaos-testing the self-healing stack.

See :mod:`.plan` for the engine and docs/robustness.md for the fault model
and the injection boundaries.
"""

from .plan import (ACTIONS, SCOPES, FaultInjected, FaultPlan,  # noqa: F401
                   FaultRule, active, device_dispatch, install,
                   instrument_scalar_ops, net_send, poison_results,
                   scalar_op, uninstall, warmup)
