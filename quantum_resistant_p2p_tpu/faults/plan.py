"""Deterministic, seedable fault-plan engine (the chaos half of self-healing).

A :class:`FaultPlan` is a list of scoped :class:`FaultRule`\\ s injected through
EXPLICIT hook points at the three boundaries where this stack meets the
outside world:

* ``net.send``       — :meth:`net.p2p_node.P2PNode.send_message` (drop /
                       delay / corrupt an outbound message before framing)
* ``device.dispatch``— :class:`provider.batched.OpQueue`'s device call
                       (raise on the Nth dispatch, poison one batch slot)
* ``scalar.op``      — every concrete provider scalar op, instrumented at
                       class-creation time by ``provider.base`` (raise on the
                       Nth matching call)
* ``warmup``         — the background jit warm-up call (kill it)
* ``process``        — the fleet health loop (fleet/manager.py): kill or
                       pause a gateway subprocess, or partition the
                       router<->gateway control link

The hooks are no-ops (one module-global ``None`` check) unless a plan is
installed, so production code pays nothing.  All randomness — corruption byte
positions, poisoned slot indices — derives from the plan seed and the rule
index, and rule counters advance only on MATCHED events, so a chaos run is
reproducible from a single seed: same plan, same faults, same order.  No
monkeypatching anywhere.

Usage (tests; docs/robustness.md has the fault model)::

    plan = FaultPlan(seed=7, rules=[
        FaultRule("net.send", "drop", match={"msg_type": "ke_response"}, nth=1),
        FaultRule("device.dispatch", "raise", nth=3, times=2),
    ])
    with plan.activate():
        ...   # drive the stack; plan.injected records what fired
"""

from __future__ import annotations

import random
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any

SCOPES = ("net.send", "device.dispatch", "scalar.op", "warmup", "process",
          "ticket")
ACTIONS = {
    "net.send": ("drop", "delay", "corrupt"),
    "device.dispatch": ("raise", "poison", "delay"),
    "scalar.op": ("raise",),
    "warmup": ("kill",),
    # process-scope faults (fleet/manager.py): the fleet health loop polls
    # process_control(gateway) once per gateway per tick, in sorted gateway
    # order on ONE loop — so rule counters advance on a deterministic event
    # stream and the injected log is byte-reproducible from the seed even
    # though the actions themselves are wall-clock chaos (a SIGKILL, a
    # SIGSTOP, a dropped control link).  ``drain_gateway`` runs the
    # graceful-drain protocol mid-storm — composed with a kill rule on the
    # next tick it is the drain-interrupt scenario.
    # ``kill_router``/``pause_router`` target CONTROL-PLANE replicas: the
    # RouterFleet driver (fleet/router.py) polls router_control(router)
    # once per router per tick, same deterministic-stream discipline —
    # killing the LEADER mid-storm is the failover scenario the lease
    # machinery exists for.
    "process": ("kill_gateway", "pause_gateway", "partition",
                "drain_gateway", "kill_router", "pause_router"),
    # ticket-scope faults (app/messaging.py ticket-resume validation): each
    # action forces exactly one typed reject verdict on the responder —
    # "corrupt" flips a byte of the presented blob (MAC failure),
    # "expire"/"replay" force those verdicts — so chaos plans exercise
    # every reject + full-handshake-fallback path end-to-end.
    "ticket": ("corrupt", "expire", "replay"),
}


class FaultInjected(RuntimeError):
    """Raised by an injection hook standing in for a real device/net fault."""


@dataclass
class FaultRule:
    """One scoped fault.  The rule fires on matched events number
    ``nth .. nth+times-1`` (1-based) of its scope at this plan."""

    scope: str
    action: str
    match: dict[str, Any] = field(default_factory=dict)
    #: first matching event (1-based) the rule fires on
    nth: int = 1
    #: how many consecutive matching events it fires for
    times: int = 1
    #: for action == "delay"
    delay_s: float = 0.05
    #: for action == "corrupt": payload field to mutate (auto-picked if None)
    corrupt_field: str | None = None

    def __post_init__(self) -> None:
        if self.scope not in SCOPES:
            raise ValueError(f"unknown fault scope {self.scope!r}; have {SCOPES}")
        if self.action not in ACTIONS[self.scope]:
            raise ValueError(
                f"action {self.action!r} invalid for scope {self.scope!r}; "
                f"have {ACTIONS[self.scope]}"
            )

    def matches(self, info: dict[str, Any]) -> bool:
        for key, want in self.match.items():
            got = info.get(key)
            if want == "*":
                continue
            if isinstance(want, str) and isinstance(got, str):
                if want not in got and want != got:
                    return False
            elif got != want:
                return False
        return True


class FaultPlan:
    """A seeded set of fault rules plus the log of what actually fired."""

    def __init__(self, seed: int, rules: list[FaultRule]):
        self.seed = seed
        self.rules = list(rules)
        #: per-rule count of MATCHED events (fired or not)
        self._matched = [0] * len(self.rules)
        #: per-rule deterministic RNG (corruption positions, poison slots)
        self._rngs = [random.Random(seed * 1_000_003 + i)
                      for i in range(len(self.rules))]
        # hooks are hit from the event loop AND executor threads
        self._lock = threading.Lock()
        #: log of injected faults, in firing order (assert on this in tests)
        self.injected: list[dict[str, Any]] = []

    # -- lifecycle -----------------------------------------------------------

    @contextmanager
    def activate(self):
        """Install this plan globally for the duration of the block."""
        install(self)
        try:
            yield self
        finally:
            uninstall(self)

    # -- event matching ------------------------------------------------------

    def _fire(self, scope: str, info: dict[str, Any],
              actions: tuple[str, ...] | None = None):
        """-> list of (rule_index, rule, entry) that fire on this event.

        ``actions`` restricts which rules see the event — the dispatch-entry
        hook and the results-poisoning hook are DIFFERENT events of the same
        scope, and a rule's counter must advance on exactly one of them.

        Entries are NOT logged here: a fired rule may still be shadowed by
        another rule consuming the event (e.g. a drop short-circuiting a
        corrupt), so each hook logs via :meth:`_record` exactly when it
        APPLIES an action — ``plan.injected`` never lists phantom faults.
        """
        out = []
        with self._lock:
            for i, rule in enumerate(self.rules):
                if rule.scope != scope or not rule.matches(info):
                    continue
                if actions is not None and rule.action not in actions:
                    continue
                self._matched[i] += 1
                n = self._matched[i]
                if rule.nth <= n < rule.nth + rule.times:
                    entry = {"scope": scope, "action": rule.action, "n": n, **info}
                    out.append((i, rule, entry))
        return out

    def _record(self, entry: dict[str, Any]) -> None:
        with self._lock:
            self.injected.append(entry)
        # every injected fault is a flight-recorder trigger (obs/flight.py):
        # chaos runs auto-dump a diagnostic bundle when a dump dir is armed,
        # making PR-3's seeded scenarios explainable event-by-event.  The
        # entry carries only scope/action/labels — no payload bytes.
        from ..obs import flight as _flight

        _flight.trigger("fault_injected", seed=self.seed, **entry)

    # -- scope hooks (called by the module-level functions below) ------------

    def net_send(self, sender: str, peer: str, msg_type: str,
                 payload: dict[str, Any]):
        """-> ("drop", None) | ("delay", seconds) | ("send", payload).

        A "corrupt" rule returns ("send", mutated-copy): one byte of one
        bytes/hex-string field is flipped at a seed-deterministic position.
        """
        info = {"sender": sender, "peer": peer, "msg_type": msg_type}
        for i, rule, entry in self._fire("net.send", info):
            if rule.action == "drop":
                self._record(entry)
                return ("drop", None)
            if rule.action == "delay":
                self._record(entry)
                return ("delay", rule.delay_s)
            payload = _corrupt_payload(payload, self._rngs[i],
                                       rule.corrupt_field)
            self._record(entry)
        return ("send", payload)

    def device_dispatch(self, label: str, n_items: int,
                        shard: int | None = None,
                        lane: str | None = None) -> None:
        """May raise FaultInjected (a device fault at the dispatch boundary).
        ``shard`` is the placement-axis coordinate (provider/scheduler.py)
        so a plan can kill ONE shard's device: match={"shard": i}; ``lane``
        is the flush's priority lane name ("rekey"/"handshake"/"bulk",
        provider/batched.py) so a gateway chaos plan can target one lane's
        flushes: match={"lane": "bulk"}."""
        info = {"op": label, "n_items": n_items, "shard": shard, "lane": lane}
        for _i, rule, entry in self._fire("device.dispatch", info,
                                          actions=("raise", "delay")):
            if rule.action == "raise":
                self._record(entry)
                raise FaultInjected(
                    f"injected device fault at dispatch of {label!r}"
                )
            if rule.action == "delay":
                import time

                self._record(entry)
                time.sleep(rule.delay_s)

    def poison_results(self, label: str, results: list[Any]) -> list[Any]:
        """Replace one batch slot's result with an Exception instance (the
        per-item failure convention of provider/batched.py)."""
        if not results:
            return results
        out = results
        info = {"op": label, "n_items": len(results)}
        for i, _rule, entry in self._fire("device.dispatch", info,
                                          actions=("poison",)):
            slot = self._rngs[i].randrange(len(results))
            entry["slot"] = slot
            self._record(entry)
            out = list(out)
            out[slot] = FaultInjected(
                f"injected poisoned batch slot {slot} in {label!r}"
            )
        return out

    def scalar_op(self, algo: str, op: str) -> None:
        """May raise FaultInjected (a fault inside one provider scalar op)."""
        for _i, rule, entry in self._fire("scalar.op", {"algo": algo, "op": op}):
            if rule.action == "raise":
                self._record(entry)
                raise FaultInjected(f"injected scalar fault in {algo}.{op}")

    def warmup(self, label: str) -> None:
        """May raise FaultInjected (the warm-up thread dies mid-compile)."""
        for _i, rule, entry in self._fire("warmup", {"op": label}):
            if rule.action == "kill":
                self._record(entry)
                raise FaultInjected(f"injected warm-up kill for {label!r}")

    def ticket_validation(self, node: str, peer: str) -> list[str]:
        """-> the ticket-scope actions firing on this resume-validation
        event (app/messaging.py applies them: corrupt the presented blob /
        force the expired / replayed verdict).  Every fired entry is
        recorded to ``injected``."""
        out: list[str] = []
        for _i, rule, entry in self._fire("ticket",
                                          {"node": node, "peer": peer}):
            self._record(entry)
            out.append(rule.action)
        return out

    def process_control(self, gateway: str) -> list[dict[str, Any]]:
        """-> the process-scope actions firing on this fleet-tick event.

        One call = one matched event for every ``process`` rule matching
        ``{"gateway": gateway}``; the fleet health loop applies the
        returned entries (``kill_gateway`` -> SIGKILL the subprocess,
        ``pause_gateway`` -> SIGSTOP for ``delay_s`` then SIGCONT,
        ``partition`` -> drop the router<->gateway control traffic for
        ``delay_s``).  Every fired entry is recorded to ``injected``.
        """
        out: list[dict[str, Any]] = []
        for _i, rule, entry in self._fire("process", {"gateway": gateway}):
            if rule.action in ("pause_gateway", "partition"):
                entry["delay_s"] = rule.delay_s
            self._record(entry)
            out.append(entry)
        return out

    def router_control(self, router: str) -> list[dict[str, Any]]:
        """-> the process-scope actions firing on this ROUTER-tick event.

        The RouterFleet driver (fleet/router.py) polls this once per
        router per tick in sorted router order; a rule matching
        ``{"router": router}`` fires here and never on the gateway
        stream (matches() requires the key to be present), so one plan
        can choreograph both tiers from one seed.  ``kill_router`` ->
        SIGKILL the replica, ``pause_router`` -> SIGSTOP for ``delay_s``
        then SIGCONT.  Every fired entry is recorded to ``injected``.
        """
        out: list[dict[str, Any]] = []
        for _i, rule, entry in self._fire("process", {"router": router}):
            if rule.action == "pause_router":
                entry["delay_s"] = rule.delay_s
            self._record(entry)
            out.append(entry)
        return out


def _corrupt_payload(payload: dict[str, Any], rng: random.Random,
                     field_name: str | None) -> dict[str, Any]:
    """Deterministically flip one byte of one corruptible field.

    Corruptible = a bytes-like value (bytes/bytearray/memoryview — the
    binary wire hands zero-copy views around), or a hex string of >= 16
    chars (the JSON wire encoding for keys/ciphertexts/signatures);
    nested one level into dict values (``ke_data``).  Returns a mutated
    COPY — the caller's dict (and any shared buffer behind a view) is
    never aliased.
    """
    paths: list[tuple[str, ...]] = []

    def scan(prefix: tuple[str, ...], obj: dict[str, Any]) -> None:
        for key in sorted(obj):
            val = obj[key]
            if isinstance(val, (bytes, bytearray, memoryview)) and len(val) > 0:
                paths.append(prefix + (key,))
            elif isinstance(val, str) and len(val) >= 16 and _is_hex(val):
                paths.append(prefix + (key,))
            elif isinstance(val, dict) and not prefix:
                scan(prefix + (key,), val)

    scan((), payload)
    if field_name is not None:
        paths = [p for p in paths if p[-1] == field_name]
    if not paths:
        return payload
    path = paths[rng.randrange(len(paths))]
    out = dict(payload)
    target: dict[str, Any] = out
    for key in path[:-1]:
        target[key] = dict(target[key])
        target = target[key]
    val = target[path[-1]]
    if isinstance(val, (bytes, bytearray, memoryview)):
        pos = rng.randrange(len(val))
        buf = bytearray(val)
        buf[pos] ^= 0xFF
        target[path[-1]] = bytes(buf)
    else:
        pos = 2 * rng.randrange(len(val) // 2)
        byte = int(val[pos:pos + 2], 16) ^ 0xFF
        target[path[-1]] = val[:pos] + format(byte, "02x") + val[pos + 2:]
    return out


def _is_hex(s: str) -> bool:
    try:
        bytes.fromhex(s if len(s) % 2 == 0 else s + "0")
        return True
    except ValueError:
        return False


# -- global installation ------------------------------------------------------

_ACTIVE: FaultPlan | None = None


def install(plan: FaultPlan) -> None:
    global _ACTIVE
    if _ACTIVE is not None and _ACTIVE is not plan:
        raise RuntimeError("another FaultPlan is already installed")
    _ACTIVE = plan


def uninstall(plan: FaultPlan | None = None) -> None:
    global _ACTIVE
    if plan is None or _ACTIVE is plan:
        _ACTIVE = None


def active() -> FaultPlan | None:
    return _ACTIVE


# -- hook functions (the only surface production code calls) ------------------


def net_send(sender: str, peer: str, msg_type: str, payload: dict[str, Any]):
    """-> ("send", payload) normally; ("drop", None) / ("delay", s) under a
    plan.  The returned payload may be a corrupted copy."""
    plan = _ACTIVE
    if plan is None:
        return ("send", payload)
    return plan.net_send(sender, peer, msg_type, payload)


def device_dispatch(label: str, n_items: int, shard: int | None = None,
                    lane: str | None = None) -> None:
    plan = _ACTIVE
    if plan is not None:
        plan.device_dispatch(label, n_items, shard=shard, lane=lane)


def poison_results(label: str, results: list[Any]) -> list[Any]:
    plan = _ACTIVE
    if plan is None:
        return results
    return plan.poison_results(label, results)


def scalar_op(algo: str, op: str) -> None:
    plan = _ACTIVE
    if plan is not None:
        plan.scalar_op(algo, op)


def warmup(label: str) -> None:
    plan = _ACTIVE
    if plan is not None:
        plan.warmup(label)


def ticket_validation(node: str, peer: str) -> list:
    """Ticket-scope hook (app/messaging.py resume validation): the fired
    corrupt/expire/replay actions for this presentation, [] without a
    plan."""
    plan = _ACTIVE
    if plan is None:
        return []
    return plan.ticket_validation(node, peer)


def process_control(gateway: str) -> list:
    """Process-scope fleet hook (fleet/manager.py health loop): the fired
    kill/pause/partition entries for this gateway's tick, [] without a
    plan."""
    plan = _ACTIVE
    if plan is None:
        return []
    return plan.process_control(gateway)


def router_control(router: str) -> list:
    """Process-scope control-plane hook (fleet/router.py chaos tick): the
    fired kill_router/pause_router entries for this router's tick, []
    without a plan."""
    plan = _ACTIVE
    if plan is None:
        return []
    return plan.router_control(router)


# -- provider scalar-op instrumentation ---------------------------------------

#: scalar ops instrumented on every concrete provider class (provider/base.py
#: calls instrument_scalar_ops from CryptoAlgorithm.__init_subclass__)
_SCALAR_OPS = ("generate_keypair", "encapsulate", "decapsulate",
               "sign", "verify", "encrypt", "decrypt")


def instrument_scalar_ops(cls) -> None:
    """Wrap the scalar ops defined on ``cls`` with the ``scalar.op`` hook.

    Idempotent; abstract methods are left alone.  The wrapper is one global
    ``None`` check when no plan is installed — negligible next to any
    crypto op it guards.
    """
    import functools

    for name in _SCALAR_OPS:
        fn = cls.__dict__.get(name)
        if (fn is None or not callable(fn)
                or getattr(fn, "__isabstractmethod__", False)
                or getattr(fn, "_qrp2p_fault_hook", False)):
            continue

        def make(fn=fn, op=name):
            @functools.wraps(fn)
            def wrapper(self, *args, **kwargs):
                plan = _ACTIVE
                if plan is not None:
                    plan.scalar_op(getattr(self, "name", type(self).__name__), op)
                return fn(self, *args, **kwargs)

            wrapper._qrp2p_fault_hook = True
            return wrapper

        setattr(cls, name, make())
