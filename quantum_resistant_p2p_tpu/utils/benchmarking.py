"""Honest device timing helpers.

On this environment's remote-TPU platform ("axon", a tunnel to one v5e chip)
``jax.block_until_ready`` returns once the *handle* is ready, before device
execution has actually finished — timing dispatch, not compute.  Round 1's
headline number (61.5M encaps/s, BENCH_r01.json) was inflated ~6000x by
exactly this.  The only reliable fence is a small host readback that depends
on the output buffer: transferring even one element forces the producing
computation (and everything it depends on) to complete.

All benchmarks in this repo time ``reps`` back-to-back dispatches followed by
one such readback, so per-dispatch overhead pipelines the way it would in
production (the batching queue also issues back-to-back batches).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import numpy as np


def enable_compile_cache(path: str = "/tmp/jax_cache_qrp2p") -> None:
    """Persistent XLA compilation cache (same dir as tests/conftest.py).

    The crypto programs are compile-heavy (minutes for the big signature
    graphs); with the cache, repeat bench runs skip straight to execution.
    Call before the first jit use in every bench/tool entry point.
    """
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def sync(tree: Any) -> None:
    """Force real completion of every array in ``tree`` via host readback."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "addressable_shards") or hasattr(leaf, "device"):
            np.asarray(jax.device_get(leaf.ravel()[:1] if hasattr(leaf, "ravel") else leaf))


def timeit(fn: Callable, *args, min_time_s: float = 1.5, trials: int = 2) -> float:
    """Best-of-``trials`` mean seconds per call of ``fn(*args)``, honest-sync.

    The first call (compile + warm-up) is excluded.  Each trial times ``reps``
    back-to-back dispatches ending in one forced readback; ``reps`` is grown
    until a trial takes at least ``min_time_s`` so the tunnel's ~100 ms fixed
    round-trip latency (measured on this environment's remote TPU) inflates
    the result by <~7% — the reported number is conservative, never flattering.
    """
    sync(fn(*args))  # compile + warm caches

    def trial(reps: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            out = fn(*args)
        sync(out)
        return time.perf_counter() - t0

    reps = 1
    total = trial(reps)
    while total < min_time_s:
        reps = max(reps * 2, int(reps * min_time_s / max(total, 1e-6)) + 1)
        total = trial(reps)
    best = total
    for _ in range(trials - 1):
        best = min(best, trial(reps))
    return best / reps
