"""Honest device timing helpers.

On this environment's remote-TPU platform ("axon", a tunnel to one v5e chip)
``jax.block_until_ready`` returns once the *handle* is ready, before device
execution has actually finished — timing dispatch, not compute.  Round 1's
headline number (61.5M encaps/s, BENCH_r01.json) was inflated ~6000x by
exactly this.  The only reliable fence is a small host readback that depends
on the output buffer: transferring even one element forces the producing
computation (and everything it depends on) to complete.

All benchmarks in this repo time ``reps`` back-to-back dispatches followed by
one such readback, so per-dispatch overhead pipelines the way it would in
production (the batching queue also issues back-to-back batches).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import numpy as np


def sync(tree: Any) -> None:
    """Force real completion of every array in ``tree`` via host readback."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "addressable_shards") or hasattr(leaf, "device"):
            np.asarray(jax.device_get(leaf.ravel()[:1] if hasattr(leaf, "ravel") else leaf))


def timeit(fn: Callable, *args, reps: int = 3, trials: int = 3) -> float:
    """Best-of-``trials`` mean seconds per call of ``fn(*args)``, honest-sync.

    The first call (compile + warm-up) is excluded.  Each trial times ``reps``
    back-to-back dispatches ending in one forced readback.
    """
    sync(fn(*args))  # compile + warm caches
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            out = fn(*args)
        sync(out)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best
