"""Host-side utilities: profiling/tracing hooks, shared helpers."""


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 0); 1 for n <= 1."""
    return 1 << (n - 1).bit_length() if n > 1 else 1
