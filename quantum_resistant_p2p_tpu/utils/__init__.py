"""Host-side utilities: benchmarking helpers, CTR-DRBG, shared helpers.
(The profiling/tracing hooks moved to ``quantum_resistant_p2p_tpu.obs``
in PR 5; the deprecation shim that bridged the old import path has been
removed.)"""


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 0); 1 for n <= 1."""
    return 1 << (n - 1).bit_length() if n > 1 else 1
