"""Host-side utilities: profiling/tracing hooks."""
