"""AES-256 CTR-DRBG (NIST SP 800-90A, no derivation function).

This is the exact RNG the NIST PQC KAT harness uses (the submission
packages' rng.c: ``randombytes_init(entropy48)`` + AES-256-CTR update), so
official ``PQCgenKAT_*.rsp`` files — whose per-count ``seed`` drives every
``randombytes`` call inside keygen/encaps — can be reproduced bit-exactly
once dropped into ``tests/vectors/`` (see tests/test_kat.py).  The reference
app gets this behavior from liboqs's internal RNG (SURVEY.md §2.2 last row);
no network access exists in this environment to fetch the official files, so
the DRBG + parser are shipped ready and exercised against self-generated
fixtures.

AES via the ``cryptography`` package (OpenSSL) — an external implementation,
not this repo's JAX AES.
"""

from __future__ import annotations

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes


def _aes256_ecb_block(key: bytes, block: bytes) -> bytes:
    enc = Cipher(algorithms.AES(key), modes.ECB()).encryptor()
    return enc.update(block) + enc.finalize()


def _incr(v: bytearray) -> None:
    for i in range(15, -1, -1):
        v[i] = (v[i] + 1) & 0xFF
        if v[i]:
            break


class CtrDrbg:
    """AES-256 CTR-DRBG without DF — NIST KAT harness ``randombytes``."""

    def __init__(self, entropy48: bytes, personalization: bytes | None = None):
        if len(entropy48) != 48:
            raise ValueError("entropy input must be 48 bytes")
        seed = bytearray(entropy48)
        if personalization:
            if len(personalization) != 48:
                raise ValueError("personalization string must be 48 bytes")
            for i in range(48):
                seed[i] ^= personalization[i]
        self._key = b"\0" * 32
        self._v = bytearray(16)
        self._update(bytes(seed))

    def _update(self, provided: bytes | None) -> None:
        temp = bytearray()
        v = bytearray(self._v)
        for _ in range(3):
            _incr(v)
            temp += _aes256_ecb_block(self._key, bytes(v))
        if provided is not None:
            for i in range(48):
                temp[i] ^= provided[i]
        self._key = bytes(temp[:32])
        self._v = bytearray(temp[32:48])

    def random_bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            _incr(self._v)
            out += _aes256_ecb_block(self._key, bytes(self._v))
        self._update(None)
        return bytes(out[:n])
