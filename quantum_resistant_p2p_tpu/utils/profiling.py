"""Deprecated shim — the profiling tools moved into the observability
subsystem (``quantum_resistant_p2p_tpu.obs``, PR 5):

* ``LatencyHistogram``  -> :class:`quantum_resistant_p2p_tpu.obs.metrics.LatencyHistogram`
* ``device_trace``      -> :func:`quantum_resistant_p2p_tpu.obs.trace.device_trace`

Existing imports keep working through this module; new code should import
from ``obs`` directly (this shim will be removed once nothing imports it).
"""

from __future__ import annotations

import warnings

from ..obs.metrics import LatencyHistogram  # noqa: F401
from ..obs.trace import device_trace  # noqa: F401

__all__ = ["LatencyHistogram", "device_trace"]

warnings.warn(
    "quantum_resistant_p2p_tpu.utils.profiling moved to "
    "quantum_resistant_p2p_tpu.obs (metrics.LatencyHistogram, "
    "trace.device_trace); update imports",
    DeprecationWarning,
    stacklevel=2,
)
