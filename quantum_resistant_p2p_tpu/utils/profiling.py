"""Tracing / profiling hooks — the observability layer SURVEY.md §5 notes the
reference lacks (its only timing is ad-hoc time.time() deltas in the test
harness).

Two tools:
* ``device_trace``: context manager around ``jax.profiler`` producing a
  TensorBoard-loadable trace of the batched crypto dispatches.
* ``LatencyHistogram``: lock-free-ish percentile tracker used by the batch
  queue stats and the swarm benchmark.
"""

from __future__ import annotations

import bisect
import contextlib
import time


@contextlib.contextmanager
def device_trace(log_dir: str = "/tmp/qrp2p_trace"):
    """Profile everything inside the block; view with TensorBoard."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


class LatencyHistogram:
    """Bounded sorted sample reservoir with percentile queries."""

    def __init__(self, cap: int = 10000):
        self.cap = cap
        self._sorted: list[float] = []
        self.count = 0
        self.total = 0.0

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        if len(self._sorted) < self.cap:
            bisect.insort(self._sorted, seconds)
        else:  # reservoir: replace a deterministic slot to stay bounded
            idx = self.count % self.cap
            del self._sorted[idx]
            bisect.insort(self._sorted, seconds)

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(time.perf_counter() - t0)

    def percentile(self, p: float) -> float | None:
        if not self._sorted:
            return None
        idx = min(len(self._sorted) - 1, int(p / 100.0 * len(self._sorted)))
        return self._sorted[idx]

    def summary(self) -> dict:
        return {
            "count": self.count,
            "mean_s": self.total / self.count if self.count else None,
            "p50_s": self.percentile(50),
            "p95_s": self.percentile(95),
            "p99_s": self.percentile(99),
        }
