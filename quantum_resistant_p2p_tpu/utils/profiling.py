"""Tracing / profiling hooks — the observability layer SURVEY.md §5 notes the
reference lacks (its only timing is ad-hoc time.time() deltas in the test
harness).

Two tools:
* ``device_trace``: context manager around ``jax.profiler`` producing a
  TensorBoard-loadable trace of the batched crypto dispatches.
* ``LatencyHistogram``: sliding-window percentile tracker backing the
  batch queue's per-flush dispatch stats (provider/batched.py QueueStats,
  surfaced via the CLI's /batchstats and the swarm benchmark's hub_queue
  section).
"""

from __future__ import annotations

import collections
import contextlib
import time


@contextlib.contextmanager
def device_trace(log_dir: str = "/tmp/qrp2p_trace"):
    """Profile everything inside the block; view with TensorBoard."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


class LatencyHistogram:
    """Sliding-window percentile tracker over the last ``cap`` samples.

    A deque of recent samples, sorted on demand: percentiles reflect the
    CURRENT behavior of the system (a lifetime reservoir would keep
    reporting stale latencies long after a regression starts).  Queries are
    rare (metrics dialogs, bench summaries), so the O(cap log cap) sort per
    query is the right trade against per-record cost.
    """

    def __init__(self, cap: int = 1024):
        self._window: collections.deque[float] = collections.deque(maxlen=cap)
        self.count = 0
        self.total = 0.0
        #: most recent sample (None before the first record): metrics
        #: surfaces like "trips in the last handshake" want the latest
        #: observation, not a percentile of the window
        self.last: float | None = None

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self._window.append(seconds)
        self.last = seconds

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(time.perf_counter() - t0)

    def percentile(self, p: float) -> float | None:
        if not self._window:
            return None
        s = sorted(self._window)
        return s[min(len(s) - 1, int(p / 100.0 * len(s)))]

    def summary(self) -> dict:
        return {
            "count": self.count,
            "mean_s": self.total / self.count if self.count else None,
            "last_s": self.last,
            "p50_s": self.percentile(50),
            "p95_s": self.percentile(95),
            "p99_s": self.percentile(99),
        }
