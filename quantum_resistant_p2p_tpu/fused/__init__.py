"""Fused multi-op handshake device programs (dispatch fusion).

One handshake on the batched TPU path used to cost ~9-11 serial device
round trips (r4 SLO decomposition): every protocol step dispatched its KEM
op and its transcript signature/verification separately, each paying the
full per-dispatch round trip while batch-1 device compute is single-digit
milliseconds.  The programs in this package run what the protocol executes
back-to-back as ONE jitted program — ML-KEM keygen/encaps/decaps, the
transcript hash (device-side, variable-length: core.keccak.sponge_varlen)
and the ML-DSA sign/verify — cutting the handshake to <= 4 trips without
changing a byte on the wire.

Exposed to the stack through the optional ``FusedHandshakeOps`` capability
(provider/base.py, provider/fused_providers.py, registry ``get_fused``).
"""

from .mlkem_mldsa import (  # noqa: F401
    encode_hex,
    get_decaps_verify_sign,
    get_encaps_verify_sign,
    get_keygen_sign,
    transcript_mu,
)
