"""ML-KEM x ML-DSA fused handshake programs — three dispatches become one.

Each program combines the device work one protocol step performs
back-to-back today (kem op, transcript hash, signature op) into a single
jitted XLA program, so a handshake step pays ONE dispatch round trip
instead of two or three.  The tricky part is that two of the transcripts
embed a device output (the hex of the fresh public key / ciphertext), so
the host cannot pre-hash them: the host passes the canonical-JSON
transcript as a *template* with a zeroed gap at a static offset, the
device hex-encodes its output into the gap (static-shape concatenation,
no gathers) and hashes the assembled message with the variable-length
sponge (``core.keccak.sponge_varlen`` — the JSON tail length differs per
lane: peer ids, timestamp reprs).

Wire compatibility: the rendered message is byte-identical to what the
separate-op path signs (``bytes.hex()`` is lowercase; the template is the
canonical JSON with a same-length placeholder), so peers cannot tell fused
and unfused stacks apart — tests/test_fused.py proves cross-path interop
and bit-exactness against the separate-op programs under injected seeds.

Program inventory (initiator/responder roles per app/messaging.py):

* ``keygen_sign``         — ke_init:     ML-KEM keygen + sign(init transcript)
* ``encaps_verify_sign``  — ke_init -> ke_response: verify(init) + encaps +
                            sign(response transcript)
* ``decaps_verify_sign``  — ke_response -> ke_confirm: verify(response) +
                            decaps + sign(confirm transcript; the confirm
                            transcript embeds no device output, so its mu
                            is hashed host-side and passed in)

The remaining step (verify of ke_confirm) is a plain single-op dispatch:
4 trips per handshake total.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core import keccak
from ..kem import mlkem
from ..sig import mldsa
from ..pyref.mlkem_ref import PARAMS as _KEM_PARAMS
from ..pyref.mldsa_ref import PARAMS as _SIG_PARAMS


def encode_hex(x: jax.Array) -> jax.Array:
    """(..., L) uint8 -> (..., 2L) uint8 lowercase ASCII hex.

    Device-side ``bytes.hex()``: pure arithmetic on the nibbles (digit or
    letter via one compare), no lookup tables, so it fuses into the
    surrounding program instead of forcing a host round trip.
    """
    x = jnp.asarray(x, jnp.uint8)
    nib = jnp.stack([x >> 4, x & 0xF], axis=-1).astype(jnp.int32)
    ch = nib + 48 + jnp.where(nib > 9, 39, 0)  # '0'..'9' then 'a'..'f'
    return ch.astype(jnp.uint8).reshape(x.shape[:-1] + (2 * x.shape[-1],))


def transcript_mu(sig_sk: jax.Array, msg: jax.Array, msg_len: jax.Array) -> jax.Array:
    """mu = SHAKE256(tr || M', 64) for the FIPS 204 pure mode, on device.

    M' = 0x00 || len(ctx)=0x00 || msg (empty context — the framing
    sig_providers._m_prime applies host-side); tr is sk[64:128].  ``msg``
    is a (..., LMAX) template buffer whose true per-lane length is
    ``msg_len`` (bytes past it are ignored by the varlen sponge).
    """
    tr = jnp.asarray(sig_sk, jnp.uint8)[..., 64:128]
    frame = jnp.zeros(msg.shape[:-1] + (2,), jnp.uint8)
    buf = jnp.concatenate([tr, frame, jnp.asarray(msg, jnp.uint8)], axis=-1)
    return keccak.sponge_varlen(buf, 66 + jnp.asarray(msg_len, jnp.int32),
                                136, 0x1F, 64)


def _insert_hex(tmpl: jax.Array, payload: jax.Array, off: int) -> jax.Array:
    """Hex-encode ``payload`` into the zeroed gap at static offset ``off``."""
    tmpl = jnp.asarray(tmpl, jnp.uint8)
    hexp = encode_hex(payload)
    return jnp.concatenate(
        [tmpl[..., :off], hexp, tmpl[..., off + hexp.shape[-1]:]], axis=-1
    )


@functools.cache
def get_keygen_sign(kem_name: str, sig_name: str, pk_off: int):
    """Jitted ke_init program: (d, z, sig_sk, rnd, tmpl, msg_len) ->
    (ek, dk, sigma, done).  ``tmpl`` is the canonical init transcript with
    a 2*ek_len zeroed gap at static byte offset ``pk_off``."""
    kp, sp = _KEM_PARAMS[kem_name], _SIG_PARAMS[sig_name]

    def run(d, z, sig_sk, rnd, tmpl, msg_len):
        ek, dk = mlkem.keygen(kp, d, z)
        msg = _insert_hex(tmpl, ek, pk_off)
        mu = transcript_mu(sig_sk, msg, msg_len)
        sigma, done = mldsa.sign_mu(sp, sig_sk, mu, rnd)
        return ek, dk, sigma, done

    return jax.jit(run)


@functools.cache
def get_encaps_verify_sign(kem_name: str, sig_name: str, ct_off: int):
    """Jitted ke_init->ke_response program:
    (ek, m, peer_pk, mu_in, sig_in, sig_sk, rnd, tmpl, msg_len) ->
    (ok, ct, shared_key, sigma, done).

    The encaps + response signature run unconditionally (speculative: a
    failed verify costs one wasted batch-1 compute, and lax.cond would
    serialise the whole batch on the slowest lane anyway); the caller
    discards everything when ``ok`` is False.
    """
    kp, sp = _KEM_PARAMS[kem_name], _SIG_PARAMS[sig_name]

    def run(ek, m, peer_pk, mu_in, sig_in, sig_sk, rnd, tmpl, msg_len):
        ok = mldsa.verify_mu(sp, peer_pk, mu_in, sig_in)
        key, ct = mlkem.encaps(kp, ek, m)
        msg = _insert_hex(tmpl, ct, ct_off)
        mu = transcript_mu(sig_sk, msg, msg_len)
        sigma, done = mldsa.sign_mu(sp, sig_sk, mu, rnd)
        return ok, ct, key, sigma, done

    # sig_in (the peer's signature, dead once verified) is donated: it is
    # byte-for-byte the same shape/dtype as the sigma output, so XLA writes
    # the response signature into the incoming one's buffer instead of
    # allocating — one signature-sized HBM buffer saved per lane.  Callers
    # must treat the operand as consumed (DONATED_ARGNUMS / donation_twin).
    return jax.jit(run, donate_argnums=(4,))


@functools.cache
def get_decaps_verify_sign(kem_name: str, sig_name: str):
    """Jitted ke_response->ke_confirm program:
    (dk, ct, peer_pk, mu_in, sig_in, sig_sk, mu_out, rnd) ->
    (ok, shared_secret, sigma, done).  The confirm transcript contains no
    device output, so its mu is hashed host-side and passed as ``mu_out``.
    """
    kp, sp = _KEM_PARAMS[kem_name], _SIG_PARAMS[sig_name]

    def run(dk, ct, peer_pk, mu_in, sig_in, sig_sk, mu_out, rnd):
        ok = mldsa.verify_mu(sp, peer_pk, mu_in, sig_in)
        ss = mlkem.decaps(kp, dk, ct)
        sigma, done = mldsa.sign_mu(sp, sig_sk, mu_out, rnd)
        return ok, ss, sigma, done

    # same aliasing as get_encaps_verify_sign: the verified peer signature's
    # buffer is reused for the confirm signature output
    return jax.jit(run, donate_argnums=(4,))


#: which positional operands each fused program consumes (donate_argnums):
#: callers must not read those operands after the call.  qrkernel's
#: read-after-donate rule polices call sites that jit with donation
#: directly; for the factory-returned programs here, ``donation_twin``
#: gives tests a CPU-faithful enforcement of the same contract.
DONATED_ARGNUMS = {
    "encaps_verify_sign": (4,),  # sig_in -> sigma
    "decaps_verify_sign": (4,),  # sig_in -> sigma
}


def donation_twin(program, argnums: tuple[int, ...]):
    """Wrap a donating jitted program so operand reuse raises on EVERY backend.

    On TPU, XLA invalidates a donated operand's buffer — a later read
    raises.  On CPU, donation is a silent no-op, so a call-site bug that
    reuses a donated operand passes tests and corrupts data only in
    production.  This twin restores the TPU semantics: after the call it
    deletes each donated jax.Array operand, making any subsequent use raise
    RuntimeError.  Tests run the fused programs through this wrapper
    (tests/test_fused.py donation-safety regression).
    """

    def run(*args):
        out = program(*args)
        for i in argnums:
            if isinstance(args[i], jax.Array):
                args[i].delete()
        return out

    return run
