// qrp_native — C++ host crypto core for the CPU backend fast path.
//
// The reference app's CPU crypto is native C (vendored liboqs, loaded via
// ctypes: reference vendor/oqs.py:122-183).  This library fills the same role
// for this framework: Keccak (SHAKE-128/256, SHA3-256/512) and a complete
// ML-KEM-512/768/1024 (FIPS 203) with deterministic seams, exposed as a thin
// extern "C" surface loaded via ctypes (no pybind11 in this environment).
// The pure-Python pyref stays as the bit-exactness oracle; this is the
// production CPU path.
//
// Build: g++ -O3 -shared -fPIC -o libqrp_native.so qrp_native.cpp

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cstddef>

namespace {

// ---------------------------------------------------------------- Keccak

const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

inline uint64_t rotl(uint64_t x, int n) { return (x << n) | (x >> (64 - n)); }

void keccak_f1600(uint64_t s[25]) {
  for (int round = 0; round < 24; ++round) {
    uint64_t c[5], d[5];
    for (int x = 0; x < 5; ++x)
      c[x] = s[x] ^ s[x + 5] ^ s[x + 10] ^ s[x + 15] ^ s[x + 20];
    for (int x = 0; x < 5; ++x) {
      d[x] = c[(x + 4) % 5] ^ rotl(c[(x + 1) % 5], 1);
      for (int y = 0; y < 5; ++y) s[x + 5 * y] ^= d[x];
    }
    // rho + pi
    uint64_t b[25];
    static const int RHO[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55, 20, 3,  10, 43,
                                25, 39, 41, 45, 15, 21, 8,  18, 2,  61, 56, 14};
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y) {
        int src = x + 5 * y;
        int dst = y + 5 * ((2 * x + 3 * y) % 5);
        b[dst] = rotl(s[src], RHO[src]);
      }
    // chi
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        s[x + 5 * y] = b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y]);
    s[0] ^= RC[round];
  }
}

struct Sponge {
  uint64_t s[25];
  unsigned rate;  // bytes
  unsigned pos;
  explicit Sponge(unsigned rate_bytes) : rate(rate_bytes), pos(0) {
    std::memset(s, 0, sizeof(s));
  }
  void absorb(const uint8_t* data, size_t len) {
    while (len) {
      size_t take = rate - pos;
      if (take > len) take = len;
      for (size_t i = 0; i < take; ++i)
        reinterpret_cast<uint8_t*>(s)[pos + i] ^= data[i];
      data += take;
      len -= take;
      pos += take;
      if (pos == rate) {
        keccak_f1600(s);
        pos = 0;
      }
    }
  }
  void finish(uint8_t ds) {
    reinterpret_cast<uint8_t*>(s)[pos] ^= ds;
    reinterpret_cast<uint8_t*>(s)[rate - 1] ^= 0x80;
    keccak_f1600(s);
    pos = 0;
  }
  void squeeze(uint8_t* out, size_t len) {
    while (len) {
      if (pos == rate) {
        keccak_f1600(s);
        pos = 0;
      }
      size_t take = rate - pos;
      if (take > len) take = len;
      std::memcpy(out, reinterpret_cast<uint8_t*>(s) + pos, take);
      out += take;
      len -= take;
      pos += take;
    }
  }
};

void shake(unsigned rate, const uint8_t* in, size_t inlen, uint8_t* out, size_t outlen) {
  Sponge sp(rate);
  sp.absorb(in, inlen);
  sp.finish(0x1f);
  sp.squeeze(out, outlen);
}

void sha3(unsigned rate, const uint8_t* in, size_t inlen, uint8_t* out, size_t outlen) {
  Sponge sp(rate);
  sp.absorb(in, inlen);
  sp.finish(0x06);
  sp.squeeze(out, outlen);
}

// ---------------------------------------------------------------- ML-KEM

constexpr int N = 256;
constexpr int Q = 3329;

struct MLKEMParams {
  int k, eta1, eta2, du, dv;
};

MLKEMParams params_for(int k) {
  if (k == 2) return {2, 3, 2, 10, 4};
  if (k == 3) return {3, 2, 2, 10, 4};
  return {4, 2, 2, 11, 5};
}

int16_t ZETAS[128];
int16_t GAMMAS[128];

struct ZetaInit {
  ZetaInit() {
    auto pw = [](int b, int e) {
      long r = 1, base = b;
      while (e) {
        if (e & 1) r = r * base % Q;
        base = base * base % Q;
        e >>= 1;
      }
      return (int)r;
    };
    auto bitrev7 = [](int i) {
      int r = 0;
      for (int b = 0; b < 7; ++b)
        if (i & (1 << b)) r |= 1 << (6 - b);
      return r;
    };
    for (int i = 0; i < 128; ++i) ZETAS[i] = (int16_t)pw(17, bitrev7(i));
    for (int i = 0; i < 128; ++i) GAMMAS[i] = (int16_t)pw(17, 2 * bitrev7(i) + 1);
  }
} zeta_init;

void ntt(int16_t f[N]) {
  int kidx = 1;
  for (int len = 128; len >= 2; len >>= 1)
    for (int start = 0; start < N; start += 2 * len) {
      int z = ZETAS[kidx++];
      for (int j = start; j < start + len; ++j) {
        int t = (int)z * f[j + len] % Q;
        f[j + len] = (int16_t)((f[j] - t + Q) % Q);
        f[j] = (int16_t)((f[j] + t) % Q);
      }
    }
}

void ntt_inv(int16_t f[N]) {
  int kidx = 127;
  for (int len = 2; len <= 128; len <<= 1)
    for (int start = 0; start < N; start += 2 * len) {
      int z = ZETAS[kidx--];
      for (int j = start; j < start + len; ++j) {
        int t = f[j];
        f[j] = (int16_t)((t + f[j + len]) % Q);
        f[j + len] = (int16_t)((long)z * ((f[j + len] - t + Q) % Q) % Q);
      }
    }
  for (int j = 0; j < N; ++j) f[j] = (int16_t)((long)f[j] * 3303 % Q);
}

void basemul(const int16_t a[N], const int16_t b[N], int16_t out[N]) {
  for (int i = 0; i < 128; ++i) {
    int a0 = a[2 * i], a1 = a[2 * i + 1], b0 = b[2 * i], b1 = b[2 * i + 1];
    out[2 * i] = (int16_t)(((long)a0 * b0 + (long)a1 * b1 % Q * GAMMAS[i]) % Q);
    out[2 * i + 1] = (int16_t)(((long)a0 * b1 + (long)a1 * b0) % Q);
  }
}

void sample_ntt(const uint8_t seed[34], int16_t out[N]) {
  Sponge sp(168);
  sp.absorb(seed, 34);
  sp.finish(0x1f);
  int count = 0;
  uint8_t buf[168];
  while (count < N) {
    sp.squeeze(buf, 168);
    for (int i = 0; i + 3 <= 168 && count < N; i += 3) {
      int d1 = buf[i] | ((buf[i + 1] & 0x0f) << 8);
      int d2 = (buf[i + 1] >> 4) | (buf[i + 2] << 4);
      if (d1 < Q) out[count++] = (int16_t)d1;
      if (d2 < Q && count < N) out[count++] = (int16_t)d2;
    }
  }
}

void cbd(const uint8_t* buf, int eta, int16_t out[N]) {
  for (int i = 0; i < N; ++i) {
    int a = 0, b = 0;
    for (int j = 0; j < eta; ++j) {
      int bit = 2 * i * eta + j;
      a += (buf[bit >> 3] >> (bit & 7)) & 1;
      bit = (2 * i + 1) * eta + j;
      b += (buf[bit >> 3] >> (bit & 7)) & 1;
    }
    out[i] = (int16_t)((a - b + Q) % Q);
  }
}

void prf(const uint8_t seed[32], uint8_t n, int eta, uint8_t* out) {
  uint8_t in[33];
  std::memcpy(in, seed, 32);
  in[32] = n;
  shake(136, in, 33, out, 64 * eta);
}

void byte_encode(const int16_t* vals, int d, uint8_t* out) {
  std::memset(out, 0, 32 * d);
  int pos = 0;
  for (int i = 0; i < N; ++i)
    for (int j = 0; j < d; ++j, ++pos)
      out[pos >> 3] |= ((vals[i] >> j) & 1) << (pos & 7);
}

void byte_decode(const uint8_t* in, int d, int16_t* out) {
  int pos = 0;
  for (int i = 0; i < N; ++i) {
    int v = 0;
    for (int j = 0; j < d; ++j, ++pos) v |= ((in[pos >> 3] >> (pos & 7)) & 1) << j;
    out[i] = (int16_t)(d == 12 ? v % Q : v);
  }
}

int compress(int x, int d) { return (int)((((long)x << (d + 1)) + Q) / (2 * Q)) % (1 << d); }
int decompress(int y, int d) { return ((y * Q) + (1 << (d - 1))) >> d; }

struct KpkeKey {
  int16_t t_hat[4][N];
  int16_t s_hat[4][N];
  uint8_t rho[32];
};

void expand_a(const uint8_t rho[32], int k, int16_t a[4][4][N], bool transposed) {
  uint8_t seed[34];
  std::memcpy(seed, rho, 32);
  for (int i = 0; i < k; ++i)
    for (int j = 0; j < k; ++j) {
      seed[32] = (uint8_t)(transposed ? i : j);
      seed[33] = (uint8_t)(transposed ? j : i);
      sample_ntt(seed, a[i][j]);
    }
}

void kpke_keygen(const MLKEMParams& p, const uint8_t d[32], uint8_t* ek, uint8_t* dk) {
  uint8_t g_in[33], g_out[64];
  std::memcpy(g_in, d, 32);
  g_in[32] = (uint8_t)p.k;
  sha3(72, g_in, 33, g_out, 64);
  const uint8_t* rho = g_out;
  const uint8_t* sigma = g_out + 32;
  int16_t a[4][4][N];
  expand_a(rho, p.k, a, false);
  int16_t s[4][N], e[4][N];
  uint8_t buf[64 * 3];
  for (int i = 0; i < p.k; ++i) {
    prf(sigma, (uint8_t)i, p.eta1, buf);
    cbd(buf, p.eta1, s[i]);
    ntt(s[i]);
  }
  for (int i = 0; i < p.k; ++i) {
    prf(sigma, (uint8_t)(p.k + i), p.eta1, buf);
    cbd(buf, p.eta1, e[i]);
    ntt(e[i]);
  }
  for (int i = 0; i < p.k; ++i) {
    int16_t acc[N] = {0}, tmp[N];
    for (int j = 0; j < p.k; ++j) {
      basemul(a[i][j], s[j], tmp);
      for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + tmp[n]) % Q);
    }
    for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + e[i][n]) % Q);
    byte_encode(acc, 12, ek + 384 * i);
    byte_encode(s[i], 12, dk + 384 * i);
  }
  std::memcpy(ek + 384 * p.k, rho, 32);
}

void kpke_encrypt(const MLKEMParams& p, const uint8_t* ek, const uint8_t m[32],
                  const uint8_t r[32], uint8_t* ct) {
  int16_t t_hat[4][N];
  for (int i = 0; i < p.k; ++i) byte_decode(ek + 384 * i, 12, t_hat[i]);
  const uint8_t* rho = ek + 384 * p.k;
  int16_t at[4][4][N];
  expand_a(rho, p.k, at, true);
  int16_t y[4][N], e1[4][N], e2[N];
  uint8_t buf[64 * 3];
  for (int i = 0; i < p.k; ++i) {
    prf(r, (uint8_t)i, p.eta1, buf);
    cbd(buf, p.eta1, y[i]);
    ntt(y[i]);
  }
  for (int i = 0; i < p.k; ++i) {
    prf(r, (uint8_t)(p.k + i), p.eta2, buf);
    cbd(buf, p.eta2, e1[i]);
  }
  prf(r, (uint8_t)(2 * p.k), p.eta2, buf);
  cbd(buf, p.eta2, e2);
  // u = invNTT(A^T y) + e1
  for (int i = 0; i < p.k; ++i) {
    int16_t acc[N] = {0}, tmp[N];
    for (int j = 0; j < p.k; ++j) {
      basemul(at[i][j], y[j], tmp);
      for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + tmp[n]) % Q);
    }
    ntt_inv(acc);
    for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + e1[i][n]) % Q);
    int16_t cmp[N];
    for (int n = 0; n < N; ++n) cmp[n] = (int16_t)compress(acc[n], p.du);
    byte_encode(cmp, p.du, ct + 32 * p.du * i);
  }
  // v = invNTT(t^T y) + e2 + Decompress(mu)
  int16_t acc[N] = {0}, tmp[N];
  for (int j = 0; j < p.k; ++j) {
    basemul(t_hat[j], y[j], tmp);
    for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + tmp[n]) % Q);
  }
  ntt_inv(acc);
  int16_t mu[N];
  byte_decode(m, 1, mu);
  for (int n = 0; n < N; ++n)
    acc[n] = (int16_t)((acc[n] + e2[n] + decompress(mu[n], 1)) % Q);
  int16_t cmp[N];
  for (int n = 0; n < N; ++n) cmp[n] = (int16_t)compress(acc[n], p.dv);
  byte_encode(cmp, p.dv, ct + 32 * p.du * p.k);
}

void kpke_decrypt(const MLKEMParams& p, const uint8_t* dk, const uint8_t* ct,
                  uint8_t m[32]) {
  int16_t u[4][N], v[N];
  for (int i = 0; i < p.k; ++i) {
    int16_t cmp[N];
    byte_decode(ct + 32 * p.du * i, p.du, cmp);
    for (int n = 0; n < N; ++n) u[i][n] = (int16_t)decompress(cmp[n], p.du);
    ntt(u[i]);
  }
  int16_t cmpv[N];
  byte_decode(ct + 32 * p.du * p.k, p.dv, cmpv);
  for (int n = 0; n < N; ++n) v[n] = (int16_t)decompress(cmpv[n], p.dv);
  int16_t acc[N] = {0}, tmp[N], s_hat[N];
  for (int i = 0; i < p.k; ++i) {
    byte_decode(dk + 384 * i, 12, s_hat);
    basemul(s_hat, u[i], tmp);
    for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + tmp[n]) % Q);
  }
  ntt_inv(acc);
  int16_t w[N];
  for (int n = 0; n < N; ++n) w[n] = (int16_t)((v[n] - acc[n] + Q) % Q);
  int16_t bits[N];
  for (int n = 0; n < N; ++n) bits[n] = (int16_t)compress(w[n], 1);
  byte_encode(bits, 1, m);
}

// ---------------------------------------------------------------- ML-DSA
//
// FIPS 204, internal forms with deterministic seams matching
// pyref/mldsa_ref.py: keygen(xi), sign_internal(sk, m_prime, rnd),
// verify_internal(pk, m_prime, sigma).  Replaces (reference): liboqs ML-DSA
// reached via crypto/signatures.py:58-188.

namespace mldsa {

constexpr int32_t MQ = 8380417;
constexpr int MD = 13;  // dropped bits (Power2Round)

struct Params {
  int k, l, eta, tau, omega;
  int32_t gamma1, gamma2;
  int ctilde_len, z_bits, w1_bits, s_bits;
  int pk_len, sk_len, sig_len;
};

constexpr Params P44 = {4, 4, 2, 39, 80, 1 << 17, (MQ - 1) / 88,
                        32, 18, 6, 3, 1312, 2560, 2420};
constexpr Params P65 = {6, 5, 4, 49, 55, 1 << 19, (MQ - 1) / 32,
                        48, 20, 4, 4, 1952, 4032, 3309};
constexpr Params P87 = {8, 7, 2, 60, 75, 1 << 19, (MQ - 1) / 32,
                        64, 20, 4, 3, 2592, 4896, 4627};

inline const Params& params_for(int level) {
  if (level == 2) return P44;
  if (level == 3) return P65;
  return P87;
}

inline int32_t freeze(int64_t x) {
  int32_t r = (int32_t)(x % MQ);
  return r < 0 ? r + MQ : r;
}

inline void secure_wipe(void* p, size_t n) {
  volatile uint8_t* b = (volatile uint8_t*)p;
  while (n--) *b++ = 0;
}

inline int32_t center(int32_t x, int32_t m) {  // mod+- into (-m/2, m/2]
  int32_t r = x % m;
  if (r < 0) r += m;
  if (r > m / 2) r -= m;
  return r;
}

int32_t DZETAS[256];
struct DZetaInit {
  DZetaInit() {
    auto pw = [](int64_t b, int e) {
      int64_t r = 1;
      while (e) {
        if (e & 1) r = r * b % MQ;
        b = b * b % MQ;
        e >>= 1;
      }
      return r;
    };
    for (int i = 0; i < 256; ++i) {
      int rev = 0;
      for (int b = 0; b < 8; ++b)
        if (i & (1 << b)) rev |= 1 << (7 - b);
      DZETAS[i] = (int32_t)pw(1753, rev);
    }
  }
} dzeta_init;

void dntt(int32_t f[N]) {
  int kidx = 0;
  for (int len = 128; len >= 1; len >>= 1)
    for (int start = 0; start < N; start += 2 * len) {
      int64_t z = DZETAS[++kidx];
      for (int j = start; j < start + len; ++j) {
        int32_t t = freeze(z * f[j + len]);
        f[j + len] = freeze((int64_t)f[j] - t);
        f[j] = freeze((int64_t)f[j] + t);
      }
    }
}

void dntt_inv(int32_t f[N]) {
  int kidx = 256;
  for (int len = 1; len <= 128; len <<= 1)
    for (int start = 0; start < N; start += 2 * len) {
      int64_t z = DZETAS[--kidx];
      for (int j = start; j < start + len; ++j) {
        int32_t t = f[j];
        f[j] = freeze((int64_t)t + f[j + len]);
        f[j + len] = freeze(z * ((int64_t)f[j + len] - t));
      }
    }
  constexpr int64_t n_inv = 8347681;  // 256^-1 mod q
  for (int j = 0; j < N; ++j) f[j] = freeze(n_inv * f[j]);
}

inline void pw_mul(const int32_t a[N], const int32_t b[N], int32_t out[N]) {
  for (int i = 0; i < N; ++i) out[i] = freeze((int64_t)a[i] * b[i]);
}

// -- rounding ---------------------------------------------------------------

inline void power2round(int32_t r, int32_t& r1, int32_t& r0) {
  r = freeze(r);
  r0 = center(r, 1 << MD);
  r1 = (r - r0) >> MD;
}

inline void decompose(const Params& p, int32_t r, int32_t& r1, int32_t& r0) {
  int32_t alpha = 2 * p.gamma2;
  r = freeze(r);
  r0 = center(r, alpha);
  if (r - r0 == MQ - 1) {
    r1 = 0;
    r0 -= 1;
  } else {
    r1 = (r - r0) / alpha;
  }
}

inline int32_t high_bits(const Params& p, int32_t r) {
  int32_t r1, r0;
  decompose(p, r, r1, r0);
  return r1;
}

inline int use_hint(const Params& p, int h, int32_t r) {
  int32_t m = (MQ - 1) / (2 * p.gamma2);
  int32_t r1, r0;
  decompose(p, r, r1, r0);
  if (!h) return r1;
  return r0 > 0 ? (r1 + 1) % m : ((r1 - 1) % m + m) % m;
}

// -- packing ----------------------------------------------------------------

void simple_bit_pack(const int32_t* c, int bits, uint8_t* out) {
  std::memset(out, 0, 32 * bits);
  int pos = 0;
  for (int i = 0; i < N; ++i)
    for (int j = 0; j < bits; ++j, ++pos)
      out[pos >> 3] |= (uint8_t)(((c[i] >> j) & 1) << (pos & 7));
}

void simple_bit_unpack(const uint8_t* b, int bits, int32_t* out) {
  int pos = 0;
  for (int i = 0; i < N; ++i) {
    int32_t v = 0;
    for (int j = 0; j < bits; ++j, ++pos)
      v |= (int32_t)((b[pos >> 3] >> (pos & 7)) & 1) << j;
    out[i] = v;
  }
}

// pack coeffs as (up - centered(c)) in `bits` bits
void bit_pack(const int32_t* c, int32_t up, int bits, uint8_t* out) {
  int32_t tmp[N];
  for (int i = 0; i < N; ++i) tmp[i] = up - center(freeze(c[i]), MQ);
  simple_bit_pack(tmp, bits, out);
}

void bit_unpack(const uint8_t* b, int32_t up, int bits, int32_t* out) {
  simple_bit_unpack(b, bits, out);
  for (int i = 0; i < N; ++i) out[i] = freeze((int64_t)up - out[i]);
}

// center(freeze(x)) over the full field
inline int32_t qcenter(int32_t x) { return center(freeze(x), MQ); }

// -- samplers ---------------------------------------------------------------

void rej_ntt_poly(const uint8_t seed[34], int32_t out[N]) {
  Sponge sp(168);
  sp.absorb(seed, 34);
  sp.finish(0x1f);
  uint8_t buf[168];
  int count = 0;
  while (count < N) {
    sp.squeeze(buf, 168);
    for (int i = 0; i + 3 <= 168 && count < N; i += 3) {
      int32_t t = buf[i] | (buf[i + 1] << 8) | ((int32_t)(buf[i + 2] & 0x7f) << 16);
      if (t < MQ) out[count++] = t;
    }
  }
}

void rej_bounded_poly(int eta, const uint8_t seed[66], int32_t out[N]) {
  Sponge sp(136);
  sp.absorb(seed, 66);
  sp.finish(0x1f);
  uint8_t buf[136];
  int count = 0;
  while (count < N) {
    sp.squeeze(buf, 136);
    for (int i = 0; i < 136 && count < N; ++i) {
      for (int half = 0; half < 2 && count < N; ++half) {
        int z = half ? (buf[i] >> 4) : (buf[i] & 0xf);
        if (eta == 2 && z < 15) out[count++] = freeze(2 - z % 5);
        else if (eta == 4 && z < 9) out[count++] = freeze(4 - z);
      }
    }
  }
}

void sample_in_ball(const Params& p, const uint8_t* ctilde, int32_t c[N]) {
  Sponge sp(136);
  sp.absorb(ctilde, (size_t)p.ctilde_len);
  sp.finish(0x1f);
  uint8_t signs[8];
  sp.squeeze(signs, 8);
  uint64_t sbits = 0;
  for (int i = 0; i < 8; ++i) sbits |= (uint64_t)signs[i] << (8 * i);
  std::memset(c, 0, N * sizeof(int32_t));
  for (int i = N - p.tau; i < N; ++i) {
    uint8_t j;
    do sp.squeeze(&j, 1); while (j > i);
    c[i] = c[j];
    c[j] = (sbits & 1) ? MQ - 1 : 1;
    sbits >>= 1;
  }
}

void expand_a(const Params& p, const uint8_t rho[32], int32_t a[8][7][N]) {
  uint8_t seed[34];
  std::memcpy(seed, rho, 32);
  for (int r = 0; r < p.k; ++r)
    for (int s = 0; s < p.l; ++s) {
      seed[32] = (uint8_t)s;
      seed[33] = (uint8_t)r;
      rej_ntt_poly(seed, a[r][s]);
    }
}

// -- hints ------------------------------------------------------------------

void hint_bit_pack(const Params& p, const uint8_t h[8][N], uint8_t* out) {
  std::memset(out, 0, (size_t)(p.omega + p.k));
  int idx = 0;
  for (int i = 0; i < p.k; ++i) {
    for (int j = 0; j < N; ++j)
      if (h[i][j]) out[idx++] = (uint8_t)j;
    out[p.omega + i] = (uint8_t)idx;
  }
}

bool hint_bit_unpack(const Params& p, const uint8_t* b, uint8_t h[8][N]) {
  std::memset(h, 0, 8 * N);
  int idx = 0;
  for (int i = 0; i < p.k; ++i) {
    int end = b[p.omega + i];
    if (end < idx || end > p.omega) return false;
    int prev = -1;
    while (idx < end) {
      int j = b[idx];
      if (prev >= 0 && j <= prev) return false;
      h[i][j] = 1;
      prev = j;
      ++idx;
    }
  }
  for (int i = idx; i < p.omega; ++i)
    if (b[i] != 0) return false;
  return true;
}

// -- keygen / sign / verify -------------------------------------------------

void keygen(const Params& p, const uint8_t xi[32], uint8_t* pk, uint8_t* sk) {
  uint8_t seed_in[34], seed[128];
  std::memcpy(seed_in, xi, 32);
  seed_in[32] = (uint8_t)p.k;
  seed_in[33] = (uint8_t)p.l;
  shake(136, seed_in, 34, seed, 128);
  const uint8_t* rho = seed;
  const uint8_t* rhop = seed + 32;
  const uint8_t* cap_k = seed + 96;

  static thread_local int32_t a[8][7][N];
  expand_a(p, rho, a);

  uint8_t sseed[66];
  std::memcpy(sseed, rhop, 64);
  int32_t s1[7][N], s2[8][N], s1h[7][N];
  for (int n = 0; n < p.l; ++n) {
    sseed[64] = (uint8_t)n;
    sseed[65] = 0;
    rej_bounded_poly(p.eta, sseed, s1[n]);
  }
  for (int n = 0; n < p.k; ++n) {
    sseed[64] = (uint8_t)(p.l + n);
    sseed[65] = 0;
    rej_bounded_poly(p.eta, sseed, s2[n]);
  }
  for (int n = 0; n < p.l; ++n) {
    std::memcpy(s1h[n], s1[n], sizeof(s1h[n]));
    dntt(s1h[n]);
  }
  // t = invNTT(A s1) + s2 ; split into t1/t0
  int32_t t1[8][N], t0[8][N];
  for (int r = 0; r < p.k; ++r) {
    int32_t acc[N] = {0}, tmp[N];
    for (int s = 0; s < p.l; ++s) {
      pw_mul(a[r][s], s1h[s], tmp);
      for (int n = 0; n < N; ++n) acc[n] = freeze((int64_t)acc[n] + tmp[n]);
    }
    dntt_inv(acc);
    for (int n = 0; n < N; ++n) {
      int32_t t = freeze((int64_t)acc[n] + s2[r][n]);
      power2round(t, t1[r][n], t0[r][n]);
    }
  }
  // pk = rho || pack(t1, 10)
  std::memcpy(pk, rho, 32);
  for (int r = 0; r < p.k; ++r) simple_bit_pack(t1[r], 23 - MD, pk + 32 + r * 320);
  // sk = rho || K || tr || pack(s1) || pack(s2) || pack(t0)
  uint8_t tr[64];
  shake(136, pk, (size_t)p.pk_len, tr, 64);
  std::memcpy(sk, rho, 32);
  std::memcpy(sk + 32, cap_k, 32);
  std::memcpy(sk + 64, tr, 64);
  int off = 128, sb = 32 * p.s_bits;
  for (int n = 0; n < p.l; ++n, off += sb) bit_pack(s1[n], p.eta, p.s_bits, sk + off);
  for (int n = 0; n < p.k; ++n, off += sb) bit_pack(s2[n], p.eta, p.s_bits, sk + off);
  for (int r = 0; r < p.k; ++r, off += 32 * MD)
    bit_pack(t0[r], 1 << (MD - 1), MD, sk + off);
  secure_wipe(s1, sizeof(s1));
  secure_wipe(s2, sizeof(s2));
  secure_wipe(s1h, sizeof(s1h));
  secure_wipe(t0, sizeof(t0));
  secure_wipe(seed, sizeof(seed));
  secure_wipe(seed_in, sizeof(seed_in));  // copy of the master secret xi
  secure_wipe(sseed, sizeof(sseed));      // rho' sampling seed
}

// scratch shared by sign/verify (single-threaded per-thread use)
struct SignScratch {
  int32_t a[8][7][N];
  int32_t s1h[7][N], s2h[8][N], t0h[8][N];
  int32_t y[7][N], yh[7][N], w[8][N], w1[8][N];
  int32_t z[7][N], c[N], ch[N];
  int32_t cs2[8][N], ct0[8][N], rm[8][N];
  uint8_t h[8][N];
};

bool sign_internal(const Params& p, const uint8_t* sk, const uint8_t* m_prime,
                   size_t mlen, const uint8_t rnd[32], uint8_t* sig) {
  const uint8_t* rho = sk;
  const uint8_t* cap_k = sk + 32;
  const uint8_t* tr = sk + 64;
  int off = 128, sb = 32 * p.s_bits;
  static thread_local SignScratch S;
  for (int n = 0; n < p.l; ++n, off += sb) {
    bit_unpack(sk + off, p.eta, p.s_bits, S.s1h[n]);
    dntt(S.s1h[n]);
  }
  for (int n = 0; n < p.k; ++n, off += sb) {
    bit_unpack(sk + off, p.eta, p.s_bits, S.s2h[n]);
    dntt(S.s2h[n]);
  }
  for (int r = 0; r < p.k; ++r, off += 32 * MD) {
    bit_unpack(sk + off, 1 << (MD - 1), MD, S.t0h[r]);
    dntt(S.t0h[r]);
  }
  expand_a(p, rho, S.a);

  uint8_t mu[64];
  {
    Sponge sp(136);
    sp.absorb(tr, 64);
    sp.absorb(m_prime, mlen);
    sp.finish(0x1f);
    sp.squeeze(mu, 64);
  }
  uint8_t rhopp[64];
  {
    Sponge sp(136);
    sp.absorb(cap_k, 32);
    sp.absorb(rnd, 32);
    sp.absorb(mu, 64);
    sp.finish(0x1f);
    sp.squeeze(rhopp, 64);
  }

  uint8_t w1_enc[8 * 32 * 6];  // k * 32 * w1_bits max
  int w1_bytes = 32 * p.w1_bits;
  // kappa is a 16-bit counter in ExpandMask; exhausting it (possible only
  // with a pathological/adversarial sk) must fail loudly, not wrap — the
  // pyref seam raises OverflowError at the same point.
  for (uint32_t kappa = 0; kappa + p.l <= 0x10000; kappa += (uint32_t)p.l) {
    // y = ExpandMask
    for (int r = 0; r < p.l; ++r) {
      uint8_t mseed[66];
      std::memcpy(mseed, rhopp, 64);
      uint16_t idx = (uint16_t)(kappa + r);
      mseed[64] = (uint8_t)(idx & 0xff);
      mseed[65] = (uint8_t)(idx >> 8);
      uint8_t buf[32 * 20];
      shake(136, mseed, 66, buf, (size_t)(32 * p.z_bits));
      bit_unpack(buf, p.gamma1, p.z_bits, S.y[r]);
      std::memcpy(S.yh[r], S.y[r], sizeof(S.yh[r]));
      dntt(S.yh[r]);
      secure_wipe(mseed, sizeof(mseed));  // rho'' copy
      secure_wipe(buf, sizeof(buf));      // packed secret mask
    }
    // w = invNTT(A yh); w1 = HighBits(w)
    for (int r = 0; r < p.k; ++r) {
      int32_t acc[N] = {0}, tmp[N];
      for (int s = 0; s < p.l; ++s) {
        pw_mul(S.a[r][s], S.yh[s], tmp);
        for (int n = 0; n < N; ++n) acc[n] = freeze((int64_t)acc[n] + tmp[n]);
      }
      dntt_inv(acc);
      std::memcpy(S.w[r], acc, sizeof(acc));
      for (int n = 0; n < N; ++n) {
        int32_t r1, r0;
        decompose(p, acc[n], r1, r0);
        S.w1[r][n] = r1;
      }
      simple_bit_pack(S.w1[r], p.w1_bits, w1_enc + r * w1_bytes);
    }
    uint8_t ctilde[64];
    {
      Sponge sp(136);
      sp.absorb(mu, 64);
      sp.absorb(w1_enc, (size_t)(p.k * w1_bytes));
      sp.finish(0x1f);
      sp.squeeze(ctilde, (size_t)p.ctilde_len);
    }
    sample_in_ball(p, ctilde, S.c);
    std::memcpy(S.ch, S.c, sizeof(S.c));
    dntt(S.ch);
    // z = y + invNTT(ch * s1h); check norm
    bool ok = true;
    for (int s = 0; s < p.l && ok; ++s) {
      int32_t tmp[N];
      pw_mul(S.ch, S.s1h[s], tmp);
      dntt_inv(tmp);
      for (int n = 0; n < N; ++n) {
        S.z[s][n] = freeze((int64_t)S.y[s][n] + tmp[n]);
        if (abs(qcenter(S.z[s][n])) >= p.gamma1 - p.tau * p.eta) {
          ok = false;
          break;
        }
      }
    }
    if (!ok) continue;
    // r_minus = w - invNTT(ch*s2h); LowBits norm check
    for (int r = 0; r < p.k && ok; ++r) {
      pw_mul(S.ch, S.s2h[r], S.cs2[r]);
      dntt_inv(S.cs2[r]);
      for (int n = 0; n < N; ++n) {
        S.rm[r][n] = freeze((int64_t)S.w[r][n] - S.cs2[r][n]);
        int32_t r1, r0;
        decompose(p, S.rm[r][n], r1, r0);
        if (abs(r0) >= p.gamma2 - p.tau * p.eta) {
          ok = false;
          break;
        }
      }
    }
    if (!ok) continue;
    // ct0 norm check
    for (int r = 0; r < p.k && ok; ++r) {
      pw_mul(S.ch, S.t0h[r], S.ct0[r]);
      dntt_inv(S.ct0[r]);
      for (int n = 0; n < N; ++n)
        if (abs(qcenter(S.ct0[r][n])) >= p.gamma2) {
          ok = false;
          break;
        }
    }
    if (!ok) continue;
    // hints
    int hcount = 0;
    for (int r = 0; r < p.k; ++r)
      for (int n = 0; n < N; ++n) {
        // MakeHint(-ct0, rm + ct0): HighBits(rm) vs HighBits(rm + ct0)
        int32_t ct0c = qcenter(S.ct0[r][n]);
        int32_t rmc = qcenter(S.rm[r][n]);
        int32_t hi_with = high_bits(p, freeze(rmc));
        int32_t hi_base = high_bits(p, freeze((int64_t)rmc + ct0c));
        S.h[r][n] = (uint8_t)(hi_with != hi_base);
        hcount += S.h[r][n];
      }
    if (hcount > p.omega) continue;
    // serialize
    std::memcpy(sig, ctilde, (size_t)p.ctilde_len);
    int soff = p.ctilde_len;
    for (int s = 0; s < p.l; ++s, soff += 32 * p.z_bits)
      bit_pack(S.z[s], p.gamma1, p.z_bits, sig + soff);
    hint_bit_pack(p, S.h, sig + soff);
    // wipe secret-derived state (expanded sk, masks, rho''); A and the
    // emitted signature are public
    secure_wipe(S.s1h, sizeof(S.s1h));
    secure_wipe(S.s2h, sizeof(S.s2h));
    secure_wipe(S.t0h, sizeof(S.t0h));
    secure_wipe(S.y, sizeof(S.y));
    secure_wipe(S.yh, sizeof(S.yh));
    secure_wipe(S.cs2, sizeof(S.cs2));
    secure_wipe(S.ct0, sizeof(S.ct0));
    secure_wipe(S.rm, sizeof(S.rm));
    secure_wipe(S.w, sizeof(S.w));
    secure_wipe(rhopp, sizeof(rhopp));
    return true;
  }
  secure_wipe(&S, sizeof(S));
  secure_wipe(rhopp, sizeof(rhopp));
  return false;
}

bool verify_internal(const Params& p, const uint8_t* pk, const uint8_t* m_prime,
                     size_t mlen, const uint8_t* sig) {
  static thread_local SignScratch S;
  const uint8_t* rho = pk;
  int32_t t1[8][N];
  for (int r = 0; r < p.k; ++r) simple_bit_unpack(pk + 32 + r * 320, 23 - MD, t1[r]);
  const uint8_t* ctilde = sig;
  int off = p.ctilde_len;
  for (int s = 0; s < p.l; ++s, off += 32 * p.z_bits) {
    bit_unpack(sig + off, p.gamma1, p.z_bits, S.z[s]);
    for (int n = 0; n < N; ++n)
      if (abs(qcenter(S.z[s][n])) >= p.gamma1 - p.tau * p.eta) return false;
  }
  if (!hint_bit_unpack(p, sig + off, S.h)) return false;
  expand_a(p, rho, S.a);
  uint8_t tr[64], mu[64];
  shake(136, pk, (size_t)p.pk_len, tr, 64);
  {
    Sponge sp(136);
    sp.absorb(tr, 64);
    sp.absorb(m_prime, mlen);
    sp.finish(0x1f);
    sp.squeeze(mu, 64);
  }
  sample_in_ball(p, ctilde, S.c);
  std::memcpy(S.ch, S.c, sizeof(S.c));
  dntt(S.ch);
  for (int s = 0; s < p.l; ++s) {
    std::memcpy(S.yh[s], S.z[s], sizeof(S.yh[s]));
    dntt(S.yh[s]);
  }
  uint8_t w1_enc[8 * 32 * 6];
  int w1_bytes = 32 * p.w1_bits;
  for (int r = 0; r < p.k; ++r) {
    int32_t acc[N] = {0}, tmp[N];
    for (int s = 0; s < p.l; ++s) {
      pw_mul(S.a[r][s], S.yh[s], tmp);
      for (int n = 0; n < N; ++n) acc[n] = freeze((int64_t)acc[n] + tmp[n]);
    }
    // ct1*2^d
    int32_t t1s[N];
    for (int n = 0; n < N; ++n) t1s[n] = freeze((int64_t)t1[r][n] << MD);
    dntt(t1s);
    pw_mul(S.ch, t1s, tmp);
    for (int n = 0; n < N; ++n) acc[n] = freeze((int64_t)acc[n] - tmp[n]);
    dntt_inv(acc);
    int32_t w1[N];
    for (int n = 0; n < N; ++n) w1[n] = use_hint(p, S.h[r][n], acc[n]);
    simple_bit_pack(w1, p.w1_bits, w1_enc + r * w1_bytes);
  }
  uint8_t ct2[64];
  {
    Sponge sp(136);
    sp.absorb(mu, 64);
    sp.absorb(w1_enc, (size_t)(p.k * w1_bytes));
    sp.finish(0x1f);
    sp.squeeze(ct2, (size_t)p.ctilde_len);
  }
  return std::memcmp(ctilde, ct2, (size_t)p.ctilde_len) == 0;
}

}  // namespace mldsa

// ---------------------------------------------------------------- SHA-2

namespace sha2 {

const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t ror32(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

void compress256(uint32_t h[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int i = 0; i < 16; ++i)
    w[i] = ((uint32_t)block[4 * i] << 24) | ((uint32_t)block[4 * i + 1] << 16) |
           ((uint32_t)block[4 * i + 2] << 8) | block[4 * i + 3];
  for (int i = 16; i < 64; ++i) {
    uint32_t s0 = ror32(w[i - 15], 7) ^ ror32(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = ror32(w[i - 2], 17) ^ ror32(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5], g = h[6], hh = h[7];
  for (int i = 0; i < 64; ++i) {
    uint32_t S1 = ror32(e, 6) ^ ror32(e, 11) ^ ror32(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = hh + S1 + ch + K256[i] + w[i];
    uint32_t S0 = ror32(a, 2) ^ ror32(a, 13) ^ ror32(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

struct Sha256 {
  uint32_t h[8];
  uint64_t total;
  uint8_t buf[64];
  size_t pos;
  Sha256() { init(); }
  void init() {
    static const uint32_t iv[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    std::memcpy(h, iv, sizeof(h));
    total = 0;
    pos = 0;
  }
  // resume from a precomputed midstate that has already absorbed `absorbed`
  // whole blocks
  void init_from(const uint32_t mid[8], uint64_t absorbed_bytes) {
    std::memcpy(h, mid, sizeof(h));
    total = absorbed_bytes;
    pos = 0;
  }
  void update(const uint8_t* data, size_t len) {
    total += len;
    while (len) {
      size_t take = 64 - pos;
      if (take > len) take = len;
      std::memcpy(buf + pos, data, take);
      pos += take;
      data += take;
      len -= take;
      if (pos == 64) {
        compress256(h, buf);
        pos = 0;
      }
    }
  }
  void final(uint8_t out[32]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (pos != 56) update(&z, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; ++i) lenb[i] = (uint8_t)(bits >> (56 - 8 * i));
    total -= 8;  // length field does not count
    update(lenb, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = (uint8_t)(h[i] >> 24);
      out[4 * i + 1] = (uint8_t)(h[i] >> 16);
      out[4 * i + 2] = (uint8_t)(h[i] >> 8);
      out[4 * i + 3] = (uint8_t)h[i];
    }
  }
};

const uint64_t K512[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

inline uint64_t ror64(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

void compress512(uint64_t h[8], const uint8_t block[128]) {
  uint64_t w[80];
  for (int i = 0; i < 16; ++i) {
    uint64_t v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | block[8 * i + j];
    w[i] = v;
  }
  for (int i = 16; i < 80; ++i) {
    uint64_t s0 = ror64(w[i - 15], 1) ^ ror64(w[i - 15], 8) ^ (w[i - 15] >> 7);
    uint64_t s1 = ror64(w[i - 2], 19) ^ ror64(w[i - 2], 61) ^ (w[i - 2] >> 6);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint64_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5], g = h[6], hh = h[7];
  for (int i = 0; i < 80; ++i) {
    uint64_t S1 = ror64(e, 14) ^ ror64(e, 18) ^ ror64(e, 41);
    uint64_t ch = (e & f) ^ (~e & g);
    uint64_t t1 = hh + S1 + ch + K512[i] + w[i];
    uint64_t S0 = ror64(a, 28) ^ ror64(a, 34) ^ ror64(a, 39);
    uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint64_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

struct Sha512 {
  uint64_t h[8];
  uint64_t total;
  uint8_t buf[128];
  size_t pos;
  Sha512() { init(); }
  void init() {
    static const uint64_t iv[8] = {
        0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
        0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
        0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
    std::memcpy(h, iv, sizeof(h));
    total = 0;
    pos = 0;
  }
  void init_from(const uint64_t mid[8], uint64_t absorbed_bytes) {
    std::memcpy(h, mid, sizeof(h));
    total = absorbed_bytes;
    pos = 0;
  }
  void update(const uint8_t* data, size_t len) {
    total += len;
    while (len) {
      size_t take = 128 - pos;
      if (take > len) take = len;
      std::memcpy(buf + pos, data, take);
      pos += take;
      data += take;
      len -= take;
      if (pos == 128) {
        compress512(h, buf);
        pos = 0;
      }
    }
  }
  void final(uint8_t out[64]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (pos != 112) update(&z, 1);
    uint8_t lenb[16] = {0};  // 128-bit length; high 64 bits zero
    for (int i = 0; i < 8; ++i) lenb[8 + i] = (uint8_t)(bits >> (56 - 8 * i));
    total -= 16;
    update(lenb, 16);
    for (int i = 0; i < 8; ++i)
      for (int j = 0; j < 8; ++j) out[8 * i + j] = (uint8_t)(h[i] >> (56 - 8 * j));
  }
};

void sha256(const uint8_t* in, size_t len, uint8_t out[32]) {
  Sha256 s;
  s.update(in, len);
  s.final(out);
}

void sha512(const uint8_t* in, size_t len, uint8_t out[64]) {
  Sha512 s;
  s.update(in, len);
  s.final(out);
}

// HMAC over either hash (big = SHA-512)
void hmac(bool big, const uint8_t* key, size_t keylen, const uint8_t* msg1,
          size_t len1, const uint8_t* msg2, size_t len2, uint8_t* out) {
  size_t bs = big ? 128 : 64, hs = big ? 64 : 32;
  uint8_t k0[128] = {0}, ipad[128], opad[128], inner[64];
  if (keylen > bs) {
    if (big) sha512(key, keylen, k0);
    else sha256(key, keylen, k0);
  } else {
    std::memcpy(k0, key, keylen);
  }
  for (size_t i = 0; i < bs; ++i) {
    ipad[i] = k0[i] ^ 0x36;
    opad[i] = k0[i] ^ 0x5c;
  }
  if (big) {
    Sha512 s;
    s.update(ipad, bs); s.update(msg1, len1); s.update(msg2, len2);
    s.final(inner);
    Sha512 o;
    o.update(opad, bs); o.update(inner, hs);
    o.final(out);
  } else {
    Sha256 s;
    s.update(ipad, bs); s.update(msg1, len1); s.update(msg2, len2);
    s.final(inner);
    Sha256 o;
    o.update(opad, bs); o.update(inner, hs);
    o.final(out);
  }
  // key material (k0 and its xor-masks are invertible to the key)
  volatile uint8_t* w;
  w = k0;   for (size_t i = 0; i < sizeof(k0); ++i) w[i] = 0;
  w = ipad; for (size_t i = 0; i < sizeof(ipad); ++i) w[i] = 0;
  w = opad; for (size_t i = 0; i < sizeof(opad); ++i) w[i] = 0;
}

}  // namespace sha2

// ---------------------------------------------------------------- SLH-DSA
//
// FIPS 205 SLH-DSA (SPHINCS+-SHA2 'simple'), all six SHA2 parameter sets,
// with deterministic seams matching pyref/slhdsa_ref.py: keygen(sk_seed,
// sk_prf, pk_seed), sign_internal(msg, sk, addrnd), verify_internal.
// Replaces (reference): liboqs SPHINCS+ reached via crypto/signatures.py:
// 191-315.  Speed: the first SHA-2 block (pk_seed || zero padding) is fixed
// per keypair, so F/H/T run from a precomputed midstate — one compression
// per F instead of two.

namespace slhdsa {

struct Params {
  const char* name;
  int n, h, d, hp, a, k, m;
  int wots_len() const { return 2 * n + 3; }
  int pk_len() const { return 2 * n; }
  int sk_len() const { return 4 * n; }
  int sig_len() const {
    return n * (1 + k * (1 + a) + d * (wots_len() + hp));
  }
  bool big() const { return n > 16; }
};

// ids: 0=128s 1=128f 2=192s 3=192f 4=256s 5=256f
const Params PARAMS[6] = {
    {"SPHINCS+-SHA2-128s-simple", 16, 63, 7, 9, 12, 14, 30},
    {"SPHINCS+-SHA2-128f-simple", 16, 66, 22, 3, 6, 33, 34},
    {"SPHINCS+-SHA2-192s-simple", 24, 63, 7, 9, 14, 17, 39},
    {"SPHINCS+-SHA2-192f-simple", 24, 66, 22, 3, 8, 33, 42},
    {"SPHINCS+-SHA2-256s-simple", 32, 64, 8, 8, 14, 22, 47},
    {"SPHINCS+-SHA2-256f-simple", 32, 68, 17, 4, 9, 35, 49},
};

enum AdrsType { WOTS_HASH, WOTS_PK, TREE, FORS_TREE, FORS_ROOTS, WOTS_PRF, FORS_PRF };

struct ADRS {
  uint8_t layer = 0;
  uint64_t tree = 0;
  uint8_t type = 0;
  uint32_t w1 = 0, w2 = 0, w3 = 0;
  void set_type_and_clear(uint8_t t) {
    type = t;
    w1 = w2 = w3 = 0;
  }
  void compressed(uint8_t out[22]) const {
    out[0] = layer;
    for (int i = 0; i < 8; ++i) out[1 + i] = (uint8_t)(tree >> (56 - 8 * i));
    out[9] = type;
    for (int i = 0; i < 4; ++i) out[10 + i] = (uint8_t)(w1 >> (24 - 8 * i));
    for (int i = 0; i < 4; ++i) out[14 + i] = (uint8_t)(w2 >> (24 - 8 * i));
    for (int i = 0; i < 4; ++i) out[18 + i] = (uint8_t)(w3 >> (24 - 8 * i));
  }
};

// Per-keypair hash engine: pk_seed midstates precomputed once.
struct Ctx {
  const Params& p;
  uint32_t mid256[8];   // SHA-256 state after (pk_seed || 0^(64-n))
  uint64_t mid512[8];   // SHA-512 state after (pk_seed || 0^(128-n)) [big only]
  const uint8_t* sk_seed;  // may be null for verify

  Ctx(const Params& pp, const uint8_t* pk_seed, const uint8_t* sks)
      : p(pp), sk_seed(sks) {
    uint8_t blk[128] = {0};
    std::memcpy(blk, pk_seed, (size_t)p.n);
    sha2::Sha256 s;
    sha2::compress256(s.h, blk);
    std::memcpy(mid256, s.h, sizeof(mid256));
    if (p.big()) {
      sha2::Sha512 s5;
      sha2::compress512(s5.h, blk);
      std::memcpy(mid512, s5.h, sizeof(mid512));
    }
  }

  // F (always SHA-256): out = SHA256(pk_seed || pad || adrs || m)[:n]
  void F(const ADRS& adrs, const uint8_t* m, size_t mlen, uint8_t* out) const {
    uint8_t a22[22], dig[32];
    adrs.compressed(a22);
    sha2::Sha256 s;
    s.init_from(mid256, 64);
    s.update(a22, 22);
    s.update(m, mlen);
    s.final(dig);
    std::memcpy(out, dig, (size_t)p.n);
  }

  // H / T_l: SHA-256 (cat 1) or SHA-512 (cats 3/5)
  void T(const ADRS& adrs, const uint8_t* m, size_t mlen, uint8_t* out) const {
    if (!p.big()) {
      F(adrs, m, mlen, out);
      return;
    }
    uint8_t a22[22], dig[64];
    adrs.compressed(a22);
    sha2::Sha512 s;
    s.init_from(mid512, 128);
    s.update(a22, 22);
    s.update(m, mlen);
    s.final(dig);
    std::memcpy(out, dig, (size_t)p.n);
  }
};

// -- WOTS+ -------------------------------------------------------------------

void wots_digits(const Params& p, const uint8_t* m, int* digits) {
  int len1 = 2 * p.n;
  int csum = 0;
  for (int i = 0; i < p.n; ++i) {
    digits[2 * i] = m[i] >> 4;
    digits[2 * i + 1] = m[i] & 0xf;
  }
  for (int i = 0; i < len1; ++i) csum += 15 - digits[i];
  csum <<= 4;  // left-align to a whole number of nibbles (len2*lg_w = 12 bits)
  digits[len1] = (csum >> 12) & 0xf;  // first 3 nibbles of csum as 2 BE bytes
  digits[len1 + 1] = (csum >> 8) & 0xf;
  digits[len1 + 2] = (csum >> 4) & 0xf;
}

void chain(const Ctx& c, uint8_t* x, int i, int s, ADRS& adrs) {
  for (int j = i; j < i + s; ++j) {
    adrs.w3 = (uint32_t)j;
    c.F(adrs, x, (size_t)c.p.n, x);
  }
}

void wots_pkgen(const Ctx& c, ADRS adrs, uint8_t* out) {
  const Params& p = c.p;
  ADRS sk_adrs = adrs;
  sk_adrs.set_type_and_clear(WOTS_PRF);
  sk_adrs.w1 = adrs.w1;
  uint8_t tmp[67 * 32];
  for (int i = 0; i < p.wots_len(); ++i) {
    sk_adrs.w2 = (uint32_t)i;
    uint8_t* xi = tmp + i * p.n;
    c.F(sk_adrs, c.sk_seed, (size_t)p.n, xi);
    adrs.w2 = (uint32_t)i;
    adrs.w3 = 0;
    chain(c, xi, 0, 15, adrs);
  }
  ADRS pk_adrs = adrs;
  pk_adrs.set_type_and_clear(WOTS_PK);
  pk_adrs.w1 = adrs.w1;
  c.T(pk_adrs, tmp, (size_t)(p.wots_len() * p.n), out);
}

void wots_sign(const Ctx& c, const uint8_t* m, ADRS adrs, uint8_t* sig) {
  const Params& p = c.p;
  int digits[67];
  wots_digits(p, m, digits);
  ADRS sk_adrs = adrs;
  sk_adrs.set_type_and_clear(WOTS_PRF);
  sk_adrs.w1 = adrs.w1;
  for (int i = 0; i < p.wots_len(); ++i) {
    sk_adrs.w2 = (uint32_t)i;
    uint8_t* si = sig + i * p.n;
    c.F(sk_adrs, c.sk_seed, (size_t)p.n, si);
    adrs.w2 = (uint32_t)i;
    adrs.w3 = 0;
    chain(c, si, 0, digits[i], adrs);
  }
}

void wots_pk_from_sig(const Ctx& c, const uint8_t* sig, const uint8_t* m,
                      ADRS adrs, uint8_t* out) {
  const Params& p = c.p;
  int digits[67];
  wots_digits(p, m, digits);
  uint8_t tmp[67 * 32];
  for (int i = 0; i < p.wots_len(); ++i) {
    adrs.w2 = (uint32_t)i;
    uint8_t* xi = tmp + i * p.n;
    std::memcpy(xi, sig + i * p.n, (size_t)p.n);
    chain(c, xi, digits[i], 15 - digits[i], adrs);
  }
  ADRS pk_adrs = adrs;
  pk_adrs.set_type_and_clear(WOTS_PK);
  pk_adrs.w1 = adrs.w1;
  c.T(pk_adrs, tmp, (size_t)(p.wots_len() * p.n), out);
}

// -- XMSS ---------------------------------------------------------------------

void xmss_node(const Ctx& c, uint32_t i, int z, ADRS adrs, uint8_t* out) {
  const Params& p = c.p;
  if (z == 0) {
    adrs.set_type_and_clear(WOTS_HASH);
    adrs.w1 = i;
    wots_pkgen(c, adrs, out);
    return;
  }
  uint8_t ln[32], rn[32];
  xmss_node(c, 2 * i, z - 1, adrs, ln);
  xmss_node(c, 2 * i + 1, z - 1, adrs, rn);
  adrs.set_type_and_clear(TREE);
  adrs.w2 = (uint32_t)z;
  adrs.w3 = i;
  uint8_t both[64];
  std::memcpy(both, ln, (size_t)p.n);
  std::memcpy(both + p.n, rn, (size_t)p.n);
  c.T(adrs, both, (size_t)(2 * p.n), out);
}

void xmss_sign(const Ctx& c, const uint8_t* m, uint32_t idx, ADRS adrs, uint8_t* sig) {
  const Params& p = c.p;
  uint8_t* auth = sig + p.wots_len() * p.n;
  for (int j = 0; j < p.hp; ++j) {
    uint32_t k = (idx >> j) ^ 1u;
    xmss_node(c, k, j, adrs, auth + j * p.n);
  }
  adrs.set_type_and_clear(WOTS_HASH);
  adrs.w1 = idx;
  wots_sign(c, m, adrs, sig);
}

void xmss_pk_from_sig(const Ctx& c, uint32_t idx, const uint8_t* sig_xmss,
                      const uint8_t* m, ADRS adrs, uint8_t* out) {
  const Params& p = c.p;
  const uint8_t* auth = sig_xmss + p.wots_len() * p.n;
  ADRS wadrs = adrs;
  wadrs.set_type_and_clear(WOTS_HASH);
  wadrs.w1 = idx;
  uint8_t node[32];
  wots_pk_from_sig(c, sig_xmss, m, wadrs, node);
  ADRS tadrs = adrs;
  tadrs.set_type_and_clear(TREE);
  tadrs.w3 = idx;
  uint8_t both[64];
  for (int k = 0; k < p.hp; ++k) {
    tadrs.w2 = (uint32_t)(k + 1);
    const uint8_t* sib = auth + k * p.n;
    if ((idx >> k) & 1) {
      tadrs.w3 = (tadrs.w3 - 1) >> 1;
      std::memcpy(both, sib, (size_t)p.n);
      std::memcpy(both + p.n, node, (size_t)p.n);
    } else {
      tadrs.w3 = tadrs.w3 >> 1;
      std::memcpy(both, node, (size_t)p.n);
      std::memcpy(both + p.n, sib, (size_t)p.n);
    }
    c.T(tadrs, both, (size_t)(2 * p.n), node);
  }
  std::memcpy(out, node, (size_t)p.n);
}

// -- Hypertree -----------------------------------------------------------------

ADRS adrs_for(uint64_t tree, int layer) {
  ADRS a;
  a.tree = tree;
  a.layer = (uint8_t)layer;
  return a;
}

void ht_sign(const Ctx& c, const uint8_t* m, uint64_t idx_tree, uint32_t idx_leaf,
             uint8_t* sig) {
  const Params& p = c.p;
  int per = (p.wots_len() + p.hp) * p.n;
  ADRS adrs = adrs_for(idx_tree, 0);
  xmss_sign(c, m, idx_leaf, adrs, sig);
  uint8_t root[32];
  xmss_pk_from_sig(c, idx_leaf, sig, m, adrs_for(idx_tree, 0), root);
  for (int j = 1; j < p.d; ++j) {
    idx_leaf = (uint32_t)(idx_tree & ((1ULL << p.hp) - 1));
    idx_tree >>= p.hp;
    uint8_t* sig_j = sig + j * per;
    xmss_sign(c, root, idx_leaf, adrs_for(idx_tree, j), sig_j);
    if (j < p.d - 1)
      xmss_pk_from_sig(c, idx_leaf, sig_j, root, adrs_for(idx_tree, j), root);
  }
}

bool ht_verify(const Ctx& c, const uint8_t* m, const uint8_t* sig_ht,
               uint64_t idx_tree, uint32_t idx_leaf, const uint8_t* pk_root) {
  const Params& p = c.p;
  int per = (p.wots_len() + p.hp) * p.n;
  uint8_t node[32];
  xmss_pk_from_sig(c, idx_leaf, sig_ht, m, adrs_for(idx_tree, 0), node);
  for (int j = 1; j < p.d; ++j) {
    idx_leaf = (uint32_t)(idx_tree & ((1ULL << p.hp) - 1));
    idx_tree >>= p.hp;
    xmss_pk_from_sig(c, idx_leaf, sig_ht + j * per, node, adrs_for(idx_tree, j), node);
  }
  return std::memcmp(node, pk_root, (size_t)p.n) == 0;
}

// -- FORS -----------------------------------------------------------------------

void fors_sk(const Ctx& c, const ADRS& adrs, uint32_t idx, uint8_t* out) {
  ADRS sk_adrs = adrs;
  sk_adrs.set_type_and_clear(FORS_PRF);
  sk_adrs.w1 = adrs.w1;
  sk_adrs.w3 = idx;
  c.F(sk_adrs, c.sk_seed, (size_t)c.p.n, out);
}

void fors_node(const Ctx& c, uint32_t i, int z, ADRS adrs, uint8_t* out) {
  const Params& p = c.p;
  if (z == 0) {
    uint8_t sk[32];
    fors_sk(c, adrs, i, sk);
    adrs.w2 = 0;
    adrs.w3 = i;
    c.F(adrs, sk, (size_t)p.n, out);
    // unrevealed FORS leaf secrets must not linger (revealed ones are in
    // the signature by design)
    for (volatile uint8_t* w = sk; w < sk + sizeof(sk); ++w) *w = 0;
    return;
  }
  uint8_t ln[32], rn[32];
  fors_node(c, 2 * i, z - 1, adrs, ln);
  fors_node(c, 2 * i + 1, z - 1, adrs, rn);
  adrs.w2 = (uint32_t)z;
  adrs.w3 = i;
  uint8_t both[64];
  std::memcpy(both, ln, (size_t)p.n);
  std::memcpy(both + p.n, rn, (size_t)p.n);
  c.T(adrs, both, (size_t)(2 * p.n), out);
}

void msg_indices(const Params& p, const uint8_t* md, uint32_t* out) {
  int bits = 0, pos = 0;
  uint64_t acc = 0;
  for (int i = 0; i < p.k; ++i) {
    while (bits < p.a) {
      acc = (acc << 8) | md[pos++];
      bits += 8;
    }
    bits -= p.a;
    out[i] = (uint32_t)((acc >> bits) & ((1ULL << p.a) - 1));
    acc &= (1ULL << bits) - 1;
  }
}

void fors_sign(const Ctx& c, const uint8_t* md, const ADRS& adrs, uint8_t* sig) {
  const Params& p = c.p;
  uint32_t indices[35];
  msg_indices(p, md, indices);
  uint8_t* out = sig;
  for (int i = 0; i < p.k; ++i) {
    fors_sk(c, adrs, ((uint32_t)i << p.a) + indices[i], out);
    out += p.n;
    for (int j = 0; j < p.a; ++j) {
      uint32_t s = (indices[i] >> j) ^ 1u;
      fors_node(c, ((uint32_t)i << (p.a - j)) + s, j, adrs, out);
      out += p.n;
    }
  }
}

void fors_pk_from_sig(const Ctx& c, const uint8_t* sig, const uint8_t* md,
                      ADRS adrs, uint8_t* out) {
  const Params& p = c.p;
  uint32_t indices[35];
  msg_indices(p, md, indices);
  int per = (1 + p.a) * p.n;
  uint8_t roots[35 * 32];
  uint8_t both[64];
  for (int i = 0; i < p.k; ++i) {
    const uint8_t* sk = sig + i * per;
    const uint8_t* auth = sk + p.n;
    adrs.w2 = 0;
    uint32_t tree_idx = ((uint32_t)i << p.a) + indices[i];
    adrs.w3 = tree_idx;
    uint8_t node[32];
    c.F(adrs, sk, (size_t)p.n, node);
    for (int j = 0; j < p.a; ++j) {
      const uint8_t* sib = auth + j * p.n;
      adrs.w2 = (uint32_t)(j + 1);
      if ((tree_idx >> j) & 1) {
        adrs.w3 = (((uint32_t)i << (p.a - j)) + (indices[i] >> j) - 1) >> 1;
        std::memcpy(both, sib, (size_t)p.n);
        std::memcpy(both + p.n, node, (size_t)p.n);
      } else {
        adrs.w3 = (((uint32_t)i << (p.a - j)) + (indices[i] >> j)) >> 1;
        std::memcpy(both, node, (size_t)p.n);
        std::memcpy(both + p.n, sib, (size_t)p.n);
      }
      c.T(adrs, both, (size_t)(2 * p.n), node);
    }
    std::memcpy(roots + i * p.n, node, (size_t)p.n);
  }
  ADRS pk_adrs = adrs;
  pk_adrs.set_type_and_clear(FORS_ROOTS);
  pk_adrs.w1 = adrs.w1;
  c.T(pk_adrs, roots, (size_t)(p.k * p.n), out);
}

// -- message hashing / top level ----------------------------------------------

void mgf1(bool big, const uint8_t* seed, size_t seedlen, uint8_t* out, int outlen) {
  int hlen = big ? 64 : 32;
  uint8_t dig[64];
  int pos = 0;
  for (uint32_t ctr = 0; pos < outlen; ++ctr) {
    uint8_t cb[4] = {(uint8_t)(ctr >> 24), (uint8_t)(ctr >> 16),
                     (uint8_t)(ctr >> 8), (uint8_t)ctr};
    if (big) {
      sha2::Sha512 s;
      s.update(seed, seedlen);
      s.update(cb, 4);
      s.final(dig);
    } else {
      sha2::Sha256 s;
      s.update(seed, seedlen);
      s.update(cb, 4);
      s.final(dig);
    }
    int take = outlen - pos < hlen ? outlen - pos : hlen;
    std::memcpy(out + pos, dig, (size_t)take);
    pos += take;
  }
}

void h_msg(const Params& p, const uint8_t* r, const uint8_t* pk_seed,
           const uint8_t* pk_root, const uint8_t* msg, size_t msglen,
           uint8_t* out) {
  uint8_t inner[64];
  size_t hs = p.big() ? 64 : 32;
  if (p.big()) {
    sha2::Sha512 s;
    s.update(r, (size_t)p.n); s.update(pk_seed, (size_t)p.n);
    s.update(pk_root, (size_t)p.n); s.update(msg, msglen);
    s.final(inner);
  } else {
    sha2::Sha256 s;
    s.update(r, (size_t)p.n); s.update(pk_seed, (size_t)p.n);
    s.update(pk_root, (size_t)p.n); s.update(msg, msglen);
    s.final(inner);
  }
  uint8_t seed[32 + 32 + 64];
  std::memcpy(seed, r, (size_t)p.n);
  std::memcpy(seed + p.n, pk_seed, (size_t)p.n);
  std::memcpy(seed + 2 * p.n, inner, hs);
  mgf1(p.big(), seed, (size_t)(2 * p.n) + hs, out, p.m);
}

void split_digest(const Params& p, const uint8_t* digest, const uint8_t** md,
                  uint64_t* idx_tree, uint32_t* idx_leaf) {
  int ka = (p.k * p.a + 7) / 8;
  int t = (p.h - p.hp + 7) / 8;
  int u = (p.hp + 7) / 8;
  *md = digest;
  uint64_t it = 0;
  for (int i = 0; i < t; ++i) it = (it << 8) | digest[ka + i];
  // h - hp can be 64 (256s: h=64, hp=8 -> 56; 128s: 63-9=54; all < 64 except
  // none); mask safely
  int bits = p.h - p.hp;
  *idx_tree = bits >= 64 ? it : (it & ((1ULL << bits) - 1));
  uint64_t il = 0;
  for (int i = 0; i < u; ++i) il = (il << 8) | digest[ka + t + i];
  *idx_leaf = (uint32_t)(il & ((1ULL << p.hp) - 1));
}

void keygen(const Params& p, const uint8_t* sk_seed, const uint8_t* sk_prf,
            const uint8_t* pk_seed, uint8_t* pk, uint8_t* sk) {
  Ctx c(p, pk_seed, sk_seed);
  ADRS adrs;
  adrs.layer = (uint8_t)(p.d - 1);
  uint8_t root[32];
  xmss_node(c, 0, p.hp, adrs, root);
  std::memcpy(pk, pk_seed, (size_t)p.n);
  std::memcpy(pk + p.n, root, (size_t)p.n);
  std::memcpy(sk, sk_seed, (size_t)p.n);
  std::memcpy(sk + p.n, sk_prf, (size_t)p.n);
  std::memcpy(sk + 2 * p.n, pk, (size_t)(2 * p.n));
}

void sign_internal(const Params& p, const uint8_t* msg, size_t msglen,
                   const uint8_t* sk, const uint8_t* addrnd, uint8_t* sig) {
  const uint8_t* sk_seed = sk;
  const uint8_t* sk_prf = sk + p.n;
  const uint8_t* pk_seed = sk + 2 * p.n;
  const uint8_t* pk_root = sk + 3 * p.n;
  const uint8_t* opt_rand = addrnd ? addrnd : pk_seed;
  // R = PRF_msg = HMAC(sk_prf, opt_rand || msg)
  uint8_t rfull[64];
  sha2::hmac(p.big(), sk_prf, (size_t)p.n, opt_rand, (size_t)p.n, msg, msglen, rfull);
  uint8_t* r = sig;
  std::memcpy(r, rfull, (size_t)p.n);
  uint8_t digest[49];
  h_msg(p, r, pk_seed, pk_root, msg, msglen, digest);
  const uint8_t* md;
  uint64_t idx_tree;
  uint32_t idx_leaf;
  split_digest(p, digest, &md, &idx_tree, &idx_leaf);
  Ctx c(p, pk_seed, sk_seed);
  ADRS adrs;
  adrs.tree = idx_tree;
  adrs.set_type_and_clear(FORS_TREE);
  adrs.w1 = idx_leaf;
  uint8_t* sig_fors = sig + p.n;
  fors_sign(c, md, adrs, sig_fors);
  uint8_t pk_fors[32];
  ADRS fadrs;
  fadrs.tree = idx_tree;
  fadrs.set_type_and_clear(FORS_TREE);
  fadrs.w1 = idx_leaf;
  fors_pk_from_sig(c, sig_fors, md, fadrs, pk_fors);
  uint8_t* sig_ht = sig_fors + p.k * (1 + p.a) * p.n;
  ht_sign(c, pk_fors, idx_tree, idx_leaf, sig_ht);
}

bool verify_internal(const Params& p, const uint8_t* msg, size_t msglen,
                     const uint8_t* sig, const uint8_t* pk) {
  const uint8_t* pk_seed = pk;
  const uint8_t* pk_root = pk + p.n;
  const uint8_t* r = sig;
  const uint8_t* sig_fors = sig + p.n;
  const uint8_t* sig_ht = sig_fors + p.k * (1 + p.a) * p.n;
  uint8_t digest[49];
  h_msg(p, r, pk_seed, pk_root, msg, msglen, digest);
  const uint8_t* md;
  uint64_t idx_tree;
  uint32_t idx_leaf;
  split_digest(p, digest, &md, &idx_tree, &idx_leaf);
  Ctx c(p, pk_seed, nullptr);
  uint8_t pk_fors[32];
  ADRS fadrs;
  fadrs.tree = idx_tree;
  fadrs.set_type_and_clear(FORS_TREE);
  fadrs.w1 = idx_leaf;
  fors_pk_from_sig(c, sig_fors, md, fadrs, pk_fors);
  return ht_verify(c, pk_fors, sig_ht, idx_tree, idx_leaf, pk_root);
}

}  // namespace slhdsa

// ---------------------------------------------------------------- AES-128

namespace aes {

uint8_t SBOX[256];
uint32_t T0[256], T1[256], T2[256], T3[256];

inline uint8_t xtime(uint8_t x) { return (uint8_t)((x << 1) ^ ((x >> 7) * 0x1b)); }

struct AesInit {
  AesInit() {
    // S-box from GF(2^8) inverse + affine map (computed, not transcribed)
    uint8_t expt[256], logt[256];
    uint8_t x = 1;
    for (int i = 0; i < 255; ++i) {
      expt[i] = x;
      logt[x] = (uint8_t)i;
      x = (uint8_t)(x ^ xtime(x));  // multiply by 3 (generator)
    }
    for (int v = 0; v < 256; ++v) {
      // exp has period 255: index (255 - log) % 255 (v=1 has log 0 -> inv 1)
      uint8_t inv = v ? expt[(255 - logt[v]) % 255] : 0;
      uint8_t r = 0x63;
      for (int sh = 0; sh < 5; ++sh)
        r ^= (uint8_t)((inv << sh) | (inv >> (8 - sh)));
      SBOX[v] = r;
    }
    for (int v = 0; v < 256; ++v) {
      uint8_t s = SBOX[v];
      uint8_t s2 = xtime(s), s3 = (uint8_t)(s2 ^ s);
      // column (2s, s, s, 3s) little-endian word
      T0[v] = (uint32_t)s2 | ((uint32_t)s << 8) | ((uint32_t)s << 16) | ((uint32_t)s3 << 24);
      T1[v] = (T0[v] << 8) | (T0[v] >> 24);
      T2[v] = (T0[v] << 16) | (T0[v] >> 16);
      T3[v] = (T0[v] << 24) | (T0[v] >> 8);
    }
  }
} aes_init;

struct Aes128 {
  uint32_t rk[44];
  explicit Aes128(const uint8_t key[16]) {
    for (int i = 0; i < 4; ++i)
      rk[i] = (uint32_t)key[4 * i] | ((uint32_t)key[4 * i + 1] << 8) |
              ((uint32_t)key[4 * i + 2] << 16) | ((uint32_t)key[4 * i + 3] << 24);
    uint8_t rcon = 1;
    for (int i = 4; i < 44; ++i) {
      uint32_t t = rk[i - 1];
      if (i % 4 == 0) {
        t = (t >> 8) | (t << 24);  // RotWord on LE layout
        t = (uint32_t)SBOX[t & 0xff] | ((uint32_t)SBOX[(t >> 8) & 0xff] << 8) |
            ((uint32_t)SBOX[(t >> 16) & 0xff] << 16) |
            ((uint32_t)SBOX[(t >> 24) & 0xff] << 24);
        t ^= rcon;
        rcon = xtime(rcon);
      }
      rk[i] = rk[i - 4] ^ t;
    }
  }
  void encrypt_block(const uint8_t in[16], uint8_t out[16]) const {
    uint32_t s0, s1, s2, s3, t0, t1, t2, t3;
    s0 = ((uint32_t)in[0] | ((uint32_t)in[1] << 8) | ((uint32_t)in[2] << 16) |
          ((uint32_t)in[3] << 24)) ^ rk[0];
    s1 = ((uint32_t)in[4] | ((uint32_t)in[5] << 8) | ((uint32_t)in[6] << 16) |
          ((uint32_t)in[7] << 24)) ^ rk[1];
    s2 = ((uint32_t)in[8] | ((uint32_t)in[9] << 8) | ((uint32_t)in[10] << 16) |
          ((uint32_t)in[11] << 24)) ^ rk[2];
    s3 = ((uint32_t)in[12] | ((uint32_t)in[13] << 8) | ((uint32_t)in[14] << 16) |
          ((uint32_t)in[15] << 24)) ^ rk[3];
    for (int r = 1; r < 10; ++r) {
      t0 = T0[s0 & 0xff] ^ T1[(s1 >> 8) & 0xff] ^ T2[(s2 >> 16) & 0xff] ^
           T3[(s3 >> 24) & 0xff] ^ rk[4 * r];
      t1 = T0[s1 & 0xff] ^ T1[(s2 >> 8) & 0xff] ^ T2[(s3 >> 16) & 0xff] ^
           T3[(s0 >> 24) & 0xff] ^ rk[4 * r + 1];
      t2 = T0[s2 & 0xff] ^ T1[(s3 >> 8) & 0xff] ^ T2[(s0 >> 16) & 0xff] ^
           T3[(s1 >> 24) & 0xff] ^ rk[4 * r + 2];
      t3 = T0[s3 & 0xff] ^ T1[(s0 >> 8) & 0xff] ^ T2[(s1 >> 16) & 0xff] ^
           T3[(s2 >> 24) & 0xff] ^ rk[4 * r + 3];
      s0 = t0; s1 = t1; s2 = t2; s3 = t3;
    }
    // final round (no MixColumns)
    uint8_t tmp[16];
    const uint32_t st[4] = {s0, s1, s2, s3};
    for (int c = 0; c < 4; ++c)
      for (int b = 0; b < 4; ++b)
        tmp[4 * c + b] = SBOX[(st[(c + b) % 4] >> (8 * b)) & 0xff];
    for (int c = 0; c < 4; ++c) {
      uint32_t w = (uint32_t)tmp[4 * c] | ((uint32_t)tmp[4 * c + 1] << 8) |
                   ((uint32_t)tmp[4 * c + 2] << 16) | ((uint32_t)tmp[4 * c + 3] << 24);
      w ^= rk[40 + c];
      out[4 * c] = (uint8_t)w;
      out[4 * c + 1] = (uint8_t)(w >> 8);
      out[4 * c + 2] = (uint8_t)(w >> 16);
      out[4 * c + 3] = (uint8_t)(w >> 24);
    }
  }
};

}  // namespace aes

// ---------------------------------------------------------------- FrodoKEM

namespace frodo {

constexpr int NBAR = 8;
// capacity bounds for the static thread_local buffers; a new parameter set
// exceeding these must raise them (runtime-checked in the extern entries)
constexpr int FRODO_MAX_N = 1344;
constexpr int FRODO_MAX_CT = 21632;

struct Params {
  const char* name;
  int n, d, b, len_sec;
  bool aes;
  const uint16_t* cdf;
  int cdf_len;
  int q_mask() const { return (1 << d) - 1; }
  int pk_len() const { return 16 + n * NBAR * d / 8; }
  int sk_len() const { return len_sec + pk_len() + 2 * n * NBAR + len_sec; }
  int ct_len() const { return (NBAR * n + NBAR * NBAR) * d / 8; }
  unsigned shake_rate() const { return n == 640 ? 168u : 136u; }
};

const uint16_t CDF640[] = {4643, 13363, 20579, 25843, 29227, 31145, 32103,
                           32525, 32689, 32745, 32762, 32766, 32767};
const uint16_t CDF976[] = {5638, 15915, 23689, 28571, 31116, 32217, 32613,
                           32731, 32760, 32766, 32767};
const uint16_t CDF1344[] = {9142, 23462, 30338, 32361, 32725, 32765, 32767};

// ids: 0=640-AES 1=640-SHAKE 2=976-AES 3=976-SHAKE 4=1344-AES 5=1344-SHAKE
const Params FPARAMS[6] = {
    {"FrodoKEM-640-AES", 640, 15, 2, 16, true, CDF640, 13},
    {"FrodoKEM-640-SHAKE", 640, 15, 2, 16, false, CDF640, 13},
    {"FrodoKEM-976-AES", 976, 16, 3, 24, true, CDF976, 11},
    {"FrodoKEM-976-SHAKE", 976, 16, 3, 24, false, CDF976, 11},
    {"FrodoKEM-1344-AES", 1344, 16, 4, 32, true, CDF1344, 7},
    {"FrodoKEM-1344-SHAKE", 1344, 16, 4, 32, false, CDF1344, 7},
};

void fshake(const Params& p, const uint8_t* in, size_t inlen, uint8_t* out,
            size_t outlen) {
  shake(p.shake_rate(), in, inlen, out, outlen);
}

// one row of A into row[n] (streamed — A is never materialised)
struct RowGen {
  const Params& p;
  const aes::Aes128* cipher;  // AES variants
  const uint8_t* seed_a;      // SHAKE variants
  RowGen(const Params& pp, const aes::Aes128* c, const uint8_t* sa)
      : p(pp), cipher(c), seed_a(sa) {}
  void row(int i, uint16_t* out) const {
    if (p.aes) {
      uint8_t blk[16] = {0}, ct[16];
      blk[0] = (uint8_t)(i & 0xff);
      blk[1] = (uint8_t)(i >> 8);
      for (int j = 0; j < p.n; j += 8) {
        blk[2] = (uint8_t)(j & 0xff);
        blk[3] = (uint8_t)(j >> 8);
        cipher->encrypt_block(blk, ct);
        for (int k = 0; k < 8; ++k)
          out[j + k] = (uint16_t)((ct[2 * k] | (ct[2 * k + 1] << 8)) & p.q_mask());
      }
    } else {
      uint8_t in[18];
      in[0] = (uint8_t)(i & 0xff);
      in[1] = (uint8_t)(i >> 8);
      std::memcpy(in + 2, seed_a, 16);
      static thread_local uint8_t buf[2 * FRODO_MAX_N];
      shake(168, in, 18, buf, (size_t)(2 * p.n));  // SHAKE-128 per spec GenA
      for (int j = 0; j < p.n; ++j)
        out[j] = (uint16_t)((buf[2 * j] | (buf[2 * j + 1] << 8)) & p.q_mask());
    }
  }
};

int16_t fsample(const Params& p, uint16_t r16) {
  // branch-free CDF inversion: the sampled noise is secret, so neither the
  // comparison count nor the sign selection may branch on it
  uint16_t t = (uint16_t)(r16 >> 1);
  uint16_t e = 0;
  for (int z = 0; z < p.cdf_len - 1; ++z)
    e = (uint16_t)(e + ((uint16_t)(p.cdf[z] - t) >> 15));  // 1 iff t > cdf[z]
  uint16_t sign = (uint16_t)(0 - (r16 & 1));  // 0x0000 or 0xffff
  return (int16_t)((e ^ sign) + (r16 & 1));
}

void sample_matrix(const Params& p, const uint8_t* rbytes, int count, int16_t* out) {
  for (int k = 0; k < count; ++k)
    out[k] = fsample(p, (uint16_t)(rbytes[2 * k] | (rbytes[2 * k + 1] << 8)));
}

// D-bit big-endian bit packing (spec Algorithms 3-4)
void fpack(const Params& p, const uint16_t* vals, int count, uint8_t* out) {
  uint32_t acc = 0;
  int bits = 0, pos = 0;
  for (int k = 0; k < count; ++k) {
    acc = (acc << p.d) | (uint32_t)(vals[k] & p.q_mask());
    bits += p.d;
    while (bits >= 8) {
      bits -= 8;
      out[pos++] = (uint8_t)((acc >> bits) & 0xff);
    }
  }
}

void funpack(const Params& p, const uint8_t* data, int count, uint16_t* out) {
  uint32_t acc = 0;
  int bits = 0, pos = 0;
  for (int k = 0; k < count; ++k) {
    while (bits < p.d) {
      acc = (acc << 8) | data[pos++];
      bits += 8;
    }
    bits -= p.d;
    out[k] = (uint16_t)((acc >> bits) & p.q_mask());
    acc &= (1u << bits) - 1;
  }
}

void fencode(const Params& p, const uint8_t* mu, uint16_t* out) {
  int step_shift = p.d - p.b;
  for (int k = 0; k < NBAR * NBAR; ++k) {
    uint16_t v = 0;
    for (int l = 0; l < p.b; ++l) {
      int bit = k * p.b + l;
      v |= (uint16_t)(((mu[bit >> 3] >> (bit & 7)) & 1) << l);
    }
    out[k] = (uint16_t)(v << step_shift);
  }
}

void fdecode(const Params& p, const uint16_t* m, uint8_t* out) {
  std::memset(out, 0, (size_t)(NBAR * NBAR * p.b / 8));
  for (int k = 0; k < NBAR * NBAR; ++k) {
    uint16_t val = (uint16_t)((((uint32_t)(m[k] & p.q_mask()) << p.b) + (1u << (p.d - 1))) >> p.d);
    val &= (uint16_t)((1 << p.b) - 1);
    for (int l = 0; l < p.b; ++l) {
      int bit = k * p.b + l;
      out[bit >> 3] |= (uint8_t)(((val >> l) & 1) << (bit & 7));
    }
  }
}

// B' = S'(8 x n) @ A + E' and V-side products, streaming A row by row.
// sp/ep row-major 8 x n; out row-major 8 x n.
void sa_plus_e(const Params& p, const RowGen& gen, const int16_t* sp,
               const int16_t* ep, uint16_t* out) {
  static thread_local uint16_t arow[FRODO_MAX_N];
  for (int i = 0; i < NBAR; ++i)
    for (int j = 0; j < p.n; ++j) out[i * p.n + j] = (uint16_t)ep[i * p.n + j];
  for (int k = 0; k < p.n; ++k) {
    gen.row(k, arow);
    for (int i = 0; i < NBAR; ++i) {
      // no skip for s == 0: the noise coefficients are secret, and in the FO
      // re-encryption path a data-dependent row skip is a timing signal
      int16_t s = sp[i * p.n + k];
      uint16_t* o = out + i * p.n;
      for (int j = 0; j < p.n; ++j)
        o[j] = (uint16_t)(o[j] + s * (int16_t)arow[j]);  // mod 2^16, masked later
    }
  }
  for (int k = 0; k < NBAR * p.n; ++k) out[k] &= (uint16_t)p.q_mask();
}

// B = A @ S + E, streaming A rows; st row-major NBAR x n (S^T), e n x NBAR.
void as_plus_e(const Params& p, const RowGen& gen, const int16_t* st,
               const int16_t* e, uint16_t* out) {
  static thread_local uint16_t arow[FRODO_MAX_N];
  for (int i = 0; i < p.n; ++i) {
    gen.row(i, arow);
    for (int j = 0; j < NBAR; ++j) {
      uint32_t acc = 0;
      const int16_t* srow = st + j * p.n;  // column j of S = row j of S^T
      for (int k = 0; k < p.n; ++k) acc += (uint32_t)((int32_t)arow[k] * srow[k]);
      out[i * NBAR + j] = (uint16_t)((acc + (uint32_t)e[i * NBAR + j]) & (uint32_t)p.q_mask());
    }
  }
}

void keygen(const Params& p, const uint8_t* s, const uint8_t* seed_se,
            const uint8_t* z, uint8_t* pk, uint8_t* sk) {
  uint8_t seed_a[16];
  fshake(p, z, (size_t)p.len_sec, seed_a, 16);
  aes::Aes128 cipher(seed_a);
  RowGen gen(p, p.aes ? &cipher : nullptr, seed_a);

  static thread_local uint8_t r[4 * FRODO_MAX_N * NBAR];
  uint8_t pre[1 + 32];
  pre[0] = 0x5f;
  std::memcpy(pre + 1, seed_se, (size_t)p.len_sec);
  fshake(p, pre, (size_t)(1 + p.len_sec), r, (size_t)(4 * p.n * NBAR));
  static thread_local int16_t st[NBAR * FRODO_MAX_N], e[FRODO_MAX_N * NBAR];
  sample_matrix(p, r, NBAR * p.n, st);
  sample_matrix(p, r + 2 * p.n * NBAR, p.n * NBAR, e);
  mldsa::secure_wipe(pre, sizeof(pre));  // held seedSE

  static thread_local uint16_t bmat[FRODO_MAX_N * NBAR];
  as_plus_e(p, gen, st, e, bmat);
  std::memcpy(pk, seed_a, 16);
  fpack(p, bmat, p.n * NBAR, pk + 16);
  // sk = s || pk || S^T (signed int16 LE) || pkh
  std::memcpy(sk, s, (size_t)p.len_sec);
  std::memcpy(sk + p.len_sec, pk, (size_t)p.pk_len());
  uint8_t* stb = sk + p.len_sec + p.pk_len();
  for (int k = 0; k < NBAR * p.n; ++k) {
    stb[2 * k] = (uint8_t)(st[k] & 0xff);
    stb[2 * k + 1] = (uint8_t)((st[k] >> 8) & 0xff);
  }
  fshake(p, pk, (size_t)p.pk_len(), sk + p.len_sec + p.pk_len() + 2 * NBAR * p.n,
         (size_t)p.len_sec);
  mldsa::secure_wipe(st, sizeof(int16_t) * NBAR * p.n);
  mldsa::secure_wipe(e, sizeof(int16_t) * p.n * NBAR);
  mldsa::secure_wipe(r, (size_t)(4 * p.n * NBAR));
}

// shared encrypt core: mu + seeds -> (bp 8xn, c 8x8); used by encaps + decaps
void encrypt(const Params& p, const uint8_t* pk, const uint8_t* mu,
             const uint8_t* seed_se, uint16_t* bp, uint16_t* c) {
  const uint8_t* seed_a = pk;
  aes::Aes128 cipher(seed_a);
  RowGen gen(p, p.aes ? &cipher : nullptr, seed_a);

  static thread_local uint8_t r[(2 * NBAR * FRODO_MAX_N + NBAR * NBAR) * 2];
  uint8_t pre[1 + 32];
  pre[0] = 0x96;
  std::memcpy(pre + 1, seed_se, (size_t)p.len_sec);
  fshake(p, pre, (size_t)(1 + p.len_sec),
         r, (size_t)((2 * NBAR * p.n + NBAR * NBAR) * 2));
  static thread_local int16_t sp[NBAR * FRODO_MAX_N], ep[NBAR * FRODO_MAX_N];
  int16_t epp[NBAR * NBAR];
  sample_matrix(p, r, NBAR * p.n, sp);
  sample_matrix(p, r + 2 * NBAR * p.n, NBAR * p.n, ep);
  sample_matrix(p, r + 4 * NBAR * p.n, NBAR * NBAR, epp);
  mldsa::secure_wipe(pre, sizeof(pre));  // held seedSE'

  sa_plus_e(p, gen, sp, ep, bp);
  // V = S' @ B + E'' + Encode(mu)
  static thread_local uint16_t bmat[FRODO_MAX_N * NBAR];
  funpack(p, pk + 16, p.n * NBAR, bmat);
  uint16_t enc_mu[NBAR * NBAR];
  fencode(p, mu, enc_mu);
  for (int i = 0; i < NBAR; ++i)
    for (int j = 0; j < NBAR; ++j) {
      uint32_t acc = 0;
      for (int k = 0; k < p.n; ++k)
        acc += (uint32_t)((int32_t)sp[i * p.n + k] * (int32_t)bmat[k * NBAR + j]);
      c[i * NBAR + j] = (uint16_t)((acc + (uint32_t)epp[i * NBAR + j] +
                                    enc_mu[i * NBAR + j]) & (uint32_t)p.q_mask());
    }
  mldsa::secure_wipe(enc_mu, sizeof(enc_mu));
  mldsa::secure_wipe(sp, sizeof(int16_t) * NBAR * p.n);
  mldsa::secure_wipe(ep, sizeof(int16_t) * NBAR * p.n);
  mldsa::secure_wipe(epp, sizeof(epp));
  mldsa::secure_wipe(r, (size_t)((2 * NBAR * p.n + NBAR * NBAR) * 2));
}

void encaps(const Params& p, const uint8_t* pk, const uint8_t* mu, uint8_t* ct,
            uint8_t* ss) {
  uint8_t pkh[32], se_k[64];
  fshake(p, pk, (size_t)p.pk_len(), pkh, (size_t)p.len_sec);
  static thread_local uint8_t buf[32 + 32];
  std::memcpy(buf, pkh, (size_t)p.len_sec);
  std::memcpy(buf + p.len_sec, mu, (size_t)p.len_sec);
  fshake(p, buf, (size_t)(2 * p.len_sec), se_k, (size_t)(2 * p.len_sec));
  const uint8_t* seed_se = se_k;
  const uint8_t* k = se_k + p.len_sec;

  static thread_local uint16_t bp[NBAR * FRODO_MAX_N];
  uint16_t c[NBAR * NBAR];
  encrypt(p, pk, mu, seed_se, bp, c);
  int c1 = NBAR * p.n * p.d / 8;
  fpack(p, bp, NBAR * p.n, ct);
  fpack(p, c, NBAR * NBAR, ct + c1);
  // ss = SHAKE(ct || k)
  static thread_local uint8_t tail[FRODO_MAX_CT + 32];
  std::memcpy(tail, ct, (size_t)p.ct_len());
  std::memcpy(tail + p.ct_len(), k, (size_t)p.len_sec);
  fshake(p, tail, (size_t)(p.ct_len() + p.len_sec), ss, (size_t)p.len_sec);
  mldsa::secure_wipe(se_k, sizeof(se_k));
  mldsa::secure_wipe(buf, sizeof(buf));  // held pkh || mu (mu is secret)
  mldsa::secure_wipe(tail, (size_t)(p.ct_len() + p.len_sec));
}

void decaps(const Params& p, const uint8_t* sk, const uint8_t* ct, uint8_t* ss) {
  const uint8_t* s = sk;
  const uint8_t* pk = sk + p.len_sec;
  const uint8_t* stb = sk + p.len_sec + p.pk_len();
  const uint8_t* pkh = stb + 2 * NBAR * p.n;

  int c1 = NBAR * p.n * p.d / 8;
  static thread_local uint16_t bp[NBAR * FRODO_MAX_N];
  uint16_t c[NBAR * NBAR];
  funpack(p, ct, NBAR * p.n, bp);
  funpack(p, ct + c1, NBAR * NBAR, c);

  // M = C - B' S  (S^T stored signed little-endian)
  static thread_local int16_t st[NBAR * FRODO_MAX_N];
  for (int k = 0; k < NBAR * p.n; ++k)
    st[k] = (int16_t)(uint16_t)(stb[2 * k] | (stb[2 * k + 1] << 8));
  uint16_t m[NBAR * NBAR];
  for (int i = 0; i < NBAR; ++i)
    for (int j = 0; j < NBAR; ++j) {
      uint32_t acc = 0;
      for (int k = 0; k < p.n; ++k)
        acc += (uint32_t)((int32_t)bp[i * p.n + k] * (int32_t)st[j * p.n + k]);
      m[i * NBAR + j] = (uint16_t)((c[i * NBAR + j] - acc) & (uint32_t)p.q_mask());
    }
  uint8_t mu_p[32];
  fdecode(p, m, mu_p);

  uint8_t se_k[64];
  static thread_local uint8_t buf[32 + 32];
  std::memcpy(buf, pkh, (size_t)p.len_sec);
  std::memcpy(buf + p.len_sec, mu_p, (size_t)p.len_sec);
  fshake(p, buf, (size_t)(2 * p.len_sec), se_k, (size_t)(2 * p.len_sec));

  static thread_local uint16_t bpp[NBAR * FRODO_MAX_N];
  uint16_t cp[NBAR * NBAR];
  encrypt(p, pk, mu_p, se_k, bpp, cp);

  // constant-time compare + select of k' vs s
  uint32_t diff = 0;
  for (int k = 0; k < NBAR * p.n; ++k) diff |= (uint32_t)(bp[k] ^ bpp[k]);
  for (int k = 0; k < NBAR * NBAR; ++k) diff |= (uint32_t)(c[k] ^ cp[k]);
  uint8_t mask = (uint8_t)(((int32_t)(diff | (0u - diff)) >> 31) & 0xff);  // 0xff iff diff != 0
  uint8_t sel[32];
  for (int i = 0; i < p.len_sec; ++i)
    sel[i] = (uint8_t)((se_k[p.len_sec + i] & (uint8_t)~mask) | (s[i] & mask));

  static thread_local uint8_t tail[FRODO_MAX_CT + 32];
  std::memcpy(tail, ct, (size_t)p.ct_len());
  std::memcpy(tail + p.ct_len(), sel, (size_t)p.len_sec);
  fshake(p, tail, (size_t)(p.ct_len() + p.len_sec), ss, (size_t)p.len_sec);
  mldsa::secure_wipe(st, sizeof(int16_t) * NBAR * p.n);
  mldsa::secure_wipe(se_k, sizeof(se_k));
  mldsa::secure_wipe(sel, sizeof(sel));
  mldsa::secure_wipe(tail, (size_t)(p.ct_len() + p.len_sec));
  // the decrypted message seed mu' and everything holding or derived from it
  // is secret — including the thread_local re-encryption outputs, which
  // would otherwise persist for the thread's lifetime
  mldsa::secure_wipe(mu_p, sizeof(mu_p));
  mldsa::secure_wipe(m, sizeof(m));
  mldsa::secure_wipe(buf, sizeof(buf));
  mldsa::secure_wipe(bpp, sizeof(uint16_t) * (size_t)(NBAR * p.n));
  mldsa::secure_wipe(cp, sizeof(cp));
}

}  // namespace frodo

// ---------------------------------------------------------------- HQC

namespace hqc {

constexpr int RM_N = 128;

struct Params {
  const char* name;
  int n, n1, k, delta, dup, w, wr;
  int n2() const { return RM_N * dup; }
  int n_bytes() const { return (n + 7) / 8; }
  int n_words() const { return (n + 63) / 64; }
  int n1n2_bits() const { return n1 * n2(); }
  int n1n2_bytes() const { return n1 * n2() / 8; }
  int pk_len() const { return 40 + n_bytes(); }
  int sk_len() const { return 40 + k + pk_len(); }
  int ct_len() const { return n_bytes() + n1n2_bytes() + 16; }
};

// capacity bounds for the static buffers (runtime-checked in the entries)
constexpr int HQC_MAX_W = 901;   // words for the largest n (57637)
constexpr int HQC_MAX_WT = 149;  // largest fixed weight (wr of HQC-256)

// ids: 0=HQC-128 1=HQC-192 2=HQC-256
const Params HPARAMS[3] = {
    {"HQC-128", 17669, 46, 16, 15, 3, 66, 75},
    {"HQC-192", 35851, 56, 24, 16, 5, 100, 114},
    {"HQC-256", 57637, 90, 32, 29, 5, 131, 149},
};

// -- GF(2^8), modulus 0x11D --------------------------------------------------

uint8_t GEXP[512];
uint8_t GLOG[256];
struct GfInit {
  GfInit() {
    int x = 1;
    for (int i = 0; i < 255; ++i) {
      GEXP[i] = (uint8_t)x;
      GLOG[x] = (uint8_t)i;
      x <<= 1;
      if (x & 0x100) x ^= 0x11D;
    }
    for (int i = 255; i < 512; ++i) GEXP[i] = GEXP[i - 255];
  }
} gf_init;

inline uint8_t gmul(uint8_t a, uint8_t b) {
  if (!a || !b) return 0;
  return GEXP[GLOG[a] + GLOG[b]];
}
inline uint8_t ginv(uint8_t a) { return GEXP[255 - GLOG[a]]; }

// -- Reed-Solomon over GF(2^8) (mirrors pyref rs_encode/rs_decode) -----------

void rs_gen_poly(const Params& p, uint8_t* g, int* glen) {
  g[0] = 1;
  int len = 1;
  for (int i = 1; i <= 2 * p.delta; ++i) {
    uint8_t root = GEXP[i];
    uint8_t ng[128] = {0};
    for (int j = 0; j < len; ++j) {
      ng[j] ^= gmul(g[j], root);
      ng[j + 1] ^= g[j];
    }
    ++len;
    std::memcpy(g, ng, (size_t)len);
  }
  *glen = len;
}

void rs_encode(const Params& p, const uint8_t* msg, uint8_t* cw) {
  uint8_t g[128];
  int glen;
  rs_gen_poly(p, g, &glen);
  int red = 2 * p.delta;
  uint8_t rem[128] = {0};
  for (int bi = p.k - 1; bi >= 0; --bi) {
    uint8_t coef = (uint8_t)(msg[bi] ^ rem[red - 1]);
    std::memmove(rem + 1, rem, (size_t)(red - 1));
    rem[0] = 0;
    if (coef)
      for (int j = 0; j < red; ++j) rem[j] ^= gmul(g[j], coef);
  }
  std::memcpy(cw, rem, (size_t)red);
  std::memcpy(cw + red, msg, (size_t)p.k);
}

void rs_decode(const Params& p, const uint8_t* cw_in, uint8_t* msg) {
  int red = 2 * p.delta;
  uint8_t c[128];
  std::memcpy(c, cw_in, (size_t)p.n1);
  uint8_t synd[58];
  bool any = false;
  for (int i = 1; i <= red; ++i) {
    uint8_t s = 0;
    for (int j = 0; j < p.n1; ++j)
      if (c[j]) s ^= GEXP[(GLOG[c[j]] + i * j) % 255];
    synd[i - 1] = s;
    any |= (s != 0);
  }
  if (!any) {
    std::memcpy(msg, c + red, (size_t)p.k);
    return;
  }
  // Berlekamp-Massey (mirrors the oracle's variable-length polynomials)
  uint8_t sigma[128] = {1}, b[128] = {1}, t[128];
  int slen = 1, blen = 1;
  int L = 0, m = 1;
  uint8_t bb = 1;
  for (int n_it = 0; n_it < red; ++n_it) {
    uint8_t d = synd[n_it];
    for (int i = 1; i <= L; ++i)
      if (i < slen && sigma[i] && synd[n_it - i]) d ^= gmul(sigma[i], synd[n_it - i]);
    if (d == 0) {
      ++m;
    } else {
      uint8_t coef = gmul(d, ginv(bb));
      int shlen = m + blen;
      int nlen = slen > shlen ? slen : shlen;
      bool grow = 2 * L <= n_it;
      int old_slen = slen;
      if (grow) std::memcpy(t, sigma, (size_t)old_slen);
      for (int i = 0; i < nlen; ++i) {
        uint8_t sv = i < slen ? sigma[i] : 0;
        uint8_t hv = (i >= m && i - m < blen) ? gmul(coef, b[i - m]) : 0;
        sigma[i] = (uint8_t)(sv ^ hv);
      }
      slen = nlen;
      if (grow) {
        L = n_it + 1 - L;
        std::memcpy(b, t, (size_t)old_slen);  // b <- pre-update sigma
        blen = old_slen;
        bb = d;
        m = 1;
      } else {
        ++m;
      }
    }
  }
  // Chien search
  int err_pos[128], nerr = 0;
  for (int j = 0; j < p.n1; ++j) {
    uint8_t val = 0;
    for (int i = 0; i < slen; ++i)
      if (sigma[i]) val ^= GEXP[(GLOG[sigma[i]] + i * ((255 - j) % 255)) % 255];
    if (val == 0) err_pos[nerr++] = j;
  }
  // Forney
  uint8_t omega[58] = {0};
  for (int i = 0; i < slen; ++i)
    for (int j = 0; j < red; ++j)
      if (i + j < red && sigma[i] && synd[j]) omega[i + j] ^= gmul(sigma[i], synd[j]);
  for (int e = 0; e < nerr; ++e) {
    int j = err_pos[e];
    uint8_t xinv = GEXP[(255 - j) % 255];
    uint8_t num = 0, xp = 1;
    for (int i = 0; i < red; ++i) {
      if (omega[i]) num ^= gmul(omega[i], xp);
      xp = gmul(xp, xinv);
    }
    uint8_t den = 0;
    uint8_t x2 = gmul(xinv, xinv);
    xp = 1;
    for (int i = 1; i < slen; i += 2) {
      if (sigma[i]) den ^= gmul(sigma[i], xp);
      xp = gmul(xp, x2);
    }
    if (den == 0) continue;
    c[j] ^= gmul(num, ginv(den));
  }
  std::memcpy(msg, c + red, (size_t)p.k);
}

// -- duplicated RM(1,7) ------------------------------------------------------

uint64_t RM_TABLE[256][2];
struct RmInit {
  RmInit() {
    for (int bnum = 0; bnum < 256; ++bnum) {
      uint64_t lo = 0, hi = 0;
      for (int j = 0; j < RM_N; ++j) {
        int bit = bnum & 1;
        for (int tt = 0; tt < 7; ++tt)
          if (((bnum >> (tt + 1)) & 1) && ((j >> tt) & 1)) bit ^= 1;
        if (bit) {
          if (j < 64) lo |= 1ull << j;
          else hi |= 1ull << (j - 64);
        }
      }
      RM_TABLE[bnum][0] = lo;
      RM_TABLE[bnum][1] = hi;
    }
  }
} rm_init;

// bits: n2-per-block view into the big vector (bit getter below)
struct BitVec {
  const uint64_t* w;
  bool get(int i) const { return (w[i >> 6] >> (i & 63)) & 1; }
};

uint8_t rm_decode_block(const Params& p, const BitVec& v, int base) {
  int16_t f[RM_N];
  for (int j = 0; j < RM_N; ++j) {
    int acc = 0;
    for (int d = 0; d < p.dup; ++d)
      acc += 1 - 2 * (int)v.get(base + d * RM_N + j);
    f[j] = (int16_t)acc;
  }
  for (int h = 1; h < RM_N; h <<= 1)
    for (int i = 0; i < RM_N; i += 2 * h)
      for (int j = i; j < i + h; ++j) {
        int16_t a = f[j], b2 = f[j + h];
        f[j] = (int16_t)(a + b2);
        f[j + h] = (int16_t)(a - b2);
      }
  int best = 0, bestv = f[0] < 0 ? -f[0] : f[0];
  for (int i = 1; i < RM_N; ++i) {
    int av = f[i] < 0 ? -f[i] : f[i];
    if (av > bestv) { bestv = av; best = i; }  // first max, like the oracle
  }
  int b0 = f[best] < 0 ? 1 : 0;
  return (uint8_t)((best << 1) | b0);
}

// -- bit-vector helpers (LE words; byte image == LE byte string) -------------

inline void vec_xor_shift(uint64_t* acc, int acc_words, const uint64_t* a,
                          int a_words, int pos) {
  int ws = pos >> 6, bs = pos & 63;
  if (bs == 0) {
    for (int i = 0; i < a_words && ws + i < acc_words; ++i) acc[ws + i] ^= a[i];
  } else {
    for (int i = 0; i < a_words && ws + i < acc_words; ++i)
      acc[ws + i] ^= a[i] << bs;
    for (int i = 0; i < a_words && ws + i + 1 < acc_words; ++i)
      acc[ws + i + 1] ^= a[i] >> (64 - bs);
  }
}

// out = x rotated left by STATIC amount c in GF(2)[x]/(x^n - 1).
// c is public (a fixed barrel-stage constant); all indexing is static.
void rotl_fixed(const Params& p, const uint64_t* x, int c, uint64_t* out) {
  int W = p.n_words();
  std::memset(out, 0, sizeof(uint64_t) * (size_t)W);
  vec_xor_shift(out, W, x, W, c);  // bits j >= c get x[j - c]
  int s = p.n - c;                 // bits j < c get x[j + n - c]
  int ws = s >> 6, bs = s & 63;
  for (int i = 0; i + ws < W; ++i) {
    uint64_t w = x[i + ws] >> bs;
    if (bs && i + ws + 1 < W) w |= x[i + ws + 1] << (64 - bs);
    out[i] ^= w;
  }
  int topbits = p.n & 63;
  if (topbits) out[W - 1] &= (1ull << topbits) - 1;
}

// out = a * sparse(sup) in GF(2)[x]/(x^n - 1); out may not alias a.
//
// Constant-time: a << pos (mod x^n - 1) is a cyclic rotation by pos, and the
// support positions are secret (y of the long-term key, r2/e/r1 of a
// session), so each rotation runs as a barrel shifter — log2(n) stages of
// STATIC-amount rotations composed with branchless mask selects.  Memory
// access patterns and branch behavior are independent of the secrets;
// secret bits appear only in data (the select masks).
void cyclic_mul_sparse(const Params& p, const uint64_t* a, const uint32_t* sup,
                       int wt, uint64_t* out) {
  int W = p.n_words();
  static thread_local uint64_t t1[HQC_MAX_W], t2[HQC_MAX_W];
  std::memset(out, 0, sizeof(uint64_t) * (size_t)W);
  for (int i = 0; i < wt; ++i) {
    uint32_t pos = sup[i];
    std::memcpy(t1, a, sizeof(uint64_t) * (size_t)W);
    for (int k = 0; (1 << k) < p.n; ++k) {
      rotl_fixed(p, t1, (1 << k) % p.n, t2);
      uint64_t m = (uint64_t)0 - (uint64_t)((pos >> k) & 1);
      for (int j = 0; j < W; ++j) t1[j] = (t2[j] & m) | (t1[j] & ~m);
    }
    for (int j = 0; j < W; ++j) out[j] ^= t1[j];
  }
  // t1/t2 hold the last secret rotation offset's image
  mldsa::secure_wipe(t1, sizeof(uint64_t) * (size_t)W);
  mldsa::secure_wipe(t2, sizeof(uint64_t) * (size_t)W);
}

// -- sampling (official seedexpander structure; pyref SeedExpander) ----------

struct SeedExpander {
  Sponge sp;
  explicit SeedExpander(const uint8_t* seed, size_t len) : sp(136) {
    sp.absorb(seed, len);
    uint8_t dom = 0x02;
    sp.absorb(&dom, 1);
    sp.finish(0x1f);
  }
  void read(uint8_t* out, size_t n) { sp.squeeze(out, n); }
};

void sample_fixed_weight(const Params& p, SeedExpander& ctx, int wt, uint32_t* sup) {
  uint8_t buf[4 * HQC_MAX_WT];
  ctx.read(buf, (size_t)(4 * wt));
  for (int i = 0; i < wt; ++i) {
    uint32_t r = (uint32_t)buf[4 * i] | ((uint32_t)buf[4 * i + 1] << 8) |
                 ((uint32_t)buf[4 * i + 2] << 16) | ((uint32_t)buf[4 * i + 3] << 24);
    sup[i] = (uint32_t)i + (uint32_t)(((uint64_t)r * (uint64_t)(p.n - i)) >> 32);
  }
  for (int i = wt - 2; i >= 0; --i) {
    bool dup = false;
    for (int j = i + 1; j < wt; ++j) dup |= (sup[j] == sup[i]);
    if (dup) sup[i] = (uint32_t)i;
  }
}

void sample_random_vector(const Params& p, SeedExpander& ctx, uint64_t* out) {
  int W = p.n_words();
  std::memset(out, 0, sizeof(uint64_t) * (size_t)W);
  ctx.read(reinterpret_cast<uint8_t*>(out), (size_t)p.n_bytes());
  int topbits = p.n & 63;
  if (topbits) out[W - 1] &= (1ull << topbits) - 1;
}

inline void support_to_vec(const Params& p, const uint32_t* sup, int wt, uint64_t* out) {
  std::memset(out, 0, sizeof(uint64_t) * (size_t)p.n_words());
  for (int i = 0; i < wt; ++i) out[sup[i] >> 6] |= 1ull << (sup[i] & 63);
}

void hash_ds(const uint8_t* data, size_t len, uint8_t dom, uint8_t* out64) {
  Sponge sp(136);
  sp.absorb(data, len);
  sp.absorb(&dom, 1);
  sp.finish(0x1f);
  sp.squeeze(out64, 64);
}

// -- KEM ---------------------------------------------------------------------

void code_encode(const Params& p, const uint8_t* msg, uint64_t* out) {
  uint8_t rs[128];
  rs_encode(p, msg, rs);
  std::memset(out, 0, sizeof(uint64_t) * (size_t)p.n_words());
  uint64_t cw[2];
  for (int i = 0; i < p.n1; ++i) {
    cw[0] = RM_TABLE[rs[i]][0];
    cw[1] = RM_TABLE[rs[i]][1];
    for (int d = 0; d < p.dup; ++d)
      vec_xor_shift(out, p.n_words(), cw, 2, i * p.n2() + d * RM_N);
  }
}

void code_decode(const Params& p, const uint64_t* v, uint8_t* msg) {
  uint8_t rs[128];
  BitVec bv{v};
  for (int i = 0; i < p.n1; ++i) rs[i] = rm_decode_block(p, bv, i * p.n2());
  rs_decode(p, rs, msg);
}

void keygen(const Params& p, const uint8_t* sk_seed, const uint8_t* sigma,
            const uint8_t* pk_seed, uint8_t* pk, uint8_t* sk) {
  SeedExpander sk_ctx(sk_seed, 40);
  uint32_t ysup[HQC_MAX_WT], xsup[HQC_MAX_WT];
  sample_fixed_weight(p, sk_ctx, p.w, ysup);   // y first (pyref order)
  sample_fixed_weight(p, sk_ctx, p.w, xsup);
  SeedExpander pk_ctx(pk_seed, 40);
  static thread_local uint64_t h[HQC_MAX_W], s[HQC_MAX_W], x[HQC_MAX_W];
  sample_random_vector(p, pk_ctx, h);
  cyclic_mul_sparse(p, h, ysup, p.w, s);
  support_to_vec(p, xsup, p.w, x);
  for (int i = 0; i < p.n_words(); ++i) s[i] ^= x[i];
  std::memcpy(pk, pk_seed, 40);
  std::memcpy(pk + 40, reinterpret_cast<uint8_t*>(s), (size_t)p.n_bytes());
  std::memcpy(sk, sk_seed, 40);
  std::memcpy(sk + 40, sigma, (size_t)p.k);
  std::memcpy(sk + 40 + p.k, pk, (size_t)p.pk_len());
  mldsa::secure_wipe(ysup, sizeof(ysup));
  mldsa::secure_wipe(xsup, sizeof(xsup));
  mldsa::secure_wipe(x, sizeof(uint64_t) * (size_t)p.n_words());
}

// (u, v) = encrypt(pk, m, theta); u/v as n-bit vectors (v truncated later)
void encrypt(const Params& p, const uint8_t* pk, const uint8_t* m,
             const uint8_t* theta, uint64_t* u, uint64_t* v) {
  int W = p.n_words();
  SeedExpander pk_ctx(pk, 40);
  static thread_local uint64_t h[HQC_MAX_W], sv[HQC_MAX_W], tmp[HQC_MAX_W], code[HQC_MAX_W];
  sample_random_vector(p, pk_ctx, h);
  std::memset(sv, 0, sizeof(uint64_t) * (size_t)W);
  std::memcpy(reinterpret_cast<uint8_t*>(sv), pk + 40, (size_t)p.n_bytes());

  SeedExpander ctx(theta, 64);
  uint32_t r2[HQC_MAX_WT], e[HQC_MAX_WT], r1[HQC_MAX_WT];
  sample_fixed_weight(p, ctx, p.wr, r2);  // pyref order: r2, e, r1
  sample_fixed_weight(p, ctx, p.wr, e);
  sample_fixed_weight(p, ctx, p.wr, r1);

  cyclic_mul_sparse(p, h, r2, p.wr, u);
  support_to_vec(p, r1, p.wr, tmp);
  for (int i = 0; i < W; ++i) u[i] ^= tmp[i];

  code_encode(p, m, code);
  cyclic_mul_sparse(p, sv, r2, p.wr, v);
  support_to_vec(p, e, p.wr, tmp);
  for (int i = 0; i < W; ++i) v[i] ^= code[i] ^ tmp[i];
  // truncate v to n1*n2 bits
  int nb = p.n1n2_bits();
  int ws = nb >> 6, bs = nb & 63;
  if (bs) v[ws] &= (1ull << bs) - 1;
  for (int i = ws + (bs ? 1 : 0); i < W; ++i) v[i] = 0;
  mldsa::secure_wipe(r2, sizeof(r2));
  mldsa::secure_wipe(e, sizeof(e));
  mldsa::secure_wipe(r1, sizeof(r1));
}

void encaps(const Params& p, const uint8_t* pk, const uint8_t* m,
            const uint8_t* salt, uint8_t* ct, uint8_t* ss) {
  static thread_local uint8_t gin[32 + 32 + 16];
  std::memcpy(gin, m, (size_t)p.k);
  std::memcpy(gin + p.k, pk, 32);
  std::memcpy(gin + p.k + 32, salt, 16);
  uint8_t theta[64];
  hash_ds(gin, (size_t)(p.k + 32 + 16), 0x03, theta);

  static thread_local uint64_t u[HQC_MAX_W], v[HQC_MAX_W];
  encrypt(p, pk, m, theta, u, v);
  std::memcpy(ct, reinterpret_cast<uint8_t*>(u), (size_t)p.n_bytes());
  std::memcpy(ct + p.n_bytes(), reinterpret_cast<uint8_t*>(v), (size_t)p.n1n2_bytes());
  std::memcpy(ct + p.n_bytes() + p.n1n2_bytes(), salt, 16);

  static thread_local uint8_t kin[32 + (HQC_MAX_W + 1) * 8 + HQC_MAX_W * 8];
  std::memcpy(kin, m, (size_t)p.k);
  std::memcpy(kin + p.k, ct, (size_t)(p.n_bytes() + p.n1n2_bytes()));
  hash_ds(kin, (size_t)(p.k + p.n_bytes() + p.n1n2_bytes()), 0x04, ss);
  mldsa::secure_wipe(theta, sizeof(theta));
  mldsa::secure_wipe(gin, (size_t)(p.k + 48));
  mldsa::secure_wipe(kin, (size_t)p.k);
}

void decaps(const Params& p, const uint8_t* sk, const uint8_t* ct, uint8_t* ss) {
  const uint8_t* sk_seed = sk;
  const uint8_t* sigma = sk + 40;
  const uint8_t* pk = sk + 40 + p.k;
  int W = p.n_words();

  static thread_local uint64_t u[HQC_MAX_W], v[HQC_MAX_W], uy[HQC_MAX_W];
  std::memset(u, 0, sizeof(uint64_t) * (size_t)W);
  std::memset(v, 0, sizeof(uint64_t) * (size_t)W);
  std::memcpy(reinterpret_cast<uint8_t*>(u), ct, (size_t)p.n_bytes());
  std::memcpy(reinterpret_cast<uint8_t*>(v), ct + p.n_bytes(), (size_t)p.n1n2_bytes());
  const uint8_t* salt = ct + p.n_bytes() + p.n1n2_bytes();

  SeedExpander sk_ctx(sk_seed, 40);
  uint32_t ysup[HQC_MAX_WT];
  sample_fixed_weight(p, sk_ctx, p.w, ysup);  // first draw = y
  cyclic_mul_sparse(p, u, ysup, p.w, uy);
  // v ^ uy truncated to n1*n2 bits
  int nb = p.n1n2_bits();
  int ws = nb >> 6, bs = nb & 63;
  if (bs) uy[ws] &= (1ull << bs) - 1;
  for (int i = ws + (bs ? 1 : 0); i < W; ++i) uy[i] = 0;
  static thread_local uint64_t vx[HQC_MAX_W];
  for (int i = 0; i < W; ++i) vx[i] = v[i] ^ uy[i];
  uint8_t m_p[32];
  code_decode(p, vx, m_p);

  static thread_local uint8_t gin[32 + 32 + 16];
  std::memcpy(gin, m_p, (size_t)p.k);
  std::memcpy(gin + p.k, pk, 32);
  std::memcpy(gin + p.k + 32, salt, 16);
  uint8_t theta[64];
  hash_ds(gin, (size_t)(p.k + 32 + 16), 0x03, theta);

  static thread_local uint64_t u2[HQC_MAX_W], v2[HQC_MAX_W];
  encrypt(p, pk, m_p, theta, u2, v2);
  uint64_t diff = 0;
  for (int i = 0; i < W; ++i) diff |= (u[i] ^ u2[i]) | (v[i] ^ v2[i]);
  // constant-time select: m' on match, sigma on mismatch
  uint8_t mask = (uint8_t)(0 - (uint8_t)(diff != 0));  // data-dependent but
  // the compare itself is over public ct vs recomputed ct'; branchless select:
  uint8_t sel[32];
  for (int i = 0; i < p.k; ++i)
    sel[i] = (uint8_t)((m_p[i] & (uint8_t)~mask) | (sigma[i] & mask));

  static thread_local uint8_t kin[32 + (HQC_MAX_W + 1) * 8 + HQC_MAX_W * 8];
  std::memcpy(kin, sel, (size_t)p.k);
  std::memcpy(kin + p.k, ct, (size_t)(p.n_bytes() + p.n1n2_bytes()));
  hash_ds(kin, (size_t)(p.k + p.n_bytes() + p.n1n2_bytes()), 0x04, ss);
  mldsa::secure_wipe(ysup, sizeof(ysup));
  mldsa::secure_wipe(m_p, sizeof(m_p));
  mldsa::secure_wipe(sel, sizeof(sel));
  mldsa::secure_wipe(theta, sizeof(theta));
  mldsa::secure_wipe(gin, (size_t)(p.k + 48));
  mldsa::secure_wipe(kin, (size_t)p.k);
  mldsa::secure_wipe(vx, sizeof(uint64_t) * (size_t)W);
  mldsa::secure_wipe(u2, sizeof(uint64_t) * (size_t)W);  // re-encryption of m'
  mldsa::secure_wipe(v2, sizeof(uint64_t) * (size_t)W);
}

}  // namespace hqc

}  // namespace

extern "C" {

// -------- hashes ------------------------------------------------------------

void qrp_shake128(const uint8_t* in, size_t inlen, uint8_t* out, size_t outlen) {
  shake(168, in, inlen, out, outlen);
}
void qrp_shake256(const uint8_t* in, size_t inlen, uint8_t* out, size_t outlen) {
  shake(136, in, inlen, out, outlen);
}
void qrp_sha3_256(const uint8_t* in, size_t inlen, uint8_t* out) {
  sha3(136, in, inlen, out, 32);
}
void qrp_sha3_512(const uint8_t* in, size_t inlen, uint8_t* out) {
  sha3(72, in, inlen, out, 64);
}

// -------- utilities ---------------------------------------------------------

void qrp_zeroize(uint8_t* buf, size_t len) {
  volatile uint8_t* p = buf;
  while (len--) *p++ = 0;
}

// -------- ML-KEM (FIPS 203 internal forms; k = 2/3/4) -----------------------

void qrp_mlkem_keygen(int k, const uint8_t d[32], const uint8_t z[32],
                      uint8_t* ek, uint8_t* dk) {
  MLKEMParams p = params_for(k);
  int eklen = 384 * k + 32;
  kpke_keygen(p, d, ek, dk);
  std::memcpy(dk + 384 * k, ek, eklen);
  sha3(136, ek, (size_t)eklen, dk + 384 * k + eklen, 32);
  std::memcpy(dk + 384 * k + eklen + 32, z, 32);
}

void qrp_mlkem_encaps(int k, const uint8_t* ek, const uint8_t m[32],
                      uint8_t* key, uint8_t* ct) {
  MLKEMParams p = params_for(k);
  int eklen = 384 * k + 32;
  uint8_t g_in[64], g_out[64];
  std::memcpy(g_in, m, 32);
  sha3(136, ek, (size_t)eklen, g_in + 32, 32);
  sha3(72, g_in, 64, g_out, 64);
  std::memcpy(key, g_out, 32);
  kpke_encrypt(p, ek, m, g_out + 32, ct);
}

void qrp_mlkem_decaps(int k, const uint8_t* dk, const uint8_t* ct, uint8_t* key) {
  MLKEMParams p = params_for(k);
  int eklen = 384 * k + 32;
  int ctlen = 32 * (p.du * p.k + p.dv);
  const uint8_t* dk_pke = dk;
  const uint8_t* ek = dk + 384 * k;
  const uint8_t* h = dk + 384 * k + eklen;
  const uint8_t* z = h + 32;
  uint8_t m2[32], g_in[64], g_out[64];
  kpke_decrypt(p, dk_pke, ct, m2);
  std::memcpy(g_in, m2, 32);
  std::memcpy(g_in + 32, h, 32);
  sha3(72, g_in, 64, g_out, 64);
  // key_bar = SHAKE256(z || ct, 32)
  uint8_t kb_in[32 + 32 * (11 * 4 + 5)];
  std::memcpy(kb_in, z, 32);
  std::memcpy(kb_in + 32, ct, (size_t)ctlen);
  uint8_t key_bar[32];
  shake(136, kb_in, (size_t)(32 + ctlen), key_bar, 32);
  uint8_t ct2[32 * (11 * 4 + 5)];
  kpke_encrypt(p, ek, m2, g_out + 32, ct2);
  // constant-time compare + select
  uint8_t diff = 0;
  for (int i = 0; i < ctlen; ++i) diff |= (uint8_t)(ct[i] ^ ct2[i]);
  uint8_t mask = (uint8_t)(((int)diff - 1) >> 8);  // 0xff iff diff == 0
  for (int i = 0; i < 32; ++i)
    key[i] = (uint8_t)((g_out[i] & mask) | (key_bar[i] & ~mask));
}

// -------- ML-DSA (FIPS 204 internal forms; level = 2/3/5) -------------------
//
// m_prime is the already-framed message M' = 0x00 || len(ctx) || ctx || M
// (same seam as pyref/mldsa_ref.py sign_internal/verify_internal).

void qrp_mldsa_keygen(int level, const uint8_t* xi, uint8_t* pk, uint8_t* sk) {
  mldsa::keygen(mldsa::params_for(level), xi, pk, sk);
}

int qrp_mldsa_sign(int level, const uint8_t* sk, const uint8_t* m_prime,
                   size_t mlen, const uint8_t* rnd, uint8_t* sig) {
  return mldsa::sign_internal(mldsa::params_for(level), sk, m_prime, mlen, rnd, sig)
             ? 1
             : 0;
}

int qrp_mldsa_verify(int level, const uint8_t* pk, const uint8_t* m_prime,
                     size_t mlen, const uint8_t* sig) {
  return mldsa::verify_internal(mldsa::params_for(level), pk, m_prime, mlen, sig) ? 1 : 0;
}

// -------- SHA-2 -------------------------------------------------------------

void qrp_sha256(const uint8_t* in, size_t inlen, uint8_t* out) {
  sha2::sha256(in, inlen, out);
}

void qrp_sha512(const uint8_t* in, size_t inlen, uint8_t* out) {
  sha2::sha512(in, inlen, out);
}

void qrp_hmac_sha256(const uint8_t* key, size_t keylen, const uint8_t* msg,
                     size_t msglen, uint8_t* out) {
  sha2::hmac(false, key, keylen, msg, msglen, nullptr, 0, out);
}

// -------- SLH-DSA (FIPS 205 internal forms) ---------------------------------
//
// param_id: 0=128s 1=128f 2=192s 3=192f 4=256s 5=256f (SHA2 'simple').
// addrnd may be NULL (deterministic variant, opt_rand = pk_seed).

void qrp_slhdsa_keygen(int param_id, const uint8_t* sk_seed, const uint8_t* sk_prf,
                       const uint8_t* pk_seed, uint8_t* pk, uint8_t* sk) {
  slhdsa::keygen(slhdsa::PARAMS[param_id], sk_seed, sk_prf, pk_seed, pk, sk);
}

void qrp_slhdsa_sign(int param_id, const uint8_t* sk, const uint8_t* msg,
                     size_t msglen, const uint8_t* addrnd, uint8_t* sig) {
  slhdsa::sign_internal(slhdsa::PARAMS[param_id], msg, msglen, sk, addrnd, sig);
}

int qrp_slhdsa_verify(int param_id, const uint8_t* pk, const uint8_t* msg,
                      size_t msglen, const uint8_t* sig) {
  return slhdsa::verify_internal(slhdsa::PARAMS[param_id], msg, msglen, sig, pk)
             ? 1
             : 0;
}

// -------- AES-128-ECB (FrodoKEM matrix generation; FIPS-197-testable) -------

void qrp_aes128_ecb(const uint8_t* key, const uint8_t* in, size_t nblocks,
                    uint8_t* out) {
  aes::Aes128 c(key);
  for (size_t i = 0; i < nblocks; ++i)
    c.encrypt_block(in + 16 * i, out + 16 * i);
}

// -------- FrodoKEM (round-3/ISO spec internal forms) ------------------------
//
// param_id: 0=640-AES 1=640-SHAKE 2=976-AES 3=976-SHAKE 4=1344-AES
// 5=1344-SHAKE.  Deterministic seams match pyref/frodo_ref.py:
// keygen(s, seedSE, z), encaps(pk, mu), decaps(sk, ct).

void qrp_frodo_keygen(int param_id, const uint8_t* s, const uint8_t* seed_se,
                      const uint8_t* z, uint8_t* pk, uint8_t* sk) {
  const frodo::Params& p = frodo::FPARAMS[param_id];
  if (p.n > frodo::FRODO_MAX_N || p.ct_len() > frodo::FRODO_MAX_CT) return;
  frodo::keygen(p, s, seed_se, z, pk, sk);
}

void qrp_frodo_encaps(int param_id, const uint8_t* pk, const uint8_t* mu,
                      uint8_t* ct, uint8_t* ss) {
  frodo::encaps(frodo::FPARAMS[param_id], pk, mu, ct, ss);
}

void qrp_frodo_decaps(int param_id, const uint8_t* sk, const uint8_t* ct,
                      uint8_t* ss) {
  frodo::decaps(frodo::FPARAMS[param_id], sk, ct, ss);
}

// -------- HQC (round-4-shaped internal forms) -------------------------------
//
// param_id: 0=HQC-128 1=HQC-192 2=HQC-256.  Deterministic seams match
// pyref/hqc_ref.py: keygen(sk_seed 40, sigma k, pk_seed 40),
// encaps(pk, m k, salt 16), decaps(sk, ct).

void qrp_hqc_keygen(int param_id, const uint8_t* sk_seed, const uint8_t* sigma,
                    const uint8_t* pk_seed, uint8_t* pk, uint8_t* sk) {
  const hqc::Params& p = hqc::HPARAMS[param_id];
  if (p.n_words() > hqc::HQC_MAX_W || p.wr > hqc::HQC_MAX_WT) return;
  hqc::keygen(p, sk_seed, sigma, pk_seed, pk, sk);
}

void qrp_hqc_encaps(int param_id, const uint8_t* pk, const uint8_t* m,
                    const uint8_t* salt, uint8_t* ct, uint8_t* ss) {
  hqc::encaps(hqc::HPARAMS[param_id], pk, m, salt, ct, ss);
}

void qrp_hqc_decaps(int param_id, const uint8_t* sk, const uint8_t* ct,
                    uint8_t* ss) {
  hqc::decaps(hqc::HPARAMS[param_id], sk, ct, ss);
}

int qrp_version(void) { return 5; }

}  // extern "C"
