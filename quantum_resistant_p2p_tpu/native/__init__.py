"""ctypes loader for the C++ host crypto core (qrp_native.cpp, this package).

Fills the role liboqs plays for the reference app (vendored .so loaded via
ctypes, reference vendor/__init__.py:12-57 + vendor/oqs.py:122-183): a native
CPU fast path for Keccak and ML-KEM, compiled on demand with g++ (pybind11 is
not available in this environment; plain extern "C" + ctypes is the binding).

``load()`` returns None when no compiler/library is available — callers fall
back to the pure-Python pyref implementations, which remain the oracles.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path

logger = logging.getLogger(__name__)

# Ships inside the package so non-editable installs carry the source
# (pyproject.toml package-data) and build-on-demand works from site-packages.
_SRC = Path(__file__).resolve().parent / "qrp_native.cpp"
_CACHE_DIR = Path(
    os.environ.get("QRP_NATIVE_CACHE", Path.home() / ".cache" / "qrp2p_tpu")
)

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> Path | None:
    _CACHE_DIR.mkdir(parents=True, exist_ok=True)
    so = _CACHE_DIR / "libqrp_native.so"
    if so.exists() and so.stat().st_mtime >= _SRC.stat().st_mtime:
        return so
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", str(so), str(_SRC)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return so
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native build failed (falling back to pure Python): %s", e)
        return None


def load() -> ctypes.CDLL | None:
    """Build-if-needed and load the native library; None on failure."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _SRC.exists():
            return None
        so = _build()
        if so is None:
            return None
        try:
            _lib = _bind(ctypes.CDLL(str(so)))
        except AttributeError:
            # Stale cached .so predating newer symbols (e.g. synced with
            # preserved mtimes): force one rebuild, then give up to the
            # pure-Python fallback rather than raising out of load().
            logger.warning("cached native library is stale; rebuilding")
            try:
                so.unlink()
                so = _build()
                _lib = _bind(ctypes.CDLL(str(so))) if so else None
            except (OSError, AttributeError) as e:
                logger.warning("native rebuild failed (pure-Python fallback): %s", e)
                _lib = None
        if _lib is not None:
            logger.info(
                "loaded native crypto core v%d from %s", _lib.qrp_version(), so
            )
        return _lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Set argtypes/restypes; raises AttributeError if a symbol is missing."""
    u8p = ctypes.POINTER(ctypes.c_uint8)
    for name, argtypes in (
        ("qrp_shake128", [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]),
        ("qrp_shake256", [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]),
        ("qrp_sha3_256", [u8p, ctypes.c_size_t, u8p]),
        ("qrp_sha3_512", [u8p, ctypes.c_size_t, u8p]),
        ("qrp_zeroize", [u8p, ctypes.c_size_t]),
        ("qrp_mlkem_keygen", [ctypes.c_int, u8p, u8p, u8p, u8p]),
        ("qrp_mlkem_encaps", [ctypes.c_int, u8p, u8p, u8p, u8p]),
        ("qrp_mlkem_decaps", [ctypes.c_int, u8p, u8p, u8p]),
        ("qrp_mldsa_keygen", [ctypes.c_int, u8p, u8p, u8p]),
        ("qrp_sha256", [u8p, ctypes.c_size_t, u8p]),
        ("qrp_sha512", [u8p, ctypes.c_size_t, u8p]),
        ("qrp_hmac_sha256", [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t, u8p]),
        ("qrp_slhdsa_keygen", [ctypes.c_int, u8p, u8p, u8p, u8p, u8p]),
        ("qrp_slhdsa_sign", [ctypes.c_int, u8p, u8p, ctypes.c_size_t, u8p, u8p]),
        ("qrp_aes128_ecb", [u8p, u8p, ctypes.c_size_t, u8p]),
        ("qrp_frodo_keygen", [ctypes.c_int, u8p, u8p, u8p, u8p, u8p]),
        ("qrp_frodo_encaps", [ctypes.c_int, u8p, u8p, u8p, u8p]),
        ("qrp_frodo_decaps", [ctypes.c_int, u8p, u8p, u8p]),
        ("qrp_hqc_keygen", [ctypes.c_int, u8p, u8p, u8p, u8p, u8p]),
        ("qrp_hqc_encaps", [ctypes.c_int, u8p, u8p, u8p, u8p, u8p]),
        ("qrp_hqc_decaps", [ctypes.c_int, u8p, u8p, u8p]),
    ):
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = None
    lib.qrp_mldsa_sign.argtypes = [ctypes.c_int, u8p, u8p, ctypes.c_size_t, u8p, u8p]
    lib.qrp_mldsa_sign.restype = ctypes.c_int
    lib.qrp_mldsa_verify.argtypes = [ctypes.c_int, u8p, u8p, ctypes.c_size_t, u8p]
    lib.qrp_mldsa_verify.restype = ctypes.c_int
    lib.qrp_slhdsa_verify.argtypes = [ctypes.c_int, u8p, u8p, ctypes.c_size_t, u8p]
    lib.qrp_slhdsa_verify.restype = ctypes.c_int
    lib.qrp_version.restype = ctypes.c_int
    return lib


def _expect(data: bytes, n: int, what: str) -> None:
    # Wrong lengths never reach the native core (it reads fixed param-set
    # sizes unconditionally) — same seam contract as the pyref oracles.
    if len(data) != n:
        raise ValueError(f"{what} must be {n} bytes, got {len(data)}")


def _buf(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data)


def _out(n: int):
    return (ctypes.c_uint8 * n)()


class NativeMLKEM:
    """Scalar ML-KEM over the native core (same seams as pyref.mlkem_ref)."""

    _K = {"ML-KEM-512": 2, "ML-KEM-768": 3, "ML-KEM-1024": 4}

    def __init__(self, name: str):
        self.lib = load()
        if self.lib is None:
            raise RuntimeError("native core unavailable")
        self.k = self._K[name]
        self.ek_len = 384 * self.k + 32
        self.dk_len = 768 * self.k + 96
        du, dv = (10, 4) if self.k < 4 else (11, 5)
        self.ct_len = 32 * (du * self.k + dv)

    def keygen(self, d: bytes, z: bytes) -> tuple[bytes, bytes]:
        ek, dk = _out(self.ek_len), _out(self.dk_len)
        self.lib.qrp_mlkem_keygen(self.k, _buf(d), _buf(z), ek, dk)
        return bytes(ek), bytes(dk)

    def encaps(self, ek: bytes, m: bytes) -> tuple[bytes, bytes]:
        key, ct = _out(32), _out(self.ct_len)
        self.lib.qrp_mlkem_encaps(self.k, _buf(ek), _buf(m), key, ct)
        return bytes(key), bytes(ct)

    def decaps(self, dk: bytes, ct: bytes) -> bytes:
        key = _out(32)
        self.lib.qrp_mlkem_decaps(self.k, _buf(dk), _buf(ct), key)
        return bytes(key)


class NativeMLDSA:
    """Scalar ML-DSA over the native core (same seams as pyref.mldsa_ref:
    keygen(xi), sign_internal(sk, m_prime, rnd), verify_internal)."""

    _LEVEL = {"ML-DSA-44": 2, "ML-DSA-65": 3, "ML-DSA-87": 5}

    def __init__(self, name: str):
        from ..pyref import mldsa_ref  # single authority for sizes

        self.lib = load()
        if self.lib is None:
            raise RuntimeError("native core unavailable")
        self.level = self._LEVEL[name]
        p = mldsa_ref.PARAMS[name]
        self.pk_len, self.sk_len, self.sig_len = p.pk_len, p.sk_len, p.sig_len

    def keygen(self, xi: bytes) -> tuple[bytes, bytes]:
        _expect(xi, 32, "xi")
        pk, sk = _out(self.pk_len), _out(self.sk_len)
        self.lib.qrp_mldsa_keygen(self.level, _buf(xi), pk, sk)
        return bytes(pk), bytes(sk)

    def sign_internal(self, sk: bytes, m_prime: bytes, rnd: bytes) -> bytes:
        _expect(sk, self.sk_len, "secret key")
        _expect(rnd, 32, "rnd")
        sig = _out(self.sig_len)
        ok = self.lib.qrp_mldsa_sign(
            self.level, _buf(sk), _buf(m_prime), len(m_prime), _buf(rnd), sig
        )
        if not ok:
            # Only reachable with a pathological/adversarial sk: the 16-bit
            # ExpandMask counter space was exhausted without an accept.
            raise RuntimeError("ML-DSA sign: rejection-sampling budget exhausted")
        return bytes(sig)

    def verify_internal(self, pk: bytes, m_prime: bytes, sig: bytes) -> bool:
        if len(pk) != self.pk_len or len(sig) != self.sig_len:
            return False
        return bool(
            self.lib.qrp_mldsa_verify(
                self.level, _buf(pk), _buf(m_prime), len(m_prime), _buf(sig)
            )
        )


class NativeSLHDSA:
    """Scalar SLH-DSA / SPHINCS+-SHA2 over the native core (same seams as
    pyref.slhdsa_ref: keygen(sk_seed, sk_prf, pk_seed),
    sign_internal(msg, sk, addrnd), verify_internal)."""

    _ID = {
        "SPHINCS+-SHA2-128s-simple": 0,
        "SPHINCS+-SHA2-128f-simple": 1,
        "SPHINCS+-SHA2-192s-simple": 2,
        "SPHINCS+-SHA2-192f-simple": 3,
        "SPHINCS+-SHA2-256s-simple": 4,
        "SPHINCS+-SHA2-256f-simple": 5,
    }

    def __init__(self, name: str):
        from ..pyref import slhdsa_ref  # single authority for sizes

        self.lib = load()
        if self.lib is None:
            raise RuntimeError("native core unavailable")
        self.param_id = self._ID[name]
        p = slhdsa_ref.PARAMS[name]
        self.n, self.sig_len = p.n, p.sig_len
        self.pk_len, self.sk_len = p.pk_len, p.sk_len

    def keygen(self, sk_seed: bytes, sk_prf: bytes, pk_seed: bytes) -> tuple[bytes, bytes]:
        for nm, s in (("sk_seed", sk_seed), ("sk_prf", sk_prf), ("pk_seed", pk_seed)):
            _expect(s, self.n, nm)
        pk, sk = _out(self.pk_len), _out(self.sk_len)
        self.lib.qrp_slhdsa_keygen(
            self.param_id, _buf(sk_seed), _buf(sk_prf), _buf(pk_seed), pk, sk
        )
        return bytes(pk), bytes(sk)

    def sign_internal(self, msg: bytes, sk: bytes, addrnd: bytes | None = None) -> bytes:
        _expect(sk, self.sk_len, "secret key")
        if addrnd is not None:
            _expect(addrnd, self.n, "addrnd")
        sig = _out(self.sig_len)
        self.lib.qrp_slhdsa_sign(
            self.param_id, _buf(sk), _buf(msg), len(msg),
            _buf(addrnd) if addrnd is not None else None, sig,
        )
        return bytes(sig)

    def verify_internal(self, msg: bytes, sig: bytes, pk: bytes) -> bool:
        if len(pk) != self.pk_len or len(sig) != self.sig_len:
            return False
        return bool(
            self.lib.qrp_slhdsa_verify(self.param_id, _buf(pk), _buf(msg), len(msg), _buf(sig))
        )


class NativeFrodoKEM:
    """Scalar FrodoKEM over the native core (same seams as pyref.frodo_ref:
    keygen(s, seedSE, z), encaps(pk, mu), decaps(sk, ct))."""

    _ID = {
        "FrodoKEM-640-AES": 0, "FrodoKEM-640-SHAKE": 1,
        "FrodoKEM-976-AES": 2, "FrodoKEM-976-SHAKE": 3,
        "FrodoKEM-1344-AES": 4, "FrodoKEM-1344-SHAKE": 5,
    }

    def __init__(self, name: str):
        from ..pyref import frodo_ref  # single authority for sizes

        self.lib = load()
        if self.lib is None:
            raise RuntimeError("native core unavailable")
        self.param_id = self._ID[name]
        p = frodo_ref.PARAMS[name]
        self.len_sec = p.len_sec
        self.pk_len, self.sk_len, self.ct_len = p.pk_len, p.sk_len, p.ct_len

    def keygen(self, s: bytes, seed_se: bytes, z: bytes) -> tuple[bytes, bytes]:
        for nm, v in (("s", s), ("seedSE", seed_se), ("z", z)):
            _expect(v, self.len_sec, nm)
        pk, sk = _out(self.pk_len), _out(self.sk_len)
        self.lib.qrp_frodo_keygen(self.param_id, _buf(s), _buf(seed_se), _buf(z), pk, sk)
        return bytes(pk), bytes(sk)

    def encaps(self, pk: bytes, mu: bytes) -> tuple[bytes, bytes]:
        _expect(pk, self.pk_len, "public key")
        _expect(mu, self.len_sec, "mu")
        ct, ss = _out(self.ct_len), _out(self.len_sec)
        self.lib.qrp_frodo_encaps(self.param_id, _buf(pk), _buf(mu), ct, ss)
        return bytes(ct), bytes(ss)

    def decaps(self, sk: bytes, ct: bytes) -> bytes:
        _expect(sk, self.sk_len, "secret key")
        _expect(ct, self.ct_len, "ciphertext")
        ss = _out(self.len_sec)
        self.lib.qrp_frodo_decaps(self.param_id, _buf(sk), _buf(ct), ss)
        return bytes(ss)


class NativeHQC:
    """Scalar HQC over the native core (same seams as pyref.hqc_ref:
    keygen(sk_seed, sigma, pk_seed), encaps(pk, m, salt), decaps(sk, ct))."""

    _ID = {"HQC-128": 0, "HQC-192": 1, "HQC-256": 2}

    def __init__(self, name: str):
        from ..pyref import hqc_ref  # single authority for sizes

        self.lib = load()
        if self.lib is None:
            raise RuntimeError("native core unavailable")
        self.param_id = self._ID[name]
        p = hqc_ref.PARAMS[name]
        self.k = p.k
        self.pk_len, self.sk_len = p.pk_len, p.sk_len
        self.ct_len, self.ss_len = p.ct_len, p.ss_len

    def keygen(self, sk_seed: bytes, sigma: bytes, pk_seed: bytes) -> tuple[bytes, bytes]:
        _expect(sk_seed, 40, "sk_seed")
        _expect(sigma, self.k, "sigma")
        _expect(pk_seed, 40, "pk_seed")
        pk, sk = _out(self.pk_len), _out(self.sk_len)
        self.lib.qrp_hqc_keygen(
            self.param_id, _buf(sk_seed), _buf(sigma), _buf(pk_seed), pk, sk
        )
        return bytes(pk), bytes(sk)

    def encaps(self, pk: bytes, m: bytes, salt: bytes) -> tuple[bytes, bytes]:
        _expect(pk, self.pk_len, "public key")
        _expect(m, self.k, "m")
        _expect(salt, 16, "salt")
        ct, ss = _out(self.ct_len), _out(self.ss_len)
        self.lib.qrp_hqc_encaps(self.param_id, _buf(pk), _buf(m), _buf(salt), ct, ss)
        return bytes(ct), bytes(ss)

    def decaps(self, sk: bytes, ct: bytes) -> bytes:
        _expect(sk, self.sk_len, "secret key")
        _expect(ct, self.ct_len, "ciphertext")
        ss = _out(self.ss_len)
        self.lib.qrp_hqc_decaps(self.param_id, _buf(sk), _buf(ct), ss)
        return bytes(ss)


def shake256(data: bytes, out_len: int) -> bytes:
    lib = load()
    if lib is None:
        raise RuntimeError("native core unavailable")
    out = _out(out_len)
    lib.qrp_shake256(_buf(data), len(data), out, out_len)
    return bytes(out)


def zeroize(buf: bytearray) -> None:
    """Best-effort secure wipe of a mutable buffer (reference analog:
    OQS_MEM_cleanse via vendor/oqs.py:383-390)."""
    lib = load()
    if lib is None:
        for i in range(len(buf)):
            buf[i] = 0
        return
    c = (ctypes.c_uint8 * len(buf)).from_buffer(buf)
    lib.qrp_zeroize(c, len(buf))


def wipe(*bufs) -> None:
    """End-of-life wipe for secret buffers of whatever type a provider
    handed back: ``bytearray`` through the native cleanse, writable
    array-likes (numpy) zero-filled in place, and immutable operands
    (``bytes``, jax device arrays) left to the GC — that last case is a
    documented CPython/XLA limitation, not a policy choice, and routing
    it through here still marks the lifetime boundary for qrlife's
    wipe-completeness check."""
    for buf in bufs:
        if isinstance(buf, bytearray):
            zeroize(buf)
        elif hasattr(buf, "dtype"):
            try:
                buf[...] = 0
            except (TypeError, ValueError):
                pass  # immutable device array: lifetime ends here, GC takes it
