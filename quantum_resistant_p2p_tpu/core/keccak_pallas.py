"""Pallas TPU kernel for the Keccak sponge — the production hash fast path.

Why a kernel at all: the pure-jnp sponge (core/keccak.py) materialises the
full batched 25-lane state between every one of the 24 rounds, so one
ML-KEM-768 encaps batch reads/writes ~38 MB of HBM per op (measured: 155 GB
per 4096-batch, wholly memory-bound).  This kernel keeps the entire state in
registers/VMEM for the whole absorb-permute-squeeze pipeline; HBM traffic
drops to the message bytes in and digest bytes out.

Layout: batch lives on the two *minor* dimensions — each of the 50 uint32
state words is an ``(8, 128)`` tile (sublanes x lanes, exactly one 32-bit
vector register) across 1024 sponge instances, so theta/chi xors and the
per-lane constant rotations are full-width VPU ops with zero register waste
(a ``(1, B)`` row layout measured 8x slower: 7/8 of every vreg idle).  The
24 rounds and the (static) absorb/squeeze block loops are fully unrolled at
trace time; rho/pi/iota constants are Python ints baked into the program.

Used by core/keccak.py when running on TPU for sponges up to
``MAX_BLOCKS_FUSED`` total blocks (covers every ML-KEM / ML-DSA / SLH-DSA
call site); longer sponges (FrodoKEM/HQC matrix expansion) stay on the
lax.scan jnp path.  Oracle: hashlib via tests/test_keccak.py, which runs
this kernel in interpret mode on CPU and natively on TPU.

Replaces (reference): the Keccak core inside vendored liboqs
(vendor/oqs.py:122-183), reached from every KEM/signature hot call.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .keccak import _PI_SRC, _RC, _RHO

#: sponges with more than this many total (absorb + squeeze) blocks fall back
#: to the jnp scan path — the fully-unrolled kernel would compile too slowly.
MAX_BLOCKS_FUSED = 16

#: sponges per grid step: 8 sublanes x 128 lanes = one vreg per state word.
_TS, _TL = 8, 128
BT = _TS * _TL


def _rotl(hi, lo, n: int):
    n %= 64
    if n == 0:
        return hi, lo
    if n >= 32:
        hi, lo = lo, hi
        n -= 32
        if n == 0:
            return hi, lo
    return (
        (hi << n) | (lo >> (32 - n)),  # qrkernel: wrapping — uint32 lane words: bits shifted past 32 drop by design, the rotation recovers them from the partner word
        (lo << n) | (hi >> (32 - n)),  # qrkernel: wrapping — same wrap-by-design rotation, low word
    )


def _f1600(sh: list, sl: list) -> tuple[list, list]:
    """One Keccak-f[1600] permutation over 50 (8, 128) uint32 tiles."""
    for rnd in range(24):
        # theta
        ch = [sh[x] ^ sh[x + 5] ^ sh[x + 10] ^ sh[x + 15] ^ sh[x + 20] for x in range(5)]
        cl = [sl[x] ^ sl[x + 5] ^ sl[x + 10] ^ sl[x + 15] ^ sl[x + 20] for x in range(5)]
        for x in range(5):
            rh, rl = _rotl(ch[(x + 1) % 5], cl[(x + 1) % 5], 1)
            dh, dl = ch[(x + 4) % 5] ^ rh, cl[(x + 4) % 5] ^ rl
            for y in range(5):
                sh[x + 5 * y] = sh[x + 5 * y] ^ dh
                sl[x + 5 * y] = sl[x + 5 * y] ^ dl
        # rho + pi
        bh, bl = [None] * 25, [None] * 25
        for dst in range(25):
            src = int(_PI_SRC[dst])
            bh[dst], bl[dst] = _rotl(sh[src], sl[src], int(_RHO[src]))
        # chi
        for y in range(5):
            row_h = [bh[x + 5 * y] for x in range(5)]
            row_l = [bl[x + 5 * y] for x in range(5)]
            for x in range(5):
                sh[x + 5 * y] = row_h[x] ^ (~row_h[(x + 1) % 5] & row_h[(x + 2) % 5])
                sl[x + 5 * y] = row_l[x] ^ (~row_l[(x + 1) % 5] & row_l[(x + 2) % 5])
        # iota
        sh[0] = sh[0] ^ jnp.uint32(int(_RC[rnd, 0]))
        sl[0] = sl[0] ^ jnp.uint32(int(_RC[rnd, 1]))
    return sh, sl


def absorb_block(in_hi: list, in_lo: list, rate_words: int) -> tuple[list, list]:
    """Single-block absorb: XOR ``rate_words`` lane words into a zero state
    and permute.  Shared preamble of the fused sampler kernels."""
    zero = jnp.zeros_like(in_hi[0])
    sh = [zero] * 25
    sl = [zero] * 25
    for w in range(rate_words):
        sh[w] = sh[w] ^ in_hi[w]
        sl[w] = sl[w] ^ in_lo[w]
    return _f1600(sh, sl)


def block_bytes(sh: list, sl: list, rate_words: int) -> list:
    """Extract the ``8 * rate_words`` rate bytes of a sponge block.

    Input: 25-element hi/lo lane-word tile lists; output: uint32 tiles with
    one byte each (little-endian within each 64-bit lane, matching
    ``core.keccak._words_to_bytes``).  Shared by the fused sampler kernels
    (kem/mlkem_pallas.py, sig/mldsa_pallas.py).
    """
    byts = []
    for w in range(rate_words):
        for b in range(8):
            word = sl[w] if b < 4 else sh[w]
            byts.append((word >> (8 * (b % 4))) & 0xFF)
    return byts


def sampler_call(kernel, rate_words: int, n_out: int, in_hi: jax.Array,
                 in_lo: jax.Array, *, interpret: bool = False) -> jax.Array:
    """Shared launcher for fused sampler kernels: words in, int32 regs out.

    Args:
      kernel: pallas kernel (in_hi_ref, in_lo_ref, out_ref) over
        (rate_words|n_out, 8, 128) uint32/int32 blocks.
      in_hi/in_lo: (rate_words, B) uint32 padded seed-block lane words,
        batch minor (B need not be a multiple of the 1024-sponge tile).

    Returns:
      (n_out, B) int32.
    """
    in_words, b = in_hi.shape
    assert in_words == rate_words
    bp = -(-b // BT) * BT
    if bp != b:
        pad = ((0, 0), (0, bp - b))
        in_hi = jnp.pad(in_hi, pad)
        in_lo = jnp.pad(in_lo, pad)
    in_hi = in_hi.reshape(in_words, bp // _TL, _TL)
    in_lo = in_lo.reshape(in_words, bp // _TL, _TL)
    out = pl.pallas_call(
        kernel,
        grid=(bp // BT,),
        in_specs=[
            pl.BlockSpec((in_words, _TS, _TL), lambda i: (0, i, 0)),
            pl.BlockSpec((in_words, _TS, _TL), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((n_out, _TS, _TL), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_out, bp // _TL, _TL), jnp.int32),
        interpret=interpret,
    )(in_hi, in_lo)
    return out.reshape(n_out, bp)[:, :b]


def _sponge_kernel(in_hi_ref, in_lo_ref, out_hi_ref, out_lo_ref,
                   *, rate_words: int, n_abs: int, n_sq: int):
    zero = jnp.zeros((_TS, _TL), jnp.uint32)
    sh = [zero] * 25
    sl = [zero] * 25
    for blk in range(n_abs):
        for w in range(rate_words):
            r = blk * rate_words + w
            sh[w] = sh[w] ^ in_hi_ref[r]
            sl[w] = sl[w] ^ in_lo_ref[r]
        sh, sl = _f1600(sh, sl)
    for blk in range(n_sq):
        for w in range(rate_words):
            r = blk * rate_words + w
            out_hi_ref[r] = sh[w]
            out_lo_ref[r] = sl[w]
        if blk + 1 < n_sq:
            sh, sl = _f1600(sh, sl)


@functools.partial(jax.jit, static_argnames=("rate_words", "n_abs", "n_sq", "interpret"))
def sponge_words(in_hi: jax.Array, in_lo: jax.Array, *, rate_words: int,
                 n_abs: int, n_sq: int, interpret: bool = False):
    """Padded-message sponge over word-transposed batches.

    Args:
      in_hi/in_lo: (n_abs*rate_words, B) uint32 — padded message lane words,
        batch on the minor axis (B need not be a multiple of the tile).
      rate_words: sponge rate in 64-bit lanes (21 SHAKE128, 17 SHAKE256,
        17 SHA3-256, 9 SHA3-512).
      n_abs/n_sq: number of absorb / squeeze blocks (static).

    Returns:
      (out_hi, out_lo): (n_sq*rate_words, B) uint32 squeezed lane words.
    """
    in_words, b = in_hi.shape
    assert in_words == n_abs * rate_words
    bp = -(-b // BT) * BT
    if bp != b:
        pad = ((0, 0), (0, bp - b))
        in_hi = jnp.pad(in_hi, pad)
        in_lo = jnp.pad(in_lo, pad)
    # (W, B) -> (W, B/128, 128): sponge j*128+l sits at [:, j, l]; a grid step
    # covers 8 consecutive j (one full vreg tile per state word).
    in_hi = in_hi.reshape(in_words, bp // _TL, _TL)
    in_lo = in_lo.reshape(in_words, bp // _TL, _TL)
    out_words = n_sq * rate_words
    kern = functools.partial(
        _sponge_kernel, rate_words=rate_words, n_abs=n_abs, n_sq=n_sq
    )
    out_hi, out_lo = pl.pallas_call(
        kern,
        grid=(bp // BT,),
        in_specs=[
            pl.BlockSpec((in_words, _TS, _TL), lambda i: (0, i, 0)),
            pl.BlockSpec((in_words, _TS, _TL), lambda i: (0, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((out_words, _TS, _TL), lambda i: (0, i, 0)),
            pl.BlockSpec((out_words, _TS, _TL), lambda i: (0, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((out_words, bp // _TL, _TL), jnp.uint32),
            jax.ShapeDtypeStruct((out_words, bp // _TL, _TL), jnp.uint32),
        ],
        interpret=interpret,
    )(in_hi, in_lo)
    out_hi = out_hi.reshape(out_words, bp)[:, :b]
    out_lo = out_lo.reshape(out_words, bp)[:, :b]
    return out_hi, out_lo
