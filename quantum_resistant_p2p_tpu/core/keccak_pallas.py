"""Pallas TPU kernel for batched Keccak-f[1600].

The jnp version (core.keccak) lowers to an XLA fori_loop whose 24-round body
materialises intermediate 25-lane stacks each round.  This kernel keeps the
whole 50-word (25 lanes x hi/lo uint32) state resident in VMEM for all 24
rounds, with the batch on the 128-lane axis — one grid cell per 128 sponges:

  layout:  state[56, B] int32 — rows 0..24 hi words, rows 28..52 lo words
           (row count padded to a multiple of 8 for int32 sublane tiling)
  grid:    (B // 128,) — each cell permutes its 128-sponge block in place

Rotations are per-lane compile-time constants, so the round body unrolls into
pure VPU bitwise ops with zero gathers.  Use ``keccak_f1600`` below as a
drop-in for core.keccak.keccak_f1600 on (batch, 25) uint32 pairs; it falls
back to the jnp implementation off-TPU (Pallas interpret mode is only used in
tests).

Reference for parity: same permutation the vendored liboqs implements in C
(reference vendor/oqs.py loads it; every KEM/sig depends on it).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import keccak as _jnp_keccak

try:  # pallas import can fail on exotic platforms; fall back silently
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

_RHO = _jnp_keccak._rho_offsets()
_PI_SRC = _jnp_keccak._pi_source()
_RC = _jnp_keccak._round_constants()

_ROWS = 56  # 25 hi + pad + 25 lo, multiple of 8
_LO_OFF = 28
_BLOCK_B = 128


def _rotl_pair(hi, lo, n: int):
    n %= 64
    if n == 0:
        return hi, lo
    if n >= 32:
        hi, lo = lo, hi
        n -= 32
        if n == 0:
            return hi, lo
    return (hi << n) | (lo >> (32 - n)), (lo << n) | (hi >> (32 - n))


def _kernel(state_ref, out_ref):
    # load the full 56x128 block once; all rounds run on register/VMEM values
    s = state_ref[:].astype(jnp.uint32)
    hi = [s[i, :] for i in range(25)]
    lo = [s[_LO_OFF + i, :] for i in range(25)]
    for rnd in range(24):
        # theta
        ch = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20] for x in range(5)]
        cl = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20] for x in range(5)]
        for x in range(5):
            r1h, r1l = _rotl_pair(ch[(x + 1) % 5], cl[(x + 1) % 5], 1)
            dh = ch[(x + 4) % 5] ^ r1h
            dl = cl[(x + 4) % 5] ^ r1l
            for y in range(5):
                hi[x + 5 * y] = hi[x + 5 * y] ^ dh
                lo[x + 5 * y] = lo[x + 5 * y] ^ dl
        # rho + pi
        bh = [None] * 25
        bl = [None] * 25
        for dst in range(25):
            src = int(_PI_SRC[dst])
            bh[dst], bl[dst] = _rotl_pair(hi[src], lo[src], int(_RHO[src]))
        # chi
        for y in range(5):
            row_h = [bh[x + 5 * y] for x in range(5)]
            row_l = [bl[x + 5 * y] for x in range(5)]
            for x in range(5):
                hi[x + 5 * y] = row_h[x] ^ (~row_h[(x + 1) % 5] & row_h[(x + 2) % 5])
                lo[x + 5 * y] = row_l[x] ^ (~row_l[(x + 1) % 5] & row_l[(x + 2) % 5])
        # iota
        hi[0] = hi[0] ^ jnp.uint32(int(_RC[rnd, 0]))
        lo[0] = lo[0] ^ jnp.uint32(int(_RC[rnd, 1]))
    out = jnp.zeros((_ROWS, _BLOCK_B), jnp.uint32)
    for i in range(25):
        out = out.at[i, :].set(hi[i])
        out = out.at[_LO_OFF + i, :].set(lo[i])
    out_ref[:] = out.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _permute_blocks(packed: jax.Array, interpret: bool = False) -> jax.Array:
    """(56, B) int32 with B % 128 == 0 -> permuted, same shape."""
    nb = packed.shape[1] // _BLOCK_B
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct(packed.shape, jnp.int32),
        grid=(nb,),
        in_specs=[pl.BlockSpec((_ROWS, _BLOCK_B), lambda i: (0, i))],
        out_specs=pl.BlockSpec((_ROWS, _BLOCK_B), lambda i: (0, i)),
        interpret=interpret,
    )(packed)


def keccak_f1600(hi: jax.Array, lo: jax.Array, interpret: bool = False):
    """Drop-in for core.keccak.keccak_f1600 on 2-D (batch, 25) uint32 pairs.

    Pads the batch up to a multiple of 128 and runs the Pallas kernel; use on
    TPU (or interpret=True in tests).
    """
    if not _HAVE_PALLAS:
        return _jnp_keccak.keccak_f1600(hi, lo)
    b = hi.shape[0]
    bpad = -(-b // _BLOCK_B) * _BLOCK_B
    packed = jnp.zeros((_ROWS, bpad), jnp.int32)
    packed = packed.at[:25, :b].set(hi.astype(jnp.int32).T)
    packed = packed.at[_LO_OFF : _LO_OFF + 25, :b].set(lo.astype(jnp.int32).T)
    out = _permute_blocks(packed, interpret=interpret)
    return (
        out[:25, :b].T.astype(jnp.uint32),
        out[_LO_OFF : _LO_OFF + 25, :b].T.astype(jnp.uint32),
    )


def use_pallas_on_tpu() -> bool:
    """True when the default backend is a TPU (where the kernel is worth it)."""
    try:
        return _HAVE_PALLAS and jax.default_backend() not in ("cpu",)
    except Exception:  # pragma: no cover
        return False
