"""Primitive TPU kernels: Keccak sponge, SHA-256, NTT, samplers, byte codecs."""
