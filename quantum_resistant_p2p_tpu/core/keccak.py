"""Batched Keccak-f[1600] permutation and SHA-3 / SHAKE sponges in JAX.

TPU-native design notes
-----------------------
TPUs have no 64-bit integer lanes, so each Keccak lane is emulated as a pair of
uint32 arrays ``(hi, lo)``; a 64-bit rotate becomes two shift/or pairs (or a
swap for rotations >= 32).  The 25-lane state is kept as two ``(..., 25)``
uint32 arrays so the whole sponge vectorises over an arbitrary leading batch
shape — thousands of independent hashes run in lockstep on the VPU.

All message and output lengths are static Python ints, so every function here
traces to a fixed-shape XLA program (jit/vmap/pjit friendly; no dynamic
shapes).  The 24 rounds run under ``lax.fori_loop`` with the round constants
held in a (24, 2) uint32 table; the rho/pi lane permutation is unrolled over
the 25 lanes with per-lane constant shifts.

Replaces (reference): the Keccak inside vendored liboqs — loaded via
``vendor/oqs.py:122-183`` and used by every KEM/signature in
``crypto/key_exchange.py`` / ``crypto/signatures.py``.  Oracle for tests:
``hashlib`` (sha3_256 / sha3_512 / shake_128 / shake_256).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# --------------------------------------------------------------------------
# Constants (computed, not transcribed, to avoid copy errors; verified against
# hashlib by tests/test_keccak.py).
# --------------------------------------------------------------------------

# Flat lane index convention: l = x + 5*y  (x = column, y = row).


def _rho_offsets() -> np.ndarray:
    r = np.zeros(25, dtype=np.int64)
    x, y = 1, 0
    for t in range(24):
        r[x + 5 * y] = ((t + 1) * (t + 2) // 2) % 64
        x, y = y, (2 * x + 3 * y) % 5
    return r


def _pi_source() -> np.ndarray:
    """src[dst] such that after rho+pi, out[dst] = rot(in[src], RHO[src])."""
    src = np.zeros(25, dtype=np.int64)
    for x in range(5):
        for y in range(5):
            dst = y + 5 * ((2 * x + 3 * y) % 5)
            src[dst] = x + 5 * y
    return src


def _round_constants() -> np.ndarray:
    """(24, 2) uint32: [:, 0] = hi word, [:, 1] = lo word."""

    def rc_bit(t: int) -> int:
        if t % 255 == 0:
            return 1
        reg = 1
        for _ in range(t % 255):
            reg <<= 1
            if reg & 0x100:
                reg ^= 0x171
        return reg & 1

    out = np.zeros((24, 2), dtype=np.uint64)
    for ir in range(24):
        rc = 0
        for j in range(7):
            if rc_bit(j + 7 * ir):
                rc |= 1 << (2**j - 1)
        out[ir, 0] = rc >> 32
        out[ir, 1] = rc & 0xFFFFFFFF
    return out.astype(np.uint32)


_RHO = _rho_offsets()
_PI_SRC = _pi_source()
_RC = _round_constants()


def _rotl_pair(hi, lo, n: int):
    """Rotate-left a (hi, lo) uint32 pair by constant n (0..63)."""
    n = n % 64
    if n == 0:
        return hi, lo
    if n >= 32:
        hi, lo = lo, hi
        n -= 32
        if n == 0:
            return hi, lo
    return (
        (hi << n) | (lo >> (32 - n)),
        (lo << n) | (hi >> (32 - n)),
    )


def keccak_f1600(hi: jax.Array, lo: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Apply Keccak-f[1600] to a batched state.

    Args:
      hi, lo: uint32 arrays of shape (..., 25) — high/low words of the 25
        64-bit lanes, flat-indexed as l = x + 5*y.
    """
    rc = jnp.asarray(_RC)

    def round_fn(i, state):
        hi, lo = state
        # ---- theta -------------------------------------------------------
        h5 = hi.reshape(hi.shape[:-1] + (5, 5))  # [..., y, x]
        l5 = lo.reshape(lo.shape[:-1] + (5, 5))
        ch = h5[..., 0, :] ^ h5[..., 1, :] ^ h5[..., 2, :] ^ h5[..., 3, :] ^ h5[..., 4, :]
        cl = l5[..., 0, :] ^ l5[..., 1, :] ^ l5[..., 2, :] ^ l5[..., 3, :] ^ l5[..., 4, :]
        # C[x+1] rotated left by 1
        r1h = (ch << 1) | (cl >> 31)
        r1l = (cl << 1) | (ch >> 31)
        dh = jnp.roll(ch, 1, axis=-1) ^ jnp.roll(r1h, -1, axis=-1)
        dl = jnp.roll(cl, 1, axis=-1) ^ jnp.roll(r1l, -1, axis=-1)
        h5 = h5 ^ dh[..., None, :]
        l5 = l5 ^ dl[..., None, :]
        hi = h5.reshape(hi.shape)
        lo = l5.reshape(lo.shape)
        # ---- rho + pi (unrolled: constant shift per lane) ----------------
        bh, bl = [], []
        for dst in range(25):
            src = int(_PI_SRC[dst])
            rh, rl = _rotl_pair(hi[..., src], lo[..., src], int(_RHO[src]))
            bh.append(rh)
            bl.append(rl)
        hi = jnp.stack(bh, axis=-1)
        lo = jnp.stack(bl, axis=-1)
        # ---- chi ---------------------------------------------------------
        h5 = hi.reshape(hi.shape[:-1] + (5, 5))
        l5 = lo.reshape(lo.shape[:-1] + (5, 5))
        h5 = h5 ^ (~jnp.roll(h5, -1, axis=-1) & jnp.roll(h5, -2, axis=-1))
        l5 = l5 ^ (~jnp.roll(l5, -1, axis=-1) & jnp.roll(l5, -2, axis=-1))
        hi = h5.reshape(hi.shape)
        lo = l5.reshape(lo.shape)
        # ---- iota --------------------------------------------------------
        hi = hi.at[..., 0].set(hi[..., 0] ^ rc[i, 0])
        lo = lo.at[..., 0].set(lo[..., 0] ^ rc[i, 1])
        return hi, lo

    return lax.fori_loop(0, 24, round_fn, (hi, lo))


# --------------------------------------------------------------------------
# Byte <-> lane packing (little-endian within each 64-bit lane).
# --------------------------------------------------------------------------


def _bytes_to_words(block: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(..., 8*n) uint8 -> ((..., n), (..., n)) uint32 hi/lo lane words."""
    b = block.astype(jnp.uint32).reshape(block.shape[:-1] + (-1, 8))
    lo = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)
    hi = b[..., 4] | (b[..., 5] << 8) | (b[..., 6] << 16) | (b[..., 7] << 24)
    return hi, lo


def pad_single_block(data: jax.Array, rate: int, ds_byte: int) -> jax.Array:
    """Keccak-pad a sub-rate message to one ``rate``-byte block.

    (..., L) uint8 with L < rate -> (..., rate) uint8: message, then the
    domain-separation byte, zeros, and 0x80 in the final byte.  Single
    source of truth for callers that feed one-block sponges directly to a
    Pallas kernel (kem/mlkem.py's fused SampleNTT path) instead of going
    through :func:`sponge`.
    """
    msg_len = data.shape[-1]
    assert msg_len < rate, (msg_len, rate)
    block = jnp.zeros(data.shape[:-1] + (rate,), jnp.uint8)
    block = block.at[..., :msg_len].set(jnp.asarray(data, jnp.uint8))
    block = block.at[..., msg_len].set(jnp.uint8(ds_byte))
    return block.at[..., rate - 1].set(block[..., rate - 1] | jnp.uint8(0x80))


def seed_block_words(seeds: jax.Array, rate: int, ds_byte: int):
    """Flatten, pad, and word-transpose XOF seeds for a fused sampler kernel.

    (..., L) uint8 seeds -> ((rate//8, B), (rate//8, B)) uint32 hi/lo lane
    words with the batch flattened onto the minor axis, plus the original
    batch shape — the input convention of keccak_pallas.sampler_call.
    """
    batch = seeds.shape[:-1]
    b = int(np.prod(batch)) if batch else 1
    flat = jnp.asarray(seeds, jnp.uint8).reshape(b, seeds.shape[-1])
    ph, plo = _bytes_to_words(pad_single_block(flat, rate, ds_byte))
    return ph.T, plo.T, batch


def _words_to_bytes(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """((..., n), (..., n)) uint32 -> (..., 8*n) uint8."""
    parts = [
        lo & 0xFF, (lo >> 8) & 0xFF, (lo >> 16) & 0xFF, (lo >> 24) & 0xFF,
        hi & 0xFF, (hi >> 8) & 0xFF, (hi >> 16) & 0xFF, (hi >> 24) & 0xFF,
    ]
    out = jnp.stack(parts, axis=-1).astype(jnp.uint8)
    return out.reshape(out.shape[:-2] + (-1,))


# --------------------------------------------------------------------------
# Sponge
# --------------------------------------------------------------------------


def _use_pallas() -> bool:
    """Pallas fast path on real TPU; pure-jnp elsewhere (tests run on CPU)."""
    import os

    flag = os.environ.get("QRP2P_PALLAS", "auto")
    if flag == "0":
        return False
    if flag == "1":
        return True
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:  # pragma: no cover  # qrlint: disable=broad-except  — backend probe: jax without a functioning platform means "no TPU", the false return IS the handling
        return False


def sponge(data: jax.Array, rate: int, ds_byte: int, out_len: int) -> jax.Array:
    """Keccak sponge with static lengths.

    Args:
      data: (..., L) uint8 message (L static; any leading batch shape).
      rate: rate in bytes (168 SHAKE128, 136 SHAKE256/SHA3-256, 72 SHA3-512).
      ds_byte: domain-separation byte (0x1F for SHAKE, 0x06 for SHA3).
      out_len: number of output bytes (static).

    Returns:
      (..., out_len) uint8.
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    batch = data.shape[:-1]
    msg_len = data.shape[-1]
    nblocks = msg_len // rate + 1
    padded_len = nblocks * rate

    padded = jnp.zeros(batch + (padded_len,), dtype=jnp.uint8)
    padded = lax.dynamic_update_slice_in_dim(padded, data, 0, axis=-1) if msg_len else padded
    padded = padded.at[..., msg_len].set(jnp.uint8(ds_byte))
    padded = padded.at[..., padded_len - 1].set(padded[..., padded_len - 1] | jnp.uint8(0x80))

    out_nblocks_total = -(-out_len // rate)
    if nblocks + out_nblocks_total <= 16 and _use_pallas():
        from . import keccak_pallas  # deferred: pallas import

        if nblocks + out_nblocks_total <= keccak_pallas.MAX_BLOCKS_FUSED:
            b = int(np.prod(batch)) if batch else 1
            ph, plo = _bytes_to_words(padded.reshape(b, padded_len))
            oh, ol = keccak_pallas.sponge_words(
                ph.T, plo.T, rate_words=rate // 8, n_abs=nblocks,
                n_sq=out_nblocks_total,
            )
            out = _words_to_bytes(oh.T, ol.T)
            return out.reshape(batch + (-1,))[..., :out_len]

    hi = jnp.zeros(batch + (25,), dtype=jnp.uint32)
    lo = jnp.zeros(batch + (25,), dtype=jnp.uint32)
    nwords = rate // 8

    def absorb(state, block):
        hi, lo = state
        bh, bl = _bytes_to_words(block)
        hi = hi.at[..., :nwords].set(hi[..., :nwords] ^ bh)
        lo = lo.at[..., :nwords].set(lo[..., :nwords] ^ bl)
        return keccak_f1600(hi, lo)

    # Unroll short sponges (lower dispatch overhead); lax.scan long ones so
    # graph size / compile time stays O(1) in message length — FrodoKEM and
    # HQC absorb/squeeze hundreds of blocks.
    if nblocks <= 4:
        for b in range(nblocks):
            hi, lo = absorb((hi, lo), padded[..., b * rate : (b + 1) * rate])
    else:
        blocks = jnp.moveaxis(
            padded.reshape(batch + (nblocks, rate)), -2, 0
        )  # (nblocks, ..., rate)
        (hi, lo), _ = lax.scan(lambda s, blk: (absorb(s, blk), None), (hi, lo), blocks)

    out_nblocks = -(-out_len // rate)
    if out_nblocks <= 4:
        out_blocks = []
        for b in range(out_nblocks):
            out_blocks.append(_words_to_bytes(hi[..., :nwords], lo[..., :nwords]))
            if b + 1 < out_nblocks:
                hi, lo = keccak_f1600(hi, lo)
        out = (
            jnp.concatenate(out_blocks, axis=-1) if len(out_blocks) > 1 else out_blocks[0]
        )
    else:
        def squeeze(state, _):
            hi, lo = state
            blk = _words_to_bytes(hi[..., :nwords], lo[..., :nwords])
            return keccak_f1600(hi, lo), blk

        _, blks = lax.scan(squeeze, (hi, lo), None, length=out_nblocks)
        out = jnp.moveaxis(blks, 0, -2).reshape(batch + (out_nblocks * rate,))
    return out[..., :out_len]


def sponge_varlen(data: jax.Array, lengths: jax.Array, rate: int, ds_byte: int,
                  out_len: int) -> jax.Array:
    """Keccak sponge over per-lane VARIABLE-length messages.

    The fixed-shape :func:`sponge` bakes the message length into the traced
    program, which is right for every crypto-internal hash (their lengths
    are parameters of the algorithm).  The fused handshake programs
    (``fused_ops``) sign protocol transcripts whose JSON tail — peer ids,
    timestamp repr — differs per lane, so the absorb must take the true
    byte length as a traced operand:

    * ``data`` is a (..., LMAX) uint8 buffer; bytes at index >= ``lengths``
      are ignored (masked to zero before padding, so callers may leave
      garbage there).
    * the domain byte lands at index ``lengths`` and 0x80 at the end of the
      block containing it, both via one-hot selects;
    * the absorb scans over the maximal block count, applying the
      permutation result only to lanes whose message reaches that block —
      lanes with shorter messages carry their final state through unchanged.

    Output matches ``hashlib`` byte-for-byte for every length <= LMAX
    (tests/test_keccak.py sweeps the block boundaries).
    """
    data = jnp.asarray(data, jnp.uint8)
    batch = data.shape[:-1]
    lmax = data.shape[-1]
    mlen = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), batch)
    nblocks = lmax // rate + 1  # always room for the ds byte when mlen == lmax
    padded_len = nblocks * rate
    idx = jnp.arange(padded_len, dtype=jnp.int32)
    buf = jnp.zeros(batch + (padded_len,), dtype=jnp.uint8)
    buf = lax.dynamic_update_slice_in_dim(buf, data, 0, axis=-1) if lmax else buf
    ml = mlen[..., None]
    buf = jnp.where(idx < ml, buf, jnp.uint8(0))
    buf = buf ^ jnp.where(idx == ml, jnp.uint8(ds_byte), jnp.uint8(0))
    last_block = mlen // rate  # block index holding the ds byte
    fin = (last_block[..., None] + 1) * rate - 1
    # ds and 0x80 share a byte only when mlen % rate == rate-1; their bits
    # are disjoint so xor == the spec's or
    buf = buf ^ jnp.where(idx == fin, jnp.uint8(0x80), jnp.uint8(0))

    nwords = rate // 8
    hi = jnp.zeros(batch + (25,), dtype=jnp.uint32)
    lo = jnp.zeros(batch + (25,), dtype=jnp.uint32)
    blocks = jnp.moveaxis(buf.reshape(batch + (nblocks, rate)), -2, 0)

    def absorb(state, xs):
        hi, lo = state
        blk, i = xs
        bh, bl = _bytes_to_words(blk)
        nh = hi.at[..., :nwords].set(hi[..., :nwords] ^ bh)
        nl = lo.at[..., :nwords].set(lo[..., :nwords] ^ bl)
        nh, nl = keccak_f1600(nh, nl)
        take = (i <= last_block)[..., None]
        return (jnp.where(take, nh, hi), jnp.where(take, nl, lo)), None

    (hi, lo), _ = lax.scan(
        absorb, (hi, lo), (blocks, jnp.arange(nblocks, dtype=jnp.int32))
    )

    out_nblocks = -(-out_len // rate)
    out_blocks = []
    for b in range(out_nblocks):
        out_blocks.append(_words_to_bytes(hi[..., :nwords], lo[..., :nwords]))
        if b + 1 < out_nblocks:
            hi, lo = keccak_f1600(hi, lo)
    out = (
        jnp.concatenate(out_blocks, axis=-1) if len(out_blocks) > 1 else out_blocks[0]
    )
    return out[..., :out_len]


@functools.partial(jax.jit, static_argnums=(2,))
def shake256_varlen(data: jax.Array, lengths: jax.Array, out_len: int) -> jax.Array:
    """(..., LMAX) uint8 + (...,) int32 true lengths -> (..., out_len) uint8."""
    return sponge_varlen(data, lengths, 136, 0x1F, out_len)


@functools.partial(jax.jit, static_argnums=(1,))
def shake128(data: jax.Array, out_len: int) -> jax.Array:
    return sponge(data, 168, 0x1F, out_len)


@functools.partial(jax.jit, static_argnums=(1,))
def shake256(data: jax.Array, out_len: int) -> jax.Array:
    return sponge(data, 136, 0x1F, out_len)


@jax.jit
def sha3_256(data: jax.Array) -> jax.Array:
    return sponge(data, 136, 0x06, 32)


@jax.jit
def sha3_512(data: jax.Array) -> jax.Array:
    return sponge(data, 72, 0x06, 64)
