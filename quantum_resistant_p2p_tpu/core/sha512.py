"""Batched SHA-512 in JAX — 64-bit words emulated as uint32 (hi, lo) pairs.

Same emulation strategy as ``core.keccak`` (TPUs have no 64-bit lanes): each
of the 8 state words and 16 schedule words is a pair of uint32 arrays; 64-bit
addition is add-with-carry, rotations are shift/or pairs (or swaps for
n >= 32).  All lengths static -> fixed-shape XLA programs over any leading
batch shape.

Needed by sig.sphincs for the 192/256-bit SPHINCS+-SHA2 parameter sets, whose
H / T_l / H_msg use SHA-512 (FIPS 205 §11.2; reference behavior inside liboqs,
crypto/signatures.py:208-212).  Oracle: hashlib.sha512.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import keccak  # _use_pallas: shared TPU-vs-CPU gate

_K64 = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F, 0xE9B5DBA58189DBBC,
    0x3956C25BF348B538, 0x59F111F1B605D019, 0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118,
    0xD807AA98A3030242, 0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235, 0xC19BF174CF692694,
    0xE49B69C19EF14AD2, 0xEFBE4786384F25E3, 0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65,
    0x2DE92C6F592B0275, 0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F, 0xBF597FC7BEEF0EE4,
    0xC6E00BF33DA88FC2, 0xD5A79147930AA725, 0x06CA6351E003826F, 0x142929670A0E6E70,
    0x27B70A8546D22FFC, 0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6, 0x92722C851482353B,
    0xA2BFE8A14CF10364, 0xA81A664BBC423001, 0xC24B8B70D0F89791, 0xC76C51A30654BE30,
    0xD192E819D6EF5218, 0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99, 0x34B0BCB5E19B48A8,
    0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB, 0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3,
    0x748F82EE5DEFB2FC, 0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915, 0xC67178F2E372532B,
    0xCA273ECEEA26619C, 0xD186B8C721C0C207, 0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178,
    0x06F067AA72176FBA, 0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC, 0x431D67C49C100D4C,
    0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A, 0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]
_KH = np.array([k >> 32 for k in _K64], dtype=np.uint32)
_KL = np.array([k & 0xFFFFFFFF for k in _K64], dtype=np.uint32)

_H64 = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]
_H0H = np.array([h >> 32 for h in _H64], dtype=np.uint32)
_H0L = np.array([h & 0xFFFFFFFF for h in _H64], dtype=np.uint32)


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _rotr64(h, l, n: int):
    if n >= 32:
        h, l = l, h
        n -= 32
    if n == 0:
        return h, l
    return (h >> n) | (l << (32 - n)), (l >> n) | (h << (32 - n))


def _shr64(h, l, n: int):
    if n >= 32:
        return jnp.zeros_like(h), h >> (n - 32)
    return h >> n, (l >> n) | (h << (32 - n))


def _xor3(a, b, c):
    return a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1]


def _block_words(block: jax.Array):
    """(..., 128) uint8 -> ((..., 16), (..., 16)) uint32 BE word pairs."""
    b = block.astype(jnp.uint32).reshape(block.shape[:-1] + (16, 8))
    hi = (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]
    lo = (b[..., 4] << 24) | (b[..., 5] << 16) | (b[..., 6] << 8) | b[..., 7]
    return hi, lo


#: below this flat batch the Pallas kernel's 1024-instance tile padding
#: wastes more than the jnp path costs (same policy as core/sha256.py)
_PALLAS_MIN_BATCH = 256


def compress(state, block: jax.Array):
    """state ((..., 8), (..., 8)) uint32 pair, block (..., 128) uint8."""
    sh, sl = state
    batch = sh.shape[:-1]
    flat = int(np.prod(batch)) if batch else 1
    if flat >= _PALLAS_MIN_BATCH and keccak._use_pallas():
        from . import sha512_pallas  # deferred: pallas import

        bh, bl = _block_words(jnp.asarray(block, jnp.uint8))
        oh, ol = sha512_pallas.compress_words(
            sh.reshape(flat, 8).T,
            sl.reshape(flat, 8).T,
            bh.reshape(flat, 16).T,
            bl.reshape(flat, 16).T,
        )
        return oh.T.reshape(batch + (8,)), ol.T.reshape(batch + (8,))

    wh, wl = _block_words(block)
    kh, kl = jnp.asarray(_KH), jnp.asarray(_KL)

    def round_fn(t, carry):
        vh, vl, wh, wl = carry
        a = (vh[..., 0], vl[..., 0]); b = (vh[..., 1], vl[..., 1])
        c = (vh[..., 2], vl[..., 2]); d = (vh[..., 3], vl[..., 3])
        e = (vh[..., 4], vl[..., 4]); f = (vh[..., 5], vl[..., 5])
        g = (vh[..., 6], vl[..., 6]); h = (vh[..., 7], vl[..., 7])
        s1 = _xor3(_rotr64(*e, 14), _rotr64(*e, 18), _rotr64(*e, 41))
        ch = (e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1])
        t1 = _add64(*h, *s1)
        t1 = _add64(*t1, *ch)
        t1 = _add64(*t1, kh[t], kl[t])
        t1 = _add64(*t1, wh[..., 0], wl[..., 0])
        s0 = _xor3(_rotr64(*a, 28), _rotr64(*a, 34), _rotr64(*a, 39))
        maj = (
            (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
            (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
        )
        t2 = _add64(*s0, *maj)
        new_a = _add64(*t1, *t2)
        new_e = _add64(*d, *t1)
        vh = jnp.stack([new_a[0], a[0], b[0], c[0], new_e[0], e[0], f[0], g[0]], axis=-1)
        vl = jnp.stack([new_a[1], a[1], b[1], c[1], new_e[1], e[1], f[1], g[1]], axis=-1)
        # schedule: w16 = sig1(w14) + w9 + sig0(w1) + w0
        w1 = (wh[..., 1], wl[..., 1]); w9 = (wh[..., 9], wl[..., 9])
        w14 = (wh[..., 14], wl[..., 14])
        sig0 = _xor3(_rotr64(*w1, 1), _rotr64(*w1, 8), _shr64(*w1, 7))
        sig1 = _xor3(_rotr64(*w14, 19), _rotr64(*w14, 61), _shr64(*w14, 6))
        w16 = _add64(*sig1, *w9)
        w16 = _add64(*w16, *sig0)
        w16 = _add64(*w16, wh[..., 0], wl[..., 0])
        wh = jnp.concatenate([wh[..., 1:], w16[0][..., None]], axis=-1)
        wl = jnp.concatenate([wl[..., 1:], w16[1][..., None]], axis=-1)
        return vh, vl, wh, wl

    vh, vl, _, _ = lax.fori_loop(0, 80, round_fn, (sh, sl, wh, wl))
    return _add64(sh, sl, vh, vl)


def init_state(batch_shape: tuple[int, ...] = ()):
    return (
        jnp.broadcast_to(jnp.asarray(_H0H), batch_shape + (8,)),
        jnp.broadcast_to(jnp.asarray(_H0L), batch_shape + (8,)),
    )


def _pad(data: jax.Array, prefix_blocks: int = 0) -> jax.Array:
    msg_len = data.shape[-1]
    total_bits = (prefix_blocks * 128 + msg_len) * 8
    pad_len = (111 - msg_len) % 128 + 17
    tail = np.zeros(pad_len, dtype=np.uint8)
    tail[0] = 0x80
    tail[-8:] = np.frombuffer(np.uint64(total_bits).byteswap().tobytes(), np.uint8)
    tail_b = jnp.broadcast_to(jnp.asarray(tail), data.shape[:-1] + (pad_len,))
    return jnp.concatenate([data, tail_b], axis=-1)


def _absorb(state, padded: jax.Array):
    for i in range(padded.shape[-1] // 128):
        state = compress(state, padded[..., i * 128 : (i + 1) * 128])
    return state


def _digest(state) -> jax.Array:
    sh, sl = state
    parts = []
    for word in (sh, sl):
        parts.append(
            jnp.stack(
                [(word >> 24) & 0xFF, (word >> 16) & 0xFF, (word >> 8) & 0xFF, word & 0xFF],
                axis=-1,
            )
        )
    # interleave: for each of 8 words -> hi 4 bytes then lo 4 bytes
    out = jnp.concatenate(parts, axis=-1).astype(jnp.uint8)  # (..., 8, 8)
    return out.reshape(out.shape[:-2] + (-1,))


def sha512(data: jax.Array) -> jax.Array:
    """(..., L) uint8 -> (..., 64) uint8; L static."""
    data = jnp.asarray(data, jnp.uint8)
    state = init_state(data.shape[:-1])
    return _digest(_absorb(state, _pad(data)))


def midstate(prefix: jax.Array):
    """State after absorbing a (..., 128k) uint8 prefix (no padding)."""
    prefix = jnp.asarray(prefix, jnp.uint8)
    if prefix.shape[-1] % 128:
        raise ValueError("midstate prefix must be a multiple of 128 bytes")
    return _absorb(init_state(prefix.shape[:-1]), prefix)


def sha512_from_midstate(state, data: jax.Array, prefix_blocks: int) -> jax.Array:
    data = jnp.asarray(data, jnp.uint8)
    return _digest(_absorb(state, _pad(data, prefix_blocks)))
