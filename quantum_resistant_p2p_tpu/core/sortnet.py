"""Gather-free bitonic sorting networks for TPU.

XLA's sort/argsort, lax.top_k, take_along_axis and scatter all serialise on
TPU for per-lane dynamic indices (measured 120-1160 ms for a (36864, 448)
compaction — the entire ML-KEM encaps budget).  A bitonic network expressed
as reshapes + min/max + where with *static* direction masks lowers to pure
vectorised VPU ops: the same compaction runs in ~13 ms.

Used for the rejection-sampling compactions in kem/mlkem.py (SampleNTT) and
sig/mldsa.py (RejNTT / SampleInBall), where spec order of accepted candidates
must be preserved: callers embed the candidate index in the sort key, making
the (unstable) bitonic network a deterministic stable partition.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def bitonic_sort(x: jax.Array) -> jax.Array:
    """Sort ascending along the last axis; length must be a power of two."""
    n = x.shape[-1]
    stages = int(np.log2(n))
    assert 1 << stages == n, f"bitonic length must be a power of 2, got {n}"
    for k in range(1, stages + 1):
        for j in range(k - 1, -1, -1):
            d = 1 << j
            xr = x.reshape(x.shape[:-1] + (n // (2 * d), 2, d))
            a, b = xr[..., 0, :], xr[..., 1, :]
            idx = np.arange(n // (2 * d))[:, None] * 2 * d + np.arange(d)[None, :]
            desc = jnp.asarray(((idx >> k) & 1).astype(bool))
            lo, hi = jnp.minimum(a, b), jnp.maximum(a, b)
            x = jnp.stack(
                [jnp.where(desc, hi, lo), jnp.where(desc, lo, hi)], axis=-2
            ).reshape(x.shape)
    return x


def bitonic_sort_regs(regs: list) -> list:
    """Bitonic-sort a Python list of same-shaped arrays, elementwise-ascending.

    The network from :func:`bitonic_sort` with the sorted axis unrolled into
    the *list* dimension: element ``i`` of the result holds, lane-for-lane,
    the i-th smallest value across the input list.  Every compare-exchange is
    a static ``minimum``/``maximum`` pair between two named arrays — no
    reshapes, rolls or gathers — which makes the helper usable inside Pallas
    TPU kernels where each list element is one resident vector tile
    (kem/mlkem_pallas.py keeps all 512 SampleNTT candidates in VMEM this way).
    ``len(regs)`` must be a power of two.
    """
    n = len(regs)
    stages = int(np.log2(n))
    assert 1 << stages == n, f"bitonic length must be a power of 2, got {n}"
    regs = list(regs)
    for k in range(1, stages + 1):
        for j in range(k - 1, -1, -1):
            d = 1 << j
            for i in range(n):
                p = i | d
                if p == i:
                    continue
                lo = jnp.minimum(regs[i], regs[p])
                hi = jnp.maximum(regs[i], regs[p])
                if (i >> k) & 1:
                    regs[i], regs[p] = hi, lo
                else:
                    regs[i], regs[p] = lo, hi
    return regs


def bitonic_sort_pairs_regs(keys: list, vals: list) -> tuple[list, list]:
    """Register-list variant of :func:`bitonic_sort_pairs`.

    Sorts ``keys`` elementwise-ascending across the list dimension, carrying
    ``vals`` through the same exchanges — the pairs analog of
    :func:`bitonic_sort_regs`, for Pallas kernels whose candidate values
    don't fit in an int32 key beside the index (sig/mldsa_pallas.py's 23-bit
    RejNTT candidates).  Keys must be elementwise-unique across the list.
    """
    n = len(keys)
    stages = int(np.log2(n))
    assert 1 << stages == n, f"bitonic length must be a power of 2, got {n}"
    keys, vals = list(keys), list(vals)
    for k in range(1, stages + 1):
        for j in range(k - 1, -1, -1):
            d = 1 << j
            for i in range(n):
                p = i | d
                if p == i:
                    continue
                swap = keys[i] > keys[p] if not ((i >> k) & 1) else keys[i] < keys[p]
                ki = jnp.where(swap, keys[p], keys[i])
                kp = jnp.where(swap, keys[i], keys[p])
                vi = jnp.where(swap, vals[p], vals[i])
                vp = jnp.where(swap, vals[i], vals[p])
                keys[i], keys[p] = ki, kp
                vals[i], vals[p] = vi, vp
    return keys, vals


def bitonic_sort_pairs(key: jax.Array, val: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Sort ``key`` ascending along the last axis, carrying ``val`` along.

    Keys must be unique per lane (callers embed the element index), so the
    network's instability is unobservable.
    """
    n = key.shape[-1]
    stages = int(np.log2(n))
    assert 1 << stages == n, f"bitonic length must be a power of 2, got {n}"
    for k in range(1, stages + 1):
        for j in range(k - 1, -1, -1):
            d = 1 << j
            kr = key.reshape(key.shape[:-1] + (n // (2 * d), 2, d))
            vr = val.reshape(val.shape[:-1] + (n // (2 * d), 2, d))
            ka, kb = kr[..., 0, :], kr[..., 1, :]
            va, vb = vr[..., 0, :], vr[..., 1, :]
            idx = np.arange(n // (2 * d))[:, None] * 2 * d + np.arange(d)[None, :]
            desc = jnp.asarray(((idx >> k) & 1).astype(bool))
            swap = (ka > kb) ^ desc
            ka2 = jnp.where(swap, kb, ka)
            kb2 = jnp.where(swap, ka, kb)
            va2 = jnp.where(swap, vb, va)
            vb2 = jnp.where(swap, va, vb)
            key = jnp.stack([ka2, kb2], axis=-2).reshape(key.shape)
            val = jnp.stack([va2, vb2], axis=-2).reshape(val.shape)
    return key, val
