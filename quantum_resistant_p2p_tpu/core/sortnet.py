"""Gather-free bitonic sorting networks for TPU.

XLA's sort/argsort, lax.top_k, take_along_axis and scatter all serialise on
TPU for per-lane dynamic indices (measured 120-1160 ms for a (36864, 448)
compaction — the entire ML-KEM encaps budget).  A bitonic network expressed
as reshapes + min/max + where with *static* direction masks lowers to pure
vectorised VPU ops: the same compaction runs in ~13 ms.

Used for the rejection-sampling compactions in kem/mlkem.py (SampleNTT) and
sig/mldsa.py (RejNTT / SampleInBall), where spec order of accepted candidates
must be preserved: callers embed the candidate index in the sort key, making
the (unstable) bitonic network a deterministic stable partition.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def bitonic_sort(x: jax.Array) -> jax.Array:
    """Sort ascending along the last axis; length must be a power of two."""
    n = x.shape[-1]
    stages = int(np.log2(n))
    assert 1 << stages == n, f"bitonic length must be a power of 2, got {n}"
    for k in range(1, stages + 1):
        for j in range(k - 1, -1, -1):
            d = 1 << j
            xr = x.reshape(x.shape[:-1] + (n // (2 * d), 2, d))
            a, b = xr[..., 0, :], xr[..., 1, :]
            idx = np.arange(n // (2 * d))[:, None] * 2 * d + np.arange(d)[None, :]
            desc = jnp.asarray(((idx >> k) & 1).astype(bool))
            lo, hi = jnp.minimum(a, b), jnp.maximum(a, b)
            x = jnp.stack(
                [jnp.where(desc, hi, lo), jnp.where(desc, lo, hi)], axis=-2
            ).reshape(x.shape)
    return x


def bitonic_sort_pairs(key: jax.Array, val: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Sort ``key`` ascending along the last axis, carrying ``val`` along.

    Keys must be unique per lane (callers embed the element index), so the
    network's instability is unobservable.
    """
    n = key.shape[-1]
    stages = int(np.log2(n))
    assert 1 << stages == n, f"bitonic length must be a power of 2, got {n}"
    for k in range(1, stages + 1):
        for j in range(k - 1, -1, -1):
            d = 1 << j
            kr = key.reshape(key.shape[:-1] + (n // (2 * d), 2, d))
            vr = val.reshape(val.shape[:-1] + (n // (2 * d), 2, d))
            ka, kb = kr[..., 0, :], kr[..., 1, :]
            va, vb = vr[..., 0, :], vr[..., 1, :]
            idx = np.arange(n // (2 * d))[:, None] * 2 * d + np.arange(d)[None, :]
            desc = jnp.asarray(((idx >> k) & 1).astype(bool))
            swap = (ka > kb) ^ desc
            ka2 = jnp.where(swap, kb, ka)
            kb2 = jnp.where(swap, ka, kb)
            va2 = jnp.where(swap, vb, va)
            vb2 = jnp.where(swap, va, vb)
            key = jnp.stack([ka2, kb2], axis=-2).reshape(key.shape)
            val = jnp.stack([va2, vb2], axis=-2).reshape(val.shape)
    return key, val
