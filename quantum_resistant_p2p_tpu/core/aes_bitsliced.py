"""Bitsliced AES-128-ECB — table-free boolean circuits on the TPU VPU.

The gather S-box (core/aes.py) is the canonical TPU anti-pattern: per-lane
dynamic ``jnp.take`` serialises, and FrodoKEM-AES runs 2.6M of them per
640x640 A-matrix (bench_report config 3: 15 encaps/s).  Bitslicing is the
canonical counter: the state is held as 128 bit-planes packed 32 blocks per
uint32 lane, SubBytes becomes a boolean circuit evaluated on whole planes
(pure AND/XOR — ideal VPU material), ShiftRows a static plane permutation,
MixColumns a handful of plane XORs.

Two S-box circuits ship.  The default is the hand-optimised
**Boyar-Peralta 113-gate circuit** (32 AND + 81 XOR/XNOR, the public
standard for bitsliced software AES) — ~6x fewer plane-ops per SubBytes
than the derived circuit below.  The DERIVED circuit stays as the
independent cross-check: squaring and the affine map are GF(2^8)-linear
(8x8 bit matrices computed from the field at import), multiplication is
schoolbook partial products + a computed reduction matrix, and inversion
is the 4-multiply/7-square addition chain for b^254 = b^-1.  The two
circuits and the table construction are asserted equal over all 256
inputs (tests/test_frodo.py); ``QRP2P_AES_DERIVED_SBOX=1`` selects the
derived circuit for A/B.

Layout: state planes (8 bits, 16 bytes, *lead, W) uint32, W = ceil(B/32)
blocks packed along the minor axis; round keys broadcast over W.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .aes import _SBOX, key_schedule  # noqa: F401 (key_schedule re-exported)

_POLY = 0x11B


def _gf_mul_int(a: int, b: int) -> int:
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & 0x100:
            a ^= _POLY
    return r


def _linear_matrix(fn) -> np.ndarray:
    """8x8 bit matrix M of a GF(2)-linear byte map: out_bit[i] spans M[i]."""
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        out = fn(1 << j)
        for i in range(8):
            m[i, j] = (out >> i) & 1
    return m


_SQ = _linear_matrix(lambda x: _gf_mul_int(x, x))
# affine part of the S-box: y = A(x) ^ 0x63 with A(x) = x ^ rotl1..rotl4
_AFF = _linear_matrix(
    lambda x: x ^ (((x << 1) | (x >> 7)) & 0xFF) ^ (((x << 2) | (x >> 6)) & 0xFF)
    ^ (((x << 3) | (x >> 5)) & 0xFF) ^ (((x << 4) | (x >> 4)) & 0xFF)
)
# x^(8+k) mod poly, k = 0..6 — reduction rows for schoolbook products
_RED = np.zeros((7, 8), dtype=np.uint8)
for _k in range(7):
    _v = 1 << (8 + _k)
    # reduce by repeated xor of shifted modulus
    for _sh in range(6, -1, -1):
        if _v & (0x100 << _sh):
            _v ^= _POLY << _sh
    for _i in range(8):
        _RED[_k, _i] = (_v >> _i) & 1

# ShiftRows on column-major state bytes (same table as core/aes.py)
_SHIFT = np.array([0, 5, 10, 15, 4, 9, 14, 3, 8, 13, 2, 7, 12, 1, 6, 11])

_POW2 = (1 << np.arange(32, dtype=np.uint32)).astype(np.uint32)


def _apply_linear(m: np.ndarray, x: list) -> list:
    """Bit-matrix times bit-plane vector: out[i] = XOR_j m[i,j] & x[j]."""
    out = []
    for i in range(8):
        acc = None
        for j in range(8):
            if m[i, j]:
                acc = x[j] if acc is None else acc ^ x[j]
        out.append(acc if acc is not None else jnp.zeros_like(x[0]))
    return out


def _mul_planes(a: list, b: list) -> list:
    """GF(2^8) product of two bit-plane bytes (schoolbook + reduction)."""
    c = [None] * 15
    for i in range(8):
        for j in range(8):
            t = a[i] & b[j]
            k = i + j
            c[k] = t if c[k] is None else c[k] ^ t
    out = list(c[:8])
    for k in range(7):  # fold x^(8+k) back via the reduction matrix
        for i in range(8):
            if _RED[k, i]:
                out[i] = out[i] ^ c[8 + k]
    return out


def _sq_planes(x: list) -> list:
    return _apply_linear(_SQ, x)


def _sbox_planes_derived(x: list) -> list:
    """S(x) = Affine(x^254) ^ 0x63, all on bit planes (derived circuit)."""
    b2 = _sq_planes(x)                     # x^2
    b3 = _mul_planes(b2, x)                # x^3
    b12 = _sq_planes(_sq_planes(b3))       # x^12
    b15 = _mul_planes(b12, b3)             # x^15
    b240 = b15
    for _ in range(4):                     # x^240
        b240 = _sq_planes(b240)
    b252 = _mul_planes(b240, b12)          # x^252
    b254 = _mul_planes(b252, b2)           # x^254 = x^-1
    y = _apply_linear(_AFF, b254)
    # ^ 0x63: flip bits 0, 1, 5, 6
    for i in (0, 1, 5, 6):
        y[i] = ~y[i]
    return y


def _sbox_planes_bp(x: list) -> list:
    """Boyar-Peralta 113-gate forward S-box (32 AND + 81 XOR/XNOR).

    The public standard circuit for bitsliced AES software.  BP's U0 is
    the byte's MSB, so U_k = x[7-k]; outputs S0..S7 map back the same way
    (the four XNOR outputs realise the 0x63 constant).  Asserted equal to
    the derived circuit and the table S-box over all 256 byte values in
    tests/test_frodo.py.
    """
    U0, U1, U2, U3 = x[7], x[6], x[5], x[4]
    U4, U5, U6, U7 = x[3], x[2], x[1], x[0]
    T1 = U0 ^ U3
    T2 = U0 ^ U5
    T3 = U0 ^ U6
    T4 = U3 ^ U5
    T5 = U4 ^ U6
    T6 = T1 ^ T5
    T7 = U1 ^ U2
    T8 = U7 ^ T6
    T9 = U7 ^ T7
    T10 = T6 ^ T7
    T11 = U1 ^ U5
    T12 = U2 ^ U5
    T13 = T3 ^ T4
    T14 = T6 ^ T11
    T15 = T5 ^ T11
    T16 = T5 ^ T12
    T17 = T9 ^ T16
    T18 = U3 ^ U7
    T19 = T7 ^ T18
    T20 = T1 ^ T19
    T21 = U6 ^ U7
    T22 = T7 ^ T21
    T23 = T2 ^ T22
    T24 = T2 ^ T10
    T25 = T20 ^ T17
    T26 = T3 ^ T16
    T27 = T1 ^ T12
    D = U7
    M1 = T13 & T6
    M2 = T23 & T8
    M3 = T14 ^ M1
    M4 = T19 & D
    M5 = M4 ^ M1
    M6 = T3 & T16
    M7 = T22 & T9
    M8 = T26 ^ M6
    M9 = T20 & T17
    M10 = M9 ^ M6
    M11 = T1 & T15
    M12 = T4 & T27
    M13 = M12 ^ M11
    M14 = T2 & T10
    M15 = M14 ^ M11
    M16 = M3 ^ M2
    M17 = M5 ^ T24
    M18 = M8 ^ M7
    M19 = M10 ^ M15
    M20 = M16 ^ M13
    M21 = M17 ^ M15
    M22 = M18 ^ M13
    M23 = M19 ^ T25
    M24 = M22 ^ M23
    M25 = M22 & M20
    M26 = M21 ^ M25
    M27 = M20 ^ M21
    M28 = M23 ^ M25
    M29 = M28 & M27
    M30 = M26 & M24
    M31 = M20 & M23
    M32 = M27 & M31
    M33 = M27 ^ M25
    M34 = M21 & M22
    M35 = M24 & M34
    M36 = M24 ^ M25
    M37 = M21 ^ M29
    M38 = M32 ^ M33
    M39 = M23 ^ M30
    M40 = M35 ^ M36
    M41 = M38 ^ M40
    M42 = M37 ^ M39
    M43 = M37 ^ M38
    M44 = M39 ^ M40
    M45 = M42 ^ M41
    M46 = M44 & T6
    M47 = M40 & T8
    M48 = M39 & D
    M49 = M43 & T16
    M50 = M38 & T9
    M51 = M37 & T17
    M52 = M42 & T15
    M53 = M45 & T27
    M54 = M41 & T10
    M55 = M44 & T13
    M56 = M40 & T23
    M57 = M39 & T19
    M58 = M43 & T3
    M59 = M38 & T22
    M60 = M37 & T20
    M61 = M42 & T1
    M62 = M45 & T4
    M63 = M41 & T2
    L0 = M61 ^ M62
    L1 = M50 ^ M56
    L2 = M46 ^ M48
    L3 = M47 ^ M55
    L4 = M54 ^ M58
    L5 = M49 ^ M61
    L6 = M62 ^ L5
    L7 = M46 ^ L3
    L8 = M51 ^ M59
    L9 = M52 ^ M53
    L10 = M53 ^ L4
    L11 = M60 ^ L2
    L12 = M48 ^ M51
    L13 = M50 ^ L0
    L14 = M52 ^ M61
    L15 = M55 ^ L1
    L16 = M56 ^ L0
    L17 = M57 ^ L1
    L18 = M58 ^ L8
    L19 = M63 ^ L4
    L20 = L0 ^ L1
    L21 = L1 ^ L7
    L22 = L3 ^ L12
    L23 = L18 ^ L2
    L24 = L15 ^ L9
    L25 = L6 ^ L10
    L26 = L7 ^ L9
    L27 = L8 ^ L10
    L28 = L11 ^ L14
    L29 = L11 ^ L17
    S0 = L6 ^ L24
    S1 = ~(L16 ^ L26)
    S2 = ~(L19 ^ L28)
    S3 = L6 ^ L21
    S4 = L20 ^ L22
    S5 = L25 ^ L29
    S6 = ~(L13 ^ L27)
    S7 = ~(L6 ^ L23)
    return [S7, S6, S5, S4, S3, S2, S1, S0]


def _sbox_planes(x: list) -> list:
    if os.environ.get("QRP2P_AES_DERIVED_SBOX") == "1":
        return _sbox_planes_derived(x)
    return _sbox_planes_bp(x)


def _xtime_planes(a: list) -> list:
    """xtime on bit planes: shift up, fold 0x1B on the old high bit."""
    hi = a[7]
    out = [hi, a[0] ^ hi, a[1], a[2] ^ hi, a[3] ^ hi, a[4], a[5], a[6]]
    return out


def _mix_columns(s: jax.Array) -> jax.Array:
    """s (8, 16, ...) -> mixed; bytes are column-major (byte = row + 4*col)."""
    c = s.reshape((8, 4, 4) + s.shape[2:])  # (bit, col, row, ...)
    a = [[c[i, :, r] for i in range(8)] for r in range(4)]  # [row][bit]
    x = [_xtime_planes(a[r]) for r in range(4)]
    rows = []
    for r in range(4):
        r1, r2, r3 = (r + 1) % 4, (r + 2) % 4, (r + 3) % 4
        rows.append([
            x[r][i] ^ x[r1][i] ^ a[r1][i] ^ a[r2][i] ^ a[r3][i]
            for i in range(8)
        ])
    out = jnp.stack(
        [jnp.stack(rows[r], axis=0) for r in range(4)], axis=2
    )  # (bit, col, row, ...)
    return out.reshape(s.shape)


def pack_blocks(blocks: jax.Array) -> tuple[jax.Array, int]:
    """(*lead, B, 16) uint8 -> planes (8, 16, *lead, W) uint32, original B.

    Blocks pack 32-per-uint32 along the minor axis (padded with zeros).
    """
    lead = blocks.shape[:-2]
    b = blocks.shape[-2]
    w = -(-b // 32)
    if w * 32 != b:
        pad = [(0, 0)] * len(lead) + [(0, w * 32 - b), (0, 0)]
        blocks = jnp.pad(blocks, pad)
    x = blocks.astype(jnp.uint32)  # (*lead, W*32, 16)
    bits = (x[..., None] >> jnp.arange(8, dtype=jnp.uint32)) & 1  # (*l, B, 16, 8)
    bits = jnp.moveaxis(bits, (-1, -2), (0, 1))  # (8, 16, *lead, W*32)
    bits = bits.reshape(bits.shape[:-1] + (w, 32))
    planes = jnp.sum(bits * jnp.asarray(_POW2), axis=-1, dtype=jnp.uint32)
    return planes, b


def unpack_blocks(planes: jax.Array, b: int) -> jax.Array:
    """planes (8, 16, *lead, W) uint32 -> (*lead, B, 16) uint8."""
    w = planes.shape[-1]
    bits = (planes[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
    bits = bits.reshape(planes.shape[:-1] + (w * 32,))  # (8, 16, *lead, B)
    bits = jnp.moveaxis(bits, (0, 1), (-1, -2))  # (*lead, B, 16, 8)
    vals = jnp.sum(bits << jnp.arange(8, dtype=jnp.uint32), axis=-1)
    return vals[..., :b, :].astype(jnp.uint8)


def _key_planes(round_keys: jax.Array) -> jax.Array:
    """(*lead, 11, 16) uint8 -> (11, 8, 16, *lead, 1) uint32 (0/~0 masks)."""
    x = round_keys.astype(jnp.uint32)
    bits = (x[..., None] >> jnp.arange(8, dtype=jnp.uint32)) & 1
    bits = jnp.moveaxis(bits, (-3, -1, -2), (0, 1, 2))  # (11, 8, 16, *lead)
    # 0 -> 0x00000000, 1 -> 0xFFFFFFFF so XOR applies the bit to all 32 lanes
    return (bits * jnp.uint32(0xFFFFFFFF))[..., None]


def encrypt_blocks(round_keys: jax.Array, blocks: jax.Array) -> jax.Array:
    """Drop-in for core.aes.encrypt_blocks, bitsliced.

    round_keys (*lead, 11, 16), blocks (*lead, B, 16) uint8 -> (*lead, B, 16).
    """
    rk = _key_planes(round_keys)
    s, b = pack_blocks(blocks)
    s = s ^ rk[0]
    for r in range(1, 10):
        bit_list = _sbox_planes([s[i] for i in range(8)])
        s = jnp.stack(bit_list, axis=0)
        s = s[:, _SHIFT]
        s = _mix_columns(s)
        s = s ^ rk[r]
    bit_list = _sbox_planes([s[i] for i in range(8)])
    s = jnp.stack(bit_list, axis=0)
    s = s[:, _SHIFT]
    s = s ^ rk[10]
    return unpack_blocks(s, b)
