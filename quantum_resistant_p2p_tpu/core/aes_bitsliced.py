"""Bitsliced AES-128-ECB — table-free boolean circuits on the TPU VPU.

The gather S-box (core/aes.py) is the canonical TPU anti-pattern: per-lane
dynamic ``jnp.take`` serialises, and FrodoKEM-AES runs 2.6M of them per
640x640 A-matrix (bench_report config 3: 15 encaps/s).  Bitslicing is the
canonical counter: the state is held as 128 bit-planes packed 32 blocks per
uint32 lane, SubBytes becomes a boolean circuit evaluated on whole planes
(pure AND/XOR — ideal VPU material), ShiftRows a static plane permutation,
MixColumns a handful of plane XORs.

The S-box circuit is DERIVED, not transcribed: squaring and the affine map
are GF(2^8)-linear (8x8 bit matrices computed from the field at import),
multiplication is schoolbook partial products + a computed reduction
matrix, and inversion is the 4-multiply/7-square addition chain for
b^254 = b^-1.  ~700 plane-ops per SubBytes vs 113 for the hand-optimised
Boyar-Peralta circuit — 6x off optimal gate count but orders of magnitude
off the gather path, and verifiable against the classic table construction
(tests/test_frodo.py drives both against the OpenSSL oracle).

Layout: state planes (8 bits, 16 bytes, *lead, W) uint32, W = ceil(B/32)
blocks packed along the minor axis; round keys broadcast over W.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .aes import _SBOX, key_schedule  # noqa: F401 (key_schedule re-exported)

_POLY = 0x11B


def _gf_mul_int(a: int, b: int) -> int:
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & 0x100:
            a ^= _POLY
    return r


def _linear_matrix(fn) -> np.ndarray:
    """8x8 bit matrix M of a GF(2)-linear byte map: out_bit[i] spans M[i]."""
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        out = fn(1 << j)
        for i in range(8):
            m[i, j] = (out >> i) & 1
    return m


_SQ = _linear_matrix(lambda x: _gf_mul_int(x, x))
# affine part of the S-box: y = A(x) ^ 0x63 with A(x) = x ^ rotl1..rotl4
_AFF = _linear_matrix(
    lambda x: x ^ (((x << 1) | (x >> 7)) & 0xFF) ^ (((x << 2) | (x >> 6)) & 0xFF)
    ^ (((x << 3) | (x >> 5)) & 0xFF) ^ (((x << 4) | (x >> 4)) & 0xFF)
)
# x^(8+k) mod poly, k = 0..6 — reduction rows for schoolbook products
_RED = np.zeros((7, 8), dtype=np.uint8)
for _k in range(7):
    _v = 1 << (8 + _k)
    # reduce by repeated xor of shifted modulus
    for _sh in range(6, -1, -1):
        if _v & (0x100 << _sh):
            _v ^= _POLY << _sh
    for _i in range(8):
        _RED[_k, _i] = (_v >> _i) & 1

# ShiftRows on column-major state bytes (same table as core/aes.py)
_SHIFT = np.array([0, 5, 10, 15, 4, 9, 14, 3, 8, 13, 2, 7, 12, 1, 6, 11])

_POW2 = (1 << np.arange(32, dtype=np.uint32)).astype(np.uint32)


def _apply_linear(m: np.ndarray, x: list) -> list:
    """Bit-matrix times bit-plane vector: out[i] = XOR_j m[i,j] & x[j]."""
    out = []
    for i in range(8):
        acc = None
        for j in range(8):
            if m[i, j]:
                acc = x[j] if acc is None else acc ^ x[j]
        out.append(acc if acc is not None else jnp.zeros_like(x[0]))
    return out


def _mul_planes(a: list, b: list) -> list:
    """GF(2^8) product of two bit-plane bytes (schoolbook + reduction)."""
    c = [None] * 15
    for i in range(8):
        for j in range(8):
            t = a[i] & b[j]
            k = i + j
            c[k] = t if c[k] is None else c[k] ^ t
    out = list(c[:8])
    for k in range(7):  # fold x^(8+k) back via the reduction matrix
        for i in range(8):
            if _RED[k, i]:
                out[i] = out[i] ^ c[8 + k]
    return out


def _sq_planes(x: list) -> list:
    return _apply_linear(_SQ, x)


def _sbox_planes(x: list) -> list:
    """S(x) = Affine(x^254) ^ 0x63, all on bit planes."""
    b2 = _sq_planes(x)                     # x^2
    b3 = _mul_planes(b2, x)                # x^3
    b12 = _sq_planes(_sq_planes(b3))       # x^12
    b15 = _mul_planes(b12, b3)             # x^15
    b240 = b15
    for _ in range(4):                     # x^240
        b240 = _sq_planes(b240)
    b252 = _mul_planes(b240, b12)          # x^252
    b254 = _mul_planes(b252, b2)           # x^254 = x^-1
    y = _apply_linear(_AFF, b254)
    # ^ 0x63: flip bits 0, 1, 5, 6
    for i in (0, 1, 5, 6):
        y[i] = ~y[i]
    return y


def _xtime_planes(a: list) -> list:
    """xtime on bit planes: shift up, fold 0x1B on the old high bit."""
    hi = a[7]
    out = [hi, a[0] ^ hi, a[1], a[2] ^ hi, a[3] ^ hi, a[4], a[5], a[6]]
    return out


def _mix_columns(s: jax.Array) -> jax.Array:
    """s (8, 16, ...) -> mixed; bytes are column-major (byte = row + 4*col)."""
    c = s.reshape((8, 4, 4) + s.shape[2:])  # (bit, col, row, ...)
    a = [[c[i, :, r] for i in range(8)] for r in range(4)]  # [row][bit]
    x = [_xtime_planes(a[r]) for r in range(4)]
    rows = []
    for r in range(4):
        r1, r2, r3 = (r + 1) % 4, (r + 2) % 4, (r + 3) % 4
        rows.append([
            x[r][i] ^ x[r1][i] ^ a[r1][i] ^ a[r2][i] ^ a[r3][i]
            for i in range(8)
        ])
    out = jnp.stack(
        [jnp.stack(rows[r], axis=0) for r in range(4)], axis=2
    )  # (bit, col, row, ...)
    return out.reshape(s.shape)


def pack_blocks(blocks: jax.Array) -> tuple[jax.Array, int]:
    """(*lead, B, 16) uint8 -> planes (8, 16, *lead, W) uint32, original B.

    Blocks pack 32-per-uint32 along the minor axis (padded with zeros).
    """
    lead = blocks.shape[:-2]
    b = blocks.shape[-2]
    w = -(-b // 32)
    if w * 32 != b:
        pad = [(0, 0)] * len(lead) + [(0, w * 32 - b), (0, 0)]
        blocks = jnp.pad(blocks, pad)
    x = blocks.astype(jnp.uint32)  # (*lead, W*32, 16)
    bits = (x[..., None] >> jnp.arange(8, dtype=jnp.uint32)) & 1  # (*l, B, 16, 8)
    bits = jnp.moveaxis(bits, (-1, -2), (0, 1))  # (8, 16, *lead, W*32)
    bits = bits.reshape(bits.shape[:-1] + (w, 32))
    planes = jnp.sum(bits * jnp.asarray(_POW2), axis=-1, dtype=jnp.uint32)
    return planes, b


def unpack_blocks(planes: jax.Array, b: int) -> jax.Array:
    """planes (8, 16, *lead, W) uint32 -> (*lead, B, 16) uint8."""
    w = planes.shape[-1]
    bits = (planes[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
    bits = bits.reshape(planes.shape[:-1] + (w * 32,))  # (8, 16, *lead, B)
    bits = jnp.moveaxis(bits, (0, 1), (-1, -2))  # (*lead, B, 16, 8)
    vals = jnp.sum(bits << jnp.arange(8, dtype=jnp.uint32), axis=-1)
    return vals[..., :b, :].astype(jnp.uint8)


def _key_planes(round_keys: jax.Array) -> jax.Array:
    """(*lead, 11, 16) uint8 -> (11, 8, 16, *lead, 1) uint32 (0/~0 masks)."""
    x = round_keys.astype(jnp.uint32)
    bits = (x[..., None] >> jnp.arange(8, dtype=jnp.uint32)) & 1
    bits = jnp.moveaxis(bits, (-3, -1, -2), (0, 1, 2))  # (11, 8, 16, *lead)
    # 0 -> 0x00000000, 1 -> 0xFFFFFFFF so XOR applies the bit to all 32 lanes
    return (bits * jnp.uint32(0xFFFFFFFF))[..., None]


def encrypt_blocks(round_keys: jax.Array, blocks: jax.Array) -> jax.Array:
    """Drop-in for core.aes.encrypt_blocks, bitsliced.

    round_keys (*lead, 11, 16), blocks (*lead, B, 16) uint8 -> (*lead, B, 16).
    """
    rk = _key_planes(round_keys)
    s, b = pack_blocks(blocks)
    s = s ^ rk[0]
    for r in range(1, 10):
        bit_list = _sbox_planes([s[i] for i in range(8)])
        s = jnp.stack(bit_list, axis=0)
        s = s[:, _SHIFT]
        s = _mix_columns(s)
        s = s ^ rk[r]
    bit_list = _sbox_planes([s[i] for i in range(8)])
    s = jnp.stack(bit_list, axis=0)
    s = s[:, _SHIFT]
    s = s ^ rk[10]
    return unpack_blocks(s, b)
