"""Batched SHA-256 / HMAC / HKDF in JAX — uint32-native, VPU-friendly.

SHA-256 is pure 32-bit arithmetic, so unlike Keccak (64-bit lanes emulated as
uint32 pairs in ``core.keccak``) it maps directly onto TPU vector lanes: the
8-word state and 64-round schedule vectorise over an arbitrary leading batch
shape with no emulation.

All lengths are static Python ints -> fixed-shape XLA programs.  The 64-round
compression runs under ``lax.fori_loop`` with the 16-word schedule window kept
as a (..., 16) uint32 array (rotating index, no dynamic shapes).

``midstate`` support: SPHINCS+-SHA2 hashes millions of 64-byte blocks whose
first block is the constant ``pk_seed || zero-pad``; precomputing that block's
state once per keypair halves the tree-hash work (FIPS 205 §11.2.1 note).

Replaces (reference): OpenSSL SHA-256/HMAC inside the `cryptography` package —
HKDF-SHA256 at app/messaging.py:23,372-377 and the SHA2 hashes inside
liboqs SPHINCS+-SHA2 (crypto/signatures.py:191-315).
Oracle: hashlib.sha256 / hmac (tests/test_sha256.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import keccak  # _use_pallas: shared TPU-vs-CPU gate

# Round constants: fractional parts of cube roots of the first 64 primes.
_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> n) | (x << (32 - n))


def _block_words(block: jax.Array) -> jax.Array:
    """(..., 64) uint8 -> (..., 16) uint32 big-endian words."""
    b = block.astype(jnp.uint32).reshape(block.shape[:-1] + (16, 4))
    return (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]


#: below this flat batch the Pallas kernel's 1024-instance tile padding
#: wastes more than the jnp path costs (scalar HKDF/HMAC calls, tests)
_PALLAS_MIN_BATCH = 256


def compress(state: jax.Array, block: jax.Array) -> jax.Array:
    """One SHA-256 compression: state (..., 8) uint32, block (..., 64) uint8."""
    batch = state.shape[:-1]
    flat = int(np.prod(batch)) if batch else 1
    if flat >= _PALLAS_MIN_BATCH and keccak._use_pallas():
        from . import sha256_pallas  # deferred: pallas import

        sw = state.reshape(flat, 8).T
        bw = _block_words(jnp.asarray(block, jnp.uint8)).reshape(flat, 16).T
        out = sha256_pallas.compress_words(sw, bw)
        return out.T.reshape(batch + (8,))

    w0 = _block_words(block)
    k = jnp.asarray(_K)

    def round_fn(t, carry):
        v, w = carry  # v: (..., 8) working vars, w: (..., 16) schedule window
        wt = w[..., 0]
        a, b, c, d, e, f, g, h = (v[..., i] for i in range(8))
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k[t] + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        v = jnp.stack([t1 + t2, a, b, c, d + t1, e, f, g], axis=-1)
        # extend schedule: w16 = sig1(w14) + w9 + sig0(w1) + w0
        w1, w9, w14 = w[..., 1], w[..., 9], w[..., 14]
        sig0 = _rotr(w1, 7) ^ _rotr(w1, 18) ^ (w1 >> 3)
        sig1 = _rotr(w14, 17) ^ _rotr(w14, 19) ^ (w14 >> 10)
        w16 = sig1 + w9 + sig0 + wt
        w = jnp.concatenate([w[..., 1:], w16[..., None]], axis=-1)
        return v, w

    v, _ = lax.fori_loop(0, 64, round_fn, (state, w0))
    return state + v


def _pad(data: jax.Array, prefix_blocks: int = 0) -> jax.Array:
    """FIPS 180-4 padding; total bit length includes prefix_blocks * 512."""
    msg_len = data.shape[-1]
    total_bits = (prefix_blocks * 64 + msg_len) * 8
    pad_len = (55 - msg_len) % 64 + 9
    tail = np.zeros(pad_len, dtype=np.uint8)
    tail[0] = 0x80
    tail[-8:] = np.frombuffer(np.uint64(total_bits).byteswap().tobytes(), np.uint8)
    tail_b = jnp.broadcast_to(jnp.asarray(tail), data.shape[:-1] + (pad_len,))
    return jnp.concatenate([data, tail_b], axis=-1)


def _absorb(state: jax.Array, padded: jax.Array) -> jax.Array:
    for i in range(padded.shape[-1] // 64):
        state = compress(state, padded[..., i * 64 : (i + 1) * 64])
    return state


def _digest(state: jax.Array) -> jax.Array:
    """(..., 8) uint32 -> (..., 32) uint8 big-endian."""
    parts = [(state >> 24) & 0xFF, (state >> 16) & 0xFF, (state >> 8) & 0xFF, state & 0xFF]
    out = jnp.stack(parts, axis=-1).astype(jnp.uint8)
    return out.reshape(out.shape[:-2] + (-1,))


def init_state(batch_shape: tuple[int, ...] = ()) -> jax.Array:
    return jnp.broadcast_to(jnp.asarray(_H0), batch_shape + (8,))


def sha256(data: jax.Array) -> jax.Array:
    """(..., L) uint8 -> (..., 32) uint8; L static."""
    data = jnp.asarray(data, jnp.uint8)
    state = init_state(data.shape[:-1])
    return _digest(_absorb(state, _pad(data)))


def sha256_from_midstate(state: jax.Array, data: jax.Array, prefix_blocks: int) -> jax.Array:
    """Finish SHA-256 from a precomputed state over ``prefix_blocks`` blocks."""
    data = jnp.asarray(data, jnp.uint8)
    return _digest(_absorb(state, _pad(data, prefix_blocks)))


def midstate(prefix: jax.Array) -> jax.Array:
    """State after absorbing a (..., 64k) uint8 prefix (no padding)."""
    prefix = jnp.asarray(prefix, jnp.uint8)
    if prefix.shape[-1] % 64:
        raise ValueError("midstate prefix must be a multiple of 64 bytes")
    return _absorb(init_state(prefix.shape[:-1]), prefix)


# --------------------------------------------------------------------------
# HMAC-SHA256 and HKDF (RFC 2104 / RFC 5869), batched, static lengths
# --------------------------------------------------------------------------


def hmac_sha256(key: jax.Array, data: jax.Array) -> jax.Array:
    """key (..., kl<=64) uint8, data (..., L) uint8 -> (..., 32) uint8."""
    key = jnp.asarray(key, jnp.uint8)
    data = jnp.asarray(data, jnp.uint8)
    if key.shape[-1] > 64:
        key = sha256(key)
    pad_k = jnp.zeros(key.shape[:-1] + (64 - key.shape[-1],), jnp.uint8)
    k64 = jnp.concatenate([key, pad_k], axis=-1)
    inner = sha256(jnp.concatenate([k64 ^ 0x36, data], axis=-1))
    return sha256(jnp.concatenate([k64 ^ 0x5C, inner], axis=-1))


def hkdf_sha256(
    ikm: jax.Array, salt: jax.Array, info: jax.Array, length: int = 32
) -> jax.Array:
    """RFC 5869 extract+expand; length <= 8160, all shapes static."""
    prk = hmac_sha256(salt, ikm)
    n = -(-length // 32)
    okm = []
    t = jnp.zeros(ikm.shape[:-1] + (0,), jnp.uint8)
    for i in range(1, n + 1):
        ctr = jnp.broadcast_to(jnp.uint8(i), ikm.shape[:-1] + (1,))
        t = hmac_sha256(prk, jnp.concatenate([t, info, ctr], axis=-1))
        okm.append(t)
    out = jnp.concatenate(okm, axis=-1) if len(okm) > 1 else okm[0]
    return out[..., :length]
