"""Batched AES-128-ECB encryption in JAX (for FrodoKEM-AES matrix expansion).

TPU-native notes: SubBytes is a 256-entry gather (``jnp.take``) — TPUs handle
small-table gathers fine; ShiftRows is a static permutation; MixColumns is
GF(2^8) xtime arithmetic on uint8 lanes; the key schedule is 10 tiny rounds
vectorised over the batch.  Everything operates on ``(..., blocks, 16)`` uint8
arrays, so one jitted program encrypts millions of counter blocks across a
batch of keys — the access pattern FrodoKEM's A-matrix generation needs
(reference behavior: AES inside liboqs FrodoKEM, crypto/key_exchange.py:332).

Oracle: cryptography's AES-ECB (tests/test_frodo.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# S-box generated from GF(2^8) inverse + affine map (computed, not transcribed).


def _make_sbox() -> np.ndarray:
    # GF(2^8) with modulus x^8+x^4+x^3+x+1 (0x11B)
    exp = np.zeros(256, dtype=np.int32)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x ^= (x << 1) ^ (0x11B if x & 0x80 else 0)
        x &= 0xFF
    inv = np.zeros(256, dtype=np.int32)
    for v in range(1, 256):
        inv[v] = exp[(255 - log[v]) % 255]
    sbox = np.zeros(256, dtype=np.uint8)
    for v in range(256):
        b = inv[v]
        r = 0x63
        for sh in (0, 1, 2, 3, 4):
            r ^= ((b << sh) | (b >> (8 - sh))) & 0xFF
        sbox[v] = r
    return sbox


_SBOX = _make_sbox()
_RCON = np.array([0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36], np.uint8)

# ShiftRows on column-major state bytes (byte i = row i%4, col i//4)
_SHIFT = np.array([0, 5, 10, 15, 4, 9, 14, 3, 8, 13, 2, 7, 12, 1, 6, 11])


def key_schedule(key: jax.Array) -> jax.Array:
    """(..., 16) uint8 -> (..., 11, 16) uint8 round keys."""
    sbox = jnp.asarray(_SBOX)
    w = [key[..., i * 4 : (i + 1) * 4] for i in range(4)]
    for r in range(10):
        last = w[-1]
        rot = jnp.concatenate([last[..., 1:], last[..., :1]], axis=-1)
        sub = jnp.take(sbox, rot.astype(jnp.int32), axis=0)
        rcon = jnp.zeros_like(sub).at[..., 0].set(_RCON[r])
        t = sub ^ rcon
        w.append(w[-4] ^ t)
        for _ in range(3):
            w.append(w[-4] ^ w[-1])
    keys = jnp.concatenate(w, axis=-1)  # (..., 44*4)
    return keys.reshape(keys.shape[:-1] + (11, 16))


def _xtime(b: jax.Array) -> jax.Array:
    return ((b << 1) ^ jnp.where(b & 0x80 != 0, 0x1B, 0)).astype(jnp.uint8) & 0xFF


def _mix_columns(s: jax.Array) -> jax.Array:
    """(..., 16) uint8 column-major state."""
    c = s.reshape(s.shape[:-1] + (4, 4))  # (..., col, row)
    a0, a1, a2, a3 = c[..., 0], c[..., 1], c[..., 2], c[..., 3]
    x0, x1, x2, x3 = _xtime(a0), _xtime(a1), _xtime(a2), _xtime(a3)
    b0 = x0 ^ (x1 ^ a1) ^ a2 ^ a3
    b1 = a0 ^ x1 ^ (x2 ^ a2) ^ a3
    b2 = a0 ^ a1 ^ x2 ^ (x3 ^ a3)
    b3 = (x0 ^ a0) ^ a1 ^ a2 ^ x3
    return jnp.stack([b0, b1, b2, b3], axis=-1).reshape(s.shape)


def encrypt_blocks(round_keys: jax.Array, blocks: jax.Array) -> jax.Array:
    """round_keys (..., 11, 16), blocks (..., B, 16) uint8 -> (..., B, 16).

    round_keys broadcast over the block axis.
    """
    sbox = jnp.asarray(_SBOX)
    shift = jnp.asarray(_SHIFT)
    rk = round_keys[..., None, :, :]  # (..., 1, 11, 16)
    s = blocks ^ rk[..., 0, :]
    for r in range(1, 10):
        s = jnp.take(sbox, s.astype(jnp.int32), axis=0)
        s = s[..., shift]
        s = _mix_columns(s)
        s = s ^ rk[..., r, :]
    s = jnp.take(sbox, s.astype(jnp.int32), axis=0)
    s = s[..., shift]
    return s ^ rk[..., 10, :]
