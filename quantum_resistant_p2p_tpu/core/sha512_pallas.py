"""Pallas TPU kernel for the SHA-512 compression function.

SPHINCS+-SHA2 at the 192/256-bit security levels computes H / T_l / PRF_msg
with SHA-512 (FIPS 205 §11.2), so an s-set sign at those levels is hundreds
of thousands of SHA-512 compressions over wide batches.  The jnp
``core.sha512.compress`` keeps the 8 emulated-64-bit state words and the
16-word schedule window as HBM-resident (hi, lo) uint32 arrays across the 80
``lax.fori_loop`` rounds — the materialise-between-rounds pattern whose
elimination doubled the SHA-256 rows (core/sha256_pallas.py).  This kernel
holds all 48 uint32 words (8+16 words x hi/lo pairs) in vector registers for
the fully-unrolled 80 rounds; HBM sees one 128-byte block in and a 64-byte
state out per instance.

Layout identical to core/keccak_pallas.py (which holds 50 registers, so 48
is proven ground): each word is an ``(8, 128)`` uint32 tile over 1024
instances, launched through the shared ``sampler_call`` plumbing with the
48 input rows split 24/24 across its two operand refs (purely a transport
split).  Oracle: the jnp path (itself hashlib-anchored by
tests/test_sha512.py); bit-exactness asserted by tests/test_sha512_pallas.py.

Replaces (reference): the SHA-512 inside liboqs SPHINCS+-SHA2
(crypto/signatures.py:191-315).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .keccak_pallas import sampler_call
from .sha512 import _K64
from .sha512 import _add64 as _add_pair
from .sha512 import _rotr64 as _rotr_pair
from .sha512 import _shr64 as _shr_pair


def _compress_tiles(words: list) -> list:
    """One SHA-512 compression over 48 uint32 word tiles.

    ``words``: 8 state (hi, lo) pairs then 16 block (hi, lo) pairs, each a
    same-shaped uint32 array.  Returns the 8 updated state pairs.  Pure
    function — the Pallas kernel calls it on VMEM tiles, tests eagerly.
    """
    v = list(words[:8])            # [(hi, lo)] * 8
    w = list(words[8:24])          # [(hi, lo)] * 16
    h0 = list(v)
    for t in range(80):
        if t >= 16:
            x15, x2 = w[(t - 15) % 16], w[(t - 2) % 16]
            s0 = _rotr_pair(*x15, 1)
            s0b = _rotr_pair(*x15, 8)
            s0c = _shr_pair(*x15, 7)
            sig0 = (s0[0] ^ s0b[0] ^ s0c[0], s0[1] ^ s0b[1] ^ s0c[1])
            s1 = _rotr_pair(*x2, 19)
            s1b = _rotr_pair(*x2, 61)
            s1c = _shr_pair(*x2, 6)
            sig1 = (s1[0] ^ s1b[0] ^ s1c[0], s1[1] ^ s1b[1] ^ s1c[1])
            acc = _add_pair(*w[t % 16], *sig0)
            acc = _add_pair(*acc, *w[(t - 7) % 16])
            w[t % 16] = _add_pair(*acc, *sig1)
        a, b, c, d, e, f, g, h = v
        e1 = _rotr_pair(*e, 14)
        e2 = _rotr_pair(*e, 18)
        e3 = _rotr_pair(*e, 41)
        s1 = (e1[0] ^ e2[0] ^ e3[0], e1[1] ^ e2[1] ^ e3[1])
        ch = ((e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1]))
        kt = _K64[t]
        t1 = _add_pair(*h, *s1)
        t1 = _add_pair(*t1, *ch)
        t1 = _add_pair(*t1, jnp.uint32(kt >> 32), jnp.uint32(kt & 0xFFFFFFFF))
        t1 = _add_pair(*t1, *w[t % 16])
        a1 = _rotr_pair(*a, 28)
        a2 = _rotr_pair(*a, 34)
        a3 = _rotr_pair(*a, 39)
        s0 = (a1[0] ^ a2[0] ^ a3[0], a1[1] ^ a2[1] ^ a3[1])
        maj = (
            (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
            (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
        )
        t2 = _add_pair(*s0, *maj)
        v = [_add_pair(*t1, *t2), a, b, c, _add_pair(*d, *t1), e, f, g]
    return [_add_pair(*o, *s) for o, s in zip(v, h0)]


def _compress_kernel(in_hi_ref, in_lo_ref, out_ref):
    # 48 input rows split 24/24: in_hi rows = state hi(8) + state lo(8) +
    # block hi words 0..7; in_lo rows = block hi words 8..15 + block lo(16).
    sh = [in_hi_ref[i] for i in range(8)]
    sl = [in_hi_ref[8 + i] for i in range(8)]
    bh = [in_hi_ref[16 + i] for i in range(8)] + [in_lo_ref[i] for i in range(8)]
    bl = [in_lo_ref[8 + i] for i in range(16)]
    words = [(sh[i], sl[i]) for i in range(8)] + [(bh[i], bl[i]) for i in range(16)]
    out = _compress_tiles(words)
    for i in range(8):
        out_ref[i] = out[i][0].astype(jnp.int32)
        out_ref[8 + i] = out[i][1].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def compress_words(
    state_hi: jax.Array,
    state_lo: jax.Array,
    block_hi: jax.Array,
    block_lo: jax.Array,
    *,
    interpret: bool = False,
):
    """Batched SHA-512 compression over word-transposed inputs.

    Args:
      state_hi/state_lo: (8, B) uint32 state word halves, batch minor.
      block_hi/block_lo: (16, B) uint32 message-block word halves.

    Returns:
      ((8, B), (8, B)) uint32 updated state halves.
    """
    in_hi = jnp.concatenate([state_hi, state_lo, block_hi[:8]], axis=0)
    in_lo = jnp.concatenate([block_hi[8:], block_lo], axis=0)
    out = sampler_call(_compress_kernel, 24, 16, in_hi, in_lo, interpret=interpret)
    out = out.astype(jnp.uint32)
    return out[:8], out[8:]
