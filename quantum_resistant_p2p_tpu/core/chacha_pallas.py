"""Batched RFC 8439 ChaCha20-Poly1305 — the device DATA plane.

Why a kernel: at fleet scale bulk traffic dwarfs handshakes, and every
AEAD seal/open used to be one scalar CPU call per message
(provider/symmetric.py) while the KEM/signature plane batched thousands of
ops per dispatch.  ChaCha20 is pure ARX — the same add/rotate/xor idioms as
the Keccak sponge kernel (core/keccak_pallas.py) — so the block function
vectorizes across the bulk lane's queued messages with zero cross-lane
traffic: one lane = one 64-byte block of one message.

Layout mirrors keccak_pallas: the batch lives on the two *minor*
dimensions — each of the 16 state words is an ``(8, 128)`` uint32 tile
(exactly one 32-bit vector register) across 1024 block instances; the 20
rounds are fully unrolled at trace time.  Messages are padded to pow2
length buckets with masked tails, so XLA compiles one program per
(batch-bucket, length-bucket, aad-bucket) triple instead of one per
message shape.

Poly1305 runs as vectorized jnp alongside the kernel output: the 130-bit
accumulator is represented as twelve radix-2^11 limbs per lane, so every
partial product of a (≤2^12) x (≤2^11) limb multiply fits a 32-bit vector
register with full carry headroom (comments carry the exact bounds).  The
AEAD MAC input is block-aligned by construction (§2.8 pads AAD and
ciphertext to 16), which is what makes variable lengths maskable: inactive
blocks leave the accumulator untouched via a per-lane select.

Oracle: the pure-Python scalar twin (pyref/chacha_ref.py) and — when the
OpenSSL wheel is present — the ``cryptography`` package;
tests/test_chacha_pallas.py pins the RFC 8439 §2.8.2 vector and every
masked-tail bucket edge through both the jnp and (interpret-mode) Pallas
paths.  Used by provider/aead_device.py behind the ``BatchedAEAD``
capability; the Pallas path engages on real TPU only (core.keccak's
``_use_pallas`` policy), the jnp twin is bit-identical elsewhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .keccak import _use_pallas

#: ChaCha20 constants "expa" "nd 3" "2-by" "te k" (RFC 8439 §2.3)
_CONSTS = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)

#: block instances per grid step: 8 sublanes x 128 lanes = one vreg per word
_TS, _TL = 8, 128
BT = _TS * _TL

#: column then diagonal quarter-round schedule (§2.3: inner_block)
_QR_SCHEDULE = (
    (0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
    (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14),
)

#: Poly1305 r clamp (§2.5): top 4 bits of bytes 3/7/11/15 and bottom 2 of
#: bytes 4/8/12 cleared
_R_CLAMP = (255, 255, 255, 15, 252, 255, 255, 15,
            252, 255, 255, 15, 252, 255, 255, 15)

#: Poly1305 limb radix: 12 limbs x 11 bits = 132 >= 130 accumulator bits.
#: Chosen so the schoolbook multiply below stays inside uint32: limbs are
#: <= 2^12 (lazy) x <= 2^11 (clamped r) -> products <= 2^23, column sums of
#: 12 products <= 12*2^23 < 2^26.6, and the 2^132 === 20 (mod 2^130-5) fold
#: adds at most 20x that: 21 * 2^26.6 < 2^31.  A 13-bit radix would
#: overflow the fold.
_RADIX = 11
_NLIMB = 12
_LMASK = (1 << _RADIX) - 1
#: 2^132 = 4 * 2^130 === 4 * 5 = 20 (mod 2^130 - 5)
_FOLD = 20


# --------------------------------------------------------------------------
# ChaCha20 block function (shared by the Pallas kernel and the jnp twin)
# --------------------------------------------------------------------------


def _rotl(x, n: int):
    """Rotate uint32 lanes left by static ``n`` (1..31)."""
    return (x << n) | (x >> (32 - n))  # qrkernel: wrapping — uint32 lane rotation: bits shifted past 32 drop by design and are recovered by the partner right shift (RFC 8439's <<<)


def _double_round(x: list) -> list:
    """One column+diagonal double round (§2.3 inner_block) over 16 uint32
    arrays.  All additions wrap mod 2^32 by design (RFC 8439 §2.1: "+"
    denotes addition modulo 2^32); uint32 lanes give exactly that."""
    x = list(x)
    for a, b, c, d in _QR_SCHEDULE:
        xa, xb, xc, xd = x[a], x[b], x[c], x[d]
        xa = xa + xb
        xd = _rotl(xd ^ xa, 16)
        xc = xc + xd
        xb = _rotl(xb ^ xc, 12)
        xa = xa + xb
        xd = _rotl(xd ^ xa, 8)
        xc = xc + xd
        xb = _rotl(xb ^ xc, 7)
        x[a], x[b], x[c], x[d] = xa, xb, xc, xd
    return x


def chacha_block_words(state: list) -> list:
    """20-round ChaCha20 block + feedforward, fully unrolled at trace time.

    ``state`` is the 16-word initial state (constants, key, counter,
    nonce), each word an ``(8, 128)`` uint32 VPU tile inside the Pallas
    kernel — unrolling keeps the whole working state in vector registers
    for all 80 quarter rounds, exactly like the keccak kernel's 24 rounds.
    (The jnp twin uses the scanned form below instead: XLA:CPU neither
    fuses nor compiles a 1000-op unrolled chain well.)
    """
    x = list(state)
    for _ in range(10):
        x = _double_round(x)
    return [x[i] + state[i] for i in range(16)]


def _chacha_stream_kernel(in_ref, out_ref):
    """One ChaCha20 block per lane.

    in_ref:  (12, 8, 128) uint32 — rows 0-7 key words, row 8 the per-lane
             block counter, rows 9-11 nonce words.
    out_ref: (16, 8, 128) uint32 — the serialized block state words.

    Blocks are independent (the counter is an input), so arbitrarily long
    messages batch as more lanes instead of an unrolled in-kernel block
    loop — the kernel compiles once per tile geometry, never per message
    length.
    """
    consts = [jnp.full((_TS, _TL), c, jnp.uint32) for c in _CONSTS]
    state = consts + [in_ref[w] for w in range(12)]
    out = chacha_block_words(state)
    for w in range(16):
        out_ref[w] = out[w]


def chacha_blocks(states: jax.Array, *, interpret: bool = False) -> jax.Array:
    """Pallas launcher: ``(12, N)`` uint32 lane states -> ``(16, N)`` blocks.

    Batch on the minor axis (N need not be a multiple of the 1024-lane
    tile); layout and padding mirror keccak_pallas.sampler_call.
    """
    w, b = states.shape
    assert w == 12
    bp = -(-b // BT) * BT
    if bp != b:
        states = jnp.pad(states, ((0, 0), (0, bp - b)))
    states = states.reshape(12, bp // _TL, _TL)
    out = pl.pallas_call(
        _chacha_stream_kernel,
        grid=(bp // BT,),
        in_specs=[pl.BlockSpec((12, _TS, _TL), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((16, _TS, _TL), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((16, bp // _TL, _TL), jnp.uint32),
        interpret=interpret,
    )(states)
    return out.reshape(16, bp)[:, :b]


def chacha_blocks_jnp(states: jax.Array) -> jax.Array:
    """Bit-identical jnp twin of :func:`chacha_blocks` (the CPU/test path).

    The 10 double rounds run under ``lax.scan`` instead of unrolled: the
    same 960 quarter-round ops as one compact 96-op loop body, which
    XLA:CPU compiles in under a second and fuses into one kernel (the
    unrolled form measured ~30 s to compile and 5x slower to run)."""
    consts = [jnp.full(states.shape[1:], c, jnp.uint32) for c in _CONSTS]
    init = jnp.stack(consts + [states[i] for i in range(12)])

    def body(x, _):
        return jnp.stack(_double_round([x[i] for i in range(16)])), None

    out, _ = jax.lax.scan(body, init, None, length=10)
    return out + init


# --------------------------------------------------------------------------
# Poly1305 (vectorized jnp, radix-2^11 limbs)
# --------------------------------------------------------------------------


def _le_words(b: jax.Array) -> jax.Array:
    """(..., 4k) uint8 -> (..., k) uint32 little-endian words."""
    w = b.astype(jnp.uint32).reshape(*b.shape[:-1], -1, 4)
    return w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)


def _words_to_u8(w: jax.Array) -> jax.Array:
    """(..., k) uint32 -> (..., 4k) uint8 little-endian bytes."""
    b = jnp.stack([w & 0xFF, (w >> 8) & 0xFF, (w >> 16) & 0xFF,
                   (w >> 24) & 0xFF], axis=-1)
    return b.reshape(*w.shape[:-1], -1).astype(jnp.uint8)


def _limbs(w: jax.Array, pad_bit: bool) -> jax.Array:
    """(..., 4) uint32 le words of one 16-byte block -> (..., 12) limbs.

    ``pad_bit`` adds 2^128 (every AEAD MAC block is a full padded 16-byte
    block, §2.8.1), which lands in limb 11 at bit 128 - 11*11 = 7.
    """
    limbs = []
    for a in range(_NLIMB - 1):
        i, off = divmod(_RADIX * a, 32)
        v = w[..., i] >> off
        if off > 32 - _RADIX:
            v = v | (w[..., i + 1] << (32 - off))
        limbs.append(v & _LMASK)
    top = (w[..., 3] >> 25) & 0x7F  # bits 121..127
    if pad_bit:
        top = top | (1 << 7)
    limbs.append(top)
    return jnp.stack(limbs, axis=-1)


def _carry(h: jax.Array) -> jax.Array:
    """One full carry pass over (..., 12) limbs, folding the carry out of
    limb 11 back into limb 0 via 2^132 === 20 (mod p)."""
    out = []
    carry = jnp.zeros_like(h[..., 0])
    for k in range(_NLIMB):
        v = h[..., k] + carry
        out.append(v & _LMASK)
        carry = v >> _RADIX
    out[0] = out[0] + carry * _FOLD
    return jnp.stack(out, axis=-1)


def _poly_mul(a: jax.Array, b: jax.Array) -> jax.Array:
    """(h * r) mod 2^130-5 on (..., 12) limb arrays.

    Bounds (see _RADIX): a limbs <= 2^12 (one lazy add of a block on top of
    carried limbs), b limbs <= 2^11 (clamped r), so every column sum plus
    the x20 fold stays under 2^31 — no uint32 wrap anywhere.
    """
    # one (..., 12, 12) outer product, then anti-diagonal column sums via
    # shifted pads — ~60 traced ops instead of the 144-multiply schoolbook
    # expansion, which XLA:CPU runs measurably faster inside the scan
    outer = a[..., :, None] * b[..., None, :]
    pad0 = [(0, 0)] * (outer.ndim - 2)
    t = jnp.pad(outer[..., 0, :], pad0 + [(0, _NLIMB - 1)])
    for i in range(1, _NLIMB):
        t = t + jnp.pad(outer[..., i, :], pad0 + [(i, _NLIMB - 1 - i)])
    c = t[..., :_NLIMB].at[..., : _NLIMB - 1].add(t[..., _NLIMB:] * _FOLD)
    # two carry passes: the first leaves limb 0 <= 2^11 + 20*2^20, the
    # second restores limbs <= 2^11 + _FOLD (< 2^12, the lazy invariant)
    return _carry(_carry(c))


def _poly_final(h: jax.Array, s_bytes: jax.Array) -> jax.Array:
    """Final reduction + s addition: (..., 12) limbs -> (..., 16) u8 tag."""
    h = _carry(_carry(h))
    # fold bits 130/131 (limb 11 bits >= 9): 2^130 === 5 (mod p)
    hi = h[..., 11] >> 9
    h = h.at[..., 11].set(h[..., 11] & 0x1FF)
    h = h.at[..., 0].add(hi * 5)
    h = _carry(h)
    # conditional subtract p: g = h + 5; h >= p  <=>  g >= 2^130
    g = h.at[..., 0].add(5)
    g = _carry(g)
    ge = (g[..., 11] >> 9) > 0
    g = g.at[..., 11].set(g[..., 11] & 0x1FF)
    h = jnp.where(ge[..., None], g, h)
    # tag = (h + s) mod 2^128, byte-serialized little-endian
    out = []
    carry = jnp.zeros_like(s_bytes[..., 0], dtype=jnp.uint32)
    for j in range(16):
        a, off = divmod(8 * j, _RADIX)
        v = h[..., a] >> off
        if off > _RADIX - 8 and a + 1 < _NLIMB:
            v = v | (h[..., a + 1] << (_RADIX - off))
        v = (v & 0xFF) + s_bytes[..., j].astype(jnp.uint32) + carry
        out.append(v & 0xFF)
        carry = v >> 8
    return jnp.stack(out, axis=-1).astype(jnp.uint8)


def poly1305_tags(r_bytes: jax.Array, s_bytes: jax.Array,
                  mac_bytes: jax.Array, active: jax.Array) -> jax.Array:
    """Batched Poly1305 over block-aligned MAC input.

    r_bytes/s_bytes: (B, 16) uint8 halves of the one-time key (r unclamped
    — the clamp is applied here); mac_bytes: (B, 16*n) uint8, every block
    a full padded 16-byte block; active: (B, n) bool — inactive blocks
    leave the accumulator untouched (the masked-variable-length trick).
    Returns (B, 16) uint8 tags.
    """
    r = _limbs(_le_words(r_bytes & jnp.asarray(_R_CLAMP, jnp.uint8)),
               pad_bit=False)
    blocks = _limbs(_le_words(mac_bytes).reshape(r_bytes.shape[0], -1, 4),
                    pad_bit=True)  # (B, n, 12)
    h0 = jnp.zeros_like(r)

    def step(h, x):
        bl, act = x
        nh = _poly_mul(h + bl, r)
        return jnp.where(act[..., None], nh, h), None

    h, _ = jax.lax.scan(step, h0, (jnp.moveaxis(blocks, 1, 0),
                                   jnp.moveaxis(active, 1, 0)))
    return _poly_final(h, s_bytes)


# --------------------------------------------------------------------------
# RFC 8439 AEAD composition (seal/open share one jitted core)
# --------------------------------------------------------------------------


@functools.partial(jax.jit,
                   static_argnames=("seal", "use_pallas", "interpret"))
def aead_core(keys: jax.Array, nonces: jax.Array, data: jax.Array,
              lens: jax.Array, aads: jax.Array, aad_lens: jax.Array, *,
              seal: bool, use_pallas: bool = False,
              interpret: bool = False) -> tuple[jax.Array, jax.Array]:
    """Batched ChaCha20-Poly1305 seal or open core.

    keys (B, 32) u8, nonces (B, 12) u8, data (B, L) u8 (plaintext when
    sealing, ciphertext when opening; L a multiple of 64), lens (B,) i32
    true byte lengths, aads (B, A) u8 (A a multiple of 16), aad_lens (B,)
    i32.  Returns ``(other, tags)``: ``other`` is the ciphertext (seal) or
    plaintext (open), zero past ``lens``; ``tags`` the (B, 16) u8 Poly1305
    tags computed over the ciphertext either way — the open caller compares
    them against the received tags.

    jit compiles one program per (B, L, A) bucket triple; callers pad to
    pow2 buckets (provider/aead_device.py) so the bucket space stays small.
    """
    b, l = data.shape
    nb = l // 64
    reps = nb + 1  # block 0 is the Poly1305 one-time key (§2.6)
    kw = jnp.repeat(_le_words(keys), reps, axis=0).T          # (8, B*reps)
    nw = jnp.repeat(_le_words(nonces), reps, axis=0).T        # (3, B*reps)
    ctr = jnp.tile(jnp.arange(reps, dtype=jnp.uint32), b)[None]
    states = jnp.concatenate([kw, ctr, nw], axis=0)           # (12, B*reps)
    blocks = (chacha_blocks(states, interpret=interpret) if use_pallas
              else chacha_blocks_jnp(states)).reshape(16, b, reps)
    poly_key = _words_to_u8(jnp.moveaxis(blocks[:8, :, 0], 0, 1))  # (B, 32)
    ks = _words_to_u8(
        jnp.moveaxis(blocks[:, :, 1:], 0, 2).reshape(b, nb * 16))  # (B, L)
    mask = jnp.arange(l) < lens[:, None]
    other = jnp.where(mask, data ^ ks, 0).astype(jnp.uint8)
    ct = other if seal else jnp.where(mask, data, 0).astype(jnp.uint8)
    # MAC input (§2.8): padded AAD || padded ciphertext || le64 lengths —
    # block-aligned by construction, so per-lane lengths mask block-wise
    aad_m = jnp.where(jnp.arange(aads.shape[1]) < aad_lens[:, None],
                      aads, 0).astype(jnp.uint8)
    len_block = jnp.concatenate([_le64(aad_lens), _le64(lens)], axis=-1)
    mac_bytes = jnp.concatenate([aad_m, ct, len_block], axis=1)
    block_starts_aad = jnp.arange(aads.shape[1] // 16) * 16
    block_starts_ct = jnp.arange(l // 16) * 16
    active = jnp.concatenate([
        block_starts_aad < aad_lens[:, None],
        block_starts_ct < lens[:, None],
        jnp.ones((b, 1), bool),  # the length block is always processed
    ], axis=1)
    tags = poly1305_tags(poly_key[:, :16], poly_key[:, 16:], mac_bytes,
                         active)
    return other, tags


def _le64(n: jax.Array) -> jax.Array:
    """(B,) int lengths -> (B, 8) uint8 little-endian (lengths < 2^31)."""
    n = n.astype(jnp.uint32)
    lo = jnp.stack([(n >> (8 * i)) & 0xFF for i in range(4)], axis=-1)
    return jnp.concatenate([lo, jnp.zeros_like(lo)],
                           axis=-1).astype(jnp.uint8)


def use_pallas_default() -> bool:
    """Pallas fast path on real TPU; jnp twin elsewhere (core.keccak's
    shared ``QRP2P_PALLAS`` policy — tests run interpret mode explicitly)."""
    return _use_pallas()
