"""Pallas TPU kernel for the SHA-256 compression function.

SPHINCS+-SHA2 is pure SHA-256: a verify is hundreds of compressions and a
sign is hundreds of thousands, all over wide batches (batch x chains/trees
instances per call).  The jnp ``core.sha256.compress`` keeps the 8-word
state and 16-word schedule window as HBM-resident arrays across the 64
``lax.fori_loop`` rounds — the same materialise-between-rounds pattern that
made the jnp Keccak sponge ~11x slower than its kernel.  This kernel holds
state and schedule in 24 vector registers for all 64 (fully unrolled)
rounds; HBM sees one 64-byte block in and a 32-byte state out per instance.

Layout identical to core/keccak_pallas.py: each of the 24 words is an
``(8, 128)`` uint32 tile over 1024 instances, launched through the shared
``sampler_call`` plumbing.  Oracle: the jnp path (itself hashlib-anchored by
tests/test_sha256.py); bit-exactness asserted by tests/test_sha256_pallas.py
eagerly and on-chip by the bench entry points.

Replaces (reference): the SHA-256 inside liboqs SPHINCS+-SHA2
(crypto/signatures.py:191-315).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .keccak_pallas import sampler_call
from .sha256 import _K, _rotr

_KI = [int(k) for k in np.asarray(_K)]


def _compress_tiles(words: list) -> list:
    """One SHA-256 compression over 24 word tiles: 8 state + 16 block words.

    Pure function of same-shaped uint32 arrays -> 8 uint32 arrays; the
    Pallas kernel calls it on VMEM tiles, tests call it eagerly.
    """
    a, b, c, d, e, f, g, h = words[:8]
    w = list(words[8:24])
    h0 = [a, b, c, d, e, f, g, h]
    for t in range(64):
        if t >= 16:
            x15, x2 = w[(t - 15) % 16], w[(t - 2) % 16]
            s0 = _rotr(x15, 7) ^ _rotr(x15, 18) ^ (x15 >> 3)
            s1 = _rotr(x2, 17) ^ _rotr(x2, 19) ^ (x2 >> 10)
            w[t % 16] = w[t % 16] + s0 + w[(t - 7) % 16] + s1
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + jnp.uint32(_KI[t]) + w[t % 16]
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        a, b, c, d, e, f, g, h = t1 + t2, a, b, c, d + t1, e, f, g
    out = [a, b, c, d, e, f, g, h]
    return [o + s for o, s in zip(out, h0)]


def _compress_kernel(in_hi_ref, in_lo_ref, out_ref):
    # sampler_call supplies two equal-width input refs; the 24 live words
    # (8 state + 16 block) are split 12/12 across them: in_hi rows 0..7 are
    # state, rows 8..11 are block words 0..3, in_lo rows 0..11 are block
    # words 4..15.  Purely a transport split — SHA-256 has no hi/lo lanes.
    words = [in_hi_ref[i] for i in range(12)] + [in_lo_ref[i] for i in range(12)]
    out = _compress_tiles(words)
    for i in range(8):
        out_ref[i] = out[i].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def compress_words(state_w: jax.Array, block_w: jax.Array, *, interpret: bool = False):
    """Batched SHA-256 compression over word-transposed inputs.

    Args:
      state_w: (8, B) uint32 current state words, batch minor.
      block_w: (16, B) uint32 message-block words (big-endian packed).

    Returns:
      (8, B) uint32 updated state words.
    """
    words = jnp.concatenate([state_w, block_w], axis=0)  # (24, B)
    out = sampler_call(
        _compress_kernel, 12, 8, words[:12], words[12:], interpret=interpret
    )
    return out.astype(jnp.uint32)
