"""Device mesh + sharded batched handshake step.

TPU-native design
-----------------
Handshakes are embarrassingly parallel, so the right decomposition is pure
data sharding: a 1-D mesh over all chips with the batch dimension of every
operand sharded across the ``"batch"`` axis.  XLA then runs each chip's shard
of keygen/encaps/decaps locally with zero cross-chip traffic on the hot path;
the only collective is a `psum` reducing per-shard success counts — a few
bytes over ICI per flush.

This replaces nothing in the reference (it had no device mesh; its
"distributed backend" is asyncio TCP, networking/p2p_node.py:277-397, which we
keep host-side unchanged): the mesh exists purely inside the crypto provider,
below the plugin boundary.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..kem import mlkem
from ..pyref.mlkem_ref import PARAMS

BATCH_AXIS = "batch"


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)} "
                f"(set XLA_FLAGS=--xla_force_host_platform_device_count={n_devices} "
                f"JAX_PLATFORMS=cpu before importing jax to emulate a mesh)"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (BATCH_AXIS,))


def shard_devices(n: int | None = None) -> list[jax.Device]:
    """The first ``n`` devices of the placement axis (default: all).

    The latency-path twin of :func:`make_mesh`: where the mesh shards ONE
    big batch across chips (GSPMD), the placement axis
    (provider/scheduler.py) pins each small queue flush WHOLE onto one of
    these devices.  Raises like make_mesh when fewer devices exist."""
    devs = jax.devices()
    if n is not None:
        if len(devs) < n:
            raise RuntimeError(
                f"need {n} devices, have {len(devs)} "
                f"(set XLA_FLAGS=--xla_force_host_platform_device_count={n} "
                f"JAX_PLATFORMS=cpu before importing jax to emulate)"
            )
        devs = devs[:n]
    return list(devs)


def shard_batch(mesh: Mesh, *arrays: jax.Array):
    """Place arrays with their leading (batch) dim sharded across the mesh."""
    sharding = NamedSharding(mesh, P(BATCH_AXIS))
    out = tuple(jax.device_put(a, sharding) for a in arrays)
    return out[0] if len(out) == 1 else out


def handshake_step(p, d, z, m):
    """One full KEM handshake over a batch: keygen -> encaps -> decaps.

    Returns (ek, ct, key_initiator, key_responder, n_ok) where n_ok is the
    global count of shared-secret agreements (a cross-chip psum when the batch
    is sharded).  This is the framework's "training step" analog: the complete
    per-handshake device computation of reference app/messaging.py:546-1134's
    five hot FFI calls, batched.
    """
    ek, dk = mlkem.keygen(p, d, z)
    key_e, ct = mlkem.encaps(p, ek, m)
    key_d = mlkem.decaps(p, dk, ct)
    n_ok = jnp.sum(jnp.all(key_e == key_d, axis=-1).astype(jnp.int32))
    return ek, ct, key_e, key_d, n_ok


@functools.cache
def make_sharded_handshake(mesh: Mesh, param_name: str = "ML-KEM-768"):
    """Jit the full handshake step with batch-sharded in/out shardings."""
    p = PARAMS[param_name]
    data_sh = NamedSharding(mesh, P(BATCH_AXIS))
    scalar_sh = NamedSharding(mesh, P())
    return jax.jit(
        functools.partial(handshake_step, p),
        in_shardings=(data_sh,) * 3,
        out_shardings=(data_sh,) * 4 + (scalar_sh,),
    )
