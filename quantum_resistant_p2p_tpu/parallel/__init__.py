"""Multi-chip scale-out: device mesh + sharded batch crypto ops.

The reference has no device parallelism (SURVEY.md §2.3) — each KEM/signature
op is one serial FFI call into liboqs (reference: crypto/key_exchange.py:155).
Here the batch axis is the scaling axis: independent handshakes shard across
chips over ICI with `jax.sharding.NamedSharding`, and only tiny collectives
(psum of success counts) cross chips.
"""

from .mesh import (  # noqa: F401
    BATCH_AXIS,
    handshake_step,
    make_mesh,
    make_sharded_handshake,
    shard_batch,
    shard_devices,
)
