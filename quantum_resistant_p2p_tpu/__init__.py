"""quantum_resistant_p2p_tpu — a TPU-native post-quantum-secure P2P framework.

Brand-new framework with the capability set of the reference application
``ShadowCZEch/quantum-resistant-p2p`` (see SURVEY.md): post-quantum KEMs
(ML-KEM, FrodoKEM, HQC), signatures (ML-DSA, SPHINCS+), AEAD messaging,
encrypted key storage and audit logging, asyncio P2P networking — but with the
cryptographic core implemented as batched JAX/Pallas TPU programs instead of
serial ctypes calls into liboqs (reference: vendor/oqs.py, crypto/*.py).

Layering (mirrors SURVEY.md §7.1):

- ``core``     — primitive kernels: Keccak sponge, SHA-256, NTT, samplers, codecs
- ``kem``      — ML-KEM / FrodoKEM / HQC batch implementations
- ``sig``      — ML-DSA / SPHINCS+ batch implementations
- ``pyref``    — pure-Python FIPS reference implementations (bit-exactness oracle
                 and CPU fallback backend; hashlib is the Keccak oracle)
- ``provider`` — the algorithm-plugin boundary (same API shape as the
                 reference's crypto/ module) + async batching queue
- ``storage``  — encrypted key vault, atomic/locked file IO, encrypted audit log
- ``net``      — asyncio TCP P2P node, UDP discovery, node identity
- ``app``      — SecureMessaging protocol engine + MessageStore
- ``cli``      — interactive client (capability parity with the reference UI)
"""

__version__ = "0.1.0"
