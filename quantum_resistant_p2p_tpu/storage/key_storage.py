"""Password-locked encrypted key vault.

Capability parity with the reference's crypto/key_storage.py (796 LoC:
Argon2id KDF, per-entry AES-GCM, HMAC-derived opaque entry IDs, purpose keys,
password change, destructive reset, key history, on-demand decrypt,
best-effort zeroization) with a fresh, simpler data model:

* One master key derived from the password — Argon2id when the linked OpenSSL
  provides it (>= 3.2), otherwise scrypt (n=2^15, r=8, p=1; this image ships
  OpenSSL 3.0, so scrypt is the default here).  The KDF and its parameters are
  recorded in the vault header, so vaults remain readable across hosts.
* Every entry is AES-256-GCM encrypted under an HKDF-derived entry key; the
  entry's on-disk ID is HMAC-SHA256(index_key, name) so names never appear in
  plaintext.  The (name, value) pair lives inside the ciphertext, which lets
  the vault enumerate its own entries after unlock.
* ALL entries — including purpose keys — are re-encrypted on password change,
  so everything survives it (the reference needed a special "persistent
  purpose key" path for this; here it is the default behavior).
"""

from __future__ import annotations

import base64
import hashlib
import hmac as hmac_mod
import logging
import os
import secrets
import time
from pathlib import Path
from typing import Any, Iterable

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

from .secure_file import AtomicFile

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1
_CHECK_PLAINTEXT = b"qrp2p-tpu-vault-check-v1"


class KeyStorageError(Exception):
    pass


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode("ascii")


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def _derive_key(password: str, salt: bytes, kdf: dict) -> bytes:
    algo = kdf["algo"]
    if algo == "argon2id":
        from cryptography.hazmat.primitives.kdf.argon2 import Argon2id

        return Argon2id(
            salt=salt,
            length=32,
            iterations=kdf["iterations"],
            lanes=kdf["lanes"],
            memory_cost=kdf["memory_cost"],
        ).derive(password.encode())
    if algo == "scrypt":
        return hashlib.scrypt(
            password.encode(), salt=salt, n=kdf["n"], r=kdf["r"], p=kdf["p"], dklen=32,
            maxmem=256 * 1024 * 1024,
        )
    raise KeyStorageError(f"unknown KDF {algo!r}")


def _default_kdf() -> dict:
    try:
        from cryptography.hazmat.primitives.kdf.argon2 import Argon2id

        Argon2id(salt=b"\0" * 16, length=32, iterations=1, lanes=1, memory_cost=32)
        return {"algo": "argon2id", "iterations": 3, "lanes": 4, "memory_cost": 100 * 1024}
    except Exception:  # qrlint: disable=broad-except  — capability probe: any failure (old OpenSSL, import error) means "use scrypt", which IS the handling
        return {"algo": "scrypt", "n": 2**15, "r": 8, "p": 1}


def _subkey(master: bytes, label: bytes) -> bytes:
    return hmac_mod.new(master, b"qrp2p-tpu/" + label, hashlib.sha256).digest()


def get_app_data_dir() -> Path:
    d = Path(os.environ.get("QRP2P_TPU_HOME", Path.home() / ".qrp2p_tpu"))
    d.mkdir(parents=True, exist_ok=True)
    os.chmod(d, 0o700)
    return d


class KeyStorage:
    """Encrypted vault holding signature keypairs, shared-key history, purpose keys."""

    def __init__(self, path: str | os.PathLike | None = None):
        self.path = Path(path) if path else get_app_data_dir() / "vault.json"
        self._file = AtomicFile(self.path)
        self._master: bytes | None = None
        self._entry_key: bytes | None = None
        self._index_key: bytes | None = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def is_unlocked(self) -> bool:
        return self._master is not None

    def unlock(self, password: str) -> bool:
        """Unlock (or initialize) the vault.  Returns False on a bad password."""
        vault = self._file.read_json()
        if vault is None:
            self._init_vault(password)
            return True
        try:
            master = _derive_key(password, _unb64(vault["salt"]), vault["kdf"])
            check = vault["check"]
            AESGCM(master).decrypt(_unb64(check["nonce"]), _unb64(check["ct"]), None)
        except Exception:  # qrlint: disable=broad-except  — unlock contract: wrong password and corrupt vault both map to False; logging the cause would oracle which one it was
            return False
        self._set_master(master)
        return True

    def lock(self) -> None:
        self._zeroize()

    def _init_vault(self, password: str) -> None:
        salt = secrets.token_bytes(16)
        kdf = _default_kdf()
        master = _derive_key(password, salt, kdf)
        nonce = secrets.token_bytes(12)
        ct = AESGCM(master).encrypt(nonce, _CHECK_PLAINTEXT, None)
        self._file.write_json(
            {
                "format_version": FORMAT_VERSION,
                "salt": _b64(salt),
                "kdf": kdf,
                "check": {"nonce": _b64(nonce), "ct": _b64(ct)},
                "entries": {},
            }
        )
        self._set_master(master)
        logger.info("initialized new key vault at %s (kdf=%s)", self.path, kdf["algo"])

    def _set_master(self, master: bytes) -> None:
        self._master = master
        self._entry_key = _subkey(master, b"entry")
        self._index_key = _subkey(master, b"index")

    def _require_unlocked(self) -> None:
        if not self.is_unlocked:
            raise KeyStorageError("vault is locked")

    # -- entries ------------------------------------------------------------

    def _entry_id(self, name: str) -> str:
        assert self._index_key is not None
        return hmac_mod.new(self._index_key, name.encode(), hashlib.sha256).hexdigest()[:32]

    def _encrypt_entry(self, name: str, value: Any) -> dict:
        assert self._entry_key is not None
        import json

        payload = json.dumps({"name": name, "value": value}).encode()
        nonce = secrets.token_bytes(12)
        ct = AESGCM(self._entry_key).encrypt(nonce, payload, None)
        return {"nonce": _b64(nonce), "ct": _b64(ct), "created_at": time.time()}

    def _decrypt_entry(self, name: str, blob: dict) -> Any:
        assert self._entry_key is not None
        import json

        pt = AESGCM(self._entry_key).decrypt(_unb64(blob["nonce"]), _unb64(blob["ct"]), None)
        rec = json.loads(pt)
        if rec["name"] != name:
            raise KeyStorageError("entry name mismatch (index collision?)")
        return rec["value"]

    def store(self, name: str, value: Any) -> None:
        """Store a JSON-serializable value (bytes values: use store_bytes)."""
        self._require_unlocked()
        vault = self._file.read_json()
        vault["entries"][self._entry_id(name)] = self._encrypt_entry(name, value)
        self._file.write_json(vault)

    def retrieve(self, name: str, default: Any = None) -> Any:
        self._require_unlocked()
        vault = self._file.read_json()
        blob = vault["entries"].get(self._entry_id(name))
        if blob is None:
            return default
        try:
            return self._decrypt_entry(name, blob)
        except Exception as e:
            logger.error("failed to decrypt entry %r: %s", name, e)
            return default

    def delete(self, name: str) -> bool:
        self._require_unlocked()
        vault = self._file.read_json()
        removed = vault["entries"].pop(self._entry_id(name), None) is not None
        if removed:
            self._file.write_json(vault)
        return removed

    def store_bytes(self, name: str, value: bytes) -> None:
        self.store(name, {"__bytes__": _b64(value)})

    def retrieve_bytes(self, name: str) -> bytes | None:
        v = self.retrieve(name)
        if isinstance(v, dict) and "__bytes__" in v:
            return _unb64(v["__bytes__"])
        return None

    def list_entries(self) -> list[dict]:
        """Decrypt and enumerate all entries: [{name, created_at}]."""
        self._require_unlocked()
        import json

        vault = self._file.read_json()
        out = []
        assert self._entry_key is not None
        for blob in vault["entries"].values():
            try:
                pt = AESGCM(self._entry_key).decrypt(
                    _unb64(blob["nonce"]), _unb64(blob["ct"]), None
                )
            except Exception as e:
                logger.error("skipping undecryptable entry: %s", e)
                continue
            out.append({"name": json.loads(pt)["name"], "created_at": blob["created_at"]})
        return out

    # -- purpose keys -------------------------------------------------------

    def get_or_create_purpose_key(self, purpose: str, length: int = 32) -> bytes:
        """Stable random key for an internal purpose (e.g. the audit log).

        Survives password changes (all entries are re-encrypted on change).
        """
        self._require_unlocked()
        name = f"purpose_key_{purpose}"
        existing = self.retrieve_bytes(name)
        if existing is not None:
            return existing
        key = secrets.token_bytes(length)
        self.store_bytes(name, key)
        return key

    # Alias matching the reference's API (crypto/key_storage.py:259).
    get_or_create_persistent_key = get_or_create_purpose_key

    # -- shared-key history (reference: key_storage.py:678-782) -------------

    KEY_HISTORY_PREFIX = "peer_shared_key_"

    def save_peer_shared_key(self, peer_id: str, key: bytes, algo: str) -> str:
        name = f"{self.KEY_HISTORY_PREFIX}{peer_id}_{time.time():.6f}"
        self.store(name, {"key": _b64(key), "algorithm": algo, "peer_id": peer_id})
        return name

    def list_key_history(self, peer_id: str | None = None) -> list[dict]:
        out = []
        for ent in self.list_entries():
            if not ent["name"].startswith(self.KEY_HISTORY_PREFIX):
                continue
            if peer_id is not None and not ent["name"].startswith(
                self.KEY_HISTORY_PREFIX + peer_id + "_"
            ):
                continue
            out.append(ent)
        return sorted(out, key=lambda e: e["created_at"], reverse=True)

    def get_key_history_value(self, name: str) -> dict | None:
        """On-demand decrypt of a historic shared key (audit this at call sites)."""
        return self.retrieve(name)

    def delete_key_history(self, name: str) -> bool:
        return self.delete(name)

    def clear_key_history(self) -> int:
        n = 0
        for ent in self.list_key_history():
            n += self.delete(ent["name"])
        return n

    # -- password management -------------------------------------------------

    def change_password(self, old_password: str, new_password: str) -> bool:
        """Re-derive the master key and re-encrypt every entry."""
        self._require_unlocked()
        vault = self._file.read_json()
        try:
            old_master = _derive_key(old_password, _unb64(vault["salt"]), vault["kdf"])
        except Exception:  # qrlint: disable=broad-except  — same contract as unlock(): any KDF failure means "wrong password" -> False
            return False
        import hmac

        # constant-time: a byte-wise != would leak how much of the derived
        # master key matches (qrflow flow-secret-compare)
        if not hmac.compare_digest(old_master, self._master):
            return False
        # Decrypt all entries under the old keys.
        plain: list[tuple[str, Any]] = []
        import json

        assert self._entry_key is not None
        for blob in vault["entries"].values():
            try:
                pt = AESGCM(self._entry_key).decrypt(
                    _unb64(blob["nonce"]), _unb64(blob["ct"]), None
                )
                rec = json.loads(pt)
                plain.append((rec["name"], rec["value"]))
            except Exception as e:
                logger.error("entry lost during password change: %s", e)
        salt = secrets.token_bytes(16)
        kdf = _default_kdf()
        master = _derive_key(new_password, salt, kdf)
        self._set_master(master)
        nonce = secrets.token_bytes(12)
        ct = AESGCM(master).encrypt(nonce, _CHECK_PLAINTEXT, None)
        self._file.write_json(
            {
                "format_version": FORMAT_VERSION,
                "salt": _b64(salt),
                "kdf": kdf,
                "check": {"nonce": _b64(nonce), "ct": _b64(ct)},
                "entries": {
                    self._entry_id(name): self._encrypt_entry(name, value)
                    for name, value in plain
                },
            }
        )
        return True

    def reset_storage(self, new_password: str, create_backup: bool = False) -> None:
        """Destructive reset: drop every entry, re-key the vault."""
        if create_backup and self.path.exists():
            backup = Path(str(self.path) + f".pre-reset-{int(time.time())}")
            backup.write_bytes(self.path.read_bytes())
        self._zeroize()
        if self.path.exists():
            self.path.unlink()
        self._init_vault(new_password)

    # -- hygiene -------------------------------------------------------------

    def _zeroize(self) -> None:
        # Python can't reliably scrub immutable bytes; drop references so the
        # GC can reclaim them and nothing in this object can decrypt further.
        self._master = None
        self._entry_key = None
        self._index_key = None
