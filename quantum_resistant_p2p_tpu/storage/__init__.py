"""Host-side persistence: atomic/locked files, encrypted key vault, audit log.

Capability parity with the reference's utils/secure_file.py,
crypto/key_storage.py and app/logging.py (SURVEY.md §2 rows 7, 13, 14).
Everything here is host-only — no TPU involvement.
"""

from .secure_file import AtomicFile, FileLock
from .key_storage import KeyStorage, KeyStorageError
from .secure_logger import SecureLogger

__all__ = [
    "AtomicFile",
    "FileLock",
    "KeyStorage",
    "KeyStorageError",
    "SecureLogger",
]
