"""Atomic, lock-protected file IO for key vaults and audit logs.

Fills the role of the reference's utils/secure_file.py:118-397 (SecureFile:
fcntl/msvcrt handle locks, PID lock-files with stale detection, atomic
write-via-temp+rename with .bak fallback) with a fresh design:

* ``FileLock`` — an advisory inter-process lock: O_CREAT lockfile holding
  ``pid:timestamp``, fcntl.flock on POSIX; a lock older than STALE_AFTER
  seconds, or whose pid is dead, is broken automatically.
* ``AtomicFile`` — read/write JSON or raw bytes with write-to-temp + fsync +
  os.replace, keeping a ``.bak`` of the previous generation and falling back
  to it when the primary is corrupt.
"""

from __future__ import annotations

import contextlib
import errno
import json
import logging
import os
import tempfile
import time
from pathlib import Path
from typing import Any

try:  # POSIX
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX hosts
    fcntl = None  # type: ignore[assignment]

logger = logging.getLogger(__name__)

STALE_AFTER = 3600.0  # seconds after which a lockfile is presumed abandoned

#: lock-retry backoff bounds: start fast (a writer usually finishes in
#: milliseconds), grow 2x per miss so a contended lock doesn't spin the CPU,
#: never wait longer than the cap (keeps worst-case latency additive, not
#: multiplicative, near the deadline)
_BACKOFF_INITIAL = 0.005
_BACKOFF_CAP = 0.25


def backoff_delays(deadline: float):
    """Monotonic-deadline exponential backoff: yields sleep durations until
    ``time.monotonic()`` passes ``deadline``, then stops.

    Pure iterator — it never sleeps itself, so the SAME schedule drives both
    the sync path (``time.sleep``) and the async path (``asyncio.sleep``)
    without this module choosing a blocking primitive for its callers.
    """
    delay = _BACKOFF_INITIAL
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return
        yield min(delay, remaining, _BACKOFF_CAP)
        delay = min(delay * 2, _BACKOFF_CAP)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError as e:
        return e.errno == errno.EPERM
    return True


class FileLock:
    """Advisory inter-process lock guarding a data file.

    Creates ``<path>.lock`` containing ``pid:monotonic-wallclock``; stale locks
    (dead pid or older than STALE_AFTER) are removed and retaken.
    """

    def __init__(self, path: str | os.PathLike, timeout: float = 10.0):
        self.lock_path = Path(str(path) + ".lock")
        self.timeout = timeout
        self._fd: int | None = None

    def acquire(self) -> None:
        """Take the lock, sleeping between retries (SYNC-ONLY: blocks the
        calling thread; from a coroutine use :meth:`acquire_async` — qrlint's
        blocking-in-async rule rejects direct calls in ``async def``)."""
        delays = backoff_delays(time.monotonic() + self.timeout)
        while not self._try_once():
            delay = next(delays, None)
            if delay is None:
                raise TimeoutError(f"could not lock {self.lock_path}")
            time.sleep(delay)

    async def acquire_async(self) -> None:
        """Async twin of :meth:`acquire`: identical backoff schedule, but
        yields the event loop between retries instead of blocking it."""
        import asyncio

        delays = backoff_delays(time.monotonic() + self.timeout)
        while not self._try_once():
            delay = next(delays, None)
            if delay is None:
                raise TimeoutError(f"could not lock {self.lock_path}")
            await asyncio.sleep(delay)

    def _try_once(self) -> bool:
        """One non-blocking acquisition attempt."""
        self._break_if_stale()
        try:
            fd = os.open(self.lock_path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        except FileExistsError:
            return False
        os.write(fd, f"{os.getpid()}:{time.time()}".encode())
        if fcntl is not None:
            fcntl.flock(fd, fcntl.LOCK_EX)
        self._fd = fd
        return True

    def release(self) -> None:
        if self._fd is None:
            return
        try:
            if fcntl is not None:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            os.close(self._fd)
        finally:
            self._fd = None
            with contextlib.suppress(OSError):
                self.lock_path.unlink()

    def _break_if_stale(self) -> None:
        try:
            raw = self.lock_path.read_text()
            pid_s, ts_s = raw.split(":", 1)
            pid, ts = int(pid_s), float(ts_s)
        except (OSError, ValueError):
            return  # no lock, or unreadable (racing); let acquire loop retry
        if not _pid_alive(pid) or (time.time() - ts) > STALE_AFTER:
            logger.warning("breaking stale lock %s (pid=%s)", self.lock_path, pid_s)
            with contextlib.suppress(OSError):
                self.lock_path.unlink()

    def __enter__(self) -> "FileLock":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()

    async def __aenter__(self) -> "FileLock":
        await self.acquire_async()
        return self

    async def __aexit__(self, *exc: object) -> None:
        self.release()


class AtomicFile:
    """Crash-safe reads/writes of a single file with backup fallback."""

    def __init__(self, path: str | os.PathLike):
        self.path = Path(path)
        self.bak_path = Path(str(path) + ".bak")
        self.lock = FileLock(path)

    # -- JSON ---------------------------------------------------------------

    def read_json(self, default: Any = None) -> Any:
        with self.lock:
            for candidate in (self.path, self.bak_path):
                try:
                    with open(candidate, "r", encoding="utf-8") as f:
                        data = json.load(f)
                    if candidate is self.bak_path:
                        logger.warning("restored %s from backup", self.path)
                    return data
                except FileNotFoundError:
                    continue
                except (json.JSONDecodeError, OSError) as e:
                    logger.error("unreadable %s: %s", candidate, e)
                    continue
            return default

    def write_json(self, data: Any) -> None:
        with self.lock:
            self._replace(json.dumps(data, indent=2).encode("utf-8"))

    # -- raw bytes ----------------------------------------------------------

    def read_bytes(self) -> bytes:
        with self.lock:
            try:
                return self.path.read_bytes()
            except FileNotFoundError:
                return b""

    def write_bytes(self, data: bytes) -> None:
        with self.lock:
            self._replace(data)

    def append_bytes(self, data: bytes) -> None:
        with self.lock:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.path, "ab") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())

    # -- internals ----------------------------------------------------------

    def _replace(self, payload: bytes) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=self.path.parent, prefix=self.path.name + ".tmp")
        try:
            os.write(fd, payload)
            os.fsync(fd)
        finally:
            os.close(fd)
        if self.path.exists():
            with contextlib.suppress(OSError):
                os.replace(self.path, self.bak_path)
        os.replace(tmp, self.path)
        os.chmod(self.path, 0o600)
