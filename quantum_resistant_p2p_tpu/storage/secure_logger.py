"""Encrypted, append-only audit log.

Capability parity with the reference's app/logging.py (449 LoC): per-record
AES-256-GCM encryption, length-prefixed records appended to daily files,
thread safety, corruption recovery by scanning forward to the next decryptable
record, filtered queries, event summaries, aggregate security metrics, and
clear_logs.

Record wire format (fresh design):
    magic  b"QL"                  (2 bytes)
    length uint32 big-endian      (nonce + ciphertext length)
    nonce  12 bytes
    ct     AES-256-GCM(key, nonce, json-payload, ad=b"qrp2p-tpu-log-v1")

The magic makes scan-ahead recovery cheap: after a corrupt record, search for
the next b"QL" and try again (reference recovers similarly: app/logging.py:160-207).
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
from collections import Counter
from datetime import datetime, timezone
from pathlib import Path
from typing import Any, Iterator

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

logger = logging.getLogger(__name__)

_MAGIC = b"QL"
_AD = b"qrp2p-tpu-log-v1"


class SecureLogger:
    """AES-GCM encrypted audit log with daily files under ``log_dir``."""

    def __init__(self, key: bytes, log_dir: str | os.PathLike | None = None):
        if len(key) != 32:
            raise ValueError("SecureLogger requires a 32-byte key")
        self._aead = AESGCM(key)
        from .key_storage import get_app_data_dir

        self.log_dir = Path(log_dir) if log_dir else get_app_data_dir() / "logs"
        self.log_dir.mkdir(parents=True, exist_ok=True)
        self._lock = threading.RLock()

    # -- write --------------------------------------------------------------

    def _current_file(self) -> Path:
        day = datetime.now(timezone.utc).strftime("%Y-%m-%d")
        return self.log_dir / f"{day}.qlog"

    def log_event(self, event_type: str, **fields: Any) -> None:
        record = {"event_type": event_type, "timestamp": time.time(), **fields}
        payload = json.dumps(record, separators=(",", ":")).encode()
        nonce = os.urandom(12)
        ct = self._aead.encrypt(nonce, payload, _AD)
        frame = _MAGIC + struct.pack(">I", len(nonce) + len(ct)) + nonce + ct
        with self._lock:
            with open(self._current_file(), "ab") as f:
                f.write(frame)
                f.flush()
                os.fsync(f.fileno())

    # -- read ---------------------------------------------------------------

    def _iter_file(self, path: Path) -> Iterator[dict]:
        try:
            blob = path.read_bytes()
        except OSError:
            return
        pos = 0
        while pos < len(blob):
            idx = blob.find(_MAGIC, pos)
            if idx < 0:
                break
            try:
                (length,) = struct.unpack_from(">I", blob, idx + 2)
                start = idx + 6
                chunk = blob[start : start + length]
                if len(chunk) != length:
                    raise ValueError("truncated record")
                pt = self._aead.decrypt(chunk[:12], chunk[12:], _AD)
                yield json.loads(pt)
                pos = start + length
            except Exception:
                # Corrupt record: scan ahead to the next magic.
                pos = idx + 2
                logger.debug("skipping corrupt log record in %s @%d", path, idx)

    def get_events(
        self,
        event_type: str | None = None,
        start_time: float | None = None,
        end_time: float | None = None,
        limit: int | None = None,
    ) -> list[dict]:
        with self._lock:
            out: list[dict] = []
            for path in sorted(self.log_dir.glob("*.qlog")):
                for rec in self._iter_file(path):
                    if event_type is not None and rec.get("event_type") != event_type:
                        continue
                    ts = rec.get("timestamp", 0.0)
                    if start_time is not None and ts < start_time:
                        continue
                    if end_time is not None and ts > end_time:
                        continue
                    out.append(rec)
            out.sort(key=lambda r: r.get("timestamp", 0.0))
            if limit is not None:
                out = out[-limit:]
            return out

    def get_event_summary(self) -> dict[str, int]:
        return dict(Counter(rec.get("event_type", "?") for rec in self.get_events()))

    def get_security_metrics(self) -> dict[str, Any]:
        """Aggregate usage metrics (reference: app/logging.py:379-432)."""
        events = self.get_events()
        algos: Counter[str] = Counter()
        totals: Counter[str] = Counter()
        bytes_sent = bytes_received = 0
        for rec in events:
            et = rec.get("event_type", "?")
            totals[et] += 1
            if "algorithm" in rec:
                algos[str(rec["algorithm"])] += 1
            if et == "message_sent":
                bytes_sent += int(rec.get("size", 0))
            elif et == "message_received":
                bytes_received += int(rec.get("size", 0))
        return {
            "total_events": len(events),
            "event_counts": dict(totals),
            "messages_sent": totals.get("message_sent", 0),
            "messages_received": totals.get("message_received", 0),
            "key_exchanges": totals.get("key_exchange", 0),
            "bytes_sent": bytes_sent,
            "bytes_received": bytes_received,
            "algorithms_used": dict(algos),
        }

    # -- hygiene -------------------------------------------------------------

    def zeroize(self) -> None:
        """Drop the AEAD (and with it the only handle on the log key): after
        this the instance can neither write nor decrypt — re-derive the
        purpose key from the vault to resume logging."""
        with self._lock:
            self._aead = None

    def clear_logs(self) -> int:
        with self._lock:
            n = 0
            for path in self.log_dir.glob("*.qlog"):
                path.unlink()
                n += 1
            return n
