"""Curses TUI — live peer list + chat pane over the CLI core.

Tightens L6 parity with the reference's desktop app
(ui/main_window.py:1-517 + peer_list.py + messaging_widget.py): a live
two-pane terminal UI with the peer list refreshing every 2 s (the
reference's connection-poll cadence, ui/messaging_widget.py:54-56), unread
counts in the peer rows (ui/peer_list.py:220-230), a scrolling message
pane, and an input line that accepts plain text (sent to the selected
peer) or any slash command from the CLI surface (cli.py HELP).

Implementation notes: stdlib ``curses`` only (textual/urwid are not in
this image).  The command processor is the SAME ``cli.CLI`` object the
line client uses — the TUI replaces stdin/stdout with a key poller and a
ring buffer, so every command path stays single-sourced and tested.  Pure
helpers (`peer_rows`, `wrap_lines`) are unit-testable without a terminal
(tests/test_tui.py).

Run: ``qrp2p --tui`` (or ``python -m quantum_resistant_p2p_tpu --tui``).
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time

from .cli import CLI

PEER_PANE_W = 28
POLL_S = 0.05        # key poll cadence
REFRESH_S = 2.0      # peer-list refresh (reference cadence)
HISTORY = 500


def peer_rows(cli: CLI, selected: int) -> list[tuple[str, bool]]:
    """-> [(row text, is_selected)] for the peer pane.

    Mirrors the reference peer list's status column (Discovered /
    Connected / Secure, ui/peer_list.py:166-196) plus unread counts.
    """
    rows: list[tuple[str, bool]] = []
    if cli.messaging is None:
        return rows
    m = cli.messaging
    connected = set(cli.node.get_peers()) if cli.node else set()
    discovered = set()
    if cli.discovery:
        discovered = set(cli.discovery.get_discovered_nodes())
    ordered = sorted(connected) + sorted(discovered - connected)
    for i, pid in enumerate(ordered):
        if pid in connected:
            status = "secure" if m.verify_key_exchange_state(pid) else "conn"
        else:
            status = "disc"
        unread = cli.store.get_unread_count(pid)
        mark = f" ({unread})" if unread else ""
        text = f"{pid[:12]} {status}{mark}"
        rows.append((text[: PEER_PANE_W - 2], i == selected))
    return rows


def wrap_lines(lines, width: int, height: int) -> list[str]:
    """Last ``height`` display rows of ``lines`` wrapped to ``width``."""
    out: list[str] = []
    for line in lines:
        line = str(line)
        if not line:
            out.append("")
            continue
        while line:
            out.append(line[:width])
            line = line[width:]
    return out[-height:]


class _PaneWriter:
    """File-like object capturing CLI .print output into the message pane."""

    def __init__(self, buf: collections.deque):
        self.buf = buf

    def write(self, text: str) -> None:
        for ln in text.split("\n"):
            if ln.strip("\r"):
                self.buf.append(ln.rstrip("\r"))

    def flush(self) -> None:  # pragma: no cover - file protocol
        pass


class Tui:
    def __init__(self, cli: CLI):
        self.cli = cli
        self.lines: collections.deque = collections.deque(maxlen=HISTORY)
        cli.out = _PaneWriter(self.lines)
        self.input = ""
        self.selected = 0
        self._dirty = True

    # ------------------------------------------------------------- selection

    def _ordered_peers(self) -> list[str]:
        connected = set(self.cli.node.get_peers()) if self.cli.node else set()
        discovered = (set(self.cli.discovery.get_discovered_nodes())
                      if self.cli.discovery else set())
        return sorted(connected) + sorted(discovered - connected)

    def selected_peer(self) -> str | None:
        peers = self._ordered_peers()
        if not peers:
            return None
        return peers[min(self.selected, len(peers) - 1)]

    # ------------------------------------------------------------------ keys

    async def on_key(self, ch: int) -> bool:
        """Process one key; returns False when the TUI should exit."""
        import curses

        if ch in (curses.KEY_UP,):
            self.selected = max(0, self.selected - 1)
        elif ch in (curses.KEY_DOWN, 9):  # down or Tab
            self.selected = min(self.selected + 1,
                                max(0, len(self._ordered_peers()) - 1))
        elif ch in (curses.KEY_BACKSPACE, 127, 8):
            self.input = self.input[:-1]
        elif ch in (10, 13):  # Enter
            line = self.input.strip()
            self.input = ""
            if not line:
                return True
            if line.split()[0] in ("/showkey", "/passwd", "/reset"):
                # these flows prompt interactively on stdin, which curses
                # owns; keep them in the line client where the prompt works
                self.lines.append(f"{line.split()[0]} is not available in the "
                                  "TUI — run the line client (qrp2p without "
                                  "--tui) for interactive prompts")
            elif line.startswith("/"):
                if not await self.cli.handle(line):
                    return False
            else:
                peer = self.selected_peer()
                if peer is None:
                    self.lines.append("no peer selected (plain text sends to peer)")
                else:
                    # direct send: no shlex round-trip, so quotes/apostrophes
                    # in chat text survive; peer id is already fully resolved
                    sent = await self.cli.messaging.send_message(
                        peer, line.encode()
                    )
                    self.lines.append(f"[me -> {peer[:8]}] {line}" if sent
                                      else "send failed")
            # reading a peer's pane clears its unread count, like the
            # reference's bold-count reset on selection
            peer = self.selected_peer()
            if peer:
                self.cli.store.mark_read(peer)
        elif 32 <= ch < 127:
            self.input += chr(ch)
        self._dirty = True
        return True

    # ---------------------------------------------------------------- render

    def render(self, scr) -> None:
        import curses

        h, w = scr.getmaxyx()
        scr.erase()
        chat_w = max(20, w - PEER_PANE_W - 1)
        # peer pane
        scr.addnstr(0, 0, "peers (↑/↓ select)".ljust(PEER_PANE_W), PEER_PANE_W,
                    curses.A_BOLD)
        for y, (text, sel) in enumerate(peer_rows(self.cli, self.selected)):
            if y + 1 >= h - 2:
                break
            attr = curses.A_REVERSE if sel else curses.A_NORMAL
            scr.addnstr(y + 1, 0, text.ljust(PEER_PANE_W - 1), PEER_PANE_W - 1, attr)
        for y in range(h - 2):
            scr.addch(y, PEER_PANE_W, curses.ACS_VLINE)
        # message pane
        for y, ln in enumerate(wrap_lines(self.lines, chat_w, h - 3)):
            scr.addnstr(y, PEER_PANE_W + 1, ln, chat_w)
        # input line
        scr.hline(h - 2, 0, curses.ACS_HLINE, w)
        prompt = f"> {self.input}"
        scr.addnstr(h - 1, 0, prompt, w - 1)
        scr.move(h - 1, min(len(prompt), w - 2))
        scr.refresh()

    # ------------------------------------------------------------------ loop

    async def run(self, scr) -> None:
        import curses

        curses.curs_set(1)
        scr.nodelay(True)
        scr.keypad(True)
        self.lines.append("TUI: ↑/↓ pick a peer, type to chat, /help for commands")
        last_refresh = 0.0
        while True:
            ch = scr.getch()
            if ch != -1:
                if not await self.on_key(ch):
                    break
            now = time.monotonic()
            if now - last_refresh > REFRESH_S:
                last_refresh = now
                self._dirty = True
            if self._dirty:
                self._dirty = False
                try:
                    self.render(scr)
                except curses.error:
                    pass  # terminal resized mid-draw; next frame fixes it
                except Exception:
                    logging.getLogger(__name__).exception("TUI render failed")
            await asyncio.sleep(POLL_S)
        await self.cli.stop()


def run_tui(cli: CLI) -> None:
    """Login must have happened; runs the asyncio+curses loop to exit."""
    import curses

    def _main(scr):
        async def amain():
            # swap cli.out into the pane BEFORE start() so the startup
            # banner (port, backend, native-core status) lands in the UI
            tui = Tui(cli)
            await cli.start()
            await tui.run(scr)

        asyncio.run(amain())

    curses.wrapper(_main)
