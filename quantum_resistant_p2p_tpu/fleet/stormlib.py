"""Storm workload environment — shared by the single-process storm bench
(tools/swarm_bench.py) and every fleet gateway subprocess (fleet/gateway.py).

Three things live here because BOTH sides need them:

* :func:`storm_env` — the process-environment guard a storm run needs:
  raise the fd soft limit (thousands of live TCP sessions in one
  process) and save/restore the module-global ``KEY_EXCHANGE_TIMEOUT``.
  Both effects are PROCESS-LOCAL, which is exactly why this is a context
  manager the fleet harness applies inside each gateway subprocess —
  applying them once in the driver would leave every other process at
  the defaults, and a raising storm session must never poison the next
  run's timeouts (the restore runs in the ``finally``).
* :class:`StormAEAD` — bench-only stdlib encrypt-then-MAC AEAD so the
  full handshake (incl. the ke_test probe) and bulk messaging run on
  images without the ``cryptography`` wheel.  Never registered as a
  provider.
* :func:`register_storm_providers` — idempotent registration of the
  hash-based STORM-KEM / STORM-SIG toys for both backends, so a storm
  measures the SERVING LOOP (transport, protocol, queues, batching,
  admission) rather than raw crypto throughput.
* :func:`prewarm_facades` — warm every pow2 flush bucket a live storm
  can land in (the run_swarm --prewarm lesson: a cold bucket silently
  degrades its whole window to the cpu fallback), shared by the swarm
  bench's planes and each gateway subprocess's engine.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import hmac
import os
from typing import Iterator


def seeded_jitter_rng(seed: int, *labels: str) -> "random.Random":
    """A deterministic per-entity jitter stream: the run's seed XOR a
    digest of the entity labels (e.g. ``gateway_id, router_id`` for one
    control link).  Every backoff/jitter site in the fleet derives its
    RNG here so a seeded storm replays byte-identically — and NEVER via
    ``hash()``, whose per-process salt would silently defeat the seeding
    across gateway subprocesses."""
    import random

    tag = hashlib.sha256(":".join(labels).encode()).digest()[:4]
    return random.Random(int(seed) ^ int.from_bytes(tag, "big"))


def raise_fd_limit(need: int) -> None:
    """A 10k-session storm needs ~2 fds per session in one process: lift
    the soft RLIMIT_NOFILE to the hard cap (best-effort)."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < need:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(max(need, soft), hard), hard))
    except (ImportError, ValueError, OSError):  # pragma: no cover
        pass


@contextlib.contextmanager
def storm_env(ke_timeout: float, fd_need: int = 0) -> Iterator[None]:
    """Enter the storm process environment: generous protocol timeout
    (cold compiles / batched flushes must not race the 20 s default),
    raised fd limit.  Restores ``KEY_EXCHANGE_TIMEOUT`` on exit even when
    the storm raises — a failed fleet session cannot poison the next
    run's timeouts in the same process."""
    from ..app import messaging as _messaging

    if fd_need:
        raise_fd_limit(fd_need)
    old_timeout = _messaging.KEY_EXCHANGE_TIMEOUT
    _messaging.KEY_EXCHANGE_TIMEOUT = ke_timeout
    try:
        yield
    finally:
        _messaging.KEY_EXCHANGE_TIMEOUT = old_timeout


async def prewarm_facades(facades, limit: int, floor: int = 1) -> list[int]:
    """Warm every pow2 flush bucket from ``floor`` up through ``limit``
    on each (non-None) batching facade, off-loop; returns the sizes
    warmed.  Without this a traffic burst lands on cold buckets and the
    degrade path quietly serves the whole window from the cpu fallback —
    warming always includes the ``floor`` bucket itself, which is what
    every flush uses when the floor exceeds the concurrency level."""
    sizes, b = [], max(1, floor)
    while b <= limit or not sizes:
        sizes.append(b)
        b *= 2
    loop = asyncio.get_running_loop()
    for facade in facades:
        if facade is None:
            continue
        await loop.run_in_executor(None, facade.warmup, tuple(sizes))
    return sizes


class StormAEAD:
    """Stdlib encrypt-then-MAC AEAD (HMAC-SHA256 over a SHA-256 keystream)
    — bench-only: lets the FULL handshake (incl. the ke_test AEAD probe)
    and bulk messaging run on images without the ``cryptography`` wheel.
    Mirrors the test suites' ToyAEAD; never registered as a provider."""

    name = "STORM-AEAD"
    display_name = "STORM-AEAD (bench-only stdlib)"
    key_size = 32
    nonce_size = 16

    @staticmethod
    def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
        out = b""
        ctr = 0
        while len(out) < n:
            out += hashlib.sha256(key + nonce + ctr.to_bytes(8, "big")).digest()
            ctr += 1
        return out[:n]

    def encrypt(self, key, plaintext, associated_data=None):
        nonce = os.urandom(self.nonce_size)
        ct = bytes(a ^ b for a, b in
                   zip(plaintext, self._keystream(key, nonce, len(plaintext))))
        tag = hmac.new(key, nonce + ct + (associated_data or b""),
                       hashlib.sha256).digest()
        return nonce + ct + tag

    def decrypt(self, key, data, associated_data=None):
        if len(data) < self.nonce_size + 32:
            raise ValueError("ciphertext too short")
        nonce, ct, tag = (data[: self.nonce_size], data[self.nonce_size:-32],
                          data[-32:])
        want = hmac.new(key, nonce + ct + (associated_data or b""),
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise ValueError("authentication failed")
        return bytes(a ^ b for a, b in
                     zip(ct, self._keystream(key, nonce, len(ct))))


_STORM_REGISTERED = False


def register_storm_providers() -> None:
    """Register the stdlib STORM-KEM/STORM-SIG toys for BOTH backends (the
    'tpu' registration rides the device-path queue machinery; 'cpu' arms
    the degrade fallback) — idempotent."""
    global _STORM_REGISTERED
    if _STORM_REGISTERED:
        return

    from ..provider.base import KeyExchangeAlgorithm, SignatureAlgorithm
    from ..provider.registry import register_kem, register_signature

    class StormKEM(KeyExchangeAlgorithm):
        name = "STORM-KEM"
        display_name = "STORM-KEM (bench-only stdlib)"
        public_key_len = 32
        secret_key_len = 32
        ciphertext_len = 32
        shared_secret_len = 32

        def __init__(self, backend="cpu"):
            self.backend = backend

        def generate_keypair(self):
            sk = os.urandom(32)
            return hashlib.sha256(b"pk" + sk).digest(), sk

        def encapsulate(self, public_key):
            ct = os.urandom(32)
            return ct, hashlib.sha256(public_key + ct).digest()

        def decapsulate(self, secret_key, ciphertext):
            pk = hashlib.sha256(b"pk" + secret_key).digest()
            return hashlib.sha256(pk + ciphertext).digest()

    class StormSig(SignatureAlgorithm):
        name = "STORM-SIG"
        display_name = "STORM-SIG (bench-only stdlib)"
        public_key_len = 32
        secret_key_len = 32
        signature_len = 32

        def __init__(self, backend="cpu"):
            self.backend = backend

        def generate_keypair(self):
            sk = os.urandom(32)
            return hashlib.sha256(b"pk" + sk).digest(), sk

        def sign(self, secret_key, message):
            pk = hashlib.sha256(b"pk" + secret_key).digest()
            return hashlib.sha256(b"sig" + pk + message).digest()

        def verify(self, public_key, message, signature):
            return hmac.compare_digest(
                signature,
                hashlib.sha256(b"sig" + public_key + message).digest())

    register_kem("STORM-KEM", lambda backend, devices=0: StormKEM(backend),
                 ("cpu", "tpu"))
    register_signature("STORM-SIG",
                       lambda backend, devices=0: StormSig(backend),
                       ("cpu", "tpu"))
    _STORM_REGISTERED = True
