"""Gateway worker: one P2PNode + SecureMessaging engine per process.

Spawned by :class:`fleet.manager.GatewayFleet` as
``python -m quantum_resistant_p2p_tpu.fleet.gateway '<json config>'``
(or run in-process as an asyncio task — ``spawn="task"`` — for
deterministic tests; same code path, same control protocol over real
localhost TCP).

Lifecycle:

1. enter :func:`fleet.stormlib.storm_env` — per-PROCESS fd limit +
   protocol-timeout guard (the single-process storm's environment,
   applied where it actually lives: in this process);
2. start the P2P node on an ephemeral port, build the engine
   (``use_batching=True`` — the full queue/scheduler/autotuner plane),
   wait for warm-up;
3. dial the router's control port, send ``__gw_hello__`` (the P2P port
   peers will be routed to), then heartbeat every ``hb_interval`` with
   liveness stats and the cumulative SLO probe totals the router
   aggregates fleet-wide;
4. answer ``__gw_probe__`` (the fleet breaker's half-open canary) with
   ``__gw_probe_ok__``;
5. on ``__gw_stop__``: write the per-node ``slo_report.json``
   (:meth:`app.messaging.SecureMessaging.slo_report`) into
   ``report_dir``, send ``__gw_bye__`` with final stats, exit 0.

Abrupt death (SIGKILL from the chaos plan, or task cancellation) skips
4-5 by construction — peers see a dropped TCP session, the router sees
missed heartbeats, and the fleet handoff machinery takes over.

HA control plane (docs/fleet.md): when the config carries a ``routers``
list instead of the single ``router_host``/``router_port`` pair, the
gateway maintains ONE control link PER router replica — hello +
heartbeats to all of them, with a seeded-jitter reconnect loop per link
so a rolled router's respawn sees a staggered redial wave, not a
thundering herd.  Authority frames (``__gw_stek__`` / ``__gw_drain__``)
carry the sender's lease epoch; the gateway honors the highest epoch it
has seen and drops anything older (the gateway-side half of stale-lease
fencing — a demoted router's pushes are rejected and flight-recorded,
never installed).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import sys
from pathlib import Path
from typing import Any, Awaitable, Callable

from ..obs import flight as obs_flight
from . import control
from .stormlib import (StormAEAD, prewarm_facades, register_storm_providers,
                       seeded_jitter_rng, storm_env)

logger = logging.getLogger(__name__)

#: config defaults; the manager overrides via the JSON blob
DEFAULTS: dict[str, Any] = {
    "gateway_id": "gw0",
    "router_host": "127.0.0.1",
    "bind_host": "127.0.0.1",
    "router_port": 0,
    #: HA mode: a list of ``{"router", "host", "port"}`` replica
    #: endpoints.  None/empty = the classic single-router link above.
    "routers": None,
    #: seeds the per-link reconnect jitter (the storm passes its seed)
    "seed": 0,
    "providers": "stdlib",
    "max_peers": 0,
    "handshake_budget": 0,
    "bulk_lane_capacity": 0,
    "max_batch": 4096,
    "max_wait_ms": 3.0,
    "autotune": True,
    "shard_devices": 0,
    "ke_timeout": 120.0,
    "hb_interval": 0.25,
    "report_dir": None,
    "fd_need": 4096,
    "prewarm_cap": 64,
    #: live telemetry endpoints (obs/http.py): None = off (the global
    #: default), 0 = ephemeral port — announced through hello/heartbeat
    #: so the router and tools/qrtop.py can find each gateway's scrape
    "telemetry_port": None,
}


def _engine_stats(engine, received: int) -> dict[str, Any]:
    """The compact heartbeat payload: liveness + the counters the fleet
    sums (device/fallback trips feed the fleet_device_served SLO; the
    cost totals feed the router's aggregated ``/fleet`` economics)."""
    q = engine._collect_queues()
    gw = {
        "msgs_received": received,
        "connections": len(engine.node.get_peers()),
        "admitted": engine.node.admitted,
        "connection_sheds": engine.node.sheds,
        "handshake_sheds": engine._ctr_handshake_sheds.value,
        "device_trips": q.get("device_trips", 0),
        "fallback_trips": q.get("fallback_trips", 0),
        "breaker_state": q.get("breaker_state"),
        "device_served_fraction": q.get("device_served_fraction"),
        "handshake_attempts": engine._handshake_latency.count,
        "telemetry_port": engine.telemetry_port,
        "cost": engine.cost.totals(),
        # the resumption/drain surface (the router's /fleet view and the
        # roll-storm report read these per gateway)
        "draining": engine.draining,
        "tickets_minted": engine._ctr_tickets_minted.value,
        "resumes_ok": engine._ctr_resumes_ok.value,
        "resume_rejects": engine._ctr_resume_rejects.value,
    }
    total = fb = 0
    for fam in ("kem_queue", "sig_queue", "fused_queue"):
        for qq in q.get(fam, {}).values():
            total += qq["ops"]
            fb += qq["fallback_ops"]
    gw["ops"] = total
    gw["fallback_ops"] = fb
    return gw


async def _dispatch(msg: dict, send: Callable[[dict], Awaitable[None]],
                    engine, gid: str, state: dict[str, Any]) -> str:
    """Handle one router control frame (shared by the single-router loop
    and every HA link).  Returns ``"ok"`` / ``"drain"`` / ``"stop"``;
    transport errors from the probe reply propagate to the caller (its
    link is dead).

    ``state["lease_epoch"]`` is the highest lease epoch this gateway has
    honored: authority frames (STEK pushes, drains) below it come from a
    router that provably LOST the lease — dropped and flight-recorded,
    the gateway-side half of stale-lease fencing.  Frames without an
    epoch (a standalone router) carry 0 and the gate stays inert."""
    mtype = msg.get("type")
    if mtype == control.GW_PROBE:
        await send({
            "type": control.GW_PROBE_OK, "gateway": gid,
            "n": msg.get("n"),
        })
    elif mtype == control.GW_TICKET_KEYS:
        epoch = int(msg.get("lease_epoch") or 0)
        if epoch < state["lease_epoch"]:
            state["stale_authority_rejects"] += 1
            obs_flight.record("stale_authority_rejected", gateway=gid,
                              frame="stek", lease_epoch=epoch,
                              honored=state["lease_epoch"])
            logger.warning("gateway %s: STEK push at stale lease epoch %d "
                           "(honoring %d) rejected", gid, epoch,
                           state["lease_epoch"])
            return "ok"
        state["lease_epoch"] = epoch
        # the fleet's ticket-sealing keys (current + previous): replace
        # the engine's private ring so tickets minted ANYWHERE in the
        # fleet resume here
        try:
            installed = engine.tickets.install([
                (str(ep), bytes.fromhex(str(key_hex)))
                for ep, key_hex in (msg.get("keys") or [])
            ], guard=True)
        except (ValueError, TypeError):
            logger.warning("gateway %s: malformed STEK push ignored", gid)
        else:
            if not installed:
                # same-lease-epoch ordering race (STEKRing.install guard):
                # a pre-rotation push arriving after the rotation must not
                # re-mint under the key the fleet is dropping
                state["stale_authority_rejects"] += 1
                obs_flight.record("stale_stek_push_skipped", gateway=gid)
    elif mtype == control.GW_DRAIN:
        epoch = int(msg.get("lease_epoch") or 0)
        if epoch < state["lease_epoch"]:
            state["stale_authority_rejects"] += 1
            obs_flight.record("stale_authority_rejected", gateway=gid,
                              frame="drain", lease_epoch=epoch,
                              honored=state["lease_epoch"])
            logger.warning("gateway %s: drain at stale lease epoch %d "
                           "(honoring %d) rejected", gid, epoch,
                           state["lease_epoch"])
            return "ok"
        state["lease_epoch"] = epoch or state["lease_epoch"]
        state["drain_reason"] = "router"
        return "drain"
    elif mtype == control.GW_STOP:
        return "stop"
    return "ok"


async def run_gateway(cfg: dict[str, Any]) -> None:
    """Run one gateway until the router says stop (or the task is
    cancelled — the abrupt-death path)."""
    cfg = {**DEFAULTS, **cfg}
    gid = str(cfg["gateway_id"])
    from ..app.messaging import SecureMessaging
    from ..net.p2p_node import P2PNode
    from ..provider import get_kem, get_signature

    with storm_env(float(cfg["ke_timeout"]), fd_need=int(cfg["fd_need"])):
        if cfg["providers"] == "stdlib":
            register_storm_providers()
            kem_name, sig_name = "STORM-KEM", "STORM-SIG"
            aead: Any = StormAEAD()
        else:
            kem_name, sig_name = "ML-KEM-768", "ML-DSA-65"
            try:
                from ..provider import get_symmetric

                aead = get_symmetric("AES-256-GCM")
            except Exception:
                logger.warning("gateway %s: real AEAD unavailable, "
                               "degrading to the stdlib storm AEAD", gid,
                               exc_info=True)
                aead = StormAEAD()
        node = P2PNode(node_id=gid, host=str(cfg["bind_host"]), port=0,
                       max_peers=int(cfg["max_peers"]))
        await node.start()
        telemetry_port = cfg.get("telemetry_port")
        engine = SecureMessaging(
            node, kem=get_kem(kem_name, "tpu"), symmetric=aead,
            signature=get_signature(sig_name, "tpu"),
            use_batching=True, max_batch=int(cfg["max_batch"]),
            max_wait_ms=float(cfg["max_wait_ms"]),
            autotune=bool(cfg["autotune"]),
            shard_devices=int(cfg["shard_devices"]),
            max_inflight_handshakes=int(cfg["handshake_budget"]),
            bulk_lane_capacity=int(cfg["bulk_lane_capacity"]),
            telemetry_port=(int(telemetry_port)
                            if telemetry_port is not None else None),
        )
        received = 0

        def on_msg(peer_id, message):
            nonlocal received
            if not message.is_system:
                received += 1

        engine.register_message_listener(on_msg)
        await engine.wait_ready()

        cap = int(cfg["prewarm_cap"])
        if cap and engine._bkem is not None:
            # warm every pow2 flush bucket this gateway's share of the
            # storm can hit
            await prewarm_facades(
                (engine._bkem, engine._bsig, engine._bfused),
                min(int(cfg["max_batch"]), cap))

        # -- control links -------------------------------------------------
        # multi=False is the classic single-router lifecycle (one link,
        # loss = exit); multi=True is the HA control plane: one link per
        # router replica, each with its own reconnect loop
        router_list = cfg.get("routers")
        multi = bool(router_list)
        if not multi:
            router_list = [{"router": "router",
                            "host": cfg["router_host"],
                            "port": cfg["router_port"]}]
        stop_ev = asyncio.Event()
        # graceful drain triggers: a router's __gw_drain__ verb OR a
        # SIGTERM (a rolling restart / orchestrator shutdown delivers
        # SIGTERM — a PLANNED restart must not look like a crash)
        drain_ev = asyncio.Event()
        #: cross-link shared state: the highest lease epoch honored (the
        #: gateway-side fencing gate) + the drain reason for the report
        state: dict[str, Any] = {"lease_epoch": 0,
                                 "stale_authority_rejects": 0,
                                 "drain_reason": None}
        #: live per-router send closures (a link registers on hello,
        #: deregisters on loss) — the bye fan-out at exit walks these
        senders: dict[str, Callable[[dict], Awaitable[None]]] = {}
        writers: dict[str, asyncio.StreamWriter] = {}

        def hello_frame() -> dict:
            return {
                "type": control.GW_HELLO, "gateway": gid,
                "p2p_port": node.port, "pid": os.getpid(),
                "max_peers": int(cfg["max_peers"]),
                # announce the scrape surface: the router's /fleet view
                # and tools/qrtop.py find each gateway's endpoints here
                "telemetry_port": engine.telemetry_port,
            }

        def hb_frame() -> dict:
            stats = _engine_stats(engine, received)
            # the lease surface rides the heartbeat: which authority
            # epoch this gateway honors, over how many router links
            stats["lease_epoch"] = state["lease_epoch"]
            stats["router_links"] = len(senders)
            stats["stale_authority_rejects"] = state["stale_authority_rejects"]
            return {
                "type": control.GW_HEARTBEAT, "gateway": gid,
                "stats": stats,
                "slo_totals": {
                    k: list(v)
                    for k, v in engine.slo.probe_totals().items()
                },
            }

        async def heartbeat(send: Callable[[dict], Awaitable[None]]) -> None:
            while not stop_ev.is_set():
                await asyncio.sleep(float(cfg["hb_interval"]))
                try:
                    await send(hb_frame())
                except (ConnectionError, OSError):
                    if not multi:
                        stop_ev.set()
                    return

        async def link(rt: dict[str, Any]) -> None:
            """One router replica's control-link lifecycle: dial, hello,
            heartbeat, dispatch — redialing with seeded-jitter backoff in
            HA mode so a rolled router's respawn sees a staggered wave."""
            rid = str(rt.get("router") or "router")
            # deterministic per-(gateway, router) jitter stream
            rng = seeded_jitter_rng(int(cfg["seed"]), gid, rid)
            backoff = 0.05
            while not (stop_ev.is_set() or drain_ev.is_set()):
                try:
                    reader, writer = await asyncio.open_connection(
                        str(rt["host"]), int(rt["port"]))
                except OSError:
                    if not multi:
                        return  # classic mode: no router, no gateway
                    await asyncio.sleep(backoff * (0.5 + rng.random()))
                    backoff = min(backoff * 2.0, 2.0)
                    continue
                backoff = 0.05
                # one writer, two senders (heartbeat task + the dispatch
                # loop's probe replies): serialize sends — two coroutines
                # suspended in the same drain() while the router
                # back-pressures the transport trip asyncio's
                # single-waiter assert and kill the heartbeat task
                send_lock = asyncio.Lock()

                async def send(frame: dict, _w=writer,
                               _lock=send_lock) -> None:
                    async with _lock:
                        await control.send_ctrl(_w, frame)

                hb_task: asyncio.Task | None = None
                lost = False
                try:
                    await send(hello_frame())
                    senders[rid] = send
                    writers[rid] = writer
                    hb_task = asyncio.create_task(heartbeat(send))
                    while True:
                        read_t = asyncio.ensure_future(
                            control.read_ctrl(reader))
                        drain_t = asyncio.ensure_future(drain_ev.wait())
                        stop_t = asyncio.ensure_future(stop_ev.wait())
                        try:
                            await asyncio.wait(
                                {read_t, drain_t, stop_t},
                                return_when=asyncio.FIRST_COMPLETED)
                        except asyncio.CancelledError:
                            # the whole link task is being torn down while
                            # we were blocked in the select: the read task
                            # would otherwise outlive us and log its EOF
                            # as an unretrieved exception
                            read_t.cancel()
                            read_t.add_done_callback(
                                lambda t: None if t.cancelled()
                                else t.exception())
                            raise
                        finally:
                            drain_t.cancel()
                            stop_t.cancel()
                        if not read_t.done():
                            # drain/stop fired: leave the link OPEN — the
                            # epilogue still owes this router a bye frame.
                            # The cancel is a no-op when an EOF raced in
                            # just now, so consume the task's outcome
                            # either way or it surfaces much later as an
                            # unretrieved-exception warning
                            read_t.cancel()
                            read_t.add_done_callback(
                                lambda t: None if t.cancelled()
                                else t.exception())
                            return
                        msg = read_t.result()
                        verdict = await _dispatch(msg, send, engine, gid,
                                                  state)
                        if verdict == "drain":
                            drain_ev.set()
                            return
                        if verdict == "stop":
                            stop_ev.set()
                            return
                except (asyncio.IncompleteReadError, ConnectionError,
                        OSError):
                    lost = True
                finally:
                    if hb_task is not None:
                        hb_task.cancel()
                    if lost:
                        senders.pop(rid, None)
                        writers.pop(rid, None)
                        writer.close()
                if not multi:
                    return  # classic mode: link loss = exit, no redial
                await asyncio.sleep(backoff * (0.5 + rng.random()))

        link_tasks = [asyncio.create_task(link(rt)) for rt in router_list]
        loop = asyncio.get_running_loop()
        sigterm_armed = False
        if cfg.get("own_process"):
            # subprocess mode only (main() sets the flag): an in-process
            # task gateway must not steal the driver's SIGTERM handling
            try:
                loop.add_signal_handler(signal.SIGTERM, drain_ev.set)
                sigterm_armed = True
            except (NotImplementedError, ValueError, RuntimeError):
                pass  # non-main thread / platform without signal support
        try:
            drain_t = asyncio.ensure_future(drain_ev.wait())
            stop_t = asyncio.ensure_future(stop_ev.wait())
            waits: set[asyncio.Future] = {drain_t, stop_t}
            if not multi:
                # classic mode additionally exits when its ONLY link ends
                # (router gone); HA links redial forever instead
                waits |= set(link_tasks)
            try:
                await asyncio.wait(waits,
                                   return_when=asyncio.FIRST_COMPLETED)
            finally:
                drain_t.cancel()
                stop_t.cancel()
            if drain_ev.is_set() and not stop_ev.is_set():
                # the graceful-drain protocol (app/messaging.py): stop
                # admitting (/readyz -> 503 draining), flush outboxes,
                # nudge every peer to resume — via ticket — on its ring
                # successor; then fall through to the report/bye path
                await engine.drain(
                    reason=state.get("drain_reason") or "sigterm")
            # per-node SLO report first (the fleet merge input), then the
            # final stats frame
            stop_ev.set()
            report_dir = cfg.get("report_dir")
            if report_dir:
                path = Path(report_dir) / f"{gid}_slo_report.json"
                report = json.dumps(engine.slo_report(), indent=2,
                                    sort_keys=True)
                try:
                    await asyncio.get_running_loop().run_in_executor(
                        None, path.write_text, report)
                except OSError:
                    logger.exception("gateway %s: slo report write failed",
                                     gid)
            for _rid, send in sorted(senders.items()):
                try:
                    await send({
                        "type": control.GW_BYE, "gateway": gid,
                        "stats": _engine_stats(engine, received),
                    })
                except (ConnectionError, OSError):
                    pass
        finally:
            # runs on the graceful path AND on task cancellation (the
            # in-process abrupt-death mode): close every transport so
            # peers see the drop immediately
            stop_ev.set()
            for t in link_tasks:
                t.cancel()
            if sigterm_armed:
                try:
                    loop.remove_signal_handler(signal.SIGTERM)
                except (NotImplementedError, ValueError, RuntimeError):
                    pass
            engine.stop_telemetry()
            for w in writers.values():
                w.close()
            await node.stop()


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m quantum_resistant_p2p_tpu.fleet.gateway "
              "'<json config>'", file=sys.stderr)
        return 2
    # the single argument is an inline JSON blob, or a path to one
    blob = argv[0]
    if not blob.lstrip().startswith("{") and Path(blob).is_file():
        blob = Path(blob).read_text()
    cfg = json.loads(blob)
    # this process IS the gateway: SIGTERM means "drain gracefully"
    cfg["own_process"] = True
    logging.basicConfig(level=logging.WARNING)
    asyncio.run(run_gateway(cfg))
    return 0


if __name__ == "__main__":
    sys.exit(main())
