"""Router replica worker + the RouterFleet driver (docs/fleet.md
"HA control plane").

Two halves:

* :func:`run_router` — ONE control-plane replica as its own process
  (``python -m quantum_resistant_p2p_tpu.fleet.router '<json config>'``):
  a :class:`fleet.manager.GatewayFleet` in **attach** mode (fixed control
  port, spawns nothing, members materialize on gateway hellos) with a
  :class:`fleet.lease.LeaderLease` deciding whether THIS replica holds
  STEK-rotation and admission authority.  SIGTERM = graceful stop (close
  the listener, stop renewing — followers claim after the TTL).

* :class:`RouterFleet` — the driver that owns the WHOLE two-tier pod: it
  pre-allocates stable control/telemetry ports, spawns N router replicas
  and G gateway processes (each gateway dials EVERY router), runs the
  seeded chaos tick (``kill_router`` / ``pause_router`` through
  faults/plan.py's ``router_control`` hook), and drives the router-roll:
  SIGTERM → await exit → respawn on the SAME ports → await reachable,
  one replica at a time.  ``spawn="task"`` runs every replica in-process
  for deterministic tests (same code path; kills degrade to abrupt
  listener teardown).

The driver deliberately has NO control-protocol surface of its own: role
discovery goes through each replica's ``/fleet`` telemetry view (or
direct object access in task mode), so the wire protocol stays exactly
the verbs the qrproto model checks.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import socket
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path
from typing import Any, Callable

from ..faults import plan as _faults
from ..obs import flight as obs_flight
from .manager import GatewayFleet
from .ring import HashRing

logger = logging.getLogger(__name__)

#: how long a router respawn may take before the roll declares it wedged
ROUTER_REGISTER_TIMEOUT_S = 30.0


def _free_port(host: str = "127.0.0.1") -> int:
    """Reserve-and-release one ephemeral port: the classic pre-allocation
    trick — a respawned replica must come back on the SAME port the
    gateways' reconnect loops and the clients' failover order are already
    dialing, so the port is chosen before the first spawn."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


# -- the replica worker --------------------------------------------------------


async def run_router(cfg: dict[str, Any],
                     *, ready_cb: Callable[[GatewayFleet], None] | None = None,
                     ) -> None:
    """Run one control-plane replica until SIGTERM/cancellation.

    ``cfg`` keys: ``router_id``, ``rank``, ``ctrl_port``, ``peers``
    (the OTHER replicas: ``[{"router", "host", "port"}, ...]``),
    ``telemetry_port``, plus the GatewayFleet knobs (``hb_interval``,
    ``per_gateway_max_peers``, ``handshake_budget``, ``seed``,
    ``lease_ttl_s``, ``lease_stagger_s``, ``ticket_key_rotation_s``).
    ``ready_cb`` (task mode) receives the live fleet object."""
    fleet = GatewayFleet(
        0,
        attach=True,
        spawn="process",
        seed=int(cfg.get("seed") or 0),
        hb_interval=float(cfg.get("hb_interval") or 0.25),
        hb_miss_limit=int(cfg.get("hb_miss_limit") or 4),
        per_gateway_max_peers=int(cfg.get("per_gateway_max_peers") or 0),
        handshake_budget=int(cfg.get("handshake_budget") or 0),
        host=str(cfg.get("host") or "127.0.0.1"),
        ctrl_port=int(cfg["ctrl_port"]),
        router_id=str(cfg.get("router_id") or "rt0"),
        router_rank=int(cfg.get("rank") or 0),
        router_peers=list(cfg.get("peers") or ()),
        lease_ttl_s=(float(cfg["lease_ttl_s"])
                     if cfg.get("lease_ttl_s") is not None else None),
        lease_stagger_s=(float(cfg["lease_stagger_s"])
                         if cfg.get("lease_stagger_s") is not None else None),
        telemetry_port=(int(cfg["telemetry_port"])
                        if cfg.get("telemetry_port") is not None else None),
        ticket_key_rotation_s=float(cfg.get("ticket_key_rotation_s") or 0.0),
    )
    stop_ev = asyncio.Event()
    loop = asyncio.get_running_loop()
    sigterm_armed = False
    if cfg.get("own_process"):
        try:
            loop.add_signal_handler(signal.SIGTERM, stop_ev.set)
            sigterm_armed = True
        except (NotImplementedError, ValueError, RuntimeError):
            pass
    await fleet.start()
    if ready_cb is not None:
        ready_cb(fleet)
    try:
        await stop_ev.wait()
    finally:
        if sigterm_armed:
            try:
                loop.remove_signal_handler(signal.SIGTERM)
            except (NotImplementedError, ValueError, RuntimeError):
                pass
        await fleet.stop()


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m quantum_resistant_p2p_tpu.fleet.router "
              "'<json config>'", file=sys.stderr)
        return 2
    blob = argv[0]
    if not blob.lstrip().startswith("{") and Path(blob).is_file():
        blob = Path(blob).read_text()
    cfg = json.loads(blob)
    cfg["own_process"] = True
    logging.basicConfig(level=logging.WARNING)
    asyncio.run(run_router(cfg))
    return 0


# -- the driver ----------------------------------------------------------------


class RouterMember:
    """Driver-side state for one router replica."""

    def __init__(self, router_id: str, rank: int, host: str,
                 ctrl_port: int, telemetry_port: int):
        self.router_id = router_id
        self.rank = rank
        self.host = host
        self.ctrl_port = ctrl_port
        self.telemetry_port = telemetry_port
        self.proc: Any = None  # spawn="process"
        self.task: asyncio.Task | None = None  # spawn="task"
        self.fleet: GatewayFleet | None = None  # task mode only
        self.killed = False
        self.restarts = 0

    @property
    def alive(self) -> bool:
        if self.proc is not None:
            return self.proc.returncode is None
        return self.task is not None and not self.task.done()

    def endpoint(self) -> dict[str, Any]:
        return {"router": self.router_id, "host": self.host,
                "port": self.ctrl_port}


class RouterFleet:
    """N replicated routers + G gateways, all owned by this driver.

    The consistent-hash machinery the data plane uses for peer→gateway
    placement places ROUTERS too: :attr:`router_ring` is a
    :class:`fleet.ring.HashRing` over router ids — clients walk
    ``successors(peer_id)`` for their per-peer failover order, so router
    load spreads and every client agrees on the order without
    coordination."""

    def __init__(
        self,
        routers: int = 2,
        gateways: int = 3,
        *,
        spawn: str = "process",
        providers: str = "stdlib",
        seed: int = 0,
        hb_interval: float = 0.25,
        hb_miss_limit: int = 4,
        per_gateway_max_peers: int = 0,
        handshake_budget: int = 0,
        gateway_kw: dict[str, Any] | None = None,
        report_dir: str | Path | None = None,
        host: str = "127.0.0.1",
        lease_ttl_s: float | None = None,
        lease_stagger_s: float | None = None,
        ticket_key_rotation_s: float = 0.0,
        register_timeout: float = 60.0,
        telemetry: bool = True,
    ):
        if routers < 1:
            raise ValueError(f"need >= 1 router, got {routers}")
        if spawn not in ("process", "task"):
            raise ValueError(f"spawn must be 'process' or 'task', got {spawn!r}")
        self.spawn = spawn
        self.providers = providers
        self.seed = seed
        self.hb_interval = hb_interval
        self.hb_miss_limit = hb_miss_limit
        self.per_gateway_max_peers = per_gateway_max_peers
        self.handshake_budget = handshake_budget
        self.gateway_kw = dict(gateway_kw or {})
        self.report_dir = Path(report_dir) if report_dir is not None else None
        self.host = host
        self.lease_ttl_s = lease_ttl_s
        self.lease_stagger_s = lease_stagger_s
        self.ticket_key_rotation_s = ticket_key_rotation_s
        self._register_timeout = register_timeout
        self._telemetry = telemetry
        # stable ports BEFORE any spawn: respawns rebind the same ones
        self.routers: dict[str, RouterMember] = {}
        for i in range(routers):
            rid = f"rt{i}"
            self.routers[rid] = RouterMember(
                rid, i, host, _free_port(host),
                _free_port(host) if telemetry else 0)
        #: routers on the SAME ring machinery the data plane uses —
        #: per-peer failover order for clients and qrtop
        self.router_ring = HashRing(sorted(self.routers), vnodes=16,
                                    seed=seed)
        self.gateway_ids = [f"gw{i}" for i in range(gateways)]
        self._gw_procs: dict[str, Any] = {}
        self._gw_tasks: dict[str, asyncio.Task] = {}
        self._chaos_task: asyncio.Task | None = None
        self._running = False
        self.router_kills = 0
        self.router_pauses = 0

    # -- config ---------------------------------------------------------------

    def router_endpoints(self) -> list[dict[str, Any]]:
        return [m.endpoint() for _rid, m in sorted(self.routers.items())]

    def _router_config(self, member: RouterMember) -> dict[str, Any]:
        peers = [m.endpoint() for rid, m in sorted(self.routers.items())
                 if rid != member.router_id]
        return {
            "router_id": member.router_id,
            "rank": member.rank,
            "host": self.host,
            "ctrl_port": member.ctrl_port,
            "peers": peers,
            "telemetry_port": (member.telemetry_port
                               if self._telemetry else None),
            "hb_interval": self.hb_interval,
            "hb_miss_limit": self.hb_miss_limit,
            "per_gateway_max_peers": self.per_gateway_max_peers,
            "handshake_budget": self.handshake_budget,
            "seed": self.seed,
            "lease_ttl_s": self.lease_ttl_s,
            "lease_stagger_s": self.lease_stagger_s,
            "ticket_key_rotation_s": self.ticket_key_rotation_s,
        }

    def _gateway_config(self, gid: str) -> dict[str, Any]:
        cfg = {
            "gateway_id": gid,
            "bind_host": self.host,
            "routers": self.router_endpoints(),
            "seed": self.seed,
            "providers": self.providers,
            "max_peers": self.per_gateway_max_peers,
            "handshake_budget": self.handshake_budget,
            "hb_interval": self.hb_interval,
            "report_dir": str(self.report_dir) if self.report_dir else None,
            "telemetry_port": 0 if self._telemetry else None,
        }
        cfg.update(self.gateway_kw)
        return cfg

    # -- lifecycle ------------------------------------------------------------

    async def start(self) -> None:
        """Routers first (reachable), then gateways, then wait until
        every router has seen every gateway register AND a leader holds
        the lease — the storm must not start against a fleet whose STEK
        authority is still unsettled."""
        if self.report_dir is not None:
            self.report_dir.mkdir(parents=True, exist_ok=True)
        self._running = True
        for _rid, member in sorted(self.routers.items()):
            await self._spawn_router(member)
        await self._await_routers_reachable(self._register_timeout)
        for gid in self.gateway_ids:
            await self._spawn_gateway(gid)
        await self._await_gateways_registered(self._register_timeout)
        await self.await_leader(self._register_timeout)
        self._chaos_task = asyncio.create_task(self._chaos_loop())
        logger.info("router fleet up: %d routers, %d gateways",
                    len(self.routers), len(self.gateway_ids))

    async def _spawn_router(self, member: RouterMember) -> None:
        cfg = self._router_config(member)
        member.killed = False
        if self.spawn == "task":
            member.fleet = None

            def on_ready(fleet: GatewayFleet, m=member) -> None:
                m.fleet = fleet

            member.task = asyncio.create_task(
                run_router(cfg, ready_cb=on_ready),
                name=f"router:{member.router_id}")
            return
        stderr = asyncio.subprocess.DEVNULL
        log_f = None
        if self.report_dir is not None:
            log_path = self.report_dir / f"{member.router_id}.log"
            stderr = log_f = await asyncio.get_running_loop().run_in_executor(
                None, lambda: open(log_path, "ab"))
        try:
            member.proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m",
                "quantum_resistant_p2p_tpu.fleet.router", json.dumps(cfg),
                stdout=asyncio.subprocess.DEVNULL, stderr=stderr,
                start_new_session=True,
            )
        finally:
            if log_f is not None:
                log_f.close()

    async def _spawn_gateway(self, gid: str) -> None:
        cfg = self._gateway_config(gid)
        if self.spawn == "task":
            from .gateway import run_gateway

            self._gw_tasks[gid] = asyncio.create_task(
                run_gateway(cfg), name=f"gateway:{gid}")
            return
        stderr = asyncio.subprocess.DEVNULL
        log_f = None
        if self.report_dir is not None:
            log_path = self.report_dir / f"{gid}.log"
            stderr = log_f = await asyncio.get_running_loop().run_in_executor(
                None, lambda: open(log_path, "wb"))
        try:
            self._gw_procs[gid] = await asyncio.create_subprocess_exec(
                sys.executable, "-m",
                "quantum_resistant_p2p_tpu.fleet.gateway", json.dumps(cfg),
                stdout=asyncio.subprocess.DEVNULL, stderr=stderr,
                start_new_session=True,
            )
        finally:
            if log_f is not None:
                log_f.close()

    async def stop(self) -> None:
        """Gateways down first (SIGTERM = graceful drain; they write their
        slo reports), routers after — the reverse of start."""
        self._running = False
        if self._chaos_task is not None:
            self._chaos_task.cancel()
        for gid, proc in sorted(self._gw_procs.items()):
            if proc.returncode is None:
                try:
                    proc.terminate()
                except ProcessLookupError:  # pragma: no cover
                    pass
        for gid, proc in sorted(self._gw_procs.items()):
            try:
                await asyncio.wait_for(proc.wait(), 10.0)
            except asyncio.TimeoutError:
                proc.kill()
                await proc.wait()
        for gid, task in sorted(self._gw_tasks.items()):
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.debug("gateway task %s raised during stop",
                             gid, exc_info=True)
        for _rid, member in sorted(self.routers.items()):
            await self._stop_router(member, graceful=True)

    async def _stop_router(self, member: RouterMember,
                           graceful: bool) -> None:
        if member.proc is not None:
            if member.proc.returncode is None:
                try:
                    if graceful:
                        member.proc.terminate()
                    else:
                        member.proc.kill()
                except ProcessLookupError:  # pragma: no cover
                    pass
            try:
                await asyncio.wait_for(member.proc.wait(), 10.0)
            except asyncio.TimeoutError:
                member.proc.kill()
                await member.proc.wait()
            member.proc = None
        if member.task is not None:
            member.task.cancel()
            try:
                await member.task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.debug("router task %s raised during stop",
                             member.router_id, exc_info=True)
            member.task = None
            member.fleet = None

    # -- readiness / role discovery -------------------------------------------

    def _fetch_fleet_view(self, member: RouterMember) -> dict[str, Any] | None:
        """One /fleet scrape (blocking; callers run it in the executor)."""
        url = (f"http://{member.host}:{member.telemetry_port}/fleet")
        try:
            with urllib.request.urlopen(url, timeout=2.0) as resp:
                return json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError):
            return None

    async def router_view(self, rid: str) -> dict[str, Any] | None:
        """The replica's router-stats block (task mode: direct object
        access; process mode: its /fleet telemetry view)."""
        member = self.routers[rid]
        if member.fleet is not None:
            return member.fleet.stats()
        if not self._telemetry:
            return None
        doc = await asyncio.get_running_loop().run_in_executor(
            None, self._fetch_fleet_view, member)
        return None if doc is None else doc.get("router")

    async def leader_id(self) -> str | None:
        """Which replica holds the lease RIGHT NOW (None = no leader —
        mid-failover, or nobody reachable)."""
        for rid in sorted(self.routers):
            view = await self.router_view(rid)
            if view and (view.get("lease") or {}).get("role") == "leader":
                return rid
        return None

    async def await_leader(self, timeout: float) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            rid = await self.leader_id()
            if rid is not None:
                return rid
            await asyncio.sleep(0.1)
        raise RuntimeError("router fleet: no replica claimed the lease "
                           f"within {timeout}s")

    async def _await_routers_reachable(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        pending = dict(self.routers)
        while pending and time.monotonic() < deadline:
            for rid, member in list(pending.items()):
                try:
                    _r, w = await asyncio.open_connection(
                        member.host, member.ctrl_port)
                    w.close()
                    del pending[rid]
                except OSError:
                    pass
            if pending:
                await asyncio.sleep(0.1)
        if pending:
            raise RuntimeError(
                f"routers never became reachable: {sorted(pending)}")

    async def _await_gateways_registered(self, timeout: float) -> None:
        """Every router must see every gateway registered (hello + STEK
        push landed) — a storm started earlier would race registration."""
        deadline = time.monotonic() + timeout
        want = set(self.gateway_ids)
        while time.monotonic() < deadline:
            ok = True
            for rid in sorted(self.routers):
                view = await self.router_view(rid)
                got = {m.get("gateway") for m in (view or {}).get("members")
                       or [] if m.get("port")}
                if not want <= got:
                    ok = False
                    break
            if ok:
                return
            await asyncio.sleep(0.1)
        raise RuntimeError("gateways never registered with every router")

    # -- chaos ----------------------------------------------------------------

    async def _chaos_loop(self) -> None:
        """The control-plane twin of the fleet health tick: poll the
        seeded plan once per router per tick, sorted order, one loop —
        the injected log stays byte-reproducible from the seed."""
        while self._running:
            await asyncio.sleep(self.hb_interval)
            for rid in sorted(self.routers):
                member = self.routers[rid]
                if member.killed:
                    continue
                for entry in _faults.router_control(rid):
                    await self._apply_chaos(member, entry)

    async def _apply_chaos(self, member: RouterMember,
                           entry: dict[str, Any]) -> None:
        action = entry.get("action")
        logger.warning("chaos: %s on %s", action, member.router_id)
        if action == "kill_router":
            await self.kill_router(member.router_id)
        elif action == "pause_router":
            self.pause_router(member.router_id,
                              float(entry.get("delay_s", 1.0)))

    async def kill_router(self, rid: str) -> None:
        """Abrupt replica death (chaos ``kill_router``): SIGKILL the
        process / tear the task down without a graceful stop.  Followers
        detect the silence (no renewals) and claim after the TTL."""
        member = self.routers[rid]
        member.killed = True
        self.router_kills += 1
        obs_flight.record("router_killed", router=rid,
                          kills=self.router_kills)
        if member.proc is not None:
            try:
                member.proc.kill()
            except ProcessLookupError:  # pragma: no cover - already gone
                pass
            await member.proc.wait()
            member.proc = None
        elif member.task is not None:
            member.task.cancel()
            try:
                await member.task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.debug("router task %s raised during kill",
                             member.router_id, exc_info=True)
            member.task = None
            member.fleet = None

    def pause_router(self, rid: str, seconds: float) -> None:
        """Chaos ``pause_router``: freeze the replica (SIGSTOP/CONT).  A
        paused LEADER stops renewing — the failover path without a death.
        Task-mode replicas cannot be frozen; the pause degrades to a
        no-op there (the kill action is the task-mode chaos tool)."""
        member = self.routers[rid]
        if member.proc is None or member.proc.returncode is not None:
            return
        pid = member.proc.pid
        self.router_pauses += 1
        obs_flight.record("router_paused", router=rid, seconds=seconds)
        try:
            os.kill(pid, signal.SIGSTOP)
        except (OSError, ProcessLookupError):  # pragma: no cover
            return
        loop = asyncio.get_running_loop()

        def resume() -> None:
            try:
                os.kill(pid, signal.SIGCONT)
            except (OSError, ProcessLookupError):  # pragma: no cover
                pass

        loop.call_later(seconds, resume)

    # -- the router roll ------------------------------------------------------

    async def restart_router(self, rid: str) -> dict[str, Any]:
        """One replica's roll: graceful stop (SIGTERM — a stopping leader
        goes silent, followers claim), respawn on the SAME ports, await
        reachable.  A chaos-killed replica just respawns."""
        member = self.routers[rid]
        t0 = time.monotonic()
        await self._stop_router(member, graceful=True)
        member.restarts += 1
        await self._spawn_router(member)
        deadline = time.monotonic() + ROUTER_REGISTER_TIMEOUT_S
        reachable = False
        while time.monotonic() < deadline:
            try:
                _r, w = await asyncio.open_connection(member.host,
                                                      member.ctrl_port)
                w.close()
                reachable = True
                break
            except OSError:
                await asyncio.sleep(0.1)
        out = {"router": rid, "reachable": reachable,
               "took_s": round(time.monotonic() - t0, 3)}
        obs_flight.record("router_restarted", **out)
        if not reachable:
            logger.error("router %s never came back after restart", rid)
        return out

    async def rolling_restart(self) -> dict[str, Any]:
        """Roll EVERY replica, one at a time, lowest rank first — the
        lease moves at most once per step, the control plane never loses
        more than one replica, and the data plane never notices (gateways
        keep serving; their reconnect loops re-register with each
        respawn)."""
        results = []
        for rid in sorted(self.routers):
            results.append(await self.restart_router(rid))
        ok = all(r["reachable"] for r in results)
        obs_flight.record("router_rolling_restart",
                          routers=[r["router"] for r in results], ok=ok)
        return {"restarted": results, "ok": ok}

    # -- reporting ------------------------------------------------------------

    async def stats(self) -> dict[str, Any]:
        rows = []
        for rid in sorted(self.routers):
            member = self.routers[rid]
            view = await self.router_view(rid)
            rows.append({
                "router": rid,
                "rank": member.rank,
                "ctrl_port": member.ctrl_port,
                "telemetry_port": member.telemetry_port,
                "alive": member.alive,
                "killed": member.killed,
                "restarts": member.restarts,
                "lease": (view or {}).get("lease"),
                "lease_rejects": (view or {}).get("lease_rejects"),
                "lease_fenced": (view or {}).get("lease_fenced"),
                "syncs_applied": (view or {}).get("syncs_applied"),
                "routes_ok": (view or {}).get("routes_ok"),
                "route_sheds": (view or {}).get("route_sheds"),
                "stek_epoch": (view or {}).get("stek_epoch"),
                "stek_rotations": (view or {}).get("stek_rotations"),
            })
        return {
            "routers": rows,
            "gateways": list(self.gateway_ids),
            "router_kills": self.router_kills,
            "router_pauses": self.router_pauses,
            "ring_members": self.router_ring.members(),
        }


if __name__ == "__main__":
    sys.exit(main())
