"""Seeded consistent-hash ring: deterministic peer→gateway assignment.

Classic consistent hashing with BOUNDED virtual nodes: each member owns
``vnodes`` points on a 64-bit ring, a key is served by the first member
point clockwise of the key's hash, and — the property the fleet's handoff
story rests on — adding or removing one member moves ONLY the arcs that
member owns (~1/N of the key space), never reshuffling the rest
(tests/test_fleet.py pins this).

Determinism: every point derives from ``sha256(seed:member:vnode)``, so
two processes given the same (seed, membership) compute byte-identical
assignments — the router and any offline tool agree on who owns a peer
without coordination.

The ring tracks MEMBERSHIP only.  Liveness lives one level up
(:class:`.manager.GatewayFleet`'s per-member breakers): routing walks
:meth:`successors` and takes the first member the fleet considers
healthy, so a dead gateway's arc drains to its ring successors and —
because membership never changed — snaps back the moment its breaker
closes again.

Members are plain string ids, so the SAME machinery places every tier:
peer→gateway assignment is the original use, and the replicated control
plane (docs/fleet.md "HA control plane") puts ROUTERS on a ring too —
clients and ``tools/qrtop.py`` walk ``successors(key)`` over router ids
to pick which replica to ask first and the deterministic failover order
when it is dead, exactly the discipline the data plane already uses.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Iterator

#: default virtual nodes per member: enough for ~±15% arc balance at
#: small fleets while keeping the ring a few hundred points (bounded
#: memory and O(log) lookups, never a point per peer)
DEFAULT_VNODES = 64


def _point(seed: int, data: str) -> int:
    """One deterministic 64-bit ring coordinate."""
    digest = hashlib.sha256(f"{seed}:{data}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


class HashRing:
    """Seeded consistent-hash ring over string member ids."""

    def __init__(self, members: Iterable[str] = (), vnodes: int = DEFAULT_VNODES,
                 seed: int = 0):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self.seed = seed
        self._members: set[str] = set()
        #: sorted, parallel: ring coordinate -> owning member
        self._points: list[int] = []
        self._owners: list[str] = []
        for m in members:
            self.add(m)

    # -- membership -----------------------------------------------------------

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: str) -> bool:
        return member in self._members

    def members(self) -> list[str]:
        return sorted(self._members)

    def add(self, member: str) -> None:
        if member in self._members:
            return
        self._members.add(member)
        for v in range(self.vnodes):
            pt = _point(self.seed, f"{member}:{v}")
            idx = bisect.bisect_left(self._points, pt)
            self._points.insert(idx, pt)
            self._owners.insert(idx, member)

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.discard(member)
        keep = [(p, o) for p, o in zip(self._points, self._owners)
                if o != member]
        self._points = [p for p, _ in keep]
        self._owners = [o for _, o in keep]

    # -- lookup ---------------------------------------------------------------

    def assign(self, key: str) -> str | None:
        """The member owning ``key``'s ring position (None when empty)."""
        for m in self.successors(key):
            return m
        return None

    def successors(self, key: str) -> Iterator[str]:
        """Distinct members in ring order starting at ``key``'s position —
        the handoff order: index 0 is the owner, index 1 the gateway that
        inherits the arc when the owner dies, and so on."""
        if not self._points:
            return
        start = bisect.bisect_right(self._points, _point(self.seed, key))
        seen: set[str] = set()
        n = len(self._points)
        for i in range(n):
            owner = self._owners[(start + i) % n]
            if owner not in seen:
                seen.add(owner)
                yield owner

    def assignment_counts(self, keys: Iterable[str]) -> dict[str, int]:
        """Keys-per-member histogram (balance diagnostics, docs/fleet.md)."""
        out: dict[str, int] = {m: 0 for m in self._members}
        for k in keys:
            owner = self.assign(k)
            if owner is not None:
                out[owner] += 1
        return out
