"""GatewayFleet: N gateway processes behind a peer-routing tier, with
gateway death as the first-class case (docs/fleet.md).

The design seed (ISSUE 11, generalizing PR 3/6): **a dead gateway is a
breaker-open shard at fleet scope**.  Each :class:`GatewayMember` owns a
:class:`provider.batched.Breaker` — the SAME closed → open → half-open →
closed state machine that guards a chip's dispatch path — driven by
fleet-level evidence instead of dispatch latency:

* missed heartbeats  → ``record_failure`` (non-probe): the breaker opens,
  the member's ring arc drains to its successors, in-flight handshakes on
  it are retried by their initiators under the existing typed busy/retry
  machinery;
* the half-open canary is a CONTROL probe (one ``__gw_probe__``
  round-trip), never a client session: ``probe_ready()`` members get
  exactly one probe per cool-off, failures escalate the backoff
  exponentially (capped) exactly like a sick chip's canary;
* probe success → ``record_success("probe")`` closes the breaker and the
  member takes its ring ownership back — membership never changed, so
  the arc snaps back with zero reshuffling of other members' peers.

Placement, quarantine and rebalance are ONE policy at both scopes:
:func:`provider.scheduler.select_slot` — the local shard axis's placement
rule — picks among :class:`GatewayMember`\\ s too (they expose the same
``breaker`` / ``inflight`` / ``index`` slot protocol): the health loop
routes the next canary probe through it, and routing falls back to it
(quarantine-aware, least-loaded) when the ring walk finds no closed
member.

Admission: the fleet budget is the SUM of per-gateway budgets over the
currently-closed members; an over-budget route query is shed AT THE
ROUTER with the same typed ``__busy__`` frame a gateway's connection
budget uses, so clients treat both scopes with one retry policy.

Cross-process SLO aggregation: each heartbeat carries the gateway's
cumulative SLO probe totals (:meth:`obs.slo.SLOEngine.probe_totals`); the
fleet sums them per spec and evaluates ONE :class:`obs.slo.SLOEngine`
over the sums — the per-node ``slo_report.json`` files the gateways write
on shutdown are the offline twin (``tools/slo_merge.py``).

HA control plane (docs/fleet.md "HA control plane"): the router itself
is no longer a load-bearing singleton.  A fleet constructed with
``router_peers`` runs as ONE REPLICA of a replicated control plane — a
:class:`fleet.lease.LeaderLease` (monotonic epochs, relative TTLs,
rank-staggered claims on the injectable clock) decides which replica
holds STEK-rotation and admission authority; the leader replicates the
full authority state (STEK ring export + membership roster) to followers
on every change over the same length-framed control link
(``__rt_lease__`` / ``__rt_sync__``), so ANY follower can assume the
lease without losing the ticket accept window.  Authority frames carry
the lease epoch; a follower fences stale epochs with ``__rt_reject__``
and the stale sender demotes loudly instead of split-braining.  Replicas
run in ``attach`` mode: gateways are spawned by the driver, dial every
router, and register via hello — members materialize on registration
instead of at spawn.

Everything here runs on the event loop (the breakers' own locks cover
their cross-thread surface); the clock is injectable so handoff/heal
tests drive deterministic timelines.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import sys
import time
from pathlib import Path
from typing import Any, Callable

from ..app.resumption import STEKRing
from ..faults import plan as _faults
from ..obs import flight as obs_flight
from ..obs import slo as obs_slo
from ..obs.metrics import Registry
from ..provider.batched import Breaker
from ..provider.scheduler import select_slot
from . import control
from .lease import LeaderLease
from .ring import HashRing

logger = logging.getLogger(__name__)

#: heartbeat cadence and the miss budget: a member whose last heartbeat is
#: older than ``hb_miss_limit * hb_interval`` is declared dead (breaker
#: opens).  Defaults favor fast CI storms; production deployments pass
#: their own (docs/fleet.md sizes the detection-latency/false-positive
#: trade).
HB_INTERVAL_S = 0.25
HB_MISS_LIMIT = 4


class FleetBusy(RuntimeError):
    """The fleet admission budget is exhausted: this route query was shed
    at the router (the wire twin is the typed ``__busy__`` frame)."""


class GatewayMember:
    """Router-side state for one gateway process — a fleet-scope slot.

    Satisfies the :func:`provider.scheduler.select_slot` slot protocol
    (``index`` / ``inflight`` / ``breaker``), which is what lets the
    shard-placement policy pick among gateways unchanged."""

    def __init__(self, gateway_id: str, index: int, cooloff_s: float = 1.0,
                 cooloff_max_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.gateway_id = gateway_id
        self.index = index
        self._cooloffs = (cooloff_s, cooloff_max_s)
        self._clock = clock
        #: fleet-scope breaker: the provider-layer state machine reused at
        #: the second placement level (module docstring)
        self.breaker = Breaker(cooloff_s, cooloff_max_s, clock=clock)
        self.breaker.label = gateway_id
        #: live sessions the router believes are on this gateway
        self.inflight = 0
        #: routes issued in the current / previous heartbeat window (not
        #: yet necessarily visible in the gateway's own connection count —
        #: the reconcile slack below)
        self.routed_since_hb = 0
        self.routed_prev_hb = 0
        #: cumulative sessions routed here
        self.assigned = 0
        # -- liveness / transport ------------------------------------------
        self.host: str | None = None
        self.port: int | None = None  # the P2P port peers dial
        self.pid: int | None = None
        #: the gateway's own telemetry listener (obs/http.py), announced
        #: in its hello/heartbeats; None when it runs without one
        self.telemetry_port: int | None = None
        #: the per-gateway admission cap the process announced in its
        #: hello — cross-checked against the router's configured cap so a
        #: respawn running a stale config is caught at registration
        self.announced_max_peers: int | None = None
        self.proc: Any = None  # asyncio subprocess (spawn="process")
        self.task: asyncio.Task | None = None  # spawn="task"
        self.writer: asyncio.StreamWriter | None = None
        #: control-connection generation: bumped on every accepted hello.
        #: A member may be re-dialed (reconnect after a transient drop, a
        #: gateway heartbeating a respawned router) while the OLD read
        #: loop is still draining — without the generation gate the stale
        #: loop's heartbeats would double-shift the inflight reconcile
        #: windows and its EOF would tear down the LIVE registration
        self.conn_gen = 0
        #: frames dropped from superseded connections (bug evidence)
        self.superseded_frames = 0
        self.last_hb: float | None = None
        self.hb_count = 0
        #: latest heartbeat stats / cumulative SLO probe totals
        self.stats: dict[str, Any] = {}
        self.slo_totals: dict[str, Any] = {}
        #: final stats from the gateway's ``__gw_bye__``
        self.final_stats: dict[str, Any] | None = None
        #: chaos partition: control traffic dropped until this clock time
        self.partitioned_until = 0.0
        #: True once stop()/kill() decided this member's life is over —
        #: excluded from routing and probing
        self.stopped = False
        self.killed = False
        #: True while a graceful drain / rolling restart owns this member:
        #: excluded from routing and from death-detection (the exit is
        #: PLANNED — declaring it dead would be noise), cleared when the
        #: respawned process re-registers
        self.draining = False
        #: rolling restarts survived (snapshot bookkeeping)
        self.restarts = 0
        self._probe_fut: asyncio.Future | None = None
        self._probe_n = 0

    @property
    def registered(self) -> bool:
        return self.port is not None

    def reset_for_respawn(self) -> None:
        """Forget the dead incarnation's transport/liveness state so the
        respawned process registers like a fresh member — ring arc,
        identity, and cumulative route counters unchanged; the fleet
        breaker is rebuilt closed (a planned restart is not failure
        evidence)."""
        self.proc = None
        self.task = None
        self.writer = None
        self.port = None
        self.pid = None
        self.telemetry_port = None
        self.announced_max_peers = None
        self.last_hb = None
        self.final_stats = None
        self.stats = {}
        self.slo_totals = {}
        self.killed = False
        self.stopped = False
        self._probe_fut = None
        self._probe_n = 0
        self.inflight = 0
        self.routed_since_hb = 0
        self.routed_prev_hb = 0
        self.restarts += 1
        self.breaker = Breaker(*self._cooloffs, clock=self._clock)
        self.breaker.label = self.gateway_id

    def snapshot(self) -> dict[str, Any]:
        b = self.breaker
        return {
            "gateway": self.gateway_id,
            "index": self.index,
            "port": self.port,
            "pid": self.pid,
            "inflight": self.inflight,
            "assigned": self.assigned,
            "heartbeats": self.hb_count,
            "breaker_state": b.state,
            "breaker_opens": b.opens,
            "breaker_closes": b.closes,
            "killed": self.killed,
            "stopped": self.stopped,
            "draining": self.draining,
            "restarts": self.restarts,
            "telemetry_port": self.telemetry_port,
            "stats": self.stats,
        }


class GatewayFleet:
    """Spawns, watches, routes to, and heals a pod of gateway processes."""

    def __init__(
        self,
        gateways: int = 3,
        *,
        spawn: str = "process",
        providers: str = "stdlib",
        seed: int = 0,
        ring_vnodes: int = 64,
        hb_interval: float = HB_INTERVAL_S,
        hb_miss_limit: int = HB_MISS_LIMIT,
        cooloff_s: float = 1.0,
        cooloff_max_s: float = 30.0,
        per_gateway_max_peers: int = 0,
        handshake_budget: int = 0,
        gateway_kw: dict[str, Any] | None = None,
        report_dir: str | Path | None = None,
        host: str = "127.0.0.1",
        clock: Callable[[], float] = time.monotonic,
        register_timeout: float = 60.0,
        telemetry_port: int | None = None,
        ticket_key_rotation_s: float = 0.0,
        attach: bool = False,
        ctrl_port: int | None = None,
        router_id: str = "rt0",
        router_rank: int = 0,
        router_peers: list[dict[str, Any]] | None = None,
        lease_ttl_s: float | None = None,
        lease_stagger_s: float | None = None,
    ):
        if spawn not in ("process", "task"):
            raise ValueError(f"spawn must be 'process' or 'task', got {spawn!r}")
        self.spawn = spawn
        self.providers = providers
        self.seed = seed
        self.hb_interval = hb_interval
        self.hb_miss_limit = hb_miss_limit
        self.per_gateway_max_peers = per_gateway_max_peers
        self.handshake_budget = handshake_budget
        self.gateway_kw = dict(gateway_kw or {})
        self.report_dir = Path(report_dir) if report_dir is not None else None
        self.host = host
        self._clock = clock
        #: attach mode (HA replicas): this router spawns NOTHING — the
        #: driver owns the gateway processes, which dial every router and
        #: materialize as members on their hello
        self.attach = attach
        self._requested_ctrl_port = ctrl_port
        self._cooloffs = (cooloff_s, cooloff_max_s)
        # -- replicated control plane (None = the classic standalone) ------
        self.router_id = router_id
        self.router_peers = list(router_peers or [])
        self.lease: LeaderLease | None = None
        if router_peers is not None:
            lease_kw: dict[str, Any] = {"clock": clock}
            if lease_ttl_s is not None:
                lease_kw["ttl_s"] = lease_ttl_s
            if lease_stagger_s is not None:
                lease_kw["claim_stagger_s"] = lease_stagger_s
            self.lease = LeaderLease(router_id, router_rank, **lease_kw)
        #: ``__rt_reject__`` fences this replica RECEIVED (each one is
        #: proof a peer holds a fresher lease than a frame we sent)
        self.lease_rejects = 0
        #: stale peer authority frames this replica fenced
        self.lease_fenced = 0
        #: RT_SYNC state replications applied from the leader
        self.syncs_applied = 0
        #: fleet birth on the injected clock: the availability SLO measures
        #: gateway-seconds SINCE START — the raw monotonic value is time
        #: since boot, which would dilute any outage into un-alertable noise
        self._t0 = clock()
        self._register_timeout = register_timeout
        # attach mode: members materialize on hello (the driver spawns the
        # gateway processes; ``gateways`` is only the expected head count)
        ids = [] if attach else [f"gw{i}" for i in range(gateways)]
        self.members: dict[str, GatewayMember] = {
            gid: GatewayMember(gid, i, cooloff_s, cooloff_max_s, clock)
            for i, gid in enumerate(ids)
        }
        #: consistent-hash peer→gateway assignment (fleet/ring.py): seeded,
        #: bounded virtual nodes; membership is STABLE across deaths —
        #: liveness is the breakers' business, so a healed gateway's arc
        #: snaps back without reshuffling anyone else's peers
        self.ring = HashRing(ids, vnodes=ring_vnodes, seed=seed)
        self._server: asyncio.Server | None = None
        self.ctrl_port: int | None = None
        self._running = False
        self._health_task: asyncio.Task | None = None
        self._bg: set[asyncio.Task] = set()
        self._watchers: list[Callable[[str, str], None]] = []
        self._registered_ev = asyncio.Event()
        # -- fleet counters (the router-side half of the admission SLI) ----
        self.routes_ok = 0
        self.route_sheds = 0
        self.rebalance_picks = 0
        self.handoffs = 0
        self._last_healthy: frozenset[str] = frozenset(ids)
        #: the fleet's authoritative session-ticket-encryption keys
        #: (app/resumption.py STEKRing: current + previous = the dual-key
        #: accept window), pushed to every gateway over the control link
        #: on registration and on rotation — one ring per fleet is what
        #: makes a ticket minted by gw1 resume on gw2 after a handoff
        self.ticket_keys = STEKRing()
        #: automatic rotation cadence on the injected clock (0 = manual
        #: rotation only via rotate_stek())
        self.ticket_key_rotation_s = ticket_key_rotation_s
        self._last_key_rotation_t = clock()
        self.key_rotations = 0
        self.registry = Registry(name="fleet")
        self.slo = self._build_slo_engine()
        #: router-side telemetry (obs/http.py): None = off (the default).
        #: When armed, the router serves the aggregated /fleet view and
        #: every gateway (unless gateway_kw overrides) opens its OWN
        #: ephemeral telemetry listener, announced via hello/heartbeat.
        self._telemetry_port = telemetry_port
        self.telemetry = None

    # -- events ---------------------------------------------------------------

    def on_event(self, handler: Callable[[str, str], None]) -> None:
        """Register a fleet transition callback ``handler(event, gateway)``
        — fired from the control read loops and the health tick (loop
        domain; qrflow models on_event registrations as loop-callback
        edges).  Events: registered / gateway_dead / gateway_healed /
        probe_failed / bye."""
        if handler not in self._watchers:
            self._watchers.append(handler)

    def _fire(self, event: str, gateway: str) -> None:
        for h in list(self._watchers):
            try:
                h(event, gateway)
            except Exception:
                logger.exception("fleet event handler failed")

    # -- lifecycle ------------------------------------------------------------

    async def start(self) -> None:
        """Start the control/route server, spawn every gateway, and wait
        until all of them registered (hello received).  Attach mode binds
        the REQUESTED control port (a respawned replica must come back
        where the gateways' reconnect loops are dialing), spawns nothing,
        and waits for nobody — registration arrives when it arrives."""
        self._server = await asyncio.start_server(
            self._on_ctrl, self.host, self._requested_ctrl_port or 0)
        self.ctrl_port = self._server.sockets[0].getsockname()[1]
        self._running = True
        if self._telemetry_port is not None:
            from ..obs.http import TelemetryServer, json_route
            from ..obs.metrics import (PROMETHEUS_CONTENT_TYPE,
                                       prometheus_text)

            def prom():
                return 200, PROMETHEUS_CONTENT_TYPE, prometheus_text(
                    self.registry).encode()

            try:
                self.telemetry = TelemetryServer({
                    "/fleet": json_route(self.fleet_view),
                    "/metrics": prom,
                    "/metrics.json": json_route(self.registry.snapshot),
                    "/slo": json_route(self.slo_status),
                    "/healthz": json_route(lambda: {
                        "ok": True, "role": "fleet-router",
                        "router": self.router_id,
                        "lease": self.lease_view(),
                        "gateways": len(self.members),
                    }),
                }, host=self.host, port=self._telemetry_port).start()
            except OSError as e:
                # an optional observability listener must never stop the
                # fleet from starting (same degrade policy as the engine)
                logger.warning("fleet telemetry disabled: cannot bind "
                               "port %s (%s)", self._telemetry_port, e)
        if self.report_dir is not None:
            self.report_dir.mkdir(parents=True, exist_ok=True)
            # a previous run's per-node reports would leak into this run's
            # collect_reports() merge (a killed gateway writes none,
            # leaving its stale twin behind to impersonate it)
            for stale in self.report_dir.glob("*_slo_report.json"):
                stale.unlink()
        if not self.attach:
            for member in self._members_sorted():
                await self._spawn_member(member)
            try:
                await asyncio.wait_for(self._registered_ev.wait(),
                                       self._register_timeout)
            except asyncio.TimeoutError:
                missing = [m.gateway_id for m in self.members.values()
                           if not m.registered]
                await self.stop()
                raise RuntimeError(
                    f"fleet start: gateways never registered: {missing}")
        self._health_task = asyncio.create_task(self._health_loop())
        logger.info("fleet up: %d gateways on router port %s (router %s)",
                    len(self.members), self.ctrl_port, self.router_id)

    def _members_sorted(self) -> list[GatewayMember]:
        return [self.members[g] for g in sorted(self.members)]

    def _gateway_config(self, member: GatewayMember) -> dict[str, Any]:
        cfg = {
            "gateway_id": member.gateway_id,
            "router_host": self.host,
            # the gateway binds its P2P listener where the router will
            # advertise it (_route_reply hands clients member.host)
            "bind_host": self.host,
            "router_port": self.ctrl_port,
            "providers": self.providers,
            "max_peers": self.per_gateway_max_peers,
            "handshake_budget": self.handshake_budget,
            "hb_interval": self.hb_interval,
            "report_dir": str(self.report_dir) if self.report_dir else None,
            # a telemetry-armed fleet scrapes its gateways too: each opens
            # an ephemeral listener, announced back through hello
            "telemetry_port": (0 if self._telemetry_port is not None
                               else None),
        }
        cfg.update(self.gateway_kw)
        return cfg

    async def _spawn_member(self, member: GatewayMember) -> None:
        cfg = self._gateway_config(member)
        if self.spawn == "task":
            from .gateway import run_gateway

            member.task = asyncio.create_task(run_gateway(cfg))
            return
        stderr = asyncio.subprocess.DEVNULL
        log_f = None
        if self.report_dir is not None:
            log_path = self.report_dir / f"{member.gateway_id}.log"
            stderr = log_f = await asyncio.get_running_loop().run_in_executor(
                None, lambda: open(log_path, "wb"))
        try:
            member.proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m",
                "quantum_resistant_p2p_tpu.fleet.gateway", json.dumps(cfg),
                stdout=asyncio.subprocess.DEVNULL, stderr=stderr,
                start_new_session=True,
            )
        finally:
            if log_f is not None:
                # the child holds its own dup of the fd; keeping the
                # router-side file object open would pin one fd per
                # gateway per fleet for the driver's lifetime
                log_f.close()
        member.pid = member.proc.pid

    async def stop(self) -> None:
        """Graceful drain: ask every live gateway to write its per-node
        SLO report and exit; SIGKILL/cancel whatever does not comply.

        An ATTACH-mode replica owns no gateway processes and must not
        reach for them: a router being rolled mid-storm that sent
        ``__gw_stop__`` on its way out would take the entire (healthy,
        serving) data plane down with it — it just closes its own
        listener and lets the gateways' reconnect loops find the respawn.
        """
        self._running = False
        if self.telemetry is not None:
            srv, self.telemetry = self.telemetry, None
            srv.stop()
        if self._health_task is not None:
            self._health_task.cancel()
        if self.attach:
            for member in self._members_sorted():
                if member.writer is not None:
                    member.writer.close()
                    member.writer = None
            for t in list(self._bg):
                t.cancel()
            if self._server is not None:
                self._server.close()
                await self._server.wait_closed()
                self._server = None
            return
        for member in self._members_sorted():
            member.stopped = True
            if member.proc is not None and member.pid is not None:
                # un-freeze a pause-chaos'd gateway so it can process the
                # stop frame and write its slo report instead of burning
                # the drain deadline SIGSTOPped (harmless if running)
                try:
                    os.kill(member.pid, signal.SIGCONT)
                except (OSError, ProcessLookupError):  # pragma: no cover
                    pass
            if member.writer is not None:
                try:
                    await control.send_ctrl(member.writer,
                                            {"type": control.GW_STOP})
                except (ConnectionError, OSError, RuntimeError):
                    pass
        deadline = 10.0
        for member in self._members_sorted():
            if member.proc is not None:
                try:
                    await asyncio.wait_for(member.proc.wait(), deadline)
                except asyncio.TimeoutError:
                    member.proc.kill()
                    await member.proc.wait()
            elif member.task is not None:
                try:
                    await asyncio.wait_for(member.task, deadline)
                except asyncio.TimeoutError:
                    member.task.cancel()
                except asyncio.CancelledError:
                    pass  # a chaos-killed in-process gateway: already dead
                except Exception:
                    logger.exception("gateway %s task died with an error "
                                     "during stop", member.gateway_id)
        for t in list(self._bg):
            t.cancel()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def kill(self, gateway_id: str) -> None:
        """Abrupt gateway death (chaos ``kill_gateway``): SIGKILL the
        subprocess / cancel the in-process task.  The member stays in the
        ring — death is the breakers' business, detected by missed
        heartbeats exactly like an unplanned crash."""
        member = self.members[gateway_id]
        member.killed = True
        if member.proc is not None:
            try:
                member.proc.kill()
            except ProcessLookupError:  # pragma: no cover - already gone
                pass
        elif member.task is not None:
            member.task.cancel()
        obs_flight.record("fleet_gateway_killed", gateway=gateway_id)

    def pause(self, gateway_id: str, seconds: float) -> None:
        """Chaos ``pause_gateway``: SIGSTOP the subprocess for ``seconds``
        then SIGCONT (in-process gateways degrade to a partition — a task
        cannot be frozen)."""
        member = self.members[gateway_id]
        if member.proc is not None and member.pid is not None:
            try:
                os.kill(member.pid, signal.SIGSTOP)
                asyncio.get_running_loop().call_later(
                    seconds, self._resume, member)
            except (OSError, ProcessLookupError):  # pragma: no cover
                pass
        else:
            self.partition(gateway_id, seconds)

    def _resume(self, member: GatewayMember) -> None:
        # no `stopped` gate: resuming a stopping/gone process is harmless,
        # while skipping it would leave a paused gateway frozen through
        # stop()'s drain
        if member.pid is not None:
            try:
                os.kill(member.pid, signal.SIGCONT)
            except (OSError, ProcessLookupError):  # pragma: no cover
                pass

    def partition(self, gateway_id: str, seconds: float) -> None:
        """Chaos ``partition``: drop router<->gateway control traffic
        (heartbeats in, probes out) for ``seconds``.  The gateway keeps
        serving peers — the fleet just cannot SEE it, the exact
        false-dead case the half-open re-entry machinery must handle."""
        member = self.members[gateway_id]
        member.partitioned_until = max(
            member.partitioned_until, self._clock() + seconds)

    # -- control server -------------------------------------------------------

    async def _on_ctrl(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        try:
            msg = await asyncio.wait_for(control.read_ctrl(reader), 10.0)
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionError, OSError, ValueError):
            # slow/garbled/dropped first frame: untrusted dialer, drop it
            writer.close()
            return
        mtype = msg.get("type")
        if mtype == control.GW_HELLO:
            await self._gateway_conn(msg, reader, writer)
        elif mtype == control.ROUTE:
            try:
                await control.send_ctrl(writer, self._route_reply(msg))
            except (ConnectionError, OSError):
                pass
            writer.close()
        elif mtype == control.ROUTE_DONE:
            self.session_done(str(msg.get("gateway", "")))
            writer.close()
        elif mtype == control.RT_LEASE:
            await self._on_rt_lease(msg, writer)
            writer.close()
        elif mtype == control.RT_SYNC:
            await self._on_rt_sync(msg, writer)
            writer.close()
        else:
            writer.close()

    async def _gateway_conn(self, hello: dict, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        gid = str(hello.get("gateway", ""))
        member = self.members.get(gid)
        if member is None:
            if not self.attach:
                logger.warning("hello from unknown gateway %r", gid)
                writer.close()
                return
            # attach mode: gateways are spawned by the driver and register
            # themselves — membership (and the ring arc) materializes here
            member = GatewayMember(gid, len(self.members), *self._cooloffs,
                                   clock=self._clock)
            self.members[gid] = member
            self.ring.add(gid)
            if self.lease is not None and self.lease.is_leader:
                self._spawn(self._replicate_state(), f"member sync:{gid}")
        if member.writer is not None and member.writer is not writer:
            # a SECOND control connection for a registered member (a
            # reconnect landing before the old loop saw its EOF): the new
            # hello supersedes.  Without this, both read loops would feed
            # _on_heartbeat — every heartbeat double-shifts the inflight
            # reconcile windows, halving the reconcile slack — and the
            # old loop's eventual EOF would null the LIVE writer, leaving
            # a serving gateway unreachable for probes and STEK pushes
            # until ITS next reconnect
            old = member.writer
            member.writer = None
            old.close()
        member.conn_gen += 1
        gen = member.conn_gen
        member.host = self.host
        member.port = int(hello.get("p2p_port", 0))
        member.pid = int(hello.get("pid") or 0) or member.pid
        tport = hello.get("telemetry_port")
        member.telemetry_port = int(tport) if tport is not None else None
        announced = hello.get("max_peers")
        member.announced_max_peers = (int(announced) if announced is not None
                                      else None)
        if (member.announced_max_peers is not None
                and self.per_gateway_max_peers
                and member.announced_max_peers != self.per_gateway_max_peers):
            # a respawn running a stale config: its own admission cap and
            # the router's budget arithmetic (_fleet_budget) now disagree —
            # routing still works, but surface the drift loudly
            logger.warning(
                "gateway %s announced max_peers=%d but the router is "
                "configured for %d per gateway — config drift", gid,
                member.announced_max_peers, self.per_gateway_max_peers)
        member.writer = writer
        member.last_hb = self._clock()
        member.draining = False  # a respawned member is serving again
        logger.info("gateway %s registered (p2p port %s)", gid, member.port)
        # push the fleet STEK ring FIRST: a gateway must never mint (or
        # refuse) tickets under its private random ring once it is part
        # of a fleet — and a respawned gateway needs the ring before its
        # first resume arrives, or every pre-restart ticket would draw
        # unknown_stek instead of resuming
        try:
            await control.send_ctrl(writer, {
                "type": control.GW_TICKET_KEYS,
                "keys": self.ticket_keys.export(),
                "lease_epoch": self._lease_epoch(),
            })
        except (ConnectionError, OSError):
            # the gateway died between hello and the push: undo the
            # registration state set above — a half-registered member
            # (port set, writer dead) would be routable, would satisfy
            # restart_member's registered check, and would stall
            # start()'s all-registered event
            member.port = None
            if member.writer is writer:
                member.writer = None
            member.last_hb = None
            writer.close()
            return
        self._fire("registered", gid)
        if all(m.registered for m in self.members.values()):
            self._registered_ev.set()
        try:
            while True:
                msg = await control.read_ctrl(reader)
                if member.conn_gen != gen:
                    # this loop's connection was superseded by a fresh
                    # hello: its frames are the DEAD incarnation's — a
                    # heartbeat here must not touch liveness or shift the
                    # reconcile windows the live connection now owns
                    member.superseded_frames += 1
                    break
                mtype = msg.get("type")
                sender = str(msg.get("gateway", gid) or gid)
                if sender != gid:
                    # a frame claiming another member's identity on gid's
                    # registered connection (stale config / confused
                    # respawn): it must not mutate gid's state, and it
                    # CERTAINLY must not mutate the claimed member's
                    logger.warning(
                        "gateway %s sent %s claiming identity %r — frame "
                        "dropped", gid, mtype, sender)
                    continue
                if mtype == control.GW_HEARTBEAT:
                    self._on_heartbeat(member, msg)
                elif mtype == control.GW_PROBE_OK:
                    self._on_probe_ok(member, msg)
                elif mtype == control.GW_BYE:
                    member.final_stats = msg.get("stats") or {}
                    self._fire("bye", gid)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            if member.writer is writer:
                member.writer = None
            writer.close()

    def _on_heartbeat(self, member: GatewayMember, msg: dict) -> None:
        if self._clock() < member.partitioned_until:
            return  # chaos partition: the router never saw it
        member.last_hb = self._clock()
        member.hb_count += 1
        member.stats = msg.get("stats") or {}
        tport = member.stats.get("telemetry_port")
        if tport is not None:
            member.telemetry_port = int(tport)
        # Reconcile the router's inflight BELIEF with the gateway's own
        # connection count: a client whose ``__route_done__`` frame was
        # lost (its open_connection error is swallowed client-side) would
        # otherwise leak its admission slot FOREVER and eventually wedge
        # the fleet budget in permanent FleetBusy.  The cap pads for
        # routes granted in the last TWO heartbeat windows, which the
        # gateway cannot be assumed to see as connections yet (a saturated
        # client loop can take more than one window to finish its dial) —
        # so a leak ages out once its peer disconnects plus two
        # heartbeats, and a slow-dialing live session is not clamped away.
        reported = member.stats.get("connections")
        if reported is not None:
            cap = (int(reported) + member.routed_since_hb
                   + member.routed_prev_hb)
            if member.inflight > cap:
                member.inflight = cap
        member.routed_prev_hb = member.routed_since_hb
        member.routed_since_hb = 0
        totals = msg.get("slo_totals") or {}
        if isinstance(totals, dict):
            member.slo_totals = totals

    def _on_probe_ok(self, member: GatewayMember, msg: dict) -> None:
        if self._clock() < member.partitioned_until:
            return  # a partitioned member's probe reply is lost too
        fut = member._probe_fut
        if fut is not None and not fut.done() and msg.get("n") == member._probe_n:
            fut.set_result(True)

    # -- replicated control plane (leader lease) ------------------------------

    @property
    def has_authority(self) -> bool:
        """May this replica rotate STEKs / own admission policy NOW?
        Standalone fleets (no lease) always do — the classic single-router
        behavior is the degenerate one-replica case."""
        return self.lease is None or self.lease.is_leader

    def lease_view(self) -> dict[str, Any]:
        if self.lease is None:
            # a standalone router IS the (only possible) authority holder
            return {"role": "leader", "epoch": 0, "holder": self.router_id,
                    "standalone": True}
        return self.lease.view()

    def _lease_epoch(self) -> int:
        return 0 if self.lease is None else self.lease.epoch

    def _observe_lease(self, holder: str, epoch: int,
                       ttl_s: float | None) -> bool:
        """Fold a peer claim/renew in; demotions surface LOUDLY (flight
        record + event), never as a silent role flip.  False = stale."""
        assert self.lease is not None
        was = self.lease.role
        ok = self.lease.observe(holder, int(epoch), ttl_s)
        if self.lease.role != was and self.lease.role == "demoted":
            logger.error("router %s DEMOTED: lease epoch %s is held by %s",
                         self.router_id, epoch, holder)
            obs_flight.trigger("router_demoted", router=self.router_id,
                               epoch=int(epoch), holder=holder)
            self._fire("lease_demoted", self.router_id)
        return ok

    async def _on_rt_lease(self, msg: dict, writer) -> None:
        """A peer's lease claim/renewal.  Stale epochs are fenced with a
        typed ``__rt_reject__`` reply carrying OUR epoch — the proof the
        stale sender needs to demote instead of split-braining."""
        if self.lease is None:
            return
        holder = str(msg.get("holder", ""))
        ttl_s = msg.get("ttl_s")
        if not self._observe_lease(holder, int(msg.get("epoch") or 0),
                                   float(ttl_s) if ttl_s is not None else None):
            self.lease_fenced += 1
            obs_flight.record("stale_lease_fenced", router=self.router_id,
                              sender=holder, at_epoch=self.lease.epoch)
            try:
                await control.send_ctrl(writer, {
                    "type": control.RT_REJECT,
                    "router": self.router_id,
                    "epoch": self.lease.epoch,
                })
            except (ConnectionError, OSError):
                pass

    async def _on_rt_sync(self, msg: dict, writer) -> None:
        """Leader → follower authority-state replication: the STEK ring
        export (current + previous — the full accept window), the
        rotation count, and the membership roster, fenced on the lease
        epoch exactly like the lease frames themselves."""
        if self.lease is None:
            return
        holder = str(msg.get("holder", ""))
        epoch = int(msg.get("epoch") or 0)
        if not self._observe_lease(holder, epoch, None):
            self.lease_fenced += 1
            obs_flight.record("stale_sync_fenced", router=self.router_id,
                              sender=holder, at_epoch=self.lease.epoch)
            try:
                await control.send_ctrl(writer, {
                    "type": control.RT_REJECT,
                    "router": self.router_id,
                    "epoch": self.lease.epoch,
                })
            except (ConnectionError, OSError):
                pass
            return
        keys = msg.get("keys")
        if keys:
            try:
                installed = self.ticket_keys.install(
                    [(str(ep), bytes.fromhex(str(key_hex)))
                     for ep, key_hex in keys], guard=True)
            except (ValueError, TypeError):
                logger.warning("router %s: malformed STEK sync from %s "
                               "ignored", self.router_id, holder)
                return
            if not installed:
                # structural regression guard (STEKRing.install): a
                # pre-rotation replicate frame landed after the rotation
                # it predates — same lease epoch, separate connections
                obs_flight.record("stale_stek_sync_skipped",
                                  router=self.router_id, sender=holder)
                return
        self.key_rotations = max(self.key_rotations,
                                 int(msg.get("rotations") or 0))
        for gid in (msg.get("members") or ()):
            gid = str(gid)
            if gid not in self.members:
                # roster adoption: a replica that (re)started after a
                # gateway registered elsewhere still places it on the ring;
                # liveness stays the gateway's own hello/heartbeat business
                self.members[gid] = GatewayMember(
                    gid, len(self.members), *self._cooloffs,
                    clock=self._clock)
                self.ring.add(gid)
        self.syncs_applied += 1

    def _lease_tick(self) -> None:
        """The lease half of the health tick: claim when the lease (plus
        our rank stagger) expired, renew at ttl/3 cadence while leading.
        Claims and renewals broadcast to every peer; a claim also
        replicates the full authority state and re-pushes the STEK ring
        to our connected gateways, so the accept window survives the
        failover (tickets minted under the dead leader still redeem)."""
        assert self.lease is not None
        if self.lease.claim_due():
            body = self.lease.claim()
            logger.warning("router %s claimed the lease (epoch %s)",
                           self.router_id, body["epoch"])
            obs_flight.record("lease_claimed", router=self.router_id,
                              epoch=body["epoch"])
            self._fire("lease_claimed", self.router_id)
            self._spawn(self._announce_lease(body, sync=True),
                        f"lease claim:{self.router_id}")
        elif self.lease.renew_due():
            body = self.lease.renew()
            self._spawn(self._announce_lease(body, sync=False),
                        f"lease renew:{self.router_id}")

    async def _announce_lease(self, body: dict[str, Any],
                              sync: bool) -> None:
        frame = {"type": control.RT_LEASE, "holder": body["holder"],
                 "epoch": body["epoch"], "ttl_s": body["ttl_s"]}
        for peer in self.router_peers:
            await self._peer_send(peer, frame)
        if self.lease is not None and self.lease.is_leader:
            # EVERY renewal re-replicates the authority state, not just
            # the claim: a follower that restarted since the last change
            # (a mid-roll respawn) converges within one renew interval
            # instead of holding a private random STEK ring until the
            # next rotation — which is exactly the window a failover
            # would lose the accept window in
            await self._replicate_state()
            if sync:
                await self._push_stek_to_gateways()

    def _sync_frame(self) -> dict[str, Any]:
        return {"type": control.RT_SYNC, "holder": self.router_id,
                "epoch": self._lease_epoch(),
                "keys": self.ticket_keys.export(),
                "rotations": self.key_rotations,
                "members": sorted(self.members)}

    async def _replicate_state(self) -> None:
        """Leader → every follower: full authority state, on every change
        (claim, STEK rotation, membership growth)."""
        frame = self._sync_frame()
        for peer in self.router_peers:
            await self._peer_send(peer, frame)

    async def _peer_send(self, peer: dict[str, Any],
                         frame: dict[str, Any]) -> None:
        """One frame to one peer replica, short-lived connection (the
        route_query discipline).  The receiver replies ONLY to fence a
        stale frame; an accepted frame is acked by the close.  A reject
        reply is proof a fresher lease exists: count it, demote loudly."""
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(str(peer.get("host") or self.host),
                                        int(peer["port"])), 2.0)
        except (OSError, asyncio.TimeoutError, ValueError, KeyError):
            return  # a dead peer misses this round; reconvergence is cheap
        try:
            await control.send_ctrl(writer, frame)
            reply = asyncio.ensure_future(control.read_ctrl(reader))
            # consume the reply task's outcome even when WE get cancelled
            # mid-wait (fleet stop, chaos kill): an EOF landing in the
            # same tick as the cancellation would otherwise surface as an
            # unretrieved-exception warning after the fact
            reply.add_done_callback(
                lambda t: None if t.cancelled() else t.exception())
            try:
                msg = await asyncio.wait_for(reply, 2.0)
            except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                    ConnectionError, OSError, ValueError):
                return  # closed without a reply = accepted
            mtype = msg.get("type")
            if mtype == control.RT_REJECT:
                # stale-lease fence bounced back at us: a peer holds proof
                # of a fresher lease — never keep claiming over it
                self.lease_rejects += 1
                peer_id = str(msg.get("router", ""))
                peer_epoch = int(msg.get("epoch") or 0)
                if self.lease is not None:
                    was = self.lease.role
                    if self.lease.observe_reject(peer_epoch):
                        logger.error(
                            "router %s DEMOTED: %s fenced our frame at "
                            "epoch %s", self.router_id, peer_id, peer_epoch)
                        obs_flight.trigger("router_demoted",
                                           router=self.router_id,
                                           epoch=peer_epoch, holder=peer_id)
                        if self.lease.role != was:
                            self._fire("lease_demoted", self.router_id)
        except (ConnectionError, OSError):
            pass
        finally:
            writer.close()

    async def _push_stek_to_gateways(self) -> None:
        """Re-push the (replicated) STEK ring to every gateway connected
        to THIS replica — the new leader's first act, so a ticket minted
        under the dead leader's key redeems on the very next resume."""
        for member in self._members_sorted():
            if member.writer is None or member.stopped:
                continue
            try:
                await control.send_ctrl(member.writer, {
                    "type": control.GW_TICKET_KEYS,
                    "keys": self.ticket_keys.export(),
                    "lease_epoch": self._lease_epoch(),
                })
            except (ConnectionError, OSError, RuntimeError):
                logger.warning("STEK re-push to %s failed",
                               member.gateway_id)

    # -- health loop / handoff ------------------------------------------------

    async def _health_loop(self) -> None:
        while self._running:
            await asyncio.sleep(self.hb_interval)
            self._health_tick()

    def _health_tick(self) -> None:
        """One fleet health pass (also driven directly by tests on an
        injected clock): chaos hooks, death detection, probe routing."""
        now = self._clock()
        # chaos first, in sorted order on ONE loop: the process-scope rule
        # counters advance on a deterministic event stream (faults/plan.py)
        for member in self._members_sorted():
            if member.stopped:
                continue
            for entry in _faults.process_control(member.gateway_id):
                self._apply_chaos(member, entry)
        # the lease half: claim/renew/demote decisions on this same tick
        if self.lease is not None:
            self._lease_tick()
        # automatic STEK rotation (dual-key window: the demoted key still
        # opens tickets minted just before the rotation) — LEADER-ONLY in
        # a replicated control plane: a follower rotating would fork the
        # accept window and orphan every in-flight ticket
        if (self.ticket_key_rotation_s and self.has_authority
                and now - self._last_key_rotation_t
                >= self.ticket_key_rotation_s):
            self._last_key_rotation_t = now
            self._spawn(self.rotate_stek(), "stek rotation")
        for member in self._members_sorted():
            if member.stopped or member.draining or member.last_hb is None:
                # a draining member's exit is PLANNED (rolling restart):
                # declaring it dead would flap the breaker for noise
                continue
            missed_for = now - member.last_hb
            if (member.breaker.state == "closed"
                    and missed_for > self.hb_miss_limit * self.hb_interval):
                # a dead gateway is a breaker-open shard at fleet scope:
                # non-probe failure — open at the base cool-off, arc drains
                # to the ring successors, probes decide re-entry
                member.breaker.record_failure("device")
                logger.warning(
                    "gateway %s missed heartbeats for %.2fs: fleet breaker "
                    "OPEN; ring arc handed to successors",
                    member.gateway_id, missed_for)
                obs_flight.trigger("fleet_gateway_dead",
                                   gateway=member.gateway_id,
                                   missed_for_s=round(missed_for, 3))
                self._fire("gateway_dead", member.gateway_id)
        self._note_rebalance()
        # probe routing through the SHARED placement policy: select_slot
        # prefers a probe-eligible slot — at fleet scope the unit of work
        # it receives is a control canary, never a client session
        live = [m for m in self._members_sorted()
                if not m.stopped and not m.draining]
        slot = select_slot(live)
        if slot is None or not slot.breaker.probe_ready():
            return
        claim = slot.breaker.acquire_dispatch()
        if claim != "probe":
            slot.breaker.release(claim)
            return
        slot._probe_n += 1
        self._spawn(self._probe_gateway(slot, slot._probe_n),
                    f"probe:{slot.gateway_id}")

    def _apply_chaos(self, member: GatewayMember, entry: dict) -> None:
        action = entry.get("action")
        logger.warning("chaos: %s on %s", action, member.gateway_id)
        if action == "kill_gateway":
            self.kill(member.gateway_id)
        elif action == "pause_gateway":
            self.pause(member.gateway_id, float(entry.get("delay_s", 1.0)))
        elif action == "partition":
            self.partition(member.gateway_id,
                           float(entry.get("delay_s", 1.0)))
        elif action == "drain_gateway":
            # graceful-drain chaos: the gateway runs the full drain
            # protocol mid-storm (a kill rule on a later tick makes this
            # the drain-interrupt scenario)
            self._spawn(self.drain(member.gateway_id),
                        f"chaos drain:{member.gateway_id}")

    async def _probe_call(self, member: GatewayMember, n: int) -> None:
        """ONE half-open canary round-trip: send ``__gw_probe__``, await
        the matching reply.  Raises on a dead/partitioned/slow gateway —
        the caller records the outcome to the member's fleet breaker
        (qrlint dispatch-except-no-breaker polices that contract)."""
        if member.writer is None:
            raise ConnectionError(f"{member.gateway_id}: no control link")
        if self._clock() < member.partitioned_until:
            raise ConnectionError(f"{member.gateway_id}: partitioned")
        loop = asyncio.get_running_loop()
        member._probe_fut = loop.create_future()
        await control.send_ctrl(member.writer,
                                {"type": control.GW_PROBE, "n": n})
        await asyncio.wait_for(member._probe_fut,
                               self.hb_miss_limit * self.hb_interval)

    async def _probe_gateway(self, member: GatewayMember, n: int) -> None:
        try:
            await self._probe_call(member, n)
        except (asyncio.TimeoutError, ConnectionError, OSError,
                RuntimeError) as e:
            # failed canary: the fleet breaker re-opens with escalating
            # backoff — a SIGKILLed gateway costs one bounded probe per
            # (growing) cool-off, never a client session
            member.breaker.record_failure("probe")
            logger.warning("gateway %s canary probe failed (%s)",
                           member.gateway_id, e)
            self._fire("probe_failed", member.gateway_id)
            return
        member.breaker.record_success("probe")
        # the probe round-trip IS fresh liveness evidence: without this the
        # next health tick would re-declare the just-healed member dead off
        # its stale pre-outage heartbeat timestamp and flap the arc
        member.last_hb = self._clock()
        logger.warning(
            "gateway %s canary probe succeeded: fleet breaker CLOSED; "
            "ring ownership restored", member.gateway_id)
        obs_flight.record("fleet_gateway_healed", gateway=member.gateway_id,
                          probes=n)
        self._fire("gateway_healed", member.gateway_id)
        self._note_rebalance()

    def _note_rebalance(self) -> None:
        healthy = frozenset(
            m.gateway_id for m in self.members.values()
            if not m.stopped and not m.draining
            and m.breaker.state == "closed")
        if healthy != self._last_healthy:
            obs_flight.record(
                "fleet_rebalance", healthy=sorted(healthy),
                avoided=sorted(set(self.members) - healthy))
            self._last_healthy = healthy

    def _spawn(self, coro, what: str) -> None:
        task = asyncio.create_task(coro, name=what)
        self._bg.add(task)
        task.add_done_callback(self._bg.discard)

    # -- routing --------------------------------------------------------------

    def fleet_budget(self) -> int | None:
        """Current fleet admission budget: the sum of per-gateway budgets
        over CLOSED members (a dead gateway's capacity is not capacity).
        None = unlimited (no per-gateway budget configured) — distinct
        from 0, which means a configured fleet with ZERO healthy capacity
        and must shed, not admit unbounded."""
        if not self.per_gateway_max_peers:
            return None
        healthy = sum(1 for m in self.members.values()
                      if not m.stopped and not m.draining
                      and m.breaker.state == "closed")
        return self.per_gateway_max_peers * healthy

    def route(self, peer_id: str,
              exclude: tuple[str, ...] = ()) -> GatewayMember | None:
        """Assign ``peer_id`` a gateway: ring owner first, then ring
        successors that are closed, then the shared placement policy's
        quarantine-aware last resort.  Raises :class:`FleetBusy` when the
        fleet admission budget is exhausted (the wire reply is the typed
        ``__busy__`` frame); returns None when no member is routable.

        ``exclude`` lists gateways the CLIENT just watched fail — honored
        for this query even when their breakers have not opened yet (the
        router may be one heartbeat behind the truth), but never treated
        as failure evidence on its own."""
        budget = self.fleet_budget()
        if budget is not None:
            # count load on the same members the budget counts capacity
            # for: a dead gateway's still-claimed sessions are being
            # re-routed — charging them against the shrunken budget would
            # over-shed during exactly the handoff window
            inflight = sum(m.inflight for m in self.members.values()
                           if not m.stopped and not m.draining
                           and m.breaker.state == "closed")
            if inflight >= budget:
                self.route_sheds += 1
                if self.route_sheds == 1 or self.route_sheds % 64 == 0:
                    logger.warning(
                        "fleet admission budget reached (%d live sessions, "
                        "budget %d): shedding route query (%d shed so far)",
                        inflight, budget, self.route_sheds)
                    obs_flight.record("load_shed", where="fleet_router",
                                      inflight=inflight, budget=budget,
                                      sheds=self.route_sheds)
                raise FleetBusy(
                    f"fleet at capacity ({inflight}/{budget} sessions)")
        chosen: GatewayMember | None = None
        owner: str | None = None
        for gid in self.ring.successors(peer_id):
            if owner is None:
                owner = gid
            member = self.members[gid]
            if (gid in exclude or member.stopped or member.draining
                    or not member.registered):
                continue
            if member.breaker.state == "closed":
                chosen = member
                break
        if chosen is None:
            # no closed member on the ring walk: the shared two-level
            # policy's degraded placement (least-loaded non-quarantined).
            # Unlike the shard scope, the routed unit here is a CLIENT
            # session, never a canary — prefer members that are NOT
            # probe-eligible (a probe-ready member is the one most likely
            # freshly dead; its probe is the health loop's job), falling
            # back to anyone only when every survivor is probe-ready.
            pool = [m for m in self._members_sorted()
                    if not m.stopped and not m.draining and m.registered
                    and m.gateway_id not in exclude]
            non_probe = [m for m in pool if not m.breaker.probe_ready()]
            chosen = select_slot(non_probe or pool)
            if chosen is None:
                return None
            self.rebalance_picks += 1
        if owner is not None and chosen.gateway_id != owner:
            self.handoffs += 1
        chosen.inflight += 1
        chosen.routed_since_hb += 1
        chosen.assigned += 1
        self.routes_ok += 1
        return chosen

    def session_done(self, gateway_id: str) -> None:
        """A routed session ended (client-side signal): release its
        admission slot."""
        member = self.members.get(gateway_id)
        if member is not None and member.inflight > 0:
            member.inflight -= 1

    # -- STEK rotation / graceful drain / rolling restart ---------------------

    async def rotate_stek(self) -> str:
        """Rotate the fleet's ticket-sealing key (the old current stays in
        the accept window) and push the new ring to every live gateway.
        Returns the new epoch.  Tickets minted before the PREVIOUS
        rotation stop resuming — the documented forward-secrecy bound."""
        if not self.has_authority:
            # a follower/demoted replica asked to rotate (operator error,
            # split-brain remnant): refusing here is the local half of the
            # fencing — the wire half is followers rejecting the stale push
            raise RuntimeError(
                f"router {self.router_id} ({self.lease_view()['role']}) "
                "does not hold the lease: STEK rotation refused")
        epoch = self.ticket_keys.rotate()
        self.key_rotations += 1
        obs_flight.record("stek_rotated", epoch=epoch,
                          rotations=self.key_rotations)
        logger.warning("fleet STEK rotated (epoch %s); pushing to %d "
                       "gateway(s)", epoch, len(self.members))
        for member in self._members_sorted():
            if member.writer is None or member.stopped:
                continue
            try:
                await control.send_ctrl(member.writer, {
                    "type": control.GW_TICKET_KEYS,
                    "keys": self.ticket_keys.export(),
                    "lease_epoch": self._lease_epoch(),
                })
            except (ConnectionError, OSError, RuntimeError):
                # a dying gateway misses the push; re-registration (or the
                # respawn after its restart) re-sends the current ring
                logger.warning("STEK push to %s failed", member.gateway_id)
        if self.lease is not None:
            # every rotation replicates: ANY follower must be able to
            # assume the lease without losing the accept window
            await self._replicate_state()
        return epoch

    async def drain(self, gateway_id: str) -> None:
        """Ask one gateway to drain gracefully: it stops admitting,
        flushes outboxes, nudges its peers to resume on their ring
        successor, writes its slo report, and exits 0.  The member is
        excluded from routing (and death detection) until it — or its
        respawned successor — re-registers."""
        member = self.members[gateway_id]
        member.draining = True
        obs_flight.record("fleet_gateway_drain", gateway=gateway_id)
        logger.warning("draining gateway %s (routing excluded)", gateway_id)
        if member.writer is not None:
            try:
                await control.send_ctrl(member.writer, {
                    "type": control.GW_DRAIN,
                    "lease_epoch": self._lease_epoch(),
                })
            except (ConnectionError, OSError, RuntimeError):
                pass  # already dying; the exit path is the same

    async def _await_exit(self, member: GatewayMember,
                          timeout: float) -> bool:
        """Wait for a draining gateway to exit; escalate to SIGKILL/cancel
        on timeout.  True = exited within the grace window."""
        if member.proc is not None:
            try:
                await asyncio.wait_for(member.proc.wait(), timeout)
                return True
            except asyncio.TimeoutError:
                logger.warning("gateway %s ignored drain for %.1fs; killing",
                               member.gateway_id, timeout)
                member.proc.kill()
                await member.proc.wait()
                return False
        if member.task is not None:
            try:
                await asyncio.wait_for(member.task, timeout)
                return True
            except asyncio.TimeoutError:
                member.task.cancel()
                return False
            except asyncio.CancelledError:
                return True  # chaos already cancelled it
            except Exception:
                logger.exception("gateway %s task died during drain",
                                 member.gateway_id)
                return True
        return True

    async def _await_registered(self, member: GatewayMember,
                                timeout: float) -> bool:
        """Poll (real time — respawn is a wall-clock operation) until the
        respawned member's hello lands."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if member.registered:
                return True
            await asyncio.sleep(0.05)
        return member.registered

    async def restart_member(self, gateway_id: str,
                             drain_timeout: float = 30.0) -> dict[str, Any]:
        """Gracefully restart ONE gateway: drain -> wait for exit ->
        respawn -> wait for re-registration (the STEK ring rides the
        re-registration hello, so pre-restart tickets resume on the new
        process)."""
        member = self.members[gateway_id]
        t0 = time.monotonic()
        await self.drain(gateway_id)
        graceful = await self._await_exit(member, drain_timeout)
        member.reset_for_respawn()
        await self._spawn_member(member)
        registered = await self._await_registered(member,
                                                  self._register_timeout)
        out = {
            "gateway": gateway_id,
            "graceful_exit": graceful,
            "registered": registered,
            "took_s": round(time.monotonic() - t0, 3),
        }
        obs_flight.record("fleet_gateway_restarted", **out)
        if not registered:
            logger.error("gateway %s never re-registered after restart",
                         gateway_id)
        return out

    async def rolling_restart(self,
                              drain_timeout: float = 30.0) -> dict[str, Any]:
        """Restart the whole fleet one gateway at a time (docs/robustness.md
        "Rolling restarts"): each member is drained (its peers nudged to
        resume — via ticket — on the ring successor), awaited, respawned,
        and re-registered before the next begins, so the fleet never loses
        more than one gateway of capacity and every moved session resumes
        for two HKDFs instead of a full handshake."""
        results = []
        for gateway_id in sorted(self.members):
            if self.members[gateway_id].stopped:
                continue
            results.append(await self.restart_member(gateway_id,
                                                     drain_timeout))
        ok = all(r["registered"] for r in results)
        obs_flight.record("fleet_rolling_restart",
                          gateways=[r["gateway"] for r in results], ok=ok)
        return {"restarted": results, "ok": ok}

    def _route_reply(self, msg: dict) -> dict:
        peer_id = str(msg.get("peer_id", ""))
        exclude = tuple(str(g) for g in msg.get("exclude") or ())
        try:
            member = self.route(peer_id, exclude)
        except FleetBusy:
            return {"type": control.BUSY, "scope": "fleet"}
        if member is None:
            return {"type": control.NO_ROUTE}
        return {"type": control.ROUTE_OK, "gateway": member.gateway_id,
                "host": member.host or self.host, "port": member.port}

    # -- fleet SLO aggregation ------------------------------------------------

    def _sum_totals(self, name: str) -> tuple[float, float]:
        good = bad = 0.0
        for m in self.members.values():
            pair = m.slo_totals.get(name)
            if isinstance(pair, (list, tuple)) and len(pair) == 2:
                good += float(pair[0])
                bad += float(pair[1])
        return good, bad

    def _sum_stat(self, key: str) -> float:
        return float(sum(float(m.stats.get(key) or 0.0)
                         for m in self.members.values()))

    def _build_slo_engine(self) -> obs_slo.SLOEngine:
        """ONE multi-window burn engine over the SUMS of every gateway's
        probe totals (heartbeat feed) — the per-node reports merged live;
        tools/slo_merge.py computes the same aggregation offline from the
        slo_report.json files."""
        eng = obs_slo.SLOEngine(registry=self.registry, clock=self._clock)
        eng.add(obs_slo.SLOSpec(
            "fleet_handshake_p99", objective=0.99,
            probe=lambda: self._sum_totals("handshake_p99"),
            description="fleet-wide initiated handshakes within the "
                        "latency threshold (sum of per-gateway totals)",
        ))
        eng.add(obs_slo.SLOSpec(
            "fleet_shed_rate", objective=0.99,
            probe=self._shed_probe,
            description="admission decisions accepted vs shed across the "
                        "router and every gateway boundary",
            fast_burn=10.0, slow_burn=1.0,
        ))
        eng.add(obs_slo.SLOSpec(
            "fleet_device_served", objective=0.9,
            probe=lambda: (self._sum_stat("device_trips"),
                           self._sum_stat("fallback_trips")),
            description="dispatch steps served from the device path "
                        "across every gateway (vs cpu fallback)",
            fast_burn=5.0, slow_burn=2.0,
        ))
        eng.add(obs_slo.SLOSpec(
            "fleet_gateway_availability", objective=0.95,
            probe=self._availability_probe,
            description="gateway-seconds the fleet breakers were closed "
                        "vs degraded (dead/partitioned/probing)",
            fast_burn=5.0, slow_burn=1.0,
        ))
        return eng

    def _shed_probe(self) -> tuple[float, float]:
        good, bad = self._sum_totals("gateway_shed_rate")
        return good + self.routes_ok, bad + self.route_sheds

    def _availability_probe(self) -> tuple[float, float]:
        bad = sum(m.breaker.degraded_seconds()
                  for m in self.members.values())
        total = len(self.members) * (self._clock() - self._t0)
        return max(0.0, total - bad), bad

    def slo_status(self) -> dict[str, Any]:
        return self.slo.status()

    def fleet_cost_totals(self) -> dict[str, Any]:
        """Fleet-wide device-cost economics: the numeric cost totals each
        gateway's heartbeat carries (obs/cost.py ``CostLedger.totals``),
        summed — plus the derived fleet padding-waste fraction."""
        sums: dict[str, Any] = {}
        per_gateway: dict[str, Any] = {}
        for m in self._members_sorted():
            cost = m.stats.get("cost")
            if not isinstance(cost, dict):
                continue
            per_gateway[m.gateway_id] = cost
            for k, v in cost.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    # int seed keeps event counts ints in the artifact
                    # (float fields stay float through float addition)
                    sums[k] = sums.get(k, 0) + v
        # the ratio fields must be re-derived from the summed raw counts,
        # not summed themselves (a sum of fractions is meaningless)
        for ratio in ("padding_waste_fraction", "opcache_hit_rate_cumulative"):
            sums.pop(ratio, None)
        total = sums.get("items_real", 0) + sums.get("items_padded", 0)
        sums["padding_waste_fraction"] = (
            round(sums.get("items_padded", 0) / total, 6) if total else None)
        looked = sums.get("opcache_hits", 0) + sums.get("opcache_misses", 0)
        sums["opcache_hit_rate_cumulative"] = (
            round(sums.get("opcache_hits", 0) / looked, 6) if looked else None)
        return {"fleet": sums, "per_gateway": per_gateway}

    def fleet_view(self) -> dict[str, Any]:
        """The aggregated ``/fleet`` document the router's telemetry
        endpoint serves: the summed SLO engine's burn report + the
        heartbeat cost totals + per-member routing/liveness state (each
        member row carries its own telemetry port, so a dashboard can
        walk from the router to every gateway's scrape)."""
        return {
            "router": self.stats(),
            "slo": self.slo_status(),
            "cost": self.fleet_cost_totals(),
        }

    # -- reporting ------------------------------------------------------------

    def stats(self) -> dict[str, Any]:
        return {
            "gateways": len(self.members),
            "router_id": self.router_id,
            "lease": self.lease_view(),
            "lease_rejects": self.lease_rejects,
            "lease_fenced": self.lease_fenced,
            "syncs_applied": self.syncs_applied,
            "spawn": self.spawn,
            "seed": self.seed,
            "ring_vnodes": self.ring.vnodes,
            "routes_ok": self.routes_ok,
            "route_sheds": self.route_sheds,
            "rebalance_picks": self.rebalance_picks,
            "handoffs": self.handoffs,
            "fleet_budget": self.fleet_budget(),
            "stek_epoch": self.ticket_keys.current_epoch,
            "stek_rotations": self.key_rotations,
            "members": [m.snapshot() for m in self._members_sorted()],
        }

    def collect_reports(self) -> list[dict[str, Any]]:
        """The per-node ``slo_report.json`` documents the gateways wrote
        on shutdown (report_dir), for :func:`obs.slo.merge_reports`."""
        if self.report_dir is None:
            return []
        out = []
        for path in sorted(self.report_dir.glob("*_slo_report.json")):
            try:
                out.append(json.loads(path.read_text()))
            except (OSError, ValueError):
                logger.warning("unreadable slo report %s", path)
        return out
