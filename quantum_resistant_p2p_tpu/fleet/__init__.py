"""Gateway-pod fleet: the multi-process serving tier (ROADMAP item 1).

Everything below ``fleet/`` exists so the single-process gateway stack
(app/messaging.py + provider/batched.py + provider/scheduler.py) can run
as N gateway PROCESSES — one protocol engine per host/chip-group — behind
a peer-routing tier, with gateway death as the first-class case:

* :mod:`.ring`     — seeded consistent-hash peer→gateway assignment
                     (bounded virtual nodes; adding/removing one gateway
                     moves only its arc).
* :mod:`.control`  — the framed control-plane protocol (hello /
                     heartbeat / probe / stop / route) between the router
                     and its gateways, reusing net/p2p_node.py's wire
                     format.
* :mod:`.gateway`  — the gateway worker entry point
                     (``python -m quantum_resistant_p2p_tpu.fleet.gateway``):
                     one P2PNode + SecureMessaging engine, heartbeats to
                     the router, per-node ``slo_report.json`` on exit.
* :mod:`.manager`  — :class:`GatewayFleet`: spawns/watches the gateways,
                     owns the ring and the fleet-scope breakers (a dead
                     gateway is a breaker-open shard at fleet scope —
                     provider/batched.py ``Breaker`` reused at the second
                     placement level), serves route queries, aggregates
                     cross-process SLO totals into one burn-rate engine.
* :mod:`.storm`    — ``run_fleet_storm``: the multi-process chaos storm
                     (tools/swarm_bench.py ``--storm --fleet N``).
* :mod:`.stormlib` — the storm workload environment shared by the
                     single-process storm and every gateway subprocess
                     (``storm_env()``, the stdlib toy providers).

Design: docs/fleet.md.  Placement, quarantine and rebalance are ONE
policy at both scopes — :func:`provider.scheduler.select_slot` picks
among local shards and among fleet gateways alike.
"""

from .manager import FleetBusy, GatewayFleet, GatewayMember  # noqa: F401
from .ring import HashRing  # noqa: F401
from .stormlib import StormAEAD, register_storm_providers, storm_env  # noqa: F401
