"""Leader lease for the replicated router control plane (docs/fleet.md
"HA control plane").

One router must hold STEK-rotation and admission-budget *authority* at a
time; every other router follows and can take over without losing the
ticket accept window.  This module is the PURE state machine for that
decision — no sockets, no tasks, no wall clock.  The router layer
(fleet/manager.py) feeds it observed claim/renew frames and asks it when
to claim; everything here is deterministic given the injected clock, so
tests drive failovers tick by tick (tests/test_router_ha.py pins seeded
determinism on two independently-clocked replicas).

Design, in the shape the rest of the repo already uses:

- **Monotonic epochs.**  A claim always uses ``max_seen_epoch + 1`` —
  the same only-forward discipline as the STEK ring's rotation epochs.
  Two routers racing a claim produce distinct epochs only if one saw the
  other's frame; if neither did, the tie breaks on (epoch, holder-id)
  ordering when the frames cross, and the loser demotes loudly.
- **Relative TTLs on injectable clocks.**  Frames carry ``ttl_s``, never
  absolute deadlines — each replica arms ``now() + ttl_s`` on ITS clock,
  so bounded clock skew shifts the window but never inverts it.
- **Rank-staggered claims.**  When a lease expires, the replica with the
  lowest live rank claims first (``rank * claim_stagger_s`` delay), so
  failover is deterministic under seeded tests instead of a thundering
  herd: rt0 dies → rt1 claims at one stagger, rt2 would claim at two.
- **Stale-lease fencing.**  Any frame carrying ``epoch < max_seen`` is
  rejected (the caller replies ``__rt_reject__``), and a leader that
  *receives* such a reject — proof a newer lease exists — demotes
  immediately instead of split-braining.  "Demoted" is a distinct,
  loudly-reported role, not a silent fallback to follower.

The transition log (``(t, from_role, to_role, epoch, reason)`` tuples)
is the seam the determinism test pins: same clocks + same observed
frames ⇒ byte-identical logs.
"""

from __future__ import annotations

import time
from typing import Any, Callable

__all__ = ["LeaderLease", "FOLLOWER", "LEADER", "DEMOTED"]

FOLLOWER = "follower"
LEADER = "leader"
DEMOTED = "demoted"

#: default lease time-to-live: a leader that misses ~2 renew intervals
#: loses the lease (renew cadence is ttl/3 — see :meth:`renew_due`)
DEFAULT_TTL_S = 1.5
#: per-rank claim stagger after expiry: rank r waits r * stagger before
#: claiming, so the lowest live rank wins deterministically
DEFAULT_CLAIM_STAGGER_S = 0.25


class LeaderLease:
    """One replica's view of the fleet-wide leader lease.

    ``node_id`` names this replica in claim frames; ``rank`` orders the
    claim stagger (rank 0 claims first — by convention the spawn index).
    ``clock`` is any monotonic ``() -> float``; tests inject fakes.
    """

    def __init__(self, node_id: str, rank: int, *,
                 ttl_s: float = DEFAULT_TTL_S,
                 claim_stagger_s: float = DEFAULT_CLAIM_STAGGER_S,
                 clock: Callable[[], float] = time.monotonic):
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        if rank < 0:
            raise ValueError(f"rank must be >= 0, got {rank}")
        self.node_id = node_id
        self.rank = int(rank)
        self.ttl_s = float(ttl_s)
        self.claim_stagger_s = float(claim_stagger_s)
        self._clock = clock
        #: highest lease epoch this replica has ever seen (claims go
        #: max_seen + 1; anything below max_seen is fenced as stale)
        self.max_seen_epoch = 0
        #: who holds the current lease, per this replica's view
        self.holder: str | None = None
        #: local deadline for the current lease.  Born one full TTL in
        #: the future — the birth grace: a freshly (re)started replica
        #: must assume a leader might exist and stay quiet until a whole
        #: TTL passes with no renewal, or every respawn would claim a
        #: stale epoch, get fenced, and come up demoted for nothing
        self.expires_at = self._clock() + self.ttl_s
        self.role = FOLLOWER
        #: append-only transition log — the determinism pin
        self.transitions: list[tuple[float, str, str, int, str]] = []
        #: stale frames fenced (mirrored into router stats)
        self.stale_rejects = 0

    # -- introspection ---------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self.role == LEADER

    @property
    def epoch(self) -> int:
        """The lease epoch in force (0 before any claim was ever seen)."""
        return self.max_seen_epoch

    def lease_expired(self, now: float | None = None) -> bool:
        now = self._clock() if now is None else now
        return now >= self.expires_at

    def view(self) -> dict[str, Any]:
        """Snapshot for ``/fleet`` + heartbeats (obs surface)."""
        return {
            "role": self.role,
            "epoch": self.max_seen_epoch,
            "holder": self.holder,
            "node": self.node_id,
            "rank": self.rank,
            "ttl_s": self.ttl_s,
            "expires_in_s": round(max(0.0, self.expires_at - self._clock()), 3),
            "stale_rejects": self.stale_rejects,
            "transitions": len(self.transitions),
        }

    # -- transitions -----------------------------------------------------------

    def _move(self, to_role: str, epoch: int, reason: str) -> None:
        if to_role != self.role:
            self.transitions.append(
                (round(self._clock(), 6), self.role, to_role, epoch, reason))
            self.role = to_role

    # -- the claim side (this replica wants the lease) -------------------------

    def claim_due(self, now: float | None = None) -> bool:
        """Should this replica claim NOW?  True once the current lease
        has been expired for this replica's rank-staggered delay.  A
        demoted replica never claims again without an explicit
        :meth:`rejoin` — demotion is loud and sticky by design."""
        if self.role == DEMOTED:
            return False
        if self.role == LEADER:
            return False
        now = self._clock() if now is None else now
        return now >= self.expires_at + self.rank * self.claim_stagger_s

    def claim(self) -> dict[str, Any]:
        """Take the lease: bump the epoch past everything seen and become
        leader.  Returns the claim frame body (epoch + relative ttl) the
        caller broadcasts as ``__rt_lease__``."""
        self.max_seen_epoch += 1
        self.holder = self.node_id
        self.expires_at = self._clock() + self.ttl_s
        self._move(LEADER, self.max_seen_epoch, "claimed")
        return {"holder": self.node_id, "epoch": self.max_seen_epoch,
                "ttl_s": self.ttl_s}

    def renew_due(self, now: float | None = None) -> bool:
        """A leader renews at ttl/3 cadence — two missed renewals still
        leave a third before followers see expiry."""
        if self.role != LEADER:
            return False
        now = self._clock() if now is None else now
        return now >= self.expires_at - (2.0 * self.ttl_s) / 3.0

    def renew(self) -> dict[str, Any]:
        """Extend our own lease (same epoch — renewal, not re-claim)."""
        if self.role != LEADER:
            raise RuntimeError(f"{self.node_id}: renew as {self.role}")
        self.expires_at = self._clock() + self.ttl_s
        return {"holder": self.node_id, "epoch": self.max_seen_epoch,
                "ttl_s": self.ttl_s}

    # -- the observe side (frames from peer replicas) --------------------------

    def observe(self, holder: str, epoch: int,
                ttl_s: float | None = None) -> bool:
        """Fold a peer's claim/renew frame in.  Returns True when the
        frame is accepted (fresh), False when it is STALE — the caller
        must then reply ``__rt_reject__`` carrying OUR epoch so the
        stale sender demotes (fencing, both directions).

        A frame at our exact epoch from the holder we already track is a
        renewal; a frame at our epoch from a DIFFERENT holder is a tied
        race — broken on holder id (lexicographically smallest wins, the
        same total order the ring uses for member ids) so both sides
        converge without a third arbiter.
        """
        epoch = int(epoch)
        ttl = self.ttl_s if ttl_s is None else float(ttl_s)
        if epoch < self.max_seen_epoch:
            self.stale_rejects += 1
            return False
        if epoch == self.max_seen_epoch and self.holder is not None:
            if holder != self.holder:
                # tied claim race: deterministic total order, no arbiter
                if min(holder, self.holder) != holder:
                    self.stale_rejects += 1
                    return False
            elif holder == self.node_id:
                # our own frame echoed back — nothing to fold in
                return True
        if epoch > self.max_seen_epoch or holder != self.holder:
            if self.role == LEADER and holder != self.node_id:
                # someone else provably holds a fresher lease: split-brain
                # averted by stepping down loudly, never by ignoring it
                self._move(DEMOTED, epoch, f"superseded_by={holder}")
            elif self.role == FOLLOWER:
                self._move(FOLLOWER, epoch, f"adopted={holder}")
        self.max_seen_epoch = epoch
        self.holder = holder
        self.expires_at = self._clock() + ttl
        return True

    def observe_reject(self, epoch: int) -> bool:
        """A peer fenced one of OUR authority frames as stale, telling us
        a lease at ``epoch`` exists.  If we thought we were leader, that
        is proof of split-brain: demote loudly.  Returns True when a
        demotion happened (the caller flight-records it)."""
        epoch = int(epoch)
        if epoch > self.max_seen_epoch:
            self.max_seen_epoch = epoch
        if self.role == LEADER:
            self._move(DEMOTED, epoch, "fenced_by_peer")
            self.holder = None
            return True
        return False

    def rejoin(self) -> None:
        """Operator/respawn path: a demoted replica re-enters as a plain
        follower (a router process restart constructs a fresh lease, so
        this mainly serves tests and the in-task router fleet)."""
        if self.role == DEMOTED:
            self._move(FOLLOWER, self.max_seen_epoch, "rejoined")
